"""Preemptive instance isolation (reference lib.rs:419-430 property):
a stalled instance must not expire another instance's adjacencies."""

import time
from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.protocols.ospf.instance import (
    IfConfig,
    IfUpMsg,
    InstanceConfig,
    OspfInstance,
)
from holo_tpu.protocols.ospf.interface import IfType
from holo_tpu.protocols.ospf.neighbor import NsmState
from holo_tpu.utils.preempt import ThreadedFabric, ThreadedLoop


class StallMsg:
    pass


def _mk_pair(fabric, loop_a, loop_b, base):
    """Two OSPF routers, one per loop, tight timers (hello 1s dead 3s)."""
    a1, a2 = "10.60.0.1", "10.60.0.2"
    r1 = OspfInstance(
        name=f"{base}1",
        config=InstanceConfig(router_id=A("1.1.1.1")),
        netio=fabric.sender_for(f"{base}1"),
    )
    r2 = OspfInstance(
        name=f"{base}2",
        config=InstanceConfig(router_id=A("2.2.2.2")),
        netio=fabric.sender_for(f"{base}2"),
    )
    cfg = lambda: IfConfig(
        if_type=IfType.POINT_TO_POINT, hello_interval=1, dead_interval=3
    )
    loop_a.register(r1)
    loop_b.register(r2)
    loop_a.call(r1.add_interface, "e0", cfg(), N("10.60.0.0/30"), A(a1))
    loop_b.call(r2.add_interface, "e0", cfg(), N("10.60.0.0/30"), A(a2))
    fabric.join(f"l-{base}", loop_a, f"{base}1", "e0", A(a1))
    fabric.join(f"l-{base}", loop_b, f"{base}2", "e0", A(a2))
    loop_a.send(f"{base}1", IfUpMsg("e0"))
    loop_b.send(f"{base}2", IfUpMsg("e0"))
    return r1, r2


def _full(r):
    return any(
        n.state == NsmState.FULL
        for a in r.areas.values()
        for i in a.interfaces.values()
        for n in i.neighbors.values()
    )


def test_slow_instance_does_not_stall_others():
    """The OSPF pair lives on its own threads; a third instance stalls
    for well past the dead interval on ANOTHER thread — the adjacency
    must survive (dedicated-thread isolation, holo-protocol lib.rs)."""
    loops = [ThreadedLoop(f"tl{i}").start() for i in range(3)]
    fabric = ThreadedFabric()
    r1, r2 = _mk_pair(fabric, loops[0], loops[1], "pp")

    class Slow:
        name = "slowpoke"

        def attach(self, loop_):
            pass

        def handle(self, msg):
            time.sleep(4.0)  # >> dead interval (3s)

    loops[2].register(Slow())

    deadline = time.monotonic() + 8
    while time.monotonic() < deadline and not (_full(r1) and _full(r2)):
        time.sleep(0.05)
    assert _full(r1) and _full(r2), "pair never converged"

    def nbr_ids(r):
        return {
            id(n)
            for a in r.areas.values()
            for i in a.interfaces.values()
            for n in i.neighbors.values()
        }

    before = nbr_ids(r1) | nbr_ids(r2)
    # Stall the third instance's thread for 4s (sleep releases the GIL,
    # like kernel IO or a TPU round trip would).
    loops[2].send("slowpoke", StallMsg())
    time.sleep(4.0)
    assert _full(r1) and _full(r2), (
        "adjacency expired while an unrelated instance was stalled"
    )
    # ...and it never even flapped (same Neighbor objects throughout).
    assert (nbr_ids(r1) | nbr_ids(r2)) == before
    for lp in loops:
        lp.stop()


def test_cooperative_loop_shows_the_hazard():
    """Control experiment: on ONE cooperative loop the same stall DOES
    expire the adjacency — the property the threaded hosts add."""
    from holo_tpu.utils.netio import MockFabric
    from holo_tpu.utils.runtime import EventLoop, RealClock

    loop = EventLoop(clock=RealClock())
    fabric = MockFabric(loop)
    r1 = OspfInstance(
        name="c1", config=InstanceConfig(router_id=A("1.1.1.1")),
        netio=fabric.sender_for("c1"),
    )
    r2 = OspfInstance(
        name="c2", config=InstanceConfig(router_id=A("2.2.2.2")),
        netio=fabric.sender_for("c2"),
    )
    cfg = lambda: IfConfig(
        if_type=IfType.POINT_TO_POINT, hello_interval=1, dead_interval=3
    )
    loop.register(r1)
    loop.register(r2)
    r1.add_interface("e0", cfg(), N("10.61.0.0/30"), A("10.61.0.1"))
    r2.add_interface("e0", cfg(), N("10.61.0.0/30"), A("10.61.0.2"))
    fabric.join("l", "c1", "e0", A("10.61.0.1"))
    fabric.join("l", "c2", "e0", A("10.61.0.2"))
    loop.send("c1", IfUpMsg("e0"))
    loop.send("c2", IfUpMsg("e0"))
    deadline = time.monotonic() + 8
    while time.monotonic() < deadline and not (_full(r1) and _full(r2)):
        loop.run_until_idle()
        time.sleep(0.02)
    assert _full(r1) and _full(r2)

    def nbr_ids(r):
        return {
            id(n)
            for a in r.areas.values()
            for i in a.interfaces.values()
            for n in i.neighbors.values()
        }

    before = nbr_ids(r1) | nbr_ids(r2)
    # One cooperative loop: a 4s stall starves EVERYTHING; the dead
    # timers fire on resume and the neighbors are torn down (the
    # adjacency may re-form within the same drain, so compare OBJECT
    # identity: new Neighbor objects prove the expiry happened).
    time.sleep(4.0)
    loop.run_until_idle()
    after = nbr_ids(r1) | nbr_ids(r2)
    assert not (before & after), (
        "expected the cooperative loop to show the starvation hazard"
    )


def test_call_propagates_exceptions_and_returns_result():
    """ThreadedLoop.call must surface the closure's result AND its
    exception: a commit-time reconfiguration error on a threaded instance
    has to fail the commit, not vanish (advisor r4)."""
    from holo_tpu.utils.preempt import ThreadedLoop

    tl = ThreadedLoop("t-call").start()
    try:
        assert tl.call(lambda: 41 + 1) == 42

        def boom():
            raise ValueError("bad peer config")

        try:
            tl.call(boom)
            raise AssertionError("expected ValueError")
        except ValueError as exc:
            assert "bad peer config" in str(exc)
        # The loop is still healthy after a raising call.
        assert tl.call(lambda: "ok") == "ok"
    finally:
        tl.stop()

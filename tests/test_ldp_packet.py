"""LDP wire codec round-trips and error paths (RFC 5036).

Reference parity: holo-ldp/src/packet/* — message set, TLV U/F-bit
handling, PDU splitting at max_pdu_len, and the DecodeError -> StatusCode
mapping (notification.rs:459-477).
"""

from ipaddress import IPv4Address as A
from ipaddress import ip_network as N

import pytest

from holo_tpu.protocols.ldp.packet import (
    AF_IPV4,
    AddressMsg,
    CapabilityMsg,
    DecodeError,
    FecPrefix,
    FecWildcard,
    HELLO_GTSM,
    HELLO_REQ_TARGETED,
    HELLO_TARGETED,
    HelloMsg,
    InitMsg,
    KeepaliveMsg,
    LabelMsg,
    MsgType,
    NotifMsg,
    Pdu,
    StatusCode,
    status_is_fatal,
)

ALL_MSGS = [
    HelloMsg(
        msg_id=1,
        holdtime=15,
        flags=HELLO_GTSM,
        ipv4_addr=A("1.1.1.1"),
        cfg_seqno=1,
    ),
    HelloMsg(
        msg_id=2,
        holdtime=45,
        flags=HELLO_TARGETED | HELLO_REQ_TARGETED,
        ipv4_addr=A("6.6.6.6"),
        cfg_seqno=2,
    ),
    InitMsg(
        msg_id=3,
        keepalive_time=180,
        lsr_id=A("2.2.2.2"),
        cap_dynamic=True,
        cap_twcard_fec=True,
        cap_unrec_notif=True,
    ),
    KeepaliveMsg(msg_id=4),
    AddressMsg(msg_id=5, addr_list=[A("10.0.1.1"), A("10.0.2.1")]),
    AddressMsg(msg_id=6, withdraw=True, addr_list=[A("10.0.1.1")]),
    LabelMsg(
        msg_id=7,
        msg_type=MsgType.LABEL_MAPPING,
        fec=[FecPrefix(N("10.0.0.0/24"))],
        label=16,
        request_id=68,
    ),
    LabelMsg(
        msg_id=8,
        msg_type=MsgType.LABEL_REQUEST,
        fec=[FecWildcard(typed_af=AF_IPV4)],
    ),
    LabelMsg(
        msg_id=9,
        msg_type=MsgType.LABEL_WITHDRAW,
        fec=[FecWildcard()],
        label=17,
    ),
    LabelMsg(
        msg_id=10,
        msg_type=MsgType.LABEL_RELEASE,
        fec=[FecPrefix(N("2001:db8::/64"))],
        label=18,
    ),
    NotifMsg(
        msg_id=11,
        status_code=StatusCode.SHUTDOWN.encode_status(),
        status_msg_id=40,
        status_msg_type=0x400,
    ),
    NotifMsg(
        msg_id=12,
        status_code=StatusCode.END_OF_LIB.encode_status(),
        fec=[FecWildcard(typed_af=AF_IPV4)],
    ),
    CapabilityMsg(msg_id=13, twcard_fec=False, unrec_notif=True),
]


def test_round_trip_all_messages():
    pdu = Pdu(A("9.9.9.9"), 0, ALL_MSGS)
    out = Pdu.decode(pdu.encode())
    assert out.lsr_id == pdu.lsr_id
    assert out.messages == ALL_MSGS


def test_pdu_split_at_max_len():
    msgs = [
        LabelMsg(
            msg_id=i,
            msg_type=MsgType.LABEL_MAPPING,
            fec=[FecPrefix(N("10.0.0.0/24"))],
            label=16,
        )
        for i in range(300)
    ]
    wire = Pdu(A("9.9.9.9"), 0, msgs).encode(max_pdu_len=600)
    total, off = 0, 0
    while off < len(wire):
        ln = int.from_bytes(wire[off + 2 : off + 4], "big") + 4
        assert ln <= 600 + 4
        sub = Pdu.decode(wire[off : off + ln])
        total += len(sub.messages)
        off += ln
    assert total == 300


@pytest.mark.parametrize(
    "mutate,kind",
    [
        (lambda w: b"\x00\x02" + w[2:], "InvalidVersion"),
        (lambda w: w[:4] + bytes(4) + w[8:], "InvalidLsrId"),
        (lambda w: w[:8] + b"\x00\x01" + w[10:], "InvalidLabelSpace"),
        (lambda w: w[:2] + b"\x00\x01" + w[4:], "InvalidPduLength"),
    ],
)
def test_decode_errors(mutate, kind):
    wire = Pdu(A("1.1.1.1"), 0, [KeepaliveMsg(msg_id=1)]).encode()
    with pytest.raises(DecodeError) as e:
        Pdu.decode(mutate(wire))
    assert e.value.kind == kind


def test_error_status_mapping():
    # notification.rs:459-477
    assert (
        DecodeError("InvalidVersion", 2).status_code()
        == StatusCode.BAD_PROTO_VERS
    )
    assert (
        DecodeError("UnknownMessage", 0x9999).status_code()
        == StatusCode.UNKNOWN_MSG_TYPE
    )
    assert (
        DecodeError("ReadOutOfBounds").status_code()
        == StatusCode.INTERNAL_ERROR
    )


def test_fatal_bit():
    assert status_is_fatal(StatusCode.SHUTDOWN.encode_status())
    assert not status_is_fatal(StatusCode.END_OF_LIB.encode_status())
    assert not status_is_fatal(StatusCode.NO_ROUTE.encode_status())


def test_unknown_ubit_message_skipped():
    # RFC 5036 §3.3 / message.rs:363: U-bit unknown messages are
    # silently ignored, not surfaced as a placeholder message.
    from holo_tpu.utils.bytesbuf import Writer

    w = Writer()
    w.u16(1).u16(0).ipv4(A("1.1.1.1")).u16(0)
    w.u16(0x8F00).u16(4).u32(99)
    buf = bytearray(w.finish())
    buf[2:4] = (len(buf) - 4).to_bytes(2, "big")
    assert Pdu.decode(bytes(buf)).messages == []


def test_truncated_tlv_maps_to_ldp_error():
    # A TLV whose declared length is shorter than its fields must raise
    # packet.DecodeError (status-mappable), not leak bytesbuf errors.
    from holo_tpu.utils.bytesbuf import Writer

    w = Writer()
    w.u16(1).u16(0).ipv4(A("1.1.1.1")).u16(0)
    w.u16(0x0202).u16(8).u32(5)
    w.u16(0x050B | 0x8000).u16(0)  # capability TLV, empty body
    buf = bytearray(w.finish())
    buf[2:4] = (len(buf) - 4).to_bytes(2, "big")
    with pytest.raises(DecodeError) as e:
        Pdu.decode(bytes(buf))
    assert e.value.status_code() == StatusCode.INTERNAL_ERROR


def test_mixed_address_list_rejected():
    from ipaddress import IPv6Address

    msg = AddressMsg(
        msg_id=1,
        addr_list=[A("10.0.0.1"), IPv6Address("2001:db8::1")],
    )
    with pytest.raises(ValueError):
        Pdu(A("1.1.1.1"), 0, [msg]).encode()


def test_hello_transport_cross_checks():
    # hello.rs:266-280: targeted hello on multicast (and vice versa).
    targeted = Pdu(
        A("1.1.1.1"),
        0,
        [HelloMsg(msg_id=1, flags=HELLO_TARGETED)],
    ).encode()
    with pytest.raises(DecodeError) as e:
        Pdu.decode(targeted, multicast=True)
    assert e.value.kind == "McastTHello"
    link = Pdu(A("1.1.1.1"), 0, [HelloMsg(msg_id=1)]).encode()
    with pytest.raises(DecodeError) as e:
        Pdu.decode(link, multicast=False)
    assert e.value.kind == "UcastLHello"

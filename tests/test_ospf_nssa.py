"""RFC 3101 NSSA end-to-end: type-7 origination, ABR translation to
type-5, default type-7 injection, and scope rules — real instances over
MockFabric (reference: holo-ospf area types / nssa handling)."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.protocols.ospf.instance import (
    IfConfig,
    InstanceConfig,
    OspfInstance,
)
from holo_tpu.protocols.ospf.interface import IfType
from holo_tpu.protocols.ospf.packet import LsaType, Options
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock

AREA0 = A("0.0.0.0")
AREA1 = A("0.0.0.1")


def _mk(loop, fabric, name, rid):
    inst = OspfInstance(
        name=name,
        config=InstanceConfig(router_id=A(rid)),
        netio=fabric.sender_for(name),
    )
    loop.register(inst)
    return inst


def _p2p(fabric, link, a, a_if, a_addr, b, b_if, b_addr, net, area,
         nssa=False):
    cfg = IfConfig(area_id=area, if_type=IfType.POINT_TO_POINT, cost=10)
    a.add_interface(a_if, cfg, N(net), A(a_addr), nssa=nssa)
    b.add_interface(b_if, cfg, N(net), A(b_addr), nssa=nssa)
    fabric.join(link, a.name, a_if, A(a_addr))
    fabric.join(link, b.name, b_if, A(b_addr))


def _bring_up(loop, routers, seconds=60):
    from holo_tpu.protocols.ospf.instance import IfUpMsg

    for r in routers:
        for area in r.areas.values():
            for ifname in area.interfaces:
                loop.send(r.name, IfUpMsg(ifname))
    loop.advance(seconds)


def _setup():
    """rt3(backbone) -- rt1(ABR) -- rt2(NSSA-internal ASBR)."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    rt1 = _mk(loop, fabric, "rt1", "1.1.1.1")
    rt2 = _mk(loop, fabric, "rt2", "2.2.2.2")
    rt3 = _mk(loop, fabric, "rt3", "3.3.3.3")
    _p2p(fabric, "l13", rt1, "eth0", "10.0.0.1", rt3, "eth0", "10.0.0.3",
         "10.0.0.0/24", AREA0)
    _p2p(fabric, "l12", rt1, "eth1", "10.0.1.1", rt2, "eth0", "10.0.1.2",
         "10.0.1.0/24", AREA1, nssa=True)
    return loop, (rt1, rt2, rt3)


def test_nssa_type7_translated_to_type5():
    loop, (rt1, rt2, rt3) = _setup()
    _bring_up(loop, (rt1, rt2, rt3))
    ext = N("203.0.113.0/24")
    rt2.redistribute(ext, metric=20)
    loop.advance(30)

    # Type-7 with the P-bit circulates inside the NSSA…
    k7 = next(
        (k for k in rt1.areas[AREA1].lsdb.entries
         if k.type == LsaType.NSSA_EXTERNAL and k.adv_rtr == A("2.2.2.2")),
        None,
    )
    assert k7 is not None, "ABR never received the type-7"
    assert rt1.areas[AREA1].lsdb.entries[k7].lsa.options & Options.NP
    # …never as a type-5 inside the NSSA…
    assert not any(
        k.type == LsaType.AS_EXTERNAL for k in rt2.areas[AREA1].lsdb.entries
    )
    # …and the elected translator (rt1, the only NSSA ABR) re-originates
    # it as a type-5 into the backbone: rt3 routes to the prefix.
    assert any(
        k.type == LsaType.AS_EXTERNAL and k.adv_rtr == A("1.1.1.1")
        for k in rt3.areas[AREA0].lsdb.entries
    ), "translator did not originate the type-5"
    route = rt3.routes.get(ext)
    assert route is not None, "backbone router missing translated route"
    assert {str(nh.addr) for nh in route.nexthops} == {"10.0.0.1"}
    # The NSSA-internal ASBR routes externals learned via its own type-7
    # machinery, and the translator advertises E in its router LSA.
    assert rt1.is_asbr


def test_nssa_withdraw_flushes_translation():
    loop, (rt1, rt2, rt3) = _setup()
    _bring_up(loop, (rt1, rt2, rt3))
    ext = N("203.0.113.0/24")
    rt2.redistribute(ext, metric=20)
    loop.advance(30)
    assert rt3.routes.get(ext) is not None
    rt2.withdraw_redistributed(ext)
    loop.advance(30)
    assert rt3.routes.get(ext) is None, "stale translated type-5 route"
    assert not rt1._nssa_translated
    assert not rt1.is_asbr


def test_nssa_abr_injects_default_type7():
    loop, (rt1, rt2, rt3) = _setup()
    _bring_up(loop, (rt1, rt2, rt3))
    # The ABR originates a P=0 default type-7 into the NSSA; the internal
    # router installs 0.0.0.0/0 toward the ABR and it is never
    # re-translated (P=0).
    k = next(
        (k for k in rt2.areas[AREA1].lsdb.entries
         if k.type == LsaType.NSSA_EXTERNAL and k.lsid == A("0.0.0.0")),
        None,
    )
    assert k is not None, "no default type-7 in the NSSA"
    lsa = rt2.areas[AREA1].lsdb.entries[k].lsa
    assert not (lsa.options & Options.NP)
    route = rt2.routes.get(N("0.0.0.0/0"))
    assert route is not None
    assert {str(nh.addr) for nh in route.nexthops} == {"10.0.1.1"}
    # The default never leaks into the backbone as a type-5.
    assert not any(
        k.type == LsaType.AS_EXTERNAL and k.lsid == A("0.0.0.0")
        for k in rt3.areas[AREA0].lsdb.entries
    )


def test_nssa_hello_bit_agreement():
    """A normal-area neighbor on an NSSA interface must not form an
    adjacency (N/E option bits disagree, RFC 3101 §2.4 / §10.5)."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    rt1 = _mk(loop, fabric, "rt1", "1.1.1.1")
    rt2 = _mk(loop, fabric, "rt2", "2.2.2.2")
    cfg = IfConfig(area_id=AREA1, if_type=IfType.POINT_TO_POINT, cost=10)
    rt1.add_interface("eth0", cfg, N("10.0.1.0/24"), A("10.0.1.1"), nssa=True)
    rt2.add_interface("eth0", cfg, N("10.0.1.0/24"), A("10.0.1.2"))
    fabric.join("l12", rt1.name, "eth0", A("10.0.1.1"))
    fabric.join("l12", rt2.name, "eth0", A("10.0.1.2"))
    _bring_up(loop, (rt1, rt2), 30)
    for r in (rt1, rt2):
        for area in r.areas.values():
            for iface in area.interfaces.values():
                assert not iface.neighbors, "mismatched areas formed adjacency"

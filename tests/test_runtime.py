"""Runtime core: deterministic scheduling, timers, crash containment, ibus."""

from dataclasses import dataclass

from holo_tpu.utils.ibus import TOPIC_INTERFACE_UPD, Ibus, IbusMsg
from holo_tpu.utils.runtime import Actor, EventLoop, VirtualClock


@dataclass
class Ping:
    n: int


class Recorder(Actor):
    def __init__(self, name):
        self.name = name
        self.got = []

    def handle(self, msg):
        self.got.append(msg)


class Crasher(Actor):
    name = "crasher"

    def handle(self, msg):
        raise RuntimeError("boom")


def mkloop():
    return EventLoop(clock=VirtualClock())


def test_fifo_delivery():
    loop = mkloop()
    a = Recorder("a")
    loop.register(a)
    for i in range(5):
        loop.send("a", Ping(i))
    loop.run_until_idle()
    assert [m.n for m in a.got] == [0, 1, 2, 3, 4]


def test_timers_fire_in_deadline_order():
    loop = mkloop()
    a = Recorder("a")
    loop.register(a)
    t2 = loop.timer("a", lambda: Ping(2))
    t1 = loop.timer("a", lambda: Ping(1))
    t3 = loop.timer("a", lambda: Ping(3))
    t2.start(2.0)
    t1.start(1.0)
    t3.start(3.0)
    loop.advance(2.5)
    assert [m.n for m in a.got] == [1, 2]
    assert t3.armed and t3.remaining() == 0.5
    loop.advance(1.0)
    assert [m.n for m in a.got] == [1, 2, 3]


def test_timer_reset_and_cancel():
    loop = mkloop()
    a = Recorder("a")
    loop.register(a)
    t = loop.timer("a", lambda: Ping(9))
    t.start(1.0)
    loop.advance(0.9)
    t.reset(1.0)  # push deadline out
    loop.advance(0.9)
    assert a.got == []
    loop.advance(0.2)
    assert [m.n for m in a.got] == [9]
    t.start(1.0)
    t.cancel()
    loop.advance(5.0)
    assert len(a.got) == 1


def test_crash_containment_and_supervision():
    loop = mkloop()
    a = Recorder("a")
    crashed = []
    loop.register(a)
    loop.register(Crasher())
    loop.set_supervisor(lambda c: crashed.append(c.actor))
    loop.send("crasher", Ping(0))
    loop.send("a", Ping(1))
    loop.run_until_idle()
    assert crashed == ["crasher"]
    assert [m.n for m in a.got] == [1]  # other actors unaffected
    assert not loop.send("crasher", Ping(2))  # crashed actor stops receiving


def test_ibus_filtered_pubsub():
    loop = mkloop()
    a, b = Recorder("a"), Recorder("b")
    loop.register(a)
    loop.register(b)
    bus = Ibus(loop)
    bus.subscribe(TOPIC_INTERFACE_UPD, "a")
    bus.subscribe(TOPIC_INTERFACE_UPD, "b", ifname="eth0")
    bus.publish(TOPIC_INTERFACE_UPD, {"mtu": 1500}, ifname="eth1")
    loop.run_until_idle()
    assert len(a.got) == 1 and len(b.got) == 0
    bus.publish(TOPIC_INTERFACE_UPD, {"mtu": 9000}, ifname="eth0")
    loop.run_until_idle()
    assert len(a.got) == 2 and len(b.got) == 1
    assert isinstance(b.got[0], IbusMsg)
    bus.unsubscribe_all("a")
    bus.publish(TOPIC_INTERFACE_UPD, {}, ifname="eth0")
    loop.run_until_idle()
    assert len(a.got) == 2

"""End-to-end IP fast reroute: OSPF computes backup tables, the RIB
flips to the precomputed repair on BFD-down / link-down, and normal
reconvergence later replaces the repair — plus the two r5 parity leaves
that ride this PR (RFC 6987 stub-router, mtu-ignore / transmit-delay).
"""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

import pytest

from holo_tpu.frr.manager import FrrConfig
from holo_tpu.testing import no_implicit_transfers


@pytest.fixture(autouse=True)
def _transfer_sanitizer():
    """E2E repair paths run under jax.transfer_guard('disallow') too —
    a protocol-layer change that smuggles a device sync outside the
    sanctioned FRR/SPF boundaries must fail here, not on a bench."""
    with no_implicit_transfers():
        yield
from holo_tpu.protocols.ospf.instance import (
    IfConfig,
    IfUpMsg,
    InstanceConfig,
    OspfInstance,
)
from holo_tpu.protocols.ospf.interface import IfType
from holo_tpu.routing.rib import MockKernel, RibManager
from holo_tpu.utils.ibus import TOPIC_BFD_STATE, BfdStateUpd, Ibus
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock
from holo_tpu.utils.southbound import Protocol

AREA0 = A("0.0.0.0")
DEST = N("10.0.23.0/30")  # the r2--r3 subnet, primary via r2 from r1


def triangle(frr_cfg):
    """r1--r2 (10), r2--r3 (10), r1--r3 (100): from r1 the r2--r3 subnet
    routes via r2; neighbor r3 is its loop-free alternate."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    buses, kernels, ribs, routers = {}, {}, {}, {}
    for name, rid in [("r1", "1.1.1.1"), ("r2", "2.2.2.2"), ("r3", "3.3.3.3")]:
        bus = Ibus(loop)
        k = MockKernel()
        rib = RibManager(bus, k)
        rib.name = f"routing-{name}"
        loop.register(rib)
        inst = OspfInstance(
            name=name,
            config=InstanceConfig(
                router_id=A(rid), frr=frr_cfg if name == "r1" else None
            ),
            netio=fabric.sender_for(name),
        )
        loop.register(inst)
        inst.attach_ibus(bus, routing_actor=rib.name)
        buses[name], kernels[name], ribs[name], routers[name] = bus, k, rib, inst

    cfg = lambda c: IfConfig(if_type=IfType.POINT_TO_POINT, cost=c)
    r1, r2, r3 = routers["r1"], routers["r2"], routers["r3"]
    r1.add_interface("e0", cfg(10), N("10.0.12.0/30"), A("10.0.12.1"))
    r2.add_interface("e0", cfg(10), N("10.0.12.0/30"), A("10.0.12.2"))
    r2.add_interface("e1", cfg(10), N("10.0.23.0/30"), A("10.0.23.1"))
    r3.add_interface("e0", cfg(10), N("10.0.23.0/30"), A("10.0.23.2"))
    r1.add_interface("e1", cfg(100), N("10.0.13.0/30"), A("10.0.13.1"))
    r3.add_interface("e1", cfg(100), N("10.0.13.0/30"), A("10.0.13.2"))
    fabric.join("l12", "r1", "e0", A("10.0.12.1"))
    fabric.join("l12", "r2", "e0", A("10.0.12.2"))
    fabric.join("l23", "r2", "e1", A("10.0.23.1"))
    fabric.join("l23", "r3", "e0", A("10.0.23.2"))
    fabric.join("l13", "r1", "e1", A("10.0.13.1"))
    fabric.join("l13", "r3", "e1", A("10.0.13.2"))
    for r in routers.values():
        for area in r.areas.values():
            for ifname in area.interfaces:
                loop.send(r.name, IfUpMsg(ifname))
    loop.advance(90)
    return loop, fabric, buses, kernels, ribs, routers


def test_bfd_down_backup_flip_then_reconverge():
    """The tentpole moment: BFD-down flips the FIB to the precomputed
    backup in O(1) (no SPF), and flood/SPF reconvergence later replaces
    the repair with the real post-failure route."""
    loop, fabric, buses, kernels, ribs, routers = triangle(
        FrrConfig(enabled=True)
    )
    k1, rib1 = kernels["r1"], ribs["r1"]

    # Converged: primary via r2, and the backup via r3 rode the install.
    nhs, proto = k1.fib[DEST]
    assert proto == Protocol.OSPFV2
    assert {str(nh.addr) for nh in nhs} == {"10.0.12.2"}
    backups = k1.backups[DEST]
    [(primary, backup)] = backups.items()
    assert str(primary.addr) == "10.0.12.2" and primary.ifname == "e0"
    assert str(backup.addr) == "10.0.13.2" and backup.ifname == "e1"

    # BFD session to r2 drops: O(1) local repair, no SPF involved.
    spf_runs = routers["r1"].spf_run_count
    buses["r1"].publish(
        TOPIC_BFD_STATE, BfdStateUpd(key=("e0", A("10.0.12.2")), state="down")
    )
    loop.run_until_idle()
    nhs, _ = k1.fib[DEST]
    assert {str(nh.addr) for nh in nhs} == {"10.0.13.2"}, "flip to backup"
    assert DEST in rib1.repaired
    # The flip itself never waited for an SPF run.
    assert routers["r1"].spf_run_count == spf_runs

    # Reconvergence: the link actually dies, OSPF floods + reruns SPF,
    # and the republished route clears the repair flag.
    fabric.set_link_up("l12", False)
    loop.advance(60)  # dead interval fires, SPF reruns
    nhs, _ = k1.fib[DEST]
    assert {str(nh.addr) for nh in nhs} == {"10.0.13.2"}
    assert DEST not in rib1.repaired, "reconvergence replaced the repair"


def test_interface_down_triggers_local_repair():
    """Carrier loss (InterfaceUpd operative=False) is the second flip
    trigger: same precomputed backup, no BFD session required."""
    from holo_tpu.utils.ibus import TOPIC_INTERFACE_UPD
    from holo_tpu.utils.southbound import InterfaceUpdMsg

    loop, fabric, buses, kernels, ribs, _ = triangle(FrrConfig(enabled=True))
    k1 = kernels["r1"]
    buses["r1"].publish(
        TOPIC_INTERFACE_UPD,
        InterfaceUpdMsg(ifname="e0", ifindex=1, mtu=1500, operative=False),
    )
    loop.run_until_idle()
    nhs, _ = k1.fib[DEST]
    assert {str(nh.addr) for nh in nhs} == {"10.0.13.2"}
    assert DEST in ribs["r1"].repaired


def test_no_frr_config_no_backups_no_flip():
    """Without fast-reroute config the BFD event leaves the FIB alone
    (nothing precomputed to flip to — reconvergence is the only path)."""
    loop, fabric, buses, kernels, ribs, _ = triangle(None)
    k1 = kernels["r1"]
    assert DEST not in k1.backups
    buses["r1"].publish(
        TOPIC_BFD_STATE, BfdStateUpd(key=("e0", A("10.0.12.2")), state="down")
    )
    loop.run_until_idle()
    nhs, _ = k1.fib[DEST]
    assert {str(nh.addr) for nh in nhs} == {"10.0.12.2"}  # unchanged
    assert DEST not in ribs["r1"].repaired


def test_stub_router_max_metric():
    """RFC 6987: flipping stub-router on re-originates the router-LSA
    with MaxLinkMetric on transit links (stub links keep their cost), so
    neighbors route around us; flipping it off restores the metrics."""
    from holo_tpu.protocols.ospf.packet import (
        MAX_LINK_METRIC,
        LsaType,
        RouterLinkType,
    )

    loop, fabric, buses, kernels, ribs, routers = triangle(None)
    r2 = routers["r2"]
    # A prefix on r3 only: from r1 the cheap path transits r2
    # (10 + 10 + 10 = 30) vs the direct cost-100 link (110).
    far = N("192.168.3.0/24")
    routers["r3"].interface_address_add("e0", far)
    loop.advance(10)
    nhs, _ = kernels["r1"].fib[far]
    assert {str(nh.addr) for nh in nhs} == {"10.0.12.2"}

    r2.set_stub_router(True)
    loop.advance(10)

    def r2_links(viewer):
        area = viewer.areas[AREA0]
        for key, e in area.lsdb.entries.items():
            if key.type == LsaType.ROUTER and key.adv_rtr == A("2.2.2.2"):
                return e.lsa.body.links
        return []

    links = r2_links(routers["r1"])  # as seen by a NEIGHBOR's LSDB
    p2p = [l for l in links if l.link_type == RouterLinkType.POINT_TO_POINT]
    stub = [l for l in links if l.link_type == RouterLinkType.STUB_NETWORK]
    assert p2p and all(l.metric == MAX_LINK_METRIC for l in p2p)
    assert stub and all(l.metric < MAX_LINK_METRIC for l in stub)
    # Transit traffic now avoids r2: r1 reaches r3's prefix directly...
    nhs, _ = kernels["r1"].fib[far]
    assert {str(nh.addr) for nh in nhs} == {"10.0.13.2"}
    # ...while r2's OWN attached prefix stays reachable through r2
    # (stub links keep their real metric — the RFC 6987 point).
    nhs, _ = kernels["r1"].fib[DEST]
    assert {str(nh.addr) for nh in nhs} == {"10.0.12.2"}

    r2.set_stub_router(False)
    loop.advance(10)
    links = r2_links(routers["r1"])
    assert all(
        l.metric < MAX_LINK_METRIC
        for l in links
        if l.link_type == RouterLinkType.POINT_TO_POINT
    )
    nhs, _ = kernels["r1"].fib[far]
    assert {str(nh.addr) for nh in nhs} == {"10.0.12.2"}


def test_mtu_mismatch_blocks_adjacency_mtu_ignore_bypasses():
    """RFC 2328 §10.6: a larger peer MTU sticks the adjacency before
    Full; the mtu-ignore leaf waves the same DD through."""
    from holo_tpu.protocols.ospf.neighbor import NsmState

    def run(mtu_ignore):
        loop = EventLoop(clock=VirtualClock())
        fabric = MockFabric(loop)
        insts = {}
        for name, rid, mtu in [("a", "1.1.1.1", 1400), ("b", "2.2.2.2", 9000)]:
            inst = OspfInstance(
                name=name,
                config=InstanceConfig(router_id=A(rid)),
                netio=fabric.sender_for(name),
            )
            loop.register(inst)
            insts[name] = inst
        cfg_a = IfConfig(
            if_type=IfType.POINT_TO_POINT, mtu=1400, mtu_ignore=mtu_ignore
        )
        cfg_b = IfConfig(if_type=IfType.POINT_TO_POINT, mtu=9000)
        insts["a"].add_interface("e0", cfg_a, N("10.0.0.0/30"), A("10.0.0.1"))
        insts["b"].add_interface("e0", cfg_b, N("10.0.0.0/30"), A("10.0.0.2"))
        fabric.join("l", "a", "e0", A("10.0.0.1"))
        fabric.join("l", "b", "e0", A("10.0.0.2"))
        for inst in insts.values():
            loop.send(inst.name, IfUpMsg("e0"))
        loop.advance(60)
        area = insts["a"].areas[AREA0]
        return [
            n.state
            for i in area.interfaces.values()
            for n in i.neighbors.values()
        ]

    states = run(mtu_ignore=False)
    assert states and all(s < NsmState.FULL for s in states), (
        "MTU mismatch must stall the adjacency"
    )
    states = run(mtu_ignore=True)
    assert states == [NsmState.FULL], "mtu-ignore must bypass the check"


def test_transmit_delay_increments_lsa_age():
    """§13.3: every hop adds the outgoing interface's InfTransDelay to
    the LSA age, so a large configured delay is visible in the
    receiver's LSDB immediately after flooding."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    insts = {}
    for name, rid, delay in [("a", "1.1.1.1", 120), ("b", "2.2.2.2", 1)]:
        inst = OspfInstance(
            name=name,
            config=InstanceConfig(router_id=A(rid)),
            netio=fabric.sender_for(name),
        )
        loop.register(inst)
        cfg = IfConfig(if_type=IfType.POINT_TO_POINT, transmit_delay=delay)
        inst.add_interface("e0", cfg, N("10.0.0.0/30"), A(f"10.0.0.{1 if name == 'a' else 2}"))
        fabric.join("l", name, "e0", A(f"10.0.0.{1 if name == 'a' else 2}"))
        insts[name] = inst
    for inst in insts.values():
        loop.send(inst.name, IfUpMsg("e0"))
    loop.advance(40)
    from holo_tpu.protocols.ospf.packet import LsaType

    # b's copy of a's router-LSA aged >= a's transmit-delay on arrival;
    # a's own copy of its LSA only aged by wall clock (< 40s here).
    now = loop.clock.now()
    for viewer, floor, ceil in [("b", 120, None), ("a", 0, 119)]:
        area = insts[viewer].areas[AREA0]
        ages = [
            e.current_age(now)
            for k, e in area.lsdb.entries.items()
            if k.type == LsaType.ROUTER and k.adv_rtr == A("1.1.1.1")
        ]
        assert ages, f"router-LSA missing in {viewer}"
        assert all(a >= floor for a in ages)
        if ceil is not None:
            assert all(a <= ceil for a in ages)


def test_repair_event_tracking_unit():
    """The RIB repair model under multiple failures and staged recovery:
    events accumulate per prefix, a second failure re-repairs, recovery
    unwinds one event at a time, duplicate events are idempotent, and an
    unrelated protocol's add/del never reverts an active repair."""
    from ipaddress import ip_network

    from holo_tpu.utils.southbound import Nexthop, RouteKeyMsg, RouteMsg

    def mk():
        loop = EventLoop(clock=VirtualClock())
        k = MockKernel()
        rib = RibManager(Ibus(loop), k)
        loop.register(rib)
        rib.attach(loop)
        return rib, k

    pfx = ip_network("10.9.9.0/24")
    nh_a = Nexthop(addr="192.0.2.1", ifname="eth0")
    nh_b = Nexthop(addr="192.0.2.2", ifname="eth1")
    bk_a = Nexthop(addr="198.51.100.1", ifname="eth2")
    bk_b = Nexthop(addr="198.51.100.2", ifname="eth3")

    rib, k = mk()
    rib.route_add(
        RouteMsg(
            protocol=Protocol.OSPFV2, prefix=pfx, distance=110, metric=10,
            nexthops=frozenset({nh_a, nh_b}),
            backups={nh_a: bk_a, nh_b: bk_b},
        )
    )
    # double failure: the second event re-repairs the repaired prefix.
    assert rib.local_repair("eth0") == 1
    assert k.fib[pfx][0] == frozenset({nh_b, bk_a})
    assert rib.local_repair("eth0") == 0, "duplicate event must be a no-op"
    assert rib.local_repair("eth1") == 1
    assert k.fib[pfx][0] == frozenset({bk_a, bk_b})
    # an unrelated (worse) protocol add/del must not revert the repair.
    other = Nexthop(addr="203.0.113.3", ifname="eth4")
    rib.route_add(
        RouteMsg(protocol=Protocol.RIPV2, prefix=pfx, distance=120,
                 metric=5, nexthops=frozenset({other}))
    )
    assert pfx in rib.repaired and k.fib[pfx][0] == frozenset({bk_a, bk_b})
    rib.route_del(RouteKeyMsg(Protocol.RIPV2, pfx))
    assert pfx in rib.repaired and k.fib[pfx][0] == frozenset({bk_a, bk_b})
    # staged recovery: one event unwinds, the other stays repaired.
    assert rib.local_restore("eth1") == 1
    assert k.fib[pfx][0] == frozenset({nh_b, bk_a}) and pfx in rib.repaired
    assert rib.local_restore("eth0") == 1
    assert k.fib[pfx][0] == frozenset({nh_a, nh_b})
    assert pfx not in rib.repaired

    # a withdrawn route takes its repair along: no resurrection later.
    rib, k = mk()
    rib.route_add(
        RouteMsg(protocol=Protocol.OSPFV2, prefix=pfx, distance=110,
                 metric=10, nexthops=frozenset({nh_a}), backups={nh_a: bk_a})
    )
    assert rib.local_repair("eth0") == 1
    rib.route_add(
        RouteMsg(protocol=Protocol.DIRECT, prefix=pfx, distance=0,
                 metric=0, nexthops=frozenset())
    )
    assert pfx not in rib.repaired
    assert rib.local_restore("eth0") == 0 and pfx not in k.fib

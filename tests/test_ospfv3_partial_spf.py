"""OSPFv3 partial SPF (reference ospfv3/spf.rs:97-163 classification,
route.rs:200-333 update_rib_partial): prefix-only changes skip Dijkstra."""

from ipaddress import IPv4Address as A
from ipaddress import IPv6Address as A6
from ipaddress import IPv6Network as N6

from holo_tpu.protocols.ospf.instance_v3 import V3IfUpMsg
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock

from tests.test_ospfv3 import mk_v3, v6link


class _CountingBackend:
    def __init__(self, inner):
        self.inner = inner
        self.name = inner.name
        self.computes = 0

    def compute(self, topo, multipath_k: int = 1):
        self.computes += 1
        return self.inner.compute(topo, multipath_k=multipath_k)


def _pair():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = mk_v3(loop, fabric, "w1", "1.1.1.1")
    r2 = mk_v3(loop, fabric, "w2", "2.2.2.2")
    v6link(fabric, "l12", r1, "e0", "fe80::1:1", r2, "e0", "fe80::2:1")
    for r in (r1, r2):
        for ifname in r.interfaces:
            loop.send(r.name, V3IfUpMsg(ifname))
    loop.advance(60)
    return loop, r1, r2


def _chain():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = mk_v3(loop, fabric, "w1", "1.1.1.1")
    r2 = mk_v3(loop, fabric, "w2", "2.2.2.2")
    r3 = mk_v3(loop, fabric, "w3", "3.3.3.3")
    v6link(fabric, "l12", r1, "e0", "fe80::1:1", r2, "e0", "fe80::2:1")
    v6link(fabric, "l23", r2, "e1", "fe80::2:2", r3, "e0", "fe80::3:1")
    for r in (r1, r2, r3):
        for ifname in r.interfaces:
            loop.send(r.name, V3IfUpMsg(ifname))
    loop.advance(60)
    return loop, r1, r2, r3


def test_intra_prefix_change_is_partial():
    """A REMOTE router's prefix change reaches us as an
    Intra-Area-Prefix change only (its Link-LSA is link-scope and never
    leaves its own link): partial run, no Dijkstra.  A later withdrawal
    drops the route (old+new prefix-set merge).  On an attached link the
    neighbor's Link-LSA changes too, correctly forcing Full — same as
    the reference (ospfv3/spf.rs:106-113)."""
    loop, r1, r2, r3 = _chain()
    counter = _CountingBackend(r1.backend)
    r1.backend = counter
    r3.interfaces["e0"].prefixes.append(N6("2001:db8:33::/64"))
    r3._originate_intra_area_prefix()
    loop.advance(30)
    assert counter.computes == 0, (
        "remote intra-area-prefix-only change must not re-run Dijkstra"
    )
    assert r1.spf_log[-1]["type"] == "intra"
    assert N6("2001:db8:33::/64") in r1.routes

    # Withdrawal: the prefix disappears from the new LSA but lives in the
    # OLD one — the merged old+new set must still cover it.
    r3.interfaces["e0"].prefixes.remove(N6("2001:db8:33::/64"))
    r3._originate_intra_area_prefix()
    loop.advance(30)
    assert counter.computes == 0
    assert N6("2001:db8:33::/64") not in r1.routes


def test_v3_external_change_is_partial():
    loop, r1, r2 = _pair()
    # Prime ASBR status (first redistribution re-originates the
    # router-LSA with the E bit — a legitimate full run).
    r2.redistribute(N6("2001:db8:aa::/48"), metric=5)
    loop.advance(30)
    counter = _CountingBackend(r1.backend)
    r1.backend = counter
    r2.redistribute(N6("2001:db8:bb::/48"), metric=7)
    loop.advance(30)
    assert counter.computes == 0
    assert r1.spf_log[-1]["type"] == "external"
    assert N6("2001:db8:bb::/48") in r1.routes


def test_v3_partial_matches_full():
    loop, r1, r2 = _pair()
    r2.redistribute(N6("2001:db8:aa::/48"), metric=5)
    r2.interfaces["e0"].prefixes.append(N6("2001:db8:22::/64"))
    r2._originate_intra_area_prefix()
    loop.advance(30)
    partial = {
        p: (r.dist, r.nexthops, r.route_type) for p, r in r1.routes.items()
    }
    r1._schedule_spf()  # force full
    loop.advance(30)
    assert r1.spf_log[-1]["type"] == "full"
    full = {
        p: (r.dist, r.nexthops, r.route_type) for p, r in r1.routes.items()
    }
    assert partial == full


def test_intra_withdrawal_falls_back_to_inter_candidate():
    """A withdrawn intra prefix with a still-valid inter-area path must
    fall back to it in the partial run (r5 review: the candidate table
    covers intra-won prefixes too)."""
    from holo_tpu.protocols.ospf import packet_v3 as P
    from ipaddress import IPv4Address

    loop, r1, r2, r3 = _chain()
    shared = N6("2001:db8:77::/64")
    # r3 advertises `shared` intra-area; an inter-area-prefix LSA for the
    # same prefix also exists (injected as if from another area's ABR —
    # r2 originates it here for simplicity via direct install on r1's
    # area through the flooding path).
    r3.interfaces["e0"].prefixes.append(shared)
    r3._originate_intra_area_prefix()
    loop.advance(30)
    assert r1.routes[shared].route_type == "intra-area"

    # Inject an inter-area candidate from r2 (an ABR-shaped source).
    area2 = next(iter(r2.areas.values()))
    lsa = P.Lsa(
        age=0, type=P.LsaType.INTER_AREA_PREFIX,
        lsid=IPv4Address("0.0.9.9"), adv_rtr=r2.router_id, seq_no=-99,
        body=P.LsaInterAreaPrefix(metric=44, prefix=shared),
    )
    lsa.encode()
    r2._install_and_flood(area2, lsa)
    loop.advance(30)
    assert r1.routes[shared].route_type == "intra-area"  # intra wins

    counter = _CountingBackend(r1.backend)
    r1.backend = counter
    # Withdraw the intra prefix: partial run must fall back to inter.
    r3.interfaces["e0"].prefixes.remove(shared)
    r3._originate_intra_area_prefix()
    loop.advance(30)
    assert counter.computes == 0
    got = r1.routes.get(shared)
    assert got is not None and got.route_type == "inter-area", got
    assert got.dist == 10 + 44


def test_v3_spf_log_in_daemon_state():
    """Daemon state exposes the v3 SPF log with run types (VERDICT r4:
    the log distinguishes full/partial in YANG state), like v2/IS-IS."""
    import ipaddress

    from holo_tpu.daemon.daemon import Daemon

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="y1")
    d2 = Daemon(loop=loop, netio=fabric, name="y2")
    fabric.join("l", "y1.ospfv3", "eth0", ipaddress.ip_address("fe80::1"))
    fabric.join("l", "y2.ospfv3", "eth0", ipaddress.ip_address("fe80::2"))
    for d, rid, ll, pfx in [
        (d1, "1.1.1.1", "fe80::1/64", "2001:db8:1::1/64"),
        (d2, "2.2.2.2", "fe80::2/64", "2001:db8:2::1/64"),
    ]:
        cand = d.candidate()
        cand.set("interfaces/interface[eth0]/address", [ll, pfx])
        cand.set("routing/control-plane-protocols/ospfv3/router-id", rid)
        cand.set(
            "routing/control-plane-protocols/ospfv3/area[0.0.0.0]"
            "/interface[eth0]/cost", 4,
        )
        d.commit(cand)
    loop.advance(60)
    # A remote redistribution change produces an "external" partial run.
    inst2 = d2.routing.instances["ospfv3"]
    inst2.redistribute(N6("2001:db8:aa::/48"), metric=5)
    loop.advance(30)
    inst2.redistribute(N6("2001:db8:bb::/48"), metric=6)
    loop.advance(30)
    log = d1.northbound.get_state()["routing"]["ospfv3"]["spf-log"]
    types = {e["type"] for e in log}
    assert "full" in types and "external" in types, types

"""Vectorized multipath (ISSUE 10): device multi-parent planes
bit-identical to the scalar multipath oracle — plain, DeltaPath
incremental, sharded-mesh and breaker-fallback arms, all under
``jax.transfer_guard("disallow")`` — plus the policy/consumption seams
(FRR SRLG + node-protection masks, max-paths route clamping, weighted
RIB install, RFC 8333 delayed flip, advisory what-if batching, and the
off-critical-path FRR force).
"""

from contextlib import contextmanager

import numpy as np
import pytest

from holo_tpu import pipeline, telemetry
from holo_tpu.frr.manager import FrrConfig, FrrEngine
from holo_tpu.frr.scalar import frr_reference
from holo_tpu.ops.graph import INF, MP_SAT, diff_topologies
from holo_tpu.parallel.mesh import (
    configure_process_mesh,
    reset_process_mesh,
)
from holo_tpu.resilience.breaker import CircuitBreaker
from holo_tpu.resilience.faults import FaultInjector, FaultPlan, inject
from holo_tpu.spf.backend import ScalarSpfBackend, TpuSpfBackend
from holo_tpu.spf.synth import (
    clone_topology as clone,
    random_ospf_topology,
    whatif_link_failure_masks,
)
from holo_tpu.testing import no_implicit_transfers

MP_FIELDS = ("parents", "pdist", "pweight", "npaths", "nh_weights")
ALL_FIELDS = ("dist", "parent", "hops", "nexthop_words") + MP_FIELDS


def tied(seed, n=36, nets=7, extra=50):
    """Random topology with a tiny cost universe: real ECMP ties."""
    return random_ospf_topology(
        n, n_networks=nets, extra_p2p=extra, max_cost=4, seed=seed
    )


def assert_same(a, b, tag=""):
    for f in ALL_FIELDS:
        x, y = getattr(a, f), getattr(b, f)
        if x is None or y is None:
            assert x is None and y is None, (tag, f)
        else:
            assert np.array_equal(x, y), (tag, f)


@contextmanager
def mesh_scope(n_batch=None, n_node=None):
    mesh = configure_process_mesh(n_batch, n_node)
    try:
        yield mesh
    finally:
        reset_process_mesh()


# ---------------------------------------------------------------- parity


def test_multipath_device_bit_identical_to_oracle():
    """Seeded property sweep: every multipath plane (parents, per-parent
    costs/weights, path counts, per-atom UCMP weights) AND the classic
    SpfTensors half are bit-identical to the scalar multipath oracle
    across widths, with real equal-cost ties in every graph."""
    oracle = ScalarSpfBackend()
    with no_implicit_transfers():
        tpu = TpuSpfBackend()
        for seed in range(4):
            topo = tied(seed)
            for k in (2, 3, 8):  # 3 exercises the pow2 pad (-> 4)
                res = tpu.compute(topo, multipath_k=k)
                ref = oracle.compute(topo, multipath_k=k)
                assert_same(res, ref, tag=(seed, k))
                # Width contract: pow2-padded parent-set planes.
                assert res.parents.shape[1] in (2, 4, 8)
                # Somebody actually has multiple equal-cost parents.
                ecmp = (res.pdist == res.dist[:, None]) & (
                    res.parents < topo.n_vertices
                )
                assert (ecmp.sum(axis=1) > 1).any()


def test_multipath_k1_is_the_unchanged_single_parent_dispatch():
    """multipath off (k=1): no planes, and byte-identical output to the
    pre-change call shape — the multipath_overhead gate's contract."""
    with no_implicit_transfers():
        tpu = TpuSpfBackend()
        topo = tied(9)
        plain = tpu.compute(topo)
        k1 = tpu.compute(topo, multipath_k=1)
        for f in MP_FIELDS:
            assert getattr(plain, f) is None and getattr(k1, f) is None
        for f in ("dist", "parent", "hops", "nexthop_words"):
            assert np.array_equal(getattr(plain, f), getattr(k1, f))


def test_multipath_delta_chain_incremental_and_bit_identical():
    """DeltaPath arm: a chain of weight deltas rides the widened
    incremental kernel (donated multipath tensors) and every step stays
    bit-identical to a from-scratch oracle run."""
    oracle = ScalarSpfBackend()
    with no_implicit_transfers():
        tpu = TpuSpfBackend()
        topo = tied(21)
        before = telemetry.snapshot(prefix="holo_spf_delta").get(
            "holo_spf_delta_total{kind=weight,path=incremental}", 0.0
        )
        tpu.compute(topo, multipath_k=4)  # roots the chain
        cur = topo
        for step in range(5):
            e = (step * 3) % cur.n_edges
            nxt = clone(cur, cost={e: int(cur.edge_cost[e]) + 1 + step})
            delta = diff_topologies(cur, nxt)
            assert delta is not None
            nxt.link_delta(delta)
            res = tpu.compute(nxt, multipath_k=4)
            assert_same(res, oracle.compute(nxt, multipath_k=4), tag=step)
            cur = nxt
        after = telemetry.snapshot(prefix="holo_spf_delta").get(
            "holo_spf_delta_total{kind=weight,path=incremental}", 0.0
        )
        assert after - before >= 5.0, "chain fell off the delta path"


def test_multipath_chain_width_change_degrades_to_full_no_prev():
    """A max-paths reconfigure mid-chain must never donate wrong-width
    tensors: the next delta for that root degrades to full-no-prev."""
    with no_implicit_transfers():
        tpu = TpuSpfBackend()
        topo = tied(5)
        tpu.compute(topo, multipath_k=2)
        nxt = clone(topo, cost={0: int(topo.edge_cost[0]) + 2})
        delta = diff_topologies(topo, nxt)
        nxt.link_delta(delta)
        before = telemetry.snapshot(prefix="holo_spf_delta").get(
            "holo_spf_delta_total{kind=weight,path=full-no-prev}", 0.0
        )
        res = tpu.compute(nxt, multipath_k=8)  # width flip mid-chain
        after = telemetry.snapshot(prefix="holo_spf_delta").get(
            "holo_spf_delta_total{kind=weight,path=full-no-prev}", 0.0
        )
        assert after - before >= 1.0
        assert_same(
            res, ScalarSpfBackend().compute(nxt, multipath_k=8), "width"
        )


def test_multipath_sharded_mesh_bit_identical():
    """Sharded arm: the multipath what-if batch dispatched over the
    (batch, node) process mesh is byte-identical to the single-device
    program and the oracle; the shard counter proves the real path."""
    topo = tied(13)
    masks = whatif_link_failure_masks(topo, 6, seed=3)
    oracle = ScalarSpfBackend()
    ref = oracle.compute_whatif(topo, masks, multipath_k=4)
    with no_implicit_transfers():
        plain = TpuSpfBackend().compute_whatif(topo, masks, multipath_k=4)
        for shape in ((4, 2), (2, 4)):
            with mesh_scope(*shape):
                before = telemetry.snapshot(
                    prefix="holo_spf_shard_dispatch"
                ).get("holo_spf_shard_dispatch_total{kind=whatif}", 0.0)
                res = TpuSpfBackend().compute_whatif(
                    topo, masks, multipath_k=4
                )
                after = telemetry.snapshot(
                    prefix="holo_spf_shard_dispatch"
                ).get("holo_spf_shard_dispatch_total{kind=whatif}", 0.0)
                assert after == before + 1
            for i in range(len(masks)):
                assert_same(res[i], ref[i], tag=("shard", shape, i))
                assert_same(res[i], plain[i], tag=("plain", shape, i))


def test_multipath_breaker_fallback_bit_identical():
    """Breaker arm: forced dispatch failures serve the multipath result
    from the scalar oracle — planes included, bit-identical."""
    topo = tied(17)
    want = ScalarSpfBackend().compute(topo, multipath_k=4)
    breaker = CircuitBreaker("mp-test", failure_threshold=10)
    tpu = TpuSpfBackend(breaker=breaker)
    plan = FaultPlan(seed=1, dispatch_fail={"spf.dispatch": 2})
    with inject(FaultInjector(plan)) as inj:
        r1 = tpu.compute(topo, multipath_k=4)
        r2 = tpu.compute(topo, multipath_k=4)
    assert inj.injected["spf.dispatch"] == 2
    assert_same(r1, want, "fallback-1")
    assert_same(r2, want, "fallback-2")


def test_multipath_invariants_property_sweep():
    """The fuzz target's loop-free/weight-consistency invariants hold
    across a seeded grid (the in-tree arm of ``multipath_invariants``)."""
    from holo_tpu.tools.fuzz import multipath_invariants

    for kind in range(3):
        for size in (1, 3, 5):
            for seed in (0, 11, 200):
                for kbyte in range(4):
                    multipath_invariants(bytes([kind, size, seed, kbyte]))


def test_saturation_is_shared_and_exact():
    """Path counts clamp identically on both engines (MP_SAT contract):
    a dense tied mesh overflows the counter and stays bit-identical."""
    # Parallel equal-cost two-hop ladders double the path count per
    # stage: 2^20 paths saturate at MP_SAT = 2^17.
    n = 44  # 22 ladder stages
    src, dst, cost = [], [], []
    for i in range(0, n - 2, 2):
        for a in (i, i + 1):
            for b in (i + 2, i + 3):
                src += [a, b]
                dst += [b, a]
                cost += [1, 1]
    from holo_tpu.ops.graph import Topology

    topo = Topology(
        n_vertices=n,
        is_router=np.ones(n, bool),
        edge_src=np.array(src, np.int32),
        edge_dst=np.array(dst, np.int32),
        edge_cost=np.array(cost, np.int32),
        root=0,
    )
    from holo_tpu.spf.synth import assign_direct_atoms

    assign_direct_atoms(topo)
    ref = ScalarSpfBackend().compute(topo, multipath_k=2)
    assert int(ref.npaths.max()) == int(MP_SAT), "ladder must saturate"
    with no_implicit_transfers():
        res = TpuSpfBackend().compute(topo, multipath_k=2)
    assert_same(res, ref, "saturation")


# ------------------------------------------------- FRR policy masks


def srlg_topo(seed=3):
    topo = tied(seed, n=24, nets=4, extra=30)
    rng = np.random.default_rng(seed)
    topo.edge_srlg = rng.integers(0, 8, topo.n_edges).astype(np.uint32)
    topo.touch()
    return topo


@pytest.mark.parametrize(
    "srlg,nodeprot", [(True, False), (False, True), (True, True)]
)
def test_frr_policy_masks_device_scalar_parity(srlg, nodeprot):
    """SRLG-disjoint and node-protection policy masks: the vectorized
    kernel and the scalar oracle agree bit-for-bit under every flag
    combination."""
    topo = srlg_topo()
    policy = FrrConfig(
        enabled=True, engine="tpu",
        srlg_disjoint=srlg, node_protection=nodeprot,
    )
    eng = FrrEngine(engine="tpu")
    eng.set_policy(policy)
    with no_implicit_transfers():
        dev = eng.compute(topo)
    ref = frr_reference(
        topo, srlg_disjoint=srlg, node_protection=nodeprot
    )
    for f in (
        "lfa_adj", "lfa_nodeprot", "rlfa_pq", "tilfa_p", "tilfa_q",
        "post_dist", "post_nh",
    ):
        assert np.array_equal(getattr(dev, f), getattr(ref, f)), f


def test_frr_srlg_policy_actually_excludes():
    """Armed SRLG policy must change selections on a topology whose
    best LFA shares a risk group with its protected link (and the
    excluded candidate never shares a group when armed)."""
    topo = srlg_topo(7)
    off = frr_reference(topo)
    on = frr_reference(topo, srlg_disjoint=True)
    assert not np.array_equal(off.lfa_adj, on.lfa_adj), (
        "seed produced no SRLG conflict; pick another"
    )
    fin = on.inputs
    for l in range(fin.n_links):
        for d in range(on.lfa_adj.shape[1]):
            a = int(on.lfa_adj[l, d])
            if a >= 0:
                assert (
                    int(fin.link_srlg[l]) & int(fin.adj_srlg[a])
                ) == 0


def test_frr_node_protection_policy_restricts():
    topo = srlg_topo(11)
    on = frr_reference(topo, node_protection=True)
    sel = on.lfa_adj >= 0
    # Every selected LFA under the policy is node-protecting.
    assert np.all(on.lfa_nodeprot[sel] == 1)


def test_per_prefix_protection_filtering():
    import ipaddress

    cfg = FrrConfig(
        enabled=True,
        protected_prefixes=(ipaddress.ip_network("10.1.0.0/16"),),
    )
    assert cfg.protects_prefix(ipaddress.ip_network("10.1.2.0/24"))
    assert not cfg.protects_prefix(ipaddress.ip_network("10.2.2.0/24"))
    assert FrrConfig(enabled=True).protects_prefix(
        ipaddress.ip_network("10.2.2.0/24")
    )


# ------------------------------------------------- RIB consumption


def _mk_rib(microloop_delay=0.0):
    from holo_tpu.routing.rib import MockKernel, RibManager
    from holo_tpu.utils.ibus import Ibus
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    loop = EventLoop(clock=VirtualClock())
    bus = Ibus(loop)
    kernel = MockKernel()
    rib = RibManager(bus, kernel, microloop_delay=microloop_delay)
    loop.register(rib)
    return loop, rib, kernel


def _route(prefix, nhs, weights=None, backups=None):
    import ipaddress

    from holo_tpu.utils.southbound import Nexthop, Protocol, RouteMsg

    hops = frozenset(
        Nexthop(addr=ipaddress.ip_address(a), ifname=i) for i, a in nhs
    )
    by_addr = {
        str(nh.addr): nh for nh in hops
    }
    return RouteMsg(
        protocol=Protocol.OSPFV2,
        prefix=ipaddress.ip_network(prefix),
        distance=110,
        metric=10,
        nexthops=hops,
        nh_weights={
            by_addr[a]: w for a, w in (weights or {}).items()
        },
        backups={
            by_addr[a]: Nexthop(
                addr=ipaddress.ip_address(b[1]), ifname=b[0]
            )
            for a, b in (backups or {}).items()
        },
    )


def test_rib_weighted_multipath_install():
    import ipaddress

    loop, rib, kernel = _mk_rib()
    msg = _route(
        "10.9.0.0/24",
        [("e0", "10.0.0.2"), ("e1", "10.0.1.2")],
        weights={"10.0.0.2": 3, "10.0.1.2": 1},
    )
    rib.route_add(msg)
    prefix = ipaddress.ip_network("10.9.0.0/24")
    nhs, _proto = kernel.fib[prefix]
    assert len(nhs) == 2
    w = kernel.weights[prefix]
    assert sorted(w.values()) == [1, 3]
    assert kernel.multipath_installs >= 1
    assert kernel.weighted_installs >= 1


def test_rib_microloop_delayed_flip():
    """RFC 8333: a reconvergence install replacing an ACTIVE repair is
    delayed by the configured window (repair keeps forwarding), then
    installed when the timer fires; a second reconvergence inside the
    window supersedes the pending install."""
    import ipaddress

    loop, rib, kernel = _mk_rib(microloop_delay=5.0)
    prefix = ipaddress.ip_network("10.9.0.0/24")
    msg = _route(
        "10.9.0.0/24",
        [("e0", "10.0.0.2"), ("e1", "10.0.1.2")],
        backups={"10.0.0.2": ("e1", "10.0.1.2")},
    )
    rib.route_add(msg)
    assert rib.local_repair("e0") == 1  # flip onto the backup
    assert prefix in rib.repaired
    survivors, _ = kernel.fib[prefix]
    assert {str(nh.addr) for nh in survivors} == {"10.0.1.2"}

    # Reconvergence republishes the prefix: the flip-back is DELAYED.
    msg2 = _route("10.9.0.0/24", [("e0", "10.0.0.3")])
    rib.route_add(msg2)
    assert prefix in rib.repaired, "repair dropped inside the window"
    survivors, _ = kernel.fib[prefix]
    assert {str(nh.addr) for nh in survivors} == {"10.0.1.2"}
    snap = telemetry.snapshot(prefix="holo_rib_microloop")
    assert snap.get("holo_rib_microloop_delays_total", 0) >= 1

    loop.advance(6.0)  # window expires -> delayed install happens
    assert prefix not in rib.repaired
    survivors, _ = kernel.fib[prefix]
    assert {str(nh.addr) for nh in survivors} == {"10.0.0.3"}


def test_rib_microloop_failure_during_window_keeps_repair():
    """A NEW failure inside the microloop window re-flips against the
    held message; window expiry must keep that repair instead of
    reinstalling the raw primaries (which contain the failed hop)."""
    import ipaddress

    loop, rib, kernel = _mk_rib(microloop_delay=5.0)
    prefix = ipaddress.ip_network("10.9.0.0/24")
    rib.route_add(
        _route(
            "10.9.0.0/24",
            [("e0", "10.0.0.2")],
            backups={"10.0.0.2": ("e1", "10.0.1.2")},
        )
    )
    rib.local_repair("e0")  # first failure: repair onto e1
    # Reconvergence around the failure: new primary on e2 (held).
    msg2 = _route(
        "10.9.0.0/24",
        [("e2", "10.0.2.1")],
        backups={"10.0.2.1": ("e3", "10.0.3.1")},
    )
    rib.route_add(msg2)
    assert prefix in rib.repaired
    # SECOND failure during the window hits the held msg's primary.
    assert rib.local_repair("e2") == 1
    survivors, _ = kernel.fib[prefix]
    assert {str(nh.addr) for nh in survivors} == {"10.0.3.1"}
    loop.advance(6.0)  # window expires
    # The repair survives; the dead 10.0.2.1 primary is NOT reinstalled.
    assert prefix in rib.repaired
    survivors, _ = kernel.fib[prefix]
    assert {str(nh.addr) for nh in survivors} == {"10.0.3.1"}


def test_ospfv3_clamp_consumes_ucmp_weights():
    """The v3 max-paths clamp ranks by the multipath dispatch's UCMP
    weights (highest mass survives), tie-broken by lowest address."""
    import ipaddress
    import types

    from holo_tpu.protocols.ospf.instance_v3 import OspfV3Instance, V6Route

    atoms = [
        ("e0", ipaddress.ip_address("fe80::1")),
        ("e1", ipaddress.ip_address("fe80::2")),
        ("e2", ipaddress.ip_address("fe80::3")),
    ]
    words = np.zeros((4, 2), np.uint32)
    words[3, 0] = 0b111
    nhw = np.zeros((4, 64), np.int32)
    nhw[3, :3] = (5, 1, 9)
    res = types.SimpleNamespace(
        dist=np.zeros(4, np.int32), nexthop_words=words, nh_weights=nhw
    )
    route = V6Route(
        prefix=ipaddress.ip_network("2001:db8::/64"), dist=10,
        nexthops=frozenset(atoms), area_id="0.0.0.0", vertex=3,
    )
    routes = {route.prefix: route}
    stub = types.SimpleNamespace(max_paths=2)
    OspfV3Instance._clamp_max_paths(
        stub, routes, {"0.0.0.0": (None, None, res, atoms, None)}
    )
    assert routes[route.prefix].nexthops == frozenset(
        {atoms[0], atoms[2]}
    )  # weights 5 and 9 survive; weight-1 e1 is clamped off


def test_ospfv2_inter_and_external_routes_clamp_too():
    """max-paths applies to the whole v2 table: inter/external routes
    (raw SPF next-hop sets via their ABR vertex) clamp in _finish_spf
    exactly like intra routes."""
    import ipaddress

    from holo_tpu.protocols.ospf.spf_run import (
        IntraRoute,
        RouteNexthop,
        clamp_multipath,
    )

    nhs = frozenset(
        RouteNexthop(f"e{i}", ipaddress.ip_address(f"10.0.{i}.2"))
        for i in range(4)
    )
    routes = {
        ipaddress.ip_network("10.50.0.0/16"): IntraRoute(
            ipaddress.ip_network("10.50.0.0/16"), 20, nhs,
            ipaddress.ip_address("0.0.0.0"), rtype="inter",
        )
    }
    assert clamp_multipath(routes, 2) == 1
    kept = routes[ipaddress.ip_network("10.50.0.0/16")].nexthops
    assert len(kept) == 2
    assert {str(nh.addr) for nh in kept} == {"10.0.0.2", "10.0.1.2"}


def test_rib_microloop_zero_delay_is_immediate():
    import ipaddress

    loop, rib, kernel = _mk_rib()
    prefix = ipaddress.ip_network("10.9.0.0/24")
    rib.route_add(
        _route(
            "10.9.0.0/24",
            [("e0", "10.0.0.2")],
            backups={"10.0.0.2": ("e1", "10.0.1.2")},
        )
    )
    rib.local_repair("e0")
    rib.route_add(_route("10.9.0.0/24", [("e0", "10.0.0.3")]))
    assert prefix not in rib.repaired
    survivors, _ = kernel.fib[prefix]
    assert {str(nh.addr) for nh in survivors} == {"10.0.0.3"}


# --------------------------------------- protocol + pipeline satellites


@pytest.fixture(autouse=True)
def _clean_pipeline():
    yield
    pipeline.reset_process_pipeline()


def test_storm_multipath_arm_installs_sets_and_weights():
    """e2e: the dual-gateway storm with max-paths=2 installs REAL
    next-hop sets with UCMP weights, deterministically."""
    from holo_tpu.spf.synth_storm import run_convergence_storm

    digs = []
    for _ in range(2):
        rep, dig, _net = run_convergence_storm(
            n_routers=60, events=24, seed=17,
            spf_backend=TpuSpfBackend(), max_paths=2,
        )
        digs.append(dig)
    assert digs[0] == digs[1]
    assert rep["fib-multipath"] > 0
    assert rep["fib-weighted"] > 0


def test_whatif_advisory_rides_pipeline_and_coalesces():
    """Satellite 1 e2e: OSPF enqueues advisory what-if batches through
    the pipeline after each SPF; rapid successive SPF runs coalesce
    (newer generation supersedes the queued older batch)."""
    from holo_tpu.spf.synth_storm import StormNet

    with no_implicit_transfers():
        pipe = pipeline.configure_process_pipeline(
            depth=1, guard=no_implicit_transfers
        )
        be = pipeline.wrap_spf_backend(TpuSpfBackend())
        net = StormNet(n_routers=60, seed=33, spf_backend=be)
        net.inst.config.whatif_advisory = 4
        before = telemetry.snapshot(prefix="holo_pipeline_coalesced")
        for i in range(6):
            net.flap(net.flappable[i], lost=False)
            net.loop.advance(6.0)
        net.loop.advance(40.0)
        pipe.drain(timeout=20)
        after = telemetry.snapshot(prefix="holo_pipeline_coalesced")
        stats = net.inst._whatif_stats
        assert stats["enqueued"] >= 2
        coalesced = sum(after.values()) - sum(before.values())
        done = stats["completed"]
        # Every enqueued batch either completed or was coalesced away.
        assert done > 0
        assert coalesced + done >= stats["enqueued"]


def test_frr_force_moves_off_spf_critical_path():
    """Satellite 2 e2e: with the pipeline armed and a tpu FRR engine,
    the SPF path never forces the LazyBackupTable — the worker's
    done-callback posts FrrTablesReadyMsg, the actor attaches backups
    afterwards, and ``holo_pipeline_wait_seconds{kind=frr}`` records no
    SPF-path wait."""
    from holo_tpu.spf.synth_storm import StormNet

    with no_implicit_transfers():
        pipe = pipeline.configure_process_pipeline(
            depth=2, guard=no_implicit_transfers
        )
        be = pipeline.wrap_spf_backend(TpuSpfBackend())
        net = StormNet(n_routers=60, seed=33, spf_backend=be)
        net.inst.config.frr = FrrConfig(enabled=True, engine="tpu")
        wait_before = telemetry.snapshot(
            prefix="holo_pipeline_wait"
        ).get("holo_pipeline_wait_seconds{kind=frr}", {"count": 0})
        for i in range(3):
            net.flap(net.flappable[i], lost=False)
            net.loop.advance(12.0)
        net.loop.advance(40.0)
        pipe.drain(timeout=20)
        # Deliver the cross-thread FrrTablesReadyMsg.
        net.loop.advance(1.0)
        wait_after = telemetry.snapshot(
            prefix="holo_pipeline_wait"
        ).get("holo_pipeline_wait_seconds{kind=frr}", {"count": 0})
        assert wait_after["count"] == wait_before["count"], (
            "the SPF path paid an FRR force wait"
        )
        # The deferred attach happened: routes carry backups.
        assert any(
            getattr(r, "backups", None) for r in net.inst.routes.values()
        )

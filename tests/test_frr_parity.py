"""Bit-identical parity: batched FRR kernel vs scalar oracle.

Acceptance gate for the FRR subsystem (ISSUE 1): every backup table —
LFA pick + node-protection flag, remote-LFA PQ coverage, TI-LFA (P, Q)
segments, post-convergence dist/next-hops — must match the scalar
oracle exactly over the synth topology family (ring, grid, fat-tree,
random, with and without LAN pseudo-nodes).
"""

import numpy as np
import pytest

from holo_tpu.frr.manager import FrrConfig, FrrEngine, resolve_backup
from holo_tpu.ops.graph import INF
from holo_tpu.spf.synth import (
    fat_tree_topology,
    grid_topology,
    random_ospf_topology,
    ring_topology,
)
from holo_tpu.testing import no_implicit_transfers

N_ATOMS = 64


@pytest.fixture(autouse=True)
def _transfer_sanitizer():
    """Every FRR parity test runs under jax.transfer_guard('disallow'):
    only the engine's sanctioned marshal/unmarshal boundary may move
    data between host and device (holo-lint runtime mode)."""
    with no_implicit_transfers():
        yield


def assert_table_parity(scalar, tpu):
    for name in (
        "lfa_adj",
        "lfa_nodeprot",
        "rlfa_pq",
        "tilfa_p",
        "tilfa_q",
        "post_dist",
        "post_nh",
    ):
        np.testing.assert_array_equal(
            getattr(scalar, name), getattr(tpu, name), err_msg=name
        )


def _topos(seed):
    return {
        "ring": ring_topology(10, seed=seed),
        "grid": grid_topology(4, 4, seed=seed),
        "fat-tree": fat_tree_topology(k=4, seed=seed),
        "random": random_ospf_topology(
            n_routers=10, n_networks=3, seed=seed
        ),
    }


@pytest.mark.parametrize("seed", range(3))
@pytest.mark.parametrize("shape", ["ring", "grid", "fat-tree", "random"])
def test_frr_kernel_oracle_parity(seed, shape):
    topo = _topos(seed)[shape]
    scalar = FrrEngine("scalar", N_ATOMS).compute(topo)
    tpu = FrrEngine("tpu", N_ATOMS).compute(topo)
    assert_table_parity(scalar, tpu)


def test_ring_uniform_cost_needs_remote_repair():
    """The textbook rLFA case: a uniform-cost ring has destinations with
    no per-neighbor LFA; rLFA/TI-LFA must cover (nearly) all of them."""
    topo = ring_topology(8, max_cost=1, seed=0)  # uniform costs
    table = FrrEngine("scalar", N_ATOMS).compute(topo)
    eligible = table.post_dist < INF
    eligible[:, topo.root] = False
    lfa_only = (table.lfa_adj >= 0) & eligible
    assert lfa_only.sum() < eligible.sum(), "ring should defeat plain LFA"
    assert table.coverage() == 1.0, "rLFA/TI-LFA should cover the ring"


def test_resolve_backup_policy_order():
    topo = ring_topology(8, max_cost=1, seed=0)
    table = FrrEngine("scalar", N_ATOMS).compute(topo)
    cfg_all = FrrConfig(enabled=True, remote_lfa=True, ti_lfa=True)
    cfg_lfa = FrrConfig(enabled=True)
    got_kinds = set()
    for l in range(table.n_links):
        for d in range(topo.n_vertices):
            if d == topo.root:
                continue
            e = resolve_backup(table, cfg_all, l, d)
            if e is not None:
                got_kinds.add(e.kind)
                if e.kind == "lfa":
                    assert e.atom is not None and e.atom >= 0
                else:
                    # LFA-disabled policy must yield nothing where only
                    # remote repairs exist.
                    assert resolve_backup(table, cfg_lfa, l, d) is None
            assert resolve_backup(table, FrrConfig(), l, d) is None
    assert "rlfa" in got_kinds or "ti-lfa" in got_kinds


def test_padding_is_result_neutral():
    """Growing the pad bucket must not change any table entry (the fuzz
    target's invariant, pinned here deterministically)."""
    from holo_tpu.frr.inputs import marshal_frr
    from holo_tpu.frr.scalar import frr_reference

    topo = random_ospf_topology(n_routers=8, n_networks=2, seed=4)
    a = frr_reference(topo, N_ATOMS, inputs=marshal_frr(topo, pad_multiple=1))
    b = frr_reference(topo, N_ATOMS, inputs=marshal_frr(topo, pad_multiple=16))
    assert_table_parity(a, b)
    # And through the device kernel, where pads actually enter the math.
    ta = FrrEngine("tpu", N_ATOMS).compute(topo)
    assert_table_parity(a, ta)


def test_forced_frr_dispatch_failure_scalar_fallback_bit_identical():
    """ISSUE 4 satellite (FRR side): a forced kernel-dispatch failure
    falls back to the oracle over the SAME marshaled inputs — every
    backup-table plane byte-identical to an uninterrupted scalar run."""
    from holo_tpu.resilience import CircuitBreaker, FaultPlan, inject

    topo = grid_topology(4, 4, seed=1)
    scalar = FrrEngine("scalar", N_ATOMS).compute(topo)
    eng = FrrEngine(
        "tpu", N_ATOMS, breaker=CircuitBreaker("frr-parity-fallback")
    )
    with inject(FaultPlan(dispatch_fail={"frr.dispatch": 1})) as inj:
        got = eng.compute(topo)
    assert inj.injected["frr.dispatch"] == 1
    assert_table_parity(scalar, got)
    assert eng.breaker.consecutive_failures == 1
    assert eng.breaker.state == "closed"
    got2 = eng.compute(topo)  # healthy: device kernel again
    assert_table_parity(scalar, got2)
    assert eng.breaker.consecutive_failures == 0


def test_lfa_never_uses_protected_interface():
    for seed in range(3):
        topo = random_ospf_topology(n_routers=9, n_networks=3, seed=seed)
        table = FrrEngine("scalar", N_ATOMS).compute(topo)
        fin = table.inputs
        for l in range(table.n_links):
            picks = table.lfa_adj[l]
            for a in picks[picks >= 0]:
                assert int(fin.adj_link[a]) != l

"""RIPv2: codec, propagation, split horizon, timeout/garbage aging."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.protocols.rip import (
    INFINITY_METRIC,
    RipCommand,
    RipIfConfig,
    RipInstance,
    RipPacket,
    Rte,
)
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def test_packet_roundtrip():
    pkt = RipPacket(
        RipCommand.RESPONSE,
        [Rte(N("10.1.0.0/16"), A("0.0.0.0"), 3, tag=7)],
    )
    out = RipPacket.decode(pkt.encode())
    assert out.command == RipCommand.RESPONSE
    assert out.rtes == [Rte(N("10.1.0.0/16"), A("0.0.0.0"), 3, 7)]


def chain(loop, fabric, n=3):
    """r0 -- r1 -- r2 chain over /30 p2p-ish LANs."""
    routers = []
    for i in range(n):
        r = RipInstance(f"rip{i}", fabric.sender_for(f"rip{i}"))
        loop.register(r)
        routers.append(r)
    for i in range(n - 1):
        net = N(f"10.0.{i}.0/30")
        a1, a2 = A(f"10.0.{i}.1"), A(f"10.0.{i}.2")
        sh = RipIfConfig(split_horizon="poison-reverse")
        routers[i].add_interface(f"e{i}r", sh, a1, net)
        routers[i + 1].add_interface(
            f"e{i}l", RipIfConfig(split_horizon="poison-reverse"), a2, net
        )
        fabric.join(f"l{i}", f"rip{i}", f"e{i}r", a1)
        fabric.join(f"l{i}", f"rip{i+1}", f"e{i}l", a2)
    return routers


def test_chain_propagation_and_metrics():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r0, r1, r2 = chain(loop, fabric)
    loop.advance(70)  # two update cycles
    # r0 learns the far subnet via r1 with metric 2 (1 hop + iface cost 1).
    route = r0.routes.get(N("10.0.1.0/30"))
    assert route is not None and route.metric == 2
    assert route.nexthop == A("10.0.0.2")
    # r2 learns the near subnet symmetric.
    route = r2.routes.get(N("10.0.0.0/30"))
    assert route is not None and route.metric == 2


def test_split_horizon_poison_reverse():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r0, r1, r2 = chain(loop, fabric)
    loop.advance(70)
    # Capture r0's updates out of e0r: routes learned from that iface must
    # be poisoned (metric 16).
    fabric.tx_log.clear()
    loop.advance(31)
    poisoned = False
    for actor, ifname, dst, data in fabric.tx_log:
        if actor == "rip0":
            pkt = RipPacket.decode(data)
            for rte in pkt.rtes:
                if rte.prefix == N("10.0.1.0/30"):
                    poisoned = rte.metric == INFINITY_METRIC
    assert poisoned, "learned route not poisoned back toward its source"


def test_ripng_v6_chain_propagation():
    """RIPng: same machinery, v6 codec + group (RFC 2080)."""
    from ipaddress import IPv6Address as A6
    from ipaddress import IPv6Network as N6

    from holo_tpu.protocols.rip import RipngPacket, RipngVersion

    # codec roundtrip
    pkt = RipngPacket(RipCommand.RESPONSE, [(N6("2001:db8:1::/48"), 7, 3)])
    out = RipngPacket.decode(pkt.encode())
    assert out.rtes == [(N6("2001:db8:1::/48"), 7, 3, None)]

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    routers = []
    for i in range(3):
        r = RipInstance(f"rng{i}", fabric.sender_for(f"rng{i}"),
                        version=RipngVersion)
        loop.register(r)
        routers.append(r)
    for i in range(2):
        net = N6(f"2001:db8:{i}::/64")
        a1, a2 = A6(f"fe80::{i}:1"), A6(f"fe80::{i}:2")
        sh = RipIfConfig(split_horizon="poison-reverse")
        routers[i].add_interface(f"e{i}r", sh, a1, net)
        routers[i + 1].add_interface(
            f"e{i}l", RipIfConfig(split_horizon="poison-reverse"), a2, net
        )
        fabric.join(f"l{i}", f"rng{i}", f"e{i}r", a1)
        fabric.join(f"l{i}", f"rng{i+1}", f"e{i}l", a2)
    loop.advance(70)
    route = routers[0].routes.get(N6("2001:db8:1::/64"))
    assert route is not None and route.metric == 2
    assert route.nexthop == A6("fe80::0:2")  # learned via link-local source
    route = routers[2].routes.get(N6("2001:db8:0::/64"))
    assert route is not None and route.metric == 2


def test_timeout_and_garbage_collection():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r0, r1, r2 = chain(loop, fabric)
    loop.advance(70)
    assert N("10.0.1.0/30") in r0.routes
    # Partition r0 from r1: r0's learned routes must time out (180s) and be
    # garbage-collected (another 120s).
    fabric.set_link_up("l0", False)
    loop.advance(185)
    route = r0.routes.get(N("10.0.1.0/30"))
    assert route is not None and route.metric == INFINITY_METRIC
    loop.advance(125)
    assert N("10.0.1.0/30") not in r0.routes
    # Connected route survives.
    assert N("10.0.0.0/30") in r0.routes


def test_ripv2_authentication():
    """RFC 2453 §4.1 simple password + RFC 2082 keyed-MD5: round-trip,
    rejection of missing/wrong credentials."""
    import pytest

    from holo_tpu.protocols.rip import RipPacket, Rte
    from holo_tpu.utils.bytesbuf import DecodeError

    rtes = [Rte(N("10.0.0.0/24"), A("0.0.0.0"), 2, 0)]
    # Simple password.
    wire = RipPacket(RipCommand.RESPONSE, rtes).encode(auth_password="s3cret")
    out = RipPacket.decode(wire, auth_password="s3cret")
    assert out.rtes[0].prefix == N("10.0.0.0/24")
    with pytest.raises(DecodeError):
        RipPacket.decode(wire, auth_password="wrong")
    with pytest.raises(DecodeError):
        # Unauthenticated packet rejected when auth is required.
        RipPacket.decode(
            RipPacket(RipCommand.RESPONSE, rtes).encode(),
            auth_password="s3cret",
        )
    # Keyed MD5.
    wire = RipPacket(RipCommand.RESPONSE, rtes).encode(
        auth_key=b"k3y", seqno=7
    )
    out = RipPacket.decode(wire, auth_key=b"k3y")
    assert out.rtes[0].metric == 2
    with pytest.raises(DecodeError):
        RipPacket.decode(wire, auth_key=b"other")
    # Tampered payload fails the digest.
    bad = bytearray(wire)
    bad[30] ^= 1
    with pytest.raises(DecodeError):
        RipPacket.decode(bytes(bad), auth_key=b"k3y")

"""Daemon-level preemptive isolation + event recording.

VERDICT round-2 item 4: the ThreadedLoop/recorder machinery must be used
by the PRODUCTION assembly, not only by unit tests — a deliberately-slow
instance must not expire a peer's dead timer *through the daemon
assembly* ([runtime] isolation = "threaded"), and a daemon-produced
recording must replay through the standard replay entry point
(reference holo-protocol/src/lib.rs:266-269,419-430; holod.toml
[event_recorder]).
"""

import json
import time
from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.daemon.config import DaemonConfig
from holo_tpu.daemon.daemon import Daemon
from holo_tpu.protocols.ospf.instance import (
    IfConfig,
    IfUpMsg,
    InstanceConfig,
    OspfInstance,
)
from holo_tpu.protocols.ospf.interface import IfType
from holo_tpu.protocols.ospf.neighbor import NsmState
from holo_tpu.utils.preempt import ThreadedLoop


def _full(inst) -> bool:
    return any(
        n.state == NsmState.FULL
        for a in inst.areas.values()
        for i in a.interfaces.values()
        for n in i.neighbors.values()
    )


def _configure_ospf(d: Daemon, rid: str, addr: str, ifname: str = "eth0"):
    cand = d.candidate()
    cand.set(f"interfaces/interface[{ifname}]/enabled", "true")
    cand.set(f"interfaces/interface[{ifname}]/address", [addr])
    cand.set("routing/control-plane-protocols/ospfv2/router-id", rid)
    base = f"routing/control-plane-protocols/ospfv2/area[0.0.0.0]/interface[{ifname}]"
    cand.set(f"{base}/interface-type", "point-to-point")
    cand.set(f"{base}/hello-interval", 1)
    cand.set(f"{base}/dead-interval", 3)
    d.commit(cand, comment="enable ospf")


def test_threaded_daemon_isolation_and_recording(tmp_path):
    """One daemon, isolation=threaded, recorder on: the config-spawned
    OSPF instance lives on its own thread; a stalled sibling instance
    (IS-IS, also config-spawned) blocking for longer than the OSPF dead
    interval does not break the adjacency; the recorder journal contains
    the instance's inputs and replays."""
    cfg = DaemonConfig()
    cfg.runtime.isolation = "threaded"
    cfg.event_recorder.enabled = True
    cfg.event_recorder.dir = str(tmp_path)
    d = Daemon(config=cfg)  # RealClock by default
    assert d.loop_router is not None and d.recorder is not None

    # Peer router on its own thread, wired into the daemon's fabric and
    # reachable through the daemon's router.
    peer_loop = ThreadedLoop("peer").start()
    peer = OspfInstance(
        name="peer-ospf",
        config=InstanceConfig(router_id=A("9.9.9.9")),
        netio=d.fabric.sender_for("peer-ospf"),
    )
    peer_loop.register(peer)
    d.loop_router.register_remote("peer-ospf", peer_loop)
    d.fabric.join("lx", "ospfv2", "eth0", A("10.70.0.1"))
    d.fabric.join("lx", "peer-ospf", "e0", A("10.70.0.2"))
    peer_loop.call(
        peer.add_interface,
        "e0",
        IfConfig(if_type=IfType.POINT_TO_POINT, hello_interval=1, dead_interval=3),
        N("10.70.0.0/30"),
        A("10.70.0.2"),
    )
    peer_loop.send("peer-ospf", IfUpMsg("e0"))

    try:
        _configure_ospf(d, "1.1.1.1", "10.70.0.1/30")
        inst = d.routing.instances["ospfv2"]
        # The instance must NOT be on the primary loop.
        assert "ospfv2" in d.instance_loops
        assert "ospfv2" not in d.loop.actors

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not (_full(inst) and _full(peer)):
            with d.lock:
                d.loop.run_until_idle()
            time.sleep(0.05)
        assert _full(inst) and _full(peer), "adjacency failed to form"

        # Spawn the stall victim through the daemon too.
        cand = d.candidate()
        cand.set("routing/control-plane-protocols/isis/system-id", "0000.0000.0001")
        cand.set("routing/control-plane-protocols/isis/interface[eth0]/metric", 7)
        d.commit(cand, comment="enable isis")
        isis = d.routing.instances["isis"]
        assert "isis" in d.instance_loops

        # Stall IS-IS for longer than the OSPF dead interval (3 s).  The
        # sleep runs on IS-IS's own thread; OSPF hellos/dead timers keep
        # being processed on theirs.
        isis.handle = lambda msg: time.sleep(4.0)
        d.loop_router.send("isis", object())
        t0 = time.monotonic()
        while time.monotonic() - t0 < 4.5:
            with d.lock:
                d.loop.run_until_idle()
            time.sleep(0.1)
            assert _full(inst), "dead timer expired while a sibling stalled"
        assert _full(inst) and _full(peer)
    finally:
        for tl in list(d.instance_loops.values()):
            tl.stop()
        peer_loop.stop()

    # The journal holds the OSPF instance's inputs (recorded on its own
    # thread) and replays through the standard entry point.
    journal = tmp_path / "holo-events.jsonl"
    assert journal.exists()
    actors = {json.loads(l)["actor"] for l in journal.read_text().splitlines()}
    assert "ospfv2" in actors

    from holo_tpu.utils.event_recorder import replay
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    rloop = EventLoop(clock=VirtualClock())

    class NullIo:
        def send(self, *a):
            pass

    replayed = OspfInstance(
        name="ospfv2",
        config=InstanceConfig(router_id=A("1.1.1.1")),
        netio=NullIo(),
    )
    rloop.register(replayed)
    replayed.add_interface(
        "eth0",
        IfConfig(if_type=IfType.POINT_TO_POINT, hello_interval=1, dead_interval=3),
        N("10.70.0.0/30"),
        A("10.70.0.1"),
    )
    n = replay(journal, rloop)
    assert n > 0
    # The replayed instance rebuilt its LSDB from the journal alone.
    assert any(area.lsdb.entries for area in replayed.areas.values())


def test_default_config_runs_threaded():
    """The DEFAULT daemon posture is per-instance OS threads (reference
    holo-protocol/src/lib.rs:419-430 production mode); cooperative is
    the virtual-clock/test fallback — polarity per VERDICT r4."""
    from holo_tpu.utils.runtime import VirtualClock

    cfg = DaemonConfig()
    assert cfg.runtime.isolation == "threaded"
    d = Daemon(config=DaemonConfig())  # real clock by default
    try:
        assert d.loop_router is not None, "default daemon must be threaded"
        _configure_ospf(d, "9.9.9.1", "10.90.0.1/30")
        assert d.instance_loops, "instance did not get its own thread"
        assert all(
            tl._thread.is_alive() for tl in d.instance_loops.values()
        )
    finally:
        d.stop()
    # Virtual-clock daemons silently downgrade (the reference's
    # `testing` feature analog).
    from holo_tpu.utils.runtime import EventLoop

    loop = EventLoop(clock=VirtualClock())
    d2 = Daemon(loop=loop, config=DaemonConfig())
    assert d2.loop_router is None

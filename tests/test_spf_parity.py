"""Bit-identical parity: TPU tensor SPF vs scalar reference Dijkstra.

The acceptance gate from BASELINE.md: every (distance, hops, first-parent,
ECMP next-hop set) must match the scalar reference semantics exactly, across
random OSPF-style topologies and what-if link-failure batches.
"""

import numpy as np
import pytest

from holo_tpu.spf.backend import ScalarSpfBackend, TpuSpfBackend
from holo_tpu.spf.synth import random_ospf_topology, whatif_link_failure_masks
from holo_tpu.testing import no_implicit_transfers

N_ATOMS = 64


@pytest.fixture(autouse=True)
def _transfer_sanitizer():
    """Every parity test runs under jax.transfer_guard('disallow'):
    only the backend's sanctioned marshal/unmarshal boundaries may
    move data between host and device (holo-lint runtime mode)."""
    with no_implicit_transfers():
        yield

# Every gather-path fixpoint formulation must be bit-identical: 'seq'
# the staged-loop form (production default, both here and in
# spf_whatif_batch), 'fused'/'packed' the one-loop variants, 'hybrid'
# the dist-loop + packed hops/next-hop loop.
ENGINES = ["fused", "packed", "seq", "hybrid"]


def assert_parity(topo, scalar_res, tpu_res):
    np.testing.assert_array_equal(scalar_res.dist, tpu_res.dist, err_msg="dist")
    np.testing.assert_array_equal(scalar_res.hops, tpu_res.hops, err_msg="hops")
    np.testing.assert_array_equal(scalar_res.parent, tpu_res.parent, err_msg="parent")
    np.testing.assert_array_equal(
        scalar_res.nexthop_words, tpu_res.nexthop_words, err_msg="nexthops"
    )


@pytest.mark.parametrize("engine", ENGINES)
@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize(
    "shape",
    [
        dict(n_routers=12, n_networks=0),
        dict(n_routers=10, n_networks=4),
        dict(n_routers=40, n_networks=10, extra_p2p=60),
    ],
)
def test_single_spf_parity(seed, shape, engine):
    topo = random_ospf_topology(seed=seed, **shape)
    scalar = ScalarSpfBackend(N_ATOMS).compute(topo)
    tpu = TpuSpfBackend(N_ATOMS, one_engine=engine).compute(topo)
    assert_parity(topo, scalar, tpu)


def test_lone_router_edgeless():
    """Regression: E=0 graphs must not crash the edge-mask gather."""
    from holo_tpu.ops.graph import Topology

    topo = Topology(
        n_vertices=1,
        is_router=np.ones(1, bool),
        edge_src=np.zeros(0, np.int32),
        edge_dst=np.zeros(0, np.int32),
        edge_cost=np.zeros(0, np.int32),
        root=0,
    )
    scalar = ScalarSpfBackend(N_ATOMS).compute(topo)
    tpu = TpuSpfBackend(N_ATOMS).compute(topo)
    assert_parity(topo, scalar, tpu)


def test_disconnected_component_unreachable():
    topo = random_ospf_topology(n_routers=8, n_networks=2, seed=1)
    # Fail every edge touching the root: everything except root unreachable.
    mask = np.ones(topo.n_edges, bool)
    for e in range(topo.n_edges):
        if topo.edge_src[e] == topo.root or topo.edge_dst[e] == topo.root:
            mask[e] = False
    scalar = ScalarSpfBackend(N_ATOMS).compute(topo, mask)
    tpu = TpuSpfBackend(N_ATOMS).compute(topo, mask)
    assert_parity(topo, scalar, tpu)
    from holo_tpu.ops.graph import INF

    unreachable = np.arange(topo.n_vertices) != topo.root
    assert (tpu.dist[unreachable] == INF).all()


@pytest.mark.parametrize("engine", ENGINES)
@pytest.mark.parametrize("seed", range(3))
def test_whatif_batch_parity(seed, engine):
    topo = random_ospf_topology(n_routers=16, n_networks=5, seed=seed)
    masks = whatif_link_failure_masks(topo, n_scenarios=8, seed=seed)
    scalar = ScalarSpfBackend(N_ATOMS).compute_whatif(topo, masks)
    tpu = TpuSpfBackend(N_ATOMS, one_engine=engine).compute_whatif(topo, masks)
    for s, t in zip(scalar, tpu):
        assert_parity(topo, s, t)


def test_ecmp_nexthop_sets_union():
    """Two equal-cost paths from the root must union their atoms."""
    from holo_tpu.ops.graph import Topology
    from holo_tpu.spf.synth import assign_direct_atoms

    # root(0) -> a(1) -> d(3), root -> b(2) -> d: both cost 2.
    src = np.array([0, 1, 0, 2, 1, 3, 2, 3], np.int32)
    dst = np.array([1, 0, 2, 0, 3, 1, 3, 2], np.int32)
    cost = np.array([1, 1, 1, 1, 1, 1, 1, 1], np.int32)
    topo = Topology(
        n_vertices=4,
        is_router=np.ones(4, bool),
        edge_src=src,
        edge_dst=dst,
        edge_cost=cost,
        root=0,
    )
    assign_direct_atoms(topo)
    scalar = ScalarSpfBackend(N_ATOMS).compute(topo)
    tpu = TpuSpfBackend(N_ATOMS).compute(topo)
    assert_parity(topo, scalar, tpu)
    # d (vertex 3) must carry both root links' atoms.
    assert bin(int(tpu.nexthop_words[3, 0])).count("1") == 2


def test_cache_invalidation_on_touch():
    """In-place cost mutation + touch() must re-marshal the device graph."""
    topo = random_ospf_topology(n_routers=10, n_networks=2, seed=5)
    be = TpuSpfBackend(N_ATOMS)
    be.compute(topo)
    topo.edge_cost[:] = 1
    topo.touch()
    tpu = be.compute(topo)
    scalar = ScalarSpfBackend(N_ATOMS).compute(topo)
    assert_parity(topo, scalar, tpu)


def test_atom_overflow_rejected():
    """More atoms than the bitmask width must raise, not corrupt."""
    from holo_tpu.ops.graph import build_ell

    topo = random_ospf_topology(n_routers=12, n_networks=4, seed=2)
    with pytest.raises(ValueError, match="atoms"):
        build_ell(topo, n_atoms=1)


def test_forced_dispatch_failure_scalar_fallback_bit_identical():
    """ISSUE 4 satellite: a forced mid-batch dispatch failure must be
    served by the breaker's scalar fallback with results byte-identical
    to an uninterrupted scalar run — the RIB cannot tell the difference.
    The next healthy dispatch runs on the device again (closed breaker,
    failure streak reset)."""
    from holo_tpu.resilience import CircuitBreaker, FaultPlan, inject

    topo = random_ospf_topology(n_routers=14, n_networks=4, seed=3)
    masks = whatif_link_failure_masks(topo, n_scenarios=6, seed=3)
    scalar = ScalarSpfBackend(N_ATOMS).compute_whatif(topo, masks)
    be = TpuSpfBackend(
        N_ATOMS, breaker=CircuitBreaker("spf-parity-fallback")
    )
    with inject(FaultPlan(dispatch_fail={"spf.dispatch": 1})) as inj:
        got = be.compute_whatif(topo, masks)
    assert inj.injected["spf.dispatch"] == 1, "the failure must have fired"
    for s, t in zip(scalar, got):
        assert_parity(topo, s, t)
    assert be.breaker.consecutive_failures == 1
    assert be.breaker.state == "closed"
    got2 = be.compute_whatif(topo, masks)  # healthy: device path again
    for s, t in zip(scalar, got2):
        assert_parity(topo, s, t)
    assert be.breaker.consecutive_failures == 0


def test_multiroot_matches_per_root():
    topo = random_ospf_topology(n_routers=12, n_networks=3, seed=7)
    roots = np.array(
        [i for i in range(topo.n_vertices) if topo.is_router[i]][:4], np.int32
    )
    backend = TpuSpfBackend(N_ATOMS)
    batch = backend.compute_multiroot(topo, roots)
    for i, r in enumerate(roots):
        t2 = random_ospf_topology(n_routers=12, n_networks=3, seed=7)
        t2.root = int(r)
        from holo_tpu.spf.synth import assign_direct_atoms

        assign_direct_atoms(t2)
        # Distances are root-dependent but atom tables differ per root, so
        # compare distances only (next hops are per-root-marshaled).
        single = ScalarSpfBackend(N_ATOMS).compute(t2)
        np.testing.assert_array_equal(single.dist, np.asarray(batch.dist[i]))

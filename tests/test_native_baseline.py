"""C++ scalar SPF baseline parity with the Python oracle."""

import shutil

import numpy as np
import pytest

from holo_tpu.spf.backend import ScalarSpfBackend
from holo_tpu.spf.synth import random_ospf_topology, whatif_link_failure_masks

pytestmark = pytest.mark.skipif(shutil.which("g++") is None, reason="needs g++")


@pytest.mark.parametrize("seed", range(4))
def test_native_matches_python_oracle(seed):
    from holo_tpu.native_build import native_spf

    topo = random_ospf_topology(n_routers=30, n_networks=8, extra_p2p=50, seed=seed)
    dist, parent, hops, nh = native_spf(topo)
    ref = ScalarSpfBackend().compute(topo)
    np.testing.assert_array_equal(ref.dist, dist)
    np.testing.assert_array_equal(ref.parent, parent)
    np.testing.assert_array_equal(ref.hops, hops)
    # nh is a 64-bit mask; reference words are uint32[N, 2].
    ref64 = ref.nexthop_words[:, 0].astype(np.uint64) | (
        ref.nexthop_words[:, 1].astype(np.uint64) << np.uint64(32)
    )
    np.testing.assert_array_equal(ref64, nh)


def test_native_batch_masks():
    from holo_tpu.native_build import native_spf_batch_dist

    topo = random_ospf_topology(n_routers=20, n_networks=4, seed=7)
    masks = whatif_link_failure_masks(topo, n_scenarios=6, seed=1)
    dists = native_spf_batch_dist(topo, masks)
    for i in range(masks.shape[0]):
        ref = ScalarSpfBackend().compute(topo, masks[i])
        np.testing.assert_array_equal(ref.dist, dists[i])

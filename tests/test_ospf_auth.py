"""OSPF authentication: MD5 cryptographic + simple password."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

import pytest

from holo_tpu.protocols.ospf.instance import (
    IfConfig,
    IfUpMsg,
    InstanceConfig,
    OspfInstance,
)
from holo_tpu.protocols.ospf.interface import IfType
from holo_tpu.protocols.ospf.neighbor import NsmState
from holo_tpu.protocols.ospf.packet import (
    AuthCtx,
    AuthType,
    Hello,
    LsRequest,
    Options,
    Packet,
)
from holo_tpu.utils.bytesbuf import DecodeError
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def mk_pkt():
    return Packet(A("1.1.1.1"), A("0.0.0.0"), LsRequest([]))


def test_md5_roundtrip_and_tamper_detection():
    auth = AuthCtx(AuthType.CRYPTOGRAPHIC, b"s3cret", key_id=5, seqno=42)
    raw = mk_pkt().encode(auth=auth)
    out = Packet.decode(raw, auth=auth)
    assert out.auth_seqno == 42
    # tampering breaks the digest
    bad = bytearray(raw)
    bad[5] ^= 0x01
    with pytest.raises(DecodeError, match="digest|length"):
        Packet.decode(bytes(bad), auth=auth)
    # wrong key rejected
    with pytest.raises(DecodeError, match="digest"):
        Packet.decode(raw, auth=AuthCtx(AuthType.CRYPTOGRAPHIC, b"wrong", key_id=5))
    # wrong key id rejected
    with pytest.raises(DecodeError, match="parameters"):
        Packet.decode(raw, auth=AuthCtx(AuthType.CRYPTOGRAPHIC, b"s3cret", key_id=6))
    # unauthenticated receiver rejects authenticated packet (type mismatch)
    with pytest.raises(DecodeError, match="mismatch"):
        Packet.decode(raw)


def test_simple_password():
    auth = AuthCtx(AuthType.SIMPLE, b"pw1")
    raw = mk_pkt().encode(auth=auth)
    assert Packet.decode(raw, auth=auth).auth_type == AuthType.SIMPLE
    with pytest.raises(DecodeError, match="password"):
        Packet.decode(raw, auth=AuthCtx(AuthType.SIMPLE, b"pw2"))


def convergence(auth1, auth2, seconds=60):
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    routers = []
    for name, rid, addr, auth in [("r1", "1.1.1.1", "10.0.0.1", auth1),
                                  ("r2", "2.2.2.2", "10.0.0.2", auth2)]:
        r = OspfInstance(name=name, config=InstanceConfig(router_id=A(rid)),
                         netio=fabric.sender_for(name))
        loop.register(r)
        cfg = IfConfig(if_type=IfType.POINT_TO_POINT, cost=1, auth=auth)
        r.add_interface("e0", cfg, N("10.0.0.0/30"), A(addr))
        fabric.join("lan", name, "e0", A(addr))
        routers.append(r)
    for r in routers:
        loop.send(r.name, IfUpMsg("e0"))
    loop.advance(seconds)
    r1 = routers[0]
    nbrs = r1.areas[A("0.0.0.0")].interfaces["e0"].neighbors
    return any(n.state == NsmState.FULL for n in nbrs.values())


def test_md5_adjacency_matching_keys():
    a = lambda: AuthCtx(AuthType.CRYPTOGRAPHIC, b"k1", key_id=1)
    assert convergence(a(), a())


def test_md5_adjacency_mismatched_keys_blocked():
    assert not convergence(
        AuthCtx(AuthType.CRYPTOGRAPHIC, b"k1", key_id=1),
        AuthCtx(AuthType.CRYPTOGRAPHIC, b"k2", key_id=1),
    )


def test_auth_vs_null_blocked():
    assert not convergence(AuthCtx(AuthType.SIMPLE, b"pw"), None)


def test_daemon_keychain_driven_md5():
    """Config-driven: both daemons reference a keychain; adjacency forms."""
    import ipaddress

    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.protocols.ospf.packet import AuthType

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="k1")
    d2 = Daemon(loop=loop, netio=fabric, name="k2")
    fabric.join("l", "k1.ospfv2", "eth0", ipaddress.ip_address("10.0.12.1"))
    fabric.join("l", "k2.ospfv2", "eth0", ipaddress.ip_address("10.0.12.2"))
    for d, rid, addr in [(d1, "1.1.1.1", "10.0.12.1/30"),
                         (d2, "2.2.2.2", "10.0.12.2/30")]:
        cand = d.candidate()
        cand.set("key-chains/key-chain[ospf-keys]/key[1]/key-string", "hunter2")
        cand.set("key-chains/key-chain[ospf-keys]/key[1]/crypto-algorithm", "md5")
        cand.set("interfaces/interface[eth0]/address", [addr])
        cand.set("routing/control-plane-protocols/ospfv2/router-id", rid)
        base = "routing/control-plane-protocols/ospfv2/area[0.0.0.0]/interface[eth0]"
        cand.set(f"{base}/interface-type", "point-to-point")
        cand.set(f"{base}/authentication/key-chain", "ospf-keys")
        d.commit(cand)
    loop.advance(60)
    inst = d1.routing.instances["ospfv2"]
    iface = list(inst.areas.values())[0].interfaces["eth0"]
    assert iface.config.auth is not None
    assert iface.config.auth.type == AuthType.CRYPTOGRAPHIC
    assert any(n.state == NsmState.FULL for n in iface.neighbors.values())


def test_daemon_keychain_lifetime_rollover():
    """Config-driven keychain with send/accept lifetimes: the daemons
    roll from key 1 to key 2 at t=60 with the adjacency intact
    (ietf-key-chain lifetimes -> utils.keychain.Keychain)."""
    import ipaddress

    from holo_tpu.daemon.daemon import Daemon

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="r1")
    d2 = Daemon(loop=loop, netio=fabric, name="r2")
    fabric.join("l", "r1.ospfv2", "eth0", ipaddress.ip_address("10.0.13.1"))
    fabric.join("l", "r2.ospfv2", "eth0", ipaddress.ip_address("10.0.13.2"))
    for d, rid, addr in [(d1, "1.1.1.1", "10.0.13.1/30"),
                         (d2, "2.2.2.2", "10.0.13.2/30")]:
        cand = d.candidate()
        kb = "key-chains/key-chain[roll]"
        cand.set(f"{kb}/key[1]/key-string", "old-secret")
        cand.set(f"{kb}/key[1]/crypto-algorithm", "md5")
        cand.set(f"{kb}/key[1]/send-lifetime/end-date-time",
                 "1970-01-01T00:01:00+00:00")
        cand.set(f"{kb}/key[1]/accept-lifetime/end-date-time",
                 "1970-01-01T00:02:00+00:00")
        cand.set(f"{kb}/key[2]/key-string", "new-secret")
        cand.set(f"{kb}/key[2]/crypto-algorithm", "hmac-sha-256")
        cand.set(f"{kb}/key[2]/send-lifetime/start-date-time",
                 "1970-01-01T00:01:00+00:00")
        cand.set(f"{kb}/key[2]/accept-lifetime/start-date-time",
                 "1970-01-01T00:00:30+00:00")
        cand.set("interfaces/interface[eth0]/address", [addr])
        cand.set("routing/control-plane-protocols/ospfv2/router-id", rid)
        base = "routing/control-plane-protocols/ospfv2/area[0.0.0.0]/interface[eth0]"
        cand.set(f"{base}/interface-type", "point-to-point")
        cand.set(f"{base}/hello-interval", 2)
        cand.set(f"{base}/dead-interval", 8)
        cand.set(f"{base}/authentication/key-chain", "roll")
        d.commit(cand)
    loop.advance(40)
    inst = d1.routing.instances["ospfv2"]
    iface = list(inst.areas.values())[0].interfaces["eth0"]

    def full():
        return any(n.state == NsmState.FULL for n in iface.neighbors.values())

    assert full(), "pre-rollover adjacency"
    assert iface.config.auth.tx_key_id == 1
    loop.advance(60)  # cross the t=60 send boundary
    assert full(), "adjacency lost across keychain rollover"
    assert iface.config.auth.tx_key_id == 2

"""VRRP stepwise conformance: all 15 reference cases replayed through
our live per-interface virtual routers (tools/stepwise_vrrp.py) —
VRRPv2, VRRPv3-IPv4 and VRRPv3-IPv6 topologies; master election,
macvlan lifecycle, virtual-address programming, gratuitous ARP /
unsolicited NA bursts, packet errors, and config changes.
"""

from pathlib import Path

import pytest

from holo_tpu.tools.stepwise_vrrp import VRRP_DIR, case_map, run_all, run_case

pytestmark = pytest.mark.skipif(
    not VRRP_DIR.exists(), reason="reference corpus not present"
)

PASS_FLOOR = 15


def test_known_case():
    cm = case_map()
    status, detail = run_case(
        VRRP_DIR / "master-down-timer1", *cm["master-down-timer1"]
    )
    assert status == "pass", detail


def test_stepwise_sweep_floor():
    res = run_all()
    passed = sorted(c for c, (s, _) in res.items() if s == "pass")
    failed = {c: d for c, (s, d) in res.items() if s != "pass"}
    assert len(passed) >= PASS_FLOOR, (
        f"only {len(passed)} VRRP cases pass (floor {PASS_FLOOR}); "
        f"failures: { {c: d[:120] for c, d in list(failed.items())[:5]} }"
    )

"""OSPF segment routing: prefix-SID advertisement + SRGB label resolution."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.protocols.ospf.instance import (
    IfConfig,
    IfUpMsg,
    InstanceConfig,
    OspfInstance,
)
from holo_tpu.protocols.ospf.interface import IfType
from holo_tpu.protocols.ospf.packet import (
    decode_ext_prefix_sid,
    encode_ext_prefix_sid,
)
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock
from holo_tpu.utils.sr import PrefixSid, Srgb, SrConfig


def test_ext_prefix_sid_codec():
    raw = encode_ext_prefix_sid(N("10.7.0.0/16"), 42, flags=0x40)
    prefix, idx, flags = decode_ext_prefix_sid(raw)
    assert prefix == N("10.7.0.0/16") and idx == 42 and flags == 0x40


def test_srgb_label_resolution():
    srgb = Srgb(lower=16000, upper=16999)
    assert srgb.label_of(42) == 16042
    assert srgb.label_of(2000) is None  # out of block


def test_prefix_sid_end_to_end():
    """r2 advertises a prefix-SID for its stub prefix; r1 resolves the
    SRGB label and associates it with the routed next hops."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)

    def rtr(name, rid, sids=None):
        sr = SrConfig(enabled=True)
        if sids:
            for psid in sids:
                sr.prefix_sids[psid.prefix] = psid
        inst = OspfInstance(
            name=name,
            config=InstanceConfig(router_id=A(rid), sr=sr),
            netio=fabric.sender_for(name),
        )
        loop.register(inst)
        return inst

    r1 = rtr("r1", "1.1.1.1")
    r2 = rtr("r2", "2.2.2.2",
             sids=[PrefixSid(N("192.168.2.0/24"), index=7)])
    cfg = IfConfig(if_type=IfType.POINT_TO_POINT, cost=4)
    r1.add_interface("e0", cfg, N("10.0.0.0/30"), A("10.0.0.1"))
    r2.add_interface("e0", cfg, N("10.0.0.0/30"), A("10.0.0.2"))
    r2.add_interface("stub", IfConfig(if_type=IfType.POINT_TO_POINT,
                                      cost=1, passive=True),
                     N("192.168.2.0/24"), A("192.168.2.1"))
    fabric.join("l", "r1", "e0", A("10.0.0.1"))
    fabric.join("l", "r2", "e0", A("10.0.0.2"))
    for r, ifs in ((r1, ["e0"]), (r2, ["e0", "stub"])):
        for i in ifs:
            loop.send(r.name, IfUpMsg(i))
    loop.advance(60)

    assert N("192.168.2.0/24") in r1.routes
    labels = r1.sr_labels
    assert N("192.168.2.0/24") in labels
    label, route = labels[N("192.168.2.0/24")]
    assert label == Srgb().lower + 7  # SRGB base + SID index
    assert {str(nh.addr) for nh in route.nexthops} == {"10.0.0.2"}

"""LDP: discovery, session, label mapping distribution, withdrawal."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.protocols.ldp import (
    LdpInstance,
    LdpMsg,
    LdpMsgType,
    NbrState,
)
from holo_tpu.utils.mpls import IMPLICIT_NULL, LabelManager
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def test_ldp_msg_roundtrips():
    for m in (
        LdpMsg(LdpMsgType.HELLO, A("1.1.1.1"), hold_time=15),
        LdpMsg(LdpMsgType.INIT, A("1.1.1.1"), keepalive_time=30),
        LdpMsg(LdpMsgType.LABEL_MAPPING, A("2.2.2.2"),
               fec=N("10.1.0.0/16"), label=10001),
        LdpMsg(LdpMsgType.LABEL_WITHDRAW, A("2.2.2.2"),
               fec=N("10.1.0.0/16"), label=10001),
    ):
        out = LdpMsg.decode(m.encode())
        assert out.type == m.type and out.lsr_id == m.lsr_id
        if m.fec:
            assert out.fec == m.fec and out.label == m.label


def test_label_manager_reuse():
    lm = LabelManager(lower=100, upper=102)
    a, b, c = lm.allocate(), lm.allocate(), lm.allocate()
    assert {a, b, c} == {100, 101, 102}
    import pytest
    from holo_tpu.utils.mpls import LabelExhausted

    with pytest.raises(LabelExhausted):
        lm.allocate()
    lm.release(b)
    assert lm.allocate() == b


def test_session_and_label_distribution():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    l1 = LdpInstance("l1", A("1.1.1.1"), fabric.sender_for("l1"))
    l2 = LdpInstance("l2", A("2.2.2.2"), fabric.sender_for("l2"))
    loop.register(l1)
    loop.register(l2)
    fabric.join("l", "l1", "e0", A("10.0.0.1"))
    fabric.join("l", "l2", "e0", A("10.0.0.2"))
    l1.add_interface("e0", A("10.0.0.1"))
    l2.add_interface("e0", A("10.0.0.2"))
    loop.advance(10)
    assert l1.neighbors[A("2.2.2.2")].state == NbrState.OPERATIONAL
    assert l2.neighbors[A("1.1.1.1")].state == NbrState.OPERATIONAL

    # l2 is egress for a prefix -> implicit null; l1 allocates a real label.
    l2.add_fec(N("203.0.113.0/24"), egress=True)
    l1.add_fec(N("203.0.113.0/24"), egress=False)
    loop.advance(2)
    lib1 = l1.lib()[N("203.0.113.0/24")]
    assert lib1["remote"]["2.2.2.2"] == IMPLICIT_NULL
    assert lib1["local"] >= 10000
    lib2 = l2.lib()[N("203.0.113.0/24")]
    assert lib2["remote"]["1.1.1.1"] == lib1["local"]

    # withdraw propagates
    l2.remove_fec(N("203.0.113.0/24"))
    loop.advance(2)
    assert "2.2.2.2" not in l1.lib()[N("203.0.113.0/24")]["remote"]


def _chain3(control_mode):
    """A(1.1.1.1) -- B(2.2.2.2) -- C(3.3.3.3), two links."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    a = LdpInstance("a", A("1.1.1.1"), fabric.sender_for("a"),
                    control_mode=control_mode)
    b = LdpInstance("b", A("2.2.2.2"), fabric.sender_for("b"),
                    control_mode=control_mode)
    c = LdpInstance("c", A("3.3.3.3"), fabric.sender_for("c"),
                    control_mode=control_mode)
    for inst in (a, b, c):
        loop.register(inst)
    fabric.join("ab", "a", "e0", A("10.0.1.1"))
    fabric.join("ab", "b", "e0", A("10.0.1.2"))
    fabric.join("bc", "b", "e1", A("10.0.2.2"))
    fabric.join("bc", "c", "e0", A("10.0.2.3"))
    a.add_interface("e0", A("10.0.1.1"))
    b.add_interface("e0", A("10.0.1.2"))
    b.add_interface("e1", A("10.0.2.2"))
    c.add_interface("e0", A("10.0.2.3"))
    loop.advance(10)
    return loop, a, b, c


def test_ordered_mode_waits_for_downstream():
    """RFC 5036 §2.6.1: a transit LSR advertises a FEC upstream only
    after its next hop has — and propagates withdrawal when it goes."""
    fec = N("203.0.113.0/24")
    loop, a, b, c = _chain3("ordered")
    # Transit binding at B with the next hop known but no downstream
    # mapping yet: B must NOT advertise to A.
    b.set_nexthops({fec: A("3.3.3.3")})
    b.add_fec(fec, egress=False)
    loop.advance(2)
    assert fec not in a.neighbors[A("2.2.2.2")].bindings
    # Egress binding appears at C -> C advertises -> B becomes eligible
    # and advertises upstream -> A learns it.
    c.add_fec(fec, egress=True)
    loop.advance(2)
    assert a.neighbors[A("2.2.2.2")].bindings.get(fec) == b.fec_table[fec][0]
    # Downstream withdraws: B withdraws upstream too.
    c.remove_fec(fec)
    loop.advance(2)
    assert fec not in a.neighbors[A("2.2.2.2")].bindings


def test_independent_mode_advertises_immediately():
    fec = N("203.0.113.0/24")
    loop, a, b, c = _chain3("independent")
    b.add_fec(fec, egress=False)  # no downstream mapping, no next hop
    loop.advance(2)
    assert a.neighbors[A("2.2.2.2")].bindings.get(fec) == b.fec_table[fec][0]


def test_system_data_tracked_while_inactive():
    """Addresses and interface state delivered BEFORE activation must be
    tracked (the reference keeps system data outside instance state,
    holo-ldp/src/instance.rs:58-63) so a later start sees them."""
    from ipaddress import ip_interface

    from holo_tpu.protocols.ldp.engine import Interface, InterfaceCfg, LdpEngine

    sent = []
    eng = LdpEngine("ldp", send_cb=lambda *a: sent.append(a))
    eng.interfaces["eth0"] = Interface(
        name="eth0", config=InterfaceCfg(ipv4_enabled=True)
    )
    assert not eng.active

    # System events arrive before the instance is configured/active.
    eng.iface_update("eth0", ifindex=3, operative=True)
    eng.addr_add("eth0", ip_interface("10.0.1.1/24"))
    eng.addr_add("lo", ip_interface("1.1.1.1/32"))

    assert eng.interfaces["eth0"].ifindex == 3
    assert eng.interfaces["eth0"].operative
    assert eng.interfaces["eth0"].ipv4_addr_list
    assert ip_interface("1.1.1.1/32") in eng.ipv4_addr_list

    # Activate: the interface must come up from the tracked state alone.
    from ipaddress import IPv4Address

    eng.config.ipv4_enabled = True
    eng.config.router_id = IPv4Address("1.1.1.1")
    eng.update()
    assert eng.active
    assert eng.interfaces["eth0"].active


def test_yang_notifications_adjacency_and_peer():
    """Reference holo-ldp northbound/notification.rs: hello-adjacency and
    peer events at discovery, session-up, and hold expiry."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    notifs = []
    l1 = LdpInstance("l1", A("1.1.1.1"), fabric.sender_for("l1"),
                     notif_cb=notifs.append)
    l2 = LdpInstance("l2", A("2.2.2.2"), fabric.sender_for("l2"))
    loop.register(l1)
    loop.register(l2)
    fabric.join("l", "l1", "e0", A("10.0.0.1"))
    fabric.join("l", "l2", "e0", A("10.0.0.2"))
    l1.add_interface("e0", A("10.0.0.1"))
    l2.add_interface("e0", A("10.0.0.2"))
    loop.advance(10)
    assert l1.neighbors[A("2.2.2.2")].state == NbrState.OPERATIONAL
    kinds = [k for n in notifs for k in n]
    assert "ietf-mpls-ldp:mpls-ldp-hello-adjacency-event" in kinds
    peer_up = [n["ietf-mpls-ldp:mpls-ldp-peer-event"] for n in notifs
               if "ietf-mpls-ldp:mpls-ldp-peer-event" in n]
    assert peer_up and peer_up[0]["event-type"] == "up"
    assert peer_up[0]["peer"]["lsr-id"] == "2.2.2.2"
    # Silence l2: hold expiry tears adjacency + peer down.
    notifs.clear()
    loop.unregister("l2")
    loop.advance(120)
    downs = [n["ietf-mpls-ldp:mpls-ldp-peer-event"] for n in notifs
             if "ietf-mpls-ldp:mpls-ldp-peer-event" in n]
    assert downs and downs[-1]["event-type"] == "down"
    adj_down = [n["ietf-mpls-ldp:mpls-ldp-hello-adjacency-event"]
                for n in notifs
                if "ietf-mpls-ldp:mpls-ldp-hello-adjacency-event" in n]
    assert adj_down and adj_down[-1]["event-type"] == "down"

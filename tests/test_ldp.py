"""LDP: discovery, session, label mapping distribution, withdrawal."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.protocols.ldp import (
    LdpInstance,
    LdpMsg,
    LdpMsgType,
    NbrState,
)
from holo_tpu.utils.mpls import IMPLICIT_NULL, LabelManager
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def test_ldp_msg_roundtrips():
    for m in (
        LdpMsg(LdpMsgType.HELLO, A("1.1.1.1"), hold_time=15),
        LdpMsg(LdpMsgType.INIT, A("1.1.1.1"), keepalive_time=30),
        LdpMsg(LdpMsgType.LABEL_MAPPING, A("2.2.2.2"),
               fec=N("10.1.0.0/16"), label=10001),
        LdpMsg(LdpMsgType.LABEL_WITHDRAW, A("2.2.2.2"),
               fec=N("10.1.0.0/16"), label=10001),
    ):
        out = LdpMsg.decode(m.encode())
        assert out.type == m.type and out.lsr_id == m.lsr_id
        if m.fec:
            assert out.fec == m.fec and out.label == m.label


def test_label_manager_reuse():
    lm = LabelManager(lower=100, upper=102)
    a, b, c = lm.allocate(), lm.allocate(), lm.allocate()
    assert {a, b, c} == {100, 101, 102}
    import pytest
    from holo_tpu.utils.mpls import LabelExhausted

    with pytest.raises(LabelExhausted):
        lm.allocate()
    lm.release(b)
    assert lm.allocate() == b


def test_session_and_label_distribution():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    l1 = LdpInstance("l1", A("1.1.1.1"), fabric.sender_for("l1"))
    l2 = LdpInstance("l2", A("2.2.2.2"), fabric.sender_for("l2"))
    loop.register(l1)
    loop.register(l2)
    fabric.join("l", "l1", "e0", A("10.0.0.1"))
    fabric.join("l", "l2", "e0", A("10.0.0.2"))
    l1.add_interface("e0", A("10.0.0.1"))
    l2.add_interface("e0", A("10.0.0.2"))
    loop.advance(10)
    assert l1.neighbors[A("2.2.2.2")].state == NbrState.OPERATIONAL
    assert l2.neighbors[A("1.1.1.1")].state == NbrState.OPERATIONAL

    # l2 is egress for a prefix -> implicit null; l1 allocates a real label.
    l2.add_fec(N("203.0.113.0/24"), egress=True)
    l1.add_fec(N("203.0.113.0/24"), egress=False)
    loop.advance(2)
    lib1 = l1.lib()[N("203.0.113.0/24")]
    assert lib1["remote"]["2.2.2.2"] == IMPLICIT_NULL
    assert lib1["local"] >= 10000
    lib2 = l2.lib()[N("203.0.113.0/24")]
    assert lib2["remote"]["1.1.1.1"] == lib1["local"]

    # withdraw propagates
    l2.remove_fec(N("203.0.113.0/24"))
    loop.advance(2)
    assert "2.2.2.2" not in l1.lib()[N("203.0.113.0/24")]["remote"]

"""BFD session FSM + OSPF fast-failure integration."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.protocols.bfd import BfdInstance, BfdPacket, BfdState
from holo_tpu.utils.ibus import Ibus
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def test_bfd_packet_roundtrip():
    p = BfdPacket(state=BfdState.INIT, detect_mult=3, my_discr=7, your_discr=9)
    out = BfdPacket.decode(p.encode())
    assert out.state == BfdState.INIT
    assert out.my_discr == 7 and out.your_discr == 9
    assert out.detect_mult == 3


def test_bfd_sessions_come_up_and_detect_failure():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    ibus = Ibus(loop)
    b1 = BfdInstance(fabric.sender_for("bfd1"), ibus)
    b2 = BfdInstance(fabric.sender_for("bfd2"), ibus)
    b1.name, b2.name = "bfd1", "bfd2"
    loop.register(b1)
    loop.register(b2)
    fabric.join("l", "bfd1", "e0", A("10.0.0.1"))
    fabric.join("l", "bfd2", "e0", A("10.0.0.2"))
    s1 = b1.register(("e0", A("10.0.0.2")), "test", A("10.0.0.1"))
    s2 = b2.register(("e0", A("10.0.0.1")), "test", A("10.0.0.2"))
    loop.advance(5)
    assert s1.state == BfdState.UP and s2.state == BfdState.UP

    fabric.set_link_up("l", False)
    loop.advance(5)  # detect time = 3 * 1s
    assert s1.state == BfdState.DOWN
    assert s1.diag.name == "TIME_EXPIRED"


def test_ospf_adjacency_killed_by_bfd():
    """BFD down must kill the OSPF adjacency in ~3s, not dead-interval 40s."""
    from holo_tpu.protocols.ospf.instance import (
        IfConfig, IfUpMsg, InstanceConfig, OspfInstance,
    )
    from holo_tpu.protocols.ospf.interface import IfType
    from holo_tpu.protocols.ospf.neighbor import NsmState

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)

    nodes = {}
    for name, rid, addr in [("r1", "1.1.1.1", "10.0.0.1"), ("r2", "2.2.2.2", "10.0.0.2")]:
        bus = Ibus(loop)
        bfd = BfdInstance(fabric.sender_for(f"{name}.bfd"), bus)
        loop.register(bfd, name=f"{name}.bfd")
        inst = OspfInstance(
            name=name,
            config=InstanceConfig(router_id=A(rid)),
            netio=fabric.sender_for(name),
        )
        loop.register(inst)
        inst.attach_ibus(bus, bfd_actor=f"{name}.bfd")
        cfg = IfConfig(if_type=IfType.POINT_TO_POINT, cost=1, bfd_enabled=True)
        inst.add_interface("e0", cfg, N("10.0.0.0/30"), A(addr))
        fabric.join("lan", name, "e0", A(addr))
        fabric.join("lan", f"{name}.bfd", "e0", A(addr))
        nodes[name] = (inst, bfd)

    for name in nodes:
        loop.send(name, IfUpMsg("e0"))
    loop.advance(30)
    r1, _ = nodes["r1"]
    iface = list(r1.areas.values())[0].interfaces["e0"]
    assert any(n.state == NsmState.FULL for n in iface.neighbors.values())

    # Silent failure: drop all frames but keep link "up" (no carrier loss).
    fabric.add_drop_rule(lambda link, dst, data: True)
    loop.advance(6)  # BFD detect (~3s) << dead interval (40s)
    assert not iface.neighbors, "BFD failed to kill adjacency quickly"


def _pair(loop, fabric, ibus, key1, key2):
    b1 = BfdInstance(fabric.sender_for("bfd1"), ibus)
    b2 = BfdInstance(fabric.sender_for("bfd2"), ibus)
    b1.name, b2.name = "bfd1", "bfd2"
    loop.register(b1)
    loop.register(b2)
    fabric.join("l", "bfd1", "e0", A("10.0.0.1"))
    fabric.join("l", "bfd2", "e0", A("10.0.0.2"))
    s1 = b1.register(key1, "test", A("10.0.0.1"))
    s2 = b2.register(key2, "test", A("10.0.0.2"))
    return b1, b2, s1, s2


def test_bfd_auth_roundtrip_and_verification():
    from holo_tpu.protocols.bfd import BfdAuth, BfdAuthType

    for atype in (
        BfdAuthType.SIMPLE_PASSWORD,
        BfdAuthType.KEYED_MD5,
        BfdAuthType.METICULOUS_KEYED_SHA1,
    ):
        p = BfdPacket(
            state=BfdState.UP,
            my_discr=5,
            your_discr=6,
            auth=BfdAuth(atype, key_id=1, seq=42),
        )
        wire = p.encode(auth_key=b"s3cret")
        out = BfdPacket.decode(wire)
        assert out.auth is not None and out.auth.auth_type == atype
        assert out.verify_auth(wire, b"s3cret")
        assert not out.verify_auth(wire, b"wrong-key")
        # Trailing datagram bytes must not shift the digest window: the
        # digest position derives from the packet's own length field.
        assert out.verify_auth(wire + b"\x00" * 7, b"s3cret")


def test_bfd_authenticated_session_rejects_bad_key():
    from holo_tpu.protocols.bfd import BfdAuthType

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    ibus = Ibus(loop)
    k1, k2 = ("e0", A("10.0.0.2")), ("e0", A("10.0.0.1"))
    b1, b2, s1, s2 = _pair(loop, fabric, ibus, k1, k2)
    b1.configure_auth(k1, BfdAuthType.METICULOUS_KEYED_MD5, b"hunter2")
    b2.configure_auth(k2, BfdAuthType.METICULOUS_KEYED_MD5, b"hunter2")
    loop.advance(5)
    assert s1.state == BfdState.UP and s2.state == BfdState.UP

    # Re-key one side only: its packets now fail verification and the
    # peer's detect timer expires.
    b1.configure_auth(k1, BfdAuthType.METICULOUS_KEYED_MD5, b"other")
    loop.advance(10)
    assert s2.state == BfdState.DOWN


def test_bfd_multihop_session():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    ibus = Ibus(loop)
    k1 = BfdInstance.session_key_mh(A("10.0.0.1"), A("10.0.0.2"))
    k2 = BfdInstance.session_key_mh(A("10.0.0.2"), A("10.0.0.1"))
    b1, b2, s1, s2 = _pair(loop, fabric, ibus, k1, k2)
    loop.advance(5)
    assert s1.state == BfdState.UP and s2.state == BfdState.UP
    assert s1.is_multihop()

    fabric.set_link_up("l", False)
    loop.advance(5)
    assert s1.state == BfdState.DOWN


def test_bfd_echo_failure_detection():
    from holo_tpu.protocols.bfd import BfdDiag

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    ibus = Ibus(loop)
    k1, k2 = ("e0", A("10.0.0.2")), ("e0", A("10.0.0.1"))
    b1, b2, s1, s2 = _pair(loop, fabric, ibus, k1, k2)
    loop.advance(5)
    assert s1.state == BfdState.UP
    # Peer advertises a nonzero echo-rx window, then we start echoing.
    s2.required_min_echo_rx = 50_000
    b1.enable_echo(k1, interval=0.2)
    loop.advance(3)
    assert s1.state == BfdState.UP  # echoes looping back fine

    # Kill the link: control packets stop AND echoes stop looping; the
    # echo detect window (interval * mult) is shorter than the control
    # detect time, so the failure diag is ECHO_FAILED.
    fabric.set_link_up("l", False)
    # Next echo goes out at +0.2s and its detect window (0.2s * 3) lapses
    # at ~0.8s — well before the 3s control-packet detect time.
    loop.advance(1.5)
    assert s1.state == BfdState.DOWN
    assert s1.diag == BfdDiag.ECHO_FAILED


def test_yang_notification_on_state_change():
    """Reference holo-bfd northbound/notification.rs: singlehop sessions
    notify under ietf-bfd-ip-sh on every state transition."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    notifs = []
    b1 = BfdInstance(fabric.sender_for("bfd1"), Ibus(loop),
                     notif_cb=notifs.append)
    b2 = BfdInstance(fabric.sender_for("bfd2"), Ibus(loop))
    b1.name, b2.name = "bfd1", "bfd2"
    loop.register(b1)
    loop.register(b2)
    fabric.join("l", "bfd1", "e0", A("10.0.0.1"))
    fabric.join("l", "bfd2", "e0", A("10.0.0.2"))
    b1.register(("e0", A("10.0.0.2")), "test", A("10.0.0.1"))
    b2.register(("e0", A("10.0.0.1")), "test", A("10.0.0.2"))
    loop.advance(5)
    sh = [n["ietf-bfd-ip-sh:singlehop-notification"] for n in notifs
          if "ietf-bfd-ip-sh:singlehop-notification" in n]
    assert sh and sh[-1]["new-state"] == "up"
    assert sh[-1]["dest-addr"] == "10.0.0.2" and sh[-1]["interface"] == "e0"
    fabric.set_link_up("l", False)
    loop.advance(5)
    sh = [n["ietf-bfd-ip-sh:singlehop-notification"] for n in notifs
          if "ietf-bfd-ip-sh:singlehop-notification" in n]
    assert sh[-1]["new-state"] == "down"

"""BFD session FSM + OSPF fast-failure integration."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.protocols.bfd import BfdInstance, BfdPacket, BfdState
from holo_tpu.utils.ibus import Ibus
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def test_bfd_packet_roundtrip():
    p = BfdPacket(state=BfdState.INIT, detect_mult=3, my_discr=7, your_discr=9)
    out = BfdPacket.decode(p.encode())
    assert out.state == BfdState.INIT
    assert out.my_discr == 7 and out.your_discr == 9
    assert out.detect_mult == 3


def test_bfd_sessions_come_up_and_detect_failure():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    ibus = Ibus(loop)
    b1 = BfdInstance(fabric.sender_for("bfd1"), ibus)
    b2 = BfdInstance(fabric.sender_for("bfd2"), ibus)
    b1.name, b2.name = "bfd1", "bfd2"
    loop.register(b1)
    loop.register(b2)
    fabric.join("l", "bfd1", "e0", A("10.0.0.1"))
    fabric.join("l", "bfd2", "e0", A("10.0.0.2"))
    s1 = b1.register(("e0", A("10.0.0.2")), "test", A("10.0.0.1"))
    s2 = b2.register(("e0", A("10.0.0.1")), "test", A("10.0.0.2"))
    loop.advance(5)
    assert s1.state == BfdState.UP and s2.state == BfdState.UP

    fabric.set_link_up("l", False)
    loop.advance(5)  # detect time = 3 * 1s
    assert s1.state == BfdState.DOWN
    assert s1.diag.name == "TIME_EXPIRED"


def test_ospf_adjacency_killed_by_bfd():
    """BFD down must kill the OSPF adjacency in ~3s, not dead-interval 40s."""
    from holo_tpu.protocols.ospf.instance import (
        IfConfig, IfUpMsg, InstanceConfig, OspfInstance,
    )
    from holo_tpu.protocols.ospf.interface import IfType
    from holo_tpu.protocols.ospf.neighbor import NsmState

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)

    nodes = {}
    for name, rid, addr in [("r1", "1.1.1.1", "10.0.0.1"), ("r2", "2.2.2.2", "10.0.0.2")]:
        bus = Ibus(loop)
        bfd = BfdInstance(fabric.sender_for(f"{name}.bfd"), bus)
        loop.register(bfd, name=f"{name}.bfd")
        inst = OspfInstance(
            name=name,
            config=InstanceConfig(router_id=A(rid)),
            netio=fabric.sender_for(name),
        )
        loop.register(inst)
        inst.attach_ibus(bus, bfd_actor=f"{name}.bfd")
        cfg = IfConfig(if_type=IfType.POINT_TO_POINT, cost=1, bfd_enabled=True)
        inst.add_interface("e0", cfg, N("10.0.0.0/30"), A(addr))
        fabric.join("lan", name, "e0", A(addr))
        fabric.join("lan", f"{name}.bfd", "e0", A(addr))
        nodes[name] = (inst, bfd)

    for name in nodes:
        loop.send(name, IfUpMsg("e0"))
    loop.advance(30)
    r1, _ = nodes["r1"]
    iface = list(r1.areas.values())[0].interfaces["e0"]
    assert any(n.state == NsmState.FULL for n in iface.neighbors.values())

    # Silent failure: drop all frames but keep link "up" (no carrier loss).
    fabric.add_drop_rule(lambda link, dst, data: True)
    loop.advance(6)  # BFD detect (~3s) << dead interval (40s)
    assert not iface.neighbors, "BFD failed to kill adjacency quickly"

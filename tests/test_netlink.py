"""Real-kernel netlink programming (root-only; skipped otherwise).

Installs routes into a dedicated kernel table via our raw rtnetlink
implementation, verifies with `ip route`, exercises ECMP, uninstall, and
the protocol-tagged stale purge.
"""

import os
import subprocess
from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

import pytest

pytestmark = pytest.mark.skipif(
    os.geteuid() != 0 or not os.path.exists("/proc/net/netlink"),
    reason="requires root + netlink",
)

TABLE = 10_007  # private table: never touches main routing


@pytest.fixture
def kernel():
    from holo_tpu.routing.netlink import NetlinkKernel

    k = NetlinkKernel(table=TABLE)
    k.purge_stale()
    yield k
    k.purge_stale()
    k.nl.close()


def ip_route_show():
    out = subprocess.run(
        ["ip", "route", "show", "table", str(TABLE)],
        capture_output=True, text=True,
    )
    return out.stdout


def test_install_uninstall_roundtrip(kernel):
    from holo_tpu.utils.southbound import Nexthop, Protocol

    kernel.install(
        N("192.0.2.0/24"),
        frozenset({Nexthop(ifname="lo")}),
        Protocol.OSPFV2,
    )
    shown = ip_route_show()
    assert "192.0.2.0/24" in shown and "lo" in shown
    assert N("192.0.2.0/24") in kernel.routes()

    kernel.uninstall(N("192.0.2.0/24"))
    assert "192.0.2.0/24" not in ip_route_show()
    # double-uninstall is a no-op (ESRCH swallowed)
    kernel.uninstall(N("192.0.2.0/24"))


def test_replace_updates_route(kernel):
    from holo_tpu.utils.southbound import Nexthop, Protocol

    subprocess.run(["ip", "link", "set", "ifb0", "up"], check=True)
    try:
        kernel.install(N("198.51.100.0/24"), frozenset({Nexthop(ifname="lo")}),
                       Protocol.OSPFV2)
        kernel.install(N("198.51.100.0/24"), frozenset({Nexthop(ifname="ifb0")}),
                       Protocol.OSPFV2)
        shown = ip_route_show()
        assert shown.count("198.51.100.0/24") == 1
        assert "ifb0" in shown
    finally:
        subprocess.run(["ip", "link", "set", "ifb0", "down"], check=False)


def test_purge_stale_only_our_protocol(kernel):
    from holo_tpu.utils.southbound import Nexthop, Protocol

    kernel.install(N("203.0.113.0/24"), frozenset({Nexthop(ifname="lo")}),
                   Protocol.STATIC)
    # Foreign route in the same table, different protocol tag:
    subprocess.run(
        ["ip", "route", "add", "203.0.113.128/25", "dev", "lo",
         "table", str(TABLE), "protocol", "static"],
        check=True,
    )
    try:
        kernel.purge_stale()
        shown = ip_route_show()
        assert "203.0.113.0/24" not in shown  # ours: purged
        assert "203.0.113.128/25" in shown  # foreign: untouched
    finally:
        subprocess.run(
            ["ip", "route", "del", "203.0.113.128/25", "table", str(TABLE)],
            check=False,
        )


def test_monitor_sees_link_and_address_events(kernel):
    """Live kernel: create a dummy link, flip it, add an address — the
    monitor reports each event."""
    from holo_tpu.routing.netlink import NetlinkMonitor

    mon = NetlinkMonitor()
    try:
        subprocess.run("ip link del vmon0 2>/dev/null", shell=True)
        subprocess.run("ip link add vmon0 type veth peer name vmon1", shell=True, check=True)
        subprocess.run("ip link set vmon0 up", shell=True, check=True)
        subprocess.run("ip addr add 192.0.2.77/24 dev vmon0", shell=True,
                       check=True)
        import time

        time.sleep(0.2)
        events = mon.drain()
        kinds = [(e.kind, e.ifname or e.addr) for e in events]
        assert any(e.kind == "link" and e.ifname == "vmon0" and e.up
                   for e in events), kinds
        assert any(e.kind == "addr" and str(e.addr) == "192.0.2.77/24"
                   for e in events), kinds

        subprocess.run("ip link del vmon0", shell=True, check=True)
        time.sleep(0.2)
        events = mon.drain()
        assert any(e.kind == "link-del" and e.ifname == "vmon0"
                   for e in events)
    finally:
        subprocess.run("ip link del vmon0 2>/dev/null", shell=True)
        mon.close()


def test_rib_manager_with_real_kernel(kernel):
    """The full path: RIB manager programming the actual kernel FIB."""
    from holo_tpu.routing.rib import RibManager
    from holo_tpu.utils.ibus import Ibus
    from holo_tpu.utils.runtime import EventLoop, VirtualClock
    from holo_tpu.utils.southbound import Nexthop, Protocol, RouteKeyMsg, RouteMsg

    loop = EventLoop(clock=VirtualClock())
    rib = RibManager(Ibus(loop), kernel)
    rib.route_add(
        RouteMsg(Protocol.OSPFV2, N("192.0.2.64/26"), 110, 20,
                 frozenset({Nexthop(ifname="lo")}))
    )
    assert "192.0.2.64/26" in ip_route_show()
    rib.route_del(RouteKeyMsg(Protocol.OSPFV2, N("192.0.2.64/26")))
    assert "192.0.2.64/26" not in ip_route_show()


def test_multicast_vif_programming():
    """Kernel VIF + MFC control (reference holo-utils/src/socket.rs:560-600
    vifctl; runs in a private netns so the host mroute socket stays free)."""
    import sys
    import pathlib
    repo_root = str(pathlib.Path(__file__).resolve().parents[1])
    script = rf'''
import subprocess, sys
sys.path.insert(0, {repo_root!r})
subprocess.run(["ip", "link", "add", "mrd0", "type", "veth",
                "peer", "name", "mrd1"], check=True)
subprocess.run(["ip", "link", "set", "mrd0", "up"], check=True)
ifindex = int(open("/sys/class/net/mrd0/ifindex").read())
from ipaddress import IPv4Address as A
from holo_tpu.routing.mroute import MulticastRouting
from holo_tpu.protocols.igmp import IgmpIfConfig, IgmpInstance
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock

loop = EventLoop(clock=VirtualClock())
fabric = MockFabric(loop)
m = MulticastRouting()
inst = IgmpInstance("igmp", fabric.sender_for("igmp"), mroute=m)
loop.register(inst)
inst.add_interface("mrd0", IgmpIfConfig(), A("10.99.0.1"), ifindex=ifindex)
assert "mrd0" in open("/proc/net/ip_mr_vif").read()
m.add_mfc(A("10.99.0.2"), A("239.1.1.1"), "mrd0", ["mrd0"])
assert "010101EF" in open("/proc/net/ip_mr_cache").read()
m.del_mfc(A("10.99.0.2"), A("239.1.1.1"))
inst.remove_interface("mrd0")
assert "mrd0" not in open("/proc/net/ip_mr_vif").read()
m.close()
print("VIF-OK")
'''
    subprocess.run(["ip", "netns", "add", "viftest"], capture_output=True)
    try:
        out = subprocess.run(
            ["ip", "netns", "exec", "viftest", sys.executable, "-c", script],
            capture_output=True, text=True, timeout=60,
        )
        assert "VIF-OK" in out.stdout, out.stderr[-800:]
    finally:
        subprocess.run(["ip", "netns", "del", "viftest"], capture_output=True)

"""Resilience subsystem units: circuit breaker FSM, restart policy +
supervisor (virtual clock), fault-plan determinism, txqueue drop-cause
attribution, event-recorder crash-safe flush."""

import json

import pytest

from holo_tpu import telemetry
from holo_tpu.resilience import (
    CircuitBreaker,
    FaultInjector,
    FaultPlan,
    InjectedFault,
    RestartPolicy,
    Supervisor,
    health_snapshot,
    inject,
)
from holo_tpu.resilience import faults as faults_mod
from holo_tpu.utils.runtime import (
    Actor,
    EventLoop,
    PoisonPill,
    VirtualClock,
)

# -- circuit breaker ----------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def mkbreaker(name, **kw):
    clk = FakeClock()
    kw.setdefault("failure_threshold", 3)
    kw.setdefault("recovery_timeout", 10.0)
    return CircuitBreaker(name, clock=clk, **kw), clk


def test_breaker_opens_after_consecutive_failures_and_short_circuits():
    br, clk = mkbreaker("u-open")
    calls = {"primary": 0, "fallback": 0}

    def bad():
        calls["primary"] += 1
        raise RuntimeError("xla died")

    def oracle():
        calls["fallback"] += 1
        return "scalar"

    for _ in range(3):
        assert br.call(bad, oracle) == "scalar"
    assert br.state == "open" and calls == {"primary": 3, "fallback": 3}
    # Open: the device is not even attempted.
    assert br.call(bad, oracle) == "scalar"
    assert calls["primary"] == 3 and calls["fallback"] == 4


def test_breaker_success_resets_failure_streak():
    br, _ = mkbreaker("u-streak")
    br.call(lambda: (_ for _ in ()).throw(RuntimeError()), lambda: None)
    br.call(lambda: (_ for _ in ()).throw(RuntimeError()), lambda: None)
    assert br.consecutive_failures == 2
    assert br.call(lambda: "ok", lambda: "fb") == "ok"
    assert br.consecutive_failures == 0 and br.state == "closed"


def test_breaker_half_open_probe_restores_service():
    br, clk = mkbreaker("u-probe")
    boom = lambda: (_ for _ in ()).throw(RuntimeError("x"))
    for _ in range(3):
        br.call(boom, lambda: "fb")
    assert br.state == "open"
    clk.t = 11.0  # past recovery_timeout
    calls = {"n": 0}

    def good():
        calls["n"] += 1
        return "device"

    assert br.call(good, lambda: "fb") == "device"
    assert br.state == "closed" and calls["n"] == 1
    # Healthy again: subsequent calls dispatch normally.
    assert br.call(good, lambda: "fb") == "device"


def test_breaker_failed_probe_reopens():
    br, clk = mkbreaker("u-reprobe")
    boom = lambda: (_ for _ in ()).throw(RuntimeError("x"))
    for _ in range(3):
        br.call(boom, lambda: "fb")
    clk.t = 11.0
    assert br.call(boom, lambda: "fb") == "fb"  # probe fails
    assert br.state == "open"
    # A fresh timeout applies before the next probe.
    assert br.call(lambda: "dev", lambda: "fb") == "fb"
    clk.t = 22.0
    assert br.call(lambda: "dev", lambda: "fb") == "dev"
    assert br.state == "closed"


def test_breaker_deadline_overrun_counts_but_keeps_completed_result():
    br, clk = mkbreaker("u-deadline", failure_threshold=2, deadline=1.0)

    def slow():
        clk.t += 5.0  # blows the 1s budget
        return "late-device"

    # The result is already in hand and bit-identical by contract:
    # return it, but count the failure so a degrading relay opens the
    # circuit (and THEN dispatches go scalar up front).
    assert br.call(slow, lambda: "fb") == "late-device"
    assert br.consecutive_failures == 1 and br.state == "closed"
    assert "deadline" in (br.last_error or "")
    assert br.call(slow, lambda: "fb") == "late-device"
    assert br.state == "open"
    assert br.call(slow, lambda: "fb") == "fb"  # open: device not tried


def test_breaker_programming_errors_pass_through():
    """TypeError/IndexError/etc. are bugs, not device failures — the
    breaker must re-raise them, not mask them behind the oracle."""
    br, _ = mkbreaker("u-passthrough")
    with pytest.raises(TypeError):
        br.call(lambda: (_ for _ in ()).throw(TypeError("bug")), lambda: "fb")
    assert br.consecutive_failures == 0 and br.state == "closed"


def test_breaker_probe_slot_released_when_passthrough_escapes():
    """A TypeError escaping the half-open probe must not wedge the
    breaker: the probe slot is released and the NEXT call probes."""
    br, clk = mkbreaker("u-probe-abort")
    boom = lambda: (_ for _ in ()).throw(RuntimeError("x"))
    for _ in range(3):
        br.call(boom, lambda: "fb")
    clk.t = 11.0  # past recovery: next call is the probe
    with pytest.raises(TypeError):
        br.call(lambda: (_ for _ in ()).throw(TypeError("bug")), lambda: "fb")
    assert br.state == "half-open"
    # The breaker is NOT wedged: this call wins the probe slot and
    # restores service.
    assert br.call(lambda: "dev", lambda: "fb") == "dev"
    assert br.state == "closed"


def test_breaker_disabled_is_a_pure_bypass():
    br, _ = mkbreaker("u-bypass", enabled=False)
    with pytest.raises(RuntimeError):
        br.call(lambda: (_ for _ in ()).throw(RuntimeError("x")), lambda: "fb")
    assert br.state == "closed" and br.consecutive_failures == 0


def test_breaker_health_snapshot_exported():
    br, _ = mkbreaker("u-health")
    br.call(lambda: (_ for _ in ()).throw(RuntimeError("x")), lambda: None)
    snap = health_snapshot()["breakers"]["u-health"]
    assert snap["state"] == "closed" and snap["consecutive-failures"] == 1
    assert "exception" in snap["last-error"]


# -- restart policy -----------------------------------------------------


def test_restart_policy_backoff_deterministic_jittered_capped():
    p = RestartPolicy(base_delay=0.5, max_delay=8.0, multiplier=2.0, jitter=0.1)
    a = [p.delay("ospfv2", i) for i in range(8)]
    b = [p.delay("ospfv2", i) for i in range(8)]
    assert a == b, "jitter must be deterministic per (actor, attempt)"
    # Exponential envelope with +/-10% jitter, capped at max_delay * 1.1.
    for i, d in enumerate(a):
        base = min(0.5 * 2.0 ** i, 8.0)
        assert base * 0.9 <= d <= base * 1.1
    # Distinct actors de-synchronize their restarts.
    assert p.delay("ospfv2", 0) != p.delay("isis", 0)


# -- supervisor on a virtual-clock loop ---------------------------------


class Worker(Actor):
    name = "worker"

    def __init__(self):
        self.got = []
        self.restarts = 0

    def handle(self, msg):
        self.got.append(msg)

    def on_restart(self):
        self.restarts += 1


def mksupervised(policy=None):
    loop = EventLoop(clock=VirtualClock())
    sup = Supervisor(policy or RestartPolicy(base_delay=1.0, jitter=0.0)).install(loop)
    w = Worker()
    loop.register(w)
    return loop, sup, w


def test_supervisor_restarts_crashed_actor_and_redelivers_held_mail():
    loop, sup, w = mksupervised()
    before = telemetry.snapshot(prefix="holo_resilience_actor_restarts")
    loop.send("worker", PoisonPill())
    loop.run_until_idle()
    assert "worker" in loop._crashed
    # Mail sent while down is held, not dropped (supervised loop).
    assert loop.send("worker", "while-down")
    loop.run_until_idle()
    assert w.got == []  # not delivered yet: actor still crashed
    loop.advance(2.0)  # past the 1s backoff: restart fires
    assert "worker" not in loop._crashed
    assert w.restarts == 1 and w.got == ["while-down"]
    assert sup.restarts["worker"] == 1
    after = telemetry.snapshot(prefix="holo_resilience_actor_restarts")
    assert (
        after.get("holo_resilience_actor_restarts_total{actor=worker}", 0)
        > before.get("holo_resilience_actor_restarts_total{actor=worker}", 0)
    )
    # Service actually restored: new mail flows normally.
    loop.send("worker", "after")
    loop.run_until_idle()
    assert w.got == ["while-down", "after"]


def test_supervisor_crash_loop_parks_actor_degraded():
    loop, sup, w = mksupervised(
        RestartPolicy(
            base_delay=0.5, jitter=0.0, crash_loop_threshold=3,
            crash_loop_window=300.0,
        )
    )
    for _ in range(3):
        loop.send("worker", PoisonPill())
        loop.advance(60.0)  # crash -> backoff -> restart (until degraded)
    assert "worker" in sup.degraded
    assert not loop.send("worker", "dead-letter"), "degraded refuses mail"
    loop.advance(120.0)
    assert "worker" in loop._crashed, "no further restarts"
    assert sup.restarts.get("worker", 0) == 2  # third crash degraded
    health = health_snapshot()["supervision"]
    assert "worker" in health["degraded-actors"]


def test_supervisor_old_crashes_age_out_of_the_window():
    loop, sup, w = mksupervised(
        RestartPolicy(
            base_delay=0.5, jitter=0.0, crash_loop_threshold=3,
            crash_loop_window=10.0,
        )
    )
    for _ in range(5):  # spaced far beyond the window: never a crash loop
        loop.send("worker", PoisonPill())
        loop.advance(100.0)
    assert "worker" not in sup.degraded
    assert sup.restarts["worker"] == 5


def test_held_mail_is_bounded_and_drops_are_introspectable():
    loop, sup, w = mksupervised()
    loop.send("worker", PoisonPill())
    loop.run_until_idle()
    loop.held_mail_limit = 8
    accepted = sum(bool(loop.send("worker", i)) for i in range(20))
    assert accepted == 8
    # The 12 refused messages are the operator's lost-mail signal.
    snap = loop.introspect()["actors"]["worker"]
    assert snap["held-mail-dropped"] == 12 and snap["crashed"]
    loop.advance(5.0)
    assert w.got == list(range(8))


def test_supervisor_self_heals_after_its_own_crash():
    """A crashed supervisor cannot wait on its own held inbox: it
    self-heals immediately, and supervision of OTHER actors survives."""
    loop, sup, w = mksupervised()
    loop.send(sup.name, PoisonPill())
    loop.run_until_idle()
    assert sup.name not in loop._crashed, "self-healed on the spot"
    # Supervision still works end to end afterwards.
    loop.send("worker", PoisonPill())
    loop.run_until_idle()
    loop.advance(2.0)
    assert w.restarts == 1 and sup.restarts["worker"] == 1
    assert sup.crashes[sup.name] == 1  # the incident is still counted


def test_unadopt_forgets_verdicts_so_replaced_instances_are_supervised():
    """Tearing an instance down on purpose is not a crash: the SAME
    supervisor must supervise a re-created actor of the same name
    afresh — no inherited degraded verdict, no stale crash history (the
    natural remediation for a crash loop is delete + re-create).
    Mirrors the daemon shape: supervisor on the home loop, the instance
    on its own adopted loop."""
    home = EventLoop(clock=VirtualClock())
    sup = Supervisor(
        RestartPolicy(
            base_delay=0.5, jitter=0.0, crash_loop_threshold=2,
            crash_loop_window=300.0,
        )
    ).install(home)

    def spin(inst_loop):
        # Drive both cooperative loops: deliveries on each, then the
        # home clock forward so backoff/restart timers fire.
        for _ in range(4):
            inst_loop.run_until_idle()
            home.advance(10.0)
            inst_loop.run_until_idle()

    loop_a = EventLoop(clock=VirtualClock())
    sup.adopt(loop_a)
    w1 = Worker()
    loop_a.register(w1)
    for _ in range(2):  # crash loop -> degraded
        loop_a.send("worker", PoisonPill())
        spin(loop_a)
    assert "worker" in sup.degraded
    # Unplace the instance: loop dropped, verdicts cleared.
    sup.unadopt(loop_a)
    assert "worker" not in sup.degraded
    assert not any(lp is loop_a for lp, _ in sup._loops)
    # Re-placed incarnation: fresh loop, same actor name.
    loop_b = EventLoop(clock=VirtualClock())
    sup.adopt(loop_b)
    w2 = Worker()
    loop_b.register(w2)
    loop_b.send("worker", PoisonPill())
    spin(loop_b)
    assert w2.restarts == 1, "one crash on the new incarnation restarts"
    assert "worker" not in sup.degraded


def test_supervisor_restarts_threaded_loop_actor_on_its_own_thread():
    """Adopted ThreadedLoop: the crash notice marshals to the home
    loop, and the restart marshals BACK — on_restart and held-mail
    redelivery run on the instance's pump thread, never the
    supervisor's."""
    import threading
    import time as _time

    from holo_tpu.utils.preempt import ThreadedLoop

    home = EventLoop(clock=VirtualClock())
    sup = Supervisor(RestartPolicy(base_delay=0.5, jitter=0.0)).install(home)
    tl = ThreadedLoop(name="inst")
    threads = []

    class TWorker(Worker):
        def on_restart(self):
            super().on_restart()
            threads.append(threading.get_ident())

    w = TWorker()
    tl.register(w, name="worker")
    sup.adopt(tl.loop, sender=tl.send)  # before start, like the daemon
    tl.start()
    tl.send("worker", PoisonPill())

    def wait(cond, what):
        deadline = _time.monotonic() + 10
        while not cond() and _time.monotonic() < deadline:
            _time.sleep(0.01)
            home.run_until_idle()  # pump CrashNotice / RestartDone
        assert cond(), what

    wait(lambda: "worker" in tl.loop._crashed, "crash")
    assert tl.send("worker", "while-down")  # held on the adopted loop
    home.advance(1.0)  # backoff expires -> RestartDue marshals to tl
    wait(lambda: sup.restarts.get("worker") == 1, "restart counted")
    assert w.restarts == 1
    assert threads and threads[0] == tl._thread.ident, (
        "on_restart must run on the instance's pump thread"
    )
    wait(lambda: w.got == ["while-down"], "held mail redelivered")
    tl.stop()


def test_restart_runner_crash_self_heals_and_supervision_survives():
    """Chaos may kill the restart runner itself; it cannot be restarted
    through its own dead inbox, so it heals in the crash callback — and
    actors on that loop still restart afterwards."""
    import time as _time

    from holo_tpu.utils.preempt import ThreadedLoop

    home = EventLoop(clock=VirtualClock())
    sup = Supervisor(RestartPolicy(base_delay=0.5, jitter=0.0)).install(home)
    tl = ThreadedLoop(name="inst2")
    w = Worker()
    tl.register(w, name="worker")
    sup.adopt(tl.loop, sender=tl.send)
    tl.start()
    tl.send(Supervisor.RUNNER, PoisonPill())

    def wait(cond, what):
        deadline = _time.monotonic() + 10
        while not cond() and _time.monotonic() < deadline:
            _time.sleep(0.01)
            home.run_until_idle()
        assert cond(), what

    wait(lambda: sup.crashes.get(Supervisor.RUNNER) == 1, "runner crash seen")
    assert Supervisor.RUNNER not in tl.loop._crashed, "runner self-healed"
    tl.send("worker", PoisonPill())
    wait(lambda: "worker" in tl.loop._crashed, "worker crash")
    home.advance(1.0)  # backoff -> RestartDue marshals through the runner
    wait(lambda: sup.restarts.get("worker") == 1, "worker restarted")
    assert w.restarts == 1
    tl.stop()


# -- fault plans --------------------------------------------------------


def test_fault_plan_streams_deterministic_and_site_independent():
    a, b = FaultInjector(FaultPlan(seed=7)), FaultInjector(FaultPlan(seed=7))
    sa = [a._rng("fabric.drop").random() for _ in range(50)]
    sb = [b._rng("fabric.drop").random() for _ in range(50)]
    assert sa == sb, "same seed + site -> same stream"
    # Draws on another site's stream must not perturb this one.
    c = FaultInjector(FaultPlan(seed=7))
    c._rng("netio.send").random()
    sc = [c._rng("fabric.drop").random() for _ in range(50)]
    assert sc == sa
    assert [
        FaultInjector(FaultPlan(seed=8))._rng("fabric.drop").random()
        for _ in range(50)
    ] != sa


def test_forced_dispatch_failures_burn_down_exactly():
    inj = FaultInjector(FaultPlan(dispatch_fail={"spf.dispatch": 2}))
    with inject(inj):
        for _ in range(2):
            with pytest.raises(InjectedFault):
                faults_mod.crashpoint("spf.dispatch")
        faults_mod.crashpoint("spf.dispatch")  # exhausted: no-op
        faults_mod.crashpoint("frr.dispatch")  # other sites untouched
    assert inj.injected["spf.dispatch"] == 2
    faults_mod.crashpoint("spf.dispatch")  # disarmed: no-op


def test_faulty_netio_raises_per_plan_and_forwards_rest():
    sent = []

    class Sink:
        def send(self, ifname, src, dst, data):
            sent.append(data)

    inj = FaultInjector(FaultPlan(seed=3, send_error_prob=0.5))
    io = inj.wrap_netio(Sink())
    errors = 0
    for i in range(40):
        try:
            io.send("e0", None, None, i)
        except OSError:
            errors += 1
    assert errors == inj.injected["netio.send"] > 0
    assert len(sent) == 40 - errors


def test_jittered_advance_preserves_total_time():
    inj = FaultInjector(FaultPlan(seed=1, timer_jitter=0.5))
    loop = EventLoop(clock=VirtualClock())
    got = []

    class T(Actor):
        name = "t"

        def handle(self, msg):
            got.append((msg, loop.clock.now()))

    loop.register(T())
    loop.timer("t", lambda: "fire").start(10.0)
    inj.jittered_advance(loop, 30.0, steps=7)
    assert loop.clock.now() == pytest.approx(30.0)
    assert [m for m, _ in got] == ["fire"]


# -- txqueue drop-cause attribution -------------------------------------


def test_txqueue_drop_causes_attributed():
    import threading

    from holo_tpu.utils.txqueue import TxTaskNetIo

    gate = threading.Event()

    class SlowBadSink:
        def __init__(self):
            self.fail = False

        def send(self, ifname, src, dst, data):
            if ifname == "slow0":
                gate.wait(timeout=10)
            if self.fail:
                raise OSError("wire died")

    sink = SlowBadSink()

    def causes(ifname):
        snap = telemetry.snapshot(prefix="holo_txqueue_dropped")
        return {
            cause: snap.get(
                f"holo_txqueue_dropped_total{{ifname={ifname},cause={cause}}}", 0
            )
            for cause in ("overflow", "send_error", "closed")
        }

    # overflow: bounded enqueue against a gated wire times out.
    tx = TxTaskNetIo(sink, maxsize=1, put_timeout=0.05)
    base = causes("slow0")
    for i in range(4):
        tx.send("slow0", None, None, i)
    assert causes("slow0")["overflow"] > base["overflow"]
    gate.set()
    tx.close()

    # send_error: the pump's send raised — the accepted packet is gone.
    sink2 = SlowBadSink()
    sink2.fail = True
    tx2 = TxTaskNetIo(sink2)
    base = causes("bad0")
    tx2.send("bad0", None, None, b"x")
    tx2.close()
    assert causes("bad0")["send_error"] > base["send_error"]

    # closed: late send after teardown.
    base = causes("bad0")
    tx2.send("bad0", None, None, b"late")
    assert causes("bad0")["closed"] > base["closed"]


# -- event recorder crash-safe flush ------------------------------------


def test_event_recorder_flush_fsyncs_journal(tmp_path):
    from holo_tpu.utils.event_recorder import EventRecorder, read_entries

    rec = EventRecorder(tmp_path / "ev.jsonl")
    rec.record("a", 1.0, {"k": 1})
    rec.flush()  # the SIGTERM path: flush + fsync, file stays open
    entries = read_entries(tmp_path / "ev.jsonl")
    assert len(entries) == 1 and entries[0]["actor"] == "a"
    rec.record("a", 2.0, {"k": 2})
    rec.close()
    rec.close()  # idempotent
    rec.flush()  # after close: a no-op, never a crash
    assert len(read_entries(tmp_path / "ev.jsonl")) == 2
    # JSON stays one-entry-per-line greppable after fsync interleaving.
    lines = (tmp_path / "ev.jsonl").read_text().splitlines()
    assert all(json.loads(l) for l in lines)

"""Stepwise conformance: the reference's per-step golden cases replayed
through our live OSPFv2 instance (tools/stepwise.py).

Every case brings ONE recorded router to convergence by replaying its
events.jsonl through the real packet/FSM/flooding machinery, then applies
the numbered step inputs and asserts the protocol-output plane (exact tx
messages) and the local-rib state plane.
"""

import os
from pathlib import Path

import pytest

from holo_tpu.tools.stepwise import OSPFV2_DIR, case_map, run_all, run_case

pytestmark = pytest.mark.skipif(
    not OSPFV2_DIR.exists(), reason="reference corpus not present"
)

# Cases that must pass (regression lock).  The full sweep also enforces a
# floor on total passes so newly-supported cases only ratchet UP.
KNOWN_PASS = [
    "ibus-addr-add1",
    "ibus-addr-add2",
    "packet-hello-validation1",
    "packet-area-mismatch1",
]
PASS_FLOOR = 86


def test_known_cases_pass():
    cm = case_map()
    for case in KNOWN_PASS:
        status, detail = run_case(OSPFV2_DIR / case, *cm[case])
        assert status == "pass", f"{case}: {detail}"


@pytest.mark.skipif(
    os.environ.get("HOLO_TPU_FULL_STEPWISE", "1") != "1",
    reason="full sweep disabled",
)
def test_stepwise_sweep_floor():
    res = run_all()
    passed = sorted(c for c, (s, _) in res.items() if s == "pass")
    failed = {c: d for c, (s, d) in res.items() if s == "fail"}
    assert len(passed) >= PASS_FLOOR, (
        f"only {len(passed)} stepwise cases pass (floor {PASS_FLOOR}); "
        f"failures: { {c: d[:120] for c, d in list(failed.items())[:5]} }"
    )

"""RIP stepwise conformance: the reference's recorded cases (both the
ripv2 and ripng corpora) replayed through our live RipInstance
(tools/stepwise_rip.py).

All 72 case directories pass: message handling (requests, responses,
third-party next hops, decode errors), timers (initial/periodic/
triggered updates with the reference's holdoff semantics, route
timeout/GC, neighbor timeout), ibus interface/address/redistribution
events, config changes (cost recalc, split horizon, passive, static
neighbors, distance), and the clear-route RPC — asserting the
protocol, ibus, and northbound-state planes.
"""

from pathlib import Path

import pytest

from holo_tpu.tools.stepwise_rip import RIP_DIR, case_map, run_all, run_case

pytestmark = pytest.mark.skipif(
    not RIP_DIR.exists(), reason="reference corpus not present"
)

KNOWN_PASS = [
    ("ripv2", "message-request1"),
    ("ripv2", "timeout-route1"),
    ("ripng", "message-response9"),
    ("ripng", "nb-config-split-horizon1"),
]
PASS_FLOOR = 72


def test_known_cases_pass():
    for family, case in KNOWN_PASS:
        cm = case_map(family)
        status, detail = run_case(
            family, RIP_DIR / family / case, *cm[case]
        )
        assert status == "pass", f"{family}/{case}: {detail}"


def test_stepwise_sweep_floor():
    res = run_all()
    passed = sorted(c for c, (s, _) in res.items() if s == "pass")
    failed = {c: d for c, (s, d) in res.items() if s != "pass"}
    assert len(passed) >= PASS_FLOOR, (
        f"only {len(passed)} RIP stepwise cases pass (floor {PASS_FLOOR}); "
        f"failures: { {c: d[:120] for c, d in list(failed.items())[:5]} }"
    )

"""OSPFv3 multi-area: ABR inter-area-prefix LSAs, stub default, externals.

Reference: holo-ospf's version-trait inter-area paths applied to v3
(spf.rs / route.rs inter-area machinery, RFC 5340 §4.4.3.4 + §4.8).
"""

from ipaddress import IPv4Address as A
from ipaddress import IPv6Address as A6
from ipaddress import IPv6Network as N6

from holo_tpu.protocols.ospf import packet_v3 as P
from holo_tpu.protocols.ospf.instance_v3 import (
    OspfV3Instance,
    V3IfConfig,
    V3IfUpMsg,
)
from holo_tpu.protocols.ospf.neighbor import NsmState
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock

AREA0 = A("0.0.0.0")
AREA1 = A("0.0.0.1")


def mk(loop, fabric, name, rid):
    r = OspfV3Instance(
        name=name, router_id=A(rid), netio=fabric.sender_for(name)
    )
    loop.register(r)
    return r


def link(fabric, lname, a, ai, alla, aid_a, b, bi, allb, aid_b, **area_kw):
    a.add_interface(ai, V3IfConfig(cost=10, area_id=aid_a), A6(alla), [], **area_kw)
    b.add_interface(bi, V3IfConfig(cost=10, area_id=aid_b), A6(allb), [], **area_kw)
    fabric.join(lname, a.name, ai, A6(alla))
    fabric.join(lname, b.name, bi, A6(allb))


def three_router_two_areas(stub=False):
    """r1 --area1-- r2(ABR) --area0-- r3; r1/r3 advertise one prefix each."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = mk(loop, fabric, "m1", "1.1.1.1")
    r2 = mk(loop, fabric, "m2", "2.2.2.2")
    r3 = mk(loop, fabric, "m3", "3.3.3.3")
    kw = {"stub": True} if stub else {}
    link(fabric, "l12", r1, "e0", "fe80::1:1", AREA1,
         r2, "e0", "fe80::2:1", AREA1, **kw)
    link(fabric, "l23", r2, "e1", "fe80::2:2", AREA0,
         r3, "e0", "fe80::3:1", AREA0)
    r1.interfaces["e0"].prefixes.append(N6("2001:db8:11::/64"))
    r3.interfaces["e0"].prefixes.append(N6("2001:db8:33::/64"))
    for r in (r1, r2, r3):
        for ifname in r.interfaces:
            loop.send(r.name, V3IfUpMsg(ifname))
    loop.advance(90)
    return loop, r1, r2, r3


def test_abr_inter_area_routes_both_directions():
    loop, r1, r2, r3 = three_router_two_areas()
    # r2 is the ABR and knows it
    assert r2.is_abr
    # r1 (area 1) reaches r3's area-0 prefix via an inter-area route
    route = r1.routes.get(N6("2001:db8:33::/64"))
    assert route is not None, sorted(map(str, r1.routes))
    assert route.dist == 10 + 10 + 10
    assert {(i, str(a)) for i, a in route.nexthops} == {("e0", "fe80::2:1")}
    # and symmetric: r3 reaches r1's area-1 prefix
    back = r3.routes.get(N6("2001:db8:11::/64"))
    assert back is not None and back.dist == 30
    # the ABR's router LSA carries the B flag in both areas
    for area in r2.areas.values():
        e = area.lsdb.get(
            P.LsaKey(P.LsaType.ROUTER, A("0.0.0.0"), A("2.2.2.2"))
        )
        assert e is not None and P.RouterFlags.B in e.lsa.body.flags
    # r1's area-1 LSDB holds the ABR's inter-area-prefix LSA
    inter = [
        e.lsa
        for e in r1.lsdb.all()
        if e.lsa.type == P.LsaType.INTER_AREA_PREFIX
        and e.lsa.adv_rtr == A("2.2.2.2")
    ]
    assert any(l.body.prefix == N6("2001:db8:33::/64") for l in inter)


def test_stub_area_gets_default_not_externals():
    loop, r1, r2, r3 = three_router_two_areas(stub=True)
    # r3 (backbone) redistributes an external prefix
    r3.redistribute(N6("2001:db8:ee::/48"), metric=20)
    loop.advance(30)
    # backbone members see the external
    assert N6("2001:db8:ee::/48") in r2.routes
    # the stub-area member does NOT see the AS-external LSA...
    assert not any(
        e.lsa.type == P.LsaType.AS_EXTERNAL for e in r1.lsdb.all()
    )
    # ...but follows the ABR's injected default instead
    default = r1.routes.get(N6("::/0"))
    assert default is not None
    assert {(i, str(a)) for i, a in default.nexthops} == {("e0", "fe80::2:1")}


def test_v3_externals_reach_other_areas():
    loop, r1, r2, r3 = three_router_two_areas()
    r3.redistribute(N6("2001:db8:ee::/48"), metric=20)
    loop.advance(30)
    # normal (non-stub) area member computes the external route via the
    # ASBR (E2: external metric ranks, distance = metric)
    route = r1.routes.get(N6("2001:db8:ee::/48"))
    assert route is not None, sorted(map(str, r1.routes))
    assert {(i, str(a)) for i, a in route.nexthops} == {("e0", "fe80::2:1")}
    # the ASBR's router LSA carries the E flag
    e = r3.lsdb.get(P.LsaKey(P.LsaType.ROUTER, A("0.0.0.0"), A("3.3.3.3")))
    assert P.RouterFlags.E in e.lsa.body.flags


def test_v3_authentication_trailer():
    """RFC 7166: matching SAs converge; tampering and wrong keys drop."""
    import pytest

    from holo_tpu.utils.bytesbuf import DecodeError

    auth = P.AuthCtxV3(key=b"s3cret", sa_id=5, seqno=7)
    pkt = P.Packet(
        A("1.1.1.1"), A("0.0.0.0"),
        P.Hello(iface_id=1, priority=1,
                options=P.Options.V6 | P.Options.E | P.Options.R,
                hello_interval=10, dead_interval=40,
                dr=A("0.0.0.0"), bdr=A("0.0.0.0"), neighbors=[]),
    )
    src, dst = A6("fe80::1"), A6("ff02::5")
    raw = pkt.encode(src, dst, auth=auth)
    out = P.Packet.decode(raw, src, dst, auth=auth)
    assert out.auth_seqno == 7
    bad = bytearray(raw)
    bad[4] ^= 0x01  # tamper inside the signed region
    with pytest.raises(DecodeError):
        P.Packet.decode(bytes(bad), src, dst, auth=auth)
    with pytest.raises(DecodeError):
        P.Packet.decode(raw, src, dst, auth=P.AuthCtxV3(key=b"wrong", sa_id=5))
    with pytest.raises(DecodeError):
        P.Packet.decode(raw[: len(raw) - 10], src, dst, auth=auth)


def _auth_pair(key_a, key_b):
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = mk(loop, fabric, "a1", "1.1.1.1")
    r2 = mk(loop, fabric, "a2", "2.2.2.2")
    r1.add_interface(
        "e0", V3IfConfig(cost=10, auth=P.AuthCtxV3(key=key_a)),
        A6("fe80::a:1"), [],
    )
    r2.add_interface(
        "e0", V3IfConfig(cost=10, auth=P.AuthCtxV3(key=key_b)),
        A6("fe80::a:2"), [],
    )
    fabric.join("l", "a1", "e0", A6("fe80::a:1"))
    fabric.join("l", "a2", "e0", A6("fe80::a:2"))
    for r in (r1, r2):
        loop.send(r.name, V3IfUpMsg("e0"))
    loop.advance(60)
    nbrs = r1.interfaces["e0"].neighbors
    return any(n.state == NsmState.FULL for n in nbrs.values())


def test_v3_auth_convergence_and_mismatch():
    assert _auth_pair(b"same-key", b"same-key")
    assert not _auth_pair(b"key-one", b"key-two")


def test_v3_auth_seqno_restart_safe(tmp_path):
    """A restarted sender must never reuse trailer seqnos (nvstore
    reservation ceiling, like the v2 crypto seqno)."""
    from holo_tpu.utils.nvstore import NvStore

    store = NvStore(tmp_path / "nv.json")

    def boot():
        loop = EventLoop(clock=VirtualClock())
        fabric = MockFabric(loop)
        r = OspfV3Instance(
            name="rs", router_id=A("9.9.9.9"),
            netio=fabric.sender_for("rs"), nvstore=store,
        )
        loop.register(r)
        return r

    first = boot()
    for _ in range(3):  # simulate heavy uptime: exhaust windows
        first._at_seqno = first._at_reserved
        first._reserve_at_seqnos()
    last_sent = first._at_seqno
    second = boot()
    assert second._at_seqno >= last_sent
    assert second._at_reserved > second._at_seqno

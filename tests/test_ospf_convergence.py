"""End-to-end OSPFv2 convergence on the in-memory fabric (virtual clock).

The multi-router analog of the reference's conformance topologies
(holo-ospf/tests/conformance): real instances exchange real packets over
MockFabric links; we assert adjacency, LSDB synchronization, and RIB
contents — then inject a link failure and assert reconvergence.
"""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

import pytest

from holo_tpu.protocols.ospf.instance import (
    IfConfig,
    InstanceConfig,
    OspfInstance,
)
from holo_tpu.protocols.ospf.interface import IfType, IsmState
from holo_tpu.protocols.ospf.neighbor import NsmState
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock

AREA0 = A("0.0.0.0")


def mk_router(loop, fabric, name, rid):
    inst = OspfInstance(
        name=name,
        config=InstanceConfig(router_id=A(rid)),
        netio=fabric.sender_for(name),
    )
    loop.register(inst)
    return inst


def p2p_link(fabric, link, a, a_if, a_addr, b, b_if, b_addr, net, cost=10):
    cfg = IfConfig(area_id=AREA0, if_type=IfType.POINT_TO_POINT, cost=cost)
    a.add_interface(a_if, cfg, N(net), A(a_addr))
    b.add_interface(b_if, cfg, N(net), A(b_addr))
    fabric.join(link, a.name, a_if, A(a_addr))
    fabric.join(link, b.name, b_if, A(b_addr))


def lan_link(fabric, link, members, net, cost=10, prios=None):
    # members: list of (inst, ifname, addr)
    for i, (inst, ifname, addr) in enumerate(members):
        prio = 1 if prios is None else prios[i]
        cfg = IfConfig(area_id=AREA0, if_type=IfType.BROADCAST, cost=cost,
                       priority=prio)
        inst.add_interface(ifname, cfg, N(net), A(addr))
        fabric.join(link, inst.name, ifname, A(addr))


def bring_up(loop, routers, seconds=60):
    from holo_tpu.protocols.ospf.instance import IfUpMsg

    for r in routers:
        for area in r.areas.values():
            for ifname in area.interfaces:
                loop.send(r.name, IfUpMsg(ifname))
    loop.advance(seconds)


def full_neighbors(r):
    out = []
    for area in r.areas.values():
        for iface in area.interfaces.values():
            for nbr in iface.neighbors.values():
                if nbr.state == NsmState.FULL:
                    out.append(nbr.router_id)
    return out


def lsdb_image(r):
    imgs = {}
    for aid, area in r.areas.items():
        imgs[aid] = sorted(
            (k.type, str(k.lsid), str(k.adv_rtr), e.lsa.seq_no, e.lsa.raw[20:])
            for k, e in area.lsdb.entries.items()
        )
    return imgs


def test_master_learns_slave_only_lsa():
    """Regression (§10.8): the slave's negotiation-DD reply carries LSA
    headers the master must process — a slave-only LSA must reach the
    master's LSDB, not silently vanish."""
    from holo_tpu.protocols.ospf.packet import (
        Lsa, LsaSummary, LsaType, Options,
    )

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = mk_router(loop, fabric, "r1", "1.1.1.1")  # lower RID -> slave
    r2 = mk_router(loop, fabric, "r2", "2.2.2.2")  # higher RID -> master
    p2p_link(fabric, "l12", r1, "eth0", "10.0.12.1", r2, "eth0", "10.0.12.2",
             "10.0.12.0/30")
    # Seed a third-party LSA into the slave's LSDB only.
    foreign = Lsa(10, Options.E, LsaType.SUMMARY_NETWORK, A("172.16.0.0"),
                  A("9.9.9.9"), -100, LsaSummary(A("255.255.0.0"), 7))
    foreign.encode()
    r1.areas[AREA0].lsdb.install(foreign, 0.0)
    bring_up(loop, [r1, r2])
    assert full_neighbors(r1) == [A("2.2.2.2")]
    assert r2.areas[AREA0].lsdb.get(foreign.key) is not None, (
        "master never requested the slave-only LSA"
    )
    assert lsdb_image(r1) == lsdb_image(r2)


def test_spf_holddown_backoff_under_churn():
    """RFC 8405: sustained churn must back off to long_delay, not run SPF
    at initial_delay frequency forever."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = mk_router(loop, fabric, "r1", "1.1.1.1")
    r2 = mk_router(loop, fabric, "r2", "2.2.2.2")
    p2p_link(fabric, "l12", r1, "eth0", "10.0.12.1", r2, "eth0", "10.0.12.2",
             "10.0.12.0/30")
    bring_up(loop, [r1, r2])
    runs_before = r1.spf_run_count
    # Churn: flap the link every 2 simulated seconds for 60s.
    for _ in range(15):
        fabric.set_link_up("l12", False)
        loop.advance(2)
        fabric.set_link_up("l12", True)
        loop.advance(2)
    churn_runs = r1.spf_run_count - runs_before
    # long_delay=5s over 60s of churn: well under once per 2s.
    assert churn_runs <= 61 // 5 + 3, f"SPF ran {churn_runs} times under churn"


def test_two_routers_p2p_full_and_routes():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = mk_router(loop, fabric, "r1", "1.1.1.1")
    r2 = mk_router(loop, fabric, "r2", "2.2.2.2")
    p2p_link(fabric, "l12", r1, "eth0", "10.0.12.1", r2, "eth0", "10.0.12.2",
             "10.0.12.0/30")
    bring_up(loop, [r1, r2])

    assert full_neighbors(r1) == [A("2.2.2.2")]
    assert full_neighbors(r2) == [A("1.1.1.1")]
    assert lsdb_image(r1) == lsdb_image(r2)
    # Both see the p2p stub route.
    assert N("10.0.12.0/30") in r1.routes
    assert N("10.0.12.0/30") in r2.routes


def test_three_router_chain_transit_routes():
    """r1 -- r2 -- r3 chain: r1 must route to r3's stub via r2."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = mk_router(loop, fabric, "r1", "1.1.1.1")
    r2 = mk_router(loop, fabric, "r2", "2.2.2.2")
    r3 = mk_router(loop, fabric, "r3", "3.3.3.3")
    p2p_link(fabric, "l12", r1, "eth0", "10.0.12.1", r2, "eth0", "10.0.12.2",
             "10.0.12.0/30", cost=10)
    p2p_link(fabric, "l23", r2, "eth1", "10.0.23.1", r3, "eth0", "10.0.23.2",
             "10.0.23.0/30", cost=5)
    bring_up(loop, [r1, r2, r3])

    assert sorted(map(str, full_neighbors(r2))) == ["1.1.1.1", "3.3.3.3"]
    assert lsdb_image(r1) == lsdb_image(r2) == lsdb_image(r3)
    # r1 -> 10.0.23.0/30 via r2 at cost 10+5.
    route = r1.routes.get(N("10.0.23.0/30"))
    assert route is not None and route.dist == 15
    nhs = {(nh.ifname, str(nh.addr)) for nh in route.nexthops}
    assert nhs == {("eth0", "10.0.12.2")}
    # r3 -> 10.0.12.0/30 via r2 at cost 5+10.
    route = r3.routes.get(N("10.0.12.0/30"))
    assert route is not None and route.dist == 15


def test_broadcast_lan_dr_election_and_network_lsa():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = mk_router(loop, fabric, "r1", "1.1.1.1")
    r2 = mk_router(loop, fabric, "r2", "2.2.2.2")
    r3 = mk_router(loop, fabric, "r3", "3.3.3.3")
    lan_link(fabric, "lan0", [(r1, "eth0", "10.0.0.1"), (r2, "eth0", "10.0.0.2"),
                              (r3, "eth0", "10.0.0.3")], "10.0.0.0/24")
    bring_up(loop, [r1, r2, r3], seconds=120)

    # Highest RID (equal priorities) should be DR.
    states = {}
    for r in (r1, r2, r3):
        iface = r.areas[AREA0].interfaces["eth0"]
        states[r.name] = (iface.state, str(iface.dr), str(iface.bdr))
    assert states["r3"][0] == IsmState.DR
    assert states["r2"][0] == IsmState.BACKUP
    assert states["r1"][0] == IsmState.DR_OTHER
    assert all(s[1] == "10.0.0.3" for s in states.values())
    # All adjacent to DR/BDR; LSDBs synced; network LSA present.
    assert lsdb_image(r1) == lsdb_image(r2) == lsdb_image(r3)
    from holo_tpu.protocols.ospf.packet import LsaType

    nets = [k for k in r1.areas[AREA0].lsdb.entries if k.type == LsaType.NETWORK]
    assert len(nets) == 1 and nets[0].adv_rtr == A("3.3.3.3")
    # Everyone routes the LAN prefix.
    for r in (r1, r2, r3):
        assert N("10.0.0.0/24") in r.routes


def test_link_failure_reconvergence():
    """Square topology: r1-r2-r4, r1-r3-r4; fail r1-r2, traffic shifts."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    rs = {n: mk_router(loop, fabric, n, rid) for n, rid in
          [("r1", "1.1.1.1"), ("r2", "2.2.2.2"), ("r3", "3.3.3.3"), ("r4", "4.4.4.4")]}
    r1, r2, r3, r4 = rs["r1"], rs["r2"], rs["r3"], rs["r4"]
    p2p_link(fabric, "l12", r1, "e0", "10.0.12.1", r2, "e0", "10.0.12.2", "10.0.12.0/30", cost=1)
    p2p_link(fabric, "l13", r1, "e1", "10.0.13.1", r3, "e0", "10.0.13.2", "10.0.13.0/30", cost=5)
    p2p_link(fabric, "l24", r2, "e1", "10.0.24.1", r4, "e0", "10.0.24.2", "10.0.24.0/30", cost=1)
    p2p_link(fabric, "l34", r3, "e1", "10.0.34.1", r4, "e1", "10.0.34.2", "10.0.34.0/30", cost=5)
    bring_up(loop, rs.values(), seconds=90)

    # Shortest r1->r4 is via r2 (cost 2 to reach 10.0.24.0/30).
    route = r1.routes.get(N("10.0.24.0/30"))
    assert route is not None and route.dist == 2
    assert {nh.ifname for nh in route.nexthops} == {"e0"}

    # Fail the r1-r2 link: dead interval expires, reconverge via r3.
    fabric.set_link_up("l12", False)
    loop.advance(120)
    route = r1.routes.get(N("10.0.24.0/30"))
    assert route is not None, "route lost after reconvergence"
    assert {nh.ifname for nh in route.nexthops} == {"e1"}
    assert route.dist == 5 + 5 + 1


def test_multi_area_inter_area_routes():
    """r1 (area 1) -- r2 (ABR: areas 1+0) -- r3 (area 0): prefixes cross
    the ABR as Summary-LSAs and both edge routers get inter-area routes."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = mk_router(loop, fabric, "r1", "1.1.1.1")
    r2 = mk_router(loop, fabric, "r2", "2.2.2.2")
    r3 = mk_router(loop, fabric, "r3", "3.3.3.3")
    area1 = A("0.0.0.1")
    cfg1 = IfConfig(area_id=area1, if_type=IfType.POINT_TO_POINT, cost=10)
    cfg0 = IfConfig(area_id=AREA0, if_type=IfType.POINT_TO_POINT, cost=5)
    r1.add_interface("e0", cfg1, N("10.0.12.0/30"), A("10.0.12.1"))
    r2.add_interface("e0", cfg1, N("10.0.12.0/30"), A("10.0.12.2"))
    r2.add_interface("e1", cfg0, N("10.0.23.0/30"), A("10.0.23.1"))
    r3.add_interface("e0", cfg0, N("10.0.23.0/30"), A("10.0.23.2"))
    fabric.join("l12", "r1", "e0", A("10.0.12.1"))
    fabric.join("l12", "r2", "e0", A("10.0.12.2"))
    fabric.join("l23", "r2", "e1", A("10.0.23.1"))
    fabric.join("l23", "r3", "e0", A("10.0.23.2"))
    bring_up(loop, [r1, r2, r3], seconds=90)

    assert r2.is_abr
    # r1 (area 1 only) reaches the area-0 prefix via a summary.
    route = r1.routes.get(N("10.0.23.0/30"))
    assert route is not None, "no inter-area route at r1"
    assert route.dist == 10 + 5
    assert {(nh.ifname, str(nh.addr)) for nh in route.nexthops} == {
        ("e0", "10.0.12.2")
    }
    # r3 (area 0 only) reaches the area-1 prefix.
    route = r3.routes.get(N("10.0.12.0/30"))
    assert route is not None and route.dist == 5 + 10
    # ABR's router LSA carries the B bit in both areas.
    from holo_tpu.protocols.ospf.packet import LsaKey, LsaType, RouterFlags

    for aid in (AREA0, area1):
        e = r2.areas[aid].lsdb.get(
            LsaKey(LsaType.ROUTER, A("2.2.2.2"), A("2.2.2.2"))
        )
        assert e is not None and e.lsa.body.flags & RouterFlags.B


def test_three_area_hierarchy_chained_abrs():
    """area1 -- ABR -- backbone -- ABR -- area2: backbone-learned
    inter-area routes are re-summarized into leaf areas (§12.4.3)."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    rs = [mk_router(loop, fabric, f"r{i}", f"{i}.{i}.{i}.{i}") for i in (1, 2, 3, 4)]
    r1, r2, r3, r4 = rs

    def alink(nm, a, ai, aa, b, bi, ba, net, c, area):
        cfg = IfConfig(area_id=A(area), if_type=IfType.POINT_TO_POINT, cost=c)
        a.add_interface(ai, cfg, N(net), A(aa))
        b.add_interface(bi, cfg, N(net), A(ba))
        fabric.join(nm, a.name, ai, A(aa))
        fabric.join(nm, b.name, bi, A(ba))

    alink("a", r1, "e0", "10.0.12.1", r2, "e0", "10.0.12.2", "10.0.12.0/30", 10, "0.0.0.1")
    alink("b", r2, "e1", "10.0.23.1", r3, "e0", "10.0.23.2", "10.0.23.0/30", 5, "0.0.0.0")
    alink("c", r3, "e1", "10.0.34.1", r4, "e0", "10.0.34.2", "10.0.34.0/30", 3, "0.0.0.2")
    bring_up(loop, rs, seconds=150)

    route = r1.routes.get(N("10.0.34.0/30"))
    assert route is not None and route.dist == 10 + 5 + 3
    route = r4.routes.get(N("10.0.12.0/30"))
    assert route is not None and route.dist == 18


def test_external_routes_type5():
    """r3 (ASBR) redistributes a prefix; r1 learns it as an E2 external
    via type-5 flooding, with next hops toward the ASBR."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = mk_router(loop, fabric, "r1", "1.1.1.1")
    r2 = mk_router(loop, fabric, "r2", "2.2.2.2")
    r3 = mk_router(loop, fabric, "r3", "3.3.3.3")
    p2p_link(fabric, "l12", r1, "e0", "10.0.12.1", r2, "e0", "10.0.12.2",
             "10.0.12.0/30", cost=10)
    p2p_link(fabric, "l23", r2, "e1", "10.0.23.1", r3, "e0", "10.0.23.2",
             "10.0.23.0/30", cost=5)
    bring_up(loop, [r1, r2, r3])

    r3.redistribute(N("203.0.113.0/24"), metric=20)
    loop.advance(30)
    route = r1.routes.get(N("203.0.113.0/24"))
    assert route is not None, "external route missing at r1"
    assert route.dist == 20  # E2: metric, internal cost breaks ties
    assert {(nh.ifname, str(nh.addr)) for nh in route.nexthops} == {
        ("e0", "10.0.12.2")
    }
    # ASBR flag set in r3's router LSA.
    from holo_tpu.protocols.ospf.packet import LsaKey, LsaType, RouterFlags

    e = r1.areas[AREA0].lsdb.get(
        LsaKey(LsaType.ROUTER, A("3.3.3.3"), A("3.3.3.3"))
    )
    assert e is not None and e.lsa.body.flags & RouterFlags.E

    # Withdrawal flushes the type-5 and removes the route everywhere.
    r3.withdraw_redistributed(N("203.0.113.0/24"))
    loop.advance(30)
    assert N("203.0.113.0/24") not in r1.routes


def test_external_across_areas_type4():
    """ASBR in area 1, consumer in area 0: the ABR's type-4 ASBR-summary
    lets area-0 routers resolve the ASBR and use the type-5 route."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = mk_router(loop, fabric, "r1", "1.1.1.1")  # area 0 only
    r2 = mk_router(loop, fabric, "r2", "2.2.2.2")  # ABR
    r3 = mk_router(loop, fabric, "r3", "3.3.3.3")  # ASBR, area 1 only
    cfg0 = IfConfig(area_id=AREA0, if_type=IfType.POINT_TO_POINT, cost=10)
    cfg1 = IfConfig(area_id=A("0.0.0.1"), if_type=IfType.POINT_TO_POINT, cost=5)
    r1.add_interface("e0", cfg0, N("10.0.12.0/30"), A("10.0.12.1"))
    r2.add_interface("e0", cfg0, N("10.0.12.0/30"), A("10.0.12.2"))
    r2.add_interface("e1", cfg1, N("10.0.23.0/30"), A("10.0.23.1"))
    r3.add_interface("e0", cfg1, N("10.0.23.0/30"), A("10.0.23.2"))
    fabric.join("l12", "r1", "e0", A("10.0.12.1"))
    fabric.join("l12", "r2", "e0", A("10.0.12.2"))
    fabric.join("l23", "r2", "e1", A("10.0.23.1"))
    fabric.join("l23", "r3", "e0", A("10.0.23.2"))
    bring_up(loop, [r1, r2, r3], seconds=90)

    r3.redistribute(N("203.0.113.0/24"), metric=20)
    loop.advance(60)
    route = r1.routes.get(N("203.0.113.0/24"))
    assert route is not None, "cross-area external missing (type-4 path)"
    assert {(nh.ifname, str(nh.addr)) for nh in route.nexthops} == {
        ("e0", "10.0.12.2")
    }
    # Appendix E: two externals sharing a network address coexist.
    r3.redistribute(N("203.0.113.0/25"), metric=30)
    loop.advance(60)
    assert N("203.0.113.0/24") in r1.routes
    assert N("203.0.113.0/25") in r1.routes
    r3.withdraw_redistributed(N("203.0.113.0/25"))
    loop.advance(60)
    assert N("203.0.113.0/24") in r1.routes  # /24 survives /25 withdrawal
    assert N("203.0.113.0/25") not in r1.routes


def test_stub_area_default_and_no_type5():
    """Stub area 1: type-5s stay out, ABR injects a default summary, and
    stub routers still reach externals via the default."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = mk_router(loop, fabric, "r1", "1.1.1.1")  # stub area 1
    r2 = mk_router(loop, fabric, "r2", "2.2.2.2")  # ABR
    r3 = mk_router(loop, fabric, "r3", "3.3.3.3")  # ASBR, backbone
    area1 = A("0.0.0.1")
    cfg1 = IfConfig(area_id=area1, if_type=IfType.POINT_TO_POINT, cost=10)
    cfg0 = IfConfig(area_id=AREA0, if_type=IfType.POINT_TO_POINT, cost=5)
    r1.add_interface("e0", cfg1, N("10.0.12.0/30"), A("10.0.12.1"), stub=True)
    r2.add_interface("e0", cfg1, N("10.0.12.0/30"), A("10.0.12.2"), stub=True)
    r2.add_interface("e1", cfg0, N("10.0.23.0/30"), A("10.0.23.1"))
    r3.add_interface("e0", cfg0, N("10.0.23.0/30"), A("10.0.23.2"))
    fabric.join("l12", "r1", "e0", A("10.0.12.1"))
    fabric.join("l12", "r2", "e0", A("10.0.12.2"))
    fabric.join("l23", "r2", "e1", A("10.0.23.1"))
    fabric.join("l23", "r3", "e0", A("10.0.23.2"))
    bring_up(loop, [r1, r2, r3], seconds=90)

    r3.redistribute(N("203.0.113.0/24"), metric=20)
    loop.advance(60)
    from holo_tpu.protocols.ospf.packet import LsaType

    # No type-5 (and no type-4) in the stub area's LSDB; a default
    # summary instead.
    stub_lsdb = r1.areas[area1].lsdb
    assert not any(k.type == LsaType.AS_EXTERNAL for k in stub_lsdb.entries)
    assert not any(k.type == LsaType.SUMMARY_ROUTER for k in stub_lsdb.entries)
    assert N("0.0.0.0/0") in r1.routes
    assert N("203.0.113.0/24") not in r1.routes  # reachable via default
    # Backbone side still has the external.
    assert N("203.0.113.0/24") in r2.routes


def test_stub_ebit_mismatch_blocks_adjacency():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = mk_router(loop, fabric, "r1", "1.1.1.1")
    r2 = mk_router(loop, fabric, "r2", "2.2.2.2")
    cfg = IfConfig(if_type=IfType.POINT_TO_POINT)
    r1.add_interface("e0", cfg, N("10.0.12.0/30"), A("10.0.12.1"), stub=True)
    r2.add_interface("e0", cfg, N("10.0.12.0/30"), A("10.0.12.2"))
    fabric.join("l12", "r1", "e0", A("10.0.12.1"))
    fabric.join("l12", "r2", "e0", A("10.0.12.2"))
    bring_up(loop, [r1, r2])
    assert full_neighbors(r1) == []  # E-bit disagreement: no adjacency


def test_daemon_redistribute_static_into_ospf():
    """Config-driven: d2 redistributes a static route; d1's RIB learns it
    through OSPF."""
    loop, fabric, d1, d2 = __import__("tests.test_daemon",
                                      fromlist=["two_daemon_setup"]
                                      ).two_daemon_setup()
    from tests.test_daemon import configure

    configure(d1, "1.1.1.1", "10.0.12.1/30")
    configure(d2, "2.2.2.2", "10.0.12.2/30")
    cand = d2.candidate()
    cand.set("routing/control-plane-protocols/ospfv2/redistribute", ["static"])
    cand.set(
        "routing/control-plane-protocols/static-routes/route[198.51.100.0/24]/next-hop",
        "192.0.2.254",
    )
    d2.commit(cand)
    loop.advance(60)
    rib1 = d1.routing.rib.active_routes()
    assert N("198.51.100.0/24") in rib1
    assert rib1[N("198.51.100.0/24")].protocol.value == "ospfv2"


def test_ecmp_on_equal_cost_paths():
    """Two equal-cost paths r1->r4 must produce two next hops."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    rs = {n: mk_router(loop, fabric, n, rid) for n, rid in
          [("r1", "1.1.1.1"), ("r2", "2.2.2.2"), ("r3", "3.3.3.3"), ("r4", "4.4.4.4")]}
    r1, r2, r3, r4 = rs["r1"], rs["r2"], rs["r3"], rs["r4"]
    p2p_link(fabric, "l12", r1, "e0", "10.0.12.1", r2, "e0", "10.0.12.2", "10.0.12.0/30", cost=1)
    p2p_link(fabric, "l13", r1, "e1", "10.0.13.1", r3, "e0", "10.0.13.2", "10.0.13.0/30", cost=1)
    p2p_link(fabric, "l24", r2, "e1", "10.0.24.1", r4, "e0", "10.0.24.2", "10.0.24.0/30", cost=1)
    p2p_link(fabric, "l34", r3, "e1", "10.0.34.1", r4, "e1", "10.0.34.2", "10.0.34.0/30", cost=1)
    # r4 loopback-ish stub via an extra LAN it alone sits on:
    lan_link(fabric, "lan4", [(r4, "e2", "192.168.4.1")], "192.168.4.0/24")
    bring_up(loop, rs.values(), seconds=90)

    route = r1.routes.get(N("192.168.4.0/24"))
    assert route is not None
    assert {nh.ifname for nh in route.nexthops} == {"e0", "e1"}
    nhs = {str(nh.addr) for nh in route.nexthops}
    assert nhs == {"10.0.12.2", "10.0.13.2"}

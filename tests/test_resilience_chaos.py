"""Chaos e2e: the resilience subsystem under injected failure.

The acceptance scenario (ISSUE 4): kill a protocol actor AND force >= 3
consecutive TPU dispatch failures — the run must end with the actor
restarted (restart counter > 0), the breaker OPEN then restored via a
half-open probe, and the final RIB bit-identical to a clean
scalar-oracle run of the same topology events.

Plus the harness's own guarantee: the same FaultPlan seed produces an
identical event-recorder sequence across two runs (chaos results must
be replayable), and OSPF reconverges through packet loss.
"""

import json
from contextlib import nullcontext
from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.protocols.ospf.instance import (
    IfConfig,
    IfUpMsg,
    InstanceConfig,
    OspfInstance,
)
from holo_tpu.protocols.ospf.interface import IfType
from holo_tpu.resilience import (
    CircuitBreaker,
    FaultInjector,
    FaultPlan,
    RestartPolicy,
    Supervisor,
    inject,
)
from holo_tpu.routing.rib import MockKernel, RibManager
from holo_tpu.utils.event_recorder import EventRecorder, instrument, read_entries
from holo_tpu.utils.ibus import Ibus
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock
from holo_tpu.utils.southbound import Protocol

AREA0 = A("0.0.0.0")
DEST = N("10.0.23.0/30")  # the r2--r3 subnet, primary via r2 from r1


def triangle(loop, fabric, r1_backend=None):
    """r1--r2 (10), r2--r3 (10), r1--r3 (100); r1 optionally computes
    SPF on an injected (breaker-guarded TPU) backend."""
    buses, kernels, ribs, routers = {}, {}, {}, {}
    for name, rid in [("r1", "1.1.1.1"), ("r2", "2.2.2.2"), ("r3", "3.3.3.3")]:
        bus = Ibus(loop)
        k = MockKernel()
        rib = RibManager(bus, k)
        rib.name = f"routing-{name}"
        loop.register(rib)
        inst = OspfInstance(
            name=name,
            config=InstanceConfig(router_id=A(rid)),
            netio=fabric.sender_for(name),
            spf_backend=r1_backend if name == "r1" else None,
        )
        loop.register(inst)
        inst.attach_ibus(bus, routing_actor=rib.name)
        buses[name], kernels[name], ribs[name], routers[name] = bus, k, rib, inst

    cfg = lambda c: IfConfig(if_type=IfType.POINT_TO_POINT, cost=c)
    r1, r2, r3 = routers["r1"], routers["r2"], routers["r3"]
    r1.add_interface("e0", cfg(10), N("10.0.12.0/30"), A("10.0.12.1"))
    r2.add_interface("e0", cfg(10), N("10.0.12.0/30"), A("10.0.12.2"))
    r2.add_interface("e1", cfg(10), N("10.0.23.0/30"), A("10.0.23.1"))
    r3.add_interface("e0", cfg(10), N("10.0.23.0/30"), A("10.0.23.2"))
    r1.add_interface("e1", cfg(100), N("10.0.13.0/30"), A("10.0.13.1"))
    r3.add_interface("e1", cfg(100), N("10.0.13.0/30"), A("10.0.13.2"))
    fabric.join("l12", "r1", "e0", A("10.0.12.1"))
    fabric.join("l12", "r2", "e0", A("10.0.12.2"))
    fabric.join("l23", "r2", "e1", A("10.0.23.1"))
    fabric.join("l23", "r3", "e0", A("10.0.23.2"))
    fabric.join("l13", "r1", "e1", A("10.0.13.1"))
    fabric.join("l13", "r3", "e1", A("10.0.13.2"))
    for r in routers.values():
        for area in r.areas.values():
            for ifname in area.interfaces:
                loop.send(r.name, IfUpMsg(ifname))
    return buses, kernels, ribs, routers


def test_chaos_actor_kill_breaker_cycle_and_rib_parity():
    """THE acceptance scenario.  The chaos arm and the clean control arm
    see the SAME topology events; the control's r1 computes on the
    scalar oracle throughout, so final-FIB equality IS the 'RIB
    bit-identical to the scalar oracle' contract."""

    def scenario(chaos: bool):
        from holo_tpu.spf.backend import TpuSpfBackend

        loop = EventLoop(clock=VirtualClock())
        fabric = MockFabric(loop)
        breaker = sup = backend = None
        if chaos:
            breaker = CircuitBreaker(
                "spf-chaos",
                failure_threshold=3,
                recovery_timeout=30.0,
                clock=loop.clock.now,
            )
            backend = TpuSpfBackend(64, breaker=breaker)
            sup = Supervisor(
                RestartPolicy(base_delay=1.0, jitter=0.1)
            ).install(loop)
        buses, kernels, ribs, routers = triangle(loop, fabric, backend)
        loop.advance(90)  # converge

        inj = FaultInjector(
            FaultPlan(seed=11, dispatch_fail={"spf.dispatch": 3})
        )
        if chaos:
            # Kill the protocol actor: the pill crashes r1 inside its
            # handler; supervision restarts it after ~1s backoff with
            # the in-flight mail held and redelivered.
            inj.kill_actor(loop, "r1")
            loop.run_until_idle()
            assert "r1" in loop._crashed
        loop.advance(5)
        if chaos:
            assert "r1" not in loop._crashed
            assert sup.restarts["r1"] > 0, "restart counter must move"

        # Three LSDB changes -> three r1 SPF runs, each a forced TPU
        # dispatch failure served bit-identically by the scalar oracle.
        with inject(inj) if chaos else nullcontext():
            for third_octet in (110, 111, 112):
                routers["r3"].interface_address_add(
                    "e0", N(f"192.168.{third_octet}.0/24")
                )
                loop.advance(15)
            if chaos:
                assert breaker.state == "open", (
                    f"3 consecutive failures must open the circuit "
                    f"(spf runs: {routers['r1'].spf_run_count})"
                )
            # While OPEN the device is not attempted (the forced-failure
            # budget is exhausted — any attempt now would SUCCEED and
            # close the circuit early, so staying open proves the
            # short-circuit).
            routers["r3"].interface_address_add("e0", N("192.168.113.0/24"))
            loop.advance(15)
            if chaos:
                assert breaker.state == "open"
            # Recovery: past the timeout the next SPF run is the
            # half-open probe; the device is healthy again (injector
            # still armed, budget spent) so service restores.
            loop.advance(31)
            routers["r3"].interface_address_add("e0", N("192.168.114.0/24"))
            loop.advance(15)
        if chaos:
            assert breaker.state == "closed", "half-open probe must restore"
            assert inj.injected["spf.dispatch"] == 3
        loop.advance(30)  # settle
        return kernels, routers

    chaos_kernels, chaos_routers = scenario(chaos=True)
    clean_kernels, clean_routers = scenario(chaos=False)

    # The chaos run converged at all...
    fib = chaos_kernels["r1"].fib
    assert DEST in fib and fib[DEST][1] == Protocol.OSPFV2
    assert N("192.168.114.0/24") in fib
    # ...and every router's final FIB is bit-identical to the clean
    # scalar-oracle run over the same topology events.
    for name in ("r1", "r2", "r3"):
        assert chaos_kernels[name].fib == clean_kernels[name].fib, name


def _recorded_run(tmp_path, tag: str):
    """One seeded chaos run with the journal on: packet drops, delayed
    ibus deliveries, jittered time, and an actor kill + restart."""
    plan = FaultPlan(
        seed=5,
        drop_prob=0.12,
        publish_delay=0.3,
        publish_delay_prob=1.0,  # ibus traffic is sparse: defer all of it
        timer_jitter=0.4,
    )
    inj = FaultInjector(plan)
    loop = EventLoop(clock=VirtualClock())
    rec = EventRecorder(tmp_path / f"events-{tag}.jsonl")
    instrument(loop, rec)
    fabric = MockFabric(loop)
    inj.wire_fabric(fabric)
    sup = Supervisor(RestartPolicy(base_delay=1.0, jitter=0.2)).install(loop)
    buses, kernels, ribs, routers = triangle(loop, fabric)
    inj.wrap_ibus(buses["r1"])
    with inject(inj):
        inj.jittered_advance(loop, 90, steps=18)
        inj.kill_actor(loop, "r1")
        loop.run_until_idle()
        inj.jittered_advance(loop, 40, steps=8)
    rec.close()
    assert sup.restarts.get("r1", 0) == 1
    assert inj.injected.get("fabric.drop", 0) > 0, "loss must actually fire"
    assert inj.injected.get("ibus.delay", 0) > 0
    # Chaos or not, the network converged.
    assert {str(nh.addr) for nh in kernels["r1"].fib[DEST][0]} == {"10.0.12.2"}
    return [
        (e["actor"], e["time"], json.dumps(e["msg"], sort_keys=True))
        for e in read_entries(tmp_path / f"events-{tag}.jsonl")
    ], dict(inj.injected)


def test_same_fault_plan_seed_identical_event_sequence(tmp_path):
    """The harness's own determinism contract: two runs of one seeded
    plan journal byte-identical (actor, time, message) sequences —
    guarding the chaos machinery itself against nondeterminism."""
    seq_a, injected_a = _recorded_run(tmp_path, "a")
    seq_b, injected_b = _recorded_run(tmp_path, "b")
    assert injected_a == injected_b
    assert len(seq_a) > 100, "the scenario must actually exercise the loop"
    assert seq_a == seq_b


def test_breaker_open_postmortem_bundle_deterministic(tmp_path):
    """ISSUE 5 chaos satellite: a forced spf.dispatch breaker-open under
    a seeded FaultPlan produces EXACTLY ONE postmortem bundle whose
    journal-seq tail matches the event recorder — and the bundle is
    byte-identical across two runs of the same seed (modulo dump path):
    spans ride the virtual clock, ids renumber, metric deltas are
    per-run counts."""
    import gc
    import time as _time

    from holo_tpu import telemetry
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.telemetry import flight

    def run(tag: str) -> str:
        from ipaddress import IPv4Network as NN

        gc.collect()  # free the previous run's breaker weakrefs
        # Determinism isolation: eviction counts depend on how full the
        # process-wide marshal cache is when the run starts (ISSUE 7
        # makes entries long-lived), so each arm starts empty.
        from holo_tpu.ops.spf_engine import shared_graph_cache

        shared_graph_cache().clear()
        loop = EventLoop(clock=VirtualClock())
        telemetry.tracer().use_clock(loop.clock.now)
        dump_dir = tmp_path / tag
        flight.configure(
            entries=1024, postmortem_dir=dump_dir, clock=loop.clock.now
        )
        rec = EventRecorder(tmp_path / f"pm-{tag}.jsonl")
        instrument(loop, rec)
        fabric = MockFabric(loop)
        breaker = CircuitBreaker(
            "spf-postmortem",
            failure_threshold=3,
            recovery_timeout=1e9,  # stay open through the settle window
            clock=loop.clock.now,
        )
        backend = TpuSpfBackend(64, breaker=breaker)
        buses, kernels, ribs, routers = triangle(loop, fabric, backend)
        loop.advance(90)  # converge
        inj = FaultInjector(
            FaultPlan(seed=7, dispatch_fail={"spf.dispatch": 3})
        )
        with inject(inj):
            for third_octet in (120, 121, 122):
                routers["r3"].interface_address_add(
                    "e0", NN(f"192.168.{third_octet}.0/24")
                )
                loop.advance(15)
        assert breaker.state == "open"
        assert inj.injected["spf.dispatch"] == 3
        rec.close()
        flight.configure(entries=0)

        bundles = sorted(dump_dir.glob("postmortem-*.json"))
        assert len(bundles) == 1, [b.name for b in bundles]
        bundle = json.loads(bundles[0].read_text())
        assert bundle["reason"] == "breaker-open:spf-postmortem"
        # The journal-seq tail joins the bundle to the journal file:
        # every [seq, actor] marker must match the recorded entry.
        entries = read_entries(tmp_path / f"pm-{tag}.jsonl")
        tail = bundle["journal-tail"]
        assert tail, "the ring must carry journal markers"
        for seq, actor in tail:
            assert entries[seq]["seq"] == seq
            assert entries[seq]["actor"] == actor
        # The breaker-open event and the open-state health verdict made
        # it into the bundle.
        events = [e for e in bundle["ring"] if e[0] == "event"]
        assert any(
            e[1] == "breaker" and e[2]["to"] == "open" for e in events
        )
        assert (
            bundle["health"]["breakers"]["spf-postmortem"]["state"] == "open"
        )
        assert bundle["metrics"][
            "holo_resilience_breaker_failures_total"
            "{breaker=spf-postmortem,cause=exception}"
        ] == 3
        return bundles[0].read_text()

    try:
        text_a = run("a")
        text_b = run("b")
    finally:
        flight.configure(entries=0)
        telemetry.tracer().use_clock(_time.monotonic)
    assert text_a == text_b, "seeded chaos bundle must be byte-identical"


def test_breaker_open_mid_storm_tags_fallback_latencies():
    """ISSUE 6 chaos satellite: when the dispatch breaker opens in the
    middle of a convergence storm, the events served by the scalar
    fallback close under phase="fallback" — the storm report splits
    them out from the batched-device distribution."""
    from holo_tpu.resilience import faults
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.spf.synth_storm import StormNet
    from holo_tpu.telemetry import convergence

    net = StormNet(n_routers=60, seed=21, spf_backend=None)
    breaker = CircuitBreaker(
        "spf-storm",
        failure_threshold=2,
        recovery_timeout=1e9,  # stays open through the storm tail
        clock=net.loop.clock.now,
    )
    net.inst.backend = TpuSpfBackend(64, breaker=breaker)
    tracker = convergence.configure(1024, clock=net.loop.clock.now)
    try:
        plan = FaultPlan(seed=21, dispatch_fail={"spf.dispatch": 2})
        with inject(FaultInjector(plan)):
            for i in range(8):
                net.flap(net.flappable[i], lost=False)
                net.loop.advance(12.0)
        net.loop.advance(60.0)
        tracker.sweep()
        assert breaker.state == "open"
        recs = [
            r for r in tracker.timelines() if r["outcome"] == "converged"
        ]
        fallbacks = [r for r in recs if r["fallback"]]
        assert fallbacks, "breaker fallback must tag convergence events"
        assert all(
            any(step == "fallback" for step, _t, _a in r["timeline"])
            for r in fallbacks
        )
        # The histogram split the storm bench reports on.
        hist = telemetry_registry_hist()
        assert hist.labels(trigger="lsa", phase="fallback").count > 0
    finally:
        convergence.configure(0)


def telemetry_registry_hist():
    from holo_tpu import telemetry

    return telemetry.registry().histogram(
        "holo_convergence_seconds", labelnames=("trigger", "phase")
    )


def test_convergence_storm_survives_pump_thread_kill():
    """ISSUE 6 satellite: a ThreadedLoop pump crash mid-run is detected
    AND respawned under the RestartPolicy (the detected-but-not-
    respawned gap), and the storm network hosted on that loop keeps
    converging afterwards."""
    import time as _time

    from holo_tpu.spf.synth_storm import StormNet
    from holo_tpu.utils.preempt import ThreadedLoop
    from holo_tpu.utils.runtime import RealClock

    home = EventLoop(clock=RealClock())
    sup = Supervisor(RestartPolicy(base_delay=0.05, jitter=0.0)).install(home)
    tl = ThreadedLoop(name="storm-host")
    net = StormNet(n_routers=40, seed=9, loop=tl)
    sup.adopt(tl.loop, sender=tl.send)
    pump_name = sup.watch_pump(tl)
    tl.start()

    def settle(pred, timeout=10.0) -> bool:
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            home.run_until_idle()
            if pred():
                return True
            _time.sleep(0.02)
        return False

    # Initial convergence on the pump thread (real clock).
    assert settle(lambda: len(net.kernel.fib) > 0), "no initial FIB"
    fib0 = dict(net.kernel.fib)

    inj = FaultInjector(FaultPlan(seed=9))
    inj.kill_pump(tl)
    assert settle(lambda: not tl.pump_alive(), 5.0), "pump must die"
    assert tl.pump_crashes == 1
    # Supervision: CrashNotice marshals home, backoff fires, respawn.
    assert settle(lambda: tl.pump_alive(), 10.0), "pump must respawn"
    assert sup.restarts.get(pump_name, 0) == 1

    # The storm keeps converging on the respawned pump: flap an edge
    # whose endpoint owns a stub prefix and watch the FIB move.
    runs0 = net.inst.spf_run_count
    net.flap(net.flappable[0], lost=False)
    assert settle(lambda: net.inst.spf_run_count > runs0), (
        "post-respawn SPF must run"
    )
    assert len(net.kernel.fib) > 0, f"FIB lost after respawn (was {fib0})"
    tl.stop()


def test_delta_chain_breaker_open_falls_back_bit_identical():
    """ISSUE 7 chaos acceptance (1/3): forced dispatch failures open
    the breaker in the middle of a DeltaPath storm — every event from
    then on is served by the scalar fallback, and the final FIB is
    bit-identical to an all-scalar control run of the same seeded
    events.  Runs under jax.transfer_guard('disallow')."""
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.spf.synth_storm import StormNet
    from holo_tpu.testing import no_implicit_transfers

    def run(backend):
        net = StormNet(n_routers=60, seed=27, spf_backend=backend)
        for i in range(8):
            net.flap(net.flappable[i], lost=False)
            net.loop.advance(12.0)
        net.ifconfig_metric()
        net.loop.advance(40.0)
        return dict(net.kernel.fib)

    with no_implicit_transfers():
        breaker = CircuitBreaker(
            "spf-delta-breaker",
            failure_threshold=2,
            recovery_timeout=1e9,  # stays open through the tail
        )
        be = TpuSpfBackend(64, breaker=breaker)
        plan = FaultPlan(seed=27, dispatch_fail={"spf.dispatch": 2})
        with inject(FaultInjector(plan)) as inj:
            chaos_fib = run(be)
        assert inj.injected["spf.dispatch"] == 2
        assert breaker.state == "open"
        control_fib = run(None)  # scalar oracle end to end
    assert chaos_fib == control_fib


def test_delta_chain_depth_cap_full_rebuild_identical_digests():
    """ISSUE 7 chaos acceptance (2/3): a depth-capped delta chain keeps
    falling back to the full-rebuild device path mid-storm — causal
    timelines AND FIB digests stay byte-identical to the uncapped
    incremental run.  Runs under jax.transfer_guard('disallow')."""
    from holo_tpu import telemetry
    from holo_tpu.ops.spf_engine import shared_graph_cache
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.spf.synth_storm import run_convergence_storm
    from holo_tpu.testing import no_implicit_transfers

    def storm():
        _report, digest, net = run_convergence_storm(
            n_routers=60, events=24, seed=29,
            spf_backend=TpuSpfBackend(64),
        )
        return digest, dict(net.kernel.fib)

    cache = shared_graph_cache()
    old_depth = cache.max_delta_depth
    with no_implicit_transfers():
        digest_inc, fib_inc = storm()
        cache.max_delta_depth = 1
        before = telemetry.snapshot(prefix="holo_spf_delta")
        try:
            digest_capped, fib_capped = storm()
        finally:
            cache.max_delta_depth = old_depth
        after = telemetry.snapshot(prefix="holo_spf_delta")
    fellback = sum(
        v for k, v in after.items() if "path=full-depth" in k
    ) - sum(v for k, v in before.items() if "path=full-depth" in k)
    assert fellback > 0, "the cap must actually force full rebuilds"
    assert digest_capped == digest_inc, "causal timelines must not move"
    assert fib_capped == fib_inc


def test_delta_padding_overflow_full_rebuild_identical():
    """ISSUE 7 chaos acceptance (3/3): a delta overflowing the ELL
    padding slack is refused in place and served by the full-rebuild
    path with bit-identical results, under the transfer guard."""
    import numpy as np

    from holo_tpu import telemetry
    from holo_tpu.ops.graph import Topology, diff_topologies
    from holo_tpu.spf.backend import ScalarSpfBackend, TpuSpfBackend
    from holo_tpu.spf.synth import random_ospf_topology
    from holo_tpu.testing import no_implicit_transfers

    with no_implicit_transfers():
        topo = random_ospf_topology(n_routers=12, n_networks=3, seed=8)
        be = TpuSpfBackend(64)
        be.compute(topo)
        v = int(topo.edge_dst[0])
        k_pad = 8 * (
            1
            + int(np.bincount(topo.edge_dst, minlength=topo.n_vertices).max())
            // 8
        )
        extra = [
            [(v + 1 + i) % topo.n_vertices, v, 5, -1]
            for i in range(k_pad + 2)
        ]
        nxt = Topology(
            n_vertices=topo.n_vertices,
            is_router=topo.is_router.copy(),
            edge_src=np.concatenate(
                [topo.edge_src, np.asarray([e[0] for e in extra], np.int32)]
            ),
            edge_dst=np.concatenate(
                [topo.edge_dst, np.asarray([e[1] for e in extra], np.int32)]
            ),
            edge_cost=np.concatenate(
                [topo.edge_cost, np.asarray([e[2] for e in extra], np.int32)]
            ),
            edge_direct_atom=np.concatenate(
                [
                    topo.edge_direct_atom,
                    np.asarray([e[3] for e in extra], np.int32),
                ]
            ),
            root=topo.root,
        )
        delta = diff_topologies(topo, nxt, max_ops=4 * k_pad + 64)
        assert delta is not None
        nxt.link_delta(delta)
        before = telemetry.snapshot(prefix="holo_spf_delta")
        got = be.compute(nxt)
        ref = ScalarSpfBackend(64).compute(nxt)
        after = telemetry.snapshot(prefix="holo_spf_delta")
    overflowed = sum(
        v for k, v in after.items() if "full-padding-overflow" in k
    ) - sum(v for k, v in before.items() if "full-padding-overflow" in k)
    assert overflowed > 0, "the overflow fallback must actually fire"
    for f in ("dist", "parent", "hops", "nexthop_words"):
        np.testing.assert_array_equal(
            getattr(ref, f), getattr(got, f), err_msg=f
        )


def test_shard_dispatch_failure_mid_storm_falls_back_bit_identical():
    """ISSUE 8 chaos satellite: with the process mesh installed (the
    real multi-chip dispatch path), forced shard-dispatch failures
    mid-storm open the breaker — every event from then on is served by
    the scalar oracle, tagged phase="fallback" on its convergence
    timeline, and the final FIB is bit-identical to an all-scalar
    control run of the same seeded events."""
    from holo_tpu.parallel.mesh import (
        configure_process_mesh,
        reset_process_mesh,
    )
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.spf.synth_storm import StormNet
    from holo_tpu.telemetry import convergence

    def run(backend, with_tracker=False):
        net = StormNet(n_routers=60, seed=31, spf_backend=backend)
        tracker = (
            convergence.configure(1024, clock=net.loop.clock.now)
            if with_tracker
            else None
        )
        for i in range(8):
            net.flap(net.flappable[i], lost=False)
            net.loop.advance(12.0)
        net.loop.advance(60.0)
        if tracker is not None:
            tracker.sweep()
        return dict(net.kernel.fib), tracker

    configure_process_mesh(4, 2)
    try:
        breaker = CircuitBreaker(
            "spf-shard-storm",
            failure_threshold=2,
            recovery_timeout=1e9,  # stays open through the storm tail
        )
        plan = FaultPlan(seed=31, dispatch_fail={"spf.shard": 2})
        with inject(FaultInjector(plan)) as inj:
            chaos_fib, tracker = run(
                TpuSpfBackend(64, breaker=breaker), with_tracker=True
            )
        assert inj.injected["spf.shard"] == 2
        assert breaker.state == "open"
        fallbacks = [
            r
            for r in tracker.timelines()
            if r["outcome"] == "converged" and r["fallback"]
        ]
        assert fallbacks, "shard failures must tag convergence events"
        assert all(
            any(step == "fallback" for step, _t, _a in r["timeline"])
            for r in fallbacks
        )
    finally:
        convergence.configure(0)
        reset_process_mesh()
    control_fib, _ = run(None)  # scalar oracle end to end
    assert chaos_fib == control_fib


def test_ospf_reconverges_through_packet_loss():
    """Convergence-under-failure, the metric that matters: with a lossy
    wire AND a link failure mid-run, retransmission machinery still
    reconverges every router onto the surviving path."""
    plan = FaultPlan(seed=9, drop_prob=0.10, timer_jitter=0.3)
    inj = FaultInjector(plan)
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    inj.wire_fabric(fabric)
    buses, kernels, ribs, routers = triangle(loop, fabric)
    inj.jittered_advance(loop, 150, steps=15)
    assert {str(nh.addr) for nh in kernels["r1"].fib[DEST][0]} == {"10.0.12.2"}
    # The r1--r2 link dies under continuing loss: r1 must end on r3.
    fabric.set_link_up("l12", False)
    inj.jittered_advance(loop, 120, steps=12)
    assert {str(nh.addr) for nh in kernels["r1"].fib[DEST][0]} == {"10.0.13.2"}

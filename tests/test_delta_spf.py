"""DeltaPath incremental SPF (ISSUE 7): property and fallback gates.

The contract: ANY delta chain served through the device-resident graph
(``DeviceGraphCache.apply_delta`` path + the seeded incremental kernel)
yields distances / parents / hops / ECMP next-hop words bit-identical
to a from-scratch marshal + full SPF of the final topology — checked
against both the full-rebuild device path and the scalar oracle.  Every
fallback trigger (chain depth, padding slack, atom width, mask
consumers needing edge ids, missing base) must land on the full-rebuild
path with the same bits.  Everything runs under the transfer-guard
sanitizer: the delta path may only move data inside its sanctioned
windows.
"""

import numpy as np
import pytest

from holo_tpu import telemetry
from holo_tpu.ops.graph import TopologyDelta, diff_topologies
from holo_tpu.ops.spf_engine import shared_graph_cache
from holo_tpu.spf.backend import ScalarSpfBackend, TpuSpfBackend
from holo_tpu.spf.synth import (
    clone_topology as clone,
    random_ospf_topology,
    whatif_link_failure_masks,
)
from holo_tpu.testing import no_implicit_transfers

N_ATOMS = 64


@pytest.fixture(autouse=True)
def _transfer_sanitizer():
    """The whole suite runs under jax.transfer_guard('disallow'): the
    delta path's scatter/seed transfers must stay inside the sanctioned
    spf.one.delta window."""
    with no_implicit_transfers():
        yield


def random_mutation(topo, rng):
    """One random storm-shaped event: metric change, link flap (both
    directions of one edge), or a fresh bidirectional edge."""
    roll = rng.random()
    if roll < 0.4 and topo.n_edges:
        e = int(rng.integers(0, topo.n_edges))
        return clone(topo, cost={e: int(rng.integers(1, 64))})
    if roll < 0.8 and topo.n_edges:
        e = int(rng.integers(0, topo.n_edges))
        s, d = int(topo.edge_src[e]), int(topo.edge_dst[e])
        keep = ~(
            ((topo.edge_src == s) & (topo.edge_dst == d))
            | ((topo.edge_src == d) & (topo.edge_dst == s))
        )
        return clone(topo, keep=keep)
    a = int(rng.integers(0, topo.n_vertices))
    b = int(rng.integers(0, topo.n_vertices))
    w = int(rng.integers(1, 32))
    return clone(topo, extra=[[a, b, w, -1], [b, a, w, -1]])


def assert_results_equal(ref, got, ctx=""):
    for f in ("dist", "parent", "hops", "nexthop_words"):
        np.testing.assert_array_equal(
            getattr(ref, f), getattr(got, f), err_msg=f"{ctx}: {f}"
        )


def delta_snapshot():
    return telemetry.snapshot(prefix="holo_spf_delta")


def count(snap, path):
    return sum(v for k, v in snap.items() if f"path={path}" in k)


@pytest.mark.parametrize("seed", range(5))
def test_random_delta_chain_bit_identical(seed):
    """THE property: a random delta chain applied via apply_delta +
    the seeded incremental kernel == from-scratch marshal + full SPF of
    the final topology, at every step, against the device full-rebuild
    path AND the scalar oracle."""
    rng = np.random.default_rng(seed)
    topo = random_ospf_topology(
        n_routers=24, n_networks=6, extra_p2p=30, seed=seed
    )
    inc_be = TpuSpfBackend(N_ATOMS)
    full_be = TpuSpfBackend(N_ATOMS, incremental=False)
    oracle = ScalarSpfBackend(N_ATOMS)
    before = delta_snapshot()
    inc_be.compute(topo)
    cur = topo
    for _step in range(10):
        nxt = random_mutation(cur, rng)
        delta = diff_topologies(cur, nxt)
        if delta is not None:
            nxt.link_delta(delta)
        got = inc_be.compute(nxt)
        fresh = full_be.compute(clone(nxt))  # distinct identity: no reuse
        ref = oracle.compute(nxt)
        assert_results_equal(ref, got, f"seed {seed} step {_step} inc")
        assert_results_equal(ref, fresh, f"seed {seed} step {_step} full")
        cur = nxt
    after = delta_snapshot()
    assert count(after, "incremental") > count(before, "incremental"), (
        "the chain must actually exercise the incremental path"
    )


def test_too_deep_delta_chain_falls_back_full_rebuild():
    cache = shared_graph_cache()
    old_depth = cache.max_delta_depth
    cache.max_delta_depth = 2
    try:
        rng = np.random.default_rng(9)
        topo = random_ospf_topology(n_routers=16, n_networks=4, seed=9)
        be = TpuSpfBackend(N_ATOMS)
        oracle = ScalarSpfBackend(N_ATOMS)
        before = delta_snapshot()
        be.compute(topo)
        cur = topo
        for _ in range(6):
            nxt = random_mutation(cur, rng)
            delta = diff_topologies(cur, nxt)
            if delta is not None:
                nxt.link_delta(delta)
            assert_results_equal(oracle.compute(nxt), be.compute(nxt))
            cur = nxt
        after = delta_snapshot()
        depth_falls = count(after, "full-depth") - count(before, "full-depth")
        assert depth_falls > 0, (
            "depth-capped chains must take the full-rebuild path"
        )
        # Accounting regression: a dispatch the cache full-rebuilt must
        # NOT also claim path="incremental" — the label means the
        # in-place-updated resident served it.
        inc_served = count(after, "incremental") - count(before, "incremental")
        assert inc_served + depth_falls <= 6
    finally:
        cache.max_delta_depth = old_depth


def test_padding_overflow_falls_back_full_rebuild():
    """Additions beyond the destination row's ELL padding slack cannot
    be absorbed in place: the delta is refused and the full rebuild
    (with a wider K bucket) serves the same bits."""
    topo = random_ospf_topology(n_routers=14, n_networks=3, seed=4)
    be = TpuSpfBackend(N_ATOMS)
    oracle = ScalarSpfBackend(N_ATOMS)
    be.compute(topo)
    # Flood one vertex with more new in-edges than any padded row holds.
    k_pad = 8 * (
        1 + int(np.bincount(topo.edge_dst, minlength=topo.n_vertices).max())
        // 8
    )
    v = int(topo.edge_dst[0])
    extra = []
    for i in range(k_pad + 4):
        peer = (v + 1 + i) % topo.n_vertices
        extra.append([peer, v, 7, -1])
    nxt = clone(topo, extra=extra)
    delta = diff_topologies(topo, nxt, max_ops=4 * k_pad + 64)
    assert delta is not None
    nxt.link_delta(delta)
    before = delta_snapshot()
    assert_results_equal(oracle.compute(nxt), be.compute(nxt))
    after = delta_snapshot()
    assert count(after, "full-padding-overflow") > count(
        before, "full-padding-overflow"
    )


def test_overload_strike_delta():
    """The node-overload delta kind: transit through the struck vertex
    dies in place (slots masked through in_src), destinations stay
    reachable — equal to a topology without the vertex's out-edges."""
    topo = random_ospf_topology(n_routers=18, n_networks=4, seed=6)
    be = TpuSpfBackend(N_ATOMS)
    oracle = ScalarSpfBackend(N_ATOMS)
    be.compute(topo)
    # Strike a non-root transit vertex.
    v = next(
        int(u) for u in np.unique(topo.edge_src) if int(u) != topo.root
    )
    nxt = clone(topo, keep=topo.edge_src != v)
    nxt.link_delta(
        TopologyDelta(
            base_key=topo.cache_key,
            overload=np.asarray([v], np.int32),
            ids_stable=False,
        )
    )
    before = delta_snapshot()
    assert_results_equal(oracle.compute(nxt), be.compute(nxt))
    after = delta_snapshot()
    assert count(after, "incremental") > count(before, "incremental")


def test_empty_delta_reuses_resident_graph_without_marshal():
    """A content-identical rebuild (LSA refresh with no topology change)
    produces an empty delta: the resident graph is aliased under the
    new key with zero marshal work."""
    topo = random_ospf_topology(n_routers=12, n_networks=2, seed=2)
    be = TpuSpfBackend(N_ATOMS)
    be.compute(topo)
    nxt = clone(topo)
    delta = diff_topologies(topo, nxt)
    assert delta is not None and delta.kind == "empty" and delta.ids_stable
    nxt.link_delta(delta)
    marshals0 = telemetry.snapshot(prefix="holo_spf_marshal_total")
    res = be.compute(nxt)
    marshals1 = telemetry.snapshot(prefix="holo_spf_marshal_total")
    assert marshals0 == marshals1, "an empty delta must not re-marshal"
    assert_results_equal(ScalarSpfBackend(N_ATOMS).compute(nxt), res)


def test_whatif_after_structural_delta_rebuilds_edge_ids():
    """Mask consumers gather through in_edge_id: a structurally-updated
    resident entry must be rebuilt for them, bit-identically."""
    topo = random_ospf_topology(n_routers=16, n_networks=4, seed=3)
    be = TpuSpfBackend(N_ATOMS)
    be.compute(topo)
    e = int(np.nonzero(topo.edge_src != topo.root)[0][0])
    s, d = int(topo.edge_src[e]), int(topo.edge_dst[e])
    keep = ~(
        ((topo.edge_src == s) & (topo.edge_dst == d))
        | ((topo.edge_src == d) & (topo.edge_dst == s))
    )
    nxt = clone(topo, keep=keep)
    delta = diff_topologies(topo, nxt)
    assert delta is not None and not delta.ids_stable
    nxt.link_delta(delta)
    be.compute(nxt)  # serve the delta chain (stale edge ids now)
    masks = whatif_link_failure_masks(nxt, n_scenarios=6, seed=3)
    scalar = ScalarSpfBackend(N_ATOMS).compute_whatif(nxt, masks)
    got = be.compute_whatif(nxt, masks)
    for sres, tres in zip(scalar, got):
        assert_results_equal(sres, tres)


def test_masked_compute_after_structural_delta_rebuilds_edge_ids():
    """Regression: compute(topo, edge_mask) gathers the scenario mask
    through in_edge_id, so it must not be served by a structurally
    delta-updated resident (stale edge ids would mask the wrong
    edges, silently)."""
    topo = random_ospf_topology(n_routers=14, n_networks=3, seed=11)
    be = TpuSpfBackend(N_ATOMS)
    be.compute(topo)
    e = int(np.nonzero(topo.edge_src != topo.root)[0][0])
    s, d = int(topo.edge_src[e]), int(topo.edge_dst[e])
    keep = ~(
        ((topo.edge_src == s) & (topo.edge_dst == d))
        | ((topo.edge_src == d) & (topo.edge_dst == s))
    )
    nxt = clone(topo, keep=keep)
    delta = diff_topologies(topo, nxt)
    assert delta is not None and not delta.ids_stable
    nxt.link_delta(delta)
    be.compute(nxt)  # mask-free: rides the delta entry (ids now stale)
    mask = np.ones(nxt.n_edges, bool)
    f = int(np.nonzero(nxt.edge_src != nxt.root)[0][-1])
    fs, fd = int(nxt.edge_src[f]), int(nxt.edge_dst[f])
    mask[
        ((nxt.edge_src == fs) & (nxt.edge_dst == fd))
        | ((nxt.edge_src == fd) & (nxt.edge_dst == fs))
    ] = False
    assert_results_equal(
        ScalarSpfBackend(N_ATOMS).compute(nxt, mask),
        be.compute(nxt, mask),
        "masked compute after struct delta",
    )


def test_frr_engine_rides_weight_delta_chain():
    """FrrEngine chooses incremental vs full rebuild: a pure metric
    delta keeps edge ids valid, so the FRR planes ride the in-place
    updated resident graph — backup tables bit-identical to the scalar
    oracle either way."""
    from holo_tpu.frr.manager import FrrEngine
    from holo_tpu.spf.synth import grid_topology

    topo = grid_topology(4, 4, seed=5)
    be = TpuSpfBackend(N_ATOMS)
    eng = FrrEngine("tpu")
    be.compute(topo)
    eng.compute(topo)
    nxt = clone(topo, cost={1: int(topo.edge_cost[1]) + 3})
    delta = diff_topologies(topo, nxt)
    assert delta is not None and delta.ids_stable
    nxt.link_delta(delta)
    be.compute(nxt)  # applies the delta; FRR below must hit the entry
    cache0 = telemetry.snapshot(prefix="holo_spf_marshal_total")
    table = eng.compute(nxt)
    assert telemetry.snapshot(prefix="holo_spf_marshal_total") == cache0, (
        "a weight-delta chain must not force an FRR re-marshal"
    )
    ref = FrrEngine("scalar").compute(nxt)
    for f in (
        "lfa_adj", "lfa_nodeprot", "rlfa_pq", "tilfa_p", "tilfa_q",
        "post_dist", "post_nh",
    ):
        np.testing.assert_array_equal(
            getattr(ref, f), getattr(table, f), err_msg=f
        )


def test_ospfv2_seam_links_deltas_in_storm():
    """LSDB-seam e2e: a real OSPFv2 instance under flap events links
    delta lineage per area and the backend serves it incrementally —
    the FIB matches a scalar-backend control run event for event."""
    from holo_tpu.spf.synth_storm import StormNet

    def run(backend):
        net = StormNet(n_routers=50, seed=13, spf_backend=backend)
        for i in range(6):
            net.flap(net.flappable[i % len(net.flappable)], lost=False)
            net.loop.advance(12.0)
        net.loop.advance(40.0)
        return dict(net.kernel.fib)

    before = delta_snapshot()
    fib_tpu = run(TpuSpfBackend(N_ATOMS))
    after = delta_snapshot()
    assert count(after, "incremental") > count(before, "incremental"), (
        "the protocol seam must link servable deltas"
    )
    fib_scalar = run(None)
    assert fib_tpu == fib_scalar


def test_cache_stats_on_gnmi_leaf():
    """Satellite: eviction/occupancy stats ride the holo-telemetry
    subtree next to the hit/miss counters."""
    from holo_tpu.telemetry.provider import TelemetryStateProvider

    topo = random_ospf_topology(n_routers=10, n_networks=2, seed=1)
    TpuSpfBackend(N_ATOMS).compute(topo)
    state = TelemetryStateProvider().get_state()
    leaf = state["holo-telemetry"]["spf-graph-cache"]
    for key in (
        "entries", "capacity", "evictions", "deltas-applied",
        "delta-entries", "max-chain-depth", "occupancy",
    ):
        assert key in leaf, key
    assert leaf["entries"] >= 1
    assert 0.0 < leaf["occupancy"] <= 1.0


# -- donation guard: the runtime half of HL109 (ISSUE 14) ---------------


def test_donation_guard_poisons_and_asserts():
    """Unit contract: disarmed note_donated is a no-op; armed, it
    deletes the donated handles, and assert_live converts a later read
    into a named DonatedBufferError at the force boundary."""
    import jax.numpy as jnp

    from holo_tpu.analysis import runtime as art
    from holo_tpu.testing import donation_guarded

    arr = jnp.arange(4)
    art.note_donated("fixture.disarmed", arr)
    assert not arr.is_deleted()
    art.assert_live("fixture.disarmed", arr)  # disarmed: no-op too
    with donation_guarded():
        arr2 = jnp.arange(8)
        art.note_donated("fixture.armed", (arr2, None))
        assert arr2.is_deleted()
        with pytest.raises(art.DonatedBufferError, match="fixture.read"):
            art.assert_live("fixture.read", arr2)
    assert art.donated_counts().get("fixture.armed", 0) >= 1


def test_donation_guard_catches_retained_prev_alias():
    """The runtime arm of the ISSUE-14 mutation proof: a reference
    that illegally outlives the DeltaPath donation (exactly the HL109
    retention bug) is poisoned by the dispatch seam, so reading it at
    test time raises instead of silently passing on the CPU platform
    (which ignores donation and would have returned stale bytes)."""
    from holo_tpu.analysis import runtime as art
    from holo_tpu.testing import donation_guarded

    with donation_guarded():
        topo = random_ospf_topology(n_routers=16, n_networks=4, seed=3)
        be = TpuSpfBackend(N_ATOMS)
        be.compute(topo)
        # The seeded bug: an alias of the retained prev tensors that
        # the next delta dispatch will donate out from under us.
        stale = next(iter(be._prev_one.values()))
        before = art.donated_counts().get("spf.one.delta", 0)
        nxt = clone(topo, cost={0: 7})
        delta = diff_topologies(topo, nxt)
        assert delta is not None
        nxt.link_delta(delta)
        be.compute(nxt)
        assert art.donated_counts().get("spf.one.delta", 0) > before, (
            "delta dispatch did not ride the incremental (donating) path"
        )
        with pytest.raises(art.DonatedBufferError):
            art.assert_live("test.readback", stale)


def test_delta_chain_parity_under_donation_guard():
    """One parity arm under the armed guard (composed with the
    transfer sanitizer via the suite's autouse fixture): poisoning
    every donated seed must not disturb bit-identity — the production
    path never reads what it donated — and both halves of the shared
    seam vocabulary must actually run."""
    from holo_tpu.analysis import runtime as art
    from holo_tpu.testing import donation_guarded

    with donation_guarded():
        rng = np.random.default_rng(11)
        topo = random_ospf_topology(
            n_routers=20, n_networks=5, extra_p2p=20, seed=11
        )
        be = TpuSpfBackend(N_ATOMS)
        oracle = ScalarSpfBackend(N_ATOMS)
        be.compute(topo)
        cur = topo
        for _step in range(6):
            nxt = random_mutation(cur, rng)
            delta = diff_topologies(cur, nxt)
            if delta is not None:
                nxt.link_delta(delta)
            assert_results_equal(
                oracle.compute(nxt), be.compute(nxt), f"step {_step}"
            )
            cur = nxt
        assert art.donated_counts().get("spf.one.delta", 0) > 0
        assert art.consumed_counts().get("spf.prev.redeposit", 0) > 0

"""RIB manager: admin distance, reselection, redistribution, OSPF wiring."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.routing.rib import MockKernel, RibManager
from holo_tpu.utils.ibus import TOPIC_REDISTRIBUTE_ADD, Ibus
from holo_tpu.utils.runtime import EventLoop, VirtualClock
from holo_tpu.utils.southbound import Nexthop, Protocol, RouteKeyMsg, RouteMsg


def mk():
    loop = EventLoop(clock=VirtualClock())
    ibus = Ibus(loop)
    kernel = MockKernel()
    rib = RibManager(ibus, kernel)
    loop.register(rib)
    return loop, ibus, kernel, rib


def test_admin_distance_selection_and_fallback():
    loop, ibus, kernel, rib = mk()
    p = N("10.1.0.0/16")
    nh_ospf = frozenset({Nexthop(addr=A("10.0.0.2"), ifname="e0")})
    nh_rip = frozenset({Nexthop(addr=A("10.0.0.3"), ifname="e1")})
    rib.route_add(RouteMsg(Protocol.RIPV2, p, 120, 4, nh_rip))
    assert kernel.fib[p][1] == Protocol.RIPV2
    rib.route_add(RouteMsg(Protocol.OSPFV2, p, 110, 20, nh_ospf))
    assert kernel.fib[p][1] == Protocol.OSPFV2  # lower distance wins
    rib.route_del(RouteKeyMsg(Protocol.OSPFV2, p))
    assert kernel.fib[p][1] == Protocol.RIPV2  # falls back
    rib.route_del(RouteKeyMsg(Protocol.RIPV2, p))
    assert p not in kernel.fib


def test_redistribution_published():
    loop, ibus, kernel, rib = mk()
    got = []

    class Sub:
        name = "bgp"

        def attach(self, l):
            pass

        def handle(self, msg):
            got.append(msg.payload)

        def on_stop(self):
            pass

    loop.register(Sub())
    ibus.subscribe(TOPIC_REDISTRIBUTE_ADD, "bgp")
    rib.route_add(RouteMsg(Protocol.OSPFV2, N("10.2.0.0/16"), 110, 5,
                           frozenset({Nexthop(addr=A("10.0.0.2"))})))
    loop.run_until_idle()
    assert len(got) == 1 and got[0].prefix == N("10.2.0.0/16")


def test_ospf_instances_program_rib():
    """Full wiring: OSPF converges and programs per-router RIB/kernels."""
    from ipaddress import IPv4Address, IPv4Network

    from holo_tpu.protocols.ospf.instance import (
        IfConfig, IfUpMsg, InstanceConfig, OspfInstance,
    )
    from holo_tpu.protocols.ospf.interface import IfType
    from holo_tpu.utils.netio import MockFabric

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    ibus = {}
    kernels = {}
    routers = {}
    for name, rid in [("r1", "1.1.1.1"), ("r2", "2.2.2.2"), ("r3", "3.3.3.3")]:
        # Each router gets its own loop-scoped bus/rib under unique names.
        bus = Ibus(loop)
        k = MockKernel()
        rib = RibManager(bus, k)
        rib.name = "routing" if name == "r1" else f"routing-{name}"
        loop.register(rib)
        inst = OspfInstance(
            name=name,
            config=InstanceConfig(router_id=IPv4Address(rid)),
            netio=fabric.sender_for(name),
        )
        loop.register(inst)
        inst.attach_ibus(bus, routing_actor=rib.name)
        ibus[name] = bus
        kernels[name] = k
        routers[name] = inst

    cfg = lambda c: IfConfig(if_type=IfType.POINT_TO_POINT, cost=c)
    r1, r2, r3 = routers["r1"], routers["r2"], routers["r3"]
    r1.add_interface("e0", cfg(10), IPv4Network("10.0.12.0/30"), IPv4Address("10.0.12.1"))
    r2.add_interface("e0", cfg(10), IPv4Network("10.0.12.0/30"), IPv4Address("10.0.12.2"))
    r2.add_interface("e1", cfg(5), IPv4Network("10.0.23.0/30"), IPv4Address("10.0.23.1"))
    r3.add_interface("e0", cfg(5), IPv4Network("10.0.23.0/30"), IPv4Address("10.0.23.2"))
    fabric.join("l12", "r1", "e0", IPv4Address("10.0.12.1"))
    fabric.join("l12", "r2", "e0", IPv4Address("10.0.12.2"))
    fabric.join("l23", "r2", "e1", IPv4Address("10.0.23.1"))
    fabric.join("l23", "r3", "e0", IPv4Address("10.0.23.2"))
    for r in routers.values():
        for area in r.areas.values():
            for ifname in area.interfaces:
                loop.send(r.name, IfUpMsg(ifname))
    loop.advance(90)

    # r1's kernel has the remote prefix via 10.0.12.2.
    fib = kernels["r1"].fib
    assert N("10.0.23.0/30") in fib
    nhs, proto = fib[N("10.0.23.0/30")]
    assert proto == Protocol.OSPFV2
    assert {str(nh.addr) for nh in nhs} == {"10.0.12.2"}
    # Local/connected prefixes are not programmed (empty next hops).
    assert N("10.0.12.0/30") not in fib

"""The holo-lint tier-1 gate: the live tree must match the baseline.

This is the in-pytest arm of the ratchet (the CLI arm is
``holo-tpu-tools lint --baseline holo_tpu/analysis/baseline.json`` in
the ROADMAP verify chain): any NEW finding fails tier-1, and a STALE
baseline entry (its finding was fixed) also fails — the baseline only
ever shrinks.
"""

from pathlib import Path

from holo_tpu.analysis import (
    all_rules,
    audit_suppressions,
    compare_to_baseline,
    default_baseline_path,
    gate_findings,
    load_baseline,
    run_paths_cached,
    self_check,
)

REPO = Path(__file__).resolve().parent.parent


def test_repo_matches_baseline():
    # Rides the incremental cache: on an unchanged tree (the verify
    # chain runs the linter twice) this replays the CLI arm's scan;
    # test_cache_replay_matches_cold_scan below proves the replay
    # faithful every run.
    result = run_paths_cached([REPO / "holo_tpu"], root=REPO)
    assert not result.parse_errors, result.parse_errors
    assert result.files_checked > 60  # the whole package, not a subset

    baseline = load_baseline(default_baseline_path())
    new, unused = compare_to_baseline(result.findings, baseline)
    # The gate rides error-tier rules only (warn-tier findings report
    # without failing tier-1 — the CLI arm applies the same split).
    new_errors = gate_findings(new)
    assert not new_errors, (
        "new holo-lint findings (fix or baseline them):\n"
        + "\n".join(f.render() for f in new_errors)
    )
    assert not unused, (
        "stale baseline entries (their findings were fixed) — ratchet by "
        "removing them from holo_tpu/analysis/baseline.json:\n"
        + "\n".join(sorted(unused))
    )


def test_cache_replay_matches_cold_scan():
    """Self-check mode: the cached replay must be byte-identical to a
    cold scan of the live tree.  A cache bug (stale replay, bad
    invalidation) fails tier-1 HERE, loudly, instead of silently
    passing a stale verdict through the gate above."""
    mismatches = self_check([REPO / "holo_tpu"], root=REPO)
    assert not mismatches, (
        "lint cache replay diverged from a cold scan (delete "
        ".holo_lint_cache.json and report this):\n"
        + "\n".join(mismatches)
    )


def test_no_stale_suppressions():
    """Every `# holo-lint: disable=` comment in the live tree still
    silences a finding on its line — dead disable comments rot the
    audit trail and must be deleted (the CLI arm enforces the same
    via --check-suppressions in tools/lint.sh)."""
    result = run_paths_cached([REPO / "holo_tpu"], root=REPO)
    stale = audit_suppressions(result)
    assert not stale, "stale suppressions:\n" + "\n".join(stale)


def test_every_suppression_carries_a_rule_id():
    # `disable=all` is for fixtures/docs, not the live tree: every
    # in-tree suppression must name the rule it silences.
    import re

    pat = re.compile(r"holo-lint:\s*disable=([A-Za-z0-9_,\s-]+)")
    offenders = []
    for path in sorted((REPO / "holo_tpu").rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        for i, line in enumerate(path.read_text().splitlines(), start=1):
            m = pat.search(line)
            if m and "all" in {s.strip() for s in m.group(1).split(",")}:
                offenders.append(f"{path}:{i}")
    assert not offenders, offenders


def test_rule_catalog_documented():
    # COMPONENTS.md documents every rule id the analyzer ships.
    text = (REPO / "COMPONENTS.md").read_text()
    missing = [r.id for r in all_rules() if r.id not in text]
    assert not missing, f"rules undocumented in COMPONENTS.md: {missing}"


def test_cli_gate_exits_clean_and_second_run_rides_the_cache():
    """The ISSUE-14 acceptance shape: the gate exits 0 (suppression
    audit included), and a second run on the unchanged tree reports
    >=90% modules cached with findings byte-identical to the first."""
    import json as _json
    import subprocess
    import sys

    def run_gate(*extra):
        return subprocess.run(
            [
                sys.executable,
                "-m",
                "holo_tpu.tools.cli",
                "lint",
                "--baseline",
                str(default_baseline_path()),
                "--check-suppressions",
                *extra,
            ],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=300,
        )

    first = run_gate("--json")
    assert first.returncode == 0, first.stdout + first.stderr
    second = run_gate("--json")
    assert second.returncode == 0, second.stdout + second.stderr
    a, b = _json.loads(first.stdout), _json.loads(second.stdout)
    assert b["schema_version"] == 3
    assert b["files_cached"] >= 0.9 * b["files_checked"], (
        b["files_cached"],
        b["files_checked"],
    )
    assert a["findings"] == b["findings"]
    assert a["stale_suppressions"] == b["stale_suppressions"] == []
    assert b["rule_seconds"], "per-rule timing missing from JSON report"
    # The HL3xx jaxpr audit joins the default gate: the second run must
    # replay every kernel from the per-kernel audit cache.
    assert b["audit"] is not None, "audit block missing from JSON report"
    assert b["audit"]["kernels_checked"] >= 30
    assert b["audit"]["kernels_cached"] == b["audit"]["kernels_checked"]
    assert a["audit"]["kernel_seconds"].keys() == (
        b["audit"]["kernel_seconds"].keys()
    )

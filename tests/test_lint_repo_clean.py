"""The holo-lint tier-1 gate: the live tree must match the baseline.

This is the in-pytest arm of the ratchet (the CLI arm is
``holo-tpu-tools lint --baseline holo_tpu/analysis/baseline.json`` in
the ROADMAP verify chain): any NEW finding fails tier-1, and a STALE
baseline entry (its finding was fixed) also fails — the baseline only
ever shrinks.
"""

from pathlib import Path

from holo_tpu.analysis import (
    all_rules,
    compare_to_baseline,
    default_baseline_path,
    gate_findings,
    load_baseline,
    run_paths,
)

REPO = Path(__file__).resolve().parent.parent


def test_repo_matches_baseline():
    result = run_paths([REPO / "holo_tpu"], root=REPO)
    assert not result.parse_errors, result.parse_errors
    assert result.files_checked > 60  # the whole package, not a subset

    baseline = load_baseline(default_baseline_path())
    new, unused = compare_to_baseline(result.findings, baseline)
    # The gate rides error-tier rules only (warn-tier findings report
    # without failing tier-1 — the CLI arm applies the same split).
    new_errors = gate_findings(new)
    assert not new_errors, (
        "new holo-lint findings (fix or baseline them):\n"
        + "\n".join(f.render() for f in new_errors)
    )
    assert not unused, (
        "stale baseline entries (their findings were fixed) — ratchet by "
        "removing them from holo_tpu/analysis/baseline.json:\n"
        + "\n".join(sorted(unused))
    )


def test_every_suppression_carries_a_rule_id():
    # `disable=all` is for fixtures/docs, not the live tree: every
    # in-tree suppression must name the rule it silences.
    import re

    pat = re.compile(r"holo-lint:\s*disable=([A-Za-z0-9_,\s-]+)")
    offenders = []
    for path in sorted((REPO / "holo_tpu").rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        for i, line in enumerate(path.read_text().splitlines(), start=1):
            m = pat.search(line)
            if m and "all" in {s.strip() for s in m.group(1).split(",")}:
                offenders.append(f"{path}:{i}")
    assert not offenders, offenders


def test_rule_catalog_documented():
    # COMPONENTS.md documents every rule id the analyzer ships.
    text = (REPO / "COMPONENTS.md").read_text()
    missing = [r.id for r in all_rules() if r.id not in text]
    assert not missing, f"rules undocumented in COMPONENTS.md: {missing}"


def test_cli_gate_exits_clean():
    import subprocess
    import sys

    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "holo_tpu.tools.cli",
            "lint",
            "--baseline",
            str(default_baseline_path()),
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr

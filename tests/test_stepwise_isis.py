"""IS-IS stepwise conformance: the reference's per-step golden cases
replayed through our live IsisInstance (tools/stepwise_isis.py).

Each case replays one recorded router's events.jsonl through the real
adjacency FSM / flooding / SPF machinery — with byte-identical LSP
re-encoding, so the recorded PSNP acks of the reference's own LSPs
validate OUR origination checksums — then applies the numbered step
inputs (PDUs, ibus events, config changes, RPCs) and asserts the
protocol-output, local-rib, LSP-database, SRM/SSN, adjacency, and
BFD-session planes.  All 79 reference cases pass, including level-all
(L1/L2) routers.
"""

from pathlib import Path

import pytest

from holo_tpu.tools.stepwise_isis import ISIS_DIR, case_map, run_all, run_case

pytestmark = pytest.mark.skipif(
    not ISIS_DIR.exists(), reason="reference corpus not present"
)

KNOWN_PASS = [
    "pdu-csnp1",
    "pdu-psnp1",
    "pdu-lsp1",
    "timeout-adj1",
    "csnp-interval1",
]
PASS_FLOOR = 79


def test_known_cases_pass():
    cm = case_map()
    for case in KNOWN_PASS:
        status, detail = run_case(ISIS_DIR / case, *cm[case])
        assert status == "pass", f"{case}: {detail}"


def test_stepwise_sweep_floor():
    res = run_all()
    passed = sorted(c for c, (s, _) in res.items() if s == "pass")
    failed = {c: d for c, (s, d) in res.items() if s == "fail"}
    assert len(passed) >= PASS_FLOOR, (
        f"only {len(passed)} IS-IS stepwise cases pass (floor {PASS_FLOOR}); "
        f"failures: { {c: d[:120] for c, d in list(failed.items())[:5]} }"
    )


def test_lsp_reencode_byte_identical():
    """Every recorded LSP in the corpus re-encodes to its exact wire
    bytes through our codec (TLV order, sub-TLVs, empty-TLV semantics)."""
    import json

    from holo_tpu.protocols.isis.packet import Lsp, decode_pdu

    ok = bad = 0
    for f in (ISIS_DIR / "topologies").glob("*/*/events.jsonl"):
        for line in f.read_text().splitlines():
            ev = json.loads(line)
            rx = (ev.get("Protocol") or {}).get("NetRxPdu")
            if not rx or "bytes" not in rx:
                continue
            raw = bytes(rx["bytes"])
            try:
                _t, pdu = decode_pdu(raw)
            except Exception:
                continue
            if not isinstance(pdu, Lsp):
                continue
            if pdu.encode() == raw:
                ok += 1
            else:
                bad += 1
    assert bad == 0 and ok > 900, f"re-encode: {ok} ok, {bad} diverged"

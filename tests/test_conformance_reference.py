"""Conformance against the reference's own recorded expectations.

For every OSPFv2 conformance topology shipped with the reference
(SURVEY.md §4), the harness decodes the recorded LSAs with OUR codecs,
runs OUR SPF/route pipeline per router, and requires the computed RIB to
be bit-identical to the reference's expected local-rib — all 63 routers
across all topologies, including multi-area, virtual links, unnumbered
and parallel links, ECMP and stub semantics.
"""

from pathlib import Path

import pytest

from holo_tpu.tools.conformance import REFERENCE_CONFORMANCE, run_topology

pytestmark = pytest.mark.skipif(
    not REFERENCE_CONFORMANCE.exists(),
    reason="reference conformance corpus not mounted",
)


def topo_dirs():
    if not REFERENCE_CONFORMANCE.exists():
        return []
    return sorted(
        p.name for p in REFERENCE_CONFORMANCE.iterdir() if p.is_dir()
    )


@pytest.mark.parametrize("backend", ["scalar", "tpu"])
@pytest.mark.parametrize("topo_name", topo_dirs())
def test_reference_topology_rib_conformance(topo_name, backend):
    """Both backends — the scalar oracle AND the tensor engine — must
    reproduce the reference's expected RIBs bit-identically."""
    factory = None
    if backend == "tpu":
        from holo_tpu.spf.backend import TpuSpfBackend

        factory = TpuSpfBackend
    results = run_topology(REFERENCE_CONFORMANCE / topo_name, factory)
    assert results, "no routers loaded"
    failures = {rt: problems for rt, problems in results.items() if problems}
    assert not failures, "\n".join(
        f"{rt}: {p}" for rt, probs in failures.items() for p in probs
    )

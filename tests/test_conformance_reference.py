"""Conformance against the reference's own recorded expectations.

For every OSPFv2 conformance topology shipped with the reference
(SURVEY.md §4), the harness decodes the recorded LSAs with OUR codecs,
runs OUR SPF/route pipeline per router, and requires the computed RIB to
be bit-identical to the reference's expected local-rib.

Known exclusions (documented unimplemented feature): routers whose
expected routes depend on VIRTUAL LINKS (topo3-x rt1/rt6).
"""

from pathlib import Path

import pytest

from holo_tpu.tools.conformance import REFERENCE_CONFORMANCE, run_topology

pytestmark = pytest.mark.skipif(
    not REFERENCE_CONFORMANCE.exists(),
    reason="reference conformance corpus not mounted",
)

# Routers reachable only through virtual links (not implemented yet).
VLINK_EXCLUSIONS = {
    ("topo3-1", "rt1"),
    ("topo3-2", "rt1"),
    ("topo3-2", "rt6"),
    ("topo3-3", "rt1"),
}


def topo_dirs():
    if not REFERENCE_CONFORMANCE.exists():
        return []
    return sorted(
        p.name for p in REFERENCE_CONFORMANCE.iterdir() if p.is_dir()
    )


@pytest.mark.parametrize("topo_name", topo_dirs())
def test_reference_topology_rib_conformance(topo_name):
    results = run_topology(REFERENCE_CONFORMANCE / topo_name)
    assert results, "no routers loaded"
    failures = {
        rt: problems
        for rt, problems in results.items()
        if problems and (topo_name, rt) not in VLINK_EXCLUSIONS
    }
    assert not failures, "\n".join(
        f"{rt}: {p}" for rt, probs in failures.items() for p in probs
    )
    # The exclusions must be exactly the vlink-dependent routers — if one
    # starts passing (vlinks implemented), tighten the list.
    for rt, problems in results.items():
        if (topo_name, rt) in VLINK_EXCLUSIONS:
            assert problems, f"{rt} now passes: remove from VLINK_EXCLUSIONS"

"""ASan/UBSan coverage for the C++ core (SURVEY.md §5: mandatory once
Rust's compile-time guarantees are dropped).

Builds native/sanitize_driver.cpp together with both native translation
units under -fsanitize=address,undefined and runs it; any heap error,
leak, overflow, or UB aborts the binary with a nonzero exit.
"""

import shutil
import subprocess
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
NATIVE = ROOT / "native"
HAVE_GXX = shutil.which("g++") is not None


@pytest.mark.skipif(not HAVE_GXX, reason="g++ unavailable")
def test_native_under_asan_ubsan(tmp_path):
    binary = tmp_path / "sanitize_driver"
    build = subprocess.run(
        [
            "g++", "-std=c++17", "-O1", "-g", "-fno-omit-frame-pointer",
            "-fsanitize=address,undefined", "-fno-sanitize-recover=all",
            str(NATIVE / "sanitize_driver.cpp"),
            str(NATIVE / "runtime_core.cpp"),
            str(NATIVE / "spf_baseline.cpp"),
            "-o", str(binary),
        ],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert build.returncode == 0, f"build failed:\n{build.stderr[-2000:]}"
    run = subprocess.run(
        [str(binary)],
        capture_output=True,
        text=True,
        timeout=300,
        env={"ASAN_OPTIONS": "detect_leaks=1", "UBSAN_OPTIONS": "print_stacktrace=1"},
    )
    assert run.returncode == 0, (
        f"sanitizer failure:\n{run.stdout[-1000:]}\n{run.stderr[-3000:]}"
    )
    assert "sanitize_driver OK" in run.stdout


@pytest.mark.skipif(not HAVE_GXX, reason="g++ unavailable")
def test_native_under_tsan_threaded_runtime(tmp_path):
    """ThreadSanitizer over the native runtime under the threaded
    daemon's exact concurrency contracts (SURVEY.md §5: mandatory now
    that [runtime] isolation = "threaded" makes the MPSC ring, poller,
    and per-thread wheels production paths).  The driver replicates the
    ThreadedLoop/ThreadedFabric shapes at the native layer — N producer
    threads vs one ring owner, cross-thread poller mutation, per-thread
    wheel ownership; the Python halves of those structures are
    GIL-serialized and covered by tests/test_preempt_stress.py."""
    binary = tmp_path / "tsan_driver"
    build = subprocess.run(
        [
            "g++", "-std=c++17", "-O1", "-g", "-fno-omit-frame-pointer",
            "-fsanitize=thread",
            str(NATIVE / "tsan_driver.cpp"),
            str(NATIVE / "runtime_core.cpp"),
            "-o", str(binary), "-lpthread",
        ],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert build.returncode == 0, f"build failed:\n{build.stderr[-2000:]}"
    run = subprocess.run(
        [str(binary)],
        capture_output=True,
        text=True,
        timeout=300,
        env={"TSAN_OPTIONS": "halt_on_error=1 exitcode=66"},
    )
    assert run.returncode == 0, (
        f"TSan failure:\n{run.stdout[-1000:]}\n{run.stderr[-4000:]}"
    )
    assert "tsan_driver OK" in run.stdout

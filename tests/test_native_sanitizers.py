"""ASan/UBSan coverage for the C++ core (SURVEY.md §5: mandatory once
Rust's compile-time guarantees are dropped).

Builds native/sanitize_driver.cpp together with both native translation
units under -fsanitize=address,undefined and runs it; any heap error,
leak, overflow, or UB aborts the binary with a nonzero exit.
"""

import subprocess
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
NATIVE = ROOT / "native"


@pytest.mark.skipif(
    subprocess.run(["which", "g++"], capture_output=True).returncode != 0,
    reason="g++ unavailable",
)
def test_native_under_asan_ubsan(tmp_path):
    binary = tmp_path / "sanitize_driver"
    build = subprocess.run(
        [
            "g++", "-std=c++17", "-O1", "-g", "-fno-omit-frame-pointer",
            "-fsanitize=address,undefined", "-fno-sanitize-recover=all",
            str(NATIVE / "sanitize_driver.cpp"),
            str(NATIVE / "runtime_core.cpp"),
            str(NATIVE / "spf_baseline.cpp"),
            "-o", str(binary),
        ],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert build.returncode == 0, f"build failed:\n{build.stderr[-2000:]}"
    run = subprocess.run(
        [str(binary)],
        capture_output=True,
        text=True,
        timeout=300,
        env={"ASAN_OPTIONS": "detect_leaks=1", "UBSAN_OPTIONS": "print_stacktrace=1"},
    )
    assert run.returncode == 0, (
        f"sanitizer failure:\n{run.stdout[-1000:]}\n{run.stderr[-3000:]}"
    )
    assert "sanitize_driver OK" in run.stdout

"""Device BGP table vs the scalar decision process (ISSUE 16).

Every arm builds two identical engines — one on the verbatim scalar
walk, one on :class:`TpuBgpTableBackend` — runs the decision process
under ``jax.transfer_guard("disallow")`` plus the armed donation guard,
and asserts the full observable state is bit-identical: Loc-RIB routes
and nexthop sets, per-candidate reject/ineligible reason strings (YANG
renders them), candidate ``igp_cost`` side effects, and the ibus
RouteIpAdd/RouteIpDel stream.
"""

from dataclasses import replace

import jax
import pytest

from holo_tpu.analysis import runtime
from holo_tpu.ops.bgp_table import (
    DeviceRankBackend,
    ScalarBgpTableBackend,
    TpuBgpTableBackend,
    backends_stats,
)
from holo_tpu.protocols.bgp_engine import (
    AdjRib,
    AsSegment,
    BaseAttrs,
    BgpEngine,
    Destination,
    NhtEntry,
    Route,
    RouteOrigin,
)
from holo_tpu.resilience.breaker import CircuitBreaker

AFS = "ipv4-unicast"


def seg(*asns):
    return (AsSegment("Sequence", tuple(asns)),)


def mk_engine(backend=None, mp=None):
    calls = []
    eng = BgpEngine(
        "r1",
        ibus_cb=lambda kind, payload: calls.append((kind, payload)),
        table_backend=backend,
    )
    eng.asn = 65000
    if mp:
        eng.multipath[AFS] = dict(mp)
    return eng, calls


def install(eng, routes, nht=(), redistribute=()):
    """routes: (prefix, peer_addr, attrs, route_type, router_id)."""
    table = eng.tables[AFS]
    for prefix, addr, attrs, route_type, rid in routes:
        dest = table.prefixes.setdefault(prefix, Destination())
        adj = dest.adj_rib.setdefault(addr, AdjRib())
        adj.in_post = Route(
            origin=RouteOrigin(identifier=rid, remote_addr=addr),
            attrs=attrs,
            route_type=route_type,
        )
        queue(eng, prefix)
    for prefix, attrs in redistribute:
        dest = table.prefixes.setdefault(prefix, Destination())
        dest.redistribute = Route(
            origin=RouteOrigin(protocol="static"),
            attrs=attrs,
            route_type="Internal",
        )
        queue(eng, prefix)
    for addr, metric in dict(nht).items():
        table.nht[addr] = NhtEntry(metric=metric)


def queue(eng, prefix):
    eng.tables[AFS].queued.add(prefix)
    if eng.table_backend is not None:
        eng.table_backend.note_route_change(AFS, prefix)


def withdraw(eng, prefix, addr):
    table = eng.tables[AFS]
    adj = table.prefixes[prefix].adj_rib[addr]
    eng._nexthop_untrack(table, prefix, adj.in_post)
    adj.in_pre = None
    adj.in_post = None
    queue(eng, prefix)


def run(eng):
    if isinstance(eng.table_backend, TpuBgpTableBackend):
        # The guards are the arm's point: any unsanctioned transfer or
        # use-after-donation in the device path must fail loudly here.
        with jax.transfer_guard("disallow"), runtime.donation_guard():
            eng.run_decision_process()
    else:
        eng.run_decision_process()


def snapshot(eng):
    out = {}
    for prefix, dest in eng.tables[AFS].prefixes.items():
        out[prefix] = {
            "local": None
            if dest.local is None
            else (
                dest.local.origin,
                dest.local.attrs,
                dest.local.route_type,
                dest.local.igp_cost,
            ),
            "nexthops": dest.local_nexthops,
            "adj": {
                addr: (
                    adj.in_post.reject_reason,
                    adj.in_post.ineligible_reason,
                    adj.in_post.igp_cost,
                )
                for addr, adj in dest.adj_rib.items()
                if adj.in_post is not None
            },
            "redistribute": None
            if dest.redistribute is None
            else (
                dest.redistribute.reject_reason,
                dest.redistribute.ineligible_reason,
            ),
        }
    return out


def assert_parity(pair, calls):
    (scalar, device) = pair
    assert snapshot(scalar) == snapshot(device)
    assert calls[0] == calls[1]


def parity_pair(routes, nht=(), mp=None, redistribute=(), backend=None):
    scalar, s_calls = mk_engine(mp=mp)
    install(scalar, routes, nht, redistribute)
    device, d_calls = mk_engine(
        backend=backend or TpuBgpTableBackend(), mp=mp
    )
    install(device, routes, nht, redistribute)
    run(scalar)
    run(device)
    assert_parity((scalar, device), (s_calls, d_calls))
    return scalar, device, s_calls, d_calls


def test_plain_best_path_parity():
    scalar, device, _, _ = parity_pair(
        [
            ("10.0.0.0/24", "1.1.1.1",
             BaseAttrs(origin="Igp", as_path=seg(100), nexthop="9.9.9.1",
                       med=100), "External", "1.1.1.1"),
            ("10.0.0.0/24", "1.1.1.2",
             BaseAttrs(origin="Igp", as_path=seg(200), nexthop="9.9.9.2",
                       med=0), "External", "1.1.1.2"),
            ("10.0.0.0/24", "1.1.1.3",
             BaseAttrs(origin="Igp", as_path=seg(100), nexthop="9.9.9.3",
                       med=0), "External", "1.1.1.3"),
            ("10.0.1.0/24", "1.1.1.2",
             BaseAttrs(origin="Egp", as_path=seg(100), nexthop="9.9.9.9"),
             "External", "1.1.1.2"),  # unresolvable next hop
            ("10.0.2.0/24", "1.1.1.2",
             BaseAttrs(origin="Igp", as_path=seg(65000, 1),
                       nexthop="9.9.9.2"), "External", "1.1.1.2"),  # AS loop
        ],
        nht={"9.9.9.1": 10, "9.9.9.2": 10, "9.9.9.3": 5},
    )
    st = device.table_backend.stats()
    assert st["dispatches"] == 1 and st["fallbacks"] == 0


def test_med_non_transitive_cycle_parity():
    """X3 beats X1 on MED, X1 beats X2 on router-id, X2 beats X3 on
    router-id: a preference CYCLE — no static sort key exists, only the
    sequential fold reproduces the oracle.  The device must agree."""
    parity_pair(
        [
            ("10.0.0.0/24", "1.1.1.1",
             BaseAttrs(origin="Igp", as_path=seg(1), nexthop="9.9.9.1",
                       med=100), "External", "0.0.0.1"),
            ("10.0.0.0/24", "1.1.1.2",
             BaseAttrs(origin="Igp", as_path=seg(2), nexthop="9.9.9.1",
                       med=0), "External", "0.0.0.2"),
            ("10.0.0.0/24", "1.1.1.3",
             BaseAttrs(origin="Igp", as_path=seg(1), nexthop="9.9.9.1",
                       med=0), "External", "0.0.0.3"),
        ],
        nht={"9.9.9.1": 10},
    )


def test_med_missing_folds_to_zero():
    parity_pair(
        [
            ("10.0.0.0/24", "1.1.1.1",
             BaseAttrs(origin="Igp", as_path=seg(1), nexthop="9.9.9.1",
                       med=None), "External", "0.0.0.1"),
            ("10.0.0.0/24", "1.1.1.2",
             BaseAttrs(origin="Igp", as_path=seg(1), nexthop="9.9.9.1",
                       med=5), "External", "0.0.0.2"),
        ],
        nht={"9.9.9.1": 10},
    )


def test_tie_breaker_ladder_parity():
    """One arm per rung: local-pref, path length, origin, peer type,
    IGP cost (incl. the None-preferred asymmetry), router-id, and the
    final peer-address / incumbent-wins fallback."""
    a = BaseAttrs(origin="Igp", as_path=seg(1), nexthop="9.9.9.1")
    cases = [
        (replace(a, local_pref=200), replace(a, local_pref=100)),
        (replace(a, as_path=seg(1)), replace(a, as_path=seg(1, 2))),
        (replace(a, origin="Igp"), replace(a, origin="Incomplete")),
        (a, a),  # full tie -> router-id rung
    ]
    for attrs1, attrs2 in cases:
        parity_pair(
            [
                ("10.0.0.0/24", "1.1.1.1", attrs1, "External", "0.0.0.2"),
                ("10.0.0.0/24", "1.1.1.2", attrs2, "External", "0.0.0.1"),
            ],
            nht={"9.9.9.1": 10},
        )
    # prefer-external + IGP cost rungs
    parity_pair(
        [
            ("10.0.0.0/24", "1.1.1.1", a, "Internal", "0.0.0.1"),
            ("10.0.0.0/24", "1.1.1.2", a, "External", "0.0.0.2"),
        ],
        nht={"9.9.9.1": 10},
    )
    parity_pair(
        [
            ("10.0.0.0/24", "1.1.1.1",
             replace(a, nexthop="9.9.9.1"), "External", "0.0.0.1"),
            ("10.0.0.0/24", "1.1.1.2",
             replace(a, nexthop="9.9.9.2"), "External", "0.0.0.2"),
        ],
        nht={"9.9.9.1": 20, "9.9.9.2": 10},
    )
    # identical router-ids -> higher-peer-address fallback
    parity_pair(
        [
            ("10.0.0.0/24", "1.1.1.2", a, "External", "0.0.0.9"),
            ("10.0.0.0/24", "1.1.1.1", a, "External", "0.0.0.9"),
        ],
        nht={"9.9.9.1": 10},
    )


def test_redistribute_column_parity():
    local = BaseAttrs(origin="Igp", as_path=())
    peer = BaseAttrs(origin="Igp", as_path=seg(1), nexthop="9.9.9.1")
    for lp in (50, 200):
        parity_pair(
            [("10.0.0.0/24", "1.1.1.1", replace(peer, local_pref=lp),
              "External", "0.0.0.1")],
            nht={"9.9.9.1": 10},
            redistribute=[("10.0.0.0/24", local)],
        )


@pytest.mark.parametrize(
    "mp",
    [
        {"enabled": True, "ebgp_max": 2, "ibgp_max": 1,
         "allow_multiple_as": True},
        {"enabled": True, "ebgp_max": 4, "ibgp_max": 1,
         "allow_multiple_as": False},
        {"enabled": False},
    ],
)
def test_multipath_parity(mp):
    parity_pair(
        [
            ("10.0.0.0/24", "1.1.1.1",
             BaseAttrs(origin="Igp", as_path=seg(1), nexthop="9.9.9.1"),
             "External", "0.0.0.1"),
            ("10.0.0.0/24", "1.1.1.2",
             BaseAttrs(origin="Igp", as_path=seg(2), nexthop="9.9.9.2"),
             "External", "0.0.0.1"),
            ("10.0.0.0/24", "1.1.1.3",
             BaseAttrs(origin="Igp", as_path=seg(3), nexthop="9.9.9.3"),
             "External", "0.0.0.1"),
        ],
        nht={"9.9.9.1": 10, "9.9.9.2": 10, "9.9.9.3": 10},
        mp=mp,
    )


def test_peer_flap_parity():
    routes = [
        ("10.0.0.0/24", "1.1.1.1",
         BaseAttrs(origin="Igp", as_path=seg(1), nexthop="9.9.9.1"),
         "External", "0.0.0.1"),
        ("10.0.0.0/24", "1.1.1.2",
         BaseAttrs(origin="Igp", as_path=seg(2), nexthop="9.9.9.2"),
         "External", "0.0.0.2"),
    ]
    nht = {"9.9.9.1": 20, "9.9.9.2": 10}
    scalar, device, s_calls, d_calls = parity_pair(routes, nht)
    for eng in (scalar, device):
        withdraw(eng, "10.0.0.0/24", "1.1.1.2")
        run(eng)
    assert_parity((scalar, device), (s_calls, d_calls))
    # flap back up
    for eng in (scalar, device):
        table = eng.tables[AFS]
        adj = table.prefixes["10.0.0.0/24"].adj_rib["1.1.1.2"]
        adj.in_post = Route(
            origin=RouteOrigin(identifier="0.0.0.2", remote_addr="1.1.1.2"),
            attrs=routes[1][2],
            route_type="External",
        )
        eng._nexthop_track(table, "10.0.0.0/24", adj.in_post)
        queue(eng, "10.0.0.0/24")
        run(eng)
    assert_parity((scalar, device), (s_calls, d_calls))


def test_incremental_chain_reuses_resident_rows():
    routes = [
        ("10.0.0.0/24", "1.1.1.1",
         BaseAttrs(origin="Igp", as_path=seg(1), nexthop="9.9.9.1"),
         "External", "0.0.0.1"),
        ("10.0.1.0/24", "1.1.1.1",
         BaseAttrs(origin="Igp", as_path=seg(1, 2), nexthop="9.9.9.1"),
         "External", "0.0.0.1"),
    ]
    scalar, device, s_calls, d_calls = parity_pair(
        routes, nht={"9.9.9.1": 10}
    )
    for eng in (scalar, device):
        table = eng.tables[AFS]
        table.nht["9.9.9.1"].prefixes = {
            "10.0.0.0/24": 1, "10.0.1.0/24": 1
        }
    scatters_before = device.table_backend.stats()["tables"][AFS]["scatters"]
    # NHT-only churn: queued via nexthop_update, no note_route_change —
    # the device must recompute from RESIDENT rows, zero re-marshal.
    for eng in (scalar, device):
        eng.nexthop_update("9.9.9.1", 99)
        run(eng)
    assert_parity((scalar, device), (s_calls, d_calls))
    st = device.table_backend.stats()["tables"][AFS]
    assert st["scatters"] == scatters_before, "NHT churn re-marshaled"
    # metric loss makes everything unresolvable -> RouteIpDel parity
    for eng in (scalar, device):
        eng.nexthop_update("9.9.9.1", None)
        run(eng)
    assert_parity((scalar, device), (s_calls, d_calls))


def test_breaker_fallback_parity():
    backend = TpuBgpTableBackend(
        breaker=CircuitBreaker(
            "bgp-table-test-fallback", failure_threshold=1, enabled=True
        )
    )
    backend._device_batch = _boom  # device path always faults
    scalar, device, _, _ = parity_pair(
        [
            ("10.0.0.0/24", "1.1.1.1",
             BaseAttrs(origin="Igp", as_path=seg(1), nexthop="9.9.9.1"),
             "External", "0.0.0.1"),
        ],
        nht={"9.9.9.1": 10},
        backend=backend,
    )
    assert device.table_backend.stats()["fallbacks"] >= 1


def _boom(*_args, **_kw):
    raise RuntimeError("injected device fault")


def test_marshal_poison_falls_back_per_prefix():
    """A route outside the lane contract (med >= 2**32) poisons only
    its own prefix; everything else stays on device, parity holds."""
    scalar, device, _, _ = parity_pair(
        [
            ("10.0.0.0/24", "1.1.1.1",
             BaseAttrs(origin="Igp", as_path=seg(1), nexthop="9.9.9.1",
                       med=2**40), "External", "0.0.0.1"),
            ("10.0.1.0/24", "1.1.1.1",
             BaseAttrs(origin="Igp", as_path=seg(1), nexthop="9.9.9.1"),
             "External", "0.0.0.1"),
        ],
        nht={"9.9.9.1": 10},
    )
    st = device.table_backend.stats()["tables"][AFS]
    assert st["poisoned"] == 1


def test_scalar_backend_is_the_identity_seam():
    routes = [
        ("10.0.0.0/24", "1.1.1.1",
         BaseAttrs(origin="Igp", as_path=seg(1), nexthop="9.9.9.1"),
         "External", "0.0.0.1"),
    ]
    bare, bare_calls = mk_engine()
    install(bare, routes, {"9.9.9.1": 10})
    bare.run_decision_process()
    seam, seam_calls = mk_engine(backend=ScalarBgpTableBackend())
    install(seam, routes, {"9.9.9.1": 10})
    seam.run_decision_process()
    assert_parity((bare, seam), (bare_calls, seam_calls))


def test_stats_ride_the_gnmi_leaf():
    backend = TpuBgpTableBackend()
    assert any(
        s["backend"] == "tpu" for s in backends_stats()
    )
    from holo_tpu.telemetry.provider import TelemetryStateProvider

    state = TelemetryStateProvider().get_state()
    assert "bgp-table" in state["holo-telemetry"], (
        "bgp_table imported but no holo-telemetry/bgp-table leaf"
    )
    del backend


def test_device_rank_backend_matches_host_sort():
    rb = DeviceRankBackend()
    ranks = [
        (-200, 1, 0, 0, 1, 7),
        (-100, 1, 0, 0, 1, 7),
        (-200, 1, 0, 0, 1, 3),
        (-200, 2, 0, 5, 2, 3),
        (-200, 1, 0, 0, 1, 3),  # duplicate: stability must hold
    ]
    order = rb.rank_order(list(ranks))
    want = sorted(range(len(ranks)), key=lambda i: ranks[i])
    assert order == want
    # out-of-contract lane -> None (caller falls back to list.sort)
    assert rb.rank_order([(0, 0, 0, 2**32, 0, 0), (0, 0, 0, 0, 0, 0)]) is None


def test_bgp_instance_decision_rides_rank_backend():
    from ipaddress import IPv4Address, IPv4Network

    from holo_tpu.protocols import bgp

    class _NullNetIo:
        def __getattr__(self, name):
            return lambda *a, **k: None

    def build(rank_backend):
        inst = bgp.BgpInstance(
            "b1", 65000, IPv4Address("10.255.0.1"), _NullNetIo()
        )
        inst.rank_backend = rank_backend
        prefix = IPv4Network("10.9.0.0/24")
        inst.originated[prefix] = bgp.PathAttrs(
            origin=bgp.Origin.IGP, as_path=()
        )
        inst._decision(prefix)
        return [e.attrs for e in inst.loc_rib[prefix]]

    assert build(None) == build(DeviceRankBackend())

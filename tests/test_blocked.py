"""Block-sparse min-plus kernel (interpret mode): exact parity with the
scalar reference on what-if batches."""

import numpy as np
import pytest

from holo_tpu.ops.blocked import (
    failed_edges_from_masks,
    marshal_blocks,
    whatif_distances_blocked,
)
from holo_tpu.spf.backend import ScalarSpfBackend
from holo_tpu.spf.synth import random_ospf_topology, whatif_link_failure_masks


@pytest.mark.parametrize("seed", range(3))
def test_blocked_distances_match_scalar(seed):
    topo = random_ospf_topology(
        n_routers=300, n_networks=40, extra_p2p=500, seed=seed
    )
    masks = whatif_link_failure_masks(topo, n_scenarios=8, seed=seed + 10)
    g = marshal_blocks(topo)
    fdst, fid = failed_edges_from_masks(topo, masks)
    out = np.asarray(
        whatif_distances_blocked(g, topo.root, fdst, fid, interpret=True)
    )
    scalar = ScalarSpfBackend().compute_whatif(topo, masks)
    for b, s in enumerate(scalar):
        np.testing.assert_array_equal(s.dist, out[b], err_msg=f"scenario {b}")


def test_blocked_rejects_parallel_edges():
    from holo_tpu.ops.graph import Topology

    topo = Topology(
        n_vertices=2,
        is_router=np.ones(2, bool),
        edge_src=np.array([0, 0, 1], np.int32),
        edge_dst=np.array([1, 1, 0], np.int32),  # duplicate 0->1
        edge_cost=np.array([1, 2, 1], np.int32),
        root=0,
    )
    with pytest.raises(ValueError, match="parallel"):
        marshal_blocks(topo)


def test_blocked_multi_failure_scenario():
    topo = random_ospf_topology(n_routers=80, n_networks=10, seed=5)
    # fail two links in one scenario (4 directed edges)
    masks = np.ones((2, topo.n_edges), bool)
    rng = np.random.default_rng(3)
    pair = {}
    for e in range(topo.n_edges):
        pair[(int(topo.edge_src[e]), int(topo.edge_dst[e]))] = e
    for _ in range(2):
        e = int(rng.integers(0, topo.n_edges))
        masks[1, e] = False
        rev = pair.get((int(topo.edge_dst[e]), int(topo.edge_src[e])))
        if rev is not None:
            masks[1, rev] = False
    g = marshal_blocks(topo)
    fdst, fid = failed_edges_from_masks(topo, masks)
    out = np.asarray(
        whatif_distances_blocked(g, topo.root, fdst, fid, interpret=True)
    )
    scalar = ScalarSpfBackend().compute_whatif(topo, masks)
    for b, s in enumerate(scalar):
        np.testing.assert_array_equal(s.dist, out[b])

"""OSPFv2 packet codec round-trips against hand-written byte images.

Style of the reference's codec tests (holo-ospf/tests/packet/ospfv2.rs):
every case asserts exact encode bytes and exact decode equality.
"""

from ipaddress import IPv4Address as A

import pytest

from holo_tpu.protocols.ospf.packet import (
    DbDesc,
    DbDescFlags,
    Hello,
    Lsa,
    LsaAsExternal,
    LsaKey,
    LsaNetwork,
    LsaRouter,
    LsAck,
    LsaSummary,
    LsaType,
    LsRequest,
    LsUpdate,
    Options,
    Packet,
    RouterFlags,
    RouterLink,
    RouterLinkType,
)
from holo_tpu.utils.bytesbuf import DecodeError, Reader, fletcher16_verify


def roundtrip_packet(pkt: Packet) -> Packet:
    raw = pkt.encode()
    out = Packet.decode(raw)
    assert out.encode() == raw
    return out


def test_hello_exact_bytes():
    pkt = Packet(
        router_id=A("1.1.1.1"),
        area_id=A("0.0.0.0"),
        body=Hello(
            mask=A("255.255.255.0"),
            hello_interval=10,
            options=Options.E,
            priority=1,
            dead_interval=40,
            dr=A("10.0.0.1"),
            bdr=A("0.0.0.0"),
            neighbors=[A("2.2.2.2")],
        ),
    )
    raw = pkt.encode()
    expect = bytes.fromhex(
        "0201003001010101000000000000"  # ver,type,len=48,rid,area,cks(hi)
    )
    # Spot-check structural fields rather than full image for the header:
    assert raw[0] == 2 and raw[1] == 1
    assert int.from_bytes(raw[2:4], "big") == len(raw) == 48
    assert raw[4:8] == bytes([1, 1, 1, 1])
    # Body image is fully deterministic:
    assert raw[24:28] == bytes([255, 255, 255, 0])
    assert int.from_bytes(raw[28:30], "big") == 10
    assert raw[30] == int(Options.E)
    assert raw[31] == 1
    assert int.from_bytes(raw[32:36], "big") == 40
    assert raw[36:40] == bytes([10, 0, 0, 1])
    assert raw[44:48] == bytes([2, 2, 2, 2])
    out = roundtrip_packet(pkt)
    assert out.body.neighbors == [A("2.2.2.2")]


def test_packet_checksum_rejects_corruption():
    pkt = Packet(A("1.1.1.1"), A("0.0.0.0"), LsRequest([]))
    raw = bytearray(pkt.encode())
    raw[5] ^= 0xFF
    with pytest.raises(DecodeError, match="checksum|length|version"):
        Packet.decode(bytes(raw))


def make_router_lsa(seq=0x80000001 - (1 << 32)):
    return Lsa(
        age=1,
        options=Options.E,
        type=LsaType.ROUTER,
        lsid=A("1.1.1.1"),
        adv_rtr=A("1.1.1.1"),
        seq_no=seq,
        body=LsaRouter(
            flags=RouterFlags(0),
            links=[
                RouterLink(RouterLinkType.POINT_TO_POINT, A("2.2.2.2"), A("10.0.0.1"), 10),
                RouterLink(RouterLinkType.STUB_NETWORK, A("10.0.0.0"), A("255.255.255.252"), 10),
            ],
        ),
    )


def test_lsa_fletcher_checksum():
    lsa = make_router_lsa()
    raw = lsa.encode()
    assert fletcher16_verify(raw[2:])
    # Corrupt a body byte: the decode is tolerant (reference parity) but
    # the instance-level validation must flag invalid-checksum so the rx
    # path discards it with an if-rx-bad-lsa notification.
    from holo_tpu.protocols.ospf.instance import OspfInstance

    bad = bytearray(raw)
    bad[25] ^= 0x01
    out_bad = Lsa.decode(Reader(bytes(bad)))
    assert OspfInstance._validate_lsa(out_bad) == "invalid-checksum"
    out = Lsa.decode(Reader(raw))
    assert OspfInstance._validate_lsa(out) is None
    assert out.body.links == lsa.body.links
    assert out.seq_no == lsa.seq_no


def test_lsa_compare_newer():
    a, b = make_router_lsa(seq=-5), make_router_lsa(seq=-4)
    a.encode(), b.encode()
    assert b.compare(a) > 0 and a.compare(b) < 0
    c = make_router_lsa(seq=-5)
    c.encode()
    assert a.compare(c) == 0


def test_network_lsa_roundtrip():
    lsa = Lsa(
        age=0,
        options=Options.E,
        type=LsaType.NETWORK,
        lsid=A("10.0.0.1"),
        adv_rtr=A("1.1.1.1"),
        seq_no=-100,
        body=LsaNetwork(A("255.255.255.0"), [A("1.1.1.1"), A("2.2.2.2")]),
    )
    raw = lsa.encode()
    out = Lsa.decode(Reader(raw))
    assert out.body.mask == A("255.255.255.0")
    assert out.body.attached == [A("1.1.1.1"), A("2.2.2.2")]


def test_summary_and_external_roundtrip():
    s = Lsa(10, Options.E, LsaType.SUMMARY_NETWORK, A("172.16.0.0"), A("1.1.1.1"),
            -7, LsaSummary(A("255.255.0.0"), 123))
    e = Lsa(10, Options.E, LsaType.AS_EXTERNAL, A("0.0.0.0"), A("1.1.1.1"),
            -7, LsaAsExternal(A("0.0.0.0"), True, 20, A("0.0.0.0"), 99))
    for lsa in (s, e):
        out = Lsa.decode(Reader(lsa.encode()))
        assert out.body.__dict__ == lsa.body.__dict__


def test_db_desc_with_headers():
    h = make_router_lsa()
    h.encode()
    pkt = Packet(
        A("1.1.1.1"), A("0.0.0.1"),
        DbDesc(mtu=1500, options=Options.E,
               flags=DbDescFlags.I | DbDescFlags.M | DbDescFlags.MS,
               dd_seq_no=0xDD01, lsa_headers=[h]),
    )
    out = roundtrip_packet(pkt)
    assert out.body.flags == DbDescFlags.I | DbDescFlags.M | DbDescFlags.MS
    assert len(out.body.lsa_headers) == 1
    assert out.body.lsa_headers[0].key == h.key


def test_ls_request_update_ack_roundtrip():
    lsa = make_router_lsa()
    lsa.encode()
    req = Packet(A("1.1.1.1"), A("0.0.0.0"),
                 LsRequest([LsaKey(LsaType.ROUTER, A("2.2.2.2"), A("2.2.2.2"))]))
    upd = Packet(A("1.1.1.1"), A("0.0.0.0"), LsUpdate([lsa]))
    ack = Packet(A("1.1.1.1"), A("0.0.0.0"), LsAck([lsa]))
    assert roundtrip_packet(req).body.entries[0].type == LsaType.ROUTER
    out = roundtrip_packet(upd)
    assert out.body.lsas[0].key == lsa.key
    assert out.body.lsas[0].raw == lsa.raw
    assert roundtrip_packet(ack).body.lsa_headers[0].key == lsa.key


def test_lls_block_roundtrip():
    """RFC 5613 LLS data block on hellos (reference packet/lls.rs)."""
    from ipaddress import IPv4Address as A

    from holo_tpu.protocols.ospf.packet import (
        AuthCtx, AuthType, Hello, LLS_EOF_LR, LLS_EOF_RS, LlsBlock,
        Options, Packet,
    )

    h = Hello(A("255.255.255.0"), 10, Options.E | Options.L, 1, 40,
              A("0.0.0.0"), A("0.0.0.0"), [])
    p = Packet(A("1.1.1.1"), A("0.0.0.0"), h,
               lls=LlsBlock(eof=LLS_EOF_LR | LLS_EOF_RS))
    out = Packet.decode(p.encode())
    assert out.lls is not None
    assert out.lls.eof == (LLS_EOF_LR | LLS_EOF_RS)

    # Under cryptographic auth the LLS block follows the digest and its
    # checksum field is unused (RFC 5613 §2.2).
    auth = AuthCtx(type=AuthType.CRYPTOGRAPHIC, key=b"k", key_id=1, seqno=9)
    out = Packet.decode(p.encode(auth=auth), auth=auth)
    assert out.lls is not None and out.lls.eof == (LLS_EOF_LR | LLS_EOF_RS)

    # Corrupting the block must be detected.
    wire = bytearray(p.encode())
    wire[-1] ^= 0xFF
    import pytest

    from holo_tpu.utils.bytesbuf import DecodeError

    with pytest.raises(DecodeError):
        Packet.decode(bytes(wire))


def test_lls_restart_signal_on_gr_hellos():
    """A restarting router's hellos carry LLS RS; the helper records it."""
    from ipaddress import IPv4Address as A

    from holo_tpu.protocols.ospf.instance import (
        IfConfig, IfUpMsg, InstanceConfig, OspfInstance,
    )
    from holo_tpu.protocols.ospf.interface import IfType
    from ipaddress import IPv4Network as N

    from holo_tpu.protocols.ospf.packet import LLS_EOF_RS
    from holo_tpu.utils.netio import MockFabric
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    insts = {}
    for name, rid, addr in (("r1", "1.1.1.1", "10.0.0.1"),
                            ("r2", "2.2.2.2", "10.0.0.2")):
        inst = OspfInstance(name=name, config=InstanceConfig(router_id=A(rid)),
                            netio=fabric.sender_for(name))
        loop.register(inst, name=name)
        fabric.join("l", name, "e0", A(addr))
        inst.add_interface("e0", IfConfig(if_type=IfType.POINT_TO_POINT),
                           N("10.0.0.0/24"), A(addr))
        loop.send(name, IfUpMsg("e0"))
        insts[name] = inst
    loop.advance(60)
    r1, r2 = insts["r1"], insts["r2"]
    nbr = r2.areas[A("0.0.0.0")].interfaces["e0"].neighbors[A("1.1.1.1")]
    assert nbr.lls_eof is None

    r1.gr_restarting = True
    loop.advance(15)  # next hello interval
    assert nbr.lls_eof is not None and nbr.lls_eof & LLS_EOF_RS

"""VRRP master election/failover + IGMP querier/membership."""

from ipaddress import IPv4Address as A

from holo_tpu.protocols.igmp import (
    ALL_SYSTEMS,
    IgmpIfConfig,
    IgmpInstance,
    IgmpPacket,
    IgmpType,
)
from holo_tpu.protocols.vrrp import (
    VrrpConfig,
    VrrpInstance,
    VrrpPacket,
    VrrpState,
)
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def test_vrrp_packet_roundtrip_v2_v3():
    for version, adv in ((2, 1), (3, 100)):
        p = VrrpPacket(version, 7, 150, adv, [A("192.0.2.254")])
        out = VrrpPacket.decode(p.encode())
        assert (out.version, out.vrid, out.priority) == (version, 7, 150)
        assert out.addresses == [A("192.0.2.254")]


def mk_vrrp(loop, fabric, name, addr, prio):
    states = []
    inst = VrrpInstance(
        name,
        VrrpConfig(vrid=9, ifname="e0", priority=prio,
                   addresses=[A("192.0.2.254")]),
        A(addr),
        fabric.sender_for(name),
        on_state=lambda s: states.append(s),
    )
    loop.register(inst)
    fabric.join("lan", name, "e0", A(addr))
    return inst, states


def test_vrrp_election_and_failover():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    v1, s1 = mk_vrrp(loop, fabric, "v1", "192.0.2.1", prio=100)
    v2, s2 = mk_vrrp(loop, fabric, "v2", "192.0.2.2", prio=200)
    v1.startup()
    v2.startup()
    loop.advance(10)
    assert v2.state == VrrpState.MASTER
    assert v1.state == VrrpState.BACKUP

    # Master dies silently: backup takes over after master-down interval.
    loop.unregister("v2")
    loop.advance(5)
    assert v1.state == VrrpState.MASTER

    # Graceful shutdown propagates fast via priority-0 advert.
    v3, _ = mk_vrrp(loop, fabric, "v3", "192.0.2.3", prio=250)
    v3.startup()
    loop.advance(5)
    assert v3.state == VrrpState.MASTER and v1.state == VrrpState.BACKUP
    v3.shutdown()
    loop.advance(1.0)
    assert v1.state == VrrpState.MASTER  # skew-time takeover, not 3x advert


def test_igmp_membership_and_querier_election():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    q1 = IgmpInstance("q1", fabric.sender_for("q1"))
    q2 = IgmpInstance("q2", fabric.sender_for("q2"))
    loop.register(q1)
    loop.register(q2)
    fabric.join("lan", "q1", "e0", A("10.0.0.1"))
    fabric.join("lan", "q2", "e0", A("10.0.0.2"))
    q1.add_interface("e0", IgmpIfConfig(), A("10.0.0.1"))
    q2.add_interface("e0", IgmpIfConfig(), A("10.0.0.2"))
    loop.advance(5)
    # Lower address must win the querier election.
    assert q1.interfaces["e0"].querier is True
    assert q2.interfaces["e0"].querier is False

    # A host reports membership; both routers track it.
    report = IgmpPacket(IgmpType.REPORT_V2, 0, A("239.1.2.3")).encode()
    from holo_tpu.utils.netio import NetRxPacket

    loop.send("q1", NetRxPacket("e0", A("10.0.0.99"), ALL_SYSTEMS, report))
    loop.send("q2", NetRxPacket("e0", A("10.0.0.99"), ALL_SYSTEMS, report))
    loop.run_until_idle()
    assert A("239.1.2.3") in q1.interfaces["e0"].groups
    assert A("239.1.2.3") in q2.interfaces["e0"].groups

    # Leave -> last-member query -> fast expiry on the querier.
    leave = IgmpPacket(IgmpType.LEAVE, 0, A("239.1.2.3")).encode()
    loop.send("q1", NetRxPacket("e0", A("10.0.0.99"), ALL_SYSTEMS, leave))
    loop.advance(3)
    assert A("239.1.2.3") not in q1.interfaces["e0"].groups


def test_vrrp_yang_new_master_notification():
    """Reference holo-vrrp northbound/notification.rs:21-29: master
    transitions raise vrrp-new-master-event with the reason."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    v1, _ = mk_vrrp(loop, fabric, "v1", "192.0.2.1", prio=100)
    v2, _ = mk_vrrp(loop, fabric, "v2", "192.0.2.2", prio=200)
    notifs = []
    v1.notif_cb = notifs.append
    v1.startup()
    v2.startup()
    loop.advance(10)
    assert v1.state == VrrpState.BACKUP and not notifs
    loop.unregister("v2")
    loop.advance(5)
    assert v1.state == VrrpState.MASTER
    ev = [n["ietf-vrrp:vrrp-new-master-event"] for n in notifs
          if "ietf-vrrp:vrrp-new-master-event" in n]
    assert ev and ev[0]["master-ip-address"] == "192.0.2.1"
    assert ev[0]["new-master-reason"] == "no-response"


def test_vrrp_new_master_reason_preempted():
    """Preempting a live lower-priority master reports 'preempted', not
    'no-response' (the master never stopped advertising)."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    v1, _ = mk_vrrp(loop, fabric, "v1", "192.0.2.1", prio=100)
    v2, _ = mk_vrrp(loop, fabric, "v2", "192.0.2.2", prio=200)
    notifs = []
    v2.notif_cb = notifs.append
    v1.startup()
    loop.advance(10)
    assert v1.state == VrrpState.MASTER
    v2.startup()  # higher priority joins and preempts
    loop.advance(15)
    assert v2.state == VrrpState.MASTER
    ev = [n["ietf-vrrp:vrrp-new-master-event"] for n in notifs
          if "ietf-vrrp:vrrp-new-master-event" in n]
    assert ev and ev[-1]["new-master-reason"] == "preempted", ev

"""IS-IS conformance against the reference's own recorded expectations.

For every IS-IS conformance topology shipped with the reference
(SURVEY.md §4), the harness decodes the recorded PDUs with OUR codecs
(narrow TLV 2/128 and wide TLV 22/135 metrics, RFC 5308 IPv6, RFC 5120
multi-topology), runs OUR SPF/route pipeline per router per level, and
requires the computed RIB — IPv4 AND IPv6, including L1 ATT-bit default
routes and L1-over-L2 preference — to be bit-identical to the
reference's expected local-rib: all 38 routers across 6 topologies.
"""

from pathlib import Path

import pytest

from holo_tpu.tools.conformance_isis import (
    REFERENCE_CONFORMANCE_ISIS,
    run_topology,
)

pytestmark = pytest.mark.skipif(
    not REFERENCE_CONFORMANCE_ISIS.exists(),
    reason="reference conformance corpus not mounted",
)


def topo_dirs():
    if not REFERENCE_CONFORMANCE_ISIS.exists():
        return []
    return sorted(
        p.name for p in REFERENCE_CONFORMANCE_ISIS.iterdir() if p.is_dir()
    )


@pytest.mark.parametrize("backend", ["scalar", "tpu"])
@pytest.mark.parametrize("topo_name", topo_dirs())
def test_reference_topology_rib_conformance(topo_name, backend):
    """Both backends — the scalar oracle AND the tensor engine — must
    reproduce the reference's expected RIBs bit-identically."""
    factory = None
    if backend == "tpu":
        from holo_tpu.spf.backend import TpuSpfBackend

        factory = TpuSpfBackend
    results = run_topology(REFERENCE_CONFORMANCE_ISIS / topo_name, factory)
    assert results, "no routers loaded"
    failures = {rt: problems for rt, problems in results.items() if problems}
    assert not failures, "\n".join(
        f"{rt}: {p}" for rt, probs in failures.items() for p in probs
    )

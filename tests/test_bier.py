"""BIER underlay: bitstring math, OSPF BFR advertisement, BIRT/F-BM.

Reference: holo-utils/src/bier.rs, holo-routing/src/birt.rs,
holo-ospf/src/bier.rs.
"""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

import pytest

from holo_tpu.utils.bier import (
    BierCfg,
    BierError,
    BierSubDomainCfg,
    Birt,
    Bitstring,
)


def test_bitstring_math():
    b1 = Bitstring.from_bfr_id(1, 64)
    assert (b1.si, b1.bits) == (0, 1)
    b64 = Bitstring.from_bfr_id(64, 64)
    assert (b64.si, b64.bits) == (0, 1 << 63)
    b65 = Bitstring.from_bfr_id(65, 64)
    assert (b65.si, b65.bits) == (1, 1)
    u = b1.union(b64)
    assert u.bits == (1 << 63) | 1
    with pytest.raises(BierError):
        b1.union(b65)  # different set identifiers
    with pytest.raises(BierError):
        Bitstring.from_bfr_id(0, 64)
    with pytest.raises(BierError):
        Bitstring.from_bfr_id(1, 100)


def test_birt_fbm_aggregation():
    """BFERs behind the same neighbor share one forwarding bitmask."""
    synced = []
    birt = Birt(bift_sync=synced.append)
    birt.nbr_add(0, 2, A("2.2.2.2"), [64], A("10.0.0.2"), ifname="e0")
    birt.nbr_add(0, 3, A("3.3.3.3"), [64], A("10.0.0.2"), ifname="e0")
    birt.nbr_add(0, 4, A("4.4.4.4"), [64], A("10.0.0.9"), ifname="e1")
    bift = birt.compute_bift()
    fbm, bfrs, ifname = bift[(0, A("10.0.0.2"), 0, 64)]
    assert fbm.bits == (1 << 1) | (1 << 2)  # bfr-ids 2 and 3
    assert {b for b, _ in bfrs} == {2, 3}
    assert ifname == "e0"
    fbm4, _, _ = bift[(0, A("10.0.0.9"), 0, 64)]
    assert fbm4.bits == 1 << 3
    assert len(synced) == 3  # re-synced per change

    birt.nbr_del(0, 3, 64)
    bift = birt.compute_bift()
    fbm, _, _ = bift[(0, A("10.0.0.2"), 0, 64)]
    assert fbm.bits == 1 << 1


def test_ext_prefix_bier_roundtrip():
    from holo_tpu.protocols.ospf.packet import (
        decode_ext_prefix_bier,
        encode_ext_prefix_bier,
    )

    data = encode_ext_prefix_bier(N("2.2.2.2/32"), 0, 7, (64, 256))
    out = decode_ext_prefix_bier(data)
    assert out == (N("2.2.2.2/32"), 0, 0, 7, (64, 256))


def test_ospf_bier_underlay_populates_birt():
    """Three routers in a line; BIER sub-domain 0 everywhere.  r1 learns
    both BFERs' prefixes with their BFR-ids and the BIRT aggregates the
    F-BM through the shared next hop (r2)."""
    from holo_tpu.protocols.ospf.instance import (
        IfConfig, IfUpMsg, InstanceConfig, OspfInstance,
    )
    from holo_tpu.protocols.ospf.interface import IfType
    from holo_tpu.utils.netio import MockFabric
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)

    def bier_cfg(bfr_id, prefix):
        return BierCfg(sub_domains={0: BierSubDomainCfg(
            sd_id=0, bfr_id=bfr_id, bfr_prefix=N(prefix), encaps=(64,),
        )})

    routers = {}
    for name, rid, bfr_id in (("r1", "1.1.1.1", 1), ("r2", "2.2.2.2", 2),
                              ("r3", "3.3.3.3", 3)):
        inst = OspfInstance(
            name=name,
            config=InstanceConfig(
                router_id=A(rid), bier=bier_cfg(bfr_id, f"{rid}/32"),
            ),
            netio=fabric.sender_for(name),
        )
        loop.register(inst, name=name)
        routers[name] = inst

    cfg = IfConfig(if_type=IfType.POINT_TO_POINT)
    links = [("l12", "r1", "e0", "10.0.1.1", "r2", "w0", "10.0.1.2"),
             ("l23", "r2", "e1", "10.0.2.1", "r3", "w1", "10.0.2.2")]
    for link, an, aif, aaddr, bn, bif, baddr in links:
        net = N(aaddr + "/24", strict=False)
        routers[an].add_interface(aif, cfg, net, A(aaddr))
        routers[bn].add_interface(bif, cfg, net, A(baddr))
        fabric.join(link, an, aif, A(aaddr))
        fabric.join(link, bn, bif, A(baddr))
    # Loopback-ish stub for each BFR prefix.
    for name, rid in (("r1", "1.1.1.1"), ("r2", "2.2.2.2"), ("r3", "3.3.3.3")):
        routers[name].add_interface(
            f"lo-{name}", IfConfig(if_type=IfType.POINT_TO_POINT, passive=True),
            N(rid + "/32"), A(rid),
        )
    for name, inst in routers.items():
        for ifname in list(inst.areas[A("0.0.0.0")].interfaces):
            loop.send(name, IfUpMsg(ifname))
    loop.advance(120)

    r1 = routers["r1"]
    assert N("3.3.3.3/32") in r1.routes
    assert N("3.3.3.3/32") in r1.bier_routes
    info, _route = r1.bier_routes[N("3.3.3.3/32")]
    assert info.bfr_id == 3 and info.sd_id == 0 and 64 in info.bfr_bss

    # Feed the learned BFERs into a BIRT the way the routing provider
    # does (route nexthop + advertised BIER info).
    birt = Birt()
    for prefix, (info, route) in r1.bier_routes.items():
        nh = next(iter(route.nexthops), None)
        if nh is None or nh.addr is None:
            continue
        birt.nbr_add(info.sd_id, info.bfr_id, prefix.network_address,
                     info.bfr_bss, nh.addr, ifname=nh.ifname)
    bift = birt.compute_bift()
    # Both r2 and r3 are reached via r2 (10.0.1.2): one shared F-BM.
    key = (0, A("10.0.1.2"), 0, 64)
    assert key in bift
    fbm, bfrs, _ = bift[key]
    assert fbm.bits == (1 << 1) | (1 << 2)
    assert {b for b, _ in bfrs} == {2, 3}

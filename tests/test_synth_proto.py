"""Protocol-marshaled bench topologies (BASELINE configs 2+3): the
builders go through the real instance marshal paths and the engine
reproduces the scalar result bit-identically."""

import numpy as np

from holo_tpu.spf.backend import ScalarSpfBackend, TpuSpfBackend


def test_ospfv3_multiarea_builder_parity():
    from holo_tpu.spf.synth_proto import ospfv3_multiarea_topologies

    topos = ospfv3_multiarea_topologies(n_routers=200, n_areas=4, seed=3)
    assert len(topos) == 4
    for topo in topos:
        assert topo.n_vertices == 51  # root + 50 per area
        s = ScalarSpfBackend().compute(topo)
        t = TpuSpfBackend().compute(topo)
        assert np.array_equal(s.dist, t.dist)
        assert np.array_equal(s.nexthop_words, t.nexthop_words)


def test_isis_l1l2_builder_parity_and_ecmp():
    from holo_tpu.spf.synth_proto import isis_l1l2_topologies

    # The builder itself asserts the 64-way (here 16-way) ECMP fan-out
    # in the L2 instance's own route table.
    topos = isis_l1l2_topologies(n_l2=360, n_l1=40, ecmp_width=16, seed=2)
    assert len(topos) == 2
    for topo in topos:
        s = ScalarSpfBackend().compute(topo)
        t = TpuSpfBackend().compute(topo)
        assert np.array_equal(s.dist, t.dist)
        assert np.array_equal(s.nexthop_words, t.nexthop_words)

"""YANG text front-end: RFC 7950-subset parsing onto schema-lite nodes."""

import pytest

from holo_tpu.yang.parser import YangParseError, load_yang, parse_text
from holo_tpu.yang.schema import Schema, SchemaError

MODULE = """
module example-routing {
  yang-version 1.1;
  namespace "urn:example:routing";
  prefix exr;

  import ietf-inet-types { prefix inet; }

  typedef percentage { type uint8; }
  typedef route-pref { type uint32; }

  grouping timer-params {
    leaf hello-interval {
      type uint16;
      default 10;
      description "Seconds between hellos.";
    }
    leaf dead-interval { type uint32; default 40; }
  }

  container routing {
    description
      "Top-level routing configuration " +
      "(concatenated string argument).";
    leaf router-id { type inet:ip-address; }
    leaf preference { type route-pref; default 100; }
    leaf load { type percentage; }
    leaf mode {
      type enumeration {
        enum normal;
        enum stub { description "no externals"; }
        enum nssa;
      }
      default normal;
    }
    leaf-list export-protocol { type string; }
    list interface {
      key "name";
      leaf name { type string; }
      leaf prefix { type inet:ip-prefix; }
      leaf enabled { type boolean; default true; }
      uses timer-params;
      container statistics {
        config false;
        leaf tx-count { type uint32; }
      }
    }
  }
}
"""


def test_parse_and_mount_module():
    nodes = load_yang(MODULE)
    assert [n.name for n in nodes] == ["routing"]
    schema = Schema()
    schema.mount(nodes[0])
    # Types mapped, defaults applied, typedefs resolved.
    pref = schema.resolve("routing/preference")
    assert pref.type == "uint32" and pref.default == 100
    assert schema.resolve("routing/load").type == "uint8"
    mode = schema.resolve("routing/mode")
    assert mode.type == "enum" and mode.enum == ("normal", "stub", "nssa")
    assert mode.default == "normal"
    # Groupings expand inside the list; list keyed by "name".
    hi = schema.resolve("routing/interface[eth0]/hello-interval")
    assert hi.type == "uint16" and hi.default == 10
    assert schema.resolve("routing/interface[eth0]/prefix").type == "prefix"
    # config false propagates.
    stats = schema.resolve("routing/interface[eth0]/statistics")
    assert stats.config is False
    # Validation behaves like the built-in modules.
    assert mode.check("stub") == "stub"
    with pytest.raises(SchemaError):
        mode.check("bogus")
    with pytest.raises(SchemaError):
        schema.resolve("routing/load").check(300)  # uint8 range


def test_parser_error_reporting():
    with pytest.raises(YangParseError):
        parse_text("module broken { leaf x { type string; }")  # missing }
    with pytest.raises(YangParseError):
        parse_text("container no-module { }")
    with pytest.raises(YangParseError):
        load_yang("module m { container c { uses nope; } }")


def test_parse_reference_shaped_module():
    """A trimmed ietf-style module with the statements the reference's
    modules lean on (must/when/status parsed+skipped, unions, presence)."""
    text = """
    module ietf-example {
      namespace "urn:ietf:params:xml:ns:yang:ietf-example";
      prefix ex;
      organization "IETF";
      contact "WG";
      revision 2024-01-01 { description "initial"; }
      container control-plane {
        presence "enables the control plane";
        leaf id { type union { type uint32; type string; } }
        leaf status-word { type string; status deprecated; }
        list protocol {
          key "type";
          leaf type { type identityref { base rt:control-plane-protocol; } }
          leaf enabled { type boolean; mandatory true; }
        }
      }
    }
    """
    nodes = load_yang(text)
    schema = Schema()
    schema.mount(nodes[0])
    cp = schema.resolve("control-plane")
    assert cp.presence is True
    assert schema.resolve("control-plane/id").type == "string"  # union fallback
    en = schema.resolve("control-plane/protocol[static]/enabled")
    assert en.mandatory is True


def test_parse_all_reference_modules():
    """The parser must swallow the reference's ENTIRE module set (the
    104 modules it loads through libyang), with cross-module grouping
    and typedef resolution."""
    from pathlib import Path

    from holo_tpu.yang.parser import load_modules

    base = Path("/root/reference/holo-yang/modules")
    if not base.exists():
        pytest.skip("reference modules not mounted")
    files = sorted(base.rglob("*.yang"))
    assert len(files) >= 100
    mods = load_modules([f.read_text() for f in files])
    assert len(mods) == len(files)
    # The parsed ietf-routing mounts and resolves in our schema.
    from holo_tpu.yang.schema import Schema

    sch = Schema()
    for node in mods["ietf-routing"]:
        sch.mount(node)
    assert "routing" in sch.roots


def test_augments_and_deviations_apply_to_foreign_trees():
    """The reference applies its augmentations/ and deviations/ modules
    onto the ietf trees at context load (holo-yang/src/lib.rs) — our
    load_modules must graft and prune the same way."""
    from pathlib import Path

    from holo_tpu.yang.parser import load_modules

    base = Path("/root/reference/holo-yang/modules")
    if not base.exists():
        pytest.skip("reference modules not mounted")
    files = sorted(base.rglob("*.yang"))
    mods = load_modules([f.read_text() for f in files])

    # holo-ietf-routing-deviations prunes /rt:routing/rt:router-id and
    # the whole routing-state tree from ietf-routing.
    routing = next(
        n for n in mods["ietf-routing"] if n.name == "routing"
    )
    assert "router-id" not in routing.children
    assert "interfaces" not in routing.children
    assert not any(
        n.name == "routing-state" for n in mods["ietf-routing"]
    )
    # ...but the ribs tree survives with active-route pruned.
    ribs = routing.children["ribs"]
    rib = ribs.children["rib"]
    assert "active-route" not in rib.children

    # ietf-ospf grafts its whole tree into ietf-routing's
    # control-plane-protocol; holo-ospf then augments THAT grafted tree
    # (fixpoint application), e.g. the hostnames operational list.
    cpp = routing.children["control-plane-protocols"]
    proto = cpp.children["control-plane-protocol"]
    ospf = proto.children["ospf"]
    assert "ietf-spf-delay" in ospf.children["spf-control"].children
    assert "hostnames" in ospf.children, (
        "holo-ospf's augment onto the grafted ospf tree did not apply"
    )

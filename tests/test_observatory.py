"""Dispatch observatory (ISSUE 12): sketch core bounds, roofline
attribution, regression sentinel, explain CLI, relay watch.

Sketch contract tests pin the DDSketch guarantees the sentinel relies
on (relative-error quantiles, merge associativity, byte-identical
serialization); the integration tests drive the REAL dispatch path —
``TpuSpfBackend`` / ``FrrEngine`` under the armed observer — including
the fault-injected dispatch delay the sentinel must flag within one
storm, and the structural "disarmed path is one global check" gate the
``bench.py observatory_overhead`` stage's <2% paired-median rides on.
"""

from __future__ import annotations

import json
import math
import random

import numpy as np
import pytest

from holo_tpu import telemetry
from holo_tpu.pipeline.tuner import (
    EngineTuner,
    reset_engine_tuner,
)
from holo_tpu.resilience import faults
from holo_tpu.telemetry import flight, observatory, profiling, relay
from holo_tpu.telemetry.observatory import (
    DDSketch,
    DeterministicTimer,
    Observatory,
    RooflinePeaks,
)


@pytest.fixture(autouse=True)
def _reset_observatory_state():
    yield
    observatory.configure(enabled=False)
    profiling.set_device_profiling(False)
    profiling.set_stage_timer(None)
    reset_engine_tuner()
    flight.configure(entries=0)


# -- sketch core ---------------------------------------------------------


def _true_quantile(vals, q):
    s = sorted(vals)
    return s[round(q * (len(s) - 1))]


def test_sketch_quantile_relative_error_bounds():
    rng = random.Random(7)
    for dist in ("uniform", "lognormal"):
        sk = DDSketch(alpha=0.01)
        vals = []
        for _ in range(5000):
            v = (
                rng.uniform(1e-4, 10.0)
                if dist == "uniform"
                else math.exp(rng.gauss(-5.0, 2.0))
            )
            vals.append(v)
            sk.observe(v)
        for q in (0.1, 0.5, 0.9, 0.99):
            true = _true_quantile(vals, q)
            est = sk.quantile(q)
            # alpha relative error on the bucket + one rank of
            # discretization slack.
            assert abs(est - true) <= 2 * sk.alpha * true + 1e-12, (
                dist, q, est, true,
            )


def test_sketch_merge_matches_combined_and_serializes_identically():
    rng = random.Random(3)
    a_vals = [rng.uniform(1e-3, 1.0) for _ in range(400)]
    b_vals = [rng.uniform(1e-2, 5.0) for _ in range(300)]
    a, b, both = DDSketch(), DDSketch(), DDSketch()
    for v in a_vals:
        a.observe(v)
        both.observe(v)
    for v in b_vals:
        b.observe(v)
        both.observe(v)
    a.merge(b)
    assert a.count == both.count
    assert a.bins == both.bins
    # Serialization is canonical up to float-sum association: compare
    # everything except the order-dependent running sum.
    da, db = a.to_doc(), both.to_doc()
    assert abs(da.pop("sum") - db.pop("sum")) < 1e-9
    assert da == db


def test_sketch_merge_associative():
    rng = random.Random(11)
    chunks = [
        [rng.uniform(1e-4, 2.0) for _ in range(150)] for _ in range(3)
    ]

    def sk(vals):
        s = DDSketch()
        for v in vals:
            s.observe(v)
        return s

    left = sk(chunks[0]).merge(sk(chunks[1])).merge(sk(chunks[2]))
    right = sk(chunks[0]).merge(sk(chunks[1]).merge(sk(chunks[2])))
    assert left.bins == right.bins
    assert left.count == right.count
    assert left.quantile(0.5) == right.quantile(0.5)


def test_sketch_bounded_bins_collapse_preserves_count_and_tail():
    sk = DDSketch(alpha=0.01, max_bins=64)
    rng = random.Random(5)
    vals = [10.0 ** rng.uniform(-9, 3) for _ in range(4000)]
    for v in vals:
        sk.observe(v)
    assert len(sk.bins) <= 64
    assert sk.count == len(vals)
    # Tail accuracy survives the low-bucket collapse.
    true99 = _true_quantile(vals, 0.99)
    assert abs(sk.quantile(0.99) - true99) <= 2 * sk.alpha * true99


def test_sketch_doc_roundtrip_and_alpha_mismatch():
    sk = DDSketch(alpha=0.02)
    for v in (0.001, 0.01, 0.1, 0.1, 1.0):
        sk.observe(v)
    back = DDSketch.from_doc(json.loads(sk.serialize()))
    assert back.serialize() == sk.serialize()
    assert back.quantile(0.5) == sk.quantile(0.5)
    with pytest.raises(ValueError):
        sk.merge(DDSketch(alpha=0.01))


def test_sketch_zero_and_negative_values():
    sk = DDSketch()
    sk.observe(0.0)
    sk.observe(-1.0)  # clock step backwards clamps to 0
    sk.observe(1.0)
    assert sk.zero == 2
    assert sk.quantile(0.0) == 0.0
    assert sk.count == 3


# -- observe path / keying ----------------------------------------------


def _spf_workload(obs_reps=4, topo_seed=1):
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.spf.synth import grid_topology

    topo = grid_topology(5, 5, seed=topo_seed)
    be = TpuSpfBackend()
    for _ in range(obs_reps):
        be.compute(topo)
    return topo, be


def test_observe_keys_carry_engine_bucket_kind():
    obs = observatory.configure(check_every=0)
    profiling.set_device_profiling(True)
    _spf_workload()
    keys = list(obs._sketches)
    sites = {k[0] for k in keys}
    assert "spf.one" in sites
    one = [k for k in keys if k[0] == "spf.one" and k[1] == "device"]
    assert one, keys
    site, stage, engine, bucket, kind = one[0]
    assert engine == "seq" and kind == "one"
    assert isinstance(bucket, tuple) and bucket[0] >= 25  # pow2(V) >= V


def test_observe_requires_no_device_profiling():
    # The observatory stays always-on even with the histogram/exemplar
    # machinery off: stage() times for the observer alone.
    obs = observatory.configure(check_every=0)
    assert not profiling.device_profiling()
    _spf_workload()
    assert any(k[0] == "spf.one" for k in obs._sketches)
    # ... and record_cost captured the roofline numerators too.
    assert obs._costs


def test_observe_skips_per_device_skew_rows():
    obs = observatory.configure(check_every=0)
    obs._observe("spf.one", "device", "3", 0.5)
    assert not obs._sketches
    obs._observe("spf.one", "device", "-", 0.5)
    assert len(obs._sketches) == 1


def test_disarmed_path_is_one_global_check():
    # Disarmed + unprofiled, stage() must return before its first
    # timer read — the structural form of the observatory_overhead
    # gate's "disarmed cost is one global check per observe".
    assert observatory.active() is None
    assert not profiling.observing()

    def boom():
        raise AssertionError("stage timed on the disarmed path")

    profiling.set_stage_timer(boom)
    try:
        with profiling.stage("x.y", "marshal"):
            pass
    finally:
        profiling.set_stage_timer(None)
    # ... and the dispatch-context wrapper is the shared null context
    # (no per-dispatch allocation while disarmed).
    assert (
        profiling.dispatch_context(kind="one")
        is profiling.dispatch_context(kind="whatif")
    )


def test_frr_dispatch_feeds_frr_keys_and_roofline():
    from holo_tpu.frr.manager import FrrEngine
    from holo_tpu.spf.synth import grid_topology

    obs = observatory.configure(check_every=0)
    profiling.set_device_profiling(True)
    FrrEngine("tpu").compute(grid_topology(4, 4, seed=2))
    assert any(
        k[0] == "frr.batch" and k[2] == "frr" for k in obs._sketches
    )
    rows = [r for r in obs.roofline() if r["site"] == "frr.batch"]
    assert rows and rows[0]["engine"] == "frr"


# -- determinism ---------------------------------------------------------


def _deterministic_run():
    obs = observatory.configure(check_every=4)
    profiling.set_stage_timer(DeterministicTimer())
    profiling.set_device_profiling(True)
    _spf_workload(obs_reps=6)
    blob = obs.serialize()
    report = json.dumps(obs.report(), sort_keys=True)
    profiling.set_stage_timer(None)
    profiling.set_device_profiling(False)
    observatory.configure(enabled=False)
    return blob, report


def test_byte_identical_serialization_across_same_seed_runs():
    b1, r1 = _deterministic_run()
    b2, r2 = _deterministic_run()
    assert b1 == b2
    assert r1 == r2
    assert json.loads(r1)["timing"] == "deterministic"


# -- roofline attribution ------------------------------------------------


def test_roofline_verdicts_from_ridge_point():
    obs = Observatory()
    # Gather-like kernel: far more bytes than flops -> memory-bound.
    obs.note_cost("spf.one", "one", "seq", ("b",), {
        "flops": 1e6, "bytes": 1e7,
    })
    # Contraction-like kernel: AI above the CPU ridge (5 flop/B).
    obs.note_cost("spf.one", "one", "tropical", ("b",), {
        "flops": 1e9, "bytes": 1e7,
    })
    rows = {r["engine"]: r for r in obs.roofline()}
    assert rows["seq"]["verdict"] == "memory-bound"
    assert rows["tropical"]["verdict"] == "compute-bound"
    # No device sketch yet: verdict present, achieved rates absent.
    assert "achieved_flops_per_sec" not in rows["seq"]


def test_roofline_achieved_rates_join_device_sketch():
    obs = Observatory(check_every=0)
    key = ("spf.one", "device", "seq", ("b",), "one")
    for _ in range(10):
        obs._sketches.setdefault(key, DDSketch()).observe(0.01)
    obs.note_cost("spf.one", "one", "seq", ("b",), {
        "flops": 1e6, "bytes": 1e7,
    })
    row = obs.roofline()[0]
    p50 = row["device_p50_s"]
    assert row["achieved_flops_per_sec"] == pytest.approx(1e6 / p50)
    assert row["achieved_bytes_per_sec"] == pytest.approx(1e7 / p50)
    # Memory-bound bucket: the attainable ceiling is AI * peak_bytes.
    attainable = row["ai_flops_per_byte"] * obs.peaks.bytes_per_sec
    assert row["roofline_fraction"] == pytest.approx(
        (1e6 / p50) / attainable, rel=1e-6
    )


def test_roofline_peaks_config_moves_the_ridge():
    # A machine with huge bandwidth relative to flops classifies the
    # same kernel compute-bound.
    obs = Observatory(peaks={"flops": 1e9, "bytes": 1e12, "name": "hbm"})
    obs.note_cost("s", "k", "e", ("b",), {"flops": 1e6, "bytes": 1e7})
    assert obs.roofline()[0]["verdict"] == "compute-bound"
    assert obs.peaks.source == "hbm"
    # The default is the honest CPU guess, labeled for the dead relay.
    assert "relay: not-used" in RooflinePeaks().source


def test_real_gather_dispatch_classified_memory_bound():
    obs = observatory.configure(check_every=0)
    profiling.set_device_profiling(True)
    _spf_workload()
    rows = [
        r
        for r in obs.roofline()
        if r["site"] == "spf.one" and r["engine"] == "seq"
    ]
    assert rows and rows[0]["verdict"] == "memory-bound"
    assert rows[0]["ai_flops_per_byte"] < obs.peaks.ridge


def test_cost_centers_ranked_by_total():
    obs = Observatory(check_every=0)
    k1 = ("a", "device", "e", "-", "k")
    k2 = ("b", "device", "e", "-", "k")
    for _ in range(3):
        obs._sketches.setdefault(k1, DDSketch()).observe(0.001)
    obs._sketches.setdefault(k2, DDSketch()).observe(1.0)
    rows = obs.cost_centers()
    assert rows[0]["site"] == "b" and rows[1]["site"] == "a"
    assert obs.cost_centers(top=1) == rows[:1]


# -- regression sentinel -------------------------------------------------


def _feed(obs, key, value, n):
    for _ in range(n):
        obs._observe(key[0], key[1], "-", value)


def test_sentinel_seeds_then_stays_silent(tmp_path):
    led = tmp_path / "ledger.json"
    obs = Observatory(check_every=4, ledger_path=led)
    _feed(obs, ("spf.one", "device"), 0.010, 16)
    assert obs.sentinel()["flags"] == 0
    assert obs.sentinel()["seeded"] >= 1
    # Persistence happens at checkpoint boundaries, never as a disk
    # write on the observing (dispatch) thread.
    assert not led.exists()
    obs.checkpoint()
    doc = json.loads(led.read_text())
    assert any("spf.one/device" in k for k in doc)
    # Fresh instrument over the persisted ledger, same latencies:
    # silent (the acceptance's "clean ledger-seeded run").
    obs2 = Observatory(check_every=4, ledger_path=led)
    _feed(obs2, ("spf.one", "device"), 0.010, 16)
    assert obs2.sentinel()["flags"] == 0
    assert obs2.sentinel()["seeded"] == 0


def test_sentinel_flags_drift_and_latches_once(tmp_path):
    led = tmp_path / "ledger.json"
    obs = Observatory(check_every=4, ledger_path=led)
    _feed(obs, ("spf.one", "device"), 0.010, 8)   # seed ~10ms
    _feed(obs, ("spf.one", "device"), 0.100, 32)  # 10x regression
    s = obs.sentinel()
    assert s["flags"] >= 1
    assert any("spf.one/device" in r for r in s["regressed"])
    # The latch fires on the TRANSITION, not per check.
    assert s["flags"] <= 2  # p50 + p99 at most once each


def test_sentinel_ratchets_improvements(tmp_path):
    led = tmp_path / "ledger.json"
    obs = Observatory(check_every=4, ledger_path=led)
    _feed(obs, ("spf.one", "device"), 0.100, 8)
    obs.checkpoint()
    seeded = json.loads(led.read_text())
    key, ent = next(iter(seeded.items()))
    obs2 = Observatory(check_every=4, ledger_path=led)
    _feed(obs2, ("spf.one", "device"), 0.050, 16)
    assert obs2.sentinel()["flags"] == 0
    obs2.checkpoint()
    ratcheted = json.loads(led.read_text())
    assert ratcheted[key]["p50"] < ent["p50"]
    assert obs2.sentinel()["ratcheted"] >= 1


def test_sentinel_corrupt_ledger_reseeds(tmp_path):
    led = tmp_path / "ledger.json"
    led.write_text("{not json")
    obs = Observatory(check_every=4, ledger_path=led)
    _feed(obs, ("spf.one", "device"), 0.010, 8)
    assert obs.sentinel()["seeded"] >= 1
    obs.checkpoint()
    assert json.loads(led.read_text())  # rewritten clean


def test_sentinel_flags_injected_dispatch_delay():
    """The acceptance scenario at unit scale: a clean seeded baseline,
    then a fault-injected dispatch delay (resilience/faults.py) — the
    sentinel flags the slowed bucket, emits the flight-ring event and
    the counter, while the dispatch itself keeps SUCCEEDING (warn-only:
    no breaker, no fallback)."""
    flight.configure(entries=512)
    obs = observatory.configure(check_every=4)
    profiling.set_device_profiling(True)
    topo, be = _spf_workload(obs_reps=12)
    assert obs.sentinel()["flags"] == 0
    before = telemetry.snapshot(prefix="holo_observatory_regressions")
    with faults.inject(
        faults.FaultPlan(dispatch_delay={"spf.dispatch": 0.02})
    ) as inj:
        for _ in range(12):
            res = be.compute(topo)
            assert res.dist is not None  # still succeeding
        assert inj.injected.get("delay:spf.dispatch", 0) >= 12
    s = obs.sentinel()
    assert s["flags"] >= 1
    after = telemetry.snapshot(prefix="holo_observatory_regressions")
    assert sum(after.values()) > sum(before.values())
    kinds = {e[1] for e in flight.recorder().snapshot_ring()
             if e[0] == "event"}
    assert "observatory-regression" in kinds
    assert be.breaker.snapshot()["state"] == "closed"


def test_sentinel_flags_slowed_bucket_within_one_storm(tmp_path):
    """Storm-scale acceptance: seed the ledger from a clean seeded
    storm, then re-run the same storm with a dispatch delay injected —
    the sentinel must flag within that one storm, and the clean run
    must have stayed silent."""
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.spf.synth_storm import run_convergence_storm

    led = tmp_path / "storm-ledger.json"
    obs = observatory.configure(check_every=4, ledger_path=led)
    profiling.set_device_profiling(True)
    run_convergence_storm(
        n_routers=40, events=16, seed=5, spf_backend=TpuSpfBackend()
    )
    assert obs.checkpoint()["flags"] == 0  # clean, ledger-seeded
    obs2 = observatory.configure(check_every=4, ledger_path=led)
    with faults.inject(
        faults.FaultPlan(
            drop_prob=0.10, dispatch_delay={"spf.dispatch": 0.03}
        )
    ):
        run_convergence_storm(
            n_routers=40, events=16, seed=5, spf_backend=TpuSpfBackend()
        )
    assert obs2.sentinel()["flags"] >= 1
    assert any("spf.one" in r for r in obs2.sentinel()["regressed"])


def test_delaypoint_disarmed_is_noop():
    faults.delaypoint("spf.dispatch")  # no injector armed: no-op
    with faults.inject(faults.FaultPlan()) as inj:
        faults.delaypoint("spf.dispatch")  # no delay planned: no-op
    assert not inj.injected


# -- surfaces: provider leaf, relay watch, CLI, tuner ledger -------------


def test_provider_leaf_carries_observatory_and_relay():
    from holo_tpu.telemetry.provider import TelemetryStateProvider

    obs = observatory.configure(check_every=0)
    obs._observe("spf.one", "device", "-", 0.01)
    relay.note_probe(False, error="probe timeout after 150s")
    state = TelemetryStateProvider().get_state()["holo-telemetry"]
    assert state["observatory"]["sketches"] == 1
    assert state["observatory"]["sentinel"]["flags"] == 0
    assert state["relay"]["status"] == "down"
    assert "timeout" in state["relay"]["last_error"]
    names = {m["name"].split("{")[0] for m in state["metric"]}
    assert "holo_relay_up" in names
    assert "holo_relay_probes_total" in names


def test_relay_watch_gauge_and_summary():
    relay.note_probe(True, took_s=1.2)
    assert relay.status()["status"] == "up"
    snap = telemetry.snapshot(prefix="holo_relay_up")
    assert snap["holo_relay_up"] == 1.0
    relay.note_probe(False, error="wedged")
    snap = telemetry.snapshot(prefix="holo_relay_up")
    assert snap["holo_relay_up"] == 0.0
    s = relay.summary(False, [{"ok": False, "error": "wedged"}])
    assert s == {"status": "down", "probes": 1, "last_error": "wedged"}
    assert relay.not_used() == "not-used"
    assert relay.not_used("forced mesh") == "not-used (forced mesh)"


def test_explain_cli_json_byte_identical(capsys):
    from holo_tpu.tools.cli import main as cli_main

    argv = ["explain", "--k", "6", "--batch", "4", "--reps", "4",
            "--json"]
    assert cli_main(argv) == 0
    out1 = capsys.readouterr().out
    assert cli_main(argv) == 0
    out2 = capsys.readouterr().out
    assert out1 == out2
    doc = json.loads(out1)
    assert doc["timing"] == "deterministic"
    assert doc["cost_centers"] and doc["roofline"]
    for row in doc["roofline"]:
        assert row["verdict"] in ("memory-bound", "compute-bound")
    # Gather engines at this scale: memory-bound, with quantiles.
    gather = [r for r in doc["roofline"] if r["site"] == "spf.one"]
    assert gather and all(
        r["verdict"] == "memory-bound" for r in gather
    )
    assert doc["tuner"], "win/loss ledger rides the report"
    # The CLI disarmed everything on exit.
    assert observatory.active() is None
    assert not profiling.device_profiling()
    assert not profiling.stage_timer_overridden()


def test_explain_cli_text_render(capsys):
    from holo_tpu.tools.cli import main as cli_main

    assert cli_main(
        ["explain", "--k", "6", "--batch", "4", "--reps", "4",
         "--top", "5"]
    ) == 0
    out = capsys.readouterr().out
    assert "top 5 cost centers" in out
    assert "memory-bound" in out
    assert "engine tuner win/loss ledger" in out
    assert "sentinel:" in out
    assert "relay: not-used" in out  # the honest CPU peak label


def test_shared_table_renderer_and_top(capsys):
    from holo_tpu.tools.cli import _print_table, _snapshot_cost_rows

    rows = _snapshot_cost_rows(
        {
            "fast": 1.0,
            "hist": {"count": 4, "sum": 9.5},
            "slow": 3.0,
        }
    )
    assert [r[0] for r in rows] == ["hist", "slow", "fast"]
    _print_table(("name", "count", "total"), rows, top=2)
    out = capsys.readouterr().out.splitlines()
    assert len(out) == 3  # header + top 2
    assert out[1].startswith("  hist")


def test_tuner_ledger_explains_win_basis():
    t = EngineTuner(engines=("packed", "fused"))
    bucket = (64, 128, 1, None, 1)
    t.cost_prior("one", bucket, "packed", {"flops": 2e6, "bytes": 1e6})
    t.cost_prior("one", bucket, "fused", {"flops": 1e6, "bytes": 5e6})
    for _ in range(3):
        t.observe("one", bucket, "packed", 0.001)
        t.observe("one", bucket, "fused", 0.002)
    rows = t.ledger()
    assert rows[0]["winner"] == "packed"
    assert rows[0]["basis"] == "packed beat fused on bytes"
    assert rows[0]["engines"]["fused"]["median_ms"] == 2.0


def test_tuner_ledger_mp_bucket_reports_measured_engine():
    t = EngineTuner()
    bucket = (64, 128, 1, None, 2)
    t.observe("one", bucket, "mp", 0.001)
    row = t.ledger()[0]
    assert row["winner"] == "mp"
    assert row["basis"] == "only measured engine"


def test_observatory_stats_leaf_shape():
    obs = observatory.configure(check_every=0)
    obs._observe("spf.one", "device", "-", 0.01)
    s = obs.stats()
    assert s["sketches"] == 1 and s["observations"] == 1
    assert "relay: not-used" in s["peaks-source"]
    snap = telemetry.snapshot(prefix="holo_observatory_sketches")
    assert snap["holo_observatory_sketches"] == 1.0

"""Unified telemetry subsystem (ISSUE 2): registry concurrency,
Prometheus exposition golden, gNMI Get/Subscribe of telemetry leaves,
SPF recompile-counter flatness, span tracing + log correlation, gNMI
subscriber overflow hardening, and event-recorder latency stamps."""

import json
import queue
import socket
import threading

import numpy as np
import pytest

from holo_tpu import telemetry
from holo_tpu.telemetry.prometheus import render_text, start_http_server
from holo_tpu.telemetry.registry import MetricsRegistry


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# -- registry core


def test_registry_concurrency_exact_totals():
    """Hammer one counter family + histogram from threads; totals must
    be exact (no lost updates)."""
    reg = MetricsRegistry()
    c = reg.counter("holo_t_hits_total", "hits", ("worker",))
    h = reg.histogram("holo_t_lat_seconds", "lat", buckets=(0.5, 1.0))
    g = reg.gauge("holo_t_depth")
    n_threads, n_iter = 8, 5000

    def work(i):
        child = c.labels(worker=str(i % 2))
        for _ in range(n_iter):
            child.inc()
            h.observe(1.0)
            g.inc()

    threads = [
        threading.Thread(target=work, args=(i,)) for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = sum(child.value for _, child in c.children())
    assert total == n_threads * n_iter
    assert c.labels(worker="0").value == n_threads * n_iter / 2
    assert h.count == n_threads * n_iter
    assert h.sum == float(n_threads * n_iter)
    assert g.value == n_threads * n_iter
    # Cumulative buckets are consistent: everything fell in le=1.0.
    cum = dict(h.cumulative())
    assert cum[1.0] == h.count and cum[float("inf")] == h.count


def test_registry_kind_conflict_and_disable():
    reg = MetricsRegistry()
    reg.counter("holo_t_x_total")
    with pytest.raises(ValueError):
        reg.gauge("holo_t_x_total")
    c = reg.counter("holo_t_y_total")
    telemetry.set_enabled(False)
    try:
        c.inc()
        assert c.value == 0.0  # disabled = no-op
    finally:
        telemetry.set_enabled(True)
    c.inc(2)
    assert c.value == 2.0


def test_prometheus_exposition_golden():
    """Exact text-format golden: HELP/TYPE blocks, label escaping,
    histogram bucket expansion with +Inf, integer formatting."""
    reg = MetricsRegistry()
    c = reg.counter("holo_g_ops_total", "operations", ("op",))
    c.labels(op="add").inc(3)
    c.labels(op='we"ird').inc()
    reg.gauge("holo_g_depth", "queue depth").set(2.5)
    h = reg.histogram("holo_g_lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(10.0)
    expected = (
        "# HELP holo_g_depth queue depth\n"
        "# TYPE holo_g_depth gauge\n"
        "holo_g_depth 2.5\n"
        "# HELP holo_g_lat_seconds latency\n"
        "# TYPE holo_g_lat_seconds histogram\n"
        'holo_g_lat_seconds_bucket{le="0.1"} 1\n'
        'holo_g_lat_seconds_bucket{le="1"} 2\n'
        'holo_g_lat_seconds_bucket{le="+Inf"} 3\n'
        "holo_g_lat_seconds_sum 10.55\n"
        "holo_g_lat_seconds_count 3\n"
        "# HELP holo_g_ops_total operations\n"
        "# TYPE holo_g_ops_total counter\n"
        'holo_g_ops_total{op="add"} 3\n'
        'holo_g_ops_total{op="we\\"ird"} 1\n'
    )
    assert render_text(reg) == expected


def test_prometheus_http_endpoint():
    import urllib.request

    reg = MetricsRegistry()
    reg.counter("holo_h_pings_total").inc(4)
    server = start_http_server(reg, "127.0.0.1:0")
    try:
        port = server.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics"
        ).read().decode()
        assert "holo_h_pings_total 4" in body
        with pytest.raises(Exception):
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope")
    finally:
        server.shutdown()
        server.server_close()


# -- span tracer


def test_tracer_nesting_and_chrome_export():
    tr = telemetry.tracer()
    before = len(tr.spans())
    assert telemetry.current_span_id() is None
    with telemetry.span("outer", instance="ospfv2") as outer_id:
        assert telemetry.current_span_id() == outer_id
        assert telemetry.current_instance() == "ospfv2"
        with telemetry.span("inner", batch=4) as inner_id:
            assert telemetry.current_span_id() == inner_id
            assert telemetry.current_instance() == "ospfv2"  # inherited
    assert telemetry.current_span_id() is None
    spans = tr.spans()[before:]
    by_name = {s.name: s for s in spans}
    assert by_name["inner"].parent_id == by_name["outer"].span_id
    doc = tr.to_chrome_trace()
    evs = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
    assert evs["inner"]["args"]["parent_id"] == by_name["outer"].span_id
    assert evs["outer"]["args"]["instance"] == "ospfv2"
    assert evs["outer"]["dur"] >= evs["inner"]["dur"]
    json.dumps(doc)  # perfetto-loadable = valid JSON


# -- SPF dispatch instrumentation


def test_spf_dispatch_recompile_counter_flat():
    """Same-shape re-runs must NOT count as recompiles — the whole point
    of the counter is to catch silent recompile storms."""
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.spf.synth import grid_topology

    topo = grid_topology(4, 4, seed=1)
    backend = TpuSpfBackend()

    def compiles():
        snap = telemetry.snapshot(prefix="holo_spf_jit_compiles_total")
        return snap.get("holo_spf_jit_compiles_total{kind=one}", 0.0)

    base = compiles()
    r1 = backend.compute(topo)
    assert compiles() == base + 1  # first shape: one compile
    r2 = backend.compute(topo)
    r3 = backend.compute(topo)
    assert compiles() == base + 1  # flat across same-shape re-runs
    assert np.array_equal(r1.dist, r2.dist) and np.array_equal(r2.dist, r3.dist)
    hits = telemetry.snapshot(prefix="holo_spf_jit_cache_hits_total")
    assert hits.get("holo_spf_jit_cache_hits_total{kind=one}", 0.0) >= 2
    # Dispatch wall-time histogram advanced once per compute call.
    disp = telemetry.snapshot(prefix="holo_spf_dispatch_seconds")
    assert (
        disp["holo_spf_dispatch_seconds{backend=tpu,kind=one}"]["count"] >= 3
    )


# -- RIB churn + FRR flip counters


def test_rib_churn_and_backup_flip_counters():
    from ipaddress import IPv4Address as A
    from ipaddress import IPv4Network as N

    from holo_tpu.routing.rib import MockKernel, RibManager
    from holo_tpu.utils.ibus import Ibus
    from holo_tpu.utils.runtime import EventLoop, VirtualClock
    from holo_tpu.utils.southbound import Nexthop, Protocol, RouteMsg

    def snap():
        return telemetry.snapshot(prefix="holo_rib")

    loop = EventLoop(clock=VirtualClock())
    rib = RibManager(Ibus(loop), MockKernel())
    loop.register(rib)
    before = snap()
    p = N("10.1.0.0/16")
    primary = Nexthop(addr=A("10.0.0.2"), ifname="e0")
    backup = Nexthop(addr=A("10.0.1.2"), ifname="e1")
    rib.route_add(
        RouteMsg(
            Protocol.OSPFV2, p, 110, 20, frozenset({primary}),
            backups={primary: backup},
        )
    )
    rib.route_add(
        RouteMsg(
            Protocol.OSPFV2, p, 110, 10, frozenset({primary}),
            backups={primary: backup},
        )
    )
    assert rib.local_repair("e0") == 1
    after = snap()

    def delta(name):
        return after.get(name, 0.0) - before.get(name, 0.0)

    assert delta("holo_rib_route_ops_total{op=add}") == 1
    assert delta("holo_rib_route_ops_total{op=replace}") == 1
    assert delta("holo_rib_backup_flips_total") == 1
    assert delta("holo_rib_kernel_installs_total{op=repair}") == 1
    assert after.get("holo_rib_prefixes") >= 1
    rib.local_restore("e0")
    assert (
        telemetry.snapshot(prefix="holo_rib").get(
            "holo_rib_backup_restores_total", 0.0
        )
        - before.get("holo_rib_backup_restores_total", 0.0)
        == 1
    )


# -- gNMI: telemetry leaves over Get/Subscribe, subscriber hardening


def test_gnmi_get_and_subscribe_telemetry_leaf():
    import holo_tpu.daemon.gnmi_server as gs
    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    marker = telemetry.counter(
        "holo_e2e_marker_total", "end-to-end visibility marker"
    )
    marker.inc(11)
    loop = EventLoop(clock=VirtualClock())
    d = Daemon(loop=loop, name="tele")
    port = free_port()
    server = gs.serve_gnmi(d, f"127.0.0.1:{port}")
    try:
        cli = gs.GnmiClient(f"127.0.0.1:{port}")
        # Get STATE at the telemetry subtree: live metric leaves.
        get = gs.pb.GetRequest(type=gs.pb.GetRequest.STATE)
        get.path.add().CopyFrom(gs.str_to_path("holo-telemetry"))
        out = cli.Get(get)
        payload = json.loads(out.notification[0].update[0].val.json_ietf_val)
        metrics = {
            m["name"]: m["value"]
            for m in payload["state"]["holo-telemetry"]["metric"]
        }
        assert metrics["holo_e2e_marker_total"] == 11.0
        # The SPF dispatch signal set is registered (instrumented paths
        # import at module load even before traffic flows).
        assert any(n.startswith("holo_spf_") for n in metrics)
        # Subscribe: the initial sync snapshot carries the same leaves.
        sub = gs.pb.SubscribeRequest()
        sub.subscribe.mode = gs.pb.SubscriptionList.ONCE
        msgs = list(cli.Subscribe(iter([sub])))
        snap = json.loads(msgs[0].update.update[0].val.json_ietf_val)
        names = {m["name"] for m in snap["holo-telemetry"]["metric"]}
        assert "holo_e2e_marker_total" in names
    finally:
        server.stop(grace=0)


def test_gnmi_subscriber_overflow_drop_counter_and_safe_removal():
    """A stalled subscriber costs counted drops, never unbounded memory;
    removal is idempotent (a double remove must not raise)."""
    import holo_tpu.daemon.gnmi_server as gs

    svc = gs.GnmiService(daemon=None)
    q: queue.Queue = queue.Queue(maxsize=2)
    svc._add_subscriber(q)
    drops0 = telemetry.snapshot(prefix="holo_gnmi").get(
        "holo_gnmi_subscribe_dropped_total", 0.0
    )
    for i in range(5):
        svc._fanout(f"notif-{i}")
    assert q.qsize() == 2  # bounded: the stall cannot grow memory
    snap = telemetry.snapshot(prefix="holo_gnmi")
    assert snap["holo_gnmi_subscribe_dropped_total"] - drops0 == 3
    svc._remove_subscriber(q)
    svc._remove_subscriber(q)  # exception-safe double removal
    # Copy-on-write snapshot (ISSUE 11): the subscriber table is an
    # immutable tuple so _fanout's lock hold is O(1).
    assert svc._subscribers == ()
    assert snap["holo_gnmi_subscribers"] == 1.0
    assert (
        telemetry.snapshot(prefix="holo_gnmi")["holo_gnmi_subscribers"] == 0.0
    )


def test_acceptance_daemon_ospf_frr_metrics_over_both_exports():
    """ISSUE 2 acceptance: a daemon pair running OSPF (tpu backend) with
    fast-reroute converges, and the daemon exposes live metrics over
    BOTH the Prometheus endpoint and gNMI Subscribe — including SPF
    dispatch timing, jit recompile count, and padded-slot occupancy."""
    import urllib.request
    from ipaddress import ip_address

    import holo_tpu.daemon.gnmi_server as gs
    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.utils.netio import MockFabric
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="m1")
    d2 = Daemon(loop=loop, netio=fabric, name="m2")
    fabric.join("l12", "m1.ospfv2", "eth0", ip_address("10.0.12.1"))
    fabric.join("l12", "m2.ospfv2", "eth0", ip_address("10.0.12.2"))
    for d, rid, addr in [
        (d1, "1.1.1.1", "10.0.12.1/30"),
        (d2, "2.2.2.2", "10.0.12.2/30"),
    ]:
        cand = d.candidate()
        cand.set("interfaces/interface[eth0]/enabled", "true")
        cand.set("interfaces/interface[eth0]/address", [addr])
        base = "routing/control-plane-protocols/ospfv2"
        cand.set(f"{base}/router-id", rid)
        cand.set(f"{base}/spf-control/backend", "tpu")
        cand.set(f"{base}/fast-reroute/lfa", "true")
        cand.set(
            f"{base}/area[0.0.0.0]/interface[eth0]/interface-type",
            "point-to-point",
        )
        d.commit(cand)
    loop.advance(60)
    assert d1.routing.instances["ospfv2"].spf_run_count > 0

    needed = (
        "holo_spf_dispatch_seconds",  # SPF dispatch timing
        "holo_spf_jit_compiles_total",  # recompile count
        "holo_spf_ell_occupancy",  # padded-slot occupancy
        "holo_frr_dispatch_seconds",
        "holo_frr_pad_occupancy",
        "holo_ospf_packets_total",
        "holo_ospf_nbr_transitions_total",
    )
    # Export 1: Prometheus text endpoint.
    server = d1.start_telemetry("127.0.0.1:0")
    try:
        port = server.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics"
        ).read().decode()
        for name in needed:
            assert f"# TYPE {name} " in body, name
    finally:
        d1.stop()
        d2.stop()
    # Export 2: gNMI Subscribe initial sync (and Get) of the state tree.
    port = free_port()
    gsrv = gs.serve_gnmi(d1, f"127.0.0.1:{port}")
    try:
        cli = gs.GnmiClient(f"127.0.0.1:{port}")
        sub = gs.pb.SubscribeRequest()
        sub.subscribe.mode = gs.pb.SubscriptionList.ONCE
        msgs = list(cli.Subscribe(iter([sub])))
        snap = json.loads(msgs[0].update.update[0].val.json_ietf_val)
        names = {m["name"] for m in snap["holo-telemetry"]["metric"]}
        assert any(n.startswith("holo_spf_dispatch_seconds") for n in names)
        assert any(
            n.startswith("holo_spf_jit_compiles_total") for n in names
        )
        assert "holo_spf_ell_occupancy" in names
        assert any(n.startswith("holo_frr_pad_occupancy") for n in names)
    finally:
        gsrv.stop(grace=0)


# -- correlated logging


def test_json_log_records_carry_instance_and_span(capsys):
    import logging

    from holo_tpu.daemon.config import DaemonConfig
    from holo_tpu.daemon.daemon import setup_logging

    cfg = DaemonConfig()
    cfg.logging.style = "json"
    root = logging.getLogger()
    old_handlers = root.handlers[:]
    old_level = root.level
    try:
        setup_logging(cfg)
        log = logging.getLogger("holo_tpu.test")
        with telemetry.span("spf.test", instance="ospfv2-a") as sid:
            log.info("inside span")
        log.info("outside span")
        err = capsys.readouterr().err
        lines = [json.loads(ln) for ln in err.strip().splitlines()]
        inside = next(l for l in lines if l["message"] == "inside span")
        outside = next(l for l in lines if l["message"] == "outside span")
        assert inside["span"] == sid
        assert inside["instance"] == "ospfv2-a"
        assert outside["span"] is None and outside["instance"] is None
    finally:
        root.handlers[:] = old_handlers
        root.setLevel(old_level)


# -- event recorder stamps


def test_event_recorder_mono_seq_stamps_and_backward_compat(tmp_path):
    from holo_tpu.utils.event_recorder import (
        EventRecorder,
        read_entries,
        replay,
    )
    from holo_tpu.utils.runtime import Actor, EventLoop, VirtualClock

    path = tmp_path / "events.jsonl"
    rec = EventRecorder(path)
    rec.record("a", 1.0, {"k": 1})
    rec.record("a", 2.0, {"k": 2})
    rec.record("b", 2.5, {"k": 3})
    rec.close()
    entries = read_entries(path)
    assert [e["seq"] for e in entries] == [0, 1, 2]
    monos = [e["mono"] for e in entries]
    assert monos == sorted(monos) and all(m >= 0 for m in monos)
    # Inter-event latency is reconstructable from the monotonic stamps.
    assert monos[2] - monos[0] >= 0

    # Backward compat: a pre-stamp recording (no mono/seq) still decodes
    # with derived defaults AND still replays.
    old = tmp_path / "old.jsonl"
    old.write_text(
        json.dumps({"actor": "x", "time": 3.0, "msg": {"k": 9}}) + "\n"
    )
    entries = read_entries(old)
    assert entries[0]["seq"] == 0 and entries[0]["mono"] == 3.0

    got = []

    class X(Actor):
        name = "x"

        def handle(self, msg):
            got.append(msg)

    loop = EventLoop(clock=VirtualClock())
    loop.register(X())
    assert replay(old, loop) == 1
    assert got == [{"k": 9}]


# -- txqueue + ibus plumbing metrics


def test_txqueue_and_ibus_metrics():
    from holo_tpu.utils.ibus import Ibus
    from holo_tpu.utils.runtime import Actor, EventLoop, VirtualClock
    from holo_tpu.utils.txqueue import TxTaskNetIo

    class SinkIo:
        def __init__(self):
            self.sent = []

        def send(self, ifname, src, dst, data):
            self.sent.append((ifname, data))

    tx = TxTaskNetIo(SinkIo())
    tx.send("eth9", None, None, b"x")
    tx.close()
    snap = telemetry.snapshot(prefix="holo_txqueue")
    assert snap.get("holo_txqueue_sent_total{ifname=eth9}", 0) >= 1
    tx.send("eth9", None, None, b"late")  # after close: counted drop
    assert (
        telemetry.snapshot(prefix="holo_txqueue")[
            "holo_txqueue_dropped_total{ifname=eth9,cause=closed}"
        ]
        >= 1
    )

    class Rx(Actor):
        name = "rx"

        def handle(self, msg):
            pass

    loop = EventLoop(clock=VirtualClock())
    ibus = Ibus(loop)
    loop.register(Rx())
    ibus.subscribe("test.topic", "rx")
    before = telemetry.snapshot(prefix="holo_ibus")
    ibus.publish("test.topic", {"x": 1})
    ibus.subscribe("test.topic", "ghost")  # never registered actor
    ibus.publish("test.topic", {"x": 2})
    after = telemetry.snapshot(prefix="holo_ibus")
    assert (
        after["holo_ibus_publish_total{topic=test.topic}"]
        - before.get("holo_ibus_publish_total{topic=test.topic}", 0)
        == 2
    )
    assert (
        after["holo_ibus_undeliverable_total{topic=test.topic}"]
        - before.get("holo_ibus_undeliverable_total{topic=test.topic}", 0)
        == 1
    )


# -- deferred occupancy sampling (holo-lint HL105 fix, PR 3) ------------


def test_deferred_mean_one_shot_release_and_kill_switch():
    """set_fn + deferred_mean: the reduction runs at scrape time (not
    on the dispatch path), the array reference is dropped after the
    first sample, and set_enabled(False) gates fn-backed gauges too."""
    import gc
    import weakref

    import numpy as np

    g = telemetry.gauge("holo_test_deferred_occupancy")
    arr = np.ones((4, 8), bool)
    arr[0, :4] = False
    ref = weakref.ref(arr)
    g.set_fn(telemetry.deferred_mean(arr))
    del arr
    gc.collect()
    assert ref() is not None  # pinned until first scrape...
    assert g.value == 1.0 - 4 / 32
    gc.collect()
    assert ref() is None  # ...released after it; value stays cached
    assert g.value == 1.0 - 4 / 32

    # Kill switch: a disabled registry must not run sampling closures.
    calls = []
    g.set_fn(lambda: calls.append(1) or 7.0)
    telemetry.set_enabled(False)
    try:
        assert g.value == 0.0 and not calls
    finally:
        telemetry.set_enabled(True)
    assert g.value == 7.0 and calls

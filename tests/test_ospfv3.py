"""OSPFv3: codecs (incl. pseudo-header checksum) + v6 convergence."""

from ipaddress import IPv4Address as A
from ipaddress import IPv6Address as A6
from ipaddress import IPv6Network as N6

import pytest

from holo_tpu.protocols.ospf import packet_v3 as P
from holo_tpu.protocols.ospf.instance_v3 import (
    OspfV3Instance,
    V3IfConfig,
    V3IfUpMsg,
)
from holo_tpu.protocols.ospf.neighbor import NsmState
from holo_tpu.utils.bytesbuf import DecodeError, Reader
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def test_hello_roundtrip_with_pseudo_header_checksum():
    pkt = P.Packet(
        A("1.1.1.1"), A("0.0.0.0"),
        P.Hello(iface_id=3, priority=1,
                options=P.Options.V6 | P.Options.E | P.Options.R,
                hello_interval=10, dead_interval=40,
                dr=A("0.0.0.0"), bdr=A("0.0.0.0"), neighbors=[A("2.2.2.2")]),
    )
    src, dst = A6("fe80::1"), A6("ff02::5")
    raw = pkt.encode(src, dst)
    out = P.Packet.decode(raw, src, dst)
    assert out.body.iface_id == 3 and out.body.neighbors == [A("2.2.2.2")]
    # corrupt -> checksum failure
    bad = bytearray(raw)
    bad[20] ^= 0xFF
    with pytest.raises(DecodeError):
        P.Packet.decode(bytes(bad), src, dst)


def test_v3_lsa_roundtrips():
    rl = P.Lsa(1, P.LsaType.ROUTER, A("0.0.0.0"), A("1.1.1.1"), -100,
               P.LsaRouterV3(links=[
                   P.RouterLinkV3(P.RouterLinkType.POINT_TO_POINT, 10, 1, 2,
                                  A("2.2.2.2"))]))
    out = P.Lsa.decode(Reader(rl.encode()))
    assert out.body.links[0].nbr_router_id == A("2.2.2.2")

    iap = P.Lsa(1, P.LsaType.INTRA_AREA_PREFIX, A("0.0.0.1"), A("1.1.1.1"),
                -99, P.LsaIntraAreaPrefix(
                    ref_type=int(P.LsaType.ROUTER), ref_lsid=A("0.0.0.0"),
                    ref_adv_rtr=A("1.1.1.1"),
                    prefixes=[(N6("2001:db8:1::/64"), 10),
                              (N6("2001:db8:2::/48"), 20)]))
    out = P.Lsa.decode(Reader(iap.encode()))
    # decode preserves per-prefix options as a third element (0 here)
    assert [(p, m) for p, m, _o in out.body.prefixes] == [
        (N6("2001:db8:1::/64"), 10),
                                 (N6("2001:db8:2::/48"), 20)]

    link = P.Lsa(1, P.LsaType.LINK, A("0.0.0.3"), A("1.1.1.1"), -98,
                 P.LsaLink(link_local=A6("fe80::1"),
                           prefixes=[N6("2001:db8:1::/64")]))
    out = P.Lsa.decode(Reader(link.encode()))
    assert out.body.link_local == A6("fe80::1")
    assert P.scope_of(int(P.LsaType.LINK)) == "link"
    assert P.scope_of(int(P.LsaType.ROUTER)) == "area"
    assert P.scope_of(int(P.LsaType.AS_EXTERNAL)) == "as"


def mk_v3(loop, fabric, name, rid):
    r = OspfV3Instance(name=name, router_id=A(rid),
                       netio=fabric.sender_for(name))
    loop.register(r)
    return r


def v6link(fabric, link, a, ai, alla, b, bi, allb):
    a_if = a.add_interface(ai, V3IfConfig(cost=10), A6(alla), [])
    b_if = b.add_interface(bi, V3IfConfig(cost=10), A6(allb), [])
    fabric.join(link, a.name, ai, A6(alla))
    fabric.join(link, b.name, bi, A6(allb))
    return a_if, b_if


def test_v3_three_router_chain_routes():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = mk_v3(loop, fabric, "v1", "1.1.1.1")
    r2 = mk_v3(loop, fabric, "v2", "2.2.2.2")
    r3 = mk_v3(loop, fabric, "v3", "3.3.3.3")
    v6link(fabric, "l12", r1, "e0", "fe80::1:1", r2, "e0", "fe80::2:1")
    v6link(fabric, "l23", r2, "e1", "fe80::2:2", r3, "e0", "fe80::3:1")
    # r3 advertises a global prefix.
    r3.interfaces["e0"].prefixes.append(N6("2001:db8:33::/64"))
    r1.interfaces["e0"].prefixes.append(N6("2001:db8:11::/64"))
    for r in (r1, r2, r3):
        for ifname in r.interfaces:
            loop.send(r.name, V3IfUpMsg(ifname))
    loop.advance(60)

    # Full adjacencies both hops.
    nbrs1 = r1.interfaces["e0"].neighbors
    assert nbrs1[A("2.2.2.2")].state == NsmState.FULL
    assert set(r1.lsdb.entries) == set(r3.lsdb.entries)

    route = r1.routes.get(N6("2001:db8:33::/64"))
    assert route is not None
    assert route.dist == 10 + 10 + 10  # two hops + prefix metric
    assert {(i, str(a)) for i, a in route.nexthops} == {("e0", "fe80::2:1")}
    # and the reverse direction
    route = r3.routes.get(N6("2001:db8:11::/64"))
    assert route is not None and route.dist == 30


def test_v3_failure_reroute_triangle():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = mk_v3(loop, fabric, "v1", "1.1.1.1")
    r2 = mk_v3(loop, fabric, "v2", "2.2.2.2")
    r3 = mk_v3(loop, fabric, "v3", "3.3.3.3")
    v6link(fabric, "l12", r1, "e0", "fe80::1:1", r2, "e0", "fe80::2:1")
    v6link(fabric, "l23", r2, "e1", "fe80::2:2", r3, "e0", "fe80::3:1")
    v6link(fabric, "l13", r1, "e1", "fe80::1:2", r3, "e1", "fe80::3:2")
    r3.interfaces["e0"].prefixes.append(N6("2001:db8:33::/64"))
    for r in (r1, r2, r3):
        for ifname in r.interfaces:
            loop.send(r.name, V3IfUpMsg(ifname))
    loop.advance(60)
    route = r1.routes[N6("2001:db8:33::/64")]
    assert {i for i, _ in route.nexthops} == {"e1"}  # direct link

    fabric.set_link_up("l13", False)
    loop.advance(120)  # dead interval
    route = r1.routes.get(N6("2001:db8:33::/64"))
    assert route is not None
    assert {i for i, _ in route.nexthops} == {"e0"}  # around via r2


def _lan3():
    """Three routers on one v6 LAN, each with a loopback prefix."""
    from holo_tpu.protocols.ospf.interface import IfType

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    routers = []
    for i in (1, 2, 3):
        inst = OspfV3Instance(f"v3r{i}", A(f"{i}.{i}.{i}.{i}"),
                              fabric.sender_for(f"v3r{i}"))
        loop.register(inst)
        cfg = V3IfConfig(if_type=IfType.BROADCAST, cost=10)
        inst.add_interface("e0", cfg, A6(f"fe80::{i}"),
                           [N6("2001:db8:99::/64")])
        inst.add_interface("lo", V3IfConfig(cost=1), A6(f"fe80::1:{i}"),
                           [N6(f"2001:db8:{i}::/64")])
        fabric.join("lan", inst.name, "e0", A6(f"fe80::{i}"))
        routers.append(inst)
    for r in routers:
        loop.send(r.name, V3IfUpMsg("e0"))
        loop.send(r.name, V3IfUpMsg("lo"))
    loop.advance(80)
    return loop, fabric, routers


def test_v3_lan_dr_election_and_routes():
    """RFC 5340 LAN: DR elected by router-id, network LSA + network-
    referenced intra-area-prefix LSA, full any-to-any v6 routes with
    link-local next hops across the LAN."""
    loop, fabric, routers = _lan3()
    r1, r2, r3 = routers
    # Highest router-id wins at equal priority.
    for r in routers:
        assert r.interfaces["e0"].dr == A("3.3.3.3"), r.name
    # The DR originated the network LSA listing all three members.
    net = [e for e in r1.lsdb.all() if e.lsa.type == P.LsaType.NETWORK
           and not e.lsa.is_maxage]
    assert len(net) == 1
    assert sorted(map(str, net[0].lsa.body.attached)) == [
        "1.1.1.1", "2.2.2.2", "3.3.3.3"]
    # Everyone routes to everyone's loopback across the LAN.
    for r in routers:
        me = int(str(r.router_id).split(".")[0])
        for i in (1, 2, 3):
            if i == me:
                continue
            route = r.routes.get(N6(f"2001:db8:{i}::/64"))
            assert route is not None, f"{r.name} missing r{i} loopback"
            assert route.dist == 10 + 1
            assert {str(a) for _, a in route.nexthops} == {f"fe80::{i}"}
        # The LAN prefix itself: via the network vertex, dist = cost,
        # next hop = the attached interface (no gateway address).
        lan = r.routes.get(N6("2001:db8:99::/64"))
        assert lan is not None and lan.dist == 10
        assert {(ifn, a) for ifn, a in lan.nexthops} == {("e0", None)}


def test_v3_lan_dr_failover():
    loop, fabric, routers = _lan3()
    r1, r2, r3 = routers
    # Kill the DR: BDR (2.2.2.2) must take over and re-originate the
    # network LSA; routes between the survivors must survive.
    loop.unregister("v3r3")
    loop.advance(120)
    for r in (r1, r2):
        assert r.interfaces["e0"].dr == A("2.2.2.2"), r.name
    route = r1.routes.get(N6("2001:db8:2::/64"))
    assert route is not None
    assert {str(a) for _, a in route.nexthops} == {"fe80::2"}
    # The dead router's loopback is gone.
    assert r1.routes.get(N6("2001:db8:3::/64")) is None


def test_v3_lan_dr_sticky_across_flap():
    """A flapped higher-id router must NOT preempt the incumbent DR
    (§9.4 stickiness via declared-DR preference; no self-claim on
    rejoin)."""
    from holo_tpu.protocols.ospf.instance_v3 import V3IfDownMsg

    loop, fabric, routers = _lan3()
    r1, r2, r3 = routers
    assert r1.interfaces["e0"].dr == A("3.3.3.3")
    loop.send("v3r3", V3IfDownMsg("e0"))
    loop.advance(120)  # incumbents re-elect: r2 takes over
    assert r1.interfaces["e0"].dr == A("2.2.2.2")
    loop.send("v3r3", V3IfUpMsg("e0"))
    loop.advance(60)
    # r3 (higher id) rejoins but r2 keeps the role; r3 reaches FULL
    # with the DR and routes flow again.
    for r in routers:
        assert r.interfaces["e0"].dr == A("2.2.2.2"), r.name
    route = r1.routes.get(N6("2001:db8:3::/64"))
    assert route is not None
    assert {str(a) for _, a in route.nexthops} == {"fe80::3"}

"""Full blocked SPF (interpret mode): bit-identical parity with the scalar
oracle on all four output planes (dist/parent/hops/nexthop bitmasks)."""

import numpy as np
import pytest

from holo_tpu.ops.blocked_spf import (
    bfs_permutation,
    failed_edges_perm,
    marshal_block_spf,
    whatif_spf_blocked,
)
from holo_tpu.spf.backend import ScalarSpfBackend
from holo_tpu.spf.synth import random_ospf_topology, whatif_link_failure_masks


def _assert_parity(topo, masks, permute=True, n_atoms=64):
    g = marshal_block_spf(topo, n_atoms=n_atoms, permute=permute)
    perm_of = np.asarray(g.orig2perm)
    fdst, fid = failed_edges_perm(perm_of, topo, masks)
    out = whatif_spf_blocked(g, fdst, fid, interpret=True)
    dist = np.asarray(out.dist)
    parent = np.asarray(out.parent)
    hops = np.asarray(out.hops)
    nh = np.asarray(out.nexthops)
    scalar = ScalarSpfBackend(n_atoms=n_atoms).compute_whatif(topo, masks)
    for b, s in enumerate(scalar):
        np.testing.assert_array_equal(s.dist, dist[b], err_msg=f"dist b={b}")
        np.testing.assert_array_equal(
            s.parent, parent[b], err_msg=f"parent b={b}"
        )
        np.testing.assert_array_equal(s.hops, hops[b], err_msg=f"hops b={b}")
        np.testing.assert_array_equal(
            s.nexthop_words, nh[b], err_msg=f"nexthops b={b}"
        )


@pytest.mark.parametrize("seed", range(3))
def test_blocked_full_parity_whatif(seed):
    topo = random_ospf_topology(
        n_routers=260, n_networks=40, extra_p2p=400, seed=seed
    )
    masks = whatif_link_failure_masks(topo, n_scenarios=6, seed=seed + 7)
    _assert_parity(topo, masks)


def test_blocked_full_parity_unpermuted():
    topo = random_ospf_topology(n_routers=120, n_networks=30, seed=9)
    masks = whatif_link_failure_masks(topo, n_scenarios=4, seed=2)
    _assert_parity(topo, masks, permute=False)


def test_blocked_full_no_failures():
    topo = random_ospf_topology(n_routers=90, n_networks=20, seed=3)
    masks = np.ones((2, topo.n_edges), bool)
    _assert_parity(topo, masks)


def test_blocked_full_multi_failure():
    topo = random_ospf_topology(n_routers=80, n_networks=10, seed=5)
    masks = np.ones((3, topo.n_edges), bool)
    rng = np.random.default_rng(11)
    pair = {
        (int(topo.edge_src[e]), int(topo.edge_dst[e])): e
        for e in range(topo.n_edges)
    }
    for b in (1, 2):
        for _ in range(2):
            e = int(rng.integers(0, topo.n_edges))
            masks[b, e] = False
            rev = pair.get((int(topo.edge_dst[e]), int(topo.edge_src[e])))
            if rev is not None:
                masks[b, rev] = False
    _assert_parity(topo, masks)


def test_bfs_permutation_reduces_blocks():
    """The point of the BFS ordering: fewer nonzero S x S block pairs."""
    topo = random_ospf_topology(
        n_routers=1500, n_networks=200, extra_p2p=2500, seed=1
    )
    g_perm = marshal_block_spf(topo, permute=True)
    g_id = marshal_block_spf(topo, permute=False)
    assert g_perm.w.shape[0] <= g_id.w.shape[0]
    perm = bfs_permutation(topo)
    assert perm[topo.root] == 0
    assert sorted(perm.tolist()) == list(range(topo.n_vertices))


def test_backend_blocked_engine_parity_and_fallback():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from holo_tpu.ops.graph import Topology
    from holo_tpu.spf.backend import TpuSpfBackend

    topo = random_ospf_topology(n_routers=150, n_networks=30, seed=4)
    masks = whatif_link_failure_masks(topo, n_scenarios=4, seed=5)
    be = TpuSpfBackend(engine="blocked")
    scalar = ScalarSpfBackend().compute_whatif(topo, masks)
    for s, t in zip(scalar, be.compute_whatif(topo, masks)):
        np.testing.assert_array_equal(s.dist, t.dist)
        np.testing.assert_array_equal(s.parent, t.parent)
        np.testing.assert_array_equal(s.hops, t.hops)
        np.testing.assert_array_equal(s.nexthop_words, t.nexthop_words)
    one = be.compute(topo)
    np.testing.assert_array_equal(
        one.dist, ScalarSpfBackend().compute(topo).dist
    )
    # parallel (src,dst) edges: blocked preconditions fail -> gather fallback
    par = Topology(
        n_vertices=3,
        is_router=np.ones(3, bool),
        edge_src=np.array([0, 0, 1, 1, 2, 0], np.int32),
        edge_dst=np.array([1, 1, 0, 2, 1, 2], np.int32),
        edge_cost=np.array([1, 2, 1, 1, 1, 9], np.int32),
        root=0,
    )
    assert TpuSpfBackend(engine="blocked").prepare_blocked(par) is None
    got = TpuSpfBackend(engine="blocked").compute(par)
    np.testing.assert_array_equal(got.dist, ScalarSpfBackend().compute(par).dist)

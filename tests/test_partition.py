"""Hierarchical partitioned SPF (ISSUE 15): correctness property gates.

The contract: the three-phase partitioned path (batched per-partition
boundary solves -> exact host skeleton stitch -> seeded final solves
with pinned-halo phase-2 exchange) is bit-identical to BOTH the
monolithic device path and the scalar oracle on every arm — plain,
what-if masks, multipath k ∈ {1, 2, 8}, DeltaPath chains whose events
cross partition boundaries, sharded mesh, and breaker fallback — for
random BFS/greedy cuts, adversarial random vertex->partition maps, and
native partition hints.  Everything runs under
``jax.transfer_guard("disallow")`` (the partitioned path may only move
data inside its sanctioned windows) and the delta chains additionally
under the armed HL109 runtime donation guard.
"""

import numpy as np
import pytest

from holo_tpu import telemetry
from holo_tpu.ops.graph import INF, Topology, diff_topologies, partition_topology
from holo_tpu.ops.partition import PartitionedSpfEngine, build_plan
from holo_tpu.parallel.mesh import (
    configure_process_mesh,
    reset_process_mesh,
)
from holo_tpu.resilience.breaker import CircuitBreaker
from holo_tpu.resilience.faults import FaultInjector, FaultPlan, inject
from holo_tpu.spf.backend import ScalarSpfBackend, TpuSpfBackend
from holo_tpu.spf.scalar import spf_reference
from holo_tpu.spf.synth import (
    clone_topology as clone,
    grid_topology,
    random_ospf_topology,
    whatif_link_failure_masks,
)
from holo_tpu.testing import donation_guarded, no_implicit_transfers

MP_FIELDS = ("parents", "pdist", "pweight", "npaths", "nh_weights")
ALL_FIELDS = ("dist", "parent", "hops", "nexthop_words") + MP_FIELDS


@pytest.fixture(autouse=True)
def _transfer_sanitizer():
    """The whole suite runs under jax.transfer_guard('disallow'): every
    partitioned-phase transfer must stay inside the sanctioned
    spf.partition.* windows."""
    with no_implicit_transfers():
        yield


def tied(seed, n=40, nets=6, extra=60):
    """Random topology with a tiny cost universe: real ECMP ties, and
    enough extra links that random cuts produce real cut-edge sets."""
    return random_ospf_topology(
        n, n_networks=nets, extra_p2p=extra, max_cost=4, seed=seed
    )


def assert_same(a, b, tag=""):
    for f in ALL_FIELDS:
        x, y = getattr(a, f), getattr(b, f)
        if x is None or y is None:
            assert x is None and y is None, (tag, f)
        else:
            assert np.array_equal(x, y), (tag, f)


def delta_count(path: str) -> float:
    return telemetry.snapshot(prefix="holo_spf_delta").get(
        f"holo_spf_delta_total{{kind=weight,path={path}}}", 0.0
    )


# ------------------------------------------------------------- the cut


def test_partition_cut_is_deterministic_exact_cover():
    for seed in range(4):
        topo = tied(seed)
        a = partition_topology(topo, max_part=12)
        b = partition_topology(topo, max_part=12)
        assert np.array_equal(a, b), "cut must be deterministic"
        assert a.min() == 0
        assert np.all(np.bincount(a) > 0), "dense non-empty ids"
        assert a.shape[0] == topo.n_vertices


def test_partition_hint_honored_verbatim():
    topo = tied(1)
    rng = np.random.default_rng(3)
    hint = rng.integers(0, 5, topo.n_vertices, dtype=np.int32)
    topo.partition_hint = hint
    part = partition_topology(topo)
    # Same grouping, dense ids in ascending hint order.
    _, want = np.unique(hint, return_inverse=True)
    assert np.array_equal(part, want.astype(np.int32))
    plan = build_plan(topo)
    assert plan.n_parts == len(np.unique(hint))


# ----------------------------------------------------- engine parity


def test_partitioned_solve_bit_identical_across_random_cuts():
    """Seeded property sweep: engine-level parity vs the scalar oracle
    for BFS/greedy cuts AND adversarial random vertex->partition maps
    (worst-case skeletons)."""
    eng = PartitionedSpfEngine()
    for seed in range(4):
        topo = tied(seed)
        rng = np.random.default_rng(seed)
        cuts = [
            partition_topology(topo, max_part=12),
            rng.integers(0, 4, topo.n_vertices).astype(np.int32),
        ]
        ref = spf_reference(topo)
        for ci, part_of in enumerate(cuts):
            res = eng.marshal(topo, n_atoms=8, part_of=part_of)
            out = eng.solve(topo, res, None, 1)
            for f in ("dist", "parent", "hops"):
                assert np.array_equal(out[f], getattr(ref, f)), (seed, ci, f)
            assert np.array_equal(
                out["nexthop_words"], ref.nexthop_words(8)
            ), (seed, ci)


def test_partitioned_backend_matches_monolithic_and_oracle():
    """Backend-level: a partition-armed backend, the monolithic device
    backend, and the scalar oracle agree bit-for-bit (the digest-parity
    contract bench gates on)."""
    mono = TpuSpfBackend()
    part = TpuSpfBackend(partition_threshold=1, partition_max_part=12)
    oracle = ScalarSpfBackend()
    for seed in range(3):
        topo = tied(seed)
        a = part.compute(topo)
        assert_same(a, mono.compute(topo), tag=("mono", seed))
        assert_same(a, oracle.compute(topo), tag=("oracle", seed))


def test_partitioned_multipath_k_sweep():
    part = TpuSpfBackend(partition_threshold=1, partition_max_part=12)
    oracle = ScalarSpfBackend()
    for k in (1, 2, 8):
        for seed in (5, 6):
            topo = tied(seed)
            res = part.compute(topo, multipath_k=k)
            ref = oracle.compute(topo, multipath_k=k)
            assert_same(res, ref, tag=(k, seed))
            if k > 1:
                # Somebody actually has multiple equal-cost parents.
                ecmp = (res.pdist == res.dist[:, None]) & (
                    res.parents < topo.n_vertices
                )
                assert (ecmp.sum(axis=1) > 1).any()


def test_partitioned_whatif_masks_bit_identical():
    part = TpuSpfBackend(partition_threshold=1, partition_max_part=12)
    oracle = ScalarSpfBackend()
    topo = tied(7)
    masks = whatif_link_failure_masks(topo, 6, seed=7)
    got = part.compute_whatif(topo, masks)
    want = oracle.compute_whatif(topo, masks)
    for i, (g, w) in enumerate(zip(got, want)):
        assert_same(g, w, tag=("whatif", i))


# ------------------------------------------------------------ DeltaPath


def test_partitioned_delta_chain_crosses_boundaries():
    """A chain of weight deltas — intra-partition AND cut-edge
    re-costs — rides the partitioned incremental path
    (``holo_spf_delta_total{path=partitioned-incremental}``) with every
    step bit-identical to the oracle, and intra-partition steps
    re-solve a bounded partition subset (the Bounded-Dijkstra radius
    claim), all under the armed donation guard."""
    oracle = ScalarSpfBackend()
    with donation_guarded():
        part = TpuSpfBackend(partition_threshold=1, partition_max_part=12)
        topo = tied(11)
        part.compute(topo)  # roots the chain, records the solve state
        res = part.partition_residents()[0]
        n_parts = res.plan.n_parts
        assert n_parts >= 3, "cut too coarse for a bounded-radius claim"
        cutset = set(res.plan.cut_eid.tolist())
        intra = [e for e in range(topo.n_edges) if e not in cutset]
        cut = sorted(cutset)
        before = delta_count("partitioned-incremental")
        bounded_seen = False
        cur = topo
        picks = [intra[0], cut[0], intra[len(intra) // 2], cut[-1], intra[-1]]
        for step, e in enumerate(picks):
            nxt = clone(cur, cost={e: int(cur.edge_cost[e]) + 1 + step})
            delta = diff_topologies(cur, nxt)
            assert delta is not None
            nxt.link_delta(delta)
            got = part.compute(nxt)
            assert_same(got, oracle.compute(nxt), tag=("delta", step, e))
            if e in cutset:
                # Cut-edge re-cost: the skeleton moves, the affected
                # closure may grow — but the chain must stay served.
                pass
            elif res.last_resolved < n_parts:
                bounded_seen = True
            cur = nxt
        after = delta_count("partitioned-incremental")
        assert after - before >= len(picks), "chain fell off the delta path"
        assert bounded_seen, (
            "no intra-partition delta re-solved a strict partition subset"
        )


def test_partitioned_delta_structural_falls_back_to_remarshal():
    """A structural delta on a CUT edge (halo/skeleton geometry change)
    is not absorbable in place: the resident re-marshals and the next
    full partitioned solve still matches the oracle."""
    oracle = ScalarSpfBackend()
    part = TpuSpfBackend(partition_threshold=1, partition_max_part=12)
    topo = tied(13)
    part.compute(topo)
    res = part.partition_residents()[0]
    e = int(res.plan.cut_eid[0])
    s, d = int(topo.edge_src[e]), int(topo.edge_dst[e])
    keep = ~(
        ((topo.edge_src == s) & (topo.edge_dst == d))
        | ((topo.edge_src == d) & (topo.edge_dst == s))
    )
    nxt = clone(topo, keep=keep)
    delta = diff_topologies(topo, nxt)
    if delta is not None:
        nxt.link_delta(delta)
    assert_same(part.compute(nxt), oracle.compute(nxt), tag="cut-struct")


# ----------------------------------------------- fallback + mesh arms


def test_partitioned_breaker_fallback_bit_identical():
    """Forced dispatch failures serve the partitioned result from the
    scalar oracle — bit-identical, chain disposition counted."""
    topo = tied(17)
    want = ScalarSpfBackend().compute(topo, multipath_k=2)
    breaker = CircuitBreaker("part-test", failure_threshold=10)
    part = TpuSpfBackend(
        breaker=breaker, partition_threshold=1, partition_max_part=12
    )
    plan = FaultPlan(seed=1, dispatch_fail={"spf.dispatch": 2})
    with inject(FaultInjector(plan)) as inj:
        r1 = part.compute(topo, multipath_k=2)
        r2 = part.compute(topo, multipath_k=2)
    assert inj.injected["spf.dispatch"] == 2
    assert_same(r1, want, "fallback-1")
    assert_same(r2, want, "fallback-2")


def test_partitioned_sharded_mesh_bit_identical():
    """Under a forced multi-device batch mesh the partition axis rides
    the batch sharding; results stay byte-identical to the oracle."""
    oracle = ScalarSpfBackend()
    mesh = configure_process_mesh(None, 1)  # all devices on batch
    try:
        part = TpuSpfBackend(
            partition_threshold=1,
            partition_parts=int(mesh.shape["batch"]),  # divides batch
        )
        for seed in (19, 23):
            topo = tied(seed)
            assert_same(
                part.compute(topo),
                oracle.compute(topo),
                tag=("mesh", seed),
            )
    finally:
        reset_process_mesh()
    del mesh


def test_partitioned_hinted_topology_end_to_end():
    """A native partition hint (the protocol-seam contract) drives the
    cut end to end through the backend and survives mutation chains."""
    oracle = ScalarSpfBackend()
    part = TpuSpfBackend(partition_threshold=1)
    topo = grid_topology(6, 8, max_cost=6, seed=29)
    hint = (np.arange(topo.n_vertices) * 4 // topo.n_vertices).astype(
        np.int32
    )
    topo.partition_hint = hint
    assert_same(part.compute(topo), oracle.compute(topo), tag="hint")
    res = part.partition_residents()[0]
    assert res.plan.n_parts == 4
    # The hint rides mutation clones: the chain keeps its cut.
    nxt = clone(topo, cost={0: int(topo.edge_cost[0]) + 3})
    delta = diff_topologies(topo, nxt)
    assert delta is not None, "hint must not break delta linking"
    nxt.link_delta(delta)
    assert_same(part.compute(nxt), oracle.compute(nxt), tag="hint-delta")


def test_partitioned_disconnected_and_tiny_graphs():
    """Edge shapes: disconnected components (INF lanes), a partition
    with no cut edges, and graphs smaller than the partition target."""
    oracle = ScalarSpfBackend()
    part = TpuSpfBackend(partition_threshold=1, partition_max_part=4)
    # Two disconnected grids: the root's component resolves, the other
    # stays INF/unreachable — sentinel contract preserved.
    g = grid_topology(3, 4, max_cost=5, seed=31)
    n = g.n_vertices
    iso = Topology(
        n_vertices=n + 5,
        is_router=np.concatenate([g.is_router, np.ones(5, bool)]),
        edge_src=g.edge_src,
        edge_dst=g.edge_dst,
        edge_cost=g.edge_cost,
        edge_direct_atom=g.edge_direct_atom,
        root=g.root,
    )
    assert_same(part.compute(iso), oracle.compute(iso), tag="disconnected")
    tiny = grid_topology(2, 2, max_cost=3, seed=37)
    assert_same(part.compute(tiny), oracle.compute(tiny), tag="tiny")

"""CSPF: constraint masks, batched computation, path extraction."""

import numpy as np

from holo_tpu.ops.cspf import Constraint, CspfEngine, LinkAttrs, constraint_masks
from holo_tpu.ops.graph import Topology
from holo_tpu.spf.backend import ScalarSpfBackend
from holo_tpu.spf.synth import assign_direct_atoms, random_ospf_topology


def diamond():
    """0 -> {1 (fast, red), 2 (slow, blue)} -> 3."""
    src = np.array([0, 1, 0, 2, 1, 3, 2, 3], np.int32)
    dst = np.array([1, 0, 2, 0, 3, 1, 3, 2], np.int32)
    cost = np.array([1, 1, 5, 5, 1, 1, 5, 5], np.int32)
    topo = Topology(4, np.ones(4, bool), src, dst, cost, root=0)
    assign_direct_atoms(topo)
    RED, BLUE = 0x1, 0x2
    affinity = np.array([RED, RED, BLUE, BLUE, RED, RED, BLUE, BLUE], np.uint32)
    bandwidth = np.array([10.0, 10.0, 100.0, 100.0, 10.0, 10.0, 100.0, 100.0])
    return topo, LinkAttrs(affinity, bandwidth), RED, BLUE


def test_unconstrained_takes_cheapest():
    topo, attrs, RED, BLUE = diamond()
    eng = CspfEngine(topo, attrs)
    (path,) = eng.compute([Constraint()], [3])
    assert path.cost == 2 and path.vertices == [0, 1, 3]


def test_exclude_affinity_forces_detour():
    topo, attrs, RED, BLUE = diamond()
    eng = CspfEngine(topo, attrs)
    (path,) = eng.compute([Constraint(exclude_any=RED)], [3])
    assert path.cost == 10 and path.vertices == [0, 2, 3]


def test_bandwidth_constraint():
    topo, attrs, RED, BLUE = diamond()
    eng = CspfEngine(topo, attrs)
    (path,) = eng.compute([Constraint(min_bandwidth=50.0)], [3])
    assert path.vertices == [0, 2, 3]  # red links have only 10 units
    # Impossible bandwidth: unreachable.
    (path,) = eng.compute([Constraint(min_bandwidth=1000.0)], [3])
    assert path.cost is None


def test_batched_requests_mixed_constraints():
    topo, attrs, RED, BLUE = diamond()
    eng = CspfEngine(topo, attrs)
    paths = eng.compute(
        [Constraint(), Constraint(exclude_any=RED),
         Constraint(include_any=RED), Constraint(max_link_metric=1)],
        [3, 3, 3, 3],
    )
    assert [p.cost for p in paths] == [2, 10, 2, 2]
    assert paths[1].vertices == [0, 2, 3]
    assert paths[3].vertices == [0, 1, 3]  # blue links cost 5 > max 1


def test_cspf_distances_match_scalar_on_random_graph():
    """The masked SSSP under a constraint equals the scalar reference on
    the equivalently pruned graph."""
    topo = random_ospf_topology(n_routers=40, n_networks=8, extra_p2p=60, seed=4)
    rng = np.random.default_rng(7)
    attrs = LinkAttrs(
        affinity=rng.integers(0, 4, topo.n_edges).astype(np.uint32),
        bandwidth=rng.uniform(1, 100, topo.n_edges),
    )
    cons = Constraint(exclude_any=0x1, min_bandwidth=20.0)
    masks = constraint_masks(topo, attrs, [cons])
    eng = CspfEngine(topo, attrs)
    dsts = [v for v in range(topo.n_vertices) if topo.is_router[v]][:5]
    paths = eng.compute([cons] * len(dsts), dsts)
    ref = ScalarSpfBackend().compute(topo, masks[0])
    from holo_tpu.ops.graph import INF

    for p in paths:
        expect = None if ref.dist[p.dst] >= INF else int(ref.dist[p.dst])
        assert p.cost == expect

"""SLO plane + synthetic canary (ISSUE 20): burn math, sentinel
latching, objective routing, canary probe attribution, and the
disarmed one-check gate.

The burn tests pin the SRE arithmetic to hand-computed fractions under
an injected clock.  The sentinel tests prove the latch contract — one
fire per excursion, re-arm on recovery, warn-only.  The canary tests
drive the REAL paths: probes through a virtual loop attribute at
``fib_commit`` with zero unattributed closes; an injected
``FaultPlan.dispatch_delay`` on the ``canary.probe`` seam trips the
fast-window sentinel exactly once while the clean arm stays silent;
and a seeded storm's production FIB digest is byte-identical with a
canary riding vs never built.  The disarmed tests poison
``profiling.clock`` and walk every seam — no clock read, no sketch
write, hook uninstalled — the same structural gate as critpath's.
"""

from __future__ import annotations

import json
import threading

import pytest

from holo_tpu import telemetry
from holo_tpu.resilience import faults
from holo_tpu.telemetry import (
    canary,
    convergence,
    observatory,
    profiling,
    slo,
)
from holo_tpu.telemetry.slo import Objective, SloEngine


@pytest.fixture(autouse=True)
def _reset_slo_state():
    yield
    from holo_tpu.pipeline import dispatch

    canary.configure(False)
    slo.configure(False)
    convergence.configure(0)
    observatory.configure(enabled=False)
    dispatch.reset_process_pipeline()
    profiling.set_device_profiling(False)
    profiling.set_stage_timer(None)


class _FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# -- objective model ------------------------------------------------------

def test_objective_validation():
    with pytest.raises(ValueError):
        Objective("x", kind="throughput")
    with pytest.raises(ValueError):
        Objective("x", target=1.0)
    with pytest.raises(ValueError):
        Objective("x", quantile=0.0)
    with pytest.raises(ValueError):
        Objective("x", threshold_s=0.0)


def test_objective_from_config_kebab_keys():
    o = Objective.from_config({
        "name": "ospf-fib", "kind": "latency", "source": "lsa",
        "quantile": 0.95, "threshold-ms": 500.0, "target": 0.99,
    })
    assert o.name == "ospf-fib"
    assert o.source == "lsa"
    assert o.threshold_s == pytest.approx(0.5)
    assert o.target == 0.99
    # defaults fill in
    assert Objective.from_config({"name": "d"}).kind == "latency"


def test_engine_rejects_duplicates_and_bad_windows():
    with pytest.raises(ValueError):
        SloEngine(objectives=(Objective("a"), Objective("a")))
    with pytest.raises(ValueError):
        SloEngine(fast_window=600.0, slow_window=60.0)


# -- burn math ------------------------------------------------------------

def test_burn_and_budget_hand_computed():
    clk = _FakeClock(1000.0)
    eng = SloEngine(
        objectives=(Objective("o", "latency", "*", 0.99, 1.0, 0.9),),
        clock=clk, fast_window=60.0, slow_window=600.0, check_every=0,
    )
    for _ in range(19):
        eng.note_endcut("lsa", 0.5, False)  # good
    eng.note_endcut("lsa", 2.0, False)  # bad
    st = eng.objective("o")
    frac, good, bad = eng._bad_frac(st, clk.t, eng.fast_window)
    assert (good, bad) == (19, 1)
    assert frac == pytest.approx(0.05)
    # burn = bad_frac / (1 - target) = 0.05 / 0.1
    assert eng.burn(st, clk.t, eng.fast_window) == pytest.approx(0.5)
    assert eng.budget_remaining(st, clk.t) == pytest.approx(0.5)
    # Empty window -> no verdict, not a zero verdict.
    clk.t += 10_000.0
    assert eng.burn(st, clk.t, eng.fast_window) is None


def test_buckets_trim_past_slow_window():
    clk = _FakeClock(0.0)
    eng = SloEngine(
        objectives=(Objective("o", target=0.9),),
        clock=clk, fast_window=60.0, slow_window=600.0, check_every=0,
    )
    st = eng.objective("o")
    for i in range(100):
        clk.t = i * 60.0
        eng.note_endcut("lsa", 0.1, False)
    eng.checkpoint()
    floor = int((clk.t - eng.slow_window) // eng.bucket_w)
    assert all(i >= floor for i in st.buckets)


def test_sentinel_latches_once_and_rearms():
    clk = _FakeClock(50.0)
    eng = SloEngine(
        objectives=(Objective("o", "latency", "*", 0.99, 1.0, 0.5),),
        clock=clk, fast_window=60.0, slow_window=600.0,
        fast_burn=1.0, slow_burn=100.0, check_every=0,
    )
    st = eng.objective("o")
    for _ in range(3):
        eng.note_endcut("lsa", 9.0, False)  # burn 2.0 > 1.0
    assert st.fires["fast"] == 1  # latched: one fire for the excursion
    for _ in range(5):
        eng.note_endcut("lsa", 9.0, False)
    assert st.fires["fast"] == 1
    for _ in range(10):
        eng.note_endcut("lsa", 0.1, False)  # frac 8/18 -> burn 0.89
    eng.checkpoint()
    assert st.latched["fast"] is False  # re-armed on recovery
    for _ in range(30):
        eng.note_endcut("lsa", 9.0, False)
    assert st.fires["fast"] == 2  # second excursion fires once more
    # warn-only surface: the counter matches the latch tally
    fires = telemetry.snapshot(prefix="holo_slo_sentinel_fires_total")
    assert any(v >= 2 for v in fires.values())


def test_canary_endcuts_never_grade_production_objectives():
    eng = SloEngine(clock=_FakeClock(), check_every=0)
    eng.note_endcut("canary", 99.0, False)
    assert eng.objective("trigger-fib").events == 0
    assert eng.objective("canary").events == 0  # probes only, via note_probe


def test_endcut_routes_by_trigger_source():
    clk = _FakeClock(10.0)
    eng = SloEngine(
        objectives=(
            Objective("all", "latency", "*", 0.99, 1.0, 0.9),
            Objective("lsa-only", "latency", "lsa", 0.99, 1.0, 0.9),
        ),
        clock=clk, check_every=0,
    )
    eng.note_endcut("lsa", 0.1, False)
    eng.note_endcut("bfd", 0.1, True)
    assert eng.objective("all").events == 2
    assert eng.objective("lsa-only").events == 1
    assert eng.objective("all").fallbacks == 1


def test_availability_down_span_arithmetic():
    clk = _FakeClock(0.0)
    eng = SloEngine(
        objectives=(
            Objective("relay", "availability", "relay", 0.99, 1.0, 0.9),
        ),
        clock=clk, fast_window=100.0, slow_window=1000.0, check_every=0,
    )
    st = eng.objective("relay")
    eng.note_relay(True)
    clk.t = 10.0
    eng.note_relay(False)
    clk.t = 30.0
    eng.note_relay(True)  # closed span: 20 s down
    clk.t = 100.0
    assert eng._down_seconds(st, clk.t, 100.0) == pytest.approx(20.0)
    # burn = (down/W) / (1-target) = 0.2 / 0.1
    assert eng.burn(st, clk.t, 100.0) == pytest.approx(2.0)
    # an OPEN down state accrues up to now; the closed [10, 30] span
    # has slid out of the [50, 150] window entirely
    eng.note_relay(False)
    clk.t = 150.0
    assert eng._down_seconds(st, clk.t, 100.0) == pytest.approx(50.0)
    row = eng._objective_row(st, clk.t)
    assert row["state"] == "down"


def test_delivery_objective_grades_served_vs_shed():
    eng = SloEngine(clock=_FakeClock(77.0), check_every=0)
    for _ in range(5):
        eng.note_served("background")
    for _ in range(5):
        eng.note_shed("background", "expired")
    st = eng.objective("background-delivery")
    frac, good, bad = eng._bad_frac(st, 77.0, eng.fast_window)
    assert (good, bad) == (5, 5)
    assert eng._sheds == {("background", "expired"): 5}
    # correctness class has no delivery objective: silently unrouted
    eng.note_served("correctness")
    assert st.events == 10


# -- wiring: hooks and feeds ---------------------------------------------

def test_configure_installs_and_uninstalls_endcut_hook():
    eng = slo.configure(check_every=0)
    assert convergence._SLO_HOOK is eng
    slo.configure(False)
    assert convergence._SLO_HOOK is None
    assert slo.active() is None


def test_fib_commit_feeds_trigger_fib_objective():
    clk = _FakeClock(5.0)
    convergence.configure(64, clock=clk)
    eng = slo.configure(check_every=0, clock=clk)
    eid = convergence.begin("lsa")
    clk.t = 5.5
    convergence.fib_commit(eids=(eid,))
    st = eng.objective("trigger-fib")
    assert st.events == 1
    assert st.sketch.count == 1
    assert eng._bad_frac(st, clk.t, eng.fast_window)[1] == 1  # good


def test_relay_watch_feeds_availability_objective():
    from holo_tpu.telemetry import relay

    eng = slo.configure(check_every=0)
    relay.note_probe(True, took_s=0.01)
    relay.note_probe(False, error="boom")
    st = eng.objective("relay")
    assert st.events == 2
    assert st.up is False


def test_pipeline_serve_and_shed_feed_delivery_objective():
    from holo_tpu.pipeline.dispatch import DispatchPipeline

    eng = slo.configure(check_every=0)
    pipe = DispatchPipeline(depth=2, name="slo-feed")
    try:
        t = pipe.submit("k", "spf", run=lambda: "v", cls="background")
        assert t.result(5.0) == "v"
    finally:
        pipe.close()
    st = eng.objective("background-delivery")
    assert eng._bad_frac(st, eng._clock(), eng.fast_window)[1] >= 1


def test_shed_margin_histogram_carries_event_exemplar():
    from holo_tpu.pipeline.dispatch import DispatchPipeline
    from holo_tpu.telemetry.provider import _exemplar_leaf

    convergence.configure(64)
    eng = slo.configure(check_every=0)
    pipe = DispatchPipeline(depth=1, name="slo-shed")
    gate = threading.Event()
    try:
        stall = pipe.submit("hold", "spf", run=lambda: gate.wait(5.0))
        eid = convergence.begin("lsa")
        with convergence.activation((eid,)):
            bg = pipe.submit(
                "k", "spf", run=lambda: "v",
                cls="background", deadline=0.05,
            )
        import time

        time.sleep(0.2)  # worker busy: the deadline lapses in-queue
        gate.set()
        assert bg.result(5.0) is None  # shed resolves empty, not raising
        assert bg.shed is not None
    finally:
        gate.set()
        pipe.close()
    assert eng._sheds.get(("background", "expired"), 0) >= 1
    fams = {f.name: f for f in telemetry.registry().families()}
    hist = fams["holo_pipeline_shed_margin_seconds"]
    total = sum(child.count for _k, child in hist.children())
    assert total >= 1
    joined = ";".join(
        _exemplar_leaf(child) for _k, child in hist.children()
    )
    assert "event_id=" in joined


def test_checkpoint_seeds_observatory_ledger_rows():
    obs = observatory.configure(check_every=0)
    clk = _FakeClock(3.0)
    eng = slo.configure(check_every=0, clock=clk)
    eng.note_endcut("lsa", 0.2, False)
    before = obs.sentinel()["seeded"]
    eng.checkpoint()
    assert obs.sentinel()["seeded"] > before


def test_provider_leaf_carries_slo_and_canary():
    from holo_tpu.telemetry.provider import TelemetryStateProvider

    eng = slo.configure(check_every=0)
    eng.note_served("background")
    st = TelemetryStateProvider().get_state()["holo-telemetry"]
    leaf = st["slo"]
    assert leaf["objectives"]["background-delivery"]["events"] == 1
    assert leaf["objectives"]["trigger-fib"]["burn-fast"] is None


# -- canary: probe attribution -------------------------------------------

def _virtual_loop():
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    return EventLoop(clock=VirtualClock())


def test_canary_probes_attribute_through_fib_commit():
    loop = _virtual_loop()
    convergence.configure(256, clock=loop.clock.now)
    eng = slo.configure(check_every=0)
    prober = canary.CanaryProber(loop, period=2.0, warmup=10.0)
    try:
        prober.start()
        loop.advance(30.0)
    finally:
        prober.stop()
    assert prober.probes >= 10
    # A flip pair coalesced into one SPF hold cancels out (metric back
    # where it started -> no install), so a couple of probes may still
    # be open — but every CLOSED probe must balance the tallies.
    assert prober.completed == prober.probes - len(prober._open)
    assert prober.completed >= 8
    assert prober.unattributed == 0
    assert prober.unattributed_fraction() == 0.0
    st = eng.objective("canary")
    assert st.events == prober.completed
    # every probe graded good on its real wall
    assert eng._bad_frac(st, eng._clock(), eng.fast_window)[2] == 0
    # the flip is a REAL route change: the leaf prefix is installed
    from ipaddress import IPv4Network

    assert IPv4Network("198.51.100.0/24") in prober.net.kernel.fib


def test_canary_tracker_disarmed_grades_nothing():
    loop = _virtual_loop()
    slo.configure(check_every=0)
    prober = canary.CanaryProber(loop, period=2.0, warmup=10.0)
    try:
        prober.start()
        loop.advance(10.0)
    finally:
        prober.stop()
    assert prober.probes == 0  # no tracker -> no causal ids -> no probes


def test_canary_configure_requires_loop():
    with pytest.raises(ValueError):
        canary.configure(True, loop=None)
    with pytest.raises(ValueError):
        canary.CanaryProber(_virtual_loop(), period=0.0)


def test_canary_breach_trips_fast_sentinel_exactly_once():
    from holo_tpu.pipeline import dispatch

    loop = _virtual_loop()
    convergence.configure(256, clock=loop.clock.now)
    eng = slo.configure(check_every=0)
    dispatch.configure_process_pipeline(depth=2, capacity=32)
    prober = canary.CanaryProber(
        loop, period=2.0, deadline=0.25, warmup=10.0
    )
    st = eng.objective("canary")
    # The breaker registry is process-global: earlier suites leave their
    # own tripped breakers behind.  Only a breaker NEWLY opened by this
    # test would indicate the sentinel touched dispatch.
    from holo_tpu.resilience import health_snapshot

    def _open_breakers():
        return {
            name
            for name, b in health_snapshot().get("breakers", {}).items()
            if b.get("state") == "open"
        }

    open_before = _open_breakers()
    try:
        prober.start()
        # Clean arm first: probes ride the pipeline, sentinel silent.
        loop.advance(10.0)
        assert st.fires["fast"] == 0
        # Breach: the canary.probe delaypoint sleeps 0.5 s REAL per
        # dispatch — over the 0.25 s objective threshold, invisible to
        # the virtual end-cuts.
        with faults.inject(
            faults.FaultPlan(dispatch_delay={"canary.probe": 0.5})
        ):
            loop.advance(8.0)
    finally:
        prober.stop()
        dispatch.reset_process_pipeline()
    bad = eng._bad_frac(st, eng._clock(), eng.fast_window)[2]
    assert bad >= 2  # the slowed probes graded bad
    assert st.fires["fast"] == 1  # latched: exactly one fire
    assert st.latched["fast"] is True
    # warn-only: no breaker newly opened, dispatch unaffected
    assert _open_breakers() == open_before


def test_storm_fib_digest_identical_with_canary_riding():
    from holo_tpu.spf.backend import ScalarSpfBackend
    from holo_tpu.spf.synth_storm import run_convergence_storm
    from holo_tpu.telemetry.canary import fib_digest

    def run(arm: bool):
        prober = None

        def hook(net, _i, _now):
            nonlocal prober
            if arm and prober is None:
                slo.configure(check_every=0)
                prober = canary.CanaryProber(
                    net.loop, period=2.0, warmup=10.0
                )
                prober.start()

        _rep, _digest, net = run_convergence_storm(
            n_routers=24, events=12, seed=7,
            spf_backend=ScalarSpfBackend(),
            event_hook=hook,
        )
        if prober is not None:
            prober.stop()
            assert prober.completed > 0
            assert prober.unattributed_fraction() < 0.01
        d = fib_digest(net.kernel.fib)
        slo.configure(False)
        return d

    control = run(arm=False)
    armed = run(arm=True)
    # The canary's routes live in its OWN kernel: the production FIB is
    # byte-identical whether the canary rode the storm or never existed.
    assert armed == control


# -- surfaces -------------------------------------------------------------

def test_explain_slo_byte_identical(capsys):
    from holo_tpu.tools.cli import main as cli_main

    argv = [
        "explain", "--slo", "--storm", "32",
        "--events", "12", "--seed", "5",
    ]
    assert cli_main(argv) == 0
    out1 = capsys.readouterr().out
    assert cli_main(argv) == 0
    out2 = capsys.readouterr().out
    assert out1 == out2
    assert "slo — windows:" in out1
    assert "trigger-fib" in out1 and "canary" in out1
    # The CLI disarmed the plane on exit.
    assert slo.active() is None
    assert canary.active() is None


def test_explain_slo_json_has_budget_math(capsys):
    from holo_tpu.tools.cli import main as cli_main

    assert cli_main(
        ["explain", "--slo", "--storm", "32", "--events", "12",
         "--seed", "5", "--json"]
    ) == 0
    doc = json.loads(capsys.readouterr().out)
    rows = {r["objective"]: r for r in doc["slo"]["objectives"]}
    tf = rows["trigger-fib"]
    assert tf["events"] > 0
    assert tf["budget_remaining"] is not None
    cn = rows["canary"]
    assert cn["events"] > 0
    assert doc["slo"]["canary"]["completed"] == cn["events"]


# -- config ---------------------------------------------------------------

def test_config_parses_slo_and_canary_knobs(tmp_path):
    from holo_tpu.daemon.config import DaemonConfig

    p = tmp_path / "holod.toml"
    p.write_text(
        """
[telemetry]
convergence-events = 256
slo = true
slo-fast-window = 600.0
slo-slow-window = 7200.0
slo-fast-burn = 10.0
canary = true
canary-period = 2.5
canary-deadline = 0.5

[[telemetry.slo-objectives]]
name = "ospf-fib"
kind = "latency"
source = "lsa"
threshold-ms = 500.0
target = 0.99
"""
    )
    cfg = DaemonConfig.load(p)
    t = cfg.telemetry
    assert t.slo is True
    assert t.slo_fast_window == 600.0 and t.slo_slow_window == 7200.0
    assert t.slo_fast_burn == 10.0
    assert t.canary is True and t.canary_period == 2.5
    assert t.canary_deadline == 0.5
    (o,) = t.slo_objectives
    assert isinstance(o, Objective)
    assert o.source == "lsa" and o.threshold_s == pytest.approx(0.5)


def test_config_rejects_bad_slo_tables(tmp_path):
    from holo_tpu.daemon.config import DaemonConfig

    p = tmp_path / "holod.toml"
    p.write_text(
        """
[telemetry]
slo = true
slo-objectives = [{ name = "x", kind = "nope" }]
"""
    )
    with pytest.raises(ValueError, match="slo-objectives invalid"):
        DaemonConfig.load(p)
    p.write_text(
        """
[telemetry]
slo = true
slo-fast-window = 7200.0
slo-slow-window = 600.0
"""
    )
    with pytest.raises(ValueError, match="slo windows"):
        DaemonConfig.load(p)


def test_config_canary_requires_convergence_tracker(tmp_path):
    from holo_tpu.daemon.config import DaemonConfig

    p = tmp_path / "holod.toml"
    p.write_text("[telemetry]\ncanary = true\n")
    with pytest.raises(ValueError, match="convergence-events"):
        DaemonConfig.load(p)


# -- disarmed contract ----------------------------------------------------

def test_disarmed_seams_are_one_global_check(monkeypatch):
    assert slo.active() is None
    assert canary.active() is None

    def boom():
        raise AssertionError("disarmed SLO seam read the clock")

    monkeypatch.setattr(profiling, "clock", boom)
    # Every module seam returns before any clock read or sketch write.
    slo.note_probe(True, 0.01)
    slo.note_served("background")
    slo.note_shed("background", "expired")
    slo.note_relay(True)
    # The convergence end-cut hook is uninstalled: fib_commit pays one
    # None check, never an SLO clock read.
    assert convergence._SLO_HOOK is None


def test_disarmed_pipeline_path_never_reads_slo_clock(monkeypatch):
    from holo_tpu.pipeline.dispatch import DispatchPipeline

    assert slo.active() is None

    def boom():
        raise AssertionError("disarmed SLO seam read the clock")

    monkeypatch.setattr(profiling, "clock", boom)
    pipe = DispatchPipeline(depth=2, name="slo-off")
    try:
        # settle path (note_served seam) and shed path (note_shed seam)
        # both cross the disarmed seams without touching the clock
        t = pipe.submit("k", "spf", run=lambda: "v", cls="background")
        assert t.result(5.0) == "v"
    finally:
        pipe.close()

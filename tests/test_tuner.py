"""Per-shape engine auto-tuner (ISSUE 9 tentpole, part b).

Deterministic explore/exploit schedule, winner promotion, cost-prior
ordered exploration, versioned table persistence (a cold daemon
reproduces the learned winners with zero re-exploration), the
auto-tuned per-shape DeltaPath depth cap (PR 7 follow-up), and the
backend integration: parity is engine-independent, so tuner flips can
never change routing output.
"""

import numpy as np
import pytest

from holo_tpu import pipeline, telemetry
from holo_tpu.pipeline.tuner import (
    DEPTH_MIN_SAMPLES,
    DEPTH_SCALE,
    ENGINES,
    EngineTuner,
    shape_bucket,
)


@pytest.fixture(autouse=True)
def _clean():
    yield
    pipeline.reset_engine_tuner()
    pipeline.reset_process_pipeline()


B = shape_bucket(1000, 4000, 8, None)


def test_shape_bucket_quantization():
    assert shape_bucket(1000, 4000, 8, None) == (1024, 4096, 8, None, 1)
    assert shape_bucket(1024, 4096, 8, None) == (1024, 4096, 8, None, 1)
    assert shape_bucket(1, 0, 1, ("m", 2)) == (1, 1, 1, ("m", 2), 1)
    # Nearby sizes share a bucket; a 2x jump does not.
    assert shape_bucket(900, 3900, 8) == shape_bucket(1000, 4000, 8)
    assert shape_bucket(900, 3900, 8) != shape_bucket(2100, 3900, 8)
    # The multipath width is part of the shape key (ISSUE 10): k=1 and
    # k=8 dispatches of the same graph are different programs.
    assert shape_bucket(1000, 4000, 8, None, k=8) == (
        1024, 4096, 8, None, 8,
    )
    assert shape_bucket(1000, 4000, 8, k=8) != shape_bucket(1000, 4000, 8)


def test_explore_then_exploit_deterministic():
    t = EngineTuner(explore_rounds=1, reprobe_every=0)
    seen = []
    for _ in range(len(ENGINES)):
        e = t.pick("one", B)
        seen.append(e)
        t.observe("one", B, e, 1.0 if e != "hybrid" else 0.1)
    # Explore phase measured every engine exactly once.
    assert sorted(seen) == sorted(ENGINES)
    # Exploit phase: the measured winner, repeatedly.
    assert [t.pick("one", B) for _ in range(5)] == ["hybrid"] * 5


def test_schedule_replays_identically():
    def run():
        t = EngineTuner(explore_rounds=2, reprobe_every=8)
        picks = []
        for i in range(64):
            e = t.pick("one", B)
            picks.append(e)
            t.observe("one", B, e, {"seq": 3.0, "fused": 2.0,
                                    "packed": 4.0, "hybrid": 1.0,
                                    "tropical": 5.0}[e])
        return picks

    assert run() == run(), "tuner schedule must be RNG-free deterministic"


def test_reprobe_revisits_non_winners():
    t = EngineTuner(explore_rounds=1, reprobe_every=4)
    for _ in range(len(ENGINES)):
        e = t.pick("one", B)
        t.observe("one", B, e, 0.1 if e == "seq" else 1.0)
    picks = [t.pick("one", B) for _ in range(16)]
    assert picks.count("seq") >= 10, picks  # mostly exploit
    assert set(picks) - {"seq"}, "reprobe must revisit non-winners"


def test_promotion_on_winner_flip_counts_and_persists(tmp_path):
    path = tmp_path / "tuner.json"
    t = EngineTuner(path=path, explore_rounds=1, reprobe_every=4)
    for _ in range(len(ENGINES)):
        e = t.pick("one", B)
        t.observe("one", B, e, 0.5 if e == "seq" else 1.0)
    assert t.stats()["winners"][t._bucket_str(("one", *B))]["winner"] == "seq"
    promos0 = t.stats()["promotions"]
    # The platform drifts: fused now measures faster, repeatedly.
    for _ in range(9):
        t.observe("one", B, "fused", 0.01)
    assert t.stats()["promotions"] > promos0
    assert path.exists(), "promotion must persist the table"


def test_cost_prior_orders_exploration():
    t = EngineTuner(explore_rounds=1, reprobe_every=0)
    t.cost_prior("one", B, "hybrid", {"flops": 10, "bytes": 10})
    t.cost_prior("one", B, "seq", {"flops": 99, "bytes": 99})
    first = t.pick("one", B)
    # Cheapest estimated bytes leads the explore order.
    assert first == "hybrid"


def test_persistence_cold_table_reproduces_winner(tmp_path):
    """The acceptance contract: a COLD tuner loading the persisted
    table picks the learned winner on its very first dispatch — no
    re-exploration after a restart."""
    path = tmp_path / "tuner.json"
    warm = EngineTuner(path=path, explore_rounds=1)
    for _ in range(len(ENGINES)):
        e = warm.pick("whatif", B)
        warm.observe("whatif", B, e, 0.2 if e == "packed" else 2.0)
    assert warm.save()
    cold = EngineTuner(path=path, explore_rounds=1, reprobe_every=0)
    assert cold.stats()["loaded-from-disk"]
    assert cold.pick("whatif", B) == "packed"
    decisions = telemetry.snapshot(
        prefix="holo_pipeline_tuner_decisions"
    )
    key = "holo_pipeline_tuner_decisions_total{kind=whatif,engine=packed,phase=exploit}"
    assert decisions.get(key, 0) >= 1, decisions


def test_persistence_version_mismatch_discarded(tmp_path):
    path = tmp_path / "tuner.json"
    path.write_text('{"version": 999, "buckets": {"bogus": {}}}')
    t = EngineTuner(path=path)
    assert not t.stats()["loaded-from-disk"]
    assert t.stats()["buckets"] == 0


def test_persistence_corrupt_file_is_relearned(tmp_path):
    path = tmp_path / "tuner.json"
    path.write_text("{not json")
    t = EngineTuner(path=path)
    assert t.stats()["buckets"] == 0
    e = t.pick("one", B)
    assert e in ENGINES


def test_depth_cap_scales_with_measured_ratio(tmp_path):
    t = EngineTuner(default_delta_depth=256)
    b = shape_bucket(500, 2000, 1, None)
    # No per-bucket measurements: the static default — unless an
    # earlier test in this process already populated the global
    # profiling-stage fallback (holo_profile_stage_seconds is
    # process-wide), in which case the fallback ratio applies.
    from holo_tpu.telemetry import profiling

    if (
        profiling.stage_median("spf.one", "delta") is None
        or profiling.stage_median("spf.one", "marshal") is None
    ):
        assert t.max_delta_depth(b) == 256
    for _ in range(DEPTH_MIN_SAMPLES):
        t.observe_delta(b, 0.001)
        t.observe_full(b, 0.040)  # delta 40x cheaper
    assert t.max_delta_depth(b) == 40 * DEPTH_SCALE
    # A bucket where the delta barely wins gets a shallow cap (floor).
    b2 = shape_bucket(50, 100, 1, None)
    for _ in range(DEPTH_MIN_SAMPLES):
        t.observe_delta(b2, 0.010)
        t.observe_full(b2, 0.011)
    assert t.max_delta_depth(b2) == DEPTH_SCALE
    # Depth observations round-trip through the persisted table.
    path = tmp_path / "tuner.json"
    assert t.save(path)
    cold = EngineTuner(path=path)
    assert cold.max_delta_depth(b) == 40 * DEPTH_SCALE


def test_device_graph_cache_consults_tuned_depth_cap():
    """Integration (PR 7 follow-up satellite): with a tuner armed, the
    shared DeviceGraphCache's delta-chain cap comes from the measured
    per-shape table — a shallow tuned cap forces the full-rebuild path
    exactly like the static knob, bit-identically."""
    from holo_tpu.ops.graph import diff_topologies
    from holo_tpu.ops.spf_engine import shared_graph_cache
    from holo_tpu.spf.backend import ScalarSpfBackend, TpuSpfBackend
    from holo_tpu.spf.synth import clone_topology, random_ospf_topology

    topo = random_ospf_topology(
        n_routers=30, n_networks=5, extra_p2p=15, seed=9
    )
    t = pipeline.configure_engine_tuner()
    b = shape_bucket(topo.n_vertices, topo.n_edges, 1, None)
    # Teach the tuner this shape barely benefits: cap = DEPTH_SCALE.
    for _ in range(DEPTH_MIN_SAMPLES):
        t.observe_delta(b, 1.0)
        t.observe_full(b, 1.0)
    assert shared_graph_cache()._depth_cap(topo) == DEPTH_SCALE
    # And the dispatch stays bit-identical either way.
    be = TpuSpfBackend()
    oracle = ScalarSpfBackend()
    be.compute(topo)
    rng = np.random.default_rng(11)
    cur = topo
    for _ in range(3):
        e = int(rng.integers(0, cur.n_edges))
        nxt = clone_topology(cur, cost={e: int(rng.integers(1, 64))})
        d = diff_topologies(cur, nxt)
        nxt.link_delta(d)
        res = be.compute(nxt)
        ref = oracle.compute(nxt)
        for f in ("dist", "parent", "hops", "nexthop_words"):
            assert np.array_equal(getattr(ref, f), getattr(res, f)), f
        cur = nxt


def test_backend_tuner_flips_are_parity_invariant():
    """Engine choice is a latency decision, never a semantic one: with
    the tuner exploring all four formulations across dispatches, every
    result stays bit-identical to the scalar oracle."""
    from holo_tpu.spf.backend import ScalarSpfBackend, TpuSpfBackend
    from holo_tpu.spf.synth import random_ospf_topology

    pipeline.configure_engine_tuner(explore_rounds=2)
    topo = random_ospf_topology(
        n_routers=40, n_networks=6, extra_p2p=25, seed=13
    )
    be = TpuSpfBackend(incremental=False)
    ref = ScalarSpfBackend().compute(topo)
    engines_used = set()
    for _ in range(10):
        res = be.compute(topo)
        for f in ("dist", "parent", "hops", "nexthop_words"):
            assert np.array_equal(getattr(ref, f), getattr(res, f)), f
        t = pipeline.active_tuner()
        st = t.stats()["winners"]
        for entry in st.values():
            engines_used.update(entry["measured-engines"])
    assert len(engines_used) == len(ENGINES), engines_used


def test_tuner_metrics_family_present():
    pipeline.configure_engine_tuner(explore_rounds=1)
    t = pipeline.active_tuner()
    e = t.pick("one", B)
    t.observe("one", B, e, 1.0)
    snap = telemetry.snapshot(prefix="holo_pipeline_tuner")
    assert any(
        k.startswith("holo_pipeline_tuner_decisions_total") for k in snap
    ), snap
    assert snap.get("holo_pipeline_tuner_buckets", 0) >= 1

"""BGP policy-worker offload: async evaluation + stale-result discard."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.protocols.bgp import BgpInstance, PeerConfig, PeerState
from holo_tpu.protocols.bgp_worker import PolicyWorker
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.policy import PolicyEngine
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def engine():
    e = PolicyEngine()
    e.load_from_config(
        {
            "defined-sets": {
                "prefix-set": {"blocked": {"prefix": ["203.0.113.0/24"]}},
            },
            "policy-definition": {
                "edge-in": {
                    "statement": {
                        "drop": {
                            "conditions": {"match-prefix-set": "blocked"},
                            "actions": {"policy-result": "reject-route"},
                        },
                        "ok": {
                            "actions": {"policy-result": "accept-route",
                                        "set-metric": 777},
                        },
                    }
                }
            },
        }
    )
    return e


def test_worker_offload_filters_and_rewrites():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    worker = PolicyWorker(engine())
    loop.register(worker)
    b1 = BgpInstance("b1", 65001, A("1.1.1.1"), fabric.sender_for("b1"))
    b2 = BgpInstance("b2", 65002, A("2.2.2.2"), fabric.sender_for("b2"),
                     policy_worker="bgp-policy-worker")
    loop.register(b1)
    loop.register(b2)
    fabric.join("l", "b1", "e0", A("10.0.0.1"))
    fabric.join("l", "b2", "e0", A("10.0.0.2"))
    b1.add_peer(PeerConfig(A("10.0.0.2"), 65002, "e0"), A("10.0.0.1"))
    # String policy name triggers the async worker path.
    b2.add_peer(PeerConfig(A("10.0.0.1"), 65001, "e0",
                           import_policy="edge-in"), A("10.0.0.2"))
    b1.start_peer(A("10.0.0.2"))
    b2.start_peer(A("10.0.0.1"))
    loop.advance(5)
    assert b2.peers[A("10.0.0.1")].state == PeerState.ESTABLISHED
    b1.originate(N("203.0.113.0/24"))
    b1.originate(N("198.51.100.0/24"))
    loop.advance(2)
    assert worker.batches_processed >= 1
    assert N("203.0.113.0/24") not in b2.loc_rib  # rejected in the worker
    best = b2.loc_rib[N("198.51.100.0/24")][0]
    assert best.attrs.med == 777  # rewritten in the worker


def test_stale_worker_results_discarded():
    """A result for a flapped session generation must not be applied."""
    from holo_tpu.protocols.bgp import PathAttrs
    from holo_tpu.protocols.bgp_worker import EvalBatchResult

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    b = BgpInstance("b", 65001, A("1.1.1.1"), fabric.sender_for("b"),
                    policy_worker="w")
    loop.register(b)
    fabric.join("l", "b", "e0", A("10.0.0.1"))
    peer = b.add_peer(PeerConfig(A("10.0.0.9"), 65002, "e0"), A("10.0.0.1"))
    peer.state = PeerState.ESTABLISHED
    old_gen = peer.generation
    # Session flaps: generation bumps.
    b._drop_peer(peer)
    peer.state = PeerState.ESTABLISHED  # re-established incarnation
    loop.send("b", EvalBatchResult(
        peer=A("10.0.0.9"), peer_generation=old_gen,
        entries=[(N("10.5.0.0/16"), PathAttrs())],
    ))
    loop.run_until_idle()
    assert N("10.5.0.0/16") not in peer.adj_rib_in  # stale: discarded
    # Fresh-generation result applies.
    loop.send("b", EvalBatchResult(
        peer=A("10.0.0.9"), peer_generation=peer.generation,
        entries=[(N("10.5.0.0/16"), PathAttrs())], token=1,
    ))
    loop.run_until_idle()
    assert N("10.5.0.0/16") in peer.adj_rib_in


def test_withdraw_beats_inflight_result():
    """A withdraw processed after the batch was requested must win over
    the in-flight policy result (no route resurrection)."""
    from holo_tpu.protocols.bgp import PathAttrs
    from holo_tpu.protocols.bgp_worker import EvalBatchResult

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    b = BgpInstance("b", 65001, A("1.1.1.1"), fabric.sender_for("b"),
                    policy_worker="w")
    loop.register(b)
    fabric.join("l", "b", "e0", A("10.0.0.1"))
    peer = b.add_peer(PeerConfig(A("10.0.0.9"), 65002, "e0"), A("10.0.0.1"))
    peer.state = PeerState.ESTABLISHED
    # Announcement batched at seq 1 (simulated), withdraw arrives at seq 2.
    peer.update_seq = 2
    peer.last_withdraw_seq[N("10.5.0.0/16")] = 2
    loop.send("b", EvalBatchResult(
        peer=A("10.0.0.9"), peer_generation=peer.generation,
        entries=[(N("10.5.0.0/16"), PathAttrs())], token=1,
    ))
    loop.run_until_idle()
    assert N("10.5.0.0/16") not in peer.adj_rib_in

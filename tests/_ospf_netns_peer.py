"""Helper: run one OSPF instance over raw sockets (launched in a netns).

Usage: python _ospf_netns_peer.py <ifname> <router-id> <addr/plen> <seconds>
Prints "FULL <nbr-id>" when the adjacency reaches FULL, then keeps running
until the deadline so the peer can finish DD/flooding.
"""

import sys
import time
from ipaddress import IPv4Address, IPv4Interface

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from holo_tpu.protocols.ospf.instance import (  # noqa: E402
    IfConfig,
    IfUpMsg,
    InstanceConfig,
    OspfInstance,
)
from holo_tpu.protocols.ospf.interface import IfType  # noqa: E402
from holo_tpu.protocols.ospf.neighbor import NsmState  # noqa: E402
from holo_tpu.utils.ip import ALL_SPF_RTRS_V4  # noqa: E402
from holo_tpu.utils.native_runtime import EPOLLIN, NativePoller  # noqa: E402
from holo_tpu.utils.rawsock import RawSocketIo  # noqa: E402
from holo_tpu.utils.runtime import EventLoop  # noqa: E402


def main() -> None:
    ifname, rid, addr, seconds = (
        sys.argv[1],
        sys.argv[2],
        IPv4Interface(sys.argv[3]),
        float(sys.argv[4]),
    )
    loop = EventLoop()
    io = RawSocketIo(loop)
    inst = OspfInstance(
        name="peer",
        config=InstanceConfig(router_id=IPv4Address(rid)),
        netio=io,
    )
    loop.register(inst)
    cfg = IfConfig(if_type=IfType.POINT_TO_POINT, cost=5,
                   hello_interval=1, dead_interval=4)
    inst.add_interface(ifname, cfg, addr.network, addr.ip)
    io.open_interface(ifname, "peer", [ALL_SPF_RTRS_V4])
    poller = NativePoller()
    for fd in io.fds():
        poller.add(fd, EPOLLIN)
    loop.send("peer", IfUpMsg(ifname))

    deadline = time.monotonic() + seconds
    announced = False
    while time.monotonic() < deadline:
        loop.run_until_idle()
        for fd, _ in poller.wait(50):
            io.pump(fd)
        if not announced:
            for area in inst.areas.values():
                for iface in area.interfaces.values():
                    for nbr in iface.neighbors.values():
                        if nbr.state == NsmState.FULL:
                            print(f"FULL {nbr.router_id}", flush=True)
                            announced = True
    print(f"ROUTES {len(inst.routes)}", flush=True)


if __name__ == "__main__":
    main()

"""Per-interface Tx tasks: bounded backpressure + isolation
(reference holo-ospf/src/tasks.rs:288-348)."""

import threading
import time

from holo_tpu.utils.netio import NetIo
from holo_tpu.utils.txqueue import TxTaskNetIo


class _Sink(NetIo):
    def __init__(self, slow_ifaces=()):
        self.sent = []
        self.slow = set(slow_ifaces)
        self.lock = threading.Lock()
        self.gate = threading.Event()

    def send(self, ifname, src, dst, data):
        if ifname in self.slow:
            self.gate.wait(timeout=10)
        with self.lock:
            self.sent.append((ifname, data))


def test_per_interface_ordering_and_delivery():
    sink = _Sink()
    tx = TxTaskNetIo(sink, maxsize=64)
    for i in range(200):
        tx.send("e0", None, None, ("e0", i))
        tx.send("e1", None, None, ("e1", i))
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and len(sink.sent) < 400:
        time.sleep(0.01)
    assert len(sink.sent) == 400
    # FIFO preserved per interface.
    for ifname in ("e0", "e1"):
        seq = [d[1] for n, d in sink.sent if n == ifname]
        assert seq == sorted(seq)
    tx.close()


def test_slow_interface_backpressures_only_itself():
    sink = _Sink(slow_ifaces={"slow0"})
    tx = TxTaskNetIo(sink, maxsize=4)

    blocked_at = []

    def producer():
        for i in range(10):  # > maxsize: the producer must block
            tx.send("slow0", None, None, i)
        blocked_at.append(time.monotonic())

    th = threading.Thread(target=producer)
    th.start()
    time.sleep(0.2)
    # The slow interface's producer is stuck (queue full, consumer gated)…
    assert th.is_alive(), "bounded queue did not backpressure"
    assert tx.queue_depth("slow0") == 4
    # …while another interface transmits freely.
    for i in range(50):
        tx.send("fast0", None, None, i)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        with sink.lock:
            if sum(1 for n, _ in sink.sent if n == "fast0") == 50:
                break
        time.sleep(0.01)
    with sink.lock:
        assert sum(1 for n, _ in sink.sent if n == "fast0") == 50
    # Open the gate: the blocked producer completes and nothing was lost.
    sink.gate.set()
    th.join(timeout=5)
    assert not th.is_alive()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        with sink.lock:
            if sum(1 for n, _ in sink.sent if n == "slow0") == 10:
                break
        time.sleep(0.01)
    with sink.lock:
        assert [d for n, d in sink.sent if n == "slow0"] == list(range(10))
    tx.close()


def test_close_drains_accepted_packets():
    sink = _Sink()
    tx = TxTaskNetIo(sink, maxsize=128)
    for i in range(100):
        tx.send("e0", None, None, i)
    tx.close()
    assert [d for _n, d in sink.sent] == list(range(100))


def test_unknown_attributes_forward_to_inner():
    """Transport-specific surface (e.g. BgpTcpIo.session_reset) must stay
    reachable through the wrapper — threaded isolation wraps the netio and
    BGP probes it via getattr (advisor r4, medium)."""

    class _TcpSink(_Sink):
        def __init__(self):
            super().__init__()
            self.resets = []

        def session_reset(self, peer):
            self.resets.append(peer)

    sink = _TcpSink()
    tx = TxTaskNetIo(sink, maxsize=8)
    fn = getattr(tx, "session_reset", None)
    assert fn is not None
    fn("10.0.0.2")
    assert sink.resets == ["10.0.0.2"]
    # Genuinely missing attributes still raise.
    try:
        tx.no_such_attr
        raise AssertionError("expected AttributeError")
    except AttributeError:
        pass
    tx.close()

"""Tropical min-plus matmul SPF engine (ISSUE 13): bit-identical parity
across every arm, tile-plane invariants, DeltaPath tile updates, tuner
integration.

The engine contract: the blocked min-plus distance fixpoint plus the
shared phase-2 machinery must be indistinguishable — bit-for-bit — from
the scalar oracle and every gather engine, across plain dispatches,
what-if edge masks (the exact repair-row path), DeltaPath chains (tiles
updated in place), the sharded mesh, breaker fallback, and the k>1
multipath planes (the DAG-tile contraction variant).
"""

import numpy as np
import pytest

from holo_tpu import pipeline
from holo_tpu.ops import tropical as trop
from holo_tpu.ops.graph import INF, build_ell, diff_topologies
from holo_tpu.ops.spf_engine import device_graph_from_ell, shared_graph_cache
from holo_tpu.spf.backend import ScalarSpfBackend, TpuSpfBackend
from holo_tpu.spf.synth import (
    clone_topology as clone,
    random_ospf_topology,
    whatif_link_failure_masks,
)
from holo_tpu.testing import no_implicit_transfers

N_ATOMS = 64
SPF_FIELDS = ("dist", "parent", "hops", "nexthop_words")
MP_FIELDS = ("parents", "pdist", "pweight", "npaths", "nh_weights")


@pytest.fixture(autouse=True)
def _clean():
    """Transfer sanitizer on every test; shared caches and tuner reset
    after (the suite shares its process with every tier-1 test)."""
    shared_graph_cache().clear()
    with no_implicit_transfers():
        yield
    pipeline.reset_engine_tuner()
    shared_graph_cache().clear()


def assert_spf(a, b, msg=""):
    for f in SPF_FIELDS:
        np.testing.assert_array_equal(
            getattr(a, f), getattr(b, f), err_msg=f"{msg}{f}"
        )


def assert_mp(a, b, msg=""):
    assert_spf(a, b, msg)
    for f in MP_FIELDS:
        np.testing.assert_array_equal(
            getattr(a, f), getattr(b, f), err_msg=f"{msg}{f}"
        )


# -- tile-plane invariants ----------------------------------------------


def test_tile_marshal_invariants():
    """Per row block: slot cb ascending with sentinel tail, pos grid
    the inverse map; every edge's entry the min over its parallel
    group; pad rows/cols INF inert."""
    topo = random_ospf_topology(
        n_routers=30, n_networks=6, extra_p2p=40, max_cost=4, seed=2
    )
    ell = build_ell(topo, n_atoms=N_ATOMS)
    tt, meta = trop.build_tiles_host(ell.in_src, ell.in_cost, ell.in_valid)
    nb, tm, b, _ = tt.tiles.shape
    n = topo.n_vertices
    assert nb * b >= n
    assert (meta["tm"], meta["block"], meta["nb"]) == (tm, b, nb)
    for r in range(nb):
        cbs = [int(c) for c in tt.cb[r]]
        real = [c for c in cbs if c < nb]
        assert real == sorted(real) and len(set(real)) == len(real)
        assert cbs[len(real):] == [nb] * (tm - len(real))
        for s, c in enumerate(real):
            assert int(tt.pos[r, c]) == s
        for s in range(len(real), tm):
            assert (tt.tiles[r, s] == int(INF)).all()
    # Dense expected matrix (min over parallel edges) vs tile entries —
    # in the marshal's PERMUTED vertex space (ISSUE 15: RCM relabeling
    # before blocking; perm/inv round-trip is asserted separately).
    perm, inv = meta["perm"], meta["inv"]
    assert np.array_equal(np.sort(perm), np.arange(n))
    assert np.array_equal(perm[inv], np.arange(n))
    want = np.full((nb * b, nb * b), int(INF), np.int64)
    rows, cols = np.nonzero(ell.in_valid)
    np.minimum.at(
        want,
        (inv[rows], inv[ell.in_src[rows, cols]]),
        ell.in_cost[rows, cols],
    )
    got = np.full_like(want, int(INF))
    for r in range(nb):
        for s in range(tm):
            c = int(tt.cb[r, s])
            if c < nb:
                got[r * b : (r + 1) * b, c * b : (c + 1) * b] = (
                    tt.tiles[r, s]
                )
    assert np.array_equal(got, want)
    # Tile-padding sentinels: rows/cols past N carry no edges.
    assert (got[n:] == int(INF)).all() and (got[:, n:] == int(INF)).all()


def test_tile_marshal_edgeless():
    """E=0 graphs marshal one inert all-INF tile (static shapes)."""
    from holo_tpu.ops.graph import Topology

    topo = Topology(
        n_vertices=1,
        is_router=np.ones(1, bool),
        edge_src=np.zeros(0, np.int32),
        edge_dst=np.zeros(0, np.int32),
        edge_cost=np.zeros(0, np.int32),
        root=0,
    )
    ell = build_ell(topo, n_atoms=N_ATOMS)
    tt, meta = trop.build_tiles_host(ell.in_src, ell.in_cost, ell.in_valid)
    assert (tt.tiles == int(INF)).all()
    scalar = ScalarSpfBackend(N_ATOMS).compute(topo)
    got = TpuSpfBackend(N_ATOMS, one_engine="tropical").compute(topo)
    assert_spf(scalar, got)


# -- device ≡ oracle parity, plain + masked arms -------------------------


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize(
    "shape",
    [
        dict(n_routers=12, n_networks=0),
        dict(n_routers=10, n_networks=4),
        # extra_p2p creates parallel (src, dst) edges: the collapsed
        # min-tile + repair-row path must stay exact through them.
        dict(n_routers=40, n_networks=10, extra_p2p=60),
    ],
)
def test_single_spf_parity(seed, shape):
    topo = random_ospf_topology(seed=seed, **shape)
    scalar = ScalarSpfBackend(N_ATOMS).compute(topo)
    got = TpuSpfBackend(N_ATOMS, one_engine="tropical").compute(topo)
    assert_spf(scalar, got)


@pytest.mark.parametrize("seed", range(3))
def test_whatif_batch_parity(seed):
    """Masked scenarios: the repair rows must reproduce the masked
    relaxation exactly (failed edges only affect their destinations)."""
    topo = random_ospf_topology(
        n_routers=16, n_networks=5, extra_p2p=20, seed=seed
    )
    masks = whatif_link_failure_masks(topo, n_scenarios=8, seed=seed)
    scalar = ScalarSpfBackend(N_ATOMS).compute_whatif(topo, masks)
    got = TpuSpfBackend(N_ATOMS, one_engine="tropical").compute_whatif(
        topo, masks
    )
    for i, (s, t) in enumerate(zip(scalar, got)):
        assert_spf(s, t, msg=f"scenario {i} ")


def test_root_disconnect_mask():
    """Worst-case mask: every root edge failed — repair rows cover the
    root's whole neighborhood, everything else unreachable."""
    topo = random_ospf_topology(n_routers=8, n_networks=2, seed=1)
    mask = np.ones(topo.n_edges, bool)
    for e in range(topo.n_edges):
        if topo.edge_src[e] == topo.root or topo.edge_dst[e] == topo.root:
            mask[e] = False
    scalar = ScalarSpfBackend(N_ATOMS).compute(topo, mask)
    got = TpuSpfBackend(N_ATOMS, one_engine="tropical").compute(topo, mask)
    assert_spf(scalar, got)
    unreachable = np.arange(topo.n_vertices) != topo.root
    assert (got.dist[unreachable] == INF).all()


def test_multiroot_parity():
    topo = random_ospf_topology(n_routers=12, n_networks=3, seed=7)
    roots = np.array(
        [i for i in range(topo.n_vertices) if topo.is_router[i]][:4],
        np.int32,
    )
    want = TpuSpfBackend(N_ATOMS).compute_multiroot(topo, roots)
    got = TpuSpfBackend(N_ATOMS, one_engine="tropical").compute_multiroot(
        topo, roots
    )
    for f in ("dist", "parent", "hops"):
        np.testing.assert_array_equal(
            getattr(want, f), getattr(got, f), err_msg=f
        )


def test_multiroot_masked_parity():
    """A non-trivial edge mask shared by every root lane must ride the
    repair-row machinery: tropical_multiroot ≡ spf_multiroot bit-for-
    bit under the mask (regression: the mask used to skip the distance
    fixpoint entirely)."""
    import jax

    from holo_tpu.ops.spf_engine import spf_multiroot

    topo = random_ospf_topology(
        n_routers=14, n_networks=4, extra_p2p=20, seed=1
    )
    mask = np.ones(topo.n_edges, bool)
    mask[::3] = False  # fail every 3rd edge
    roots = np.arange(3, dtype=np.int32)
    ell = build_ell(topo, n_atoms=N_ATOMS)
    g = jax.device_put(device_graph_from_ell(ell))
    tt = jax.device_put(
        trop.build_tiles_host(ell.in_src, ell.in_cost, ell.in_valid)[0]
    )
    rr = trop.repair_rows_host(
        topo.edge_dst, mask[None, :], topo.n_vertices
    )[0]
    mask_dev = jax.device_put(mask)
    rr_dev = jax.device_put(rr)
    roots_dev = jax.device_put(roots)
    want = jax.jit(lambda *a: spf_multiroot(*a))(g, roots_dev, mask_dev)
    got = jax.jit(lambda *a: trop.tropical_multiroot(*a))(
        g, tt, roots_dev, mask_dev, rr_dev
    )
    for f in ("dist", "parent", "hops", "nexthops"):
        np.testing.assert_array_equal(
            np.asarray(getattr(want, f)),
            np.asarray(getattr(got, f)),
            err_msg=f,
        )


def test_whatif_chunked_lanes():
    """The lane-chunked (lax.map) what-if path is bit-identical to the
    single-chunk program."""
    import jax

    topo = random_ospf_topology(n_routers=14, n_networks=4, seed=4)
    masks = whatif_link_failure_masks(topo, n_scenarios=10, seed=4)
    ell = build_ell(topo, n_atoms=N_ATOMS)
    g = jax.device_put(device_graph_from_ell(ell))
    tt = jax.device_put(
        trop.build_tiles_host(ell.in_src, ell.in_cost, ell.in_valid)[0]
    )
    rr = trop.repair_rows_host(topo.edge_dst, masks, topo.n_vertices)
    # Explicit puts only: the autouse transfer guard stays armed.
    root = jax.device_put(np.int32(topo.root))
    masks_dev = jax.device_put(masks)
    rr_dev = jax.device_put(rr)
    whole = jax.jit(
        lambda *a: trop.tropical_whatif_batch(*a)
    )(g, tt, root, masks_dev, rr_dev)
    chunked = jax.jit(
        lambda *a: trop.tropical_whatif_batch(*a, chunk=4)
    )(g, tt, root, masks_dev, rr_dev)
    for f in ("dist", "parent", "hops", "nexthops"):
        np.testing.assert_array_equal(
            np.asarray(getattr(whole, f)),
            np.asarray(getattr(chunked, f)),
            err_msg=f,
        )


# -- k>1 multipath (the A-lane consumer) ---------------------------------


@pytest.mark.parametrize("k", [2, 8])
@pytest.mark.parametrize("seed", range(3))
def test_multipath_parity(k, seed):
    """mp_tropical (DAG-tile contraction planes) ≡ the scalar multipath
    oracle, tied weights forcing real ECMP/UCMP mass."""
    topo = random_ospf_topology(
        n_routers=20, n_networks=5, extra_p2p=30, max_cost=3, seed=seed
    )
    scalar = ScalarSpfBackend(N_ATOMS).compute(topo, multipath_k=k)
    got = TpuSpfBackend(N_ATOMS, one_engine="tropical").compute(
        topo, multipath_k=k
    )
    assert_mp(scalar, got, msg=f"k={k} ")


# -- DeltaPath chains: tiles updated in place ----------------------------


def test_delta_chain_parity_and_inplace_tiles():
    topo = random_ospf_topology(
        n_routers=18, n_networks=4, extra_p2p=10, max_cost=5, seed=7
    )
    be = TpuSpfBackend(N_ATOMS, one_engine="tropical")
    sc = ScalarSpfBackend(N_ATOMS)
    assert_spf(sc.compute(topo), be.compute(topo))
    before = shared_graph_cache().stats()
    assert before["tropical-entries"] >= 1
    cur = topo
    for step in range(8):
        op = step % 3
        if op == 0:  # weight change (ids stable)
            nxt = clone(cur, cost={(step * 3) % cur.n_edges: 1 + step})
        elif op == 1:  # drop a directed edge pair member
            keep = np.ones(cur.n_edges, bool)
            keep[(step * 5) % cur.n_edges] = False
            nxt = clone(cur, keep=keep)
        else:  # add a directed edge
            nxt = clone(
                cur, extra=[[step % cur.n_vertices, (step + 3) % cur.n_vertices, 2, -1]]
            )
        d = diff_topologies(cur, nxt)
        assert d is not None
        nxt.link_delta(d)
        assert_spf(sc.compute(nxt), be.compute(nxt), msg=f"step {step} ")
        cur = nxt
    stats = shared_graph_cache().stats()
    assert stats["deltas-applied"] >= 8, stats
    # The chain kept a live tile attachment (or lazily rebuilt one):
    # the final entry serves tropical without a full re-marshal.
    assert stats["tropical-entries"] >= 1, stats


def test_delta_overload_strikes_tiles():
    """A transit strike must mask the struck vertex's tile COLUMNS in
    place — the relaxation may still reach it, never through it."""
    from holo_tpu.ops.graph import TopologyDelta

    topo = random_ospf_topology(n_routers=14, n_networks=3, seed=9)
    be = TpuSpfBackend(N_ATOMS, one_engine="tropical")
    sc = ScalarSpfBackend(N_ATOMS)
    be.compute(topo)
    strike = next(
        v
        for v in range(topo.n_vertices)
        if topo.is_router[v] and v != topo.root
    )
    keep = topo.edge_src != strike
    nxt = clone(topo, keep=keep)
    nxt.link_delta(
        TopologyDelta(
            base_key=topo.cache_key,
            overload=np.asarray([strike], np.int32),
            ids_stable=False,
        )
    )
    assert_spf(sc.compute(nxt), be.compute(nxt))


# -- sharded mesh arms ---------------------------------------------------


@pytest.mark.parametrize("shape", [(8, 1), (4, 2), (2, 4)])
def test_sharded_mesh_parity(shape):
    """Every tropical arm under a process mesh (batch- and node-
    sharded): one, delta chain (tiles updated in place under the
    mesh), what-if, multipath, multiroot — byte-identical to the
    scalar oracle.  Runs on the conftest's forced 8-device virtual CPU
    platform."""
    from holo_tpu.parallel.mesh import (
        configure_process_mesh,
        process_mesh,
        reset_process_mesh,
    )

    topo = random_ospf_topology(
        n_routers=20, n_networks=5, extra_p2p=12, max_cost=4, seed=3
    )
    masks = whatif_link_failure_masks(topo, 6, seed=2)
    sc = ScalarSpfBackend(N_ATOMS)
    roots = np.array(
        [i for i in range(topo.n_vertices) if topo.is_router[i]][:5],
        np.int32,
    )
    configure_process_mesh(*shape)
    try:
        be = TpuSpfBackend(N_ATOMS, one_engine="tropical")
        assert_spf(sc.compute(topo), be.compute(topo), msg="one ")
        cur = topo
        for step in range(3):
            nxt = clone(cur, cost={(step * 5) % cur.n_edges: 2 + step})
            d = diff_topologies(cur, nxt)
            nxt.link_delta(d)
            assert_spf(
                sc.compute(nxt), be.compute(nxt), msg=f"delta{step} "
            )
            cur = nxt
        for a, b in zip(
            sc.compute_whatif(topo, masks), be.compute_whatif(topo, masks)
        ):
            assert_spf(a, b, msg="whatif ")
        assert_mp(
            sc.compute(topo, multipath_k=4),
            be.compute(topo, multipath_k=4),
            msg="mp ",
        )
        mr_s = sc.compute_multiroot(topo, roots)
        mr_t = be.compute_multiroot(topo, roots)
        for f in ("dist", "parent", "hops"):
            np.testing.assert_array_equal(
                getattr(mr_s, f), getattr(mr_t, f), err_msg=f"mr {f}"
            )
    finally:
        reset_process_mesh()
    assert process_mesh() is None


# -- breaker fallback arm ------------------------------------------------


def test_breaker_fallback_bit_identical():
    from holo_tpu.resilience import CircuitBreaker, FaultPlan, inject

    topo = random_ospf_topology(n_routers=14, n_networks=4, seed=3)
    masks = whatif_link_failure_masks(topo, n_scenarios=6, seed=3)
    scalar = ScalarSpfBackend(N_ATOMS).compute_whatif(topo, masks)
    be = TpuSpfBackend(
        N_ATOMS,
        one_engine="tropical",
        breaker=CircuitBreaker("tropical-parity-fallback"),
    )
    with inject(FaultPlan(dispatch_fail={"spf.dispatch": 1})) as inj:
        got = be.compute_whatif(topo, masks)
    assert inj.injected["spf.dispatch"] == 1
    for s, t in zip(scalar, got):
        assert_spf(s, t)
    assert be.breaker.state == "closed"
    got2 = be.compute_whatif(topo, masks)  # healthy: device path again
    for s, t in zip(scalar, got2):
        assert_spf(s, t)
    assert be.breaker.consecutive_failures == 0


# -- tuner integration ---------------------------------------------------


def test_tuner_explores_tropical_and_mp_family():
    """The armed tuner A/Bs tropical per shape bucket (kind one/whatif)
    and the mp pair for k>1 single dispatches — results bit-identical
    throughout, so the flips are latency-only."""
    from holo_tpu.pipeline.tuner import ENGINES, MP_ENGINES

    t = pipeline.configure_engine_tuner(explore_rounds=1, reprobe_every=0)
    topo = random_ospf_topology(n_routers=14, n_networks=4, seed=1)
    sc = ScalarSpfBackend(N_ATOMS)
    be = TpuSpfBackend(N_ATOMS)
    ref = sc.compute(topo)
    for i in range(2 * len(ENGINES) + 2):
        assert_spf(ref, be.compute(topo), msg=f"one i={i} ")
    mref = sc.compute(topo, multipath_k=8)
    for i in range(2 * len(MP_ENGINES) + 2):
        assert_mp(mref, be.compute(topo, multipath_k=8), msg=f"mp i={i} ")
    measured = set()
    for v in t.stats()["winners"].values():
        measured |= set(v["measured-engines"])
    assert "tropical" in measured
    assert {"mp", "mp_tropical"} <= measured


def test_tuner_bucket_keying_mp_candidates():
    """Candidate sets per bucket: k=1 buckets choose among the gather +
    tropical family; k>1 kind=one among the mp pair; k>1 what-if stays
    mp-only (the per-scenario DAG-tile scatter would multiply by B)."""
    from holo_tpu.pipeline.tuner import (
        ENGINES,
        MP_ENGINES,
        EngineTuner,
        shape_bucket,
    )

    t = EngineTuner(explore_rounds=1, reprobe_every=0)
    b1 = shape_bucket(1000, 4000, 1, None, k=1)
    b8 = shape_bucket(1000, 4000, 1, None, k=8)
    assert t._candidates("one", b1) == ENGINES
    assert "tropical" in t._candidates("whatif", b1)
    assert t._candidates("one", b8) == MP_ENGINES
    assert t._candidates("whatif", b8) == ("mp",)
    # mp-family winners stand on their own bucket.
    t.observe("one", b8, "mp", 2.0)
    t.observe("one", b8, "mp_tropical", 0.5)
    assert t.current_winner("one", b8) == "mp_tropical"
    assert t.current_winner("one", b1) is None  # never measured


def test_tuner_table_v2_discarded(tmp_path):
    """Version migration: a persisted v2 table (pre-tropical engine
    set) must be discarded cleanly — the tuner re-learns instead of
    exploiting winners measured over the old candidate set."""
    import json

    from holo_tpu.pipeline.tuner import TABLE_VERSION, EngineTuner

    assert TABLE_VERSION == 3
    p = tmp_path / "tuner.json"
    p.write_text(
        json.dumps(
            {
                "version": 2,
                "engines": ["seq", "fused", "packed", "hybrid"],
                "buckets": {
                    '["one", 1024, 4096, 1, null, 1]': {
                        "dispatches": 99,
                        "winner": "seq",
                        "samples": {"seq": [0.001]},
                        "cost": {},
                    }
                },
                "depth": {},
            }
        )
    )
    t = EngineTuner(path=p)
    assert not t._loaded
    assert t.stats()["buckets"] == 0
    # A fresh save/load round-trips at v3.
    assert t.save()
    t2 = EngineTuner(path=p)
    assert t2._loaded


def test_explain_ledger_win_basis_for_tropical():
    """`holo-tpu-tools explain` surfaces WHY tropical wins or loses a
    bucket on the cost model's axes ("won on flops, not bytes")."""
    from holo_tpu.pipeline.tuner import EngineTuner, shape_bucket

    t = EngineTuner(explore_rounds=1, reprobe_every=0)
    b = shape_bucket(10000, 700000, 128, None, k=1)
    # Tropical: more flops, fewer bytes, fastest wall (the MXU story).
    t.cost_prior("whatif", b, "tropical", {"flops": 9e9, "bytes": 1e8})
    t.cost_prior("whatif", b, "seq", {"flops": 1e9, "bytes": 9e8})
    t.observe("whatif", b, "seq", 0.200)
    t.observe("whatif", b, "tropical", 0.020)
    row = next(r for r in t.ledger() if r["kind"] == "whatif")
    assert row["winner"] == "tropical"
    assert row["basis"] == "tropical beat seq on bytes"
    assert row["engines"]["tropical"]["cost"]["flops"] == 9e9


def test_incremental_routes_through_tropical_winner():
    """A bucket whose measured full-dispatch winner is tropical routes
    its DeltaPath incremental kernel through the tiles (and stays
    bit-identical)."""
    from holo_tpu.pipeline.tuner import shape_bucket

    t = pipeline.configure_engine_tuner(explore_rounds=1, reprobe_every=0)
    topo = random_ospf_topology(n_routers=16, n_networks=4, seed=5)
    from holo_tpu.parallel.mesh import mesh_cache_key

    b = shape_bucket(
        topo.n_vertices, topo.n_edges, 1, mesh_cache_key(), k=1
    )
    # Pre-seed measurements so exploit picks tropical immediately.
    for e, wall in (
        ("seq", 0.1), ("fused", 0.1), ("packed", 0.1),
        ("hybrid", 0.1), ("tropical", 0.001),
    ):
        t.observe("one", b, e, wall)
    be = TpuSpfBackend(N_ATOMS)
    sc = ScalarSpfBackend(N_ATOMS)
    assert be._trop_incremental(topo, 1)
    assert_spf(sc.compute(topo), be.compute(topo))
    nxt = clone(topo, cost={0: 7})
    d = diff_topologies(topo, nxt)
    nxt.link_delta(d)
    assert_spf(sc.compute(nxt), be.compute(nxt))


def test_fuzz_target_registered():
    from holo_tpu.tools.fuzz import targets, tropical_tile_invariants

    assert targets()["tropical_tile_invariants"] is tropical_tile_invariants
    # One seeded pass of the invariant body (the coverage loop rides
    # tests/test_fuzz_coverage.py).
    tropical_tile_invariants(bytes([2, 3, 5, 1]))


# -- SRLG satellite ------------------------------------------------------


def test_srlg_bits_and_interface_wiring():
    from holo_tpu.protocols.ospf.spf_run import (
        apply_interface_srlg,
        srlg_bits,
    )

    assert srlg_bits(()) == 0
    assert srlg_bits((0, 3)) == 0b1001
    assert srlg_bits((35,)) == srlg_bits((3,))  # mod-32 fold
    topo = random_ospf_topology(n_routers=8, n_networks=2, seed=0)
    atom_ifnames = []
    n_atoms = int(topo.edge_direct_atom.max()) + 1
    atom_ifnames = [
        ("eth0" if a % 2 == 0 else "eth1") for a in range(n_atoms)
    ]
    apply_interface_srlg(topo, atom_ifnames, {"eth0": srlg_bits((1, 2))})
    for e in range(topo.n_edges):
        a = int(topo.edge_direct_atom[e])
        want = (
            srlg_bits((1, 2))
            if a >= 0 and atom_ifnames[a] == "eth0"
            else 0
        )
        assert int(topo.edge_srlg[e]) == want, f"edge {e}"


def test_srlg_interface_config_fields():
    """The fast-reroute SRLG seam exists on every protocol's interface
    config (OSPFv2/v3 + IS-IS) — the ROADMAP carry-over's config
    surface."""
    from holo_tpu.protocols.isis.instance import IsisIfConfig
    from holo_tpu.protocols.ospf.instance_v3 import V3IfConfig
    from holo_tpu.protocols.ospf.interface import IfConfig

    for cls in (IfConfig, V3IfConfig, IsisIfConfig):
        assert cls().srlg == ()

"""C++ runtime core: timer wheel semantics, MPSC ring, epoll poller."""

import os
import shutil
import threading

import pytest

pytestmark = pytest.mark.skipif(shutil.which("g++") is None, reason="needs g++")


def test_timer_wheel_order_and_cancel():
    from holo_tpu.utils.native_runtime import NativeTimerWheel

    w = NativeTimerWheel()
    t1 = w.create(101)
    t2 = w.create(102)
    t3 = w.create(103)
    w.arm(t1, 0.010)
    w.arm(t2, 0.005)
    w.arm(t3, 2.000)  # lands in level-1 wheel
    assert w.advance(0.004) == []
    assert w.advance(0.006) == [102]
    assert w.advance(0.050) == [101]
    w.cancel(t3)
    assert w.advance(3.0) == []
    # re-arm after cancel works (generation bump)
    w.arm(t3, 3.5)
    assert w.advance(4.0) == [103]


def test_timer_wheel_rearm_replaces():
    from holo_tpu.utils.native_runtime import NativeTimerWheel

    w = NativeTimerWheel()
    t = w.create(7)
    w.arm(t, 0.010)
    w.arm(t, 0.100)  # reset: old deadline must not fire
    assert w.advance(0.050) == []
    assert w.advance(0.150) == [7]


def test_timer_wheel_many_long_timers():
    from holo_tpu.utils.native_runtime import NativeTimerWheel

    w = NativeTimerWheel()
    handles = [w.create(i) for i in range(500)]
    for i, h in enumerate(handles):
        w.arm(h, 0.001 * (i + 1) * 17 % 90 + 0.001)
    fired = w.advance(100.0)
    assert sorted(fired) == list(range(500))


def test_msg_ring_spsc_and_threads():
    from holo_tpu.utils.native_runtime import NativeMsgRing

    r = NativeMsgRing(capacity=64, slot_size=64)
    assert r.pop() is None
    assert r.push(b"hello")
    assert r.push(b"world")
    assert r.pop() == b"hello"
    assert r.pop() == b"world"

    # two producer threads, one consumer
    r2 = NativeMsgRing(capacity=1024, slot_size=16)
    n_each = 200

    def producer(tag):
        for i in range(n_each):
            while not r2.push(f"{tag}:{i}".encode()):
                pass

    ts = [threading.Thread(target=producer, args=(t,)) for t in ("a", "b")]
    for t in ts:
        t.start()
    got = []
    while len(got) < 2 * n_each:
        m = r2.pop()
        if m is not None:
            got.append(m)
    for t in ts:
        t.join()
    seq_a = [int(m.split(b":")[1]) for m in got if m.startswith(b"a")]
    seq_b = [int(m.split(b":")[1]) for m in got if m.startswith(b"b")]
    assert seq_a == list(range(n_each))  # per-producer FIFO preserved
    assert seq_b == list(range(n_each))


def test_poller_pipe_readiness():
    from holo_tpu.utils.native_runtime import EPOLLIN, NativePoller

    rfd, wfd = os.pipe()
    p = NativePoller()
    p.add(rfd, EPOLLIN)
    assert p.wait(0) == []
    os.write(wfd, b"x")
    events = p.wait(100)
    assert events and events[0][0] == rfd
    os.read(rfd, 1)
    assert p.wait(0) == []
    p.remove(rfd)
    os.close(rfd)
    os.close(wfd)


def test_monotonic_now_advances():
    import time

    from holo_tpu.utils.native_runtime import monotonic_now

    a = monotonic_now()
    time.sleep(0.01)
    assert monotonic_now() > a

"""Async dispatch pipeline semantics (ISSUE 9 tentpole contract).

Covers the queue/ownership contract the pipeline promises the protocol
layer: strict result ordering per (uid, root) key, coalescing of
superseded what-if batches, donation safety under depth-2 delta chains
(one in-flight entry per key — the DeltaPath ownership handoff),
breaker-open skip of advisory batches, split-phase breaker fallback
parity, and the mid-storm ``pipeline.dispatch`` crashpoint chaos test:
forced pipelined-dispatch failures must leave the final FIB
bit-identical to a synchronous control run, under
``jax.transfer_guard("disallow")``.
"""

import threading
import time

import numpy as np
import pytest

from holo_tpu import pipeline
from holo_tpu.ops.graph import diff_topologies
from holo_tpu.pipeline.dispatch import DispatchPipeline
from holo_tpu.resilience.breaker import CircuitBreaker
from holo_tpu.resilience.faults import FaultInjector, FaultPlan, inject
from holo_tpu.spf.backend import ScalarSpfBackend, TpuSpfBackend
from holo_tpu.spf.synth import (
    clone_topology,
    random_ospf_topology,
    whatif_link_failure_masks,
)
from holo_tpu.testing import no_implicit_transfers


@pytest.fixture(autouse=True)
def _clean_process_state():
    yield
    pipeline.reset_process_pipeline()
    pipeline.reset_engine_tuner()


def _topo(seed=1, n=30):
    return random_ospf_topology(
        n_routers=n, n_networks=5, extra_p2p=n // 2, seed=seed
    )


# -- core queue semantics ----------------------------------------------


def test_per_key_ordering_and_cross_key_progress():
    """Results complete in submission order per key; independent keys
    interleave freely (only per-key order is promised)."""
    pipe = DispatchPipeline(depth=2)
    done = []
    lock = threading.Lock()

    def work(key, i, delay):
        def run():
            time.sleep(delay)
            with lock:
                done.append((key, i))
            return (key, i)

        return run

    tickets = []
    for i in range(4):
        tickets.append(
            pipe.submit(("a", 0), "one", run=work("a", i, 0.01))
        )
        tickets.append(
            pipe.submit(("b", 0), "one", run=work("b", i, 0.0))
        )
    for t in tickets:
        t.result(timeout=10)
    pipe.close()
    for key in ("a", "b"):
        seq = [i for k, i in done if k == key]
        assert seq == sorted(seq), f"per-key order violated for {key}: {seq}"


def test_split_phase_overlap_and_single_inflight_per_key():
    """Split-phase items overlap across keys (launch i+1 while i is in
    flight) but NEVER within one key — the DeltaPath donation handoff.
    The stats probe records the max concurrent in-flight per key."""
    pipe = DispatchPipeline(depth=2)
    events = []
    lock = threading.Lock()

    def mk(key, i):
        def launch():
            with lock:
                events.append(("launch", key, i))
            return (key, i)

        def finish(h):
            time.sleep(0.02)
            with lock:
                events.append(("finish", key, i))
            return h

        return launch, finish

    tickets = []
    for i in range(3):
        for key in ("k1", "k2"):
            la, fi = mk(key, i)
            tickets.append(
                pipe.submit((key,), "one", launch=la, finish=fi)
            )
    for t in tickets:
        t.result(timeout=10)
    stats = pipe.stats()
    pipe.close()
    assert stats["max-inflight-per-key"] <= 1, stats
    # Per-key phase ordering: finish(i) precedes launch(i+1) for the
    # same key (the ownership handoff), even with depth-2 overlap.
    for key in ("k1", "k2"):
        seq = [(ev, i) for ev, k, i in events if k == key]
        for i in range(2):
            assert seq.index(("finish", i)) < seq.index(("launch", i + 1))
    # And some genuine overlap happened across keys.
    assert stats["overlap-seconds"] > 0.0


def test_whatif_coalescing_shared_and_superseded():
    pipe = DispatchPipeline(depth=1)
    release = threading.Event()
    ran = []

    def blocker():
        release.wait(5)
        return "blocker"

    def batch(gen):
        def run():
            ran.append(gen)
            return f"batch-{gen}"

        return run

    # Occupy the worker so subsequent submits stay queued.
    t0 = pipe.submit(("x",), "one", run=blocker)
    t1 = pipe.submit(("w",), "whatif", run=batch(1), generation=1,
                     coalesce=True)
    # Same (key, generation): shared ticket, no duplicate work.
    t1b = pipe.submit(("w",), "whatif", run=batch(1), generation=1,
                      coalesce=True)
    assert t1b is t1
    # Newer generation supersedes the queued older batch.
    t2 = pipe.submit(("w",), "whatif", run=batch(2), generation=2,
                     coalesce=True)
    release.set()
    assert t0.result(timeout=10) == "blocker"
    assert t2.result(timeout=10) == "batch-2"
    assert t1.result(timeout=10) is None and t1.superseded
    stats = pipe.stats()
    pipe.close()
    assert ran == [2], f"superseded batch must not run: {ran}"
    assert stats["coalesced"] == 2  # one shared + one superseded


def test_breaker_open_skips_advisory_batch_entirely():
    """While the circuit is open the what-if batch is not enqueued at
    all — no scalar re-run, no queue slot, just a skipped ticket (the
    ISSUE 9 breaker-awareness contract)."""
    pipe = DispatchPipeline(depth=1)
    breaker = CircuitBreaker(
        "pipeline-skip-test", failure_threshold=1, recovery_timeout=1e9
    )
    breaker.call(
        lambda: (_ for _ in ()).throw(RuntimeError("boom")),
        lambda: None,
    )
    assert breaker.state == "open"
    ran = []
    t = pipe.submit(
        ("w",), "whatif", run=lambda: ran.append(1), generation=1,
        coalesce=True, skip_when_open=breaker,
    )
    assert t.skipped and t.result(timeout=1) is None
    stats = pipe.stats()
    pipe.close()
    assert not ran
    assert stats["breaker-skipped"] == 1 and stats["submitted"] == 0


def test_async_whatif_breaker_open_skip_via_backend():
    topo = _topo(seed=3)
    masks = whatif_link_failure_masks(topo, 4, seed=1)
    pipe = pipeline.configure_process_pipeline(depth=2)
    breaker = CircuitBreaker(
        "async-whatif-test", failure_threshold=1, recovery_timeout=1e9
    )
    be = pipeline.wrap_spf_backend(TpuSpfBackend(breaker=breaker))
    # Healthy: the advisory batch computes and matches the oracle.
    ticket = be.compute_whatif_async(topo, masks)
    res = ticket.result(timeout=30)
    ref = ScalarSpfBackend().compute_whatif(topo, masks)
    for r, s in zip(ref, res):
        assert np.array_equal(r.dist, s.dist)
    # Open circuit: skipped outright.
    breaker.call(
        lambda: (_ for _ in ()).throw(RuntimeError("boom")),
        lambda: None,
    )
    assert breaker.state == "open"
    t2 = be.compute_whatif_async(topo, masks)
    assert t2.skipped and t2.result(timeout=1) is None


def test_passthrough_exception_surfaces_at_force_time():
    """Bug-class exceptions (TypeError & friends) must not be masked by
    the fallback: they re-raise on the caller's thread when the lazy
    result is forced — the synchronous passthrough contract — and
    release the breaker's probe slot without counting a failure."""
    pipe = pipeline.configure_process_pipeline(depth=1)
    inner = TpuSpfBackend()
    be = pipeline.wrap_spf_backend(inner)
    topo = _topo(seed=11)

    def buggy_launch(t, edge_mask=None):
        raise TypeError("bug, not a device failure")

    inner.launch_one = buggy_launch
    res = be.compute(topo)
    with pytest.raises(TypeError):
        _ = res.dist
    assert be.breaker.state == "closed"  # never counted as device failure
    assert be.breaker.consecutive_failures == 0
    pipe.close()


# -- parity + donation safety ------------------------------------------


def test_async_parity_and_delta_chain_donation_safety():
    """Depth-2 delta chains through the pipeline: consecutive deltas
    for ONE key are serialized by the ownership handoff, the resident
    graph + retained tensors are donated exactly as in the synchronous
    path, and every step is bit-identical to the scalar oracle.  Runs
    under the transfer sanitizer."""
    pipe = pipeline.configure_process_pipeline(
        depth=2, guard=no_implicit_transfers
    )
    be = pipeline.wrap_spf_backend(TpuSpfBackend())
    oracle = ScalarSpfBackend()
    rng = np.random.default_rng(5)
    with no_implicit_transfers():
        topo = _topo(seed=5, n=40)
        be.compute(topo).wait()  # warm: marshal + retain seed tensors
        results = []
        chain = [topo]
        # Two consecutive deltas submitted back-to-back: the second's
        # launch must wait for the first's finish (which re-deposits
        # the retained tensors) — otherwise full-no-prev or worse, a
        # donated-buffer reuse.
        for step in range(2):
            prev = chain[-1]
            e = int(rng.integers(0, prev.n_edges))
            nxt = clone_topology(prev, cost={e: int(rng.integers(1, 64))})
            delta = diff_topologies(prev, nxt)
            assert delta is not None
            nxt.link_delta(delta)
            chain.append(nxt)
            results.append((nxt, be.compute(nxt)))
        for nxt, lazy in results:
            ref = oracle.compute(nxt)
            for f in ("dist", "parent", "hops", "nexthop_words"):
                assert np.array_equal(getattr(ref, f), getattr(lazy, f)), f
    from holo_tpu import telemetry

    snap = telemetry.snapshot(prefix="holo_spf_delta")
    incr = sum(
        v for k, v in snap.items() if "path=incremental" in k
    )
    assert incr >= 2, f"delta chain did not stay incremental: {snap}"
    assert pipe.stats()["max-inflight-per-key"] <= 1


def test_async_breaker_fallback_bit_identical():
    """Split-phase launch failure -> breaker accounting + scalar
    fallback, same output as the oracle."""
    pipe = pipeline.configure_process_pipeline(depth=2)
    breaker = CircuitBreaker(
        "async-fallback-test", failure_threshold=2, recovery_timeout=1e9
    )
    be = pipeline.wrap_spf_backend(TpuSpfBackend(breaker=breaker))
    topo = _topo(seed=7)
    ref = ScalarSpfBackend().compute(topo)
    plan = FaultPlan(seed=7, dispatch_fail={"pipeline.dispatch": 2})
    with inject(FaultInjector(plan)) as inj:
        r1 = be.compute(topo)
        assert np.array_equal(r1.dist, ref.dist)
        r2 = be.compute(topo)
        assert np.array_equal(r2.dist, ref.dist)
        assert np.array_equal(r2.nexthop_words, ref.nexthop_words)
    assert inj.injected["pipeline.dispatch"] == 2
    assert breaker.state == "open"
    # Open circuit: compute still serves (oracle, at launch admit).
    r3 = be.compute(topo)
    assert np.array_equal(r3.dist, ref.dist)


# -- chaos: mid-storm crashpoint vs synchronous control -----------------


def test_pipeline_dispatch_crashpoint_mid_storm_bit_identical_fibs():
    """ISSUE 9 chaos acceptance: forced ``pipeline.dispatch`` failures
    mid-storm open the breaker; every subsequent pipelined dispatch is
    served by the scalar fallback, and the final FIB is bit-identical
    to a SYNCHRONOUS control run of the same seeded storm.  Runs under
    ``jax.transfer_guard("disallow")`` (the pipeline worker installs
    the same sanitizer via its guard hook)."""
    from holo_tpu.spf.synth_storm import StormNet

    def run(backend, asynchronous):
        net = StormNet(n_routers=60, seed=33, spf_backend=backend)
        for i in range(8):
            net.flap(net.flappable[i], lost=False)
            net.loop.advance(12.0)
        net.ifconfig_metric()
        net.loop.advance(40.0)
        if asynchronous:
            pipeline.process_pipeline().drain(timeout=10)
        return dict(net.kernel.fib)

    with no_implicit_transfers():
        # Control: synchronous TpuSpfBackend, no chaos.
        control_fib = run(TpuSpfBackend(64), asynchronous=False)
        # Async arm under chaos: same storm, pipelined backend, two
        # forced pipeline.dispatch failures -> breaker open -> scalar.
        pipeline.configure_process_pipeline(
            depth=2, guard=no_implicit_transfers
        )
        breaker = CircuitBreaker(
            "pipeline-storm", failure_threshold=2, recovery_timeout=1e9
        )
        be = pipeline.wrap_spf_backend(TpuSpfBackend(64, breaker=breaker))
        plan = FaultPlan(seed=33, dispatch_fail={"pipeline.dispatch": 2})
        with inject(FaultInjector(plan)) as inj:
            chaos_fib = run(be, asynchronous=True)
        assert inj.injected["pipeline.dispatch"] == 2
        assert breaker.state == "open"
    assert chaos_fib == control_fib


def test_async_storm_digest_matches_sync_and_scalar():
    """Clean storm tri-parity (the bench pipeline_spf gate at test
    scale): the async-pipelined arm's causal timeline digest is
    byte-identical to the synchronous device arm's — pipelining must
    not reorder, drop, or re-attribute a single causal step — and the
    final FIBs of all THREE arms (async / sync / all-scalar) are
    identical.  (The scalar arm's causal digest legitimately differs:
    its dispatch entries record mode=scalar, which is the point of the
    attribution.)"""
    from holo_tpu.spf.synth_storm import run_convergence_storm

    def arm(backend, asynchronous=False):
        report, digest, net = run_convergence_storm(
            n_routers=60, events=24, seed=35, spf_backend=backend,
        )
        if asynchronous:
            pipeline.process_pipeline().drain(timeout=10)
        return digest, dict(net.kernel.fib)

    d_sync, fib_sync = arm(TpuSpfBackend(64))
    _d_scalar, fib_scalar = arm(None)
    pipeline.configure_process_pipeline(depth=2)
    d_async, fib_async = arm(
        pipeline.wrap_spf_backend(TpuSpfBackend(64)), asynchronous=True
    )
    assert d_async == d_sync, "pipelining perturbed the causal timeline"
    assert fib_async == fib_sync == fib_scalar


# -- FRR through the pipeline ------------------------------------------


def test_async_frr_overlaps_and_matches_oracle():
    from holo_tpu.frr.manager import FrrEngine
    from holo_tpu.spf.synth import grid_topology

    pipe = pipeline.configure_process_pipeline(depth=2)
    topo = grid_topology(5, 5, seed=3)
    ref = FrrEngine("scalar").compute(topo)
    eng = pipeline.wrap_frr_engine(FrrEngine("tpu"))
    be = pipeline.wrap_spf_backend(TpuSpfBackend())
    # SPF + FRR for one topology ride distinct keys: both enqueue
    # without blocking, then force.
    spf_res = be.compute(topo)
    table = eng.compute(topo)
    assert spf_res.dist is not None
    for f in ("lfa_adj", "rlfa_pq", "tilfa_p", "tilfa_q", "post_nh"):
        assert np.array_equal(getattr(ref, f), getattr(table, f)), f
    assert pipe.stats()["completed"] >= 2


def test_wrap_helpers_are_identity_when_unarmed():
    be = TpuSpfBackend()
    assert pipeline.wrap_spf_backend(be) is be
    scalar = ScalarSpfBackend()
    pipeline.configure_process_pipeline(depth=1)
    assert pipeline.wrap_spf_backend(scalar) is scalar
    assert pipeline.wrap_spf_backend(be) is not be

"""YANG-lite data trees, diffs, and the 3-phase transaction engine."""

import pytest

from holo_tpu.northbound.core import Northbound
from holo_tpu.northbound.provider import (
    Callbacks,
    CommitError,
    CommitPhase,
    Provider,
)
from holo_tpu.yang.data import DataTree, DiffKind, diff_trees
from holo_tpu.yang.modules import full_schema
from holo_tpu.yang.schema import SchemaError


@pytest.fixture
def schema():
    return full_schema()


def test_set_get_delete_roundtrip(schema):
    t = DataTree(schema)
    t.set("interfaces/interface[eth0]")
    t.set("interfaces/interface[eth0]/mtu", 9000)
    t.set("interfaces/interface[eth0]/enabled", "true")
    assert t.get("interfaces/interface[eth0]/mtu") == 9000
    assert t.get("interfaces/interface[eth0]/enabled") is True
    t.delete("interfaces/interface[eth0]/mtu")
    assert t.get("interfaces/interface[eth0]/mtu") is None
    t.delete("interfaces/interface[eth0]")
    assert t.get("interfaces/interface[eth0]") is None


def test_type_validation_rejects(schema):
    t = DataTree(schema)
    t.set("interfaces/interface[eth0]")
    with pytest.raises(SchemaError):
        t.set("interfaces/interface[eth0]/mtu", 70000)  # > uint16
    with pytest.raises(SchemaError):
        t.set("interfaces/interface[eth0]/type", "carrier-pigeon")
    with pytest.raises(SchemaError):
        t.set("interfaces/interface[eth0]/bogus-leaf", 1)


def test_diff_create_modify_delete(schema):
    old = DataTree(schema)
    old.set("interfaces/interface[eth0]/mtu", 1500)
    new = old.copy()
    new.set("interfaces/interface[eth0]/mtu", 9000)
    new.set("interfaces/interface[eth1]/mtu", 1500)
    new.delete("interfaces/interface[eth0]/description")
    ops = diff_trees(old, new)
    kinds = {(o.kind, o.path) for o in ops}
    assert (DiffKind.MODIFY, "interfaces/interface[eth0]/mtu") in kinds
    assert (DiffKind.CREATE, "interfaces/interface[eth1]") in kinds
    # deletes are child-first
    old2, new2 = new, old
    ops2 = diff_trees(old2, new2)
    del_paths = [o.path for o in ops2 if o.kind == DiffKind.DELETE]
    assert del_paths.index("interfaces/interface[eth1]/mtu") < del_paths.index(
        "interfaces/interface[eth1]"
    )


def test_json_roundtrip(schema):
    t = DataTree(schema)
    t.set("routing/control-plane-protocols/ospfv2/router-id", "1.1.1.1")
    t.set("routing/control-plane-protocols/ospfv2/area[0.0.0.0]")
    t.set(
        "routing/control-plane-protocols/ospfv2/area[0.0.0.0]/interface[eth0]/cost",
        25,
    )
    t2 = DataTree.from_json(schema, t.to_json())
    assert diff_trees(t, t2) == [] or all(
        o.kind != DiffKind.MODIFY for o in diff_trees(t, t2)
    )
    assert (
        t2.get(
            "routing/control-plane-protocols/ospfv2/area[0.0.0.0]/interface[eth0]/cost"
        )
        == 25
    )


class RecordingProvider(Provider):
    name = "rec"
    subtree_prefixes = ("interfaces",)

    def __init__(self, veto=False):
        self.phases = []
        self.veto = veto

    def commit(self, phase, old, new, changes):
        self.phases.append((phase, tuple(c.path for c in changes)))
        if self.veto and phase == CommitPhase.PREPARE:
            raise CommitError("no thanks")


def test_two_phase_commit_apply(schema):
    p = RecordingProvider()
    other = RecordingProvider()
    other.subtree_prefixes = ("system",)
    nb = Northbound(schema, [p, other])
    cand = nb.running.copy()
    cand.set("interfaces/interface[eth0]/mtu", 1400)
    txn = nb.commit(cand, comment="t1")
    assert [ph for ph, _ in p.phases] == [CommitPhase.PREPARE, CommitPhase.APPLY]
    assert other.phases == []  # unrelated subtree: not called
    assert nb.running.get("interfaces/interface[eth0]/mtu") == 1400
    assert txn.id == 1


def test_prepare_veto_aborts(schema):
    good, bad = RecordingProvider(), RecordingProvider(veto=True)
    nb = Northbound(schema, [good, bad])
    cand = nb.running.copy()
    cand.set("interfaces/interface[eth0]/mtu", 1400)
    with pytest.raises(CommitError):
        nb.commit(cand)
    assert nb.running.get("interfaces/interface[eth0]/mtu") is None
    # good provider saw Prepare then Abort; never Apply.
    assert [ph for ph, _ in good.phases] == [CommitPhase.PREPARE, CommitPhase.ABORT]


def test_rollback_and_confirmed_commit(schema):
    p = RecordingProvider()
    nb = Northbound(schema, [p])
    c1 = nb.running.copy()
    c1.set("interfaces/interface[eth0]/mtu", 1400)
    t1 = nb.commit(c1, now=100.0)
    c2 = nb.running.copy()
    c2.set("interfaces/interface[eth0]/mtu", 9000)
    nb.commit(c2, now=101.0)
    assert nb.running.get("interfaces/interface[eth0]/mtu") == 9000
    nb.rollback(t1.id)
    assert nb.running.get("interfaces/interface[eth0]/mtu") == 1400

    # confirmed commit rolls back when unconfirmed
    c3 = nb.running.copy()
    c3.set("interfaces/interface[eth0]/mtu", 1200)
    nb.commit(c3, confirmed_timeout=60.0, now=200.0)
    assert nb.running.get("interfaces/interface[eth0]/mtu") == 1200
    assert not nb.check_confirmed_timeout(now=230.0)
    assert nb.check_confirmed_timeout(now=261.0)
    assert nb.running.get("interfaces/interface[eth0]/mtu") == 1400


def test_txn_persistence(schema, tmp_path):
    db = tmp_path / "nb.json"
    p = RecordingProvider()
    nb = Northbound(schema, [p], db_path=db)
    cand = nb.running.copy()
    cand.set("system/hostname", "rt1")
    # system isn't in p's subtree; commit with no matching provider still records
    nb.commit(cand, comment="hostname")
    nb2 = Northbound(schema, [RecordingProvider()], db_path=db)
    assert nb2.get_transaction(1).comment == "hostname"

"""Test config: force a deterministic 8-device virtual CPU mesh.

Must set env before the first `import jax` anywhere in the test process
(SURVEY-mandated determinism; mirrors the reference's `testing`/
`deterministic` feature discipline, holo-ospf/Cargo.toml:49-52).
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from holo_tpu.testing import force_virtual_cpu_mesh  # noqa: E402

force_virtual_cpu_mesh(8)

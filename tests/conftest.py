"""Test config: force a deterministic 8-device virtual CPU mesh.

Must set env before the first `import jax` anywhere in the test process
(SURVEY-mandated determinism; mirrors the reference's `testing`/
`deterministic` feature discipline, holo-ospf/Cargo.toml:49-52).
"""

import os

# The environment pre-imports jax via PYTHONPATH site hooks, so env vars are
# too late for platform selection — but jax.config still works as long as no
# backend has been initialized yet.  XLA_FLAGS is read at backend init.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.device_count() == 8, jax.devices()

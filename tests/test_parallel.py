"""Sharded multi-chip SPF on the 8-device virtual CPU mesh."""

import numpy as np
import pytest

from holo_tpu.ops.graph import build_ell
from holo_tpu.ops.spf_engine import device_graph_from_ell
from holo_tpu.parallel import make_spf_mesh, shard_graph, sharded_whatif_step
from holo_tpu.spf.backend import ScalarSpfBackend
from holo_tpu.spf.synth import random_ospf_topology, whatif_link_failure_masks


def _assert_matches_scalar(topo, out, masks):
    """Bit-identical check of every scenario against the scalar oracle."""
    n = topo.n_vertices
    scalar = ScalarSpfBackend().compute_whatif(topo, masks)
    for i, s in enumerate(scalar):
        np.testing.assert_array_equal(s.dist, np.asarray(out.dist[i])[:n])
        np.testing.assert_array_equal(
            s.nexthop_words, np.asarray(out.nexthops[i])[:n]
        )


@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_sharded_whatif_matches_scalar(mesh_shape):
    topo = random_ospf_topology(n_routers=24, n_networks=8, extra_p2p=40, seed=3)
    masks = whatif_link_failure_masks(topo, n_scenarios=8, seed=4)

    mesh = make_spf_mesh(*mesh_shape)
    g = shard_graph(device_graph_from_ell(build_ell(topo)), mesh)
    run = sharded_whatif_step(mesh)
    out = run(g, topo.root, masks)
    _assert_matches_scalar(topo, out, masks)


def test_node_sharding_pads_rows():
    topo = random_ospf_topology(n_routers=11, n_networks=2, seed=9)  # N=13, odd
    mesh = make_spf_mesh(2, 4)
    g = shard_graph(device_graph_from_ell(build_ell(topo)), mesh)
    assert g.in_src.shape[0] % 4 == 0
    run = sharded_whatif_step(mesh)
    masks = whatif_link_failure_masks(topo, n_scenarios=4, seed=0)
    out = run(g, topo.root, masks)
    scalar = ScalarSpfBackend().compute(topo, masks[1])
    np.testing.assert_array_equal(
        scalar.dist, np.asarray(out.dist[1])[: topo.n_vertices]
    )


def test_node_sharding_scales_to_large_graph():
    """A 10k+-vertex LSDB over node>=2: each device holds only a row
    block of the graph planes, so this exercises real vertex-axis
    sharding (not a toy that trivially fits one shard), and the sharded
    result stays bit-identical to the scalar oracle."""
    topo = random_ospf_topology(
        n_routers=9000, n_networks=1500, extra_p2p=18000, seed=11
    )
    assert topo.n_vertices >= 10_000
    masks = whatif_link_failure_masks(topo, n_scenarios=4, seed=5)

    mesh = make_spf_mesh(2, 4)  # node=4: 4-way row sharding
    g = shard_graph(device_graph_from_ell(build_ell(topo)), mesh)
    rows = g.in_src.shape[0]
    assert rows >= topo.n_vertices and rows % 4 == 0

    run = sharded_whatif_step(mesh)
    out = run(g, topo.root, masks)
    _assert_matches_scalar(topo, out, masks)

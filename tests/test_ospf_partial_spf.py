"""Partial SPF: summary/external-only changes must not re-run Dijkstra
(reference holo-ospf/src/spf.rs:49-60,513-516 Full-vs-Partial trigger
classification; route.rs:200-333 update_rib_partial)."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock

from tests.test_ospf_convergence import bring_up, mk_router, p2p_link


class _CountingBackend:
    """Wraps the instance's real backend; counts Dijkstra dispatches."""

    def __init__(self, inner):
        self.inner = inner
        self.name = inner.name
        self.computes = 0

    def compute(self, topo, multipath_k: int = 1):
        self.computes += 1
        return self.inner.compute(topo, multipath_k=multipath_k)


def _mk_pair():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = mk_router(loop, fabric, "r1", "1.1.1.1")
    r2 = mk_router(loop, fabric, "r2", "2.2.2.2")
    p2p_link(fabric, "l12", r1, "e0", "10.0.0.1", r2, "e0", "10.0.0.2",
             "10.0.0.0/30")
    bring_up(loop, [r1, r2])
    return loop, r1, r2


def test_external_only_change_skips_dijkstra():
    """A type-5-only change runs the partial path: zero backend.compute
    calls, the route still lands, and the SPF log records 'external'."""
    loop, r1, r2 = _mk_pair()
    # Prime ASBR status: the FIRST redistribution re-originates r2's
    # router-LSA (E flag), which is legitimately a full-SPF topology
    # change.  Subsequent type-5s are external-only.
    r2.redistribute(N("192.0.2.0/24"), metric=10)
    loop.advance(30)
    counter = _CountingBackend(r1.backend)
    r1.backend = counter
    r2.redistribute(N("203.0.113.0/24"), metric=20)
    loop.advance(30)
    assert counter.computes == 0, (
        "type-5-only change must not re-run Dijkstra"
    )
    assert N("203.0.113.0/24") in r1.routes
    assert r1.routes[N("203.0.113.0/24")].rtype == "external-2"
    assert r1.spf_log[-1]["type"] == "external"

    # Withdrawal is equally partial and removes the route.
    r2.withdraw_redistributed(N("203.0.113.0/24"))
    loop.advance(30)
    assert counter.computes == 0
    assert N("203.0.113.0/24") not in r1.routes


def test_router_lsa_change_still_runs_full():
    """Topology changes (Router-LSA) keep forcing a full run."""
    loop, r1, r2 = _mk_pair()
    counter = _CountingBackend(r1.backend)
    r1.backend = counter
    # A cost change re-originates r2's Router-LSA.
    area = next(iter(r2.areas.values()))
    area.interfaces["e0"].config.cost = 55
    r2._originate_router_lsa(area)
    loop.advance(30)
    assert counter.computes > 0, "router-LSA change must run full SPF"
    assert r1.spf_log[-1]["type"] == "full"
    assert N("10.0.0.0/30") in r1.routes


def test_partial_and_full_agree_on_external_routes():
    """Route table after a partial external update is identical to what a
    forced full recomputation produces (the acceptance gate)."""
    loop, r1, r2 = _mk_pair()
    for i in range(4):
        r2.redistribute(N(f"198.51.{i}.0/24"), metric=10 + i)
    loop.advance(30)
    partial_routes = {
        p: (r.dist, r.nexthops, r.rtype) for p, r in r1.routes.items()
    }
    # Force a full run and compare.
    r1._schedule_spf()
    loop.advance(30)
    assert r1.spf_log[-1]["type"] == "full"
    full_routes = {
        p: (r.dist, r.nexthops, r.rtype) for p, r in r1.routes.items()
    }
    assert partial_routes == full_routes


def test_summary_only_change_is_partial_inter():
    """A summary (type-3) metric change at a non-ABR reruns only the
    inter-area stage from the cached SPT — no Dijkstra — and the route
    distance updates (route.rs:239-267)."""
    from holo_tpu.protocols.ospf.instance import IfConfig
    from holo_tpu.protocols.ospf.interface import IfType

    AREA0, AREA1 = A("0.0.0.0"), A("0.0.0.1")
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = mk_router(loop, fabric, "p1", "1.1.1.1")   # area 0 only
    abr = mk_router(loop, fabric, "pa", "2.2.2.2")  # ABR
    r3 = mk_router(loop, fabric, "p3", "3.3.3.3")   # area 1 only
    c0 = IfConfig(area_id=AREA0, if_type=IfType.POINT_TO_POINT, cost=10)
    c1 = IfConfig(area_id=AREA1, if_type=IfType.POINT_TO_POINT, cost=10)
    r1.add_interface("e0", c0, N("10.0.0.0/30"), A("10.0.0.1"))
    abr.add_interface("e0", c0, N("10.0.0.0/30"), A("10.0.0.2"))
    abr.add_interface("e1", c1, N("10.0.1.0/30"), A("10.0.1.1"))
    r3.add_interface("e1", c1, N("10.0.1.0/30"), A("10.0.1.2"))
    fabric.join("l0", "p1", "e0", A("10.0.0.1"))
    fabric.join("l0", "pa", "e0", A("10.0.0.2"))
    fabric.join("l1", "pa", "e1", A("10.0.1.1"))
    fabric.join("l1", "p3", "e1", A("10.0.1.2"))
    bring_up(loop, [r1, abr, r3])
    assert N("10.0.1.0/30") in r1.routes
    before = r1.routes[N("10.0.1.0/30")].dist

    counter = _CountingBackend(r1.backend)
    r1.backend = counter
    # Raise area-1 link cost: r3/abr re-run full locally, but r1 only
    # sees a changed type-3 summary from the ABR.
    for inst in (abr, r3):
        area = inst.areas[AREA1]
        area.interfaces["e1"].config.cost = 40
        inst._originate_router_lsa(area)
    loop.advance(30)
    assert counter.computes == 0, (
        "summary-only change at a non-ABR must not re-run Dijkstra"
    )
    assert r1.spf_log[-1]["type"] == "inter"
    after = r1.routes[N("10.0.1.0/30")].dist
    assert after == before + 30, (before, after)

"""Shared-delta gNMI fan-out (ISSUE 11): epoch/versioning contract,
interval-bucket sharing, subscriber churn under a convergence storm,
breaker fallback to the per-subscriber walk path, and the subscriber-
lock discipline fix."""

import queue
import threading
import types

import pytest

import holo_tpu.daemon.gnmi_server as gs
from holo_tpu import telemetry
from holo_tpu.telemetry import delta, flight

# The package __init__ shadows the `registry` submodule with the
# registry() accessor function; reach the module through sys.modules.
import sys as _sys

registry_mod = _sys.modules["holo_tpu.telemetry.registry"]
from holo_tpu.telemetry.provider import TelemetryStateProvider


def _sub(path="", mode=None, interval_ns=0, suppress=False, heartbeat_ns=0):
    s = gs.pb.Subscription()
    if path:
        s.path.CopyFrom(gs.str_to_path(path))
    s.mode = mode if mode is not None else gs.pb.SAMPLE
    s.sample_interval = interval_ns
    s.suppress_redundant = suppress
    s.heartbeat_interval = heartbeat_ns
    return s


def _paths(notif):
    return [gs.path_to_str(u.path) for u in notif.update]


def _drain(q):
    out = []
    while True:
        try:
            out.append(q.get_nowait())
        except queue.Empty:
            return out


class _Harness:
    """FanoutEngine on a manual clock with injectable state trees —
    the engine without the gRPC plumbing around it."""

    def __init__(self, tick=1.0, **kw):
        self.now = 0.0
        self.state = {}
        self.dropped = []
        self.engine = delta.FanoutEngine(
            fetch_state=lambda: self.state,
            deliver=self._deliver,
            tick=tick,
            clock=lambda: self.now,
            # Timestamps carry the epoch id: monotonicity/torn-epoch
            # assertions read them straight off the wire format.
            clock_ns=lambda: self.engine._epoch,
            **kw,
        )

    def _deliver(self, q, sid, notif, in_burst):
        try:
            q.put_nowait(notif)
            return True
        except queue.Full:
            self.dropped.append(sid)
            return False

    def tick(self, advance=1.0, state=None):
        self.now += advance
        return self.engine.tick_now(self.now, state=state)


def _metric_state(**values):
    """A holo-telemetry-shaped state tree with the given metric leaves."""
    return {
        "holo-telemetry": {
            "metric": [
                {"name": k, "value": v, "labels": ""}
                for k, v in sorted(values.items())
            ]
        }
    }


# -- epoch / change-set contract -----------------------------------------


def test_epoch_advances_only_on_change_and_deltas_carry_changed_leaves():
    h = _Harness()
    h.state = _metric_state(a=1.0, b=2.0)
    q = queue.Queue(64)
    handle = h.engine.attach(
        q, 1, [_sub("holo-telemetry", interval_ns=int(1e9), suppress=True)]
    )
    assert handle
    r1 = h.tick()
    assert r1["fired"] == 1 and r1["epoch"] == 1 and r1["walked"]
    first = _drain(q)
    assert len(first) == 1  # full sync: every leaf, once
    assert "holo-telemetry/metric[a]/value" in _paths(first[0])
    assert "holo-telemetry/metric[b]/value" in _paths(first[0])
    # Unchanged tick: epoch holds, nothing is delivered.
    r2 = h.tick()
    assert r2["epoch"] == 1 and _drain(q) == []
    # One leaf moves: the delta carries exactly its changed leaves.
    h.state = _metric_state(a=1.0, b=3.0)
    r3 = h.tick()
    assert r3["epoch"] == 2
    (d,) = _drain(q)
    assert _paths(d) == ["holo-telemetry/metric[b]/value"]
    assert d.update[0].val.double_val == 3.0
    assert d.timestamp > first[0].timestamp  # monotonic epoch ids


def test_bucket_shares_one_render_across_hundreds_of_cursors():
    h = _Harness()
    h.state = _metric_state(**{f"m{i}": float(i) for i in range(50)})
    queues = [queue.Queue(8) for _ in range(300)]
    for i, q in enumerate(queues):
        h.engine.attach(
            q,
            i + 1,
            [_sub("holo-telemetry", interval_ns=int(1e9), suppress=True)],
        )
    def renders():
        snap = telemetry.snapshot(prefix="holo_gnmi_fanout_shared_renders")
        return sum(v for v in snap.values())

    r0 = renders()
    h.tick()
    notifs = [q.get_nowait() for q in queues]
    # Literally ONE shared object fanned out to all 300 queues.
    assert all(n is notifs[0] for n in notifs)
    assert renders() - r0 == 1
    # A delta tick shares the same way.
    h.state = _metric_state(
        **{f"m{i}": float(i) for i in range(49)} | {"m49": -1.0}
    )
    r1 = renders()
    h.tick()
    notifs = [q.get_nowait() for q in queues]
    assert all(n is notifs[0] for n in notifs)
    assert _paths(notifs[0]) == ["holo-telemetry/metric[m49]/value"]
    assert renders() - r1 == 1


def test_heartbeat_is_a_render_cache_hit_over_unchanged_epoch():
    h = _Harness()
    h.state = _metric_state(x=5.0)
    q = queue.Queue(64)
    h.engine.attach(
        q,
        1,
        [
            _sub(
                "holo-telemetry",
                interval_ns=int(1e9),
                suppress=True,
                heartbeat_ns=int(1e9),
            )
        ],
    )
    h.tick()  # full sync + cache fill
    _drain(q)

    def hits():
        return telemetry.snapshot(prefix="holo_gnmi_fanout_render").get(
            "holo_gnmi_fanout_render_cache_total{result=hit}", 0.0
        )

    h0 = hits()
    h.tick()  # unchanged: beat fires, full render reused from cache
    (beat,) = _drain(q)
    assert "holo-telemetry/metric[x]/value" in _paths(beat)
    assert hits() > h0


def test_late_joiner_first_notification_is_full_sync():
    h = _Harness()
    h.state = _metric_state(quiet=7.0, busy=0.0)
    q1 = queue.Queue(64)
    spec = [_sub("holo-telemetry", interval_ns=int(1e9), suppress=True)]
    h.engine.attach(q1, 1, spec)
    h.tick()
    h.state = _metric_state(quiet=7.0, busy=1.0)
    h.tick()
    _drain(q1)
    # Joiner after two epochs: its first push must be the FULL subtree
    # (including the quiet leaf that last changed at epoch 1), while
    # the veteran cursor sees only deltas.
    q2 = queue.Queue(64)
    h.engine.attach(q2, 2, spec)
    h.state = _metric_state(quiet=7.0, busy=2.0)
    h.tick()
    (vet,) = _drain(q1)
    (joiner,) = _drain(q2)
    assert _paths(vet) == ["holo-telemetry/metric[busy]/value"]
    assert "holo-telemetry/metric[quiet]/value" in _paths(joiner)
    assert "holo-telemetry/metric[busy]/value" in _paths(joiner)


# -- byte-identity vs the per-subscriber walk path -----------------------


def test_engine_output_byte_identical_to_legacy_walk_path():
    """The shared-render path and the legacy ``_SubSampler`` walk path
    stepped over the SAME state sequence at the SAME times produce
    byte-identical notification streams (the fallback contract the
    bench gnmi_fanout stage gates end to end)."""
    svc = gs.GnmiService(daemon=None, shared_fanout=False)
    svc._clock_ns = lambda: 777_000
    for suppress, heartbeat_ns in (
        (True, 0),
        (False, 0),
        (True, int(4e9)),
    ):
        h = _Harness()
        h.engine._clock_ns = lambda: 777_000
        sub = _sub(
            "holo-telemetry",
            interval_ns=int(1e9),
            suppress=suppress,
            heartbeat_ns=heartbeat_ns,
        )
        sampler = gs._SubSampler(sub, now=0.0)
        q = queue.Queue(1024)
        h.engine.attach(q, 1, [sub])
        engine_out, legacy_out = [], []
        vals = [
            {"a": 1.0, "b": 1.0},
            {"a": 1.0, "b": 2.0},
            {"a": 1.0, "b": 2.0},  # idle step
            {"a": 3.0, "b": 2.0},
            {"a": 3.0, "b": 2.0},
            {"a": 4.0, "b": 5.0},
            {"a": 4.0, "b": 5.0},
            {"a": 4.0, "b": 5.0},
            {"a": 9.0, "b": 5.0},
        ]
        for step, v in enumerate(vals, start=1):
            state = _metric_state(**v)
            h.tick(state=state)
            engine_out.extend(_drain(q))
            if sampler.advance_if_due(float(step)):
                out = svc._sample_notif(sampler, state)
                if out is not None:
                    legacy_out.append(out)
        assert [n.SerializeToString() for n in engine_out] == [
            n.SerializeToString() for n in legacy_out
        ], f"suppress={suppress} heartbeat={heartbeat_ns}"


# -- write-stamp short-circuit -------------------------------------------


def test_idle_ticks_skip_the_walk_under_an_unchanged_write_stamp():
    """Leaf-version stamping at write time (registry.py): with every
    bucket under holo-telemetry/metric and no registry writes, the
    engine proves the snapshot unchanged WITHOUT walking it."""
    probe = telemetry.counter("holo_fanout_skip_probe_total")
    probe.inc()
    provider = TelemetryStateProvider()
    walks = [0]

    def fetch():
        walks[0] += 1
        return provider.get_state(None)

    h = _Harness()
    h.engine._fetch_state = fetch
    q = queue.Queue(64)
    leaf = "holo-telemetry/metric[holo_fanout_skip_probe_total]/value"
    h.engine.attach(q, 1, [_sub(leaf, interval_ns=int(1e9), suppress=True)])
    # Callback-backed gauges registered by OTHER suites void the stamp
    # contract by design; pin the count to isolate the mechanism.
    saved = registry_mod._VOLATILE[0]
    registry_mod._VOLATILE[0] = 0
    try:
        r1 = h.tick()
        assert r1["walked"] and walks[0] == 1
        assert len(_drain(q)) == 1  # full sync
        r2 = h.tick()
        r3 = h.tick()
        assert not r2["walked"] and not r3["walked"]
        assert walks[0] == 1, "unchanged stamp must skip the walk"
        probe.inc()  # a stamped write re-arms the walk
        r4 = h.tick()
        assert r4["walked"] and walks[0] == 2
        (d,) = _drain(q)
        assert _paths(d) == [leaf]
        # External invalidation (commit/yang) also re-arms it.
        h.engine.invalidate()
        r5 = h.tick()
        assert r5["walked"] and walks[0] == 3
    finally:
        registry_mod._VOLATILE[0] = saved


def test_heartbeat_served_subscriber_quiesces_on_an_idle_system():
    """The engine's own bookkeeping (tick/cache/push counters) is
    stamped=False: serving heartbeats from the render cache must not
    re-arm the next tick's walk, or an idle system would churn
    forever (walk -> see own counters changed -> new epoch -> deliver
    -> bump -> walk ...)."""
    probe = telemetry.counter("holo_quiesce_probe_total")
    probe.inc()
    provider = TelemetryStateProvider()
    walks = [0]

    def fetch():
        walks[0] += 1
        return provider.get_state(None)

    # Service path: on_push (the stamped=False sample-updates counter)
    # fires per delivery, exactly the feedback loop under test.
    stub = types.SimpleNamespace(
        lock=threading.RLock(),
        northbound=types.SimpleNamespace(
            get_state=lambda p=None: provider.get_state(None)
        ),
    )
    svc = gs.GnmiService(stub, shared_fanout=True, fanout_tick=1.0)
    now = [0.0]
    eng = svc.fanout
    eng._clock = lambda: now[0]
    eng._fetch_state = fetch
    q = queue.Queue(64)
    leaf = "holo-telemetry/metric[holo_quiesce_probe_total]/value"
    eng.attach(
        q,
        svc._add_subscriber(q),
        [_sub(leaf, interval_ns=int(1e9), suppress=True,
              heartbeat_ns=int(1e9))],
    )
    saved = registry_mod._VOLATILE[0]
    registry_mod._VOLATILE[0] = 0
    try:
        now[0] = 1.0
        r1 = eng.tick_now(now[0])
        assert r1["walked"] and r1["delivered"] == 1 and walks[0] == 1
        for i in range(2, 6):
            now[0] = float(i)
            r = eng.tick_now(now[0])
            # Beats keep flowing (from the render cache) but the walk
            # never re-arms: the system is quiescent.
            assert r["delivered"] == 1 and not r["walked"]
        assert walks[0] == 1
        assert len(_drain(q)) == 5
    finally:
        registry_mod._VOLATILE[0] = saved


def test_fetch_scope_is_the_union_of_subscribed_roots():
    """A narrow subscription must not cost a full provider-tree walk:
    the service's fetch closure scopes get_state to the union of
    bucket roots (None only when some bucket wants the whole tree)."""
    seen = []
    stub = types.SimpleNamespace(
        lock=threading.RLock(),
        northbound=types.SimpleNamespace(
            get_state=lambda p=None: seen.append(p) or {}
        ),
    )
    svc = gs.GnmiService(stub, shared_fanout=True, fanout_tick=1.0)
    eng = svc.fanout
    assert eng.sample_roots() is None  # no buckets yet
    q1, q2 = queue.Queue(8), queue.Queue(8)
    h1 = eng.attach(
        q1, svc._add_subscriber(q1),
        [_sub("holo-telemetry/metric", interval_ns=int(1e9))],
    )
    eng.attach(
        q2, svc._add_subscriber(q2),
        [_sub("holo-runtime", interval_ns=int(1e9))],
    )
    assert eng.sample_roots() == ("holo-runtime", "holo-telemetry/metric")
    svc._fetch_state()
    assert seen == ["holo-runtime", "holo-telemetry/metric"]
    # A whole-tree subscription collapses the scope to a full walk.
    q3 = queue.Queue(8)
    h3 = eng.attach(
        q3, svc._add_subscriber(q3), [_sub("", interval_ns=int(1e9))]
    )
    assert eng.sample_roots() is None
    seen.clear()
    svc._fetch_state()
    assert seen == [None]
    eng.detach(h3)
    eng.detach(h1)
    assert eng.sample_roots() == ("holo-runtime",)
    # Nested roots collapse to their covering prefix; past the cap the
    # scope falls back to one full walk (every provider runs per
    # get_state call, so N scoped fetches can cost MORE than one).
    q4 = queue.Queue(8)
    eng.attach(
        q4, svc._add_subscriber(q4),
        [_sub("holo-runtime/main-loop", interval_ns=int(1e9))],
    )
    assert eng.sample_roots() == ("holo-runtime",)
    q5 = queue.Queue(8)
    eng.attach(
        q5, svc._add_subscriber(q5),
        [
            _sub(f"root{i}", interval_ns=int(1e9))
            for i in range(delta.MAX_SCOPED_ROOTS + 1)
        ],
    )
    assert eng.sample_roots() is None


def test_dropped_first_full_sync_retries_until_delivered():
    """The full-sync baseline debt clears only on a CONFIRMED put: a
    subscriber whose bounded queue was full at its first fire retries
    the full sync at the next fire instead of silently serving deltas
    against a baseline the client never saw."""
    h = _Harness()
    h.state = _metric_state(quiet=1.0, busy=0.0)
    slow: queue.Queue = queue.Queue(maxsize=1)
    slow.put_nowait("stuck")  # full before the first fire
    h.engine.attach(
        slow, 1,
        [_sub("holo-telemetry", interval_ns=int(1e9), suppress=True)],
    )
    r1 = h.tick()
    assert r1["dropped"] == 1 and r1["delivered"] == 0
    slow.get_nowait()  # consumer recovers
    h.state = _metric_state(quiet=1.0, busy=2.0)
    h.tick()
    (first,) = _drain(slow)
    # Retried FULL sync — not a delta missing the quiet leaf.
    assert "holo-telemetry/metric[quiet]/value" in _paths(first)
    assert "holo-telemetry/metric[busy]/value" in _paths(first)


def test_registry_write_stamp_and_volatility_accounting():
    s0 = telemetry.write_stamp()
    c = telemetry.counter("holo_stamp_unit_total")
    c.inc()
    assert telemetry.write_stamp() > s0
    assert c.labels().stamp == telemetry.write_stamp()
    g = telemetry.gauge("holo_stamp_unit_gauge")
    s1 = telemetry.write_stamp()
    g.set(4.0)
    assert telemetry.write_stamp() > s1
    v0 = telemetry.volatile_children()
    g.set_fn(lambda: 1.0)
    assert telemetry.volatile_children() == v0 + 1
    g.set_fn(None)
    assert telemetry.volatile_children() == v0


# -- breaker / fallback --------------------------------------------------


def test_breaker_opens_after_consecutive_failures_and_recovers():
    h = _Harness(breaker_threshold=3, breaker_cooldown=30.0)
    h.state = _metric_state(z=1.0)
    q = queue.Queue(8)
    spec = [_sub("holo-telemetry", interval_ns=int(1e9), suppress=True)]
    h.engine.attach(q, 1, spec)

    def boom():
        raise RuntimeError("provider exploded")

    good = h.engine._fetch_state
    h.engine._fetch_state = boom
    fb0 = sum(
        telemetry.snapshot(prefix="holo_gnmi_fanout_fallback").values()
    )
    for _ in range(3):
        h.now += 1.0
        assert h.engine.tick_guarded(h.now) is None
    assert not h.engine.healthy()
    assert h.engine.stats()["breaker"] == "open"
    # Open breaker refuses new cursors (streams run the walk path).
    assert h.engine.attach(queue.Queue(8), 2, spec) is None
    fb1 = sum(
        telemetry.snapshot(prefix="holo_gnmi_fanout_fallback").values()
    )
    assert fb1 - fb0 >= 4  # 3 tick failures + 1 refused attach
    # Cooldown elapses -> half-open; a successful tick closes.
    h.engine._fetch_state = good
    h.now += 31.0
    assert h.engine.healthy()
    assert h.engine.stats()["breaker"] == "half-open"
    assert h.engine.tick_guarded(h.now) is not None
    assert h.engine.stats()["breaker"] == "closed"


def test_stream_degrades_to_walk_path_when_breaker_opens():
    """E2E over real gRPC: a live SAMPLE stream keeps receiving pushes
    after the engine breaker opens — served by the legacy walk path,
    with the degradation counted."""
    import socket

    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    marker = telemetry.counter("holo_degrade_probe_total")
    marker.inc(2)
    d = Daemon(loop=EventLoop(clock=VirtualClock()), name="deg")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    server = gs.serve_gnmi(d, f"127.0.0.1:{port}")
    svc = d._gnmi_service
    try:
        cli = gs.GnmiClient(f"127.0.0.1:{port}")
        leaf = "holo-telemetry/metric[holo_degrade_probe_total]/value"
        req = gs.pb.SubscribeRequest()
        req.subscribe.mode = gs.pb.SubscriptionList.STREAM
        sub = req.subscribe.subscription.add()
        sub.path.CopyFrom(gs.str_to_path(leaf))
        sub.mode = gs.pb.SAMPLE
        sub.sample_interval = 50_000_000  # 50ms
        stream = cli.Subscribe(iter([req]))
        got = []
        done = threading.Event()
        poisoned = threading.Event()
        after = []

        def consume():
            for m in stream:
                if not (m.HasField("update") and m.update.update):
                    continue
                if not m.update.update[0].path.elem:
                    continue
                got.append(m.update)
                if poisoned.is_set():
                    after.append(m.update)
                    if len(after) >= 2:
                        done.set()
                        return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        deadline = threading.Event()
        for _ in range(100):
            if got:
                break
            deadline.wait(0.05)
        assert got, "engine path must push sampled leaves"
        # Poison the engine: the ticker's next fetches fail, the
        # breaker opens, and the stream must keep flowing on the
        # legacy samplers.
        def boom():
            raise RuntimeError("state provider down")

        # Park the ticker so its (possibly skip-path, hence successful)
        # ticks cannot reset the failure streak mid-forcing, then fail
        # deterministically: invalidate() forces a walk attempt, and a
        # future `now` keeps the bucket due each forced tick.
        svc.fanout.stop()
        svc.fanout._fetch_state = boom
        import time as time_mod

        ahead = time_mod.monotonic()
        for _ in range(svc.fanout._threshold):
            svc.fanout.invalidate()
            ahead += 1.0
            svc.fanout.tick_guarded(ahead)
        assert not svc.fanout.healthy()
        poisoned.set()
        assert done.wait(8.0), "stream must survive on the walk path"
        assert all(
            gs.path_to_str(u.path) == leaf
            for n in after
            for u in n.update
        )
        snap = telemetry.snapshot(prefix="holo_gnmi_fanout_fallback")
        assert sum(snap.values()) > 0
    finally:
        server.stop(grace=0)
        if svc.fanout is not None:
            svc.fanout.stop()


# -- lock discipline (satellite fix) -------------------------------------


def test_fanout_never_holds_subscriber_lock_during_puts():
    """HL203 surface: _fanout snapshots the copy-on-write subscriber
    tuple under the lock and performs EVERY put (and the drop path)
    after release — the Ibus._subs discipline."""
    svc = gs.GnmiService(daemon=None, shared_fanout=False)
    held = []

    class Probe:
        def __init__(self, full=False):
            self.full = full

        def put_nowait(self, item):
            held.append(svc._sub_lock.locked())
            if self.full:
                raise queue.Full

    ok_q, full_q = Probe(), Probe(full=True)
    svc._add_subscriber(ok_q)
    svc._add_subscriber(full_q)
    svc._fanout("n1")
    svc._fanout("n2")  # second round exercises the open-burst path
    assert held == [False] * 4
    svc._remove_subscriber(ok_q)
    svc._remove_subscriber(full_q)


def test_fanout_lock_hold_is_constant_in_subscriber_count():
    """The lock region is two reference reads: adding 500 subscribers
    must not change what happens under the lock (no per-queue work)."""
    svc = gs.GnmiService(daemon=None, shared_fanout=False)
    for _ in range(500):
        svc._add_subscriber(queue.Queue(maxsize=4))
    with svc._sub_lock:
        snap = svc._subscribers
        bursts = set(svc._bursts)
    assert isinstance(snap, tuple) and len(snap) == 500
    assert bursts == set()
    svc._fanout("x")
    assert all(q.qsize() == 1 for q, _ in snap)


# -- drop bursts through the shared path ---------------------------------


def test_shared_path_drop_bursts_reach_flight_ring_per_subscriber():
    """Forced slow consumer on the SHARED render path: the bounded
    queue drops, and the per-subscriber burst story lands in the
    flight ring exactly as on the legacy fanout path."""
    flight.configure(entries=1024)
    try:
        provider = TelemetryStateProvider()
        stub = types.SimpleNamespace(
            lock=threading.RLock(),
            northbound=types.SimpleNamespace(
                get_state=lambda p=None: provider.get_state(None)
            ),
        )
        svc = gs.GnmiService(stub, shared_fanout=True, fanout_tick=0.5)
        now = [0.0]
        svc.fanout._clock = lambda: now[0]
        beat = telemetry.counter("holo_burst_probe_total")
        slow: queue.Queue = queue.Queue(maxsize=1)
        sid = svc._add_subscriber(slow)
        svc.fanout.attach(
            slow,
            sid,
            [_sub("holo-telemetry/metric", interval_ns=int(5e8))],
        )
        for _ in range(4):  # 1 fills the queue, 3 drop
            beat.inc()
            now[0] += 0.5
            svc.fanout.tick_now(now[0])
        ring = flight.recorder().snapshot_ring()
        starts = [
            e
            for e in ring
            if e[0] == "event"
            and e[1] == "gnmi-drop-burst-start"
            and e[2]["subscriber"] == sid
        ]
        assert len(starts) == 1
        # Draining ends the burst on the next successful shared put.
        slow.get_nowait()
        beat.inc()
        now[0] += 0.5
        svc.fanout.tick_now(now[0])
        ring = flight.recorder().snapshot_ring()
        ends = [
            e
            for e in ring
            if e[0] == "event"
            and e[1] == "gnmi-drop-burst"
            and e[2]["subscriber"] == sid
        ]
        assert len(ends) == 1
        assert ends[0][2]["dropped"] == 3
        assert ends[0][2]["ended"] == "drained"
    finally:
        flight.configure(entries=0)


# -- churn under a convergence storm (satellite) -------------------------


def test_subscriber_churn_under_storm_never_observes_a_torn_epoch():
    """Subscribers joining/leaving mid-convergence-storm: monotonic
    epoch ids per session, first notification is a full sync, and
    correlated leaves always arrive from ONE epoch snapshot.  The
    storm's own causal digest is unaffected by the riding fleet."""
    from holo_tpu.spf.synth_storm import run_convergence_storm

    provider = TelemetryStateProvider()
    quiet = telemetry.counter("holo_churn_quiet_probe_total")
    quiet.inc(7)
    pair_a = telemetry.counter("holo_churn_pair_a_total")
    pair_b = telemetry.counter("holo_churn_pair_b_total")
    quiet_leaf = "holo-telemetry/metric[holo_churn_quiet_probe_total]/value"
    sessions: dict[int, list] = {}
    box: dict = {}

    def attach(net, sid):
        q = queue.Queue(4096)
        box["svc"].fanout.attach(
            q,
            sid,
            [_sub("holo-telemetry/metric", interval_ns=int(5e8),
                  suppress=True)],
        )
        sessions[sid] = []
        box.setdefault("queues", {})[sid] = q

    def hook(net, i, now):
        if "svc" not in box:
            stub = types.SimpleNamespace(
                lock=threading.RLock(),
                northbound=types.SimpleNamespace(
                    get_state=lambda p=None: provider.get_state(None)
                ),
            )
            svc = gs.GnmiService(stub, shared_fanout=True, fanout_tick=0.5)
            svc.fanout._clock = net.loop.clock.now
            svc.fanout._clock_ns = lambda: svc.fanout._epoch
            box["svc"] = svc
        if i == 3:
            attach(net, 1)
            attach(net, 2)
        if i == 20:
            attach(net, 3)  # joins mid-storm
        # Correlated writes BEFORE the tick: any notification carrying
        # both leaves must show them equal (one epoch snapshot).
        pair_a.inc()
        pair_b.inc()
        box["svc"].fanout.tick_now(now)
        for sid, q in box.get("queues", {}).items():
            sessions[sid].extend(_drain(q))
        if i == 35 and 2 in box["queues"]:
            handlebars = box["queues"].pop(2)  # leaves mid-storm
            box["svc"]._remove_subscriber(handlebars)

    _report, digest, _net = run_convergence_storm(
        n_routers=120, events=50, seed=11, event_hook=hook
    )
    _r2, digest_control, _n2 = run_convergence_storm(
        n_routers=120, events=50, seed=11
    )
    assert digest == digest_control, "riding fleet must not perturb the storm"
    assert set(sessions) == {1, 2, 3}
    a_leaf = "holo-telemetry/metric[holo_churn_pair_a_total]/value"
    b_leaf = "holo-telemetry/metric[holo_churn_pair_b_total]/value"
    for sid, notifs in sessions.items():
        assert notifs, f"session {sid} saw no pushes"
        # First notification is a full sync: it carries the quiet leaf
        # (which never changes during the storm); deltas never do.
        assert quiet_leaf in _paths(notifs[0])
        for later in notifs[1:]:
            assert quiet_leaf not in _paths(later)
        # Monotonic epoch ids per session (timestamps carry epochs).
        stamps = [n.timestamp for n in notifs]
        assert stamps == sorted(stamps)
        assert len(set(stamps)) == len(stamps)
        # No torn epoch: correlated counters always arrive equal.
        for n in notifs:
            vals = {
                gs.path_to_str(u.path): u.val.double_val for u in n.update
            }
            if a_leaf in vals and b_leaf in vals:
                assert vals[a_leaf] == vals[b_leaf]
        # The mid-storm joiner's first epoch is later than a founder's.
    assert sessions[3][0].timestamp > sessions[1][0].timestamp


# -- config / provider surfaces ------------------------------------------


def test_config_parses_fanout_and_device_trace_keys(tmp_path):
    from holo_tpu.daemon.config import DaemonConfig

    p = tmp_path / "holod.toml"
    p.write_text(
        """
[telemetry]
enabled = false
gnmi-shared-fanout = false
fanout-tick = 0.25
device-trace-dir = "/tmp/holo-trace"
"""
    )
    cfg = DaemonConfig.load(str(p))
    assert cfg.telemetry.gnmi_shared_fanout is False
    assert cfg.telemetry.fanout_tick == 0.25
    assert cfg.telemetry.device_trace_dir == "/tmp/holo-trace"
    # Defaults: engine on, 1s tick, no trace dir.
    dflt = DaemonConfig()
    assert dflt.telemetry.gnmi_shared_fanout is True
    assert dflt.telemetry.fanout_tick == 1.0
    assert dflt.telemetry.device_trace_dir is None


def test_provider_surfaces_fanout_stats_leaf():
    h = _Harness()
    delta.register_engine(h.engine)
    h.state = _metric_state(p=1.0)
    q = queue.Queue(8)
    h.engine.attach(
        q, 1, [_sub("holo-telemetry", interval_ns=int(1e9))]
    )
    h.tick()
    state = TelemetryStateProvider().get_state()
    rows = state["holo-telemetry"].get("gnmi-fanout")
    assert rows is not None
    row = rows if isinstance(rows, dict) else rows[0]
    found = [
        r
        for r in ([row] if isinstance(row, dict) else row)
        if r.get("subscribers", -1) >= 0
    ]
    assert found and found[0]["breaker"] in ("closed", "open", "half-open")


def test_capture_device_trace_without_tpu_is_explicit_not_used(tmp_path):
    from holo_tpu.telemetry import profiling

    row = profiling.capture_device_trace(tmp_path / "trace")
    assert row["relay"] == "not-used"
    assert row["captured"] is False
    assert row.get("platform", "cpu") != "tpu"
    assert "reason" in row or "error" in row


def test_daemon_boot_with_device_trace_dir_never_fails(tmp_path):
    from holo_tpu.daemon.config import DaemonConfig
    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    cfg = DaemonConfig()
    cfg.telemetry.device_trace_dir = str(tmp_path / "trace")
    d = Daemon(config=cfg, loop=EventLoop(clock=VirtualClock()), name="dtr")
    assert d._device_trace is not None
    assert d._device_trace["relay"] == "not-used"


def test_on_change_sessions_receive_deltas_at_the_base_tick():
    """ON_CHANGE is a first-class citizen of the delta engine: state
    subtree changes reach ON_CHANGE cursors at the base tick (the
    legacy path only ever served them commit/yang notifications and
    heartbeats)."""
    h = _Harness(tick=0.5)
    h.state = _metric_state(oc=1.0)
    q = queue.Queue(64)
    h.engine.attach(
        q, 1, [_sub("holo-telemetry", mode=gs.pb.ON_CHANGE)]
    )
    h.tick(advance=0.5)
    # ON_CHANGE join: the Subscribe preamble is the sync — the first
    # engine epoch (all leaves "changed") does flow, after which only
    # real changes do.
    _drain(q)
    h.tick(advance=0.5)
    assert _drain(q) == []  # no change, no push
    h.state = _metric_state(oc=2.0)
    h.tick(advance=0.5)
    (d,) = _drain(q)
    assert _paths(d) == ["holo-telemetry/metric[oc]/value"]
    snap = telemetry.snapshot(prefix="holo_gnmi_sample")
    # Engine-side pushes ride the same updates counter under their own
    # mode label when wired through the service; the harness has no
    # on_push -> no assertion on the label here.
    assert snap is not None

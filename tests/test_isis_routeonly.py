"""IS-IS Full-vs-RouteOnly SPF split (reference holo-isis/src/spf.rs:
150-156, lsdb.rs:1558-1612): a prefix-only LSP change recomputes routes
over the cached SPT without a Dijkstra dispatch; IS-reach changes keep
forcing Full."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.protocols.isis.instance import IsisIfConfig, IsisIfUpMsg

from tests.test_isis import link, mk_net


class _CountingBackend:
    def __init__(self, inner):
        self.inner = inner
        self.name = inner.name
        self.computes = 0

    def compute(self, topo, multipath_k: int = 1):
        self.computes += 1
        return self.inner.compute(topo, multipath_k=multipath_k)


def _converged_pair():
    loop, fabric, (r1, r2) = mk_net(2)
    link(loop, fabric, r1, "e0", "10.0.12.1", r2, "e0", "10.0.12.2",
         "10.0.12.0/30", 10)
    for r in (r1, r2):
        for ifname in list(r.interfaces):
            loop.send(r.name, IsisIfUpMsg(ifname))
    loop.advance(30)
    return loop, r1, r2


def test_prefix_only_change_is_route_only():
    loop, r1, r2 = _converged_pair()
    counter = _CountingBackend(r1.backend)
    r1.backend = counter
    # A passive circuit adds an ext_ip_reach prefix to r2's LSP without
    # touching its IS-reachability.
    r2.add_interface(
        "lo1", IsisIfConfig(metric=1, passive=True),
        A("192.0.2.1"), N("192.0.2.0/24"),
    )
    loop.send(r2.name, IsisIfUpMsg("lo1"))
    loop.advance(30)
    assert counter.computes == 0, (
        "prefix-only LSP change must not re-run Dijkstra"
    )
    assert r1.spf_log[-1]["type"] == "route-only"
    route = r1.routes.get(N("192.0.2.0/24"))
    assert route is not None and route[0] == 10 + 1


def test_adjacency_change_is_full():
    loop, r1, r2 = _converged_pair()
    counter = _CountingBackend(r1.backend)
    r1.backend = counter
    # Metric change rewrites r2's ext_is_reach: topology changed.
    r2.interfaces["e0"].config.metric = 33
    r2._originate_lsp(force=True)
    loop.advance(30)
    assert counter.computes > 0
    assert r1.spf_log[-1]["type"] == "full"


def test_route_only_and_full_agree():
    loop, r1, r2 = _converged_pair()
    for i in range(3):
        r2.add_interface(
            f"lo{i}", IsisIfConfig(metric=2 + i, passive=True),
            A(f"198.51.{i}.1"), N(f"198.51.{i}.0/24"),
        )
        loop.send(r2.name, IsisIfUpMsg(f"lo{i}"))
    loop.advance(30)
    partial = dict(r1.routes)
    r1._schedule_spf()  # force a full run
    loop.advance(30)
    assert r1.spf_log[-1]["type"] == "full"
    assert r1.routes == partial


def test_spf_log_type_in_daemon_state():
    """The daemon's operational state exposes the SPF log with the
    Full-vs-RouteOnly classification (VERDICT r4: the log must
    distinguish run types in YANG state)."""
    import ipaddress

    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.utils.netio import MockFabric
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="s1")
    d2 = Daemon(loop=loop, netio=fabric, name="s2")
    fabric.join("l", "s1.isis", "eth0", ipaddress.ip_address("10.0.60.1"))
    fabric.join("l", "s2.isis", "eth0", ipaddress.ip_address("10.0.60.2"))
    for d, sysid, addr in [
        (d1, "0000.0000.0021", "10.0.60.1/30"),
        (d2, "0000.0000.0022", "10.0.60.2/30"),
    ]:
        cand = d.candidate()
        cand.set("interfaces/interface[eth0]/address", [addr])
        base = "routing/control-plane-protocols/isis"
        cand.set(f"{base}/system-id", sysid)
        cand.set(f"{base}/level", "level-2")
        cand.set(f"{base}/interface[eth0]/interface-type", "point-to-point")
        d.commit(cand)
    loop.advance(30)
    # A prefix-only change on d2 -> route-only run on d1.
    cand = d2.candidate()
    cand.set("interfaces/interface[lo9]/address", ["192.0.2.9/32"])
    cand.set(
        "routing/control-plane-protocols/isis/interface[lo9]/metric", 1
    )
    d2.commit(cand)
    loop.advance(30)
    log = d1.northbound.get_state()["routing"]["isis"]["spf-log"]
    types = {e["type"] for e in log}
    assert "full" in types
    assert all({"level", "run", "type"} <= set(e) for e in log)

"""gNMI northbound: Capabilities/Get/Set/Subscribe against a live daemon."""

import json
import socket

from holo_tpu.daemon.daemon import Daemon
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_gnmi_end_to_end():
    import holo_tpu.daemon.gnmi_server as gs

    loop = EventLoop(clock=VirtualClock())
    d = Daemon(loop=loop, name="gn")
    port = free_port()
    server = gs.serve_gnmi(d, f"127.0.0.1:{port}")
    try:
        cli = gs.GnmiClient(f"127.0.0.1:{port}")
        caps = cli.Capabilities(gs.pb.CapabilityRequest())
        assert "JSON_IETF" in caps.supported_encodings
        assert any(m.name == "routing" for m in caps.supported_models)

        # Set: typed leaf + JSON subtree merge.
        req = gs.pb.SetRequest()
        u1 = req.update.add()
        u1.path.CopyFrom(gs.str_to_path("system/hostname"))
        u1.val.string_val = "gnmi-rtr"
        u2 = req.update.add()
        u2.path.CopyFrom(gs.str_to_path("interfaces"))
        u2.val.json_ietf_val = json.dumps(
            {"interface": {"eth0": {"mtu": 4000, "address": ["192.0.2.1/24"]}}}
        )
        resp = cli.Set(req)
        assert len(resp.response) == 2

        # Get CONFIG at a path.
        get = gs.pb.GetRequest(type=gs.pb.GetRequest.CONFIG)
        get.path.add().CopyFrom(gs.str_to_path("system/hostname"))
        out = cli.Get(get)
        payload = json.loads(out.notification[0].update[0].val.json_ietf_val)
        assert payload["config"] == "gnmi-rtr"

        # Get ALL at root includes state.
        out = cli.Get(gs.pb.GetRequest(type=gs.pb.GetRequest.ALL))
        payload = json.loads(out.notification[0].update[0].val.json_ietf_val)
        assert payload["state"]["system"]["hostname"] == "gnmi-rtr"
        assert payload["config"]["interfaces"]["interface"]["eth0"]["mtu"] == 4000

        # Set with invalid value aborts with INVALID_ARGUMENT.
        bad = gs.pb.SetRequest()
        ub = bad.update.add()
        ub.path.CopyFrom(gs.str_to_path("interfaces/interface[eth0]/mtu"))
        ub.val.string_val = "999999"
        import grpc
        import pytest

        with pytest.raises(grpc.RpcError) as ei:
            cli.Set(bad)
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT

        # Get with PROTO encoding: one Update per leaf, native types
        # (reference gnmi.rs gen_update_proto).
        assert "PROTO" in caps.supported_encodings
        get = gs.pb.GetRequest(
            type=gs.pb.GetRequest.CONFIG, encoding=gs.pb.PROTO
        )
        get.path.add().CopyFrom(gs.str_to_path("interfaces"))
        out = cli.Get(get)
        updates = out.notification[0].update
        by_path = {
            gs.path_to_str(u.path): u.val for u in updates
        }
        mtu_path = next(p for p in by_path if p.endswith("/mtu"))
        assert by_path[mtu_path].WhichOneof("value") == "uint_val"
        assert by_path[mtu_path].uint_val == 4000
        hn = cli.Get(
            gs.pb.GetRequest(
                type=gs.pb.GetRequest.CONFIG, encoding=gs.pb.PROTO,
                path=[gs.str_to_path("system/hostname")],
            )
        )
        vals = hn.notification[0].update
        assert any(
            v.val.WhichOneof("value") == "string_val"
            and v.val.string_val == "gnmi-rtr"
            for v in vals
        )

        # Subscribe ONCE: snapshot + sync_response.
        sub = gs.pb.SubscribeRequest()
        sub.subscribe.mode = gs.pb.SubscriptionList.ONCE
        msgs = list(cli.Subscribe(iter([sub])))
        assert any(m.HasField("sync_response") and m.sync_response for m in msgs)
        snap = json.loads(msgs[0].update.update[0].val.json_ietf_val)
        assert snap["system"]["hostname"] == "gnmi-rtr"
    finally:
        server.stop(grace=0)


def test_gnmi_serve_wires_shared_fanout_from_config():
    """serve_gnmi arms the shared-delta fan-out engine by default
    (ISSUE 11) and honours `[telemetry] gnmi-shared-fanout = false`
    (the byte-identical per-subscriber walk configuration)."""
    import holo_tpu.daemon.gnmi_server as gs
    from holo_tpu.daemon.config import DaemonConfig

    d = Daemon(loop=EventLoop(clock=VirtualClock()), name="fw1")
    port = free_port()
    server = gs.serve_gnmi(d, f"127.0.0.1:{port}")
    try:
        svc = d._gnmi_service
        assert svc.fanout is not None
        assert svc.fanout.tick == d.config.telemetry.fanout_tick
        assert svc.fanout.stats()["breaker"] == "closed"
        assert svc.fanout._thread is not None  # ticker armed
    finally:
        server.stop(grace=0)
    # server.stop joins the fan-out ticker too (no leaked engine per
    # serve_gnmi call — the pre-existing caller contract suffices).
    assert svc.fanout._thread is None

    cfg = DaemonConfig()
    cfg.telemetry.gnmi_shared_fanout = False
    d2 = Daemon(config=cfg, loop=EventLoop(clock=VirtualClock()), name="fw2")
    port = free_port()
    server = gs.serve_gnmi(d2, f"127.0.0.1:{port}")
    try:
        assert d2._gnmi_service.fanout is None
    finally:
        server.stop(grace=0)


def test_gnmi_subscribe_streams_yang_notifications():
    """Protocol YANG notifications reach gNMI STREAM subscribers as
    updates pathed by the notification's qualified name."""
    import socket as _socket
    import threading

    import holo_tpu.daemon.gnmi_server as gs
    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    loop = EventLoop(clock=VirtualClock())
    d = Daemon(loop=loop, name="gn2")
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    server = gs.serve_gnmi(d, f"127.0.0.1:{port}")
    try:
        cli = gs.GnmiClient(f"127.0.0.1:{port}")
        got = []
        synced = threading.Event()

        def consume():
            sub = gs.pb.SubscribeRequest()
            sub.subscribe.mode = gs.pb.SubscriptionList.STREAM
            for m in cli.Subscribe(iter([sub])):
                if m.HasField("sync_response"):
                    synced.set()
                    continue
                paths = [
                    "/".join(e.name for e in u.path.elem)
                    for u in m.update.update
                ]
                if any("nbr-state-change" in p for p in paths):
                    got.append(m)
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        assert synced.wait(10), "no sync_response"
        import time as _time

        _time.sleep(0.3)
        d._dispatch_yang_notification(
            {"ietf-ospf:nbr-state-change": {"state": "full"}}
        )
        t.join(10)
        assert got, "gNMI stream delivered no YANG notification"
        body = json.loads(got[0].update.update[0].val.json_ietf_val)
        assert body["state"] == "full"
    finally:
        server.stop(grace=0)

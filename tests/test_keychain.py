"""Keychain send/accept lifetimes + live key rollover
(reference holo-utils/src/keychain.rs:42-92; the overlap of the old
key's accept lifetime with the new key's send lifetime is what makes
rollover lossless)."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.protocols.ospf.neighbor import NsmState
from holo_tpu.utils.keychain import Key, Keychain, KeyLifetime
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def _rollover_chain():
    """Key 1 sends until t=100 and is accepted until t=140; key 2 sends
    from t=100 and is accepted from t=60 — a 40 s overlap either side."""
    return Keychain(
        "roll",
        [
            Key(1, "md5", b"old-key",
                send_lifetime=KeyLifetime(None, 100),
                accept_lifetime=KeyLifetime(None, 140)),
            Key(2, "hmac-sha-256", b"new-key",
                send_lifetime=KeyLifetime(100, None),
                accept_lifetime=KeyLifetime(60, None)),
        ],
    )


def test_lookup_semantics():
    kc = _rollover_chain()
    assert kc.key_lookup_send(50).id == 1
    assert kc.key_lookup_send(100).id == 2  # boundary: start inclusive
    assert kc.key_lookup_send(99.9).id == 1
    assert kc.key_lookup_accept(1, 120).id == 1  # old still accepted
    assert kc.key_lookup_accept(1, 140) is None  # accept window over
    assert kc.key_lookup_accept(2, 50) is None  # not yet
    assert kc.key_lookup_accept(2, 70).id == 2
    assert kc.key_lookup_accept_any(50).id == 1
    assert kc.key_lookup_accept_any(150).id == 2


def test_from_config_lifetimes():
    kc = Keychain.from_config(
        "c",
        {
            "key": {
                "1": {
                    "key-string": "aaa",
                    "crypto-algorithm": "md5",
                    "send-lifetime": {
                        "start-date-time": "1970-01-01T00:00:10+00:00",
                        "end-date-time": "1970-01-01T00:01:40+00:00",
                    },
                    "accept-lifetime": {
                        "start-date-time": 0,
                        "end-date-time": 130,
                    },
                },
                "2": {"key-string": "bbb"},
            }
        },
    )
    k1 = kc.key_lookup_accept(1, 50)
    assert k1 is not None and k1.string == b"aaa"
    assert kc.key_lookup_send(5).id == 2  # key 1 send starts at t=10
    assert kc.key_lookup_send(50).id == 1  # ascending id, both active


def test_ospf_rollover_zero_loss():
    """OSPF adjacency across a send-key boundary: zero auth failures,
    neighbor stays FULL, even with different algorithms per key
    (the VERDICT acceptance test)."""
    from holo_tpu.protocols.ospf import packet as pkt_mod
    from holo_tpu.protocols.ospf.instance import (
        IfConfig, IfUpMsg, InstanceConfig, OspfInstance,
    )
    from holo_tpu.protocols.ospf.interface import IfType
    from holo_tpu.protocols.ospf.packet import AuthCtx, AuthType

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    failures = []
    orig_decode = pkt_mod.Packet.decode.__func__

    def counting_decode(cls, data, auth=None):
        try:
            return orig_decode(cls, data, auth)
        except pkt_mod.DecodeError as e:
            failures.append(str(e))
            raise

    pkt_mod.Packet.decode = classmethod(counting_decode)
    try:
        routers = []
        for name, rid, addr in (
            ("a1", "1.1.1.1", "10.0.0.1"),
            ("a2", "2.2.2.2", "10.0.0.2"),
        ):
            inst = OspfInstance(
                name=name,
                config=InstanceConfig(router_id=A(rid)),
                netio=fabric.sender_for(name),
            )
            loop.register(inst)
            auth = AuthCtx(
                AuthType.CRYPTOGRAPHIC,
                keychain=_rollover_chain(),
                clock=loop.clock.now,
            )
            cfg = IfConfig(
                if_type=IfType.POINT_TO_POINT,
                hello_interval=2, dead_interval=8, auth=auth,
            )
            inst.add_interface("e0", cfg, N("10.0.0.0/30"), A(addr))
            fabric.join("l", name, "e0", A(addr))
            routers.append(inst)
        for r in routers:
            loop.send(r.name, IfUpMsg("e0"))
        loop.advance(40)  # converge well before the t=100 boundary

        def full(r):
            return any(
                n.state == NsmState.FULL
                for a in r.areas.values()
                for i in a.interfaces.values()
                for n in i.neighbors.values()
            )

        assert all(full(r) for r in routers), "pre-rollover adjacency"
        failures.clear()
        loop.advance(120)  # cross t=100: key 1 -> key 2, algo changes too
        assert all(full(r) for r in routers), "adjacency lost in rollover"
        assert failures == [], f"auth failures across rollover: {failures}"
        # The new key is genuinely in use now (key id 2 on the wire).
        a = routers[0]._iface("e0")[1].config.auth
        assert a.tx_key_id == 2
    finally:
        pkt_mod.Packet.decode = classmethod(orig_decode)


def test_isis_rollover_zero_loss():
    """IS-IS LSP/hello auth across a send-key boundary (RFC 5310 key
    ids; reference packet/auth.rs AuthMethod::Keychain)."""
    from holo_tpu.protocols.isis import packet as ipkt
    from holo_tpu.protocols.isis.instance import IsisIfConfig, IsisIfUpMsg
    from holo_tpu.protocols.isis.packet import AuthCtxIsis

    from tests.test_isis import link, mk_net

    kc = Keychain(
        "iroll",
        [
            Key(1, "hmac-sha1", b"old",
                send_lifetime=KeyLifetime(None, 100),
                accept_lifetime=KeyLifetime(None, 140)),
            Key(2, "hmac-sha256", b"new",
                send_lifetime=KeyLifetime(100, None),
                accept_lifetime=KeyLifetime(60, None)),
        ],
    )
    loop, fabric, (r1, r2) = mk_net(2)
    for r in (r1, r2):
        r.auth = AuthCtxIsis(
            key=b"", keychain=kc, clock=loop.clock.now
        )
    link(loop, fabric, r1, "e0", "10.0.12.1", r2, "e0", "10.0.12.2",
         "10.0.12.0/30", 10)
    failures = []
    orig = ipkt.verify_pdu_auth

    def counting_verify(data, tlvs, auth):
        try:
            return orig(data, tlvs, auth)
        except ipkt.AuthError as e:
            failures.append(str(e))
            raise

    ipkt.verify_pdu_auth = counting_verify
    try:
        for r in (r1, r2):
            for ifname in list(r.interfaces):
                loop.send(r.name, IsisIfUpMsg(ifname))
        loop.advance(40)
        assert set(r1.lsdb) == set(r2.lsdb) and r1.routes, "pre-rollover"
        failures.clear()
        loop.advance(120)  # cross the t=100 send boundary
        from holo_tpu.protocols.isis.instance import AdjacencyState

        assert r1.interfaces["e0"].adj.state == AdjacencyState.UP
        assert r2.interfaces["e0"].adj.state == AdjacencyState.UP
        assert failures == [], f"auth failures across rollover: {failures}"
        assert r1.auth.for_send().key_id == 2  # new key on the wire
    finally:
        ipkt.verify_pdu_auth = orig


def test_isis_md5_rollover_tries_all_accept_keys():
    """RFC 5304 HMAC-MD5 carries no key id: during the overlap window
    verification must try every accept-active md5 key, or rollover
    drops each PDU signed with the other key (r5 review)."""
    from holo_tpu.protocols.isis.instance import (
        AdjacencyState, IsisIfUpMsg,
    )
    from holo_tpu.protocols.isis.packet import AuthCtxIsis

    from tests.test_isis import link, mk_net

    kc = Keychain(
        "md5roll",
        [
            Key(1, "hmac-md5", b"old",
                send_lifetime=KeyLifetime(None, 100),
                accept_lifetime=KeyLifetime(None, 140)),
            Key(2, "hmac-md5", b"new",
                send_lifetime=KeyLifetime(100, None),
                accept_lifetime=KeyLifetime(60, None)),
        ],
    )
    loop, fabric, (r1, r2) = mk_net(2)
    for r in (r1, r2):
        r.auth = AuthCtxIsis(key=b"", keychain=kc, clock=loop.clock.now)
    link(loop, fabric, r1, "e0", "10.0.14.1", r2, "e0", "10.0.14.2",
         "10.0.14.0/30", 10)
    for r in (r1, r2):
        for ifname in list(r.interfaces):
            loop.send(r.name, IsisIfUpMsg(ifname))
    loop.advance(40)
    assert r1.interfaces["e0"].adj.state == AdjacencyState.UP
    loop.advance(120)  # cross t=100: both keys md5, no wire key id
    assert r1.interfaces["e0"].adj.state == AdjacencyState.UP
    assert r2.interfaces["e0"].adj.state == AdjacencyState.UP
    assert r1.auth.for_send().key == b"new"


def test_malformed_lifetime_fails_closed():
    """A typo'd date-time must reject the commit, not silently make the
    key immortal (r5 review)."""
    import pytest

    from holo_tpu.utils.keychain import Keychain

    with pytest.raises(ValueError, match="invalid lifetime"):
        Keychain.from_config(
            "bad",
            {"key": {"1": {
                "key-string": "x",
                "send-lifetime": {"end-date-time": "2026-13-01T00:00:00Z"},
            }}},
        )

    from holo_tpu.daemon.daemon import Daemon

    loop = EventLoop(clock=VirtualClock())
    d = Daemon(loop=loop, netio=MockFabric(loop), name="kv")
    cand = d.candidate()
    cand.set("key-chains/key-chain[bad]/key[1]/key-string", "x")
    cand.set(
        "key-chains/key-chain[bad]/key[1]/send-lifetime/end-date-time",
        "2026-13-01T00:00:00Z",
    )
    with pytest.raises(Exception, match="key-chain 'bad'"):
        d.commit(cand)


def test_ospf_send_gap_goes_unauthenticated():
    """A keychain coverage gap (no active send key) sends NULL-auth
    packets — the reference's get_key_send -> None behavior — rather
    than signing with a phantom empty key under a real key id."""
    from holo_tpu.protocols.ospf.packet import (
        AuthCtx, AuthType, Hello, Options, Packet,
    )

    kc = Keychain(
        "gap",
        [Key(1, "md5", b"k", send_lifetime=KeyLifetime(None, 10))],
    )
    t = [50.0]  # inside the gap
    auth = AuthCtx(AuthType.CRYPTOGRAPHIC, keychain=kc, clock=lambda: t[0])
    pkt = Packet(
        A("1.1.1.1"), A("0.0.0.0"),
        Hello(A("255.255.255.252"), 2, Options.E, 1, 8, A("0.0.0.0"),
              A("0.0.0.0"), []),
    )
    raw = pkt.encode(auth=auth)
    # Auth type field (bytes 14:16) is NULL, not CRYPTOGRAPHIC.
    assert int.from_bytes(raw[14:16], "big") == int(AuthType.NULL)


def test_daemon_isis_keychain_auth():
    """Config-driven IS-IS: instance authentication via a key-chain
    (reference configuration.rs:531-597 AuthMethod::Keychain) — the
    daemon-assembled instances sign/verify LSPs with the lifetime-
    resolved key, including the OSPF-style ietf algorithm names."""
    import ipaddress

    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.utils.netio import MockFabric

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="i1")
    d2 = Daemon(loop=loop, netio=fabric, name="i2")
    fabric.join("l", "i1.isis", "eth0", ipaddress.ip_address("10.0.20.1"))
    fabric.join("l", "i2.isis", "eth0", ipaddress.ip_address("10.0.20.2"))
    for d, sysid, addr in [
        (d1, "0000.0000.0001", "10.0.20.1/30"),
        (d2, "0000.0000.0002", "10.0.20.2/30"),
    ]:
        cand = d.candidate()
        kb = "key-chains/key-chain[isis-keys]"
        cand.set(f"{kb}/key[1]/key-string", "lsp-secret")
        cand.set(f"{kb}/key[1]/crypto-algorithm", "hmac-sha-256")
        base = "routing/control-plane-protocols/isis"
        cand.set("interfaces/interface[eth0]/address", [addr])
        cand.set(f"{base}/system-id", sysid)
        cand.set(f"{base}/level", "level-2")
        cand.set(f"{base}/authentication/key-chain", "isis-keys")
        cand.set(f"{base}/interface[eth0]/interface-type", "point-to-point")
        d.commit(cand)
    loop.advance(30)
    i1 = d1.routing.instances["isis"]
    i2 = d2.routing.instances["isis"]
    assert i1.auth is not None and i1.auth.keychain is not None
    from holo_tpu.protocols.isis.instance import AdjacencyState

    assert i1.interfaces["eth0"].adj.state == AdjacencyState.UP
    assert set(i1.lsdb) == set(i2.lsdb) and len(i1.lsdb) >= 2
    # The resolved send key uses the normalized IS-IS algo name.
    assert i1.auth.for_send().algo == "hmac-sha256"

    # An instance with a MISMATCHED inline key never syncs.
    d3 = Daemon(loop=loop, netio=fabric, name="i3")
    fabric.join("l2", "i1.isis", "eth1", ipaddress.ip_address("10.0.21.1"))
    fabric.join("l2", "i3.isis", "eth0", ipaddress.ip_address("10.0.21.2"))
    cand = d3.candidate()
    base = "routing/control-plane-protocols/isis"
    cand.set("interfaces/interface[eth0]/address", ["10.0.21.2/30"])
    cand.set(f"{base}/system-id", "0000.0000.0003")
    cand.set(f"{base}/level", "level-2")
    cand.set(f"{base}/authentication/key", "wrong-secret")
    cand.set(f"{base}/interface[eth0]/interface-type", "point-to-point")
    d3.commit(cand)
    cand = d1.candidate()
    cand.set("interfaces/interface[eth1]/address", ["10.0.21.1/30"])
    cand.set(f"{base}/interface[eth1]/interface-type", "point-to-point")
    d1.commit(cand)
    loop.advance(30)
    i3 = d3.routing.instances["isis"]
    assert not i3.lsdb or set(i3.lsdb) != set(i1.lsdb)


def test_isis_auth_live_reconfig_and_rollover():
    """Keychain store changes and auth config changes reach a RUNNING
    IS-IS instance (r5 review): adding a key re-resolves the snapshot,
    and enabling auth later than instance creation applies it."""
    import ipaddress

    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.utils.netio import MockFabric

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d = Daemon(loop=loop, netio=fabric, name="ik")
    base = "routing/control-plane-protocols/isis"
    cand = d.candidate()
    cand.set("interfaces/interface[eth0]/address", ["10.0.30.1/30"])
    cand.set(f"{base}/system-id", "0000.0000.0009")
    cand.set(f"{base}/level", "level-2")
    cand.set(f"{base}/interface[eth0]/interface-type", "point-to-point")
    d.commit(cand)
    inst = d.routing.instances["isis"]
    assert inst.auth is None  # no auth configured yet

    # Enable keychain auth on the RUNNING instance.
    cand = d.candidate()
    cand.set("key-chains/key-chain[ik]/key[1]/key-string", "one")
    cand.set("key-chains/key-chain[ik]/key[1]/crypto-algorithm", "md5")
    cand.set(f"{base}/authentication/key-chain", "ik")
    d.commit(cand)
    assert inst.auth is not None and inst.auth.keychain is not None
    assert len(inst.auth.keychain.keys) == 1

    # Key rotation: adding key 2 to the chain must reach the instance
    # WITHOUT touching the isis config (TOPIC_KEYCHAIN_UPD path).
    cand = d.candidate()
    cand.set("key-chains/key-chain[ik]/key[2]/key-string", "two")
    cand.set("key-chains/key-chain[ik]/key[2]/crypto-algorithm", "md5")
    d.commit(cand)
    assert len(inst.auth.keychain.keys) == 2

    # Inline key ids are masked to the u16 the TLV carries.
    cand = d.candidate()
    cand.delete(f"{base}/authentication/key-chain")
    cand.set(f"{base}/authentication/key", "inline")
    cand.set(f"{base}/authentication/key-id", 70000)
    d.commit(cand)
    assert inst.auth.keychain is None
    assert inst.auth.key_id == 70000 & 0xFFFF


def test_rip_keychain_rollover_zero_loss():
    """Config-driven RIPv2 MD5 via a key-chain with lifetimes: two
    daemons exchange authenticated updates across a send-key boundary
    without losing routes (the wire key id selects the accept key)."""
    import ipaddress

    import pytest as _pytest

    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.utils.netio import MockFabric

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="r1")
    d2 = Daemon(loop=loop, netio=fabric, name="r2")
    fabric.join("l", "r1.ripv2", "eth0", ipaddress.ip_address("10.0.40.1"))
    fabric.join("l", "r2.ripv2", "eth0", ipaddress.ip_address("10.0.40.2"))
    for d, addr, extra in [
        (d1, "10.0.40.1/30", "192.0.2.0/24"),
        (d2, "10.0.40.2/30", "198.51.100.0/24"),
    ]:
        cand = d.candidate()
        kb = "key-chains/key-chain[rip-keys]"
        cand.set(f"{kb}/key[1]/key-string", "one")
        cand.set(f"{kb}/key[1]/send-lifetime/end-date-time", 60)
        cand.set(f"{kb}/key[1]/accept-lifetime/end-date-time", 120)
        cand.set(f"{kb}/key[2]/key-string", "two")
        cand.set(f"{kb}/key[2]/send-lifetime/start-date-time", 60)
        cand.set(f"{kb}/key[2]/accept-lifetime/start-date-time", 30)
        cand.set("interfaces/interface[eth0]/address", [addr])
        cand.set("interfaces/interface[lo0]/address", [extra])
        base = "routing/control-plane-protocols/ripv2"
        cand.set(f"{base}/update-interval", 5)
        cand.set(f"{base}/interface[eth0]/cost", 1)
        cand.set(f"{base}/interface[lo0]/cost", 1)
        cand.set(f"{base}/interface[eth0]/authentication/key-chain",
                 "rip-keys")
        d.commit(cand)
    loop.advance(30)
    i1 = d1.routing.instances["ripv2"]
    far = ipaddress.ip_network("198.51.100.0/24")
    assert far in i1.routes, "authenticated route exchange failed"
    loop.advance(80)  # cross the t=60 send boundary (key 1 -> key 2)
    assert far in i1.routes, "route lost across RIP key rollover"
    cfg = i1.interfaces["eth0"][0]
    k = cfg.auth_keychain.key_lookup_send(loop.clock.now())
    assert k is not None and k.id == 2  # signing with the new key now

    # A third daemon with NO auth config never syncs with r1.
    d3 = Daemon(loop=loop, netio=fabric, name="r3")
    fabric.join("l", "r3.ripv2", "eth0", ipaddress.ip_address("10.0.40.3"))
    cand = d3.candidate()
    cand.set("interfaces/interface[eth0]/address", ["10.0.40.3/30"])
    cand.set("routing/control-plane-protocols/ripv2/interface[eth0]/cost", 1)
    d3.commit(cand)
    loop.advance(30)
    i3 = d3.routing.instances["ripv2"]
    assert far not in i3.routes  # unauthenticated: r2's updates rejected

    # RIPng rejects auth config outright (RFC 2080).
    cand = d1.candidate()
    cand.set(
        "routing/control-plane-protocols/ripng/interface[eth0]"
        "/authentication/key", "x",
    )
    with _pytest.raises(Exception, match="RIPng has no in-protocol"):
        d1.commit(cand)


def test_keychain_reference_validation_symmetry():
    """A typo'd key-chain reference is rejected at commit time for
    EVERY consumer — IS-IS and RIP, not just OSPF (r5 review)."""
    import pytest as _pytest

    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.utils.netio import MockFabric

    loop = EventLoop(clock=VirtualClock())
    d = Daemon(loop=loop, netio=MockFabric(loop), name="kv2")
    for path in (
        "routing/control-plane-protocols/isis/authentication/key-chain",
        "routing/control-plane-protocols/isis/interface[e0]"
        "/hello-authentication/key-chain",
        "routing/control-plane-protocols/ripv2/interface[e0]"
        "/authentication/key-chain",
    ):
        cand = d.candidate()
        if "isis" in path:
            cand.set(
                "routing/control-plane-protocols/isis/system-id",
                "0000.0000.0011",
            )
        cand.set(path, "no-such-chain")
        with _pytest.raises(Exception, match="unknown key-chain"):
            d.commit(cand)


def test_isis_keychain_sha512():
    """Every algorithm the key-chain enum allows signs IS-IS PDUs
    (r5 review: sha-384/512 used to KeyError at encode time)."""
    from holo_tpu.protocols.isis.packet import AuthCtxIsis

    kc = Keychain("s", [Key(5, "hmac-sha-512", b"k512")])
    auth = AuthCtxIsis(key=b"", keychain=kc, clock=lambda: 1.0)
    eff = auth.for_send()
    assert eff.algo == "hmac-sha512"
    assert len(eff._hmac(b"payload")) == 64


def test_rip_keychain_key_id_over_255():
    """Keychain key ids above 255 still authenticate: the receiver
    compares the masked wire id (r5 review)."""
    from holo_tpu.protocols.rip import RipIfConfig, RipPacket, RipCommand

    kc = Keychain("r", [Key(300, "md5", b"sekrit")])
    cfg = RipIfConfig(auth_keychain=kc, auth_clock=lambda: 1.0)
    pw, key, key_id, seqno, lookup = cfg.auth_tuple(7)
    assert key == b"sekrit" and key_id == 300 & 0xFF
    raw = RipPacket(RipCommand.RESPONSE, []).encode(
        auth_key=key, auth_key_id=key_id, seqno=seqno
    )
    out = RipPacket.decode(raw, auth_key_lookup=lookup)
    assert out.command == RipCommand.RESPONSE


def test_ospfv3_keychain_rollover_zero_loss():
    """Config-driven OSPFv3 RFC 7166 auth via a key-chain: the trailer
    SA id is the key id, rollover crosses a send boundary (including an
    algorithm change) with the adjacency intact (reference
    ospfv3/packet/mod.rs:860-876 AuthMethod::Keychain)."""
    import ipaddress

    import pytest as _pytest

    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.utils.netio import MockFabric

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="v1")
    d2 = Daemon(loop=loop, netio=fabric, name="v2")
    fabric.join("l", "v1.ospfv3", "eth0", ipaddress.ip_address("fe80::1"))
    fabric.join("l", "v2.ospfv3", "eth0", ipaddress.ip_address("fe80::2"))
    for d, rid, ll, pfx in [
        (d1, "1.1.1.1", "fe80::1/64", "2001:db8:1::1/64"),
        (d2, "2.2.2.2", "fe80::2/64", "2001:db8:2::1/64"),
    ]:
        cand = d.candidate()
        kb = "key-chains/key-chain[v3-keys]"
        cand.set(f"{kb}/key[1]/key-string", "one")
        cand.set(f"{kb}/key[1]/crypto-algorithm", "hmac-sha-256")
        cand.set(f"{kb}/key[1]/send-lifetime/end-date-time", 90)
        cand.set(f"{kb}/key[1]/accept-lifetime/end-date-time", 150)
        cand.set(f"{kb}/key[2]/key-string", "two")
        cand.set(f"{kb}/key[2]/crypto-algorithm", "hmac-sha-512")
        cand.set(f"{kb}/key[2]/send-lifetime/start-date-time", 90)
        cand.set(f"{kb}/key[2]/accept-lifetime/start-date-time", 45)
        cand.set("interfaces/interface[eth0]/address", [ll, pfx])
        cand.set("routing/control-plane-protocols/ospfv3/router-id", rid)
        base = (
            "routing/control-plane-protocols/ospfv3/area[0.0.0.0]"
            "/interface[eth0]"
        )
        cand.set(f"{base}/cost", 4)
        cand.set(f"{base}/hello-interval", 2)
        cand.set(f"{base}/dead-interval", 8)
        cand.set(f"{base}/authentication/key-chain", "v3-keys")
        d.commit(cand)
    loop.advance(40)
    from ipaddress import IPv6Network as N6

    far = N6("2001:db8:2::/64")
    assert far in d1.routing.rib.active_routes(), "v3 auth exchange failed"
    inst = d1.routing.instances["ospfv3"]
    auth = inst.interfaces["eth0"].config.auth
    assert auth is not None and auth.keychain is not None
    assert auth.resolve_send().sa_id == 1
    loop.advance(120)  # cross t=90: key/algo roll to sha-512 key 2
    assert far in d1.routing.rib.active_routes(), "route lost in rollover"
    nbrs = inst.interfaces["eth0"].neighbors
    from holo_tpu.protocols.ospf.neighbor import NsmState

    assert any(n.state == NsmState.FULL for n in nbrs.values())
    assert auth.resolve_send().sa_id == 2

    # v2-style auth types are rejected for v3 at commit time.
    cand = d1.candidate()
    cand.set(
        "routing/control-plane-protocols/ospfv3/area[0.0.0.0]"
        "/interface[eth0]/authentication/type", "md5",
    )
    with _pytest.raises(Exception, match="RFC 7166"):
        d1.commit(cand)


def test_rip_md5_replay_rejected():
    """RFC 2082 §3.2.2: a captured authenticated RESPONSE replayed
    after newer packets were accepted is discarded (r5 review)."""
    from ipaddress import IPv4Address as A4
    from ipaddress import IPv4Network as N4

    from holo_tpu.protocols.rip import (
        RipIfConfig, RipInstance, RipPacket, RipCommand, Rte,
    )
    from holo_tpu.utils.netio import MockFabric, NetRxPacket

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    inst = RipInstance("rp", netio=fabric.sender_for("rp"))
    loop.register(inst)
    inst.add_interface(
        "e0", RipIfConfig(auth_key=b"k", auth_key_id=1),
        A4("10.0.50.1"), N4("10.0.50.0/24"),
    )
    src = A4("10.0.50.2")

    def adv(prefix, metric, seqno):
        raw = RipPacket(
            RipCommand.RESPONSE, [Rte(N4(prefix), A4("0.0.0.0"), metric)]
        ).encode(auth_key=b"k", auth_key_id=1, seqno=seqno)
        loop.send("rp", NetRxPacket("e0", src, A4("224.0.0.9"), raw))
        loop.advance(1)

    captured = N4("203.0.113.0/24")
    adv("203.0.113.0/24", 1, seqno=5)
    assert captured in inst.routes
    # Route withdrawn with a NEWER seqno...
    adv("203.0.113.0/24", 16, seqno=6)
    assert inst.routes[captured].metric == 16  # poisoned
    # ...then the old packet is replayed: it must NOT resurrect it.
    adv("203.0.113.0/24", 1, seqno=5)
    assert inst.routes[captured].metric == 16, "replayed packet accepted"


def test_ospfv3_rejects_md5_keychain():
    """A chain containing md5 keys (incl. the crypto-algorithm default)
    cannot be referenced by an OSPFv3 interface — commit rejected
    (r5 review: used to commit silently and run unauthenticated)."""
    import pytest as _pytest

    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.utils.netio import MockFabric

    loop = EventLoop(clock=VirtualClock())
    d = Daemon(loop=loop, netio=MockFabric(loop), name="vm")
    cand = d.candidate()
    cand.set("key-chains/key-chain[m]/key[1]/key-string", "x")  # md5 default
    cand.set("interfaces/interface[eth0]/address", ["fe80::9/64"])
    cand.set("routing/control-plane-protocols/ospfv3/router-id", "9.9.9.9")
    cand.set(
        "routing/control-plane-protocols/ospfv3/area[0.0.0.0]"
        "/interface[eth0]/authentication/key-chain", "m",
    )
    with _pytest.raises(Exception, match="no RFC 7166 algorithm"):
        d.commit(cand)


def test_rip_replay_floor_resets_on_neighbor_timeout():
    """A restarted peer (auth seqno back near zero) recovers once its
    neighbor entry times out — the replay floor must not outlive the
    neighbor (r5 review)."""
    from ipaddress import IPv4Address as A4
    from ipaddress import IPv4Network as N4

    from holo_tpu.protocols.rip import (
        RipCommand, RipIfConfig, RipInstance, RipPacket, Rte,
    )
    from holo_tpu.utils.netio import MockFabric, NetRxPacket

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    inst = RipInstance("rf", netio=fabric.sender_for("rf"))
    loop.register(inst)
    inst.add_interface(
        "e0", RipIfConfig(auth_key=b"k", auth_key_id=1),
        A4("10.0.51.1"), N4("10.0.51.0/24"),
    )
    src = A4("10.0.51.2")

    def adv(metric, seqno):
        raw = RipPacket(
            RipCommand.RESPONSE,
            [Rte(N4("203.0.113.0/24"), A4("0.0.0.0"), metric)],
        ).encode(auth_key=b"k", auth_key_id=1, seqno=seqno)
        loop.send("rf", NetRxPacket("e0", src, A4("224.0.0.9"), raw))
        loop.advance(1)

    adv(1, seqno=500)
    assert N4("203.0.113.0/24") in inst.routes
    # Peer "reboots": low seqno rejected while the floor stands...
    adv(2, seqno=3)
    assert inst.routes[N4("203.0.113.0/24")].metric == 2  # cost 1 + 1
    # metric unchanged means rejected; verify via the floor directly:
    assert inst._rx_auth_seqnos[("e0", src)] == 500
    inst.nbr_timeout(src)
    assert ("e0", src) not in inst._rx_auth_seqnos
    adv(4, seqno=3)  # now accepted
    assert inst.routes[N4("203.0.113.0/24")].metric == 5


def test_ospfv3_rejects_empty_keychain():
    import pytest as _pytest

    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.utils.netio import MockFabric

    loop = EventLoop(clock=VirtualClock())
    d = Daemon(loop=loop, netio=MockFabric(loop), name="ve")
    cand = d.candidate()
    cand.set("key-chains/key-chain[empty]/name", "empty")
    cand.set("interfaces/interface[eth0]/address", ["fe80::8/64"])
    cand.set("routing/control-plane-protocols/ospfv3/router-id", "8.8.8.8")
    cand.set(
        "routing/control-plane-protocols/ospfv3/area[0.0.0.0]"
        "/interface[eth0]/authentication/key-chain", "empty",
    )
    with _pytest.raises(Exception, match="has no keys"):
        d.commit(cand)


def test_empty_keychain_rejected_for_all_consumers():
    """An empty chain is a silent auth outage for EVERY consumer —
    rejected at commit for OSPFv2, IS-IS, and RIP too (r5 review)."""
    import pytest as _pytest

    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.utils.netio import MockFabric

    loop = EventLoop(clock=VirtualClock())
    d = Daemon(loop=loop, netio=MockFabric(loop), name="ke")
    for path, extra in (
        (
            "routing/control-plane-protocols/ospfv2/area[0.0.0.0]"
            "/interface[e0]/authentication/key-chain",
            [("routing/control-plane-protocols/ospfv2/router-id",
              "7.7.7.7")],
        ),
        (
            "routing/control-plane-protocols/isis/authentication"
            "/key-chain",
            [("routing/control-plane-protocols/isis/system-id",
              "0000.0000.0031")],
        ),
        (
            "routing/control-plane-protocols/ripv2/interface[e0]"
            "/authentication/key-chain",
            [],
        ),
    ):
        cand = d.candidate()
        cand.set("key-chains/key-chain[hollow]/name", "hollow")
        for p, v in extra:
            cand.set(p, v)
        cand.set(path, "hollow")
        with _pytest.raises(Exception, match="has no keys"):
            d.commit(cand)

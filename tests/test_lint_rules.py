"""holo-lint golden fixtures: every rule fires on a known-bad snippet,
honors `# holo-lint: disable=<id>`, and stays quiet on the clean
rewrite.  The snippets are the rule catalog's executable documentation
— each triple is (bad, suppressed, clean) for one rule id.
"""

import textwrap

from holo_tpu.analysis import LintConfig, run_source

OPS = "holo_tpu/ops/_fixture.py"  # tracer (dispatch) scope
DAEMON = "holo_tpu/daemon/_fixture.py"  # concurrency scope
SHARED = "holo_tpu/telemetry/_fixture.py"  # HL204 shared-state scope
OUTSIDE = "holo_tpu/yang/_fixture.py"  # out of every rule scope


def lint(src: str, relpath: str):
    return run_source(textwrap.dedent(src), relpath, LintConfig())


def rules_fired(src: str, relpath: str) -> set[str]:
    return {f.rule for f in lint(src, relpath).findings}


def assert_triple(rule: str, bad: str, suppressed: str, clean: str, path: str):
    """One flagged snippet, one suppressed, one clean — per rule."""
    res = lint(bad, path)
    assert rule in {f.rule for f in res.findings}, (
        f"{rule} did not fire on its bad fixture:\n"
        + "\n".join(f.render() for f in res.findings)
    )
    sup = lint(suppressed, path)
    assert rule not in {f.rule for f in sup.findings}, f"{rule} not suppressed"
    assert rule in {f.rule for f in sup.suppressed}, (
        f"{rule} suppression not recorded"
    )
    cl = lint(clean, path)
    assert rule not in {f.rule for f in cl.findings}, (
        f"{rule} fired on the clean fixture:\n"
        + "\n".join(f.render() for f in cl.findings)
    )


# -- HL101: implicit host sync on device value --------------------------

HL101_BAD = """
    import jax.numpy as jnp
    import numpy as np

    def dispatch(g, mask):
        out = jnp.add(g, mask)
        return np.asarray(out)
"""
HL101_SUPPRESSED = """
    import jax.numpy as jnp
    import numpy as np

    def dispatch(g, mask):
        out = jnp.add(g, mask)
        return np.asarray(out)  # holo-lint: disable=HL101
"""
HL101_CLEAN = """
    import jax.numpy as jnp
    import numpy as np
    from holo_tpu.analysis.runtime import sanctioned_transfer

    def dispatch(g, mask):
        out = jnp.add(g, mask)
        with sanctioned_transfer("fixture.unmarshal"):
            return np.asarray(out)
"""


def test_hl101_host_sync():
    assert_triple("HL101", HL101_BAD, HL101_SUPPRESSED, HL101_CLEAN, OPS)


def test_hl101_item_and_float_forms():
    src = """
        import jax.numpy as jnp

        def peek(x):
            y = jnp.sum(x)
            return float(y)

        def peek2(x):
            y = jnp.sum(x)
            return y.item()
    """
    findings = lint(src, OPS).findings
    assert sum(f.rule == "HL101" for f in findings) == 2


def test_hl101_out_of_scope_module_is_ignored():
    assert rules_fired(HL101_BAD, OUTSIDE) == set()


# -- HL102: Python control flow on traced value -------------------------

HL102_BAD = """
    import jax.numpy as jnp

    def step(x):
        y = jnp.sum(x)
        if y > 0:
            return y
        return y + 1
"""
HL102_SUPPRESSED = """
    import jax.numpy as jnp

    def step(x):
        y = jnp.sum(x)
        if y > 0:  # holo-lint: disable=HL102
            return y
        return y + 1
"""
HL102_CLEAN = """
    import jax.numpy as jnp

    def step(x):
        y = jnp.sum(x)
        if x.shape[0] > 0:  # shape data is static under trace
            return jnp.where(y > 0, y, y + 1)
        return y
"""


def test_hl102_traced_control_flow():
    assert_triple("HL102", HL102_BAD, HL102_SUPPRESSED, HL102_CLEAN, OPS)


def test_hl102_none_checks_are_static():
    src = """
        import jax.numpy as jnp

        def step(mask, x):
            y = jnp.sum(x)
            if mask is not None and mask.shape[0] > 0:
                y = y + 1
            while x.ndim > 2:
                x = x[0]
            return y
    """
    assert "HL102" not in rules_fired(src, OPS)


def test_hl102_profiling_barrier_returns_host_bool():
    """profiling.device_stages is a block_until_ready completion
    barrier returning host metadata: branching on it is host-decidable
    (ISSUE 8 — the per-device device-phase split), taint stops there
    exactly like float()/item()."""
    src = """
        import jax.numpy as jnp

        from holo_tpu.telemetry import profiling

        def step(x):
            out = jnp.cumsum(x)
            if not profiling.device_stages("spf.whatif", out):
                profiling.sync(out)
            return out
    """
    assert "HL102" not in rules_fired(src, OPS)


# -- HL103: jit recompile hazards ---------------------------------------

HL103_BAD = """
    import jax

    def run(xs):
        return jax.jit(lambda v: v + 1)(xs)
"""
HL103_SUPPRESSED = """
    import jax

    def run(xs):
        return jax.jit(lambda v: v + 1)(xs)  # holo-lint: disable=HL103
"""
HL103_CLEAN = """
    import jax

    _STEP = jax.jit(lambda v: v + 1)

    def run(xs):
        return _STEP(xs)
"""


def test_hl103_recompile_hazard():
    assert_triple("HL103", HL103_BAD, HL103_SUPPRESSED, HL103_CLEAN, OPS)


def test_hl103_jit_in_loop():
    src = """
        import jax

        def sweep(batches):
            outs = []
            for b in batches:
                f = jax.jit(lambda v: v * 2)
                outs.append(f(b))
            return outs
    """
    assert "HL103" in rules_fired(src, OPS)


def test_hl103_guarded_lazy_init_is_clean():
    src = """
        import jax

        class Backend:
            def __init__(self):
                self._jit_fn = None

            def compute(self, x):
                if self._jit_fn is None:
                    self._jit_fn = jax.jit(lambda v: v + 1)
                return self._jit_fn(x)
    """
    assert "HL103" not in rules_fired(src, OPS)


# -- HL104: float/dtype parity drift ------------------------------------

HL104_BAD = """
    import jax.numpy as jnp

    def relax(x):
        y = jnp.asarray(x)
        return y / 2
"""
HL104_SUPPRESSED = """
    import jax.numpy as jnp

    def relax(x):
        y = jnp.asarray(x)
        return y / 2  # holo-lint: disable=HL104
"""
HL104_CLEAN = """
    import jax.numpy as jnp

    def relax(x):
        y = jnp.asarray(x)
        return y // 2
"""


def test_hl104_parity_drift():
    assert_triple("HL104", HL104_BAD, HL104_SUPPRESSED, HL104_CLEAN, OPS)


def test_hl104_float_dtype_and_literal():
    src = """
        import jax.numpy as jnp

        def bad_dtype(x):
            return jnp.asarray(x, jnp.float32)

        def bad_literal(x):
            return jnp.full(x.shape, 1.5)
    """
    findings = lint(src, OPS).findings
    assert sum(f.rule == "HL104" for f in findings) == 2


# -- HL105: eager metric computation on dispatch path -------------------

HL105_BAD = """
    import numpy as np
    from holo_tpu import telemetry

    _OCC = telemetry.gauge("holo_fixture_occupancy")

    def marshal(valid):
        _OCC.set(float(np.asarray(valid).mean()))
"""
HL105_SUPPRESSED = """
    import numpy as np
    from holo_tpu import telemetry

    _OCC = telemetry.gauge("holo_fixture_occupancy")

    def marshal(valid):
        _OCC.set(float(np.asarray(valid).mean()))  # holo-lint: disable=HL105
"""
HL105_CLEAN = """
    from holo_tpu import telemetry

    _OCC = telemetry.gauge("holo_fixture_occupancy")

    def marshal(valid, n_valid, n_slots):
        _OCC.set_fn(lambda v=valid: float(v.mean()))  # deferred: scrape time
        _OCC.set(n_valid / n_slots)  # O(1) metadata is fine too
"""


def test_hl105_eager_metric():
    assert_triple("HL105", HL105_BAD, HL105_SUPPRESSED, HL105_CLEAN, OPS)


# -- HL106: swallow-and-continue on dispatch/actor-loop code ------------

HL106_BAD = """
    def dispatch_batch(self, g, masks):
        try:
            return self._jit_batch(g, masks)
        except Exception:
            pass
"""
HL106_SUPPRESSED = """
    def dispatch_batch(self, g, masks):
        try:
            return self._jit_batch(g, masks)
        except Exception:  # holo-lint: disable=HL106
            pass
"""
HL106_CLEAN = """
    import logging

    log = logging.getLogger(__name__)

    def dispatch_batch(self, g, masks):
        try:
            return self._jit_batch(g, masks)
        except Exception:
            log.exception("dispatch failed; falling back")
            return self._oracle(g, masks)
"""


def test_hl106_swallowed_exception():
    assert_triple("HL106", HL106_BAD, HL106_SUPPRESSED, HL106_CLEAN, OPS)


def test_hl106_bare_except_and_tuple_forms():
    src = """
        def pump(self):
            try:
                self.step()
            except:
                pass

        def pump2(self):
            try:
                self.step()
            except (ValueError, Exception):
                ...
    """
    findings = lint(src, DAEMON).findings
    assert sum(f.rule == "HL106" for f in findings) == 2


def test_hl106_narrow_or_handled_is_clean():
    src = """
        import queue

        def pump(self):
            try:
                self.q.put(1, timeout=5)
            except queue.Full:
                pass  # narrow: a deliberate, understood case

        def pump2(self):
            try:
                self.step()
            except Exception:
                self.crashed += 1
    """
    assert "HL106" not in rules_fired(src, DAEMON)


def test_hl106_out_of_scope_module_is_ignored():
    assert rules_fired(HL106_BAD, OUTSIDE) == set()


# -- HL201: attribute mutated outside its owning lock -------------------

HL201_BAD = """
    import threading

    class Shared:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}

        def snapshot(self):
            with self._lock:
                return dict(self._items)

        def poke(self, k, v):
            self._items[k] = v
"""
HL201_SUPPRESSED = """
    import threading

    class Shared:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}

        def snapshot(self):
            with self._lock:
                return dict(self._items)

        def poke(self, k, v):
            self._items[k] = v  # holo-lint: disable=HL201
"""
HL201_CLEAN = """
    import threading

    class Shared:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}

        def snapshot(self):
            with self._lock:
                return dict(self._items)

        def poke(self, k, v):
            with self._lock:
                self._items[k] = v
"""


def test_hl201_unlocked_mutation():
    assert_triple("HL201", HL201_BAD, HL201_SUPPRESSED, HL201_CLEAN, DAEMON)


def test_hl201_init_writes_exempt():
    # __init__ writes before the object is shared: never flagged.
    assert "HL201" not in rules_fired(HL201_CLEAN, DAEMON)


# -- HL202: blocking call while holding a lock --------------------------

HL202_BAD = """
    import threading
    import time

    class Pump:
        def __init__(self):
            self._lock = threading.Lock()

        def run(self, q, item):
            with self._lock:
                q.put(item)
                time.sleep(0.1)
"""
HL202_SUPPRESSED = """
    import threading

    class Pump:
        def __init__(self):
            self._lock = threading.Lock()

        def run(self, q, item):
            with self._lock:
                q.put(item)  # holo-lint: disable=HL202
"""
HL202_CLEAN = """
    import threading

    class Pump:
        def __init__(self):
            self._lock = threading.Lock()
            self._pending = []

        def run(self, q):
            with self._lock:
                batch = list(self._pending)
                self._pending.clear()
            for item in batch:
                q.put(item)
"""


def test_hl202_blocking_under_lock():
    assert_triple("HL202", HL202_BAD, HL202_SUPPRESSED, HL202_CLEAN, DAEMON)


def test_hl202_condition_wait_is_the_correct_pattern():
    src = """
        import threading

        class Waiter:
            def __init__(self):
                self._lock = threading.Lock()
                self._wake = threading.Condition(self._lock)

            def pump(self):
                with self._wake:
                    self._wake.wait(timeout=0.5)
    """
    assert "HL202" not in rules_fired(src, DAEMON)


def test_hl202_nested_locks():
    src = """
        import threading

        class TwoLocks:
            def __init__(self):
                self._lock = threading.Lock()
                self._sub_lock = threading.Lock()

            def both(self):
                with self._lock:
                    with self._sub_lock:
                        return 1
    """
    assert "HL202" in rules_fired(src, DAEMON)


# -- HL203: callback invoked while holding a lock -----------------------

HL203_BAD = """
    import threading

    class Notifier:
        def __init__(self):
            self._lock = threading.Lock()
            self._cbs = []

        def fire(self, msg):
            with self._lock:
                for cb in self._cbs:
                    cb(msg)
"""
HL203_SUPPRESSED = """
    import threading

    class Notifier:
        def __init__(self):
            self._lock = threading.Lock()
            self._cbs = []

        def fire(self, msg):
            with self._lock:
                for cb in self._cbs:
                    cb(msg)  # holo-lint: disable=HL203
"""
HL203_CLEAN = """
    import threading

    class Notifier:
        def __init__(self):
            self._lock = threading.Lock()
            self._cbs = []

        def fire(self, msg):
            with self._lock:
                targets = list(self._cbs)
            for cb in targets:
                cb(msg)
"""


def test_hl203_callback_under_lock():
    assert_triple("HL203", HL203_BAD, HL203_SUPPRESSED, HL203_CLEAN, DAEMON)


# -- HL204: thread-shared container with no lock ------------------------

HL204_BAD = """
    class Bus:
        def __init__(self):
            self._subs = {}

        def add(self, k, v):
            self._subs[k] = v

        def fanout(self, msg):
            return [s for s in self._subs.values() if s]
"""
HL204_SUPPRESSED = """
    class Bus:
        def __init__(self):
            self._subs = {}

        def add(self, k, v):
            self._subs[k] = v  # holo-lint: disable=HL204

        def fanout(self, msg):
            return [s for s in self._subs.values() if s]
"""
HL204_CLEAN = """
    import threading

    class Bus:
        def __init__(self):
            self._subs = {}
            self._lock = threading.Lock()

        def add(self, k, v):
            with self._lock:
                self._subs[k] = v

        def fanout(self, msg):
            with self._lock:
                return [s for s in self._subs.values() if s]
"""


def test_hl204_no_lock_shared_container():
    assert_triple("HL204", HL204_BAD, HL204_SUPPRESSED, HL204_CLEAN, SHARED)


def test_hl204_daemon_actor_classes_out_of_scope():
    # daemon/ providers run under the single-threaded actor model:
    # HL204's scope excludes them by design.
    assert "HL204" not in rules_fired(HL204_BAD, DAEMON)


# -- HL107: host side effect in lax control-flow callable ---------------

HL107_BAD = """
    import jax
    import jax.numpy as jnp

    from holo_tpu import telemetry

    _ROUNDS = telemetry.counter("fixture_rounds_total", "rounds")

    def relax(g, dist):
        def cond(carry):
            d, changed = carry
            return changed

        def body(carry):
            d, _ = carry
            _ROUNDS.labels(site="relax").inc()
            new = jnp.minimum(d, d[g] + 1)
            return new, jnp.any(new != d)

        out, _ = jax.lax.while_loop(cond, body, (dist, jnp.bool_(True)))
        return out
"""
HL107_SUPPRESSED = """
    import jax
    import jax.numpy as jnp

    from holo_tpu import telemetry

    _ROUNDS = telemetry.counter("fixture_rounds_total", "rounds")

    def relax(g, dist):
        def cond(carry):
            d, changed = carry
            return changed

        def body(carry):
            d, _ = carry
            _ROUNDS.labels(site="relax").inc()  # holo-lint: disable=HL107
            new = jnp.minimum(d, d[g] + 1)
            return new, jnp.any(new != d)

        out, _ = jax.lax.while_loop(cond, body, (dist, jnp.bool_(True)))
        return out
"""
HL107_CLEAN = """
    import jax
    import jax.numpy as jnp

    from holo_tpu import telemetry

    _ROUNDS = telemetry.counter("fixture_rounds_total", "rounds")

    def relax(g, dist):
        def cond(carry):
            d, changed, it = carry
            return changed

        def body(carry):
            d, _, it = carry
            new = jnp.minimum(d, d[g] + 1)
            return new, jnp.any(new != d), it + 1

        out, _, rounds = jax.lax.while_loop(
            cond, body, (dist, jnp.bool_(True), 0)
        )
        _ROUNDS.labels(site="relax").inc()  # host side: after the loop
        return out
"""


def test_hl107_loop_host_closure():
    assert_triple(
        "HL107", HL107_BAD, HL107_SUPPRESSED, HL107_CLEAN, OPS
    )


def test_hl107_lambda_and_time_forms():
    src = """
        import time

        import jax
        import jax.numpy as jnp

        def run(x):
            t = jax.lax.fori_loop(
                0, 4, lambda i, c: c + time.perf_counter(), x
            )
            return jax.lax.cond(
                x[0] > 0, lambda: jnp.sum(x), lambda: jnp.zeros(())
            ) + t
    """
    findings = lint(src, OPS).findings
    assert sum(f.rule == "HL107" for f in findings) == 1


def test_hl107_keyword_callable_form():
    src = """
        import jax
        import jax.numpy as jnp

        from holo_tpu import telemetry

        _ROUNDS = telemetry.counter("fixture_rounds_total", "rounds")

        def relax(g, dist):
            def cond(c):
                return c[1]

            def body(c):
                _ROUNDS.inc()
                return jnp.minimum(c[0], c[0][g]), c[1]

            out, _ = jax.lax.while_loop(
                cond_fun=cond, body_fun=body, init_val=(dist, True)
            )
            return out
    """
    assert "HL107" in rules_fired(src, OPS)


def test_hl107_same_named_bodies_resolve_per_scope():
    """Two functions each defining a nested `body` (the codebase's own
    cond/body convention) must resolve independently: the dirty one
    fires, the clean one does not shadow it."""
    src = """
        import jax
        import jax.numpy as jnp

        from holo_tpu import telemetry

        _ROUNDS = telemetry.counter("fixture_rounds_total", "rounds")

        def clean_loop(g, x):
            def body(c):
                return jnp.minimum(c, c[g])

            def cond(c):
                return jnp.any(c > 0)

            return jax.lax.while_loop(cond, body, x)

        def dirty_loop(g, x):
            def body(c):
                _ROUNDS.inc()
                return jnp.minimum(c, c[g])

            def cond(c):
                return jnp.any(c > 0)

            return jax.lax.while_loop(cond, body, x)
    """
    findings = [f for f in lint(src, OPS).findings if f.rule == "HL107"]
    # A module-wide name map resolves BOTH loops' `body` to the last
    # def seen (the dirty one) and flags both call sites.
    assert len(findings) == 1


def test_hl107_bare_import_form():
    src = """
        from jax.lax import while_loop

        import jax.numpy as jnp

        from holo_tpu import telemetry

        _ROUNDS = telemetry.counter("fixture_rounds_total", "rounds")

        def relax(g, dist):
            def cond(c):
                return jnp.any(c > 0)

            def body(c):
                _ROUNDS.inc()
                return jnp.minimum(c, c[g])

            return while_loop(cond, body, dist)
    """
    assert "HL107" in rules_fired(src, OPS)


def test_hl107_is_error_tier():
    """Promoted from warn (PR 7 soak) to error tier: HL107 findings now
    gate tier-1 like every other shipped rule."""
    res = lint(HL107_BAD, OPS)
    tiers = {f.rule: f.severity for f in res.findings}
    assert tiers.get("HL107") == "error"


def test_hl107_out_of_scope_module_is_ignored():
    assert "HL107" not in rules_fired(HL107_BAD, OUTSIDE)


# -- HL108: cross-module device-value host sink (ISSUE 9 satellite) -----

HELPER_PATH = "holo_tpu/telemetry/_helper_fixture.py"
HELPER_SRC = """
    import numpy as np

    def summarize(planes, scale=1):
        # Host sink on a parameter: np.asarray(planes) materializes
        # whatever the caller passed — harmless for host arrays, a
        # hidden device->host transfer for device values.
        return np.asarray(planes).sum() * scale

    def shape_only(planes):
        return planes.shape[0]  # metadata read: not a sink
"""

HL108_BAD = """
    import jax.numpy as jnp

    from holo_tpu.telemetry._helper_fixture import summarize

    def dispatch(g, mask):
        out = jnp.add(g, mask)
        return summarize(out)
"""
HL108_SUPPRESSED = """
    import jax.numpy as jnp

    from holo_tpu.telemetry._helper_fixture import summarize

    def dispatch(g, mask):
        out = jnp.add(g, mask)
        return summarize(out)  # holo-lint: disable=HL108
"""
HL108_CLEAN = """
    import jax.numpy as jnp

    from holo_tpu.analysis.runtime import sanctioned_transfer
    from holo_tpu.telemetry._helper_fixture import summarize

    def dispatch(g, mask):
        out = jnp.add(g, mask)
        with sanctioned_transfer("fixture.unmarshal"):
            return summarize(out)
"""


def lint_pair(caller_src: str, caller_path: str = OPS):
    from holo_tpu.analysis.core import run_sources

    return run_sources(
        [
            (HELPER_PATH, textwrap.dedent(HELPER_SRC)),
            (caller_path, textwrap.dedent(caller_src)),
        ],
        LintConfig(),
    )


def test_hl108_cross_module_sink():
    res = lint_pair(HL108_BAD)
    assert "HL108" in {f.rule for f in res.findings}, [
        f.render() for f in res.findings
    ]
    # The finding anchors at the CALL SITE in the dispatch module.
    f = next(f for f in res.findings if f.rule == "HL108")
    assert f.path == OPS and "summarize" in f.message
    sup = lint_pair(HL108_SUPPRESSED)
    assert "HL108" not in {f.rule for f in sup.findings}
    assert "HL108" in {f.rule for f in sup.suppressed}
    cl = lint_pair(HL108_CLEAN)
    assert "HL108" not in {f.rule for f in cl.findings}, [
        f.render() for f in cl.findings
    ]


def test_hl108_module_attribute_call_form():
    src = """
        import jax.numpy as jnp

        import holo_tpu.telemetry._helper_fixture as helpers

        def dispatch(g, mask):
            out = jnp.add(g, mask)
            return helpers.summarize(out)
    """
    res = lint_pair(src)
    assert "HL108" in {f.rule for f in res.findings}


def test_hl108_keyword_argument_form():
    src = """
        import jax.numpy as jnp

        from holo_tpu.telemetry._helper_fixture import summarize

        def dispatch(g, mask):
            out = jnp.add(g, mask)
            return summarize(planes=out)
    """
    assert "HL108" in {f.rule for f in lint_pair(src).findings}


def test_hl108_host_value_and_non_sink_param_stay_clean():
    src = """
        import numpy as np

        import jax.numpy as jnp

        from holo_tpu.telemetry._helper_fixture import (
            shape_only,
            summarize,
        )

        def dispatch(g, mask):
            out = jnp.add(g, mask)
            host = np.ones(4)
            a = summarize(host)     # host value: no transfer
            b = shape_only(out)     # metadata-only helper: no sink
            # Tainted value on a NON-sinking parameter position only.
            c = summarize(host, scale=2)
            return a + b + c
    """
    res = lint_pair(src)
    assert "HL108" not in {f.rule for f in res.findings}, [
        f.render() for f in res.findings
    ]


def test_hl108_same_module_helper_is_hl101_territory():
    """A sink helper in the SAME module is out of HL108's scope (the
    cross-module rule must not double-report what per-module taint can
    in principle see)."""
    src = """
        import numpy as np

        import jax.numpy as jnp

        def local_summarize(planes):
            return np.asarray(planes).sum()

        def dispatch(g, mask):
            out = jnp.add(g, mask)
            return local_summarize(out)
    """
    res = lint(src, OPS)
    assert "HL108" not in {f.rule for f in res.findings}


def test_hl108_sanctioned_helper_body_not_indexed():
    helper = """
        import numpy as np

        from holo_tpu.analysis.runtime import sanctioned_transfer

        def unmarshal(planes):
            with sanctioned_transfer("fixture.unmarshal"):
                return np.asarray(planes)
    """
    caller = """
        import jax.numpy as jnp

        from holo_tpu.telemetry._helper_fixture import unmarshal

        def dispatch(g, mask):
            out = jnp.add(g, mask)
            return unmarshal(out)
    """
    from holo_tpu.analysis.core import run_sources

    res = run_sources(
        [
            (HELPER_PATH, textwrap.dedent(helper)),
            (OPS, textwrap.dedent(caller)),
        ],
        LintConfig(),
    )
    assert "HL108" not in {f.rule for f in res.findings}


def test_hl108_out_of_scope_caller_is_ignored():
    res = lint_pair(HL108_BAD, caller_path=OUTSIDE)
    assert "HL108" not in {f.rule for f in res.findings}


def test_hl108_is_error_tier():
    res = lint_pair(HL108_BAD)
    tiers = {f.rule: f.severity for f in res.findings}
    assert tiers.get("HL108") == "error"


# -- machinery ----------------------------------------------------------


def test_disable_all_and_previous_line():
    src = """
        import jax.numpy as jnp
        import numpy as np

        def dispatch(g):
            out = jnp.add(g, 1)
            # holo-lint: disable=all
            return np.asarray(out)
    """
    res = lint(src, OPS)
    assert not res.findings and res.suppressed


def test_parse_error_reported_not_raised():
    res = lint("def broken(:\n", OPS)
    assert res.parse_errors and not res.findings


def test_baseline_multiset_semantics():
    from collections import Counter

    from holo_tpu.analysis import compare_to_baseline
    from holo_tpu.analysis.core import Finding

    f = Finding("HL101", "a.py", 3, "fn", "msg")
    g = Finding("HL101", "a.py", 9, "fn", "msg")  # same key, other line
    baseline = Counter({f.key: 1})
    new, unused = compare_to_baseline([f, g], baseline)
    assert len(new) == 1 and not unused  # second duplicate is NEW
    new, unused = compare_to_baseline([], baseline)
    assert not new and unused[f.key] == 1  # stale entry surfaces


# -- HL109: use-after-donate (ISSUE 14) ---------------------------------

HL109_BAD = """
    import jax

    _STEP = jax.jit(lambda g, prev, seeds: g, donate_argnums=(1,))

    def dispatch(g, prev, seeds):
        out = _STEP(g, prev, seeds)
        return out + prev
"""
HL109_SUPPRESSED = """
    import jax

    _STEP = jax.jit(lambda g, prev, seeds: g, donate_argnums=(1,))

    def dispatch(g, prev, seeds):
        out = _STEP(g, prev, seeds)
        return out + prev  # holo-lint: disable=HL109
"""
HL109_CLEAN = """
    import jax

    _STEP = jax.jit(lambda g, prev, seeds: g, donate_argnums=(1,))

    def dispatch(g, prev, seeds):
        out = _STEP(g, prev, seeds)
        return out
"""


def test_hl109_use_after_donate():
    assert_triple("HL109", HL109_BAD, HL109_SUPPRESSED, HL109_CLEAN, OPS)


def test_hl109_retention_form():
    # The `self._prev[k] = prev` retention the DeltaPath handoff bans:
    # the dict would hand a consumed buffer to the NEXT dispatch.
    src = """
        import jax

        _STEP = jax.jit(lambda g, prev, seeds: g, donate_argnums=(1,))

        class Backend:
            def run(self, g, prev, key, seeds):
                out = _STEP(g, prev, seeds)
                self._prev[key] = prev
                return out
    """
    res = lint(src, OPS)
    f = next(f for f in res.findings if f.rule == "HL109")
    assert "retained" in f.message and "Backend.run" in f.context


def test_hl109_donate_argnames_keyword_form():
    src = """
        import jax

        _STEP = jax.jit(lambda g, prev: g, donate_argnames=("prev",))

        def dispatch(g, prev):
            out = _STEP(g, prev=prev)
            return prev
    """
    assert "HL109" in rules_fired(src, OPS)


def test_hl109_factory_local_binding_form():
    # `step = _step_for(k); step(g, prev)` — the per-width jit-cache
    # idiom: the local resolves through the factory's donation.
    src = """
        import jax

        def _step_for(k):
            return jax.jit(lambda g, prev: g, donate_argnums=(1,))

        def dispatch(g, prev):
            step = _step_for(2)
            out = step(g, prev)
            return prev
    """
    assert "HL109" in rules_fired(src, OPS)


def test_hl109_rebind_kills_taint():
    src = """
        import jax

        _STEP = jax.jit(lambda g, prev, seeds: g, donate_argnums=(1,))

        def dispatch(g, prev, seeds):
            out = _STEP(g, prev, seeds)
            prev = out
            return prev
    """
    assert "HL109" not in rules_fired(src, OPS)


def test_hl109_guard_seams_are_exempt():
    # note_donated's own argument read and the consumes_donated window
    # are the shared vocabulary with the runtime guard — never findings.
    src = """
        import jax

        from holo_tpu.analysis.runtime import consumes_donated, note_donated

        _STEP = jax.jit(lambda g, prev: g, donate_argnums=(1,))

        def dispatch(g, prev):
            out = _STEP(g, prev)
            note_donated("fixture.delta", prev)
            with consumes_donated("fixture.redeposit"):
                stash = prev
            return out
    """
    assert "HL109" not in rules_fired(src, OPS)


DONOR_PATH = "holo_tpu/spf/_donor_fixture.py"
DONOR_SRC = """
    import jax

    _STEP = jax.jit(lambda g, prev: g, donate_argnums=(1,))

    def incr_step(g, prev):
        return _STEP(g, prev)
"""


def test_hl109_cross_module_donated_arg():
    # The donation taints THROUGH an imported helper: incr_step's
    # `prev` parameter lands on _STEP's donated position, so calling
    # it consumes the caller's actual argument.
    import textwrap as _tw

    from holo_tpu.analysis.core import run_sources

    caller = """
        from holo_tpu.spf._donor_fixture import incr_step

        def dispatch(g, prev):
            out = incr_step(g, prev)
            return prev
    """
    res = run_sources(
        [
            (DONOR_PATH, _tw.dedent(DONOR_SRC)),
            (OPS, _tw.dedent(caller)),
        ],
        LintConfig(),
    )
    hits = [f for f in res.findings if f.rule == "HL109"]
    assert hits and hits[0].path == OPS, [
        f.render() for f in res.findings
    ]


def test_hl109_out_of_scope_module_is_ignored():
    assert rules_fired(HL109_BAD, OUTSIDE) == set()


def test_hl109_is_error_tier():
    res = lint(HL109_BAD, OPS)
    tiers = {f.rule: f.severity for f in res.findings}
    assert tiers.get("HL109") == "error"


# -- HL110: unconstrained loop carry (ISSUE 14) -------------------------

HL110_BAD = """
    import jax
    import jax.numpy as jnp
    from jax.lax import with_sharding_constraint

    _REPL = None

    def _constrain_replicated(x):
        return with_sharding_constraint(x, _REPL)

    def fixpoint(g, dist):
        dist0 = dist * 2

        def cond(c):
            return c[1]

        def body(c):
            return (c[0], jnp.bool_(False))

        out, _ = jax.lax.while_loop(cond, body, (dist0, jnp.bool_(True)))
        return out
"""
HL110_SUPPRESSED = """
    import jax
    import jax.numpy as jnp
    from jax.lax import with_sharding_constraint

    _REPL = None

    def _constrain_replicated(x):
        return with_sharding_constraint(x, _REPL)

    def fixpoint(g, dist):
        dist0 = dist * 2

        def cond(c):
            return c[1]

        def body(c):
            return (c[0], jnp.bool_(False))

        # holo-lint: disable=HL110
        out, _ = jax.lax.while_loop(cond, body, (dist0, jnp.bool_(True)))
        return out
"""
HL110_CLEAN = """
    import jax
    import jax.numpy as jnp
    from jax.lax import with_sharding_constraint

    _REPL = None

    def _constrain_replicated(x):
        return with_sharding_constraint(x, _REPL)

    def fixpoint(g, dist):
        dist0 = dist * 2

        def cond(c):
            return c[1]

        def body(c):
            return (c[0], jnp.bool_(False))

        out, _ = jax.lax.while_loop(
            cond, body, (_constrain_replicated(dist0), jnp.bool_(True))
        )
        return out
"""


def test_hl110_unconstrained_loop_carry():
    assert_triple("HL110", HL110_BAD, HL110_SUPPRESSED, HL110_CLEAN, OPS)


def test_hl110_fresh_constructors_are_clean_seeds():
    # jnp.zeros/ones/bool_ carries inherit no sharding — no fence
    # needed.  zeros_like is absent from the allowlist on purpose.
    src = """
        import jax
        import jax.numpy as jnp
        from jax.lax import with_sharding_constraint

        def _constrain_replicated(x):
            return with_sharding_constraint(x, None)

        def fixpoint(n):
            def cond(c):
                return c[1]

            def body(c):
                return (c[0], jnp.bool_(False))

            out, _ = jax.lax.while_loop(
                cond, body, (jnp.zeros((4,), jnp.uint32), jnp.bool_(True))
            )
            return out
    """
    assert "HL110" not in rules_fired(src, OPS)


def test_hl110_scan_and_fori_forms():
    src = """
        import jax
        import jax.numpy as jnp
        from jax.lax import with_sharding_constraint

        def _constrain_replicated(x):
            return with_sharding_constraint(x, None)

        def sweep(g, dist):
            carry, _ = jax.lax.scan(lambda c, x: (c, x), dist, g)
            return carry

        def rounds(g, dist):
            return jax.lax.fori_loop(0, 4, lambda i, c: c, dist)
    """
    findings = [f for f in lint(src, OPS).findings if f.rule == "HL110"]
    assert len(findings) == 2, [f.render() for f in findings]


def test_hl110_module_without_fence_is_out_of_scope():
    # No replication fence declared -> the module's carries legitimately
    # ride GSPMD propagation (the gather engines).
    src = """
        import jax
        import jax.numpy as jnp

        def fixpoint(g, dist):
            dist0 = dist * 2

            def cond(c):
                return c[1]

            def body(c):
                return (c[0], jnp.bool_(False))

            out, _ = jax.lax.while_loop(cond, body, (dist0, jnp.bool_(True)))
            return out
    """
    assert "HL110" not in rules_fired(src, OPS)


def test_hl110_imported_fence_with_mesh_jit_closure():
    # Pass-1 resolution: the kernel module imports a fence and is
    # reached from a per-mesh jit builder, so its unfenced carry flags
    # even with no locally-defined fence helper.
    import textwrap as _tw

    from holo_tpu.analysis.core import run_sources

    kern = """
        import jax
        import jax.numpy as jnp

        from holo_tpu.ops.tropical import _constrain_replicated

        def kernel(g, dist):
            dist0 = dist + 1

            def cond(c):
                return c[1]

            def body(c):
                return (c[0], jnp.bool_(False))

            out, _ = jax.lax.while_loop(cond, body, (dist0, jnp.bool_(True)))
            return out
    """
    builder = """
        import jax
        from jax.sharding import NamedSharding

        from holo_tpu.ops._kern_fixture import kernel

        def build(mesh, spec):
            return jax.jit(
                lambda g, d: kernel(g, d),
                out_shardings=NamedSharding(mesh, spec),
            )
    """
    res = run_sources(
        [
            ("holo_tpu/ops/_kern_fixture.py", _tw.dedent(kern)),
            ("holo_tpu/parallel/_mesh_fixture.py", _tw.dedent(builder)),
        ],
        LintConfig(),
    )
    hits = [f for f in res.findings if f.rule == "HL110"]
    assert hits and hits[0].path == "holo_tpu/ops/_kern_fixture.py", [
        f.render() for f in res.findings
    ]


def test_hl110_is_error_tier():
    res = lint(HL110_BAD, OPS)
    tiers = {f.rule: f.severity for f in res.findings}
    assert tiers.get("HL110") == "error"


# -- HL205: cross-thread publication (ISSUE 14) -------------------------

HL205_BAD = """
    import threading

    class Fanout:
        def __init__(self):
            self._lock = threading.Lock()
            self.rendered = None
            self._thread = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            self.rendered = self._render()

        def _render(self):
            return object()

        def snapshot(self):
            return self.rendered
"""
HL205_SUPPRESSED = """
    import threading

    class Fanout:
        def __init__(self):
            self._lock = threading.Lock()
            self.rendered = None
            self._thread = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            self.rendered = self._render()  # holo-lint: disable=HL205

        def _render(self):
            return object()

        def snapshot(self):
            return self.rendered
"""
HL205_CLEAN = """
    import threading

    class Fanout:
        def __init__(self):
            self._lock = threading.Lock()
            self.rendered = None
            self._thread = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            with self._lock:
                self.rendered = self._render()

        def _render(self):
            return object()

        def snapshot(self):
            with self._lock:
                return self.rendered
"""


def test_hl205_cross_thread_publication():
    assert_triple(
        "HL205", HL205_BAD, HL205_SUPPRESSED, HL205_CLEAN, SHARED
    )


def test_hl205_is_error_tier_gated():
    # Promoted after the ISSUE 14/15 soak (HL107 precedent): findings
    # now gate tier-1 like the rest of the lock family.
    from holo_tpu.analysis import gate_findings

    res = lint(HL205_BAD, SHARED)
    f = next(f for f in res.findings if f.rule == "HL205")
    assert f.severity == "error"
    assert f in gate_findings(res.findings)


def test_hl205_approved_seams_are_clean():
    # COW tuple swap (the Ibus discipline) and a constant flag latch
    # are approved publications; a write reached only through the
    # thread path still counts via the self-call closure.
    src = """
        import threading

        class Ticker:
            def __init__(self):
                self.subs = ()
                self._closed = False
                self._thread = threading.Thread(target=self._run)

            def _run(self):
                self._tick()

            def _tick(self):
                self.subs = tuple(list(self.subs))
                self._closed = True

            def read_side(self):
                return self.subs, self._closed
    """
    assert "HL205" not in rules_fired(src, SHARED)


def test_hl205_registry_thread_root_without_thread_ctor():
    # `_worker` is in the thread-root registry: the Thread(target=...)
    # construction may live in a supervisor module the class never
    # sees, so the name alone marks the method thread-side.
    src = """
        class Pipeline:
            def _worker(self):
                self.stats = {"n": 1}

            def snapshot(self):
                return self.stats
    """
    assert "HL205" in rules_fired(src, "holo_tpu/pipeline/_fixture.py")


def test_hl205_out_of_scope_module_is_ignored():
    assert rules_fired(HL205_BAD, OUTSIDE) == set()


def test_soak_tier_is_empty():
    # The severity-tier contract: HL205 finished its soak in ISSUE 16,
    # so no AST rule ships at warn.  The ISSUE 18 jaxpr-audit rules
    # soak their advisory tiers (dtype widening, bucket budget, fence
    # realization) at warn; the donation and host-leak proofs (HL301,
    # HL302) gate at error from birth.  Adding or promoting a soak
    # must edit this test.
    from holo_tpu.analysis import all_rules

    soak = {r.id for r in all_rules() if r.severity == "warn"}
    assert soak == {"HL303", "HL304", "HL305"}
    errors = {r.id for r in all_rules() if r.severity == "error"}
    assert {"HL301", "HL302"} <= errors


# -- suppression audit (ISSUE 14) ---------------------------------------


def test_suppression_audit_flags_stale_sites():
    from holo_tpu.analysis import audit_suppressions

    src = """
        import jax.numpy as jnp

        def ok(x):
            return x + 1  # holo-lint: disable=HL101
    """
    stale = audit_suppressions(lint(src, OPS))
    assert len(stale) == 1 and "HL101" in stale[0], stale


def test_suppression_audit_live_site_not_flagged():
    from holo_tpu.analysis import audit_suppressions

    assert audit_suppressions(lint(HL101_SUPPRESSED, OPS)) == []


def test_suppression_audit_wrong_rule_id_is_stale():
    # Suppressing a DIFFERENT rule than the one firing: the HL102
    # disable does nothing (the HL101 finding still reports) and the
    # audit calls the comment out as rot.
    from holo_tpu.analysis import audit_suppressions

    src = """
        import jax.numpy as jnp
        import numpy as np

        def dispatch(g):
            out = jnp.add(g, 1)
            return np.asarray(out)  # holo-lint: disable=HL102
    """
    res = lint(src, OPS)
    assert "HL101" in {f.rule for f in res.findings}
    stale = audit_suppressions(res)
    assert len(stale) == 1 and "HL102" in stale[0], stale


def test_suppression_audit_disable_all_covered():
    from holo_tpu.analysis import audit_suppressions

    live = """
        import jax.numpy as jnp
        import numpy as np

        def dispatch(g):
            out = jnp.add(g, 1)
            # holo-lint: disable=all
            return np.asarray(out)
    """
    assert audit_suppressions(lint(live, OPS)) == []
    stale = """
        import jax.numpy as jnp

        def ok(x):
            # holo-lint: disable=all
            return x + 1
    """
    out = audit_suppressions(lint(stale, OPS))
    assert len(out) == 1 and "disable=all" in out[0], out


# -- incremental lint cache (ISSUE 14) ----------------------------------

CACHED_BAD_MODULE = """
import jax.numpy as jnp
import numpy as np


def dispatch(g):
    out = jnp.add(g, 1)
    return np.asarray(out)
"""


def _mini_tree(root):
    pkg = root / "holo_tpu" / "ops"
    pkg.mkdir(parents=True)
    mod = pkg / "mod.py"
    mod.write_text(CACHED_BAD_MODULE)
    (root / "holo_tpu" / "clean.py").write_text("X = 1\n")
    return mod


def _views(result):
    return [f.render() for f in result.findings]


def test_lint_cache_replays_byte_identical(tmp_path):
    from holo_tpu.analysis import run_paths_cached

    mod = _mini_tree(tmp_path)
    cache = tmp_path / "cache.json"
    cold = run_paths_cached(
        [tmp_path / "holo_tpu"], tmp_path, cache_path=cache
    )
    assert cold.files_cached == 0 and cold.files_checked == 2
    assert "HL101" in {f.rule for f in cold.findings}
    warm = run_paths_cached(
        [tmp_path / "holo_tpu"], tmp_path, cache_path=cache
    )
    assert warm.files_cached == warm.files_checked == 2
    assert _views(warm) == _views(cold)
    assert warm.rule_seconds == cold.rule_seconds

    # Touch without edit: content hash revalidates, stays cached.
    import os

    st = mod.stat()
    os.utime(mod, ns=(st.st_atime_ns, st.st_mtime_ns + 10_000_000))
    touched = run_paths_cached(
        [tmp_path / "holo_tpu"], tmp_path, cache_path=cache
    )
    assert touched.files_cached == 2


def test_lint_cache_miss_on_edit_rescans_everything(tmp_path):
    from holo_tpu.analysis import run_paths_cached

    mod = _mini_tree(tmp_path)
    cache = tmp_path / "cache.json"
    run_paths_cached([tmp_path / "holo_tpu"], tmp_path, cache_path=cache)
    mod.write_text(CACHED_BAD_MODULE.replace("np.asarray(out)", "out"))
    res = run_paths_cached(
        [tmp_path / "holo_tpu"], tmp_path, cache_path=cache
    )
    assert res.files_cached == 0  # all-or-nothing: full rescan
    assert "HL101" not in {f.rule for f in res.findings}


def test_lint_cache_miss_on_file_set_change(tmp_path):
    from holo_tpu.analysis import run_paths_cached

    _mini_tree(tmp_path)
    cache = tmp_path / "cache.json"
    run_paths_cached([tmp_path / "holo_tpu"], tmp_path, cache_path=cache)
    (tmp_path / "holo_tpu" / "extra.py").write_text("Y = 2\n")
    res = run_paths_cached(
        [tmp_path / "holo_tpu"], tmp_path, cache_path=cache
    )
    assert res.files_cached == 0 and res.files_checked == 3


def test_lint_cache_miss_on_ruleset_change(tmp_path, monkeypatch):
    from holo_tpu.analysis import cache as cache_mod

    _mini_tree(tmp_path)
    cache = tmp_path / "cache.json"
    cache_mod.run_paths_cached(
        [tmp_path / "holo_tpu"], tmp_path, cache_path=cache
    )
    monkeypatch.setattr(
        cache_mod, "ruleset_fingerprint", lambda: "deadbeefdeadbeef"
    )
    res = cache_mod.run_paths_cached(
        [tmp_path / "holo_tpu"], tmp_path, cache_path=cache
    )
    assert res.files_cached == 0  # edited rule set invalidates replay


def test_lint_cache_custom_rule_subsets_bypass_cache(tmp_path):
    # Fixture subsets must never poison the full-registry cache.
    import json

    from holo_tpu.analysis import run_paths_cached
    from holo_tpu.analysis.rules_tracer import RULES as TRACER_RULES

    _mini_tree(tmp_path)
    cache = tmp_path / "cache.json"
    run_paths_cached([tmp_path / "holo_tpu"], tmp_path, cache_path=cache)
    before = json.loads(cache.read_text())
    res = run_paths_cached(
        [tmp_path / "holo_tpu"],
        tmp_path,
        rules=[TRACER_RULES[0]()],
        cache_path=cache,
    )
    assert res.files_cached == 0
    assert json.loads(cache.read_text()) == before


def test_lint_cache_self_check_detects_tampered_replay(tmp_path):
    # The loud-failure mode: a cache whose stored result diverges from
    # a cold scan of the same tree must be reported, not trusted.
    import json

    from holo_tpu.analysis import self_check

    _mini_tree(tmp_path)
    cache = tmp_path / "cache.json"
    assert (
        self_check([tmp_path / "holo_tpu"], tmp_path, cache_path=cache)
        == []
    )
    doc = json.loads(cache.read_text())
    doc["result"]["findings"] = []  # tamper: drop the HL101 finding
    cache.write_text(json.dumps(doc))
    mismatches = self_check(
        [tmp_path / "holo_tpu"], tmp_path, cache_path=cache
    )
    assert mismatches and any("cold scan only" in m for m in mismatches)


# -- seeded mutation proofs (ISSUE 14 acceptance) -----------------------

from pathlib import Path as _Path

_REPO = _Path(__file__).resolve().parent.parent


def test_mutation_dropping_constrain_replicated_caught_by_hl110():
    """Teeth proof: delete the PR-13 GSPMD firewall from a scratch
    copy of ops/tropical.py and HL110 must catch exactly that."""
    path = "holo_tpu/ops/tropical.py"
    src = (_REPO / path).read_text()
    fenced = "cond, body, (_constrain_replicated(aff0), jnp.bool_(True), 0)"
    assert fenced in src, "mutation anchor moved — update this test"
    assert "HL110" not in {
        f.rule for f in run_source(src, path).findings
    }
    mutated = src.replace(
        fenced, "cond, body, (aff0, jnp.bool_(True), 0)"
    )
    res = run_source(mutated, path)
    hits = [f for f in res.findings if f.rule == "HL110"]
    assert hits and any("aff0" in f.message for f in hits), [
        f.render() for f in res.findings
    ]


def test_mutation_rereading_donated_prev_caught_by_hl109():
    """Teeth proof: retain the donated previous tensors after the
    DeltaPath dispatch in a scratch copy of spf/backend.py and HL109
    must catch exactly that."""
    path = "holo_tpu/spf/backend.py"
    src = (_REPO / path).read_text()
    anchor = 'note_donated("spf.one.delta", prev)'
    assert anchor in src, "mutation anchor moved — update this test"
    assert "HL109" not in {
        f.rule for f in run_source(src, path).findings
    }
    mutated = src.replace(
        anchor, anchor + "\n        self._stale_prev = prev"
    )
    res = run_source(mutated, path)
    hits = [f for f in res.findings if f.rule == "HL109"]
    assert hits and any("retained" in f.message for f in hits), [
        f.render() for f in res.findings
    ]


def test_hl109_self_rebind_is_clean():
    # `prev = step(g, prev)` rebinds prev to the FRESH output — the
    # natural incremental-dispatch style must not keep the old taint
    # (the sorted walk visits the Assign before its value Call, so the
    # rebind kill replays after the donation taints).
    src = """
        import jax

        _STEP = jax.jit(lambda g, prev, seeds: g, donate_argnums=(1,))

        def dispatch(g, prev, seeds):
            prev = _STEP(g, prev, seeds)
            use = prev + 1
            return use, prev
    """
    res = lint(src, OPS)
    assert "HL109" not in {f.rule for f in res.findings}, [
        f.render() for f in res.findings
    ]


def test_hl109_tuple_rebind_is_clean():
    src = """
        import jax

        _STEP = jax.jit(lambda g, prev, seeds: (g, g), donate_argnums=(1,))

        def dispatch(g, prev, seeds):
            prev, aux = _STEP(g, prev, seeds)
            return prev + aux
    """
    res = lint(src, OPS)
    assert "HL109" not in {f.rule for f in res.findings}, [
        f.render() for f in res.findings
    ]


def test_donation_guard_env_knob_arms_at_import():
    import os
    import subprocess
    import sys

    code = (
        "from holo_tpu.analysis.runtime import donation_guard_armed;"
        "print(donation_guard_armed())"
    )
    for val, want in (("1", "True"), ("0", "False")):
        out = subprocess.run(
            [sys.executable, "-c", code],
            env={**os.environ, "HOLO_TPU_DONATION_GUARD": val},
            capture_output=True,
            text=True,
            cwd=_REPO,
            timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == want, (val, out.stdout, out.stderr)


def test_cli_self_check_refuses_adhoc_paths():
    # --self-check exercises the default cache file; over an ad-hoc
    # path set it would store that partial file set and force the next
    # gate run cold, so the CLI refuses (usage error, cache untouched).
    import subprocess
    import sys

    cache = _REPO / ".holo_lint_cache.json"
    before = cache.read_bytes() if cache.exists() else None
    out = subprocess.run(
        [
            sys.executable,
            "-m",
            "holo_tpu.tools.cli",
            "lint",
            "--self-check",
            "holo_tpu/ops",
        ],
        cwd=_REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 2 and "--self-check" in out.stderr
    after = cache.read_bytes() if cache.exists() else None
    assert before == after

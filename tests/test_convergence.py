"""Convergence observatory (ISSUE 6): causal event→FIB tracing.

Covers the propagation contract unit-by-unit (origin stamp → ibus
envelope → delivery-hook context → RIB commit), the deterministic
seeded-storm e2e (identical causal timelines across runs; final FIB
bit-identical to a clean scalar run), and the exemplar/flight surfaces
the observatory feeds.
"""

import time

import pytest

from holo_tpu import telemetry
from holo_tpu.telemetry import convergence
from holo_tpu.utils.ibus import Ibus, IbusMsg
from holo_tpu.utils.runtime import Actor, EventLoop, VirtualClock


@pytest.fixture()
def tracker():
    loop_clock = [0.0]
    tr = convergence.configure(256, clock=lambda: loop_clock[0])
    tr._test_clock = loop_clock  # advance by mutating [0]
    yield tr
    convergence.configure(0)


def _conv_hist():
    return telemetry.registry().histogram(
        "holo_convergence_seconds", labelnames=("trigger", "phase")
    )


# ---------------------------------------------------------------- units


def test_begin_activation_current(tracker):
    assert convergence.current() == ()
    eid = convergence.begin("lsa", detail="x")
    with convergence.activation(eid):
        assert convergence.current() == (eid,)
        with convergence.activation((eid, eid + 1)):
            assert convergence.current() == (eid, eid + 1)
        assert convergence.current() == (eid,)
    assert convergence.current() == ()


def test_disarmed_is_noop():
    convergence.configure(0)
    assert convergence.begin("lsa") is None
    assert convergence.current() == ()
    with convergence.activation(None):
        pass
    convergence.observe("spf")
    convergence.fib_commit()
    assert convergence.sweep() == 0


def test_ibus_envelope_captures_active_event(tracker):
    eid = convergence.begin("bfd")
    with convergence.activation(eid):
        msg = IbusMsg("t", "payload")
    assert msg.event_id == (eid,)
    assert IbusMsg("t", "p").event_id is None


def test_delivery_hook_reactivates_context(tracker):
    """ibus publish → subscriber actor handling runs INSIDE the causal
    context the publisher had active (the runtime delivery hook)."""
    loop = EventLoop(clock=VirtualClock())
    bus = Ibus(loop)
    seen = []

    class Sub(Actor):
        name = "sub"

        def handle(self, msg):
            seen.append(convergence.current())

    loop.register(Sub())
    bus.subscribe("topic", "sub")
    eid = convergence.begin("lsa")
    with convergence.activation(eid):
        bus.publish("topic", "hello")
    loop.run_until_idle()
    assert seen == [(eid,)]


def test_marshalled_callback_carries_event_id(tracker):
    from holo_tpu.utils.preempt import _MarshalCall

    eid = convergence.begin("lsa")
    with convergence.activation(eid):
        mc = _MarshalCall(lambda: None, ())
    assert mc.event_id == (eid,)
    assert _MarshalCall(lambda: None, ()).event_id is None


def test_observe_once_per_phase_with_exemplar(tracker):
    before = _conv_hist().labels(trigger="lsa", phase="spf").count
    eid = convergence.begin("lsa")
    tracker._test_clock[0] = 1.5
    convergence.observe("spf", eids=(eid,))
    convergence.observe("spf", eids=(eid,))  # dedup: once per phase
    child = _conv_hist().labels(trigger="lsa", phase="spf")
    assert child.count == before + 1
    # No span active -> the exemplar carries the event id join key.
    ex = child.exemplars()
    assert any(
        ("event_id", str(eid)) in pairs for pairs, _v in ex.values()
    )


def test_fib_commit_closes_event_and_flags_fallback(tracker):
    eid = convergence.begin("lsa")
    with convergence.activation(eid):
        convergence.note_dispatch("spf", "fallback")
        tracker._test_clock[0] = 2.0
        convergence.fib_commit(op="install")
    recs = tracker.timelines()
    assert len(recs) == 1 and recs[0]["outcome"] == "converged"
    assert recs[0]["fallback"] is True
    assert [s for s, _t, _a in recs[0]["timeline"]] == [
        "origin", "dispatch", "fallback",
    ]
    assert tracker.stats()["open"] == 0
    # The total landed under phase="fallback", not "fib".
    assert _conv_hist().labels(trigger="lsa", phase="fallback").count >= 1


def test_rib_chain_event_to_fib(tracker):
    """ibus request → RibManager route_add → kernel install closes the
    event with rib + fib phases observed."""
    from ipaddress import IPv4Address as A
    from ipaddress import IPv4Network as N

    from holo_tpu.routing.rib import MockKernel, RibManager
    from holo_tpu.utils.southbound import Nexthop, Protocol, RouteMsg

    loop = EventLoop(clock=VirtualClock())
    bus = Ibus(loop)
    kernel = MockKernel()
    rib = RibManager(bus, kernel)
    loop.register(rib)
    eid = convergence.begin("lsa")
    with convergence.activation(eid):
        bus.request(
            "routing",
            RouteMsg(
                protocol=Protocol.OSPFV2,
                prefix=N("10.9.0.0/24"),
                distance=110,
                metric=10,
                nexthops=frozenset({Nexthop(addr=A("10.0.0.2"), ifname="e0")}),
            ),
            sender="test",
        )
    loop.run_until_idle()
    assert N("10.9.0.0/24") in kernel.fib
    recs = tracker.timelines()
    assert len(recs) == 1 and recs[0]["outcome"] == "converged"
    steps = [s for s, _t, _a in recs[0]["timeline"]]
    assert "rib" in steps and "fib" in steps


def test_capacity_evicts_oldest_open_event():
    tr = convergence.configure(4, clock=time.monotonic)
    try:
        eids = [convergence.begin("lsa") for _ in range(6)]
        assert tr.stats()["open"] == 4
        outcomes = {r["eid"]: r["outcome"] for r in tr.timelines()}
        assert outcomes == {eids[0]: "evicted", eids[1]: "evicted"}
    finally:
        convergence.configure(0)


def test_isis_spf_delay_fsm_survives_causal_stamp():
    """Regression guard: the causal stamp in IS-IS _schedule_spf must
    ride ALONGSIDE the RFC 8405 delay-FSM transition, not replace it
    (quiet → short-wait on the first IGP event)."""
    from holo_tpu.protocols.isis.instance import IsisInstance

    loop = EventLoop(clock=VirtualClock())
    inst = IsisInstance("is-fsm", b"\x00\x00\x00\x00\x00\x01")
    loop.register(inst)
    assert inst.spf_delay_state == "quiet"
    inst._schedule_spf()
    assert inst.spf_delay_state == "short-wait"
    inst.spf_delay_event("learn")
    assert inst.spf_delay_state == "long-wait"


# ---------------------------------------------------------- storm e2e


def test_storm_deterministic_and_scalar_parity():
    """ISSUE 6 acceptance: two seeded storms produce byte-identical
    causal timelines, and the TPU-backend storm's final FIB is
    bit-identical to a clean scalar-backend run of the same seed."""
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.spf.synth_storm import run_convergence_storm

    kw = dict(n_routers=60, events=40, seed=11)
    r1, d1, net1 = run_convergence_storm(
        spf_backend=TpuSpfBackend(), **kw
    )
    r2, d2, net2 = run_convergence_storm(
        spf_backend=TpuSpfBackend(), **kw
    )
    assert d1 == d2, "same seed must produce identical causal timelines"
    assert r1["triggers"] == r2["triggers"]
    # Clean scalar run: same seed, same events, oracle backend.
    _r3, _d3, net3 = run_convergence_storm(spf_backend=None, **kw)
    assert net1.kernel.fib == net3.kernel.fib, (
        "device-backend storm FIB must be bit-identical to the scalar run"
    )
    assert r1["outcomes"].get("converged", 0) > 0
    # The device backend actually served the SPF-bound triggers.
    assert "device" in r1["triggers"]["lsa"]


def test_storm_loss_shows_in_tail():
    """10% loss defers LSA arrivals by the retransmit penalty: the lsa
    trigger's max latency must exceed the no-loss run's."""
    from holo_tpu.spf.synth_storm import run_convergence_storm

    lossy, _, _ = run_convergence_storm(
        n_routers=60, events=40, seed=11, drop_prob=0.5
    )
    clean, _, _ = run_convergence_storm(
        n_routers=60, events=40, seed=11, drop_prob=0.0
    )
    lm = lossy["triggers"]["lsa"]["all"]["max"]
    cm = clean["triggers"]["lsa"]["all"]["max"]
    assert lm > cm, (lm, cm)


def test_storm_timelines_reach_flight_ring():
    """Completed causal timelines land in the flight-recorder ring (and
    therefore in postmortem bundles)."""
    from holo_tpu.spf.synth_storm import run_convergence_storm
    from holo_tpu.telemetry import flight

    flight.configure(entries=4096)
    try:
        report, _d, _n = run_convergence_storm(
            n_routers=60, events=30, seed=5
        )
        ring = flight.recorder().snapshot_ring()
        conv = [
            e for e in ring if e[0] == "event" and e[1] == "convergence"
        ]
        assert len(conv) >= report["outcomes"].get("converged", 0) > 0
        assert all("trigger" in e[2] and "phases" in e[2] for e in conv)
    finally:
        flight.configure(entries=0)


# ------------------------------------------------------- gNMI surfaces


def test_gnmi_metric_leaf_carries_exemplars():
    """PR 5 carry-over: the gNMI holo-telemetry metric leaves now carry
    the OpenMetrics exemplar span ids Prometheus already renders."""
    from holo_tpu.telemetry.provider import TelemetryStateProvider

    hist = telemetry.histogram(
        "holo_test_exemplar_seconds", "t", ("site",)
    )
    hist.labels(site="x").observe(0.004, exemplar={"span_id": 41})
    state = TelemetryStateProvider().get_state()
    rows = {
        m["name"]: m
        for m in state["holo-telemetry"]["metric"]
    }
    row = rows["holo_test_exemplar_seconds_count{site=x}"]
    assert "span_id=41" in row["exemplars"]
    assert "value=0.004" in row["exemplars"]
    # Non-histogram rows carry no exemplar leaf.
    assert "exemplars" not in rows.get(
        "holo_test_exemplar_seconds_sum{site=x}", {}
    )


def test_gnmi_drop_bursts_recorded_in_flight_ring():
    """PR 5 carry-over: per-subscriber dropped-update bursts land in the
    flight ring with the subscriber ordinal, so a postmortem shows WHO
    was shedding and when."""
    import queue

    from holo_tpu.daemon.gnmi_server import GnmiService
    from holo_tpu.telemetry import flight

    flight.configure(entries=1024)
    try:
        svc = GnmiService(daemon=None)
        q = queue.Queue(maxsize=2)
        svc._add_subscriber(q)
        for _ in range(5):  # 2 delivered, 3 dropped
            svc._fanout("notif")
        ring = flight.recorder().snapshot_ring()
        starts = [
            e for e in ring
            if e[0] == "event" and e[1] == "gnmi-drop-burst-start"
        ]
        assert len(starts) == 1 and starts[0][2]["subscriber"] == 1
        # Draining the queue ends the burst with the dropped count.
        q.get_nowait()
        q.get_nowait()
        svc._fanout("notif")
        ring = flight.recorder().snapshot_ring()
        ends = [
            e for e in ring
            if e[0] == "event" and e[1] == "gnmi-drop-burst"
        ]
        assert len(ends) == 1
        assert ends[0][2]["dropped"] == 3
        assert ends[0][2]["ended"] == "drained"
        # A subscriber dying mid-burst closes its story too.
        q2 = queue.Queue(maxsize=1)
        svc._add_subscriber(q2)
        svc._fanout("a")
        svc._fanout("b")  # q2 full -> burst opens (q drained above)
        svc._remove_subscriber(q2)
        ring = flight.recorder().snapshot_ring()
        disc = [
            e for e in ring
            if e[0] == "event"
            and e[1] == "gnmi-drop-burst"
            and e[2].get("ended") == "disconnect"
        ]
        assert len(disc) == 1 and disc[0][2]["subscriber"] == 2
    finally:
        flight.configure(entries=0)


# ------------------------------------------------------ lint severity


def test_lint_severity_tiers():
    from holo_tpu.analysis import Rule, gate_findings, run_source

    class WarnRule(Rule):
        id = "HL999"
        title = "test warn rule"
        severity = "warn"

        def check(self, mod):
            return [self.finding(mod, mod.tree, "soaking rule hit")]

    class ErrRule(WarnRule):
        id = "HL998"
        severity = "error"

    res = run_source("x = 1\n", "holo_tpu/ops/x.py", rules=[WarnRule(), ErrRule()])
    assert len(res.findings) == 2
    gated = gate_findings(res.findings)
    assert [f.rule for f in gated] == ["HL998"]
    warn = next(f for f in res.findings if f.rule == "HL999")
    assert warn.severity == "warn"
    assert "(warn)" in warn.render()
    assert "severity" not in warn.key  # tier changes never churn keys


def test_lint_baseline_records_severity(tmp_path):
    import json

    from holo_tpu.analysis import Finding, write_baseline

    f = Finding("HL999", "p.py", 1, "<module>", "m", severity="warn")
    write_baseline(tmp_path / "b.json", [f])
    doc = json.loads((tmp_path / "b.json").read_text())
    assert doc["findings"][0]["severity"] == "warn"


def test_list_rules_shows_severity():
    from holo_tpu.analysis import all_rules

    assert all(r.severity in ("error", "warn") for r in all_rules())
    # Every established rule stays on gate duty; the warn tier carries
    # exactly the rules currently soaking toward error tier.  HL107
    # soaked through PR 7 and was promoted in ISSUE 8; HL205 soaked
    # from ISSUE 14 and was promoted in ISSUE 16.  Promote, don't
    # accumulate: ISSUE 18's advisory jaxpr-audit rules (dtype
    # widening, bucket budget, fence realization) are the current
    # soak set; HL301/HL302 landed straight at error tier.
    soaking = {r.id for r in all_rules() if r.severity == "warn"}
    assert soaking == {"HL303", "HL304", "HL305"}
    errors = {r.id for r in all_rules() if r.severity == "error"}
    assert {"HL301", "HL302"} <= errors

"""Decoder fuzzing: every packet decoder must either succeed or raise
DecodeError — never crash with an arbitrary exception.

The reference ships 31 libFuzzer targets over its decoders (SURVEY.md
§4.3); this is the same contract enforced with seeded random + mutation
fuzzing in-process (a libFuzzer/atheris harness can reuse these corpus
builders verbatim).
"""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

import pytest

from holo_tpu.utils.bytesbuf import DecodeError, Reader

ITERATIONS = 300


def corpus():
    """Valid packets of every protocol — mutation seeds."""
    from holo_tpu.protocols import bfd, bgp, igmp, ldp, rip, vrrp
    from holo_tpu.protocols.isis import packet as isis_pkt
    from holo_tpu.protocols.ospf import packet as ospf_pkt

    out = []
    out.append(
        ospf_pkt.Packet(
            A("1.1.1.1"), A("0.0.0.0"),
            ospf_pkt.Hello(A("255.255.255.0"), 10, ospf_pkt.Options.E, 1, 40,
                           A("0.0.0.0"), A("0.0.0.0"), [A("2.2.2.2")]),
        ).encode()
    )
    lsa = ospf_pkt.Lsa(
        1, ospf_pkt.Options.E, ospf_pkt.LsaType.ROUTER, A("1.1.1.1"),
        A("1.1.1.1"), -100,
        ospf_pkt.LsaRouter(links=[
            ospf_pkt.RouterLink(ospf_pkt.RouterLinkType.POINT_TO_POINT,
                                A("2.2.2.2"), A("10.0.0.1"), 10)]),
    )
    lsa.encode()
    out.append(
        ospf_pkt.Packet(A("1.1.1.1"), A("0.0.0.0"),
                        ospf_pkt.LsUpdate([lsa])).encode()
    )
    out.append(
        isis_pkt.HelloP2p(3, b"\x00" * 5 + b"\x01", 9, 1, {
            "area_addresses": [b"\x49\x00\x01"],
            "ip_addresses": [A("10.0.0.1")],
        }).encode()
    )
    ilsp = isis_pkt.Lsp(2, 1200, isis_pkt.LspId(b"\x00" * 5 + b"\x01"), 1,
                        tlvs={"ext_ip_reach": [isis_pkt.ExtIpReach(N("10.0.0.0/24"), 10)]})
    out.append(ilsp.encode())
    out.append(isis_pkt.Snp(2, True, b"\x00" * 5 + b"\x01",
                            [(1200, isis_pkt.LspId(b"\x00" * 5 + b"\x02"), 1, 0xAB)]).encode())
    # Hand-built LSP exercising the narrow (2/128/130), v6 (232/236),
    # hostname (137) and RFC 5120 MT (229/222/237) decode branches.
    # Lifetime 0 skips the checksum so raw TLVs can be spliced freely.
    def tlv(t, value):
        return bytes([t, len(value)]) + value

    mt_tlvs = (
        tlv(2, bytes([0])  # virtual flag
            + bytes([10, 0x80, 0x80, 0x80]) + b"\x00" * 5 + b"\x02\x00")
        + tlv(128, bytes([10, 0x80, 0x80, 0x80, 10, 0, 1, 0,
                          255, 255, 255, 0]))
        + tlv(130, bytes([10 | 0x40, 0x80, 0x80, 0x80, 203, 0, 113, 0,
                          255, 255, 255, 0]))
        + tlv(137, b"rt1")
        + tlv(229, bytes([0x00, 0x00, 0x40, 0x02]))  # MT ids: 0, 2(A)
        + tlv(222, bytes([0x00, 0x02]) + b"\x00" * 5 + b"\x03\x00"
              + bytes([0, 0, 10, 0]))
        + tlv(232, bytes(15) + b"\x01")
        + tlv(236, bytes([0, 0, 0, 10, 0, 16, 0x20, 0x01]))
        + tlv(237, bytes([0x00, 0x02, 0, 0, 0, 10, 0, 16, 0x20, 0x01]))
    )
    body = (
        (0).to_bytes(2, "big")  # lifetime 0: checksum not verified
        + b"\x00" * 5 + b"\x01\x00\x00"  # LSP id
        + (7).to_bytes(4, "big")  # seqno
        + (0).to_bytes(2, "big")  # cksum
        + bytes([0x03])
        + mt_tlvs
    )
    pdu_len = 8 + 2 + len(body)
    out.append(
        bytes([0x83, 27, 1, 0, 20, 1, 0, 0])
        + pdu_len.to_bytes(2, "big")
        + body
    )
    from ipaddress import IPv6Address as A6
    from ipaddress import IPv6Network as N6

    from holo_tpu.protocols.ospf import packet_v3 as v3

    h3 = v3.Packet(
        A("1.1.1.1"), A("0.0.0.0"),
        v3.Hello(1, 1, v3.Options.V6 | v3.Options.E | v3.Options.R,
                 10, 40, A("0.0.0.0"), A("0.0.0.0"), [A("2.2.2.2")]),
    )
    out.append(h3.encode(A6("fe80::1"), A6("ff02::5")))
    l3 = v3.Lsa(1, v3.LsaType.INTRA_AREA_PREFIX, A("0.0.0.1"), A("1.1.1.1"),
                -99, v3.LsaIntraAreaPrefix(
                    ref_type=int(v3.LsaType.ROUTER), ref_lsid=A("0.0.0.0"),
                    ref_adv_rtr=A("1.1.1.1"),
                    prefixes=[(N6("2001:db8:1::/64"), 10)]))
    l3.encode()
    out.append(v3.Packet(A("1.1.1.1"), A("0.0.0.0"), v3.LsUpdate([l3])).encode())
    n3 = v3.Lsa(1, v3.LsaType.NETWORK, A("0.0.0.4"), A("3.3.3.3"), -98,
                v3.LsaNetworkV3(attached=[A("1.1.1.1"), A("3.3.3.3")]))
    n3.encode()
    out.append(v3.Packet(A("3.3.3.3"), A("0.0.0.0"), v3.LsUpdate([n3])).encode())
    t7 = ospf_pkt.Lsa(
        1, ospf_pkt.Options.NP, ospf_pkt.LsaType.NSSA_EXTERNAL,
        A("203.0.113.0"), A("2.2.2.2"), -97,
        ospf_pkt.LsaAsExternal(mask=A("255.255.255.0"), e_bit=True,
                               metric=20, fwd_addr=A("0.0.0.0"), tag=0),
    )
    t7.encode()
    out.append(
        ospf_pkt.Packet(A("2.2.2.2"), A("0.0.0.1"),
                        ospf_pkt.LsUpdate([t7])).encode()
    )
    out.append(bgp.encode_msg(bgp.OpenMsg(65001, 90, A("1.1.1.1"))))
    out.append(bgp.encode_msg(bgp.UpdateMsg(
        nlri=[N("10.0.0.0/8")],
        attrs=bgp.PathAttrs(bgp.Origin.IGP, (65001,), A("10.0.0.1")))))
    out.append(rip.RipPacket(rip.RipCommand.RESPONSE,
                             [rip.Rte(N("10.0.0.0/16"), A("0.0.0.0"), 3)]).encode())
    out.append(bfd.BfdPacket(bfd.BfdState.UP, my_discr=1, your_discr=2).encode())
    out.append(vrrp.VrrpPacket(3, 1, 100, 100, [A("192.0.2.254")]).encode())
    out.append(vrrp.VrrpPacket(2, 1, 100, 1, [A("192.0.2.254")]).encode())
    out.append(igmp.IgmpPacket(igmp.IgmpType.REPORT_V2, 0, A("239.0.0.1")).encode())
    out.append(ldp.LdpMsg(ldp.LdpMsgType.LABEL_MAPPING, A("1.1.1.1"),
                          fec=N("10.0.0.0/16"), label=10001).encode())
    # Full RFC 5036 codec seeds (ldp/packet.py): session messages with
    # capabilities, typed wildcards, status TLVs, auth'd BFD packets.
    from holo_tpu.protocols.ldp import packet as ldp_full

    out.append(
        ldp_full.Pdu(
            A("1.1.1.1"),
            0,
            [
                ldp_full.HelloMsg(
                    msg_id=1,
                    flags=ldp_full.HELLO_GTSM,
                    ipv4_addr=A("1.1.1.1"),
                    cfg_seqno=1,
                ),
                ldp_full.InitMsg(
                    msg_id=2,
                    lsr_id=A("2.2.2.2"),
                    cap_dynamic=True,
                    cap_twcard_fec=True,
                    cap_unrec_notif=True,
                ),
                ldp_full.AddressMsg(
                    msg_id=3, addr_list=[A("10.0.0.1")]
                ),
                ldp_full.LabelMsg(
                    msg_id=4,
                    fec=[
                        ldp_full.FecPrefix(N("10.0.0.0/24")),
                        ldp_full.FecWildcard(
                            typed_af=ldp_full.AF_IPV4
                        ),
                    ],
                    label=16,
                ),
                ldp_full.NotifMsg(
                    msg_id=5,
                    status_code=(
                        ldp_full.StatusCode.SHUTDOWN.encode_status()
                    ),
                ),
            ],
        ).encode()
    )
    out.append(
        bfd.BfdPacket(
            bfd.BfdState.UP,
            my_discr=1,
            your_discr=2,
            auth=bfd.BfdAuth(
                bfd.BfdAuthType.METICULOUS_KEYED_SHA1, key_id=1, seq=7
            ),
        ).encode(auth_key=b"k")
    )
    return out


def decoders():
    from holo_tpu.protocols import bfd, bgp, igmp, ldp, rip, vrrp
    from holo_tpu.protocols.isis import packet as isis_pkt
    from holo_tpu.protocols.ospf import packet as ospf_pkt

    from holo_tpu.protocols.ospf import packet_v3 as v3

    return {
        "ospf_packet": ospf_pkt.Packet.decode,
        "ospf_lsa": lambda b: ospf_pkt.Lsa.decode(Reader(b)),
        "ospfv3_packet": v3.Packet.decode,
        "ospfv3_lsa": lambda b: v3.Lsa.decode(Reader(b)),
        "isis_pdu": isis_pkt.decode_pdu,
        "bgp_msg": bgp.decode_msg,
        "rip": rip.RipPacket.decode,
        "bfd": bfd.BfdPacket.decode,
        "vrrp": vrrp.VrrpPacket.decode,
        "igmp": igmp.IgmpPacket.decode,
        "ldp": ldp.LdpMsg.decode,
        "ldp_pdu": _ldp_pdu_decode,
    }


def _ldp_pdu_decode(data: bytes):
    from holo_tpu.protocols.ldp import packet as ldp_full

    try:
        return ldp_full.Pdu.decode(data)
    except ldp_full.DecodeError as e:
        raise DecodeError(str(e)) from e


#: the seeded chaos plan driving every fuzz stream (ISSUE 9 satellite:
#: FaultPlan is the repo's one source of deterministic randomness — the
#: fuzz targets now draw their corpus mutations from the same per-site
#: streams the chaos harness uses, so a failing iteration replays
#: bit-for-bit from (FUZZ_SEED, target name) alone, and interleaving
#: targets can never perturb each other's sequences)
FUZZ_SEED = 0x5EED


def fuzz_stream(name: str):
    """The per-target deterministic RNG: ``FaultPlan.rng`` keyed by the
    fuzz site, exactly like a dispatch/wire chaos seam."""
    from holo_tpu.resilience.faults import FaultPlan

    return FaultPlan(seed=FUZZ_SEED).rng(f"fuzz:{name}")


def fuzz_cases(name: str, seeds, iterations=ITERATIONS):
    """Deterministic mutation sequence for one decoder target."""
    rng = fuzz_stream(name)
    for _ in range(iterations):
        mode = rng.randrange(3)
        if mode == 0:  # pure random bytes
            yield rng.randbytes(rng.randrange(0, 200))
        elif mode == 1:  # mutate a valid packet
            data = bytearray(rng.choice(seeds))
            for _ in range(rng.randrange(1, 8)):
                if data:
                    data[rng.randrange(len(data))] = rng.randrange(256)
            yield bytes(data)
        else:  # truncate a valid packet
            seed = rng.choice(seeds)
            yield seed[: rng.randrange(0, len(seed) + 1)]


@pytest.mark.parametrize("name", sorted(decoders().keys()))
def test_fuzz_decoder(name):
    decode = decoders()[name]
    seeds = corpus()
    crashes = []
    for i, data in enumerate(fuzz_cases(name, seeds)):
        try:
            decode(data)
        except DecodeError:
            pass
        except Exception as e:  # noqa: BLE001 - the point of the fuzzer
            crashes.append((i, type(e).__name__, str(e)[:80], data.hex()[:60]))
    assert not crashes, crashes[:3]


def test_fuzz_streams_are_plan_deterministic_and_independent():
    """Same (seed, site) -> same byte sequence; different sites ->
    independent streams (the FaultPlan per-site contract the fuzz
    targets now inherit)."""
    seeds = corpus()
    a = list(fuzz_cases("bgp_msg", seeds, iterations=40))
    b = list(fuzz_cases("bgp_msg", seeds, iterations=40))
    assert a == b, "fuzz stream must replay bit-for-bit"
    c = list(fuzz_cases("rip", seeds, iterations=40))
    assert a != c, "per-target streams must be independent"

"""OSPFv3 stepwise conformance: the reference's two recorded cases
(holo-ospf/tests/conformance/ospfv3/packet-lsupd-self-orig{1,2},
described in .../ospfv3/mod.rs:13-50) replayed through the live v3
instance.

Both inject a newer SELF-ORIGINATED Router-LSA at a LAN router (the
recorded input is rt3 on topo2-1's eth-sw1; here the equivalent
three-router LAN) and assert the recorded output plane:

  case 1 (lsa-id 0.0.0.0, newer than the database copy):
    - one LS Update flooding the RECEIVED instance,
    - one LS Update with the re-originated copy (seq = received + 1),
    - every adjacency's retransmission queue length rises to 1.
  case 2 (lsa-id 0.0.0.1, absent from the LSDB):
    - one LS Update flooding the received instance,
    - one LS Update with the same LSA at MaxAge (the flush),
    - the MaxAge copy sits in the LSDB; rxmt queue length is 1.
"""

from ipaddress import IPv4Address as A
from ipaddress import IPv6Address as A6

from holo_tpu.protocols.ospf import packet_v3 as P
from holo_tpu.protocols.ospf.neighbor import NsmState
from holo_tpu.utils.netio import NetRxPacket

from tests.test_ospfv3 import _lan3

# The recorded input's sequence number (0x83215600 as a signed 32-bit
# value) — far newer than the converged instance's own copy.
_RECORDED_SEQ = 0x83215600 - (1 << 32)


def _inject_self_orig(loop, subject, sender, lsid: A):
    """Deliver to ``subject`` an LsUpdate from ``sender`` carrying a
    copy of subject's own Router-LSA with the recorded newer seq-no and
    the case's lsa-id."""
    own = next(
        e.lsa
        for e in subject.lsdb.all()
        if e.lsa.type == P.LsaType.ROUTER
        and e.lsa.adv_rtr == subject.router_id
    )
    bogus = P.Lsa(
        age=1,
        type=P.LsaType.ROUTER,
        lsid=lsid,
        adv_rtr=subject.router_id,
        seq_no=_RECORDED_SEQ,
        body=own.body,
    )
    bogus.encode()
    pkt = P.Packet(
        router_id=sender.router_id,
        area_id=A("0.0.0.0"),
        body=P.LsUpdate([bogus]),
    )
    src = A6(f"fe80::{int(str(sender.router_id).split('.')[0])}")
    dst = A6(f"fe80::{int(str(subject.router_id).split('.')[0])}")
    loop.send(
        subject.name,
        NetRxPacket("e0", src, dst, pkt.encode(src=src, dst=dst)),
    )
    loop.run_until_idle()
    return bogus


def _capture_tx(subject):
    sent = []
    orig = subject.netio

    class _Tap:
        def send(self, ifname, src, dst, data):
            try:
                pkt = P.Packet.decode(data, src=src, dst=dst)
            except Exception:
                pkt = None
            if pkt is not None and pkt.body.TYPE == P.PacketType.LS_UPDATE:
                sent.append(pkt)
            orig.send(ifname, src, dst, data)

    subject.netio = _Tap()
    return sent


def test_v3_self_orig_newer_copy_is_outpaced():
    """packet-lsupd-self-orig1 (mod.rs:13-30)."""
    loop, fabric, routers = _lan3()
    r1, _r2, r3 = routers
    sent = _capture_tx(r3)
    _inject_self_orig(loop, r3, r1, A("0.0.0.0"))

    flooded = [
        (l.lsid, l.seq_no, l.is_maxage)
        for pkt in sent
        for l in pkt.body.lsas
        if l.type == P.LsaType.ROUTER and l.adv_rtr == r3.router_id
    ]
    # Two instances went out: the received one, then the outpacing one.
    seqs = [s for _lsid, s, _m in flooded]
    assert _RECORDED_SEQ in seqs, "received self-orig instance not flooded"
    assert _RECORDED_SEQ + 1 in seqs, "re-originated instance not flooded"
    # The database copy is the re-originated instance.
    cur = r3.lsdb.get(
        P.LsaKey(P.LsaType.ROUTER, A("0.0.0.0"), r3.router_id)
    )
    assert cur is not None and cur.lsa.seq_no == _RECORDED_SEQ + 1
    # Every adjacency's retransmission queue holds it.
    for iface in r3.interfaces.values():
        for nbr in iface.neighbors.values():
            if nbr.state == NsmState.FULL:
                assert len(nbr.ls_rxmt) == 1, (
                    f"rxmt qlen {len(nbr.ls_rxmt)} != 1"
                )


def test_v3_self_orig_unknown_lsid_is_flushed():
    """packet-lsupd-self-orig2 (mod.rs:32-50)."""
    loop, fabric, routers = _lan3()
    r1, _r2, r3 = routers
    sent = _capture_tx(r3)
    _inject_self_orig(loop, r3, r1, A("0.0.0.1"))

    flooded = [
        (l.seq_no, l.is_maxage)
        for pkt in sent
        for l in pkt.body.lsas
        if l.type == P.LsaType.ROUTER
        and l.adv_rtr == r3.router_id
        and l.lsid == A("0.0.0.1")
    ]
    assert (_RECORDED_SEQ, False) in flooded, "received instance not flooded"
    assert any(m for _s, m in flooded), "MaxAge flush not flooded"
    # The LSDB retains the MaxAge copy until the rxmt lists drain.
    cur = r3.lsdb.get(
        P.LsaKey(P.LsaType.ROUTER, A("0.0.0.1"), r3.router_id)
    )
    assert cur is not None and cur.lsa.is_maxage
    for iface in r3.interfaces.values():
        for nbr in iface.neighbors.values():
            if nbr.state == NsmState.FULL:
                assert len(nbr.ls_rxmt) == 1

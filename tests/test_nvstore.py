"""Durable-state store + auth seqno boot seeding (ADVICE round-1 items)."""

from ipaddress import IPv4Address as A

from holo_tpu.protocols.ospf.instance import InstanceConfig, OspfInstance
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.nvstore import NvStore
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def test_nvstore_roundtrip_and_incr(tmp_path):
    p = tmp_path / "nv.json"
    s = NvStore(p)
    assert s.get("x") is None
    s.put("x", {"a": 1})
    assert s.incr("boot") == 1
    assert s.incr("boot") == 2
    # re-open: contents survive
    s2 = NvStore(p)
    assert s2.get("x") == {"a": 1}
    assert s2.incr("boot") == 3


def _mk_instance(nvstore):
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    return OspfInstance(
        name="ospf-a",
        config=InstanceConfig(router_id=A("1.1.1.1")),
        netio=fabric.sender_for("ospf-a"),
        nvstore=nvstore,
    )


def test_crypto_seq_restart_never_reuses_seqnos(tmp_path):
    store = NvStore(tmp_path / "nv.json")
    first = _mk_instance(store)
    # simulate long uptime: exhaust several reservation windows
    for _ in range(3):
        first._crypto_seq = first._crypto_reserved
        first._reserve_seqnos()
    last_sent = first._crypto_seq
    # a "restart" (new instance, same store) must seed strictly above every
    # seqno the previous boot could have used, regardless of uptime
    second = _mk_instance(store)
    assert second._crypto_seq >= last_sent
    assert second._crypto_reserved > second._crypto_seq
    assert store.get("ospf/ospf-a/boot-count") == 2


def test_crypto_seq_zero_without_store():
    assert _mk_instance(None)._crypto_seq == 0


def test_tx_path_extends_reservation_at_window_boundary(tmp_path):
    """Crossing the reserved ceiling on a real transmit must durably extend
    the reservation BEFORE the boundary seqno goes on the wire."""
    from ipaddress import IPv4Network as N

    from holo_tpu.protocols.ospf.instance import IfConfig, IfUpMsg
    from holo_tpu.protocols.ospf.interface import IfType
    from holo_tpu.protocols.ospf.packet import AuthCtx, AuthType, Packet

    store = NvStore(tmp_path / "nv.json")
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    inst = OspfInstance(
        name="r1",
        config=InstanceConfig(router_id=A("1.1.1.1")),
        netio=fabric.sender_for("r1"),
        nvstore=store,
    )
    loop.register(inst)
    auth = AuthCtx(AuthType.CRYPTOGRAPHIC, b"k", key_id=1)
    inst.add_interface(
        "e0",
        IfConfig(if_type=IfType.POINT_TO_POINT, cost=1, auth=auth),
        N("10.0.0.0/30"),
        A("10.0.0.1"),
    )
    fabric.join("l", "r1", "e0", A("10.0.0.1"))
    # Park the counter one below the ceiling; the next hello crosses it.
    inst._crypto_seq = inst._crypto_reserved - 1
    loop.send(inst.name, IfUpMsg("e0"))
    loop.advance(1)  # at least one hello transmits
    sent = [Packet.decode(d, auth=auth) for (_, _, _, d) in fabric.tx_log]
    assert sent, "no packets transmitted"
    top = max(p.auth_seqno for p in sent)
    assert top >= NvStore(tmp_path / "nv.json").get("ospf/r1/seqno-ceiling") - (
        OspfInstance._SEQNO_WINDOW
    ), "reservation not extended"
    # Invariant: every transmitted seqno is strictly below the durable ceiling.
    assert top < store.get("ospf/r1/seqno-ceiling")

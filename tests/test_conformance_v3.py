"""OSPFv3 reference conformance: all 44 recorded routers across the 7
topologies (single/multi-area, stub areas, LAN + p2p + parallel links,
single and dual virtual links) replay bit-identically through OUR v3
codecs + SPF pipeline (tools/conformance_v3.py)."""

from pathlib import Path

import pytest

from holo_tpu.tools.conformance_v3 import V3_DIR, run_all, run_topology

pytestmark = pytest.mark.skipif(
    not V3_DIR.exists(), reason="reference corpus not present"
)


def test_known_topology():
    res = run_topology(V3_DIR / "topo1-1")
    bad = {k: v for k, v in res.items() if v}
    assert not bad, bad


def test_all_routers_conformant():
    res = run_all()
    assert len(res) == 44
    bad = {k: "; ".join(v)[:200] for k, v in res.items() if v}
    assert not bad, f"non-conformant: {bad}"

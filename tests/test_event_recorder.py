"""Event recording + replay: an OSPF convergence run is recorded, then a
fresh instance replays one router's inputs and reaches the same LSDB —
the reference's holo-replay reproduction workflow (SURVEY.md §5)."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.protocols.ospf.instance import (
    IfConfig,
    IfUpMsg,
    InstanceConfig,
    OspfInstance,
)
from holo_tpu.protocols.ospf.interface import IfType
from holo_tpu.utils.event_recorder import EventRecorder, instrument, replay
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def test_record_and_replay_ospf(tmp_path):
    rec_path = tmp_path / "events-r1.jsonl"
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    recorder = EventRecorder(rec_path)
    instrument(loop, recorder, actors={"r1"})

    def rtr(name, rid):
        r = OspfInstance(name=name, config=InstanceConfig(router_id=A(rid)),
                         netio=fabric.sender_for(name))
        loop.register(r)
        return r

    r1, r2 = rtr("r1", "1.1.1.1"), rtr("r2", "2.2.2.2")
    cfg = IfConfig(if_type=IfType.POINT_TO_POINT, cost=4)
    r1.add_interface("e0", cfg, N("10.0.12.0/30"), A("10.0.12.1"))
    r2.add_interface("e0", cfg, N("10.0.12.0/30"), A("10.0.12.2"))
    fabric.join("l", "r1", "e0", A("10.0.12.1"))
    fabric.join("l", "r2", "e0", A("10.0.12.2"))
    loop.send("r1", IfUpMsg("e0"))
    loop.send("r2", IfUpMsg("e0"))
    loop.advance(60)
    recorder.close()
    live_lsdb = sorted(
        (str(k.lsid), e.lsa.seq_no) for k, e in
        list(r1.areas.values())[0].lsdb.entries.items()
    )
    live_routes = dict(r1.routes)
    assert live_routes, "live run produced no routes"

    # Fresh loop, ONE instance, no fabric: replay r1's recorded inputs.
    loop2 = EventLoop(clock=VirtualClock())

    class NullIo:
        def send(self, *a):
            pass

    r1b = OspfInstance(name="r1", config=InstanceConfig(router_id=A("1.1.1.1")),
                       netio=NullIo())
    loop2.register(r1b)
    r1b.add_interface("e0", cfg, N("10.0.12.0/30"), A("10.0.12.1"))
    n = replay(rec_path, loop2)
    assert n > 0
    replayed_lsdb = sorted(
        (str(k.lsid), e.lsa.seq_no) for k, e in
        list(r1b.areas.values())[0].lsdb.entries.items()
    )
    assert replayed_lsdb == live_lsdb
    assert set(r1b.routes) == set(live_routes)

"""Daemon assembly: config-driven instance lifecycle + gRPC northbound.

The capstone test mirrors the reference's full stack (SURVEY.md §3.1-3.3):
configuration commits spawn protocol instances, adjacency forms over the
fabric, SPF runs, and the RIB/kernel gets programmed — all from northbound
transactions, under the virtual clock.
"""

import json
from ipaddress import IPv4Network as N

from holo_tpu.daemon.daemon import Daemon
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock
from holo_tpu.utils.southbound import Protocol


def two_daemon_setup():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="d1")
    d2 = Daemon(loop=loop, netio=fabric, name="d2")
    fabric.join("l12", "d1.ospfv2", "eth0", __import__("ipaddress").ip_address("10.0.12.1"))
    fabric.join("l12", "d2.ospfv2", "eth0", __import__("ipaddress").ip_address("10.0.12.2"))
    return loop, fabric, d1, d2


def configure(d: Daemon, rid: str, addr: str):
    cand = d.candidate()
    cand.set("interfaces/interface[eth0]/enabled", "true")
    cand.set("interfaces/interface[eth0]/address", [addr])
    cand.set("routing/control-plane-protocols/ospfv2/router-id", rid)
    cand.set(
        "routing/control-plane-protocols/ospfv2/area[0.0.0.0]/interface[eth0]/interface-type",
        "point-to-point",
    )
    cand.set(
        "routing/control-plane-protocols/ospfv2/area[0.0.0.0]/interface[eth0]/cost", 7
    )
    d.commit(cand, comment="enable ospf")


def test_config_commit_spawns_ospf_and_converges():
    loop, fabric, d1, d2 = two_daemon_setup()
    configure(d1, "1.1.1.1", "10.0.12.1/30")
    configure(d2, "2.2.2.2", "10.0.12.2/30")
    assert "ospfv2" in d1.routing.instances
    loop.advance(60)

    state = d1.routing.get_state()
    nbrs = state["routing"]["ospfv2"]["neighbors"]
    assert nbrs.get("2.2.2.2", {}).get("state") == "full"
    # Connected prefix: DIRECT owns it; OSPF never installs its own
    # nexthop-less local routes (reference route.rs skips them).
    rib = d1.routing.rib.active_routes()
    assert N("10.0.12.0/30") in rib
    assert rib[N("10.0.12.0/30")].protocol == Protocol.DIRECT
    entries = d1.routing.rib.routes[N("10.0.12.0/30")].entries
    assert Protocol.OSPFV2 not in entries
    # ...but the instance computed it (it is simply local, hence no install).
    inst = d1.routing.instances["ospfv2"]
    assert N("10.0.12.0/30") in inst.routes


def test_static_routes_program_rib():
    loop = EventLoop(clock=VirtualClock())
    d = Daemon(loop=loop, name="s1")
    cand = d.candidate()
    cand.set(
        "routing/control-plane-protocols/static-routes/route[10.9.0.0/16]/next-hop",
        "10.0.0.254",
    )
    d.commit(cand)
    rib = d.routing.rib.active_routes()
    assert N("10.9.0.0/16") in rib
    assert rib[N("10.9.0.0/16")].protocol == Protocol.STATIC


def test_static_route_delete_withdraws():
    loop = EventLoop(clock=VirtualClock())
    d = Daemon(loop=loop, name="s2")
    cand = d.candidate()
    cand.set(
        "routing/control-plane-protocols/static-routes/route[10.9.0.0/16]/next-hop",
        "10.0.0.254",
    )
    d.commit(cand)
    assert N("10.9.0.0/16") in d.routing.rib.active_routes()
    cand2 = d.candidate()
    cand2.delete("routing/control-plane-protocols/static-routes/route[10.9.0.0/16]")
    d.commit(cand2)
    assert N("10.9.0.0/16") not in d.routing.rib.active_routes()
    assert N("10.9.0.0/16") not in d.routing.rib.kernel.fib


def test_ospf_disable_withdraws_routes():
    loop, fabric, d1, d2 = two_daemon_setup()
    configure(d1, "1.1.1.1", "10.0.12.1/30")
    configure(d2, "2.2.2.2", "10.0.12.2/30")
    loop.advance(60)
    assert N("10.0.12.0/30") in d1.routing.instances["ospfv2"].routes
    cand = d1.candidate()
    cand.set("routing/control-plane-protocols/ospfv2/enabled", "false")
    d1.commit(cand)
    assert "ospfv2" not in d1.routing.instances
    # No OSPF contribution remains anywhere in the RIB.
    assert all(
        Protocol.OSPFV2 not in pr.entries
        for pr in d1.routing.rib.routes.values()
    )


def test_tpu_backend_opt_in_convergence():
    """spf-control/backend=tpu: config-driven opt-in to the tensor SPF
    backend, converging end to end (on the virtual CPU mesh here; the
    same path runs on the real chip)."""
    from holo_tpu.spf.backend import TpuSpfBackend

    loop, fabric, d1, d2 = two_daemon_setup()
    for d, rid, addr in [(d1, "1.1.1.1", "10.0.12.1/30"),
                         (d2, "2.2.2.2", "10.0.12.2/30")]:
        cand = d.candidate()
        cand.set("interfaces/interface[eth0]/address", [addr])
        cand.set("routing/control-plane-protocols/ospfv2/router-id", rid)
        cand.set(
            "routing/control-plane-protocols/ospfv2/spf-control/backend", "tpu"
        )
        cand.set(
            "routing/control-plane-protocols/ospfv2/area[0.0.0.0]/interface[eth0]/interface-type",
            "point-to-point",
        )
        d.commit(cand)
    inst = d1.routing.instances["ospfv2"]
    assert isinstance(inst.backend, TpuSpfBackend)
    loop.advance(60)
    state = d1.routing.get_state()
    assert state["routing"]["ospfv2"]["neighbors"]["2.2.2.2"]["state"] == "full"
    rib = d1.routing.rib.active_routes()
    assert N("10.0.12.0/30") in rib
    # the SPF log records the backend that ran
    assert state["routing"]["ospfv2"]["spf-log"][-1]["backend"] == "tpu"


def test_isis_config_driven_convergence():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="i1")
    d2 = Daemon(loop=loop, netio=fabric, name="i2")
    import ipaddress

    fabric.join("l", "i1.isis", "eth0", ipaddress.ip_address("10.0.12.1"))
    fabric.join("l", "i2.isis", "eth0", ipaddress.ip_address("10.0.12.2"))
    for d, sid, addr in [(d1, "0.0.0.0.0.1", "10.0.12.1/30"),
                         (d2, "0.0.0.0.0.2", "10.0.12.2/30")]:
        cand = d.candidate()
        cand.set("interfaces/interface[eth0]/address", [addr])
        cand.set("routing/control-plane-protocols/isis/system-id", sid)
        cand.set("routing/control-plane-protocols/isis/interface[eth0]/metric", 7)
        d.commit(cand)
    loop.advance(30)
    # DIRECT owns the connected prefix; IS-IS computes it but never
    # installs CONNECTED routes (reference route.rs:285-301) — same
    # rule OSPF follows.
    from holo_tpu.utils.southbound import Protocol as P

    entries = d1.routing.rib.routes[N("10.0.12.0/30")].entries
    assert P.ISIS not in entries
    assert d1.routing.rib.active_routes()[N("10.0.12.0/30")].protocol == P.DIRECT
    inst = d1.routing.instances["isis"]
    assert N("10.0.12.0/30") in inst.routes  # computed, just not installed
    assert N("10.0.12.0/30") in inst.connected_prefixes


def test_ospfv3_config_driven_convergence():
    import ipaddress

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="w1")
    d2 = Daemon(loop=loop, netio=fabric, name="w2")
    fabric.join("l", "w1.ospfv3", "eth0", ipaddress.ip_address("fe80::1"))
    fabric.join("l", "w2.ospfv3", "eth0", ipaddress.ip_address("fe80::2"))
    for d, rid, ll, pfx in [
        (d1, "1.1.1.1", "fe80::1/64", "2001:db8:1::1/64"),
        (d2, "2.2.2.2", "fe80::2/64", "2001:db8:2::1/64"),
    ]:
        cand = d.candidate()
        cand.set("interfaces/interface[eth0]/address", [ll, pfx])
        cand.set("routing/control-plane-protocols/ospfv3/router-id", rid)
        cand.set(
            "routing/control-plane-protocols/ospfv3/area[0.0.0.0]/interface[eth0]/cost",
            4,
        )
        d.commit(cand)
    loop.advance(60)
    from ipaddress import IPv6Network as N6

    rib = d1.routing.rib.active_routes()
    assert N6("2001:db8:2::/64") in rib
    assert rib[N6("2001:db8:2::/64")].protocol.value == "ospfv3"


def test_bgp_config_driven_with_policy():
    import ipaddress

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="b1")
    d2 = Daemon(loop=loop, netio=fabric, name="b2")
    fabric.join("l", "b1.bgp", "eth0", ipaddress.ip_address("10.0.0.1"))
    fabric.join("l", "b2.bgp", "eth0", ipaddress.ip_address("10.0.0.2"))

    for d, asn, rid, addr, peer in [
        (d1, 65001, "1.1.1.1", "10.0.0.1/30", "10.0.0.2"),
        (d2, 65002, "2.2.2.2", "10.0.0.2/30", "10.0.0.1"),
    ]:
        cand = d.candidate()
        cand.set("interfaces/interface[eth0]/address", [addr])
        # policy on d2: reject 203.0.113.0/24
        if d is d2:
            cand.set(
                "routing-policy/defined-sets/prefix-set[blocked]/prefix",
                ["203.0.113.0/24"],
            )
            cand.set(
                "routing-policy/policy-definition[edge-in]/statement[drop]/conditions/match-prefix-set",
                "blocked",
            )
            cand.set(
                "routing-policy/policy-definition[edge-in]/statement[drop]/actions/policy-result",
                "reject-route",
            )
            cand.set(
                "routing-policy/policy-definition[edge-in]/statement[ok]/actions/policy-result",
                "accept-route",
            )
        cand.set("routing/control-plane-protocols/bgp/as", asn)
        cand.set("routing/control-plane-protocols/bgp/router-id", rid)
        cand.set(
            f"routing/control-plane-protocols/bgp/neighbor[{peer}]/peer-as",
            65001 if d is d2 else 65002,
        )
        cand.set(
            f"routing/control-plane-protocols/bgp/neighbor[{peer}]/connect-retry-interval",
            2,
        )
        if d is d2:
            cand.set(
                f"routing/control-plane-protocols/bgp/neighbor[{peer}]/import-policy",
                "edge-in",
            )
        d.commit(cand)
    loop.advance(10)
    b1 = d1.routing.instances["bgp"]
    b1.originate(N("198.51.100.0/24"))
    b1.originate(N("203.0.113.0/24"))
    loop.advance(5)
    rib2 = d2.routing.rib.active_routes()
    assert N("198.51.100.0/24") in rib2
    assert rib2[N("198.51.100.0/24")].protocol.value == "bgp"
    assert N("203.0.113.0/24") not in rib2  # blocked by configured policy


def test_grpc_northbound_end_to_end():
    """Drive the daemon purely through the gRPC client."""
    import holo_tpu.daemon.grpc_server as gs

    loop = EventLoop(clock=VirtualClock())
    d = Daemon(loop=loop, name="g1")
    server = d.start_grpc("127.0.0.1:0")
    port = server.add_insecure_port("127.0.0.1:0")  # discover an open port?
    # add_insecure_port(0) on started server returns 0; rebuild instead:
    server.stop(grace=0)
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    server = d.start_grpc(f"127.0.0.1:{port}")
    try:
        cli = gs.NorthboundClient(f"127.0.0.1:{port}")
        caps = cli.Capabilities(gs.pb.CapabilitiesRequest())
        assert "routing" in caps.modules and caps.version

        # Commit via path edits.
        resp = cli.Commit(
            gs.pb.CommitRequest(
                operation=gs.pb.CommitOperation.CHANGE,
                edits=[
                    gs.pb.PathEdit(operation="set",
                                   path="system/hostname", value="tpu-rtr-1"),
                    gs.pb.PathEdit(operation="set",
                                   path="interfaces/interface[lo0]/type",
                                   value="loopback"),
                ],
                comment="via-grpc",
            )
        )
        assert resp.error == "" and resp.transaction_id == 1

        cfg = json.loads(cli.GetConfig(gs.pb.GetConfigRequest()).config_json)
        assert cfg["system"]["hostname"] == "tpu-rtr-1"

        state = json.loads(cli.GetState(gs.pb.GetStateRequest()).state_json)
        assert state["system"]["hostname"] == "tpu-rtr-1"

        txns = cli.ListTransactions(gs.pb.ListTransactionsRequest())
        assert [t.comment for t in txns.transactions] == ["via-grpc"]

        # Validation failure surfaces as error, nothing committed.
        bad = cli.Commit(
            gs.pb.CommitRequest(
                operation=gs.pb.CommitOperation.CHANGE,
                edits=[gs.pb.PathEdit(operation="set",
                                      path="interfaces/interface[lo0]/mtu",
                                      value="999999")],
            )
        )
        assert bad.error != "" and bad.transaction_id == 0

        # Rollback-style: GetTransaction returns the recorded config.
        txn = cli.GetTransaction(gs.pb.GetTransactionRequest(id=1))
        assert "tpu-rtr-1" in txn.config_json
    finally:
        server.stop(grace=0)


def test_ldp_config_driven_session_and_lib():
    """LDP lifecycle from config: two daemons discover each other, reach
    OPERATIONAL, exchange labels for their connected FECs, and the
    label-distribution-control knob is consumed (mode change restarts)."""
    import ipaddress

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="m1")
    d2 = Daemon(loop=loop, netio=fabric, name="m2")
    fabric.join("l", "m1.ldp", "eth0", ipaddress.ip_address("10.0.12.1"))
    fabric.join("l", "m2.ldp", "eth0", ipaddress.ip_address("10.0.12.2"))
    for d, lsr in [(d1, "1.1.1.1"), (d2, "2.2.2.2")]:
        cand = d.candidate()
        cand.set("interfaces/interface[eth0]/address",
                 [f"10.0.12.{lsr[0]}/30"])
        cand.set("routing/control-plane-protocols/ldp/lsr-id", lsr)
        cand.set(
            "routing/control-plane-protocols/ldp/interface[eth0]/hello-interval",
            5,
        )
        d.commit(cand)
    loop.advance(20)
    ldp1 = d1.routing.instances["ldp"]
    ldp2 = d2.routing.instances["ldp"]
    from holo_tpu.protocols.ldp import NbrState

    assert ldp1.neighbors[ipaddress.ip_address("2.2.2.2")].state == NbrState.OPERATIONAL
    # Connected networks became egress FECs and labels flowed.
    lib = ldp1.lib()[N("10.0.12.0/30")]
    assert lib["egress"] and "2.2.2.2" in lib["remote"]
    # Operational state surfaces the LIB.
    state = d1.routing.get_state()
    assert state["routing"]["ldp"]["control-mode"] == "independent"
    assert "10.0.12.0/30" in state["routing"]["ldp"]["lib"]
    # Mode flip restarts the LSR with ordered control.
    cand = d1.candidate()
    cand.set("routing/control-plane-protocols/ldp/label-distribution-control",
             "ordered")
    d1.commit(cand)
    loop.advance(20)
    assert d1.routing.instances["ldp"].control_mode == "ordered"
    assert d1.routing.instances["ldp"] is not ldp1  # new incarnation
    # Disable tears down.
    cand = d1.candidate()
    cand.set("routing/control-plane-protocols/ldp/enabled", False)
    d1.commit(cand)
    assert "ldp" not in d1.routing.instances


def test_grpc_tls(tmp_path):
    """gRPC northbound over TLS (holo-daemon grpc.rs TLS option): a
    self-signed server cert; the client trusts it as root CA."""
    import datetime

    pytest.importorskip(
        "cryptography", reason="self-signed cert generation needs pyca"
    )
    import grpc as _grpc
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "localhost")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=1))
        .not_valid_after(now + datetime.timedelta(hours=1))
        .add_extension(
            x509.SubjectAlternativeName([x509.DNSName("localhost")]),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    cert_pem = tmp_path / "cert.pem"
    key_pem = tmp_path / "key.pem"
    cert_pem.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_pem.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        )
    )

    from holo_tpu.daemon import grpc_server as gs
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    loop = EventLoop(clock=VirtualClock())
    d = Daemon(loop=loop, name="tls1")
    d.config.grpc.tls_cert = str(cert_pem)
    d.config.grpc.tls_key = str(key_pem)
    server = d.start_grpc("localhost:0")
    port = server._bound_port
    assert port
    creds = _grpc.ssl_channel_credentials(
        root_certificates=cert_pem.read_bytes()
    )
    channel = _grpc.secure_channel(f"localhost:{port}", creds)
    pb = gs.pb
    resp = channel.unary_unary(
        "/holo_tpu.Northbound/Capabilities",
        request_serializer=pb.CapabilitiesRequest.SerializeToString,
        response_deserializer=pb.CapabilitiesResponse.FromString,
    )(pb.CapabilitiesRequest(), timeout=10)
    assert resp.modules
    channel.close()
    server.stop(None)


def test_grpc_get_xml_and_lyb_encodings():
    """GetConfig/GetState honor the request's DataEncoding (reference
    client parity: JSON default, YANG-XML, compact binary)."""
    import base64
    import socket
    from xml.etree import ElementTree as ET

    import holo_tpu.daemon.grpc_server as gs
    from holo_tpu.yang.serde import from_lyb, from_xml

    loop = EventLoop(clock=VirtualClock())
    d = Daemon(loop=loop, name="enc1")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    server = d.start_grpc(f"127.0.0.1:{port}")
    try:
        cli = gs.NorthboundClient(f"127.0.0.1:{port}")
        cli.Commit(
            gs.pb.CommitRequest(
                operation=gs.pb.CommitOperation.CHANGE,
                edits=[
                    gs.pb.PathEdit(operation="set",
                                   path="system/hostname", value="xml-rtr"),
                ],
                comment="enc",
            )
        )
        # XML round-trips to the same content as JSON — including a
        # keyed list whose key would not be a legal element name
        # (schema-aware expansion re-injects the key leaf).
        cli.Commit(
            gs.pb.CommitRequest(
                operation=gs.pb.CommitOperation.CHANGE,
                edits=[
                    gs.pb.PathEdit(
                        operation="set",
                        path="routing/control-plane-protocols/"
                             "static-routes/route[10.99.0.0/16]/next-hop",
                        value="10.0.0.2",
                    ),
                ],
                comment="enc2",
            )
        )
        xml = cli.GetConfig(
            gs.pb.GetConfigRequest(encoding=gs.pb.XML)
        ).config_json
        root = ET.fromstring(xml)
        assert root.tag == "config"
        parsed = from_xml(xml)
        assert parsed["system"]["hostname"] == "xml-rtr"
        route = parsed["routing"]["control-plane-protocols"][
            "static-routes"]["route"]
        route = route[0] if isinstance(route, list) else route
        assert route["prefix"] == "10.99.0.0/16"
        assert route["next-hop"] == "10.0.0.2"
        # LYB-lite round-trips bit-exactly.
        b64 = cli.GetConfig(
            gs.pb.GetConfigRequest(encoding=gs.pb.LYB)
        ).config_json
        tree = from_lyb(base64.b64decode(b64))
        assert tree["system"]["hostname"] == "xml-rtr"
        # JSON behavior is unchanged.
        cfg = json.loads(cli.GetConfig(gs.pb.GetConfigRequest()).config_json)
        assert cfg["system"]["hostname"] == "xml-rtr"
        # State XML parses and carries the routing containers.
        sxml = cli.GetState(
            gs.pb.GetStateRequest(encoding=gs.pb.XML)
        ).state_json
        assert ET.fromstring(sxml).tag == "state"
    finally:
        server.stop(grace=0)


def test_yang_modeled_state_served_through_daemon():
    """VERDICT §5 observability: GetState serves the standard
    module-qualified ietf-ospf / ietf-isis operational trees (the same
    renderers the conformance harnesses diff), not just ad-hoc dicts."""
    loop, fabric, d1, d2 = two_daemon_setup()
    configure(d1, "1.1.1.1", "10.0.12.1/30")
    configure(d2, "2.2.2.2", "10.0.12.2/30")
    loop.advance(60)
    state = d1.northbound.get_state(None)
    ospf = state["routing"]["ietf-ospf:ospf"]
    # Standard tree shape with live content.
    area = ospf["areas"]["area"][0]
    nbr = area["interfaces"]["interface"][0]["neighbors"]["neighbor"][0]
    assert nbr["neighbor-router-id"] == "2.2.2.2"
    assert nbr["state"] == "full"
    assert ospf["local-rib"]["route"][0]["prefix"] == "10.0.12.0/30"
    assert ospf["spf-control"]["ietf-spf-delay"]["current-state"]

    # IS-IS likewise once configured.
    import ipaddress

    fabric.join("li", "d1.isis", "eth0",
                ipaddress.ip_address("10.0.12.1"))
    fabric.join("li", "d2.isis", "eth0",
                ipaddress.ip_address("10.0.12.2"))
    for d, sid in ((d1, "0000.0000.0001"), (d2, "0000.0000.0002")):
        cand = d.candidate()
        cand.set("routing/control-plane-protocols/isis/system-id", sid)
        cand.set(
            "routing/control-plane-protocols/isis/interface[eth0]/metric", 7
        )
        d.commit(cand)
    loop.advance(60)
    isis = d1.northbound.get_state(None)["routing"]["ietf-isis:isis"]
    levels = isis["database"]["levels"]
    assert levels and levels[0]["holo-isis:lsp-count"] >= 2
    adj = isis["interfaces"]["interface"][0]["adjacencies"]["adjacency"][0]
    assert adj["neighbor-sysid"] == "0000.0000.0002"
    assert adj["state"] == "up"


def test_logging_config_styles_and_subsystems(tmp_path):
    """[logging]: styles, file sink, per-subsystem level overrides
    (reference main.rs:59-146 tracing configuration)."""
    import logging as pylog

    from holo_tpu.daemon.config import DaemonConfig
    from holo_tpu.daemon.daemon import setup_logging

    toml = tmp_path / "holod.toml"
    logfile = tmp_path / "holo.log"
    toml.write_text(
        f"""
[logging]
level = "warning"
style = "json"
file = "{logfile}"

[logging.subsystems]
ospf = "debug"
providers = "error"
"""
    )
    cfg = DaemonConfig.load(toml)
    assert cfg.logging.subsystems == {"ospf": "debug", "providers": "error"}
    old_handlers = pylog.getLogger().handlers[:]
    old_level = pylog.getLogger().level
    try:
        setup_logging(cfg)
        assert pylog.getLogger().level == pylog.WARNING
        assert pylog.getLogger("holo_tpu.ospf").level == pylog.DEBUG
        assert pylog.getLogger("holo_tpu.providers").level == pylog.ERROR
        pylog.getLogger("holo_tpu.ospf").debug("subsystem-trace-line")
        for h in pylog.getLogger().handlers:
            h.flush()
        line = logfile.read_text().strip().splitlines()[-1]
        rec = json.loads(line)  # json style emits one object per line
        assert rec["level"] == "debug"
        assert rec["target"] == "holo_tpu.ospf"
        assert rec["message"] == "subsystem-trace-line"
        # Root level accepts the same vocabulary as the subsystems:
        # "trace" is the reference's most-verbose name, not a typo.
        cfg.logging.level = "trace"
        setup_logging(cfg)
        assert pylog.getLogger().level == pylog.DEBUG
    finally:
        for h in pylog.getLogger().handlers:
            if h not in old_handlers:
                h.close()
        pylog.getLogger().handlers[:] = old_handlers
        pylog.getLogger().setLevel(old_level)
        pylog.getLogger("holo_tpu.ospf").setLevel(pylog.NOTSET)
        pylog.getLogger("holo_tpu.providers").setLevel(pylog.NOTSET)


def test_runtime_introspection_state():
    """The scheduler introspection plane (tokio-console analog,
    reference main.rs:115-133): per-actor inbox depth / delivered
    counters / crash flags through GetState."""
    from holo_tpu.daemon.config import DaemonConfig
    from holo_tpu.daemon.daemon import Daemon

    d = Daemon(config=DaemonConfig.load(None))
    cand = d.candidate()
    cand.set("system/hostname", "rt-probe")
    d.commit(cand)
    state = d.northbound.get_state("holo-runtime")
    rt = state["holo-runtime"]["main-loop"]
    actors = rt["actors"]
    # The five base providers live on the main loop and have processed
    # at least the commit fan-out.
    names = set(actors)
    assert any("system" in n for n in names), names
    assert any("routing" in n for n in names), names
    assert all(a["inbox-depth"] == 0 for a in actors.values())
    assert not any(a["crashed"] for a in actors.values())
    assert rt["timers-armed"] >= 0
    # Scoped GetState for another subtree must not include the runtime.
    assert "holo-runtime" not in d.northbound.get_state("routing")


def test_rip_config_driven_convergence():
    """Config-driven RIPv2: daemon spawns the instance, interfaces join
    from the interface table, learned routes land in the RIB (connected
    prefixes stay with DIRECT — reference never installs them)."""
    import ipaddress

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="r1")
    d2 = Daemon(loop=loop, netio=fabric, name="r2")
    fabric.join("l", "r1.ripv2", "eth0", ipaddress.ip_address("10.0.12.1"))
    fabric.join("l", "r2.ripv2", "eth0", ipaddress.ip_address("10.0.12.2"))
    for d, addr, stub in [
        (d1, "10.0.12.1/30", "10.99.1.0/24"),
        (d2, "10.0.12.2/30", "10.99.2.0/24"),
    ]:
        cand = d.candidate()
        cand.set("interfaces/interface[eth0]/address", [addr])
        cand.set("routing/control-plane-protocols/ripv2/interface[eth0]/cost", 1)
        cand.set(
            f"routing/control-plane-protocols/static-routes/route[{stub}]/next-hop",
            addr.split("/")[0],
        )
        d.commit(cand)
    assert "ripv2" in d1.routing.instances
    loop.advance(90)
    from holo_tpu.utils.southbound import Protocol as P

    # d1 learned d2's connected prefix... no — connected isn't advertised
    # beyond the shared link; RIP advertises its route table: d2's
    # connected 10.0.12.0/30 is suppressed on d1 (already DIRECT) but the
    # instance-level learning works both ways.  Assert the RIP instances
    # exchanged and hold each other as neighbors.
    i1 = d1.routing.instances["ripv2"]
    assert any(str(a) == "10.0.12.2" for a in i1.neighbors)
    # connected prefix: DIRECT owns it, RIPV2 never installs its own.
    rib = d1.routing.rib.active_routes()
    assert rib[N("10.0.12.0/30")].protocol == P.DIRECT
    entries = d1.routing.rib.routes[N("10.0.12.0/30")].entries
    assert P.RIPV2 not in entries
    state = d1.routing.get_state()
    assert "10.0.12.0/30" in state["routing"]["ripv2"]["routes"]
    # Disable: instance torn down, neighbors gone from state.
    cand = d1.candidate()
    cand.set("routing/control-plane-protocols/ripv2/enabled", "false")
    d1.commit(cand)
    assert "ripv2" not in d1.routing.instances


def test_igmp_config_driven_querier():
    """Config-driven IGMP: daemon spawns the querier, a membership
    report populates group state."""
    import ipaddress

    from holo_tpu.protocols.igmp import IgmpPacket

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="q1")
    fabric.join("lan", "q1.igmp", "eth0", ipaddress.ip_address("10.0.1.1"))
    host = fabric.sender_for("host")
    fabric.join("lan", "host", "e0", ipaddress.ip_address("10.0.1.50"))
    cand = d1.candidate()
    cand.set("interfaces/interface[eth0]/address", ["10.0.1.1/24"])
    cand.set(
        "routing/control-plane-protocols/igmp/interface[eth0]/version", 2
    )
    d1.commit(cand)
    assert "igmp" in d1.routing.instances
    loop.advance(5)
    # Host joins 239.1.1.1 (v2 membership report).
    report = IgmpPacket(
        type=0x16, max_resp=0, group=ipaddress.ip_address("239.1.1.1")
    ).encode()
    host.send("e0", ipaddress.ip_address("10.0.1.50"),
              ipaddress.ip_address("239.1.1.1"), report)
    loop.advance(2)
    inst = d1.routing.instances["igmp"]
    groups = inst.interfaces["eth0"].groups
    assert ipaddress.ip_address("239.1.1.1") in groups
    state = d1.routing.get_state()
    assert "239.1.1.1" in state["routing"]["igmp"]["interfaces"]["eth0"]["groups"]


def test_isis_level_all_config_driven():
    """level=level-all spawns the L1/L2 node (both instances on one
    loop); adjacency forms at both levels and level reconfiguration
    restarts the incarnation."""
    import ipaddress

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="m1")
    d2 = Daemon(loop=loop, netio=fabric, name="m2")
    fabric.join("l", "m1.isis", "eth0", ipaddress.ip_address("10.0.12.1"))
    fabric.join("l", "m2.isis", "eth0", ipaddress.ip_address("10.0.12.2"))
    for d, sid, addr in [(d1, "0.0.0.0.0.1", "10.0.12.1/30"),
                         (d2, "0.0.0.0.0.2", "10.0.12.2/30")]:
        cand = d.candidate()
        cand.set("interfaces/interface[eth0]/address", [addr])
        cand.set("routing/control-plane-protocols/isis/system-id", sid)
        cand.set("routing/control-plane-protocols/isis/level", "level-all")
        cand.set("routing/control-plane-protocols/isis/interface[eth0]/metric", 5)
        d.commit(cand)
    node = d1.routing.instances["isis"]
    assert hasattr(node, "instances") and len(list(node.instances())) == 2
    loop.advance(30)
    for inst in node.instances():
        ups = [a for i in inst.interfaces.values() for a in i.up_adjacencies()]
        assert ups, f"L{inst.level} adjacency did not form"
    state = d1.routing.get_state()
    assert state["routing"]["isis"]["spf-run-count"] >= 1
    # Level change restarts the incarnation as a single-level instance.
    cand = d1.candidate()
    cand.set("routing/control-plane-protocols/isis/level", "level-2")
    d1.commit(cand)
    inst2 = d1.routing.instances["isis"]
    assert not hasattr(inst2, "instances")
    assert inst2.level == 2 and inst2.level_name == "level-2"


def test_yang_notifications_reach_daemon_listeners():
    """Protocol YANG notifications (reference notification.rs) flow from
    config-spawned instances through the daemon's fan-out, where every
    management surface's Subscribe stream taps in."""
    loop, fabric, d1, d2 = two_daemon_setup()
    seen = []
    d1.add_notification_listener(seen.append)
    configure(d1, "1.1.1.1", "10.0.12.1/30")
    configure(d2, "2.2.2.2", "10.0.12.2/30")
    loop.advance(60)
    kinds = {k for n in seen for k in n}
    assert "ietf-ospf:nbr-state-change" in kinds, kinds
    assert "ietf-ospf:if-state-change" in kinds, kinds
    full = [
        n["ietf-ospf:nbr-state-change"]
        for n in seen
        if n.get("ietf-ospf:nbr-state-change", {}).get("state") == "full"
    ]
    assert full and full[-1]["neighbor-router-id"] == "2.2.2.2"
    assert full[-1]["routing-protocol-name"].endswith("ospfv2")


def test_grpc_subscribe_streams_protocol_notifications():
    """gRPC Subscribe delivers protocol YANG notifications with the
    notification's qualified name as the topic (filterable)."""
    import socket as _socket
    import threading

    import holo_tpu.daemon.grpc_server as gs

    loop = EventLoop(clock=VirtualClock())
    d = Daemon(loop=loop, name="gsub1")
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    server = d.start_grpc(f"127.0.0.1:{port}")
    try:
        cli = gs.NorthboundClient(f"127.0.0.1:{port}")
        got = []
        ready = threading.Event()

        def _consume():
            ready.set()
            for note in cli.Subscribe(
                gs.pb.SubscribeRequest(
                    topics=["ietf-ospf:if-state-change"]
                )
            ):
                got.append(note)
                break

        t = threading.Thread(target=_consume, daemon=True)
        t.start()
        ready.wait(5)
        import time as _time

        _time.sleep(0.3)  # let the stream register its queue
        # Emit straight through the daemon dispatch (the same path the
        # marshalled instance callback uses).
        d._dispatch_yang_notification(
            {"ietf-ospf:nbr-state-change": {"state": "init"}}  # filtered
        )
        d._dispatch_yang_notification(
            {"ietf-ospf:if-state-change": {"state": "dr",
                                           "interface": {"interface": "e0"}}}
        )
        t.join(10)
        assert got, "Subscribe stream delivered nothing"
        assert got[0].topic == "ietf-ospf:if-state-change"
        assert json.loads(got[0].payload_json)["state"] == "dr"
    finally:
        server.stop(grace=0)


def test_isis_level_all_notifications_use_instance_name():
    """A level-all node's notifications name the configured protocol
    instance, not its internal per-level actors, and flow through the
    daemon fan-out like any single-level instance's."""
    import ipaddress

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="n1")
    d2 = Daemon(loop=loop, netio=fabric, name="n2")
    seen = []
    d1.add_notification_listener(seen.append)
    fabric.join("l", "n1.isis", "eth0", ipaddress.ip_address("10.0.12.1"))
    fabric.join("l", "n2.isis", "eth0", ipaddress.ip_address("10.0.12.2"))
    for d, sid, addr in [(d1, "0.0.0.0.0.1", "10.0.12.1/30"),
                         (d2, "0.0.0.0.0.2", "10.0.12.2/30")]:
        cand = d.candidate()
        cand.set("interfaces/interface[eth0]/address", [addr])
        cand.set("routing/control-plane-protocols/isis/system-id", sid)
        cand.set("routing/control-plane-protocols/isis/level", "level-all")
        cand.set("routing/control-plane-protocols/isis/interface[eth0]/metric", 5)
        d.commit(cand)
    loop.advance(30)
    adj = [n["ietf-isis:adjacency-state-change"] for n in seen
           if "ietf-isis:adjacency-state-change" in n]
    ups = [b for b in adj if b["state"] == "up"]
    assert ups, seen
    names = {b["routing-protocol-name"] for b in ups}
    assert names == {"n1.isis"}, names  # node name, no -l1/-l2 suffix
    assert {b["isis-level"] for b in ups} <= {"level-1", "level-2"}


def test_ospf_cost_live_reconfig():
    """A cost change on a RUNNING interface re-originates the router
    LSA and reconverges the neighbor (reference InterfaceCostUpdate) —
    v2 and v3."""
    import ipaddress

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="c1")
    d2 = Daemon(loop=loop, netio=fabric, name="c2")
    fabric.join("l4", "c1.ospfv2", "eth0", ipaddress.ip_address("10.0.70.1"))
    fabric.join("l4", "c2.ospfv2", "eth0", ipaddress.ip_address("10.0.70.2"))
    fabric.join("l6", "c1.ospfv3", "eth1", ipaddress.ip_address("fe80::71"))
    fabric.join("l6", "c2.ospfv3", "eth1", ipaddress.ip_address("fe80::72"))
    for d, rid, a4, ll, pfx in [
        (d1, "1.1.1.1", "10.0.70.1/30", "fe80::71/64", "2001:db8:71::1/64"),
        (d2, "2.2.2.2", "10.0.70.2/30", "fe80::72/64", "2001:db8:72::1/64"),
    ]:
        cand = d.candidate()
        cand.set("interfaces/interface[eth0]/address", [a4])
        cand.set("interfaces/interface[eth1]/address", [ll, pfx])
        base = "routing/control-plane-protocols"
        cand.set(f"{base}/ospfv2/router-id", rid)
        ob = f"{base}/ospfv2/area[0.0.0.0]/interface[eth0]"
        cand.set(f"{ob}/interface-type", "point-to-point")
        cand.set(f"{base}/ospfv3/router-id", rid)
        cand.set(f"{base}/ospfv3/area[0.0.0.0]/interface[eth1]/cost", 10)
        d.commit(cand)
    loop.advance(60)
    from ipaddress import IPv4Network as N4
    from ipaddress import IPv6Network as N6

    rib = d1.routing.rib.active_routes()
    assert N6("2001:db8:72::/64") in rib

    # v2 cost change: d2's peer prefix distance moves with it.
    cand = d1.candidate()
    cand.set(
        "routing/control-plane-protocols/ospfv2/area[0.0.0.0]"
        "/interface[eth0]/cost", 55,
    )
    cand.set(
        "routing/control-plane-protocols/ospfv3/area[0.0.0.0]"
        "/interface[eth1]/cost", 66,
    )
    d1.commit(cand)
    loop.advance(30)
    v2 = d1.routing.instances["ospfv2"]
    area = next(iter(v2.areas.values()))
    assert area.interfaces["eth0"].config.cost == 55
    assert v2.routes[N4("10.0.70.0/30")].dist == 55  # our own cost now
    v3 = d1.routing.instances["ospfv3"]
    assert v3.interfaces["eth1"].config.cost == 66
    assert v3.routes[N6("2001:db8:72::/64")].dist == 66 + 10  # + d2 prefix metric


def test_ospf_live_rekey_and_v3_prefix_metric():
    """r5 review regressions: (1) an inline key change on a RUNNING v2
    interface re-keys at commit time; (2) a v3 cost change updates the
    NEIGHBOR'S view of our prefixes (intra-area-prefix re-origination)."""
    import ipaddress

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="k1")
    d2 = Daemon(loop=loop, netio=fabric, name="k2")
    fabric.join("l7", "k1.ospfv2", "eth0", ipaddress.ip_address("10.0.71.1"))
    fabric.join("l7", "k2.ospfv2", "eth0", ipaddress.ip_address("10.0.71.2"))
    fabric.join("l8", "k1.ospfv3", "eth1", ipaddress.ip_address("fe80::81"))
    fabric.join("l8", "k2.ospfv3", "eth1", ipaddress.ip_address("fe80::82"))
    for d, rid, a4, ll, pfx in [
        (d1, "1.1.1.1", "10.0.71.1/30", "fe80::81/64", "2001:db8:81::1/64"),
        (d2, "2.2.2.2", "10.0.71.2/30", "fe80::82/64", "2001:db8:82::1/64"),
    ]:
        cand = d.candidate()
        cand.set("interfaces/interface[eth0]/address", [a4])
        cand.set("interfaces/interface[eth1]/address", [ll, pfx])
        base = "routing/control-plane-protocols"
        cand.set(f"{base}/ospfv2/router-id", rid)
        ob = f"{base}/ospfv2/area[0.0.0.0]/interface[eth0]"
        cand.set(f"{ob}/interface-type", "point-to-point")
        cand.set(f"{ob}/authentication/type", "md5")
        cand.set(f"{ob}/authentication/key", "old-key")
        cand.set(f"{base}/ospfv3/router-id", rid)
        cand.set(f"{base}/ospfv3/area[0.0.0.0]/interface[eth1]/cost", 10)
        d.commit(cand)
    loop.advance(60)
    from holo_tpu.protocols.ospf.neighbor import NsmState

    def full(d):
        inst = d.routing.instances["ospfv2"]
        return any(
            n.state == NsmState.FULL
            for a in inst.areas.values()
            for i in a.interfaces.values()
            for n in i.neighbors.values()
        )

    assert full(d1) and full(d2)
    # (1) Re-key BOTH sides on running interfaces: the commit applies
    # the new key immediately — adjacency survives and new packets
    # authenticate with the new key.
    for d in (d1, d2):
        cand = d.candidate()
        cand.set(
            "routing/control-plane-protocols/ospfv2/area[0.0.0.0]"
            "/interface[eth0]/authentication/key", "new-key",
        )
        d.commit(cand)
    inst = d1.routing.instances["ospfv2"]
    area = next(iter(inst.areas.values()))
    assert area.interfaces["eth0"].config.auth.key == b"new-key"
    loop.advance(60)  # several hello/dead cycles on the new key
    assert full(d1) and full(d2), "adjacency lost after live re-key"

    # (2) v3 cost change must move the NEIGHBOR'S distance to OUR
    # prefix (the intra-area-prefix LSA carries the metric).
    from ipaddress import IPv6Network as N6

    cand = d1.candidate()
    cand.set(
        "routing/control-plane-protocols/ospfv3/area[0.0.0.0]"
        "/interface[eth1]/cost", 66,
    )
    d1.commit(cand)
    loop.advance(30)
    v3_d2 = d2.routing.instances["ospfv3"]
    assert v3_d2.routes[N6("2001:db8:81::/64")].dist == 10 + 66, (
        v3_d2.routes.get(N6("2001:db8:81::/64"))
    )


import pytest


@pytest.mark.parametrize("level", ["level-2", "level-all"])
def test_isis_metric_live_reconfig(level):
    """IS-IS metric change on a RUNNING circuit re-originates the LSP
    and moves the neighbor's route metric (reference InterfaceUpdate) —
    both the single-level instance and the L1/L2 node fan-out."""
    import ipaddress

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="m1")
    d2 = Daemon(loop=loop, netio=fabric, name="m2")
    fabric.join("l9", "m1.isis", "eth0", ipaddress.ip_address("10.0.72.1"))
    fabric.join("l9", "m2.isis", "eth0", ipaddress.ip_address("10.0.72.2"))
    for d, sysid, addr, lo in [
        (d1, "0000.0000.0051", "10.0.72.1/30", "192.0.2.51/32"),
        (d2, "0000.0000.0052", "10.0.72.2/30", "198.51.100.52/32"),
    ]:
        cand = d.candidate()
        cand.set("interfaces/interface[eth0]/address", [addr])
        cand.set("interfaces/interface[lo0]/address", [lo])
        base = "routing/control-plane-protocols/isis"
        cand.set(f"{base}/system-id", sysid)
        cand.set(f"{base}/level", level)
        cand.set(f"{base}/interface[eth0]/interface-type", "point-to-point")
        cand.set(f"{base}/interface[eth0]/metric", 10)
        cand.set(f"{base}/interface[lo0]/metric", 1)
        d.commit(cand)
    loop.advance(40)
    from ipaddress import IPv4Network as N4

    far = N4("192.0.2.51/32")
    i2 = d2.routing.instances["isis"]
    assert far in i2.routes and i2.routes[far][0] == 10 + 1

    cand = d1.candidate()
    cand.set(
        "routing/control-plane-protocols/isis/interface[eth0]/metric", 40
    )
    d1.commit(cand)
    loop.advance(30)
    # The changed metric is d1's OUTBOUND edge, so it is d1's own route
    # to d2's prefix that moves (d2's path to d1 uses d2's metric).
    i1 = d1.routing.instances["isis"]
    far2 = N4("198.51.100.52/32")
    assert far2 in i1.routes and i1.routes[far2][0] == 40 + 1, (
        i1.routes.get(far2)
    )


def test_ospf_passive_and_hello_live_reconfig():
    """Passive flip and hello/dead changes apply to RUNNING circuits:
    passive=true kills the adjacency and parks the hello task,
    passive=false revives it (reference InterfaceUpdate family)."""
    import ipaddress

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="p1")
    d2 = Daemon(loop=loop, netio=fabric, name="p2")
    fabric.join("la", "p1.ospfv2", "eth0", ipaddress.ip_address("10.0.73.1"))
    fabric.join("la", "p2.ospfv2", "eth0", ipaddress.ip_address("10.0.73.2"))
    for d, rid, a4 in [
        (d1, "1.1.1.1", "10.0.73.1/30"),
        (d2, "2.2.2.2", "10.0.73.2/30"),
    ]:
        cand = d.candidate()
        cand.set("interfaces/interface[eth0]/address", [a4])
        base = "routing/control-plane-protocols/ospfv2"
        cand.set(f"{base}/router-id", rid)
        ob = f"{base}/area[0.0.0.0]/interface[eth0]"
        cand.set(f"{ob}/interface-type", "point-to-point")
        cand.set(f"{ob}/hello-interval", 2)
        cand.set(f"{ob}/dead-interval", 8)
        d.commit(cand)
    loop.advance(40)
    from holo_tpu.protocols.ospf.neighbor import NsmState

    def full(d):
        inst = d.routing.instances["ospfv2"]
        return any(
            n.state == NsmState.FULL
            for a in inst.areas.values()
            for i in a.interfaces.values()
            for n in i.neighbors.values()
        )

    assert full(d1) and full(d2)
    # Passive on d1: the adjacency dies (our side immediately, d2's by
    # dead timer).
    cand = d1.candidate()
    cand.set(
        "routing/control-plane-protocols/ospfv2/area[0.0.0.0]"
        "/interface[eth0]/passive", True,
    )
    d1.commit(cand)
    loop.advance(20)
    assert not full(d1) and not full(d2)
    # Back to active: the hello task restarts and FULL re-forms.
    cand = d1.candidate()
    cand.set(
        "routing/control-plane-protocols/ospfv2/area[0.0.0.0]"
        "/interface[eth0]/passive", False,
    )
    d1.commit(cand)
    loop.advance(40)
    assert full(d1) and full(d2), "adjacency did not revive after passive=false"


def test_ospfv3_passive_live_reconfig():
    """v3 analog of the passive flip: adjacency dies, prefixes stay
    advertised, revival re-forms FULL (r5 review: v2/v3 divergence)."""
    import ipaddress

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d1 = Daemon(loop=loop, netio=fabric, name="q1")
    d2 = Daemon(loop=loop, netio=fabric, name="q2")
    fabric.join("lb", "q1.ospfv3", "eth0", ipaddress.ip_address("fe80::91"))
    fabric.join("lb", "q2.ospfv3", "eth0", ipaddress.ip_address("fe80::92"))
    for d, rid, ll, pfx in [
        (d1, "1.1.1.1", "fe80::91/64", "2001:db8:91::1/64"),
        (d2, "2.2.2.2", "fe80::92/64", "2001:db8:92::1/64"),
    ]:
        cand = d.candidate()
        cand.set("interfaces/interface[eth0]/address", [ll, pfx])
        base = "routing/control-plane-protocols/ospfv3"
        cand.set(f"{base}/router-id", rid)
        cand.set(f"{base}/area[0.0.0.0]/interface[eth0]/cost", 10)
        cand.set(f"{base}/area[0.0.0.0]/interface[eth0]/hello-interval", 2)
        cand.set(f"{base}/area[0.0.0.0]/interface[eth0]/dead-interval", 8)
        d.commit(cand)
    loop.advance(40)
    from holo_tpu.protocols.ospf.neighbor import NsmState

    def full(d):
        inst = d.routing.instances["ospfv3"]
        return any(
            n.state == NsmState.FULL
            for i in inst.interfaces.values()
            for n in i.neighbors.values()
        )

    assert full(d1) and full(d2)
    cand = d1.candidate()
    cand.set(
        "routing/control-plane-protocols/ospfv3/area[0.0.0.0]"
        "/interface[eth0]/passive", True,
    )
    d1.commit(cand)
    loop.advance(20)
    assert not full(d1) and not full(d2)
    cand = d1.candidate()
    cand.set(
        "routing/control-plane-protocols/ospfv3/area[0.0.0.0]"
        "/interface[eth0]/passive", False,
    )
    d1.commit(cand)
    loop.advance(40)
    assert full(d1) and full(d2), "v3 adjacency did not revive"

"""OSPF graceful restart (RFC 3623): helper mode keeps routes through a
neighbor's restart; without GR the same restart drops them."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.protocols.ospf.instance import (
    IfConfig,
    IfUpMsg,
    InstanceConfig,
    OspfInstance,
)
from holo_tpu.protocols.ospf.interface import IfType
from holo_tpu.protocols.ospf.neighbor import NsmState
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def setup(loop, fabric):
    def rtr(name, rid):
        r = OspfInstance(name=name, config=InstanceConfig(router_id=A(rid)),
                         netio=fabric.sender_for(name))
        loop.register(r)
        return r

    cfg = IfConfig(if_type=IfType.POINT_TO_POINT, cost=1)
    r1, r2 = rtr("r1", "1.1.1.1"), rtr("r2", "2.2.2.2")
    r1.add_interface("e0", cfg, N("10.0.0.0/30"), A("10.0.0.1"))
    r2.add_interface("e0", cfg, N("10.0.0.0/30"), A("10.0.0.2"))
    # a second prefix so r1 holds a route THROUGH r2
    r2.add_interface("stub", IfConfig(if_type=IfType.POINT_TO_POINT, cost=1,
                                      passive=True),
                     N("192.168.2.0/24"), A("192.168.2.1"))
    fabric.join("l", "r1", "e0", A("10.0.0.1"))
    fabric.join("l", "r2", "e0", A("10.0.0.2"))
    for r, ifs in ((r1, ["e0"]), (r2, ["e0", "stub"])):
        for i in ifs:
            loop.send(r.name, IfUpMsg(i))
    loop.advance(60)
    return r1, r2


def restart_r2(loop, fabric, graceful: bool):
    """Simulate an r2 control-plane restart (instance dies and returns)."""
    r2_old = loop.actors["r2"]
    if graceful:
        r2_old.send_grace_lsas(grace_period=120)
        loop.run_until_idle()
    loop.unregister("r2")
    loop.advance(60)  # dead interval (40s) elapses during the outage
    r2_new = OspfInstance(name="r2",
                          config=InstanceConfig(router_id=A("2.2.2.2")),
                          netio=fabric.sender_for("r2"))
    loop.register(r2_new)
    if graceful:
        r2_new.begin_graceful_restart(grace_period=120)
    cfg = IfConfig(if_type=IfType.POINT_TO_POINT, cost=1)
    r2_new.add_interface("e0", cfg, N("10.0.0.0/30"), A("10.0.0.2"))
    r2_new.add_interface("stub", IfConfig(if_type=IfType.POINT_TO_POINT,
                                          cost=1, passive=True),
                         N("192.168.2.0/24"), A("192.168.2.1"))
    loop.send("r2", IfUpMsg("e0"))
    loop.send("r2", IfUpMsg("stub"))
    loop.advance(60)
    return r2_new


def test_without_gr_routes_drop_during_restart():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1, r2 = setup(loop, fabric)
    assert N("192.168.2.0/24") in r1.routes
    r2_old = r2
    loop.unregister("r2")
    loop.advance(60)  # dead interval expires -> adjacency killed
    assert N("192.168.2.0/24") not in r1.routes, "route should drop w/o GR"


def test_gr_helper_retains_routes_through_restart():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1, r2 = setup(loop, fabric)
    assert N("192.168.2.0/24") in r1.routes

    dropped = []
    orig_cb = r1.route_cb

    def watch(routes):
        if N("192.168.2.0/24") not in routes:
            dropped.append(loop.clock.now())

    r1.route_cb = watch

    restart_r2(loop, fabric, graceful=True)
    # Route held for the entire restart window and adjacency re-formed.
    assert not dropped, f"route dropped during graceful restart at {dropped}"
    assert N("192.168.2.0/24") in r1.routes
    iface = r1.areas[A("0.0.0.0")].interfaces["e0"]
    nbr = iface.neighbors[A("2.2.2.2")]
    assert nbr.state == NsmState.FULL
    assert nbr.gr_deadline is None  # helper exited after re-FULL


def test_restarting_side_expiry_resumes_origination():
    """A vanished pre-restart neighbor must not suppress origination
    forever: the restarting side exits GR at the grace deadline."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1, r2 = setup(loop, fabric)
    r2.send_grace_lsas(grace_period=40)
    loop.run_until_idle()
    loop.unregister("r2")
    loop.advance(10)
    # r2 restarts but r1 never comes back (fail its link).
    fabric.set_link_up("l", False)
    r2n = OspfInstance(name="r2", config=InstanceConfig(router_id=A("2.2.2.2")),
                       netio=fabric.sender_for("r2"))
    loop.register(r2n)
    r2n.begin_graceful_restart(grace_period=40)
    cfg = IfConfig(if_type=IfType.POINT_TO_POINT, cost=1)
    r2n.add_interface("e0", cfg, N("10.0.0.0/30"), A("10.0.0.2"))
    r2n.add_interface("stub", IfConfig(if_type=IfType.POINT_TO_POINT, cost=1,
                                       passive=True),
                      N("192.168.2.0/24"), A("192.168.2.1"))
    loop.send("r2", IfUpMsg("e0"))
    loop.send("r2", IfUpMsg("stub"))
    loop.advance(60)  # grace (40s) lapses without resync
    assert not r2n.gr_restarting
    # Origination resumed: r2 advertises its stub and routes locally.
    assert N("192.168.2.0/24") in r2n.routes


def test_gr_grace_expiry_kills_adjacency():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1, r2 = setup(loop, fabric)
    r2.send_grace_lsas(grace_period=50)
    loop.run_until_idle()
    loop.unregister("r2")  # restarts... and never comes back
    loop.advance(120)  # grace (50s) + margin
    assert N("192.168.2.0/24") not in r1.routes
    iface = r1.areas[A("0.0.0.0")].interfaces["e0"]
    assert A("2.2.2.2") not in iface.neighbors

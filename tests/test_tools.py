"""Dev-tools CLI: schema dump, coverage, validate, replay."""

import json
import subprocess
import sys

from holo_tpu.tools.cli import main


def run_cli(*argv, capsys):
    rc = main(list(argv))
    out = capsys.readouterr().out
    return rc, out


def test_schema_and_coverage(capsys):
    rc, out = run_cli("schema", "system", capsys=capsys)
    assert rc == 0 and "hostname" in out
    rc, out = run_cli("coverage", capsys=capsys)
    assert rc == 0 and "TOTAL" in out and "routing" in out


def test_validate(tmp_path, capsys):
    good = tmp_path / "good.json"
    good.write_text(json.dumps({"system": {"hostname": "x"}}))
    rc, out = run_cli("validate", str(good), capsys=capsys)
    assert rc == 0 and "valid" in out
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"system": {"bogus-leaf": 1}}))
    rc, out = run_cli("validate", str(bad), capsys=capsys)
    assert rc == 1 and "INVALID" in out


def test_replay_cli(tmp_path, capsys):
    """Record a convergence, replay it via the CLI, check the report."""
    from ipaddress import IPv4Address as A
    from ipaddress import IPv4Network as N

    from holo_tpu.protocols.ospf.instance import (
        IfConfig, IfUpMsg, InstanceConfig, OspfInstance,
    )
    from holo_tpu.protocols.ospf.interface import IfType
    from holo_tpu.utils.event_recorder import EventRecorder, instrument
    from holo_tpu.utils.netio import MockFabric
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    rec = tmp_path / "events.jsonl"
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    recorder = EventRecorder(rec)
    instrument(loop, recorder, actors={"r1"})

    def rtr(name, rid, addr):
        r = OspfInstance(name=name, config=InstanceConfig(router_id=A(rid)),
                         netio=fabric.sender_for(name))
        loop.register(r)
        cfg = IfConfig(if_type=IfType.POINT_TO_POINT, cost=3)
        r.add_interface("e0", cfg, N("10.0.0.0/30"), A(addr))
        fabric.join("l", name, "e0", A(addr))
        return r

    r1 = rtr("r1", "1.1.1.1", "10.0.0.1")
    rtr("r2", "2.2.2.2", "10.0.0.2")
    loop.send("r1", IfUpMsg("e0"))
    loop.send("r2", IfUpMsg("e0"))
    loop.advance(60)
    recorder.close()

    setup = tmp_path / "setup.json"
    setup.write_text(json.dumps({
        "actor": "r1",
        "router-id": "1.1.1.1",
        "interfaces": {"e0": {"type": "point-to-point", "cost": 3,
                              "prefix": "10.0.0.0/30",
                              "address": "10.0.0.1"}},
    }))
    rc, out = run_cli("replay", str(rec), "--setup", str(setup),
                      capsys=capsys)
    assert rc == 0
    assert "replayed" in out and "ROUTER" in out
    assert "10.0.0.0/30" in out  # route reproduced offline


def test_postmortem_cli_summary_and_json(tmp_path, capsys):
    """`postmortem <bundle>` renders the forensics summary; `--json`
    re-emits the canonical sorted JSON; non-bundles are rejected."""
    from holo_tpu import telemetry
    from holo_tpu.telemetry import flight

    t = [0.0]
    rec = flight.FlightRecorder(
        capacity=64, postmortem_dir=tmp_path, clock=lambda: t[0]
    )
    telemetry.tracer().on_complete = rec.note_span
    try:
        telemetry.counter("holo_pmcli_probe_total").inc(2)
        with telemetry.span("spf.dispatch", kind="one", backend="tpu"):
            pass
        rec.journal_mark(41, "r1")
        rec.journal_mark(42, "r1")
        rec.event("breaker", breaker="spf-dispatch", to="open")
        path, _ = rec.postmortem("breaker-open:spf-dispatch")
    finally:
        telemetry.tracer().on_complete = None

    rc, out = run_cli("postmortem", str(path), capsys=capsys)
    assert rc == 0
    assert "breaker-open:spf-dispatch" in out
    assert "journal tail: seq 41..42" in out
    assert "spf.dispatch" in out  # the span made the summary
    assert "holo_pmcli_probe_total += 2" in out

    rc, out = run_cli("postmortem", "--json", str(path), capsys=capsys)
    assert rc == 0
    doc = json.loads(out)
    assert doc["schema"] == "holo-postmortem/1"
    assert doc["journal-tail"] == [[41, "r1"], [42, "r1"]]

    bogus = tmp_path / "not-a-bundle.json"
    bogus.write_text(json.dumps({"hello": 1}))
    rc, _ = run_cli("postmortem", str(bogus), capsys=capsys)
    assert rc == 2


def test_deviations_generator(capsys):
    """`deviations MODULE.yang` emits the holo-tools yang_deviations
    skeleton: header, import with the module's own prefix, one
    commented-out not-supported deviation per node, footer
    (reference holo-tools/src/yang_deviations.rs)."""
    import glob

    from holo_tpu.tools.cli import main

    mods = glob.glob(
        "/root/reference/holo-yang/modules/ietf/ietf-key-chain*.yang"
    )
    if not mods:
        import pytest

        pytest.skip("reference YANG corpus unavailable")
    rc = main(["deviations", mods[0]])
    out = capsys.readouterr().out
    assert rc == 0
    assert out.startswith("module holo-ietf-key-chain-deviations {")
    assert "import ietf-key-chain {\n    prefix key-chain;" in out
    assert 'deviation "/key-chain:key-chains/key-chain:key-chain"' in out
    assert "deviate not-supported;" in out
    assert out.rstrip().endswith("}")

"""LDP stepwise conformance: the reference's recorded corpus replayed
through the live LdpEngine + real RFC 5036 wire codec
(tools/stepwise_ldp.py).

All 70 step-case directories pass (the CLI sweep also replays the 10 topology routers, reporting 80 total) — discovery (link + targeted hellos,
hold timeouts, hello-accept), session establishment (TCP accept/connect
roles, init/keepalive FSM, backoff), the full label distribution set
(mapping/request/withdraw/release incl. typed-wildcard FECs, No-Route and
Loop-Detected notifications, decode-error notifications), address
messages, config changes (instance/interface/targeted enable-disable) and
the clear-peer / clear-hello-adjacency RPCs — asserting the protocol,
ibus (label FIB), northbound-notif, and northbound-state planes.  Both
topology snapshots additionally converge to bit-identical operational
trees on every router.
"""

from pathlib import Path

import pytest

from holo_tpu.tools.stepwise_ldp import (
    LDP_DIR,
    case_map,
    run_all,
    run_case,
    run_topology,
)

pytestmark = pytest.mark.skipif(
    not LDP_DIR.exists(), reason="reference corpus not present"
)

KNOWN_PASS = [
    "message-label-mapping1",
    "message-addr2",
    "tcp-accept1",
    "nb-config-tnbr1",
    "timeout-nbr1",
    "message-decode-error1",
]
PASS_FLOOR = 70


def test_known_cases_pass():
    cm = case_map()
    for case in KNOWN_PASS:
        topo, rt = cm[case]
        status, detail = run_case(LDP_DIR / case, topo, rt)
        assert status == "pass", f"{case}: {detail}"


def test_stepwise_sweep_floor():
    res = run_all()
    passed = sorted(c for c, (s, _) in res.items() if s == "pass")
    failed = {c: d for c, (s, d) in res.items() if s != "pass"}
    assert len(passed) >= PASS_FLOOR, (
        f"only {len(passed)} LDP stepwise cases pass (floor {PASS_FLOOR}); "
        f"failures: { {c: d[:120] for c, d in list(failed.items())[:5]} }"
    )


@pytest.mark.parametrize("topo", ["topo1-1", "topo2-1"])
def test_topology_convergence(topo):
    res = run_topology(topo)
    assert res, f"no routers found for {topo}"
    bad = {c: d for c, (s, d) in res.items() if s != "pass"}
    assert not bad, f"{topo}: {bad}"

"""Sharded multi-chip SPF through the REAL dispatch path (ISSUE 8).

tests/test_parallel.py proves the mesh/layout scaffolding against the
scalar oracle; THIS suite proves the production promotion: with a
process mesh installed (`parallel.configure_process_mesh`, what the
daemon does at boot from ``[parallel]``), `TpuSpfBackend` and
`FrrEngine` dispatch sharded — and their output stays byte-identical
to both the single-device path and the scalar oracle, under
``jax.transfer_guard("disallow")``.  The suite runs on the 8-device
virtual CPU mesh the conftest forces (the same
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` shape the
acceptance criteria name).
"""

from contextlib import contextmanager

import numpy as np
import pytest

from holo_tpu import telemetry
from holo_tpu.frr.manager import FrrEngine
from holo_tpu.ops.graph import diff_topologies
from holo_tpu.ops.spf_engine import shared_graph_cache
from holo_tpu.parallel.mesh import (
    configure_process_mesh,
    process_mesh,
    reset_process_mesh,
)
from holo_tpu.spf.backend import ScalarSpfBackend, TpuSpfBackend
from holo_tpu.spf.synth import (
    clone_topology as clone,
    random_ospf_topology,
    whatif_link_failure_masks,
)
from holo_tpu.telemetry import profiling
from holo_tpu.testing import no_implicit_transfers

SPF_FIELDS = ("dist", "parent", "hops", "nexthop_words")
FRR_FIELDS = (
    "lfa_adj", "lfa_nodeprot", "rlfa_pq", "tilfa_p", "tilfa_q",
    "post_dist", "post_nh",
)


@contextmanager
def mesh_scope(n_batch=None, n_node=None, devices=None):
    """Install a process mesh for one test and ALWAYS uninstall after —
    the suite shares its process with every unsharded tier-1 test."""
    mesh = configure_process_mesh(n_batch, n_node, devices)
    try:
        yield mesh
    finally:
        reset_process_mesh()


@pytest.fixture(autouse=True)
def _no_leaked_mesh():
    yield
    assert process_mesh() is None, "a test leaked the process mesh"
    reset_process_mesh()


def assert_spf_equal(ref, got, msg=""):
    for f in SPF_FIELDS:
        np.testing.assert_array_equal(
            getattr(ref, f), getattr(got, f), err_msg=f"{msg} {f}"
        )


def _topo(seed=3, routers=24):
    return random_ospf_topology(
        n_routers=routers, n_networks=8, extra_p2p=40, seed=seed
    )


def shard_count(kind: str) -> float:
    snap = telemetry.snapshot(prefix="holo_spf_shard_dispatch_total")
    return snap.get(f"holo_spf_shard_dispatch_total{{kind={kind}}}", 0.0)


# -- the acceptance scenario: 8-scenario what-if over 8 devices ----------


@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2), (2, 4)])
def test_sharded_whatif_bit_identical_to_plain_and_oracle(mesh_shape):
    """An 8-scenario what-if batch through the real TpuSpfBackend
    sharded path is byte-identical to the single-device dispatch AND
    the scalar oracle, for every mesh factorization, under the
    transfer guard — and it demonstrably took the sharded path (the
    shard-dispatch counter moved)."""
    topo = _topo()
    masks = whatif_link_failure_masks(topo, n_scenarios=8, seed=4)
    with no_implicit_transfers():
        oracle = ScalarSpfBackend().compute_whatif(topo, masks)
        plain = TpuSpfBackend().compute_whatif(topo, masks)
        before = shard_count("whatif")
        with mesh_scope(*mesh_shape):
            shard = TpuSpfBackend().compute_whatif(topo, masks)
    assert shard_count("whatif") == before + 1
    for i, (o, p, s) in enumerate(zip(oracle, plain, shard)):
        assert_spf_equal(o, s, f"{mesh_shape} scen {i} vs oracle")
        assert_spf_equal(p, s, f"{mesh_shape} scen {i} vs plain")


def test_row_padding_and_sentinel_renorm():
    """node=4 over a 13-vertex LSDB pads graph rows to 16: results must
    still slice back to N with the no-parent sentinel renormalized to
    N (not the padded row count) — the bit-identity load-bearing
    detail of the readback contract."""
    topo = random_ospf_topology(n_routers=11, n_networks=2, seed=9)
    assert topo.n_vertices % 4 != 0  # the padding case, by construction
    with no_implicit_transfers():
        ref = ScalarSpfBackend().compute(topo)
        with mesh_scope(2, 4):
            got = TpuSpfBackend().compute(topo)
    assert got.dist.shape == (topo.n_vertices,)
    assert got.parent.max() <= topo.n_vertices
    assert_spf_equal(ref, got)


def test_odd_scenario_batch_pads_and_slices():
    """B=5 does not divide the 8-wide batch axis: the dispatch pads
    with no-failure scenarios and hands back exactly 5 results."""
    topo = _topo(seed=7)
    masks = whatif_link_failure_masks(topo, n_scenarios=5, seed=1)
    with no_implicit_transfers():
        oracle = ScalarSpfBackend().compute_whatif(topo, masks)
        with mesh_scope(8, 1):
            got = TpuSpfBackend().compute_whatif(topo, masks)
    assert len(got) == 5
    for i, (o, s) in enumerate(zip(oracle, got)):
        assert_spf_equal(o, s, f"scen {i}")


def test_sharded_multiroot_parity():
    topo = random_ospf_topology(n_routers=11, n_networks=2, seed=9)
    roots = np.asarray([0, 1, 3], np.int32)  # odd count: batch-padded
    with no_implicit_transfers():
        ref = ScalarSpfBackend().compute_multiroot(topo, roots)
        with mesh_scope(2, 4):
            got = TpuSpfBackend().compute_multiroot(topo, roots)
    for f in ("dist", "parent", "hops"):
        assert got.dist.shape == (3, topo.n_vertices)
        np.testing.assert_array_equal(
            getattr(ref, f), getattr(got, f), err_msg=f
        )


def test_one_device_mesh_matches_plain_path():
    """The sharding_overhead gate's configuration: a 1-device mesh runs
    the mesh-aware code path and must produce the plain path's bits."""
    import jax

    topo = _topo(seed=5)
    masks = whatif_link_failure_masks(topo, n_scenarios=4, seed=2)
    with no_implicit_transfers():
        plain = TpuSpfBackend().compute_whatif(topo, masks)
        with mesh_scope(1, 1, devices=jax.devices()[:1]):
            got = TpuSpfBackend().compute_whatif(topo, masks)
    for p, s in zip(plain, got):
        assert_spf_equal(p, s)


# -- DeltaPath composes with sharding ------------------------------------


def test_delta_chain_on_sharded_resident_stays_incremental():
    """A weight-delta chain against a node-sharded resident graph is
    served by the in-place apply + seeded incremental kernel (not a
    re-marshal), bit-identical to the oracle at every step."""
    rng = np.random.default_rng(13)
    topo = _topo(seed=13)
    with no_implicit_transfers():
        with mesh_scope(4, 2):
            be = TpuSpfBackend()
            be.compute(topo)
            before = telemetry.snapshot(prefix="holo_spf_delta")
            cur = topo
            for step in range(4):
                e = int(rng.integers(0, cur.n_edges))
                nxt = clone(cur, cost={e: int(rng.integers(1, 64))})
                d = diff_topologies(cur, nxt)
                if d is not None:
                    nxt.link_delta(d)
                got = be.compute(nxt)
                assert_spf_equal(
                    ScalarSpfBackend().compute(nxt), got, f"step {step}"
                )
                cur = nxt
            after = telemetry.snapshot(prefix="holo_spf_delta")
            stats = shared_graph_cache().stats()

    def count(snap, needle):
        return sum(v for k, v in snap.items() if needle in k)

    assert (
        count(after, "path=incremental") > count(before, "path=incremental")
    ), "the sharded resident must serve the chain incrementally"
    assert stats["sharded-entries"] >= 1
    assert stats["mesh"] == {"batch": 4, "node": 2}


# -- FRR all-roots plane --------------------------------------------------


def test_sharded_frr_bit_identical_to_plain_and_oracle():
    topo = random_ospf_topology(
        n_routers=13, n_networks=3, extra_p2p=20, seed=5
    )
    with no_implicit_transfers():
        ref = FrrEngine("scalar").compute(topo)
        plain = FrrEngine("tpu").compute(topo)
        before = shard_count("frr")
        with mesh_scope(4, 2):
            shard = FrrEngine("tpu").compute(topo)
    assert shard_count("frr") == before + 1
    for f in FRR_FIELDS:
        np.testing.assert_array_equal(
            getattr(ref, f), getattr(shard, f), err_msg=f"{f} vs oracle"
        )
        np.testing.assert_array_equal(
            getattr(plain, f), getattr(shard, f), err_msg=f"{f} vs plain"
        )


# -- observability satellites --------------------------------------------


def test_per_device_stage_profiling_splits_by_device():
    """A profiled sharded dispatch emits holo_profile_stage_seconds
    device-phase rows labeled per device id — one per mesh device —
    alongside the whole-span device='-' row."""
    topo = _topo(seed=11)
    masks = whatif_link_failure_masks(topo, n_scenarios=8, seed=3)

    def device_rows():
        snap = telemetry.snapshot(prefix="holo_profile_stage_seconds")
        return {
            k: v["count"]
            for k, v in snap.items()
            if "site=spf.whatif,stage=device" in k
        }

    before = device_rows()
    profiling.set_device_profiling(True)
    try:
        with mesh_scope(4, 2):
            TpuSpfBackend().compute_whatif(topo, masks)
    finally:
        profiling.set_device_profiling(False)
    after = device_rows()
    for dev in range(8):
        key = (
            "holo_profile_stage_seconds"
            f"{{site=spf.whatif,stage=device,device={dev}}}"
        )
        assert after.get(key, 0) == before.get(key, 0) + 1, key
    whole = (
        "holo_profile_stage_seconds"
        "{site=spf.whatif,stage=device,device=-}"
    )
    assert after.get(whole, 0) == before.get(whole, 0) + 1


def test_cache_stats_per_device_placement_on_gnmi_leaf():
    """Satellite: the spf-graph-cache leaf carries mesh + per-device
    entries/rows/bytes placement for sharded residents."""
    from holo_tpu.telemetry.provider import TelemetryStateProvider

    topo = _topo(seed=17)
    with mesh_scope(2, 4):
        TpuSpfBackend().compute(topo)
        state = TelemetryStateProvider().get_state()
        leaf = state["holo-telemetry"]["spf-graph-cache"]
        assert leaf["sharded-entries"] >= 1
        assert leaf["mesh"] == {"batch": 2, "node": 4}
        per_dev = leaf["per-device"]
        assert len(per_dev) == 8  # every mesh device holds a row block
        rows_total = sum(d["rows"] for d in per_dev.values())
        for d in per_dev.values():
            assert d["entries"] >= 1
            assert d["bytes"] > 0
        # node=4 row-shards the padded rows; batch=2 replicates them.
        assert rows_total % 2 == 0

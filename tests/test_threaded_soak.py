"""Multi-protocol soak under the DEFAULT (threaded) daemon posture.

Two real-clock daemons run OSPFv2 + IS-IS + RIPv2 simultaneously, each
instance on its own OS thread, exchanging real frames over the shared
fabric for several seconds: adjacencies form concurrently, routes land
in both RIBs, a live reconfiguration commits mid-traffic, and shutdown
joins every instance thread.  This is the production assembly the
reference runs (holo-protocol/src/lib.rs:419-430 per-instance
spawn_blocking), exercised end to end rather than per subsystem.
"""

import time
from ipaddress import ip_address

from holo_tpu.daemon.config import DaemonConfig
from holo_tpu.daemon.daemon import Daemon


def _wait(cond, timeout=25.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def test_multi_protocol_threaded_soak():
    assert DaemonConfig().runtime.isolation == "threaded"
    # ONE thread-safe wire spans both daemons: ThreadedFabric delivery
    # posts into each endpoint's OWNING router, which wakes that
    # instance's thread — real frames crossing real threads.
    from holo_tpu.utils.preempt import ThreadedFabric

    wire = ThreadedFabric()
    d1 = Daemon(config=DaemonConfig(), name="s1", netio=wire.sender_for)
    d2 = Daemon(config=DaemonConfig(), name="s2", netio=wire.sender_for)
    assert d1.loop_router is not None and d2.loop_router is not None
    links = [
        ("ospf-l", "ospfv2", "eth0", "10.80.0.1", "10.80.0.2"),
        ("isis-l", "isis", "eth1", "10.81.0.1", "10.81.0.2"),
        ("rip-l", "ripv2", "eth2", "10.82.0.1", "10.82.0.2"),
    ]
    for link, actor, ifname, a1, a2 in links:
        wire.join(link, d1.loop_router, f"s1.{actor}", ifname, ip_address(a1))
        wire.join(link, d2.loop_router, f"s2.{actor}", ifname, ip_address(a2))

    try:
        for d, rid, sysid, o, i, r in (
            (d1, "1.1.1.1", "0000.0000.0041", "10.80.0.1/30",
             "10.81.0.1/30", "10.82.0.1/30"),
            (d2, "2.2.2.2", "0000.0000.0042", "10.80.0.2/30",
             "10.81.0.2/30", "10.82.0.2/30"),
        ):
            cand = d.candidate()
            cand.set("interfaces/interface[eth0]/address", [o])
            cand.set("interfaces/interface[eth1]/address", [i])
            cand.set("interfaces/interface[eth2]/address", [r])
            base = "routing/control-plane-protocols"
            cand.set(f"{base}/ospfv2/router-id", rid)
            ob = f"{base}/ospfv2/area[0.0.0.0]/interface[eth0]"
            cand.set(f"{ob}/interface-type", "point-to-point")
            cand.set(f"{ob}/hello-interval", 1)
            cand.set(f"{ob}/dead-interval", 4)
            cand.set(f"{base}/isis/system-id", sysid)
            cand.set(f"{base}/isis/level", "level-2")
            cand.set(f"{base}/isis/interface[eth1]/interface-type",
                     "point-to-point")
            cand.set(f"{base}/ripv2/update-interval", 2)
            cand.set(f"{base}/ripv2/interface[eth2]/cost", 1)
            # A per-daemon loopback prefix gives RIP something to LEARN
            # (the shared /30 is connected on both sides).
            lo = "192.0.2.1/32" if d is d1 else "198.51.100.1/32"
            cand.set("interfaces/interface[lo0]/address", [lo])
            cand.set(f"{base}/ripv2/interface[lo0]/cost", 1)
            d.commit(cand)

        # Every instance on its own thread in both daemons (loop names
        # carry the daemon prefix, e.g. "s1.ospfv2").
        for d in (d1, d2):
            suffixes = {n.split(".")[-1] for n in d.instance_loops}
            assert suffixes >= {"ospfv2", "isis", "ripv2"}, (
                d.instance_loops.keys()
            )

        from holo_tpu.protocols.ospf.neighbor import NsmState

        def ospf_full(d):
            inst = d.routing.instances.get("ospfv2")
            return inst is not None and any(
                n.state == NsmState.FULL
                for a in inst.areas.values()
                for i2 in a.interfaces.values()
                for n in i2.neighbors.values()
            )

        from holo_tpu.protocols.isis.instance import AdjacencyState

        def isis_up(d):
            inst = d.routing.instances.get("isis")
            if inst is None:
                return False
            iface = inst.interfaces.get("eth1")
            return (
                iface is not None
                and iface.adj is not None
                and iface.adj.state == AdjacencyState.UP
            )

        def rip_learned(d):
            inst = d.routing.instances.get("ripv2")
            return inst is not None and any(
                r.route_type == "rip" for r in inst.routes.values()
            )

        assert _wait(lambda: ospf_full(d1) and ospf_full(d2)), (
            "OSPF adjacency did not form under threaded isolation"
        )
        assert _wait(lambda: isis_up(d1) and isis_up(d2)), (
            "IS-IS adjacency did not form under threaded isolation"
        )
        assert _wait(lambda: rip_learned(d1) and rip_learned(d2)), (
            "RIP routes did not propagate under threaded isolation"
        )

        # Live reconfiguration mid-traffic: an OSPF cost change commits
        # through the threaded marshalling without disturbing the others.
        cand = d1.candidate()
        cand.set(
            "routing/control-plane-protocols/ospfv2/area[0.0.0.0]"
            "/interface[eth0]/cost", 44,
        )
        d1.commit(cand)
        time.sleep(2.0)
        assert ospf_full(d1) and isis_up(d1) and rip_learned(d1)
        inst = d1.routing.instances["ospfv2"]
        area = next(iter(inst.areas.values()))
        assert area.interfaces["eth0"].config.cost == 44
    finally:
        d1.stop()
        d2.stop()
    # Instance threads joined on stop.
    for d in (d1, d2):
        for tl in d.instance_loops.values():
            assert not tl._thread.is_alive()

"""BGP topology conformance: the reference's recorded router snapshots
replayed through the live BgpEngine (tools/stepwise_bgp.py).

All 10 routers across topo1-1 (eBGP mesh with redistribution) and
topo2-1 (iBGP full mesh + eBGP + multipath) converge with all four
output planes matching the recording: every protocol message sent
(Opens with capabilities, Keepalives, grouped Updates), the ibus plane
(RouterIdSub, redistribution subs, nexthop tracking, RouteIpAdd with
recursive nexthops), established/backward-transition notifications, and
the full ietf-bgp operational tree (neighbors, capabilities, Adj-RIB-In/
Out pre+post with eligibility/reject reasons, Loc-RIB, attr-sets
compared structurally).
"""

from pathlib import Path

import pytest

from holo_tpu.tools.stepwise_bgp import BGP_DIR, run_all, run_router

pytestmark = pytest.mark.skipif(
    not BGP_DIR.exists(), reason="reference corpus not present"
)


def test_known_router_passes():
    status, detail = run_router("topo1-1", "rt1")
    assert status == "pass", detail


def test_all_routers_pass():
    res = run_all()
    assert len(res) == 10
    bad = {c: d for c, (s, d) in res.items() if s != "pass"}
    assert not bad, f"failures: { {c: d[:200] for c, d in bad.items()} }"

"""Interface actuation: VRRP macvlans + admin/MTU apply.

Reference: holo-vrrp/src/instance.rs:301-311 (virtual-MAC macvlan) and
holo-interface/src/netlink.rs:242-270 (config apply).
"""

import os
import subprocess

import pytest

from holo_tpu.daemon.daemon import Daemon
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def test_vrrp_master_owns_macvlan_config_driven():
    """Config-driven VRRP: the master creates the virtual-MAC macvlan
    with the VIP; losing mastership (higher-priority advert) deletes it."""
    from ipaddress import IPv4Address as A

    from holo_tpu.protocols.vrrp import VrrpState
    from holo_tpu.utils.netio import MockFabric

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d = Daemon(loop=loop, netio=fabric, name="v")
    fabric.join("lan", "v.vrrp-eth0-7", "eth0", A("10.0.0.1"))

    c = d.candidate()
    c.set("interfaces/interface[eth0]/enabled", "true")
    c.set("interfaces/interface[eth0]/address", ["10.0.0.1/24"])
    base = "routing/control-plane-protocols/vrrp"
    c.set(f"{base}/instance[7]/vrid", 7)
    c.set(f"{base}/instance[7]/interface", "eth0")
    c.set(f"{base}/instance[7]/priority", 200)
    c.set(f"{base}/instance[7]/virtual-address", ["10.0.0.100"])
    d.commit(c)
    loop.advance(15)

    inst = d.routing.vrrp_instances[7]
    assert inst.state == VrrpState.MASTER
    lm = d.routing.link_mgr
    name = "vrrp7.eth0"
    assert name in lm.links
    assert lm.links[name]["parent"] == "eth0"
    assert lm.links[name]["mac"] == bytes((0, 0, 0x5E, 0, 1, 7))
    assert lm.links[name]["up"] is True
    assert any(str(a.ip) == "10.0.0.100" for a in lm.links[name]["addrs"])

    # A higher-priority master appears: we step down, macvlan goes away.
    from holo_tpu.protocols.vrrp import VrrpPacket
    from holo_tpu.utils.netio import NetRxPacket

    adv = VrrpPacket(
        version=3, vrid=7, priority=250, max_advert_int=100,
        addresses=[A("10.0.0.100")],
    )
    loop.send(
        "v.vrrp-eth0-7",
        NetRxPacket("eth0", A("10.0.0.2"), A("224.0.0.18"), adv.encode()),
    )
    loop.advance(2)
    assert inst.state == VrrpState.BACKUP
    assert name not in lm.links


def test_admin_mtu_apply_records_actuation():
    """Config enabled/mtu changes flow to the link manager."""
    from holo_tpu.routing.netlink import MockLinkManager

    loop = EventLoop(clock=VirtualClock())
    d = Daemon(loop=loop, name="m")
    lm = MockLinkManager()
    lm.links["eth1"] = {"addrs": []}  # link exists in the kernel
    d.interface.link_mgr = lm
    c = d.candidate()
    c.set("interfaces/interface[eth1]/enabled", "true")
    c.set("interfaces/interface[eth1]/mtu", 9000)
    d.commit(c)
    # first creation applies mtu (differs from the 1500 default state)
    assert ("set-link", "eth1", None, 9000) in lm.log
    c = d.candidate()
    c.set("interfaces/interface[eth1]/enabled", "false")
    c.set("interfaces/interface[eth1]/mtu", 9000)
    d.commit(c)
    assert ("set-link", "eth1", False, None) in lm.log


NEED_ROOT = os.geteuid() != 0 or not os.path.exists("/proc/net/netlink")


@pytest.mark.skipif(NEED_ROOT, reason="requires root + netlink")
def test_linkmanager_real_kernel_macvlan():
    """Real kernel: create a macvlan over a veth, set MTU/admin, address
    it, and delete — the production actuation path end to end."""
    from ipaddress import ip_interface

    from holo_tpu.routing.netlink import LinkManager, NetlinkSocket, link_table

    def sh(cmd, check=True):
        return subprocess.run(cmd, shell=True, check=check,
                              capture_output=True, text=True)

    sh("ip link del vactu0 2>/dev/null", check=False)
    sh("ip link add vactu0 type veth peer name vactu1")
    try:
        lm = LinkManager()
        lm.create_macvlan("vactu0", "vmac0", bytes((0, 0, 0x5E, 0, 1, 9)))
        try:
            lm.set_link("vmac0", up=True, mtu=1400)
            lm.add_address("vmac0", ip_interface("10.99.7.1/24"))
            out = sh("ip -d link show vmac0").stdout
            assert "macvlan" in out and "00:00:5e:00:01:09" in out
            assert "mtu 1400" in out
            addr = sh("ip addr show vmac0").stdout
            assert "10.99.7.1/24" in addr
        finally:
            lm.delete_link("vmac0")
        assert "vmac0" not in link_table(NetlinkSocket())
    finally:
        sh("ip link del vactu0", check=False)

"""Interface actuation: VRRP macvlans + admin/MTU apply.

Reference: holo-vrrp/src/instance.rs:301-311 (virtual-MAC macvlan) and
holo-interface/src/netlink.rs:242-270 (config apply).
"""

import os
import subprocess

import pytest

from holo_tpu.daemon.daemon import Daemon
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def test_vrrp_master_owns_macvlan_config_driven():
    """Config-driven VRRP: the master creates the virtual-MAC macvlan
    with the VIP; losing mastership (higher-priority advert) deletes it."""
    from ipaddress import IPv4Address as A

    from holo_tpu.protocols.vrrp import VrrpState
    from holo_tpu.utils.netio import MockFabric

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    d = Daemon(loop=loop, netio=fabric, name="v")
    fabric.join("lan", "v.vrrp-eth0-7", "eth0", A("10.0.0.1"))

    c = d.candidate()
    c.set("interfaces/interface[eth0]/enabled", "true")
    c.set("interfaces/interface[eth0]/address", ["10.0.0.1/24"])
    base = "routing/control-plane-protocols/vrrp"
    c.set(f"{base}/instance[7]/vrid", 7)
    c.set(f"{base}/instance[7]/interface", "eth0")
    c.set(f"{base}/instance[7]/priority", 200)
    c.set(f"{base}/instance[7]/virtual-address", ["10.0.0.100"])
    d.commit(c)
    loop.advance(15)

    inst = d.routing.vrrp_instances[7]
    assert inst.state == VrrpState.MASTER
    lm = d.routing.link_mgr
    name = "vrrp7.eth0"
    assert name in lm.links
    assert lm.links[name]["parent"] == "eth0"
    assert lm.links[name]["mac"] == bytes((0, 0, 0x5E, 0, 1, 7))
    assert lm.links[name]["up"] is True
    assert any(str(a.ip) == "10.0.0.100" for a in lm.links[name]["addrs"])

    # A higher-priority master appears: we step down, macvlan goes away.
    from holo_tpu.protocols.vrrp import VrrpPacket
    from holo_tpu.utils.netio import NetRxPacket

    adv = VrrpPacket(
        version=3, vrid=7, priority=250, max_advert_int=100,
        addresses=[A("10.0.0.100")],
    )
    loop.send(
        "v.vrrp-eth0-7",
        NetRxPacket("eth0", A("10.0.0.2"), A("224.0.0.18"), adv.encode()),
    )
    loop.advance(2)
    assert inst.state == VrrpState.BACKUP
    assert name not in lm.links


def test_admin_mtu_apply_records_actuation():
    """Config enabled/mtu changes flow to the link manager."""
    from holo_tpu.routing.netlink import MockLinkManager

    loop = EventLoop(clock=VirtualClock())
    d = Daemon(loop=loop, name="m")
    lm = MockLinkManager()
    lm.links["eth1"] = {"addrs": []}  # link exists in the kernel
    d.interface.link_mgr = lm
    c = d.candidate()
    c.set("interfaces/interface[eth1]/enabled", "true")
    c.set("interfaces/interface[eth1]/mtu", 9000)
    d.commit(c)
    # first creation applies mtu (differs from the 1500 default state)
    assert ("set-link", "eth1", None, 9000) in lm.log
    c = d.candidate()
    c.set("interfaces/interface[eth1]/enabled", "false")
    c.set("interfaces/interface[eth1]/mtu", 9000)
    d.commit(c)
    assert ("set-link", "eth1", False, None) in lm.log


NEED_ROOT = os.geteuid() != 0 or not os.path.exists("/proc/net/netlink")


@pytest.mark.skipif(NEED_ROOT, reason="requires root + netlink")
def test_linkmanager_real_kernel_macvlan():
    """Real kernel: create a macvlan over a veth, set MTU/admin, address
    it, and delete — the production actuation path end to end."""
    from ipaddress import ip_interface

    from holo_tpu.routing.netlink import LinkManager, NetlinkSocket, link_table

    def sh(cmd, check=True):
        return subprocess.run(cmd, shell=True, check=check,
                              capture_output=True, text=True)

    sh("ip link del vactu0 2>/dev/null", check=False)
    sh("ip link add vactu0 type veth peer name vactu1")
    try:
        lm = LinkManager()
        lm.create_macvlan("vactu0", "vmac0", bytes((0, 0, 0x5E, 0, 1, 9)))
        try:
            lm.set_link("vmac0", up=True, mtu=1400)
            lm.add_address("vmac0", ip_interface("10.99.7.1/24"))
            out = sh("ip -d link show vmac0").stdout
            assert "macvlan" in out and "00:00:5e:00:01:09" in out
            assert "mtu 1400" in out
            addr = sh("ip addr show vmac0").stdout
            assert "10.99.7.1/24" in addr
        finally:
            lm.delete_link("vmac0")
        assert "vmac0" not in link_table(NetlinkSocket())
    finally:
        sh("ip link del vactu0", check=False)


def test_vlan_subinterface_config_driven():
    """A "vlan"-typed interface with parent + vlan-id is created via the
    link manager on first appearance (reference holo-interface
    configuration.rs:354-365 Event::VlanCreate)."""
    from holo_tpu.routing.netlink import MockLinkManager

    loop = EventLoop(clock=VirtualClock())
    d = Daemon(loop=loop, name="vl")
    lm = MockLinkManager()
    lm.links["eth0"] = {"addrs": []}
    d.interface.link_mgr = lm
    c = d.candidate()
    c.set("interfaces/interface[eth0.100]/type", "vlan")
    c.set("interfaces/interface[eth0.100]/parent-interface", "eth0")
    c.set("interfaces/interface[eth0.100]/vlan-id", 100)
    d.commit(c)
    assert ("create-vlan", "eth0", "eth0.100", 100) in lm.log
    assert lm.links["eth0.100"]["vlan_id"] == 100
    # Re-commit: no duplicate creation (first-appearance semantics).
    c = d.candidate()
    c.set("interfaces/interface[eth0.100]/mtu", 1400)
    d.commit(c)
    assert lm.log.count(("create-vlan", "eth0", "eth0.100", 100)) == 1


@pytest.mark.skipif(NEED_ROOT, reason="requires root + netlink")
def test_linkmanager_real_kernel_vlan():
    """Real kernel: create an 802.1Q subinterface over a veth, verify
    the kernel sees kind vlan + the id, and delete (reference
    holo-interface/src/netlink.rs:271-285)."""
    from holo_tpu.routing.netlink import LinkManager, NetlinkSocket, link_table

    def sh(cmd, check=True):
        return subprocess.run(cmd, shell=True, check=check,
                              capture_output=True, text=True)

    sh("ip link del vlanp0 2>/dev/null", check=False)
    sh("ip link add vlanp0 type veth peer name vlanp1")
    try:
        lm = LinkManager()
        import pytest as _pytest

        with _pytest.raises(ValueError):
            lm.create_vlan("vlanp0", "bad.0", 0)  # id out of range
        try:
            lm.create_vlan("vlanp0", "vlanp0.42", 42)
        except OSError as e:
            import errno as _errno

            if e.errno == _errno.EOPNOTSUPP:
                _pytest.skip("kernel lacks the 8021q module")
            raise
        try:
            out = sh("ip -d link show vlanp0.42").stdout
            assert "vlan" in out and "id 42" in out
            assert "vlanp0" in out  # parented correctly
        finally:
            lm.delete_link("vlanp0.42")
        assert "vlanp0.42" not in link_table(NetlinkSocket())
    finally:
        sh("ip link del vlanp0", check=False)


def test_vlan_change_and_teardown(caplog):
    """VLAN actuation is change-driven with symmetric teardown (r5
    review): vlan leaves added in a LATER commit still create the
    device, an id change recreates it, and config removal deletes the
    kernel link."""
    import pytest as _pytest

    from holo_tpu.routing.netlink import MockLinkManager

    loop = EventLoop(clock=VirtualClock())
    d = Daemon(loop=loop, name="vt")
    lm = MockLinkManager()
    lm.links["eth0"] = {"addrs": []}
    d.interface.link_mgr = lm

    # Commit 1: plain interface entry — no vlan yet.
    c = d.candidate()
    c.set("interfaces/interface[eth0.7]/mtu", 1400)
    d.commit(c)
    assert not [e for e in lm.log if e[0] == "create-vlan"]
    # Commit 2: vlan leaves arrive later — device must still be created.
    c = d.candidate()
    c.set("interfaces/interface[eth0.7]/type", "vlan")
    c.set("interfaces/interface[eth0.7]/parent-interface", "eth0")
    c.set("interfaces/interface[eth0.7]/vlan-id", 7)
    d.commit(c)
    assert ("create-vlan", "eth0", "eth0.7", 7) in lm.log
    # Commit 3: id change recreates (delete + create).
    c = d.candidate()
    c.set("interfaces/interface[eth0.7]/vlan-id", 8)
    d.commit(c)
    assert ("delete-link", "eth0.7") in lm.log
    assert ("create-vlan", "eth0", "eth0.7", 8) in lm.log
    # Commit 4: removal tears the kernel device down.
    c = d.candidate()
    c.delete("interfaces/interface[eth0.7]")
    d.commit(c)
    assert lm.log.count(("delete-link", "eth0.7")) == 2
    # Validation: bad id / missing parent reject the commit.
    c = d.candidate()
    c.set("interfaces/interface[eth0.9]/type", "vlan")
    c.set("interfaces/interface[eth0.9]/parent-interface", "eth0")
    c.set("interfaces/interface[eth0.9]/vlan-id", 4095)
    with _pytest.raises(Exception, match="vlan-id must be 1-4094"):
        d.commit(c)
    c = d.candidate()
    c.set("interfaces/interface[eth0.9]/type", "vlan")
    c.set("interfaces/interface[eth0.9]/vlan-id", 9)
    with _pytest.raises(Exception, match="BOTH"):
        d.commit(c)

"""Routing policy engine: match sets, statement chains, BGP integration."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.utils.policy import (
    Actions,
    Conditions,
    DefinedSets,
    Policy,
    PolicyEngine,
    PolicyResult,
    PrefixSet,
    RouteContext,
    Statement,
)


def test_prefix_set_ranges():
    ps = PrefixSet("p").add("10.0.0.0/8", ge=16, le=24)
    assert ps.matches(N("10.1.0.0/16"))
    assert ps.matches(N("10.1.2.0/24"))
    assert not ps.matches(N("10.0.0.0/8"))  # too short
    assert not ps.matches(N("10.1.2.128/25"))  # too long
    assert not ps.matches(N("11.0.0.0/16"))  # outside base
    exact = PrefixSet("e").add("192.0.2.0/24")
    assert exact.matches(N("192.0.2.0/24"))
    assert not exact.matches(N("192.0.2.0/25"))


def test_statement_chain_edits_then_terminal():
    sets = DefinedSets(prefix_sets={"nets": PrefixSet("nets").add("10.0.0.0/8", ge=8, le=32)})
    pol = Policy(
        "p",
        statements=[
            Statement("tag-it", Conditions(prefix_set="nets"),
                      Actions(set_tag=77)),  # non-terminal edit
            Statement("accept-all", Conditions(), Actions(result=PolicyResult.ACCEPT)),
        ],
    )
    ctx = RouteContext(prefix=N("10.5.0.0/16"))
    assert pol.evaluate(ctx, sets) == PolicyResult.ACCEPT
    assert ctx.tag == 77
    ctx2 = RouteContext(prefix=N("172.16.0.0/16"))
    assert pol.evaluate(ctx2, sets) == PolicyResult.ACCEPT
    assert ctx2.tag is None  # first statement didn't match


def test_engine_from_yang_config_and_bgp_hook():
    engine = PolicyEngine()
    engine.load_from_config(
        {
            "defined-sets": {
                "prefix-set": {"blocked": {"prefix": ["203.0.113.0/24"]}},
            },
            "policy-definition": {
                "edge-in": {
                    "statement": {
                        "drop-doc": {
                            "conditions": {"match-prefix-set": "blocked"},
                            "actions": {"policy-result": "reject-route"},
                        },
                        "accept": {
                            "actions": {"policy-result": "accept-route",
                                        "set-metric": 500},
                        },
                    }
                }
            },
        }
    )
    ctx = RouteContext(prefix=N("203.0.113.0/24"))
    assert engine.apply("edge-in", ctx) == PolicyResult.REJECT
    ctx = RouteContext(prefix=N("198.51.100.0/24"))
    assert engine.apply("edge-in", ctx) == PolicyResult.ACCEPT
    assert ctx.metric == 500

    # End-to-end with BGP: the hook filters and rewrites attributes.
    from holo_tpu.protocols.bgp import (
        BgpInstance, PeerConfig, PeerState,
    )
    from holo_tpu.utils.netio import MockFabric
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    b1 = BgpInstance("b1", 65001, A("1.1.1.1"), fabric.sender_for("b1"))
    b2 = BgpInstance("b2", 65002, A("2.2.2.2"), fabric.sender_for("b2"))
    loop.register(b1)
    loop.register(b2)
    fabric.join("l", "b1", "e0", A("10.0.0.1"))
    fabric.join("l", "b2", "e0", A("10.0.0.2"))
    b1.add_peer(PeerConfig(A("10.0.0.2"), 65002, "e0"), A("10.0.0.1"))
    b2.add_peer(
        PeerConfig(A("10.0.0.1"), 65001, "e0",
                   import_policy=engine.bgp_import_hook("edge-in")),
        A("10.0.0.2"),
    )
    b1.start_peer(A("10.0.0.2"))
    b2.start_peer(A("10.0.0.1"))
    loop.advance(5)
    assert b2.peers[A("10.0.0.1")].state == PeerState.ESTABLISHED
    b1.originate(N("203.0.113.0/24"))
    b1.originate(N("198.51.100.0/24"))
    loop.advance(2)
    assert N("203.0.113.0/24") not in b2.loc_rib  # rejected by policy
    best = b2.loc_rib[N("198.51.100.0/24")][0]
    assert best.attrs.med == 500  # rewritten by set-metric


def test_community_set_match_and_set_actions():
    """ietf-bgp-policy: match-community-set (any/all/invert) and
    set-community add/remove/replace through the BGP import hook."""
    from holo_tpu.protocols.bgp import PathAttrs
    from holo_tpu.utils.policy import PolicyEngine, parse_community

    assert parse_community("65001:100") == (65001 << 16) | 100

    eng = PolicyEngine()
    eng.load_from_config(
        {
            "defined-sets": {
                "community-set": {
                    "cust": {"member": ["65001:100", "65001:200"]},
                }
            },
            "policy-definition": {
                "imp": {
                    "statement": {
                        "10-tag": {
                            "conditions": {"match-community-set": "cust"},
                            "actions": {
                                "set-community": {
                                    "method": "add",
                                    "communities": ["65009:1"],
                                },
                                "set-local-pref": 200,
                                "policy-result": "accept-route",
                            },
                        },
                        "20-rest": {
                            "conditions": {},
                            "actions": {"policy-result": "reject-route"},
                        },
                    }
                }
            },
        }
    )
    hook = eng.bgp_import_hook("imp")
    from ipaddress import IPv4Network as N

    tagged = PathAttrs(communities=(parse_community("65001:100"),))
    out = hook(N("10.0.0.0/24"), tagged)
    assert out is not None and out.local_pref == 200
    assert parse_community("65009:1") in out.communities
    assert parse_community("65001:100") in out.communities  # add keeps

    untagged = PathAttrs()
    assert hook(N("10.1.0.0/24"), untagged) is None  # fell to reject

    # invert + replace: untagged routes match, get stamped.
    eng.load_from_config(
        {
            "defined-sets": {
                "community-set": {"cust": {"member": ["65001:100"]}}
            },
            "policy-definition": {
                "imp": {
                    "statement": {
                        "10": {
                            "conditions": {
                                "match-community-set": "cust",
                                "community-match-options": "invert",
                            },
                            "actions": {
                                "set-community": {
                                    "method": "replace",
                                    "communities": ["65000:999"],
                                },
                                "policy-result": "accept-route",
                            },
                        }
                    }
                }
            },
        }
    )
    hook = eng.bgp_import_hook("imp")
    out = hook(N("10.2.0.0/24"), PathAttrs(communities=(1,)))
    assert out is not None and out.communities == (parse_community("65000:999"),)
    assert hook(N("10.3.0.0/24"), tagged) is None  # tagged inverted away


def test_bgp_condition_and_action_surface():
    """Reference BgpPolicyCondition/-Action parity
    (holo-utils/src/policy.rs:259-386): comparisons, as-path sets,
    neighbor sets, prepend, set-med arithmetic, origin/nexthop edits."""
    from ipaddress import IPv4Network as N

    from holo_tpu.protocols.bgp import Origin, PathAttrs
    from holo_tpu.utils.policy import PolicyEngine, parse_large_community

    eng = PolicyEngine()
    eng.load_from_config(
        {
            "defined-sets": {
                "as-path-set": {"upstreams": {"member": [65100, 65200]}},
                "neighbor-set": {"edge": {"address": ["10.0.0.9"]}},
                "large-community-set": {
                    "lc": {"member": ["65001:1:2"]},
                },
            },
            "policy-definition": {
                "shape": {
                    "statement": {
                        "10-prepend-upstream": {
                            "conditions": {
                                "match-as-path-set": "upstreams",
                                "med": {"value": 50, "op": "le"},
                            },
                            "actions": {
                                "set-as-path-prepend": {"asn": 65001, "repeat": 2},
                                "set-med": {"add": 10},
                                "set-route-origin": "incomplete",
                                "set-next-hop": "192.0.2.9",
                                "set-large-community": {
                                    "method": "add",
                                    "communities": ["65001:1:2"],
                                },
                                "policy-result": "accept-route",
                            },
                        },
                        "20-neighbor-gate": {
                            "conditions": {"match-neighbor-set": "edge"},
                            "actions": {"policy-result": "accept-route"},
                        },
                        "30-long-paths": {
                            "conditions": {
                                "as-path-length": {"value": 5, "op": "ge"}
                            },
                            "actions": {"policy-result": "reject-route"},
                        },
                    }
                }
            },
        }
    )
    hook = eng.bgp_import_hook("shape", neighbor="10.0.0.9")
    # Statement 10: as-path set + med<=50 -> prepend, med+=10, origin,
    # nexthop, large community.
    attrs = PathAttrs(Origin.IGP, (65100,), med=20)
    out = hook(N("10.0.0.0/24"), attrs)
    assert out.as_path == (65001, 65001, 65100)
    assert out.med == 30
    assert out.origin == Origin.INCOMPLETE
    assert str(out.next_hop) == "192.0.2.9"
    assert parse_large_community("65001:1:2") in out.large_communities
    # Statement 20: falls through 10 (med too high), matches neighbor set.
    out2 = hook(N("10.1.0.0/24"), PathAttrs(Origin.IGP, (65300,), med=500))
    assert out2 is not None and out2.as_path == (65300,)
    # A 5-hop path from a non-edge neighbor falls to statement 30: reject.
    hook_other = eng.bgp_import_hook("shape", neighbor="10.0.0.1")
    long_path = PathAttrs(Origin.IGP, (1, 2, 3, 4, 5), med=500)
    assert hook_other(N("10.2.0.0/24"), long_path) is None


def test_engine_attrs_through_hook():
    """The same hook drives the engine's segment-shaped BaseAttrs."""
    from ipaddress import IPv4Network as N

    from holo_tpu.protocols.bgp_engine import AsSegment, BaseAttrs
    from holo_tpu.utils.policy import PolicyEngine

    eng = PolicyEngine()
    eng.load_from_config(
        {
            "policy-definition": {
                "p": {
                    "statement": {
                        "10": {
                            "conditions": {"origin-eq": "igp"},
                            "actions": {
                                "set-as-path-prepend": {"asn": 65009},
                                "set-route-origin": "egp",
                                "policy-result": "accept-route",
                            },
                        }
                    }
                }
            }
        }
    )
    hook = eng.bgp_import_hook("p")
    attrs = BaseAttrs(
        origin="Igp",
        as_path=(AsSegment("Sequence", (65100,)),),
        nexthop="10.0.0.1",
    )
    out = hook(N("10.0.0.0/24"), attrs)
    assert out.origin == "Egp"
    assert out.as_path[0].members == (65009, 65100)

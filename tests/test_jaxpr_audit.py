"""HL3xx jaxpr kernel audit (ISSUE 18): golden fixtures per rule,
seeded mutations of real seams, registry inertness, the per-kernel
cache, and the repo-wide audit-clean gate.

The fixtures build :class:`KernelSpec` rows by hand and drive
``audit_kernel``/``audit_entries`` directly — no registry, no cache —
so each rule's fire/clean/suppressed behavior is proven in isolation.
The mutation tests then take REAL registered kernels and break exactly
one declared contract (drop a donation, unfence a mesh carry, widen a
lane, unbound the bucket budget), proving the audit catches the defect
classes it was built for on the production kernels themselves.
"""

import dataclasses
import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from holo_tpu.analysis import gate_findings, run_audit_cached
from holo_tpu.analysis.kernels import KernelSpec, register_kernel, registry
from holo_tpu.analysis.jaxpr_audit import (
    SEAM_MODULES,
    _audit_mesh,
    apply_suppressions,
    audit_entries,
    audit_kernel,
    load_registry,
    run_audit,
    spec_signature,
)

REPO = Path(__file__).resolve().parent.parent


def _spec(shape=(64,), dtype=jnp.int32):
    return jax.ShapeDtypeStruct(shape, dtype)


def _entry(name, builder, specs, **kw):
    kw.setdefault("buckets", 1)
    kw.setdefault("module", "fixture_mod.py")
    kw.setdefault("line", 3)
    return KernelSpec(name=name, builder=builder, specs=specs, **kw)


def _rules_fired(entry, mesh=None):
    findings, wall = audit_kernel(entry, mesh=mesh)
    assert wall >= 0.0
    return {f.rule for f in findings}, findings


# -- golden fixtures: one flagged + one clean per rule ------------------


def test_clean_kernel_produces_no_findings():
    entry = _entry(
        "fix.clean",
        lambda: jax.jit(lambda x: x + 1, donate_argnums=(0,)),
        lambda: (_spec(),),
        donate=(0,),
    )
    fired, findings = _rules_fired(entry)
    assert fired == set(), [f.render() for f in findings]


def test_hl301_dropped_donation_fires():
    # Mutation shape #1: the wrapper forgets donate_argnums while the
    # registration still declares the donation.
    entry = _entry(
        "fix.donation.dropped",
        lambda: jax.jit(lambda x: x + 1),  # no donate_argnums
        lambda: (_spec(),),
        donate=(0,),
    )
    fired, findings = _rules_fired(entry)
    assert fired == {"HL301"}
    (f,) = findings
    assert f.severity == "error"
    assert "0/1" in f.message


def test_hl301_donated_but_unused_arg_fires():
    # The true-positive class this PR fixed in the incremental
    # multipath seams: a donated argument the kernel never reads is
    # pruned before XLA, so its alias can never realize — the buffer
    # is neither reused nor reclaimed.
    entry = _entry(
        "fix.donation.unused",
        lambda: jax.jit(lambda a, b: a + 1, donate_argnums=(1,)),
        lambda: (_spec(), _spec()),
        donate=(1,),
    )
    fired, _ = _rules_fired(entry)
    assert fired == {"HL301"}


def test_hl301_partial_pytree_donation_counts_leaves():
    # Two donated leaves, only one realized: the finding reports the
    # leaf count, not just the argnum.
    entry = _entry(
        "fix.donation.partial",
        lambda: jax.jit(
            lambda pair: pair[0] + 1, donate_argnums=(0,)
        ),
        lambda: ((_spec(), _spec()),),
        donate=(0,),
    )
    fired, findings = _rules_fired(entry)
    assert fired == {"HL301"}
    assert "1/2" in findings[0].message


def test_hl302_host_callback_fires():
    def kernel(x):
        jax.debug.print("leak {}", x[0])
        return x + 1

    entry = _entry("fix.hostleak", lambda: jax.jit(kernel), lambda: (_spec(),))
    fired, findings = _rules_fired(entry)
    assert fired == {"HL302"}
    assert findings[0].severity == "error"
    assert "debug_callback" in findings[0].message


def test_hl302_pure_callback_fires():
    def kernel(x):
        return jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct((64,), jnp.int32), x
        )

    entry = _entry("fix.purecb", lambda: jax.jit(kernel), lambda: (_spec(),))
    fired, _ = _rules_fired(entry)
    assert "HL302" in fired


def test_hl303_float_mean_in_uint32_plane_fires():
    # Mutation shape #3: a stray jnp.mean in the saturating-uint32
    # plane silently widens to float32.
    entry = _entry(
        "fix.widen",
        lambda: jax.jit(lambda x: (x + jnp.uint32(1), jnp.mean(x))),
        lambda: (_spec(dtype=jnp.uint32),),
    )
    fired, findings = _rules_fired(entry)
    assert fired == {"HL303"}
    (f,) = findings
    assert f.severity == "warn"
    assert "float32" in f.message


def test_hl303_respects_widened_declaration():
    # The same kernel is clean when the registration widens the
    # discipline explicitly (e.g. the FRR SRLG plane's float scoring).
    entry = _entry(
        "fix.widen.ok",
        lambda: jax.jit(lambda x: (x + jnp.uint32(1), jnp.mean(x))),
        lambda: (_spec(dtype=jnp.uint32),),
        dtypes=("int32", "uint32", "bool", "float32"),
    )
    fired, _ = _rules_fired(entry)
    assert fired == set()


def test_hl304_unbounded_buckets_fires():
    # Mutation shape #4: a dispatch seam with no declared shape-bucket
    # bound — unbounded recompiles.
    entry = _entry(
        "fix.unbounded",
        lambda: jax.jit(lambda x: x + 1),
        lambda: (_spec(),),
        buckets=None,
    )
    fired, findings = _rules_fired(entry)
    assert fired == {"HL304"}
    assert "unbounded" in findings[0].message


def test_hl304_over_budget_fires():
    entry = _entry(
        "fix.overbudget",
        lambda: jax.jit(lambda x: x + 1),
        lambda: (_spec(),),
        buckets=80,
        budget=64,
    )
    fired, findings = _rules_fired(entry)
    assert fired == {"HL304"}
    assert "80" in findings[0].message


def test_hl305_missing_fence_fires():
    entry = _entry(
        "fix.unfenced",
        lambda: jax.jit(lambda x: x + 1),
        lambda: (_spec(),),
        fences=1,
    )
    fired, findings = _rules_fired(entry)
    assert fired == {"HL305"}
    assert findings[0].severity == "warn"


def test_hl305_realized_fence_is_clean():
    import numpy as np

    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs >=2 CPU devices (conftest forces 8)")
    mesh = jax.sharding.Mesh(np.array(devices), ("d",))
    sharding = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("d")
    )

    entry = _entry(
        "fix.fenced",
        lambda: jax.jit(
            lambda x: jax.lax.with_sharding_constraint(x + 1, sharding)
        ),
        lambda: (_spec((len(devices) * 8,)),),
        fences=1,
    )
    fired, _ = _rules_fired(entry)
    assert fired == set()


def test_hl305_mesh_needing_kernel_skipped_without_mesh():
    entry = _entry(
        "fix.meshonly",
        lambda mesh: jax.jit(lambda x: x + 1),
        lambda: (_spec(),),
        fences=1,
        needs_mesh=True,
    )
    per_kernel, seconds, skipped = audit_entries([entry], mesh=None)
    assert skipped == ["fix.meshonly"]
    assert per_kernel == {} and seconds == {}


# -- suppression flow ---------------------------------------------------


def test_audit_findings_honor_disable_comments(tmp_path):
    mod = tmp_path / "fixture_mod.py"
    mod.write_text(
        "# fixture seam module\n"
        "# holo-lint: disable=HL304\n"
        "register_kernel_call_site = None\n"
    )
    entry = _entry(
        "fix.suppressed",
        lambda: jax.jit(lambda x: x + 1),
        lambda: (_spec(),),
        buckets=None,  # fires HL304...
        line=3,  # ...anchored under the disable comment on line 2
    )
    findings, _ = audit_kernel(entry)
    live, suppressed = apply_suppressions(findings, str(tmp_path))
    assert live == []
    assert [f.rule for f in suppressed] == ["HL304"]

    # A different rule id on the same line stays live.
    other = dataclasses.replace(entry, name="fix.other", fences=1)
    findings, _ = audit_kernel(other)
    live, suppressed = apply_suppressions(
        [f for f in findings if f.rule == "HL305"], str(tmp_path)
    )
    assert [f.rule for f in live] == ["HL305"]
    assert suppressed == []


# -- seeded mutations of REAL registered kernels ------------------------


def test_mutation_real_incremental_kernel_without_donation():
    # Take the production incremental seam and rebuild its jit WITHOUT
    # donate_argnums: the audit must flag the dropped donation.
    from holo_tpu.ops.spf_engine import spf_one_incremental

    entry = load_registry()["spf.one.incremental"]
    mutated = dataclasses.replace(
        entry,
        builder=lambda: jax.jit(
            lambda g, r, prev, seeds: spf_one_incremental(
                g, r, prev, seeds, None
            )
        ),
    )
    findings, _ = audit_kernel(mutated)
    assert {f.rule for f in findings} == {"HL301"}


def test_mutation_real_sharded_kernel_without_fence():
    # Replace the sharded what-if builder with the UNfenced plain batch
    # kernel (the PR-13 GSPMD miscompile shape): HL305 must fire.
    from holo_tpu.ops.spf_engine import spf_whatif_batch

    mesh = _audit_mesh()
    if mesh is None:
        pytest.skip("needs a multi-device CPU mesh (conftest forces 8)")
    entry = load_registry()["spf.shard.whatif"]
    mutated = dataclasses.replace(
        entry,
        builder=lambda m: jax.jit(
            lambda g, r, ms: spf_whatif_batch(g, r, ms, None, engine="seq")
        ),
    )
    findings, _ = audit_kernel(mutated, mesh=mesh)
    assert "HL305" in {f.rule for f in findings}


def test_mutation_real_kernel_with_unbounded_buckets():
    entry = load_registry()["spf.tropical.one"]
    mutated = dataclasses.replace(entry, buckets=None)
    findings, _ = audit_kernel(mutated)
    assert {f.rule for f in findings} == {"HL304"}


# -- registry: inert outside audit mode ---------------------------------


def _restore_registry(saved):
    from holo_tpu.analysis import kernels

    kernels._REGISTRY.clear()
    kernels._REGISTRY.update(saved)


def test_registration_never_invokes_thunks():
    saved = registry()

    def boom(*a, **k):  # pragma: no cover - the assertion IS the test
        raise AssertionError("audit thunk invoked outside audit mode")

    try:
        register_kernel("test.inert", builder=boom, specs=boom, buckets=1)
        entry = registry()["test.inert"]
        assert entry.builder is boom
        assert entry.specs is boom
        # The call site anchors like an AST finding would.
        assert entry.module == "tests/test_jaxpr_audit.py"
        assert entry.line > 0
    finally:
        _restore_registry(saved)


def test_register_decorator_form_and_overwrite():
    saved = registry()
    try:

        @register_kernel("test.deco", specs=lambda: (), buckets=1)
        def build():  # pragma: no cover - never invoked
            raise AssertionError("invoked")

        assert registry()["test.deco"].builder is build
        assert registry()["test.deco"].module == "tests/test_jaxpr_audit.py"

        # Re-registration under the same name overwrites (idempotent
        # module re-imports).
        register_kernel(
            "test.deco", builder=build, specs=lambda: (), buckets=2
        )
        assert registry()["test.deco"].buckets == 2
    finally:
        _restore_registry(saved)


def test_every_seam_module_registers_kernels():
    entries = load_registry()
    assert len(entries) >= 30
    by_module = {e.module for e in entries.values()}
    for mod in SEAM_MODULES:
        rel = mod.replace(".", "/") + ".py"
        assert rel in by_module, f"no kernels registered from {rel}"
    # Every anchor points at a real line of a real file.
    for e in entries.values():
        src = (REPO / e.module).read_text().splitlines()
        assert 0 < e.line <= len(src), (e.name, e.module, e.line)


def test_spec_signature_is_stable_and_contract_sensitive():
    entries = load_registry()
    entry = entries["spf.one.incremental"]
    assert spec_signature(entry) == spec_signature(entry)
    widened = dataclasses.replace(entry, donate=())
    assert spec_signature(widened) != spec_signature(entry)
    rebudgeted = dataclasses.replace(entry, buckets=8)
    assert spec_signature(rebudgeted) != spec_signature(entry)


# -- the repo-wide gate -------------------------------------------------


def test_repo_audit_error_tier_is_clean():
    """ISSUE 18 acceptance: every registered kernel lowers and passes
    HL301/HL302 with the error-tier baseline kept empty."""
    result = run_audit_cached(REPO)
    assert result.kernels_checked >= 30
    assert result.skipped == [], result.skipped
    errors = gate_findings(result.findings)
    assert errors == [], "\n".join(f.render() for f in errors)


def test_repo_audit_currently_warn_clean():
    # Not a permanent contract (HL303/304/305 soak at warn), but today
    # the tree is fully clean — a new warn finding should be a
    # deliberate decision, not drift.
    result = run_audit_cached(REPO)
    assert result.findings == [], "\n".join(
        f.render() for f in result.findings
    )


# -- the per-kernel cache -----------------------------------------------


def test_audit_cache_cold_then_warm(tmp_path):
    cache = tmp_path / "audit_cache.json"
    cold = run_audit_cached(REPO, cache_path=cache, no_cache=False)
    assert cache.exists()
    assert cold.kernels_checked >= 30

    warm = run_audit_cached(REPO, cache_path=cache)
    assert warm.kernels_cached == warm.kernels_checked == (
        cold.kernels_checked
    )
    assert [f.render() for f in warm.findings] == [
        f.render() for f in cold.findings
    ]
    assert set(warm.kernel_seconds) == set(cold.kernel_seconds)


def test_audit_cache_no_cache_bypasses_read_and_write(tmp_path):
    cache = tmp_path / "audit_cache.json"
    run_audit_cached(REPO, cache_path=cache)
    before = cache.read_bytes()
    fresh = run_audit_cached(REPO, cache_path=cache, no_cache=True)
    assert fresh.kernels_cached == 0  # full re-lowering
    assert cache.read_bytes() == before  # and no rewrite


def test_audit_cache_per_kernel_fingerprint_reuse(tmp_path):
    """Corrupt ONE kernel's fingerprint in the cache document and break
    the fully-warm fast path: only that kernel re-lowers; the rest
    replay from their per-kernel rows."""
    cache = tmp_path / "audit_cache.json"
    run_audit_cached(REPO, cache_path=cache)
    doc = json.loads(cache.read_text())
    victim = sorted(doc["kernels"])[0]
    doc["kernels"][victim]["fingerprint"] = "stale"
    # Invalidate a recorded file stat so the warm fast path falls
    # through to the armed (fingerprint-checking) path.
    a_file = sorted(doc["files"])[0]
    doc["files"][a_file]["mtime_ns"] = 1
    doc["files"][a_file]["size"] = 1
    doc["files"][a_file]["sha256"] = "not-the-real-hash"
    cache.write_text(json.dumps(doc))

    result = run_audit_cached(REPO, cache_path=cache)
    assert result.kernels_cached == result.kernels_checked - 1


def test_warm_audit_replay_never_imports_jax():
    """The fully-warm path must stay jax-free: that is what keeps the
    warm lint gate near the AST-only wall time."""
    # Warm the default cache (what the gate itself uses).
    run_audit_cached(REPO)
    probe = (
        "import sys\n"
        "from pathlib import Path\n"
        "from holo_tpu.analysis import run_audit_cached\n"
        f"res = run_audit_cached(Path({str(REPO)!r}))\n"
        "assert res.kernels_checked >= 30, res.kernels_checked\n"
        "assert res.kernels_cached == res.kernels_checked\n"
        "assert 'jax' not in sys.modules, 'warm replay imported jax'\n"
        "print('ok')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", probe],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ok" in proc.stdout


def test_self_check_audit_arm_is_faithful():
    from holo_tpu.analysis import self_check

    mismatches = self_check([REPO / "holo_tpu"], root=REPO, audit=True)
    assert not mismatches, "\n".join(mismatches)

"""Raw-socket IO over real kernel interfaces (root-gated).

The flagship check: two REAL OSPF instances in network namespaces wired by
a veth pair exchange REAL protocol packets through raw sockets and reach
FULL adjacency — the production transport path end to end.
"""

import os
import subprocess

import pytest

pytestmark = pytest.mark.skipif(
    os.geteuid() != 0 or not os.path.exists("/proc/net/netlink"),
    reason="requires root + netlink",
)


def sh(cmd, check=True):
    return subprocess.run(cmd, shell=True, check=check, capture_output=True,
                          text=True)


NS = "htpu-test-ns"


@pytest.fixture
def netns_veth():
    """veth pair with one end moved into a fresh network namespace —
    packets genuinely cross the virtual wire (same-netns veth pairs
    short-circuit through the local stack)."""
    sh(f"ip netns del {NS} 2>/dev/null", check=False)
    sh("ip link del vhtpu0 2>/dev/null", check=False)
    sh(f"ip netns add {NS}")
    sh("ip link add vhtpu0 type veth peer name vhtpu1")
    sh(f"ip link set vhtpu1 netns {NS}")
    sh("ip addr add 10.99.0.1/30 dev vhtpu0")
    sh("ip link set vhtpu0 up")
    sh(f"ip netns exec {NS} ip addr add 10.99.0.2/30 dev vhtpu1")
    sh(f"ip netns exec {NS} ip link set vhtpu1 up")
    sh(f"ip netns exec {NS} ip link set lo up")
    yield ("vhtpu0", "vhtpu1")
    sh("ip link del vhtpu0", check=False)
    sh(f"ip netns del {NS}", check=False)


def test_raw_ospf_adjacency_over_netns_veth(netns_veth):
    """The production transport end to end: our instance (raw sockets +
    C++ epoll poller) peers with another instance running inside a network
    namespace, over a real veth wire."""
    import sys
    import time
    from pathlib import Path

    from ipaddress import IPv4Address as A
    from ipaddress import IPv4Network as N

    from holo_tpu.protocols.ospf.instance import (
        IfConfig, IfUpMsg, InstanceConfig, OspfInstance,
    )
    from holo_tpu.protocols.ospf.interface import IfType
    from holo_tpu.protocols.ospf.neighbor import NsmState
    from holo_tpu.utils.ip import ALL_SPF_RTRS_V4
    from holo_tpu.utils.native_runtime import EPOLLIN, NativePoller
    from holo_tpu.utils.rawsock import RawSocketIo
    from holo_tpu.utils.runtime import EventLoop

    a_if, b_if = netns_veth
    peer_script = Path(__file__).parent / "_ospf_netns_peer.py"
    peer = subprocess.Popen(
        ["ip", "netns", "exec", NS, sys.executable, str(peer_script),
         b_if, "2.2.2.2", "10.99.0.2/30", "25"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        loop = EventLoop()  # real clock
        io = RawSocketIo(loop)
        r1 = OspfInstance(
            name="r1", config=InstanceConfig(router_id=A("1.1.1.1")), netio=io
        )
        loop.register(r1)
        cfg = IfConfig(if_type=IfType.POINT_TO_POINT, cost=5,
                       hello_interval=1, dead_interval=4)
        r1.add_interface(a_if, cfg, N("10.99.0.0/30"), A("10.99.0.1"))
        io.open_interface(a_if, "r1", [ALL_SPF_RTRS_V4])
        poller = NativePoller()
        for fd in io.fds():
            poller.add(fd, EPOLLIN)
        loop.send("r1", IfUpMsg(a_if))

        deadline = time.monotonic() + 20.0
        full = False
        while time.monotonic() < deadline and not full:
            loop.run_until_idle()
            for fd, _ in poller.wait(50):
                io.pump(fd)
            nbrs = r1.areas[A("0.0.0.0")].interfaces[a_if].neighbors
            full = any(n.state == NsmState.FULL for n in nbrs.values())
        assert full, "adjacency never reached FULL over the netns veth"
        # The peer's stub prefix arrived via real flooding.
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and N("10.99.0.0/30") not in r1.routes:
            loop.run_until_idle()
            for fd, _ in poller.wait(50):
                io.pump(fd)
        assert N("10.99.0.0/30") in r1.routes
    finally:
        out, err = peer.communicate(timeout=30)
    assert "FULL 1.1.1.1" in out, f"peer never saw us: {out!r} {err[-400:]!r}"

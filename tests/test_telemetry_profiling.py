"""Deep profiling + flight recorder (ISSUE 5): per-dispatch sub-span
nesting, compile-time cost-analysis capture per shape bucket, histogram
exemplars + OpenMetrics rendering, flight-recorder ring/postmortem
mechanics, and gNMI STREAM sampled-interval pushes."""

import json
import socket
import threading
import time

import pytest

from holo_tpu import telemetry
from holo_tpu.telemetry import flight, profiling
from holo_tpu.telemetry.prometheus import render_text
from holo_tpu.telemetry.registry import MetricsRegistry


@pytest.fixture
def profiled():
    """Arm device profiling for one test and always disarm after."""
    profiling.set_device_profiling(True)
    try:
        yield
    finally:
        profiling.set_device_profiling(False)


def _stage_counts():
    snap = telemetry.snapshot(prefix="holo_profile_stage_seconds")
    return {k: v["count"] for k, v in snap.items()}


# -- sub-span nesting ----------------------------------------------------


def test_dispatch_splits_into_nested_subspans(profiled):
    """A profiled SPF dispatch yields marshal/device/readback sub-spans
    nested under the spf.dispatch span, and one stage-histogram
    observation each."""
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.spf.synth import grid_topology

    topo = grid_topology(4, 4, seed=1)
    backend = TpuSpfBackend()
    tracer = telemetry.tracer()
    before_spans = len(tracer.spans())
    before_counts = _stage_counts()
    backend.compute(topo)
    spans = tracer.spans()[before_spans:]
    by_name = {}
    for s in spans:
        by_name.setdefault(s.name, s)
    dispatch = by_name["spf.dispatch"]
    for stage_name in ("marshal", "device", "readback"):
        sub = by_name[f"spf.one.{stage_name}"]
        assert sub.parent_id == dispatch.span_id, stage_name
        assert sub.attrs["stage"] == stage_name
        key = (
            f"holo_profile_stage_seconds"
            f"{{site=spf.one,stage={stage_name},device=-}}"
        )
        assert _stage_counts()[key] == before_counts.get(key, 0) + 1

    # Disarmed: the same dispatch emits no sub-spans and no stage rows.
    profiling.set_device_profiling(False)
    before_spans = len(tracer.spans())
    counts = _stage_counts()
    backend.compute(topo)
    names = {s.name for s in tracer.spans()[before_spans:]}
    assert names == {"spf.dispatch"}
    assert _stage_counts() == counts


def test_frr_dispatch_profiled_subspans(profiled):
    from holo_tpu.frr.manager import FrrEngine
    from holo_tpu.spf.synth import grid_topology

    topo = grid_topology(4, 4, seed=2)
    tracer = telemetry.tracer()
    before = len(tracer.spans())
    FrrEngine("tpu").compute(topo)
    spans = tracer.spans()[before:]
    by_name = {s.name: s for s in spans}
    dispatch = by_name["frr.dispatch"]
    for stage_name in ("marshal", "device", "readback"):
        assert by_name[f"frr.batch.{stage_name}"].parent_id == dispatch.span_id


# -- compile-time cost analysis -----------------------------------------


def test_cost_analysis_captured_per_shape_bucket(profiled):
    """One cost-table entry per fresh (engine, shape) bucket, exactly
    mirroring the jit cache: a re-run on a seen shape adds nothing, a
    new topology shape adds one."""
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.spf.synth import grid_topology

    profiling.clear_cost_table()
    backend = TpuSpfBackend()
    t4 = grid_topology(4, 4, seed=1)
    t5 = grid_topology(5, 5, seed=1)
    backend.compute(t4)
    one_buckets = [k for k in profiling.cost_table() if k[0] == "spf.one"]
    assert len(one_buckets) == 1
    backend.compute(t4)  # same shape: jit cache hit, no new capture
    assert len([k for k in profiling.cost_table() if k[0] == "spf.one"]) == 1
    backend.compute(t5)  # fresh shape bucket
    table = profiling.cost_table()
    one_buckets = [k for k in table if k[0] == "spf.one"]
    assert len(one_buckets) == 2
    for key in one_buckets:
        assert table[key]["flops"] > 0
        assert table[key]["bytes"] > 0
    # The per-site gauges track the last-compiled bucket.
    snap = telemetry.snapshot(prefix="holo_profile_cost")
    assert snap["holo_profile_cost_flops{site=spf.one}"] > 0


def test_cost_analysis_disarmed_is_free():
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.spf.synth import grid_topology

    profiling.clear_cost_table()
    TpuSpfBackend().compute(grid_topology(4, 4, seed=3))
    assert profiling.cost_table() == {}


# -- exemplars -----------------------------------------------------------


def test_histogram_exemplar_attachment_and_rendering():
    """Exemplars land in the bucket the observation fell into and render
    in OpenMetrics syntax after the bucket count — but ONLY under the
    OpenMetrics mode: the classic 0.0.4 grammar rejects the suffix, so
    the default render must stay exemplar-free."""
    reg = MetricsRegistry()
    h = reg.histogram("holo_x_lat_seconds", "lat", buckets=(0.1, 1.0))
    h.observe(0.05, exemplar={"span_id": 7})
    h.observe(0.5)  # no exemplar: bucket renders bare
    h.observe(0.7, exemplar={"span_id": 9})
    ex = h.labels().exemplars()
    assert ex[0.1] == ((("span_id", "7"),), 0.05)
    assert ex[1.0] == ((("span_id", "9"),), 0.7)
    text = render_text(reg, openmetrics=True)
    assert 'holo_x_lat_seconds_bucket{le="0.1"} 1 # {span_id="7"} 0.05' in text
    assert 'holo_x_lat_seconds_bucket{le="1"} 3 # {span_id="9"} 0.7' in text
    assert 'le="+Inf"} 3\n' in text  # untouched buckets render bare
    assert "# {" not in render_text(reg)  # 0.0.4 scrape stays clean


def test_metrics_endpoint_negotiates_openmetrics_exemplars():
    """The HTTP endpoint serves 0.0.4 (no exemplars) by default and
    OpenMetrics (+ exemplars + # EOF) when the scraper Accepts it."""
    import urllib.request

    from holo_tpu.telemetry.prometheus import start_http_server

    reg = MetricsRegistry()
    h = reg.histogram("holo_neg_lat_seconds", buckets=(0.1,))
    h.observe(0.05, exemplar={"span_id": 3})
    server = start_http_server(reg, "127.0.0.1:0")
    try:
        port = server.server_address[1]
        url = f"http://127.0.0.1:{port}/metrics"
        plain = urllib.request.urlopen(url)
        body = plain.read().decode()
        assert "# {" not in body and "# EOF" not in body
        assert "version=0.0.4" in plain.headers["Content-Type"]
        req = urllib.request.Request(
            url, headers={"Accept": "application/openmetrics-text"}
        )
        om = urllib.request.urlopen(req)
        body = om.read().decode()
        assert '# {span_id="3"} 0.05' in body
        assert body.endswith("# EOF\n")
        assert "openmetrics-text" in om.headers["Content-Type"]
    finally:
        server.shutdown()
        server.server_close()


def test_profiled_dispatch_exemplars_link_to_subspans(profiled):
    """The stage histogram's exemplars carry span ids that exist in the
    tracer ring as the matching sub-spans — the bucket→trace join."""
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.spf.synth import grid_topology

    backend = TpuSpfBackend()
    backend.compute(grid_topology(4, 4, seed=4))
    fam = telemetry.histogram(
        "holo_profile_stage_seconds", labelnames=("site", "stage", "device")
    )
    child = fam.labels(site="spf.one", stage="marshal", device="-")
    exemplars = child.exemplars()
    assert exemplars, "profiled dispatch must attach an exemplar"
    span_ids = {
        s.span_id
        for s in telemetry.tracer().spans()
        if s.name == "spf.one.marshal"
    }
    for labels, _value in exemplars.values():
        assert dict(labels).keys() == {"span_id"}
        assert int(dict(labels)["span_id"]) in span_ids
    # And the OpenMetrics scrape surface carries the join.
    assert "# {span_id=" in render_text(telemetry.registry(), openmetrics=True)


# -- flight recorder -----------------------------------------------------


def test_flight_ring_bounded_and_renumbered(tmp_path):
    """Ring stays bounded; span ids renumber relative to the first
    recorded span so seeded runs produce identical bundles; journal
    marks and events carry the injected clock's stamps."""
    t = [0.0]
    rec = flight.FlightRecorder(
        capacity=4, postmortem_dir=tmp_path, clock=lambda: t[0]
    )
    tracer = telemetry.tracer()
    tracer.on_complete = rec.note_span
    try:
        with telemetry.span("warm"):
            pass
        for i in range(6):
            t[0] = float(i)
            rec.journal_mark(i, "r1")
        ring = rec.snapshot_ring()
        assert len(ring) == 4  # bounded: oldest entries fell off
        assert ring[0][0] == "journal" and ring[0][1] == 2
        with telemetry.span("s2"):
            pass
        first_span = next(e for e in rec.snapshot_ring() if e[0] == "span")
        assert first_span[2] == 1  # renumbered: warm was span 0, s2 is 1
        rec.event("breaker", breaker="spf-dispatch#3", to="open")
        path, bundle = rec.postmortem("breaker-open:spf-dispatch#3")
        assert path is not None and path.exists()
        assert bundle["reason"] == "breaker-open:spf-dispatch"  # scrubbed
        ev = next(e for e in bundle["ring"] if e[0] == "event")
        assert ev[2]["breaker"] == "spf-dispatch"
        assert bundle["journal-tail"][-1] == [5, "r1"]
        assert json.loads(path.read_text()) == bundle
    finally:
        tracer.on_complete = None


def test_flight_metric_deltas_are_counter_counts_only():
    """The bundle metric section carries counter/histogram-count deltas
    since arm time — no gauges, no wall-time sums."""
    c = telemetry.counter("holo_fx_events_total")
    g = telemetry.gauge("holo_fx_depth")
    h = telemetry.histogram("holo_fx_lat_seconds")
    c.inc(2)
    rec = flight.FlightRecorder(capacity=8)
    c.inc(3)
    g.set(99)
    h.observe(0.25)
    deltas = rec.metric_deltas()
    assert deltas["holo_fx_events_total"] == 3  # delta, not absolute
    assert deltas["holo_fx_lat_seconds"] == 1  # count delta only
    assert not any(k.startswith("holo_fx_depth") for k in deltas)


def test_flight_postmortem_debounced_per_reason(tmp_path):
    """A flapping breaker re-opening every few seconds must not fill
    the disk: repeat dumps for one reason inside min_dump_interval are
    suppressed; a different reason (or the window expiring) dumps."""
    t = [0.0]
    rec = flight.FlightRecorder(
        capacity=16, postmortem_dir=tmp_path, clock=lambda: t[0],
        min_dump_interval=60.0,
    )
    p1, b1 = rec.postmortem("breaker-open:spf")
    assert p1 is not None and b1 is not None
    t[0] = 10.0
    assert rec.postmortem("breaker-open:spf") == (None, None)  # debounced
    p2, _ = rec.postmortem("crash-loop:r1")  # distinct reason: dumps
    assert p2 is not None
    t[0] = 75.0
    p3, _ = rec.postmortem("breaker-open:spf")  # window expired
    assert p3 is not None
    assert len(sorted(tmp_path.glob("postmortem-*.json"))) == 3


def test_flight_trigger_disarmed_is_noop(tmp_path):
    flight.configure(entries=0)
    assert flight.trigger("breaker-open:x") is None
    assert not list(tmp_path.iterdir())


# -- gNMI STREAM sampling ------------------------------------------------


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _stream(cli, gs, *subs):
    """Subscribe STREAM with the given Subscription protos; returns the
    response iterator."""
    req = gs.pb.SubscribeRequest()
    req.subscribe.mode = gs.pb.SubscriptionList.STREAM
    for s in subs:
        req.subscribe.subscription.add().CopyFrom(s)
    return cli.Subscribe(iter([req]))


def _collect(stream, n_notifs, timeout=8.0):
    """First ``n_notifs`` non-sync sampled/heartbeat notifications
    (update messages whose updates carry real paths)."""
    got = []
    done = threading.Event()

    def run():
        for m in stream:
            if (
                m.HasField("update")
                and m.update.update
                and m.update.update[0].path.elem
            ):
                got.append(m.update)
                if len(got) >= n_notifs:
                    done.set()
                    return

    t = threading.Thread(target=run, daemon=True)
    t.start()
    done.wait(timeout)
    return got


def test_gnmi_sample_stream_pushes_metric_leaves():
    """SAMPLE + sample_interval pushes periodic holo-telemetry leaf
    updates (typed, per-leaf paths) without any state change."""
    import holo_tpu.daemon.gnmi_server as gs
    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    marker = telemetry.counter("holo_sample_seen_total")
    marker.inc(5)
    d = Daemon(loop=EventLoop(clock=VirtualClock()), name="smp")
    port = _free_port()
    server = gs.serve_gnmi(d, f"127.0.0.1:{port}")
    try:
        cli = gs.GnmiClient(f"127.0.0.1:{port}")
        sub = gs.pb.Subscription()
        sub.path.CopyFrom(gs.str_to_path("holo-telemetry"))
        sub.mode = gs.pb.SAMPLE
        sub.sample_interval = 60_000_000  # 60ms
        notifs = _collect(_stream(cli, gs, sub), 2)
        assert len(notifs) >= 2, "two sampled intervals must push"
        by_path = {
            gs.path_to_str(u.path): u.val for u in notifs[0].update
        }
        key = "holo-telemetry/metric[holo_sample_seen_total]/value"
        assert by_path[key].WhichOneof("value") == "double_val"
        assert by_path[key].double_val == 5.0
        assert all(
            p.startswith("holo-telemetry") for p in by_path
        ), "subscription path must scope the push"
        snap = telemetry.snapshot(prefix="holo_gnmi_sample")
        assert snap.get("holo_gnmi_sample_updates_total{mode=sample}", 0) > 0
    finally:
        server.stop(grace=0)


def test_gnmi_sample_suppress_redundant_with_heartbeat():
    """suppress_redundant drops unchanged leaves from sampled pushes; a
    value change resumes them; the heartbeat resends regardless."""
    import holo_tpu.daemon.gnmi_server as gs
    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    marker = telemetry.counter("holo_suppress_probe_total")
    marker.inc()
    d = Daemon(loop=EventLoop(clock=VirtualClock()), name="sup")
    port = _free_port()
    server = gs.serve_gnmi(d, f"127.0.0.1:{port}")
    try:
        cli = gs.GnmiClient(f"127.0.0.1:{port}")
        leaf = "holo-telemetry/metric[holo_suppress_probe_total]/value"
        sub = gs.pb.Subscription()
        sub.path.CopyFrom(gs.str_to_path(leaf))
        sub.mode = gs.pb.SAMPLE
        sub.sample_interval = 50_000_000  # 50ms
        sub.suppress_redundant = True
        sub.heartbeat_interval = 1_000_000_000  # 1s

        stream = _stream(cli, gs, sub)
        first = _collect(stream, 1)
        assert len(first) == 1  # initial sample: leaf sent once
        # Unchanged: further samples are suppressed until the value
        # moves.  Poke the counter and the next sample resumes.
        time.sleep(0.2)
        marker.inc()
        more = _collect(stream, 1)
        assert more, "changed leaf must be sampled again"
        vals = [u.val.double_val for u in more[0].update]
        assert vals == [2.0]
        # Heartbeat: with no further change, the 1s beat resends the
        # unchanged leaf (sampled suppression alone would stay silent).
        beat = _collect(stream, 1, timeout=4.0)
        assert beat, "heartbeat must resend unchanged leaves"
        assert [u.val.double_val for u in beat[0].update] == [2.0]
        snap = telemetry.snapshot(prefix="holo_gnmi_sample")
        assert (
            snap.get("holo_gnmi_sample_updates_total{mode=heartbeat}", 0) > 0
        )
    finally:
        server.stop(grace=0)


def test_gnmi_on_change_heartbeat_resends_unchanged_leaves():
    """ON_CHANGE + heartbeat_interval: no state changes at all, yet the
    subscriber sees the leaf at every beat (the satellite fix — before,
    heartbeat_interval was silently ignored)."""
    import holo_tpu.daemon.gnmi_server as gs
    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    telemetry.counter("holo_onchange_probe_total").inc(4)
    d = Daemon(loop=EventLoop(clock=VirtualClock()), name="hb")
    port = _free_port()
    server = gs.serve_gnmi(d, f"127.0.0.1:{port}")
    try:
        cli = gs.GnmiClient(f"127.0.0.1:{port}")
        leaf = "holo-telemetry/metric[holo_onchange_probe_total]/value"
        sub = gs.pb.Subscription()
        sub.path.CopyFrom(gs.str_to_path(leaf))
        sub.mode = gs.pb.ON_CHANGE
        sub.heartbeat_interval = 80_000_000  # 80ms
        notifs = _collect(_stream(cli, gs, sub), 2)
        assert len(notifs) >= 2, "two heartbeats must fire"
        for n in notifs:
            assert [gs.path_to_str(u.path) for u in n.update] == [leaf]
            assert n.update[0].val.double_val == 4.0
    finally:
        server.stop(grace=0)

"""Next-hop tracking: registration, longest-prefix resolution, updates."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.routing.rib import MockKernel, NhtRegister, NhtUpd, RibManager
from holo_tpu.utils.ibus import TOPIC_NHT_UPD, Ibus
from holo_tpu.utils.runtime import Actor, EventLoop, VirtualClock
from holo_tpu.utils.southbound import Nexthop, Protocol, RouteKeyMsg, RouteMsg


class Sink(Actor):
    name = "sink"

    def __init__(self):
        self.updates = []

    def handle(self, msg):
        if isinstance(msg.payload, NhtUpd):
            self.updates.append(msg.payload)


def test_nht_lifecycle():
    loop = EventLoop(clock=VirtualClock())
    ibus = Ibus(loop)
    rib = RibManager(ibus, MockKernel())
    loop.register(rib, name="routing-rib")
    sink = Sink()
    loop.register(sink)
    ibus.subscribe(TOPIC_NHT_UPD, "sink")

    # Register before any route exists: immediate "unreachable".
    ibus.request("routing-rib", NhtRegister(A("10.9.9.9")), sender="sink")
    loop.run_until_idle()
    assert sink.updates[-1].reachable is False

    # A covering route appears: update fires with the resolving prefix.
    rib.route_add(RouteMsg(Protocol.OSPFV2, N("10.9.0.0/16"), 110, 7,
                           frozenset({Nexthop(addr=A("10.0.0.2"))})))
    loop.run_until_idle()
    assert sink.updates[-1].reachable is True
    assert sink.updates[-1].via_prefix == N("10.9.0.0/16")

    # A more specific route takes over: update with the new prefix.
    rib.route_add(RouteMsg(Protocol.STATIC, N("10.9.9.0/24"), 1, 0,
                           frozenset({Nexthop(addr=A("10.0.0.3"))})))
    loop.run_until_idle()
    assert sink.updates[-1].via_prefix == N("10.9.9.0/24")

    # No change -> no spurious update.
    n = len(sink.updates)
    rib.route_add(RouteMsg(Protocol.RIPV2, N("172.16.0.0/16"), 120, 1,
                           frozenset({Nexthop(addr=A("10.0.0.4"))})))
    loop.run_until_idle()
    assert len(sink.updates) == n

    # Both covering routes vanish: unreachable again.
    rib.route_del(RouteKeyMsg(Protocol.STATIC, N("10.9.9.0/24")))
    rib.route_del(RouteKeyMsg(Protocol.OSPFV2, N("10.9.0.0/16")))
    loop.run_until_idle()
    assert sink.updates[-1].reachable is False

"""BGP over real TCP: framing, MP-BGP (IPv6 unicast), TCP-MD5.

Sessions run over loopback addresses (127.0.x.y) with a non-privileged
port — the same BgpTcpIo + instance code path the daemon binds to port
179.  Reference: holo-bgp/src/network.rs, af.rs:25,59-62,
holo-utils/src/socket.rs:38-53.
"""

import socket
import time
from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N
from ipaddress import IPv6Address as A6
from ipaddress import IPv6Network as N6

import pytest

from holo_tpu.protocols.bgp import (
    BgpInstance,
    PathAttrs,
    PeerConfig,
    PeerState,
    UpdateMsg,
    decode_msg,
    encode_msg,
)
from holo_tpu.utils.runtime import EventLoop, RealClock
from holo_tpu.utils.tcpio import BgpTcpIo, pump_once, set_md5sig

PORT = 17901


def test_mp_update_roundtrip():
    upd = UpdateMsg(
        withdrawn=[N("10.1.0.0/16")],
        attrs=PathAttrs(as_path=(65001,), next_hop=A("10.0.0.1"),
                        nh6=A6("fd00::1")),
        nlri=[N("10.2.0.0/16")],
        nlri6=[N6("fd00:2::/48"), N6("fd00:3::/64")],
        withdrawn6=[N6("fd00:dead::/32")],
    )
    t, out = decode_msg(encode_msg(upd))
    assert out.withdrawn == [N("10.1.0.0/16")]
    assert out.nlri == [N("10.2.0.0/16")]
    assert out.nlri6 == [N6("fd00:2::/48"), N6("fd00:3::/64")]
    assert out.withdrawn6 == [N6("fd00:dead::/32")]
    assert out.attrs.nh6 == A6("fd00::1")
    assert out.attrs.next_hop == A("10.0.0.1")
    assert out.attrs.as_path == (65001,)


def _mk_speaker(loop, name, asn, rid, local_ip, port=PORT):
    io = BgpTcpIo(loop, name, port=port)
    inst = BgpInstance(name, asn, A(rid), io)
    loop.register(inst)
    io.listen(local_ip)
    return inst, io


def _peer(inst, io, local_ip, peer_ip, remote_as, md5_key=None, **kw):
    cfg = PeerConfig(
        addr=__import__("ipaddress").ip_address(peer_ip),
        remote_as=remote_as,
        ifname="tcp",
        hold_time=15,
        connect_retry=0.3,
        **kw,
    )
    inst.add_peer(cfg, __import__("ipaddress").ip_address(local_ip))
    io.add_peer(local_ip, peer_ip, md5_key=md5_key)
    inst.start_peer(cfg.addr)


def _drive(loop, ios, until, timeout=12.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        pump_once(ios, timeout_ms=20)
        loop.run_until_idle()
        if until():
            return True
    return False


def test_ebgp_ibgp_chain_over_tcp_v4_and_v6():
    """r1 --iBGP-- r2 --eBGP-- r3: v4 and v6 routes cross both sessions."""
    loop = EventLoop(clock=RealClock())
    r1, io1 = _mk_speaker(loop, "r1", 65001, "1.1.1.1", "127.0.1.1")
    r2, io2 = _mk_speaker(loop, "r2", 65001, "2.2.2.2", "127.0.1.2")
    r3, io3 = _mk_speaker(loop, "r3", 65002, "3.3.3.3", "127.0.1.3")
    io2.listen("127.0.2.2")  # second address for the eBGP leg

    _peer(r1, io1, "127.0.1.1", "127.0.1.2", 65001)
    _peer(r2, io2, "127.0.1.2", "127.0.1.1", 65001)
    _peer(r2, io2, "127.0.2.2", "127.0.1.3", 65002)
    _peer(r3, io3, "127.0.1.3", "127.0.2.2", 65001)
    # v6 next-hop sources for MP routes carried over the v4 sessions
    for r, nh in ((r1, "fd00::1"), (r2, "fd00::2"), (r3, "fd00::3")):
        r.set_local_addr6("tcp", A6(nh))

    ios = [io1, io2, io3]
    assert _drive(
        loop,
        ios,
        lambda: all(
            p.state == PeerState.ESTABLISHED
            for inst in (r1, r2, r3)
            for p in inst.peers.values()
        ),
    ), "sessions did not establish"

    r1.originate(N("10.10.0.0/16"))
    r1.originate(N6("fd00:10::/32"))
    r3.originate(N("10.30.0.0/16"))
    loop.run_until_idle()

    assert _drive(
        loop,
        ios,
        lambda: N("10.10.0.0/16") in r3.loc_rib
        and N6("fd00:10::/32") in r3.loc_rib
        and N("10.30.0.0/16") in r1.loc_rib,
    ), "routes did not propagate"

    # eBGP hop prepended exactly once along the chain
    best_v4 = r3.loc_rib[N("10.10.0.0/16")][0]
    assert best_v4.attrs.as_path == (65001,)
    best_v6 = r3.loc_rib[N6("fd00:10::/32")][0]
    assert best_v6.attrs.as_path == (65001,)
    assert best_v6.attrs.nh6 == A6("fd00::2")  # set by r2 at the AS edge
    back = r1.loc_rib[N("10.30.0.0/16")][0]
    assert back.attrs.as_path == (65002,)

    # withdraw crosses the wire too
    del r1.originated[N6("fd00:10::/32")]
    r1._decision(N6("fd00:10::/32"))
    assert _drive(loop, ios, lambda: N6("fd00:10::/32") not in r3.loc_rib)
    for io in ios:
        io.close()


def test_chaos_tcp_resets_and_partial_writes_reconverge():
    """ISSUE 9 satellite: seeded FaultPlan chaos over the BGP TCP
    transport — injected connection resets (identical surface to a
    peer RST) and partial writes (sends capped to a few bytes, so the
    length-delimited framing must reassemble across arbitrary
    fragmentation) while routes are being exchanged.  Once the plan
    disarms, the deterministic role split re-establishes the session
    and ``_advertise_all`` resends the Adj-RIB-Out: every originated
    route must converge on both speakers — the same final RIB a clean
    run produces."""
    from holo_tpu.resilience.faults import FaultInjector, FaultPlan, inject

    loop = EventLoop(clock=RealClock())
    r1, io1 = _mk_speaker(loop, "c1", 65001, "1.1.1.1", "127.0.5.1", port=17904)
    r2, io2 = _mk_speaker(loop, "c2", 65002, "2.2.2.2", "127.0.5.2", port=17904)
    _peer(r1, io1, "127.0.5.1", "127.0.5.2", 65002)
    _peer(r2, io2, "127.0.5.2", "127.0.5.1", 65001)
    ios = [io1, io2]

    def established():
        return all(
            p.state == PeerState.ESTABLISHED
            for inst in (r1, r2)
            for p in inst.peers.values()
        )

    assert _drive(loop, ios, established), "no initial session"

    nets = [N(f"10.{50 + i}.0.0/16") for i in range(8)]
    plan = FaultPlan(seed=31, tcp_reset_prob=0.04,
                     tcp_partial_write_prob=0.6)
    inj = FaultInjector(plan)
    with inject(inj):
        # Originate under fire: every route announcement rides a
        # transport that keeps fragmenting and resetting under it.
        for i, net in enumerate(nets):
            (r1 if i % 2 == 0 else r2).originate(net)
            _drive(loop, ios, lambda: False, timeout=0.4)
    fired = {k: v for k, v in inj.injected.items() if k.startswith("tcp.")}
    assert fired, "chaos plan never fired a tcp transport seam"

    # Disarmed: session recovers, full Adj-RIB-Out resend reconverges.
    assert _drive(
        loop,
        ios,
        lambda: established()
        and all(n in r1.loc_rib and n in r2.loc_rib for n in nets),
        timeout=25.0,
    ), (
        f"no reconvergence after tcp chaos (fired={fired}; "
        f"r1={sorted(str(n) for n in r1.loc_rib)}, "
        f"r2={sorted(str(n) for n in r2.loc_rib)})"
    )
    for io in ios:
        io.close()


def test_chaos_tcp_same_seed_same_injection_sequence():
    """The tcp seams ride FaultPlan's per-site deterministic streams:
    the same plan replays the same reset/partial decisions."""
    from holo_tpu.resilience.faults import FaultInjector, FaultPlan

    def sequence():
        inj = FaultInjector(
            FaultPlan(seed=7, tcp_reset_prob=0.3,
                      tcp_partial_write_prob=0.5)
        )
        return (
            [inj.tcp_reset("tcp.flush.reset") for _ in range(32)],
            [inj.tcp_send_cap(400) for _ in range(32)],
            dict(inj.injected),
        )

    assert sequence() == sequence()


def _md5_supported():
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        set_md5sig(s, "127.0.0.1", b"k")
        return True
    except OSError:
        return False
    finally:
        s.close()


@pytest.mark.skipif(not _md5_supported(), reason="kernel lacks TCP_MD5SIG")
def test_tcp_md5_session():
    loop = EventLoop(clock=RealClock())
    r1, io1 = _mk_speaker(loop, "m1", 65001, "1.1.1.1", "127.0.3.1", port=17902)
    r2, io2 = _mk_speaker(loop, "m2", 65002, "2.2.2.2", "127.0.3.2", port=17902)
    _peer(r1, io1, "127.0.3.1", "127.0.3.2", 65002, md5_key=b"s3cret")
    _peer(r2, io2, "127.0.3.2", "127.0.3.1", 65001, md5_key=b"s3cret")
    ios = [io1, io2]
    assert _drive(
        loop,
        ios,
        lambda: all(
            p.state == PeerState.ESTABLISHED
            for inst in (r1, r2)
            for p in inst.peers.values()
        ),
        timeout=15.0,
    ), "MD5-protected session did not establish"
    for io in ios:
        io.close()


@pytest.mark.skipif(not _md5_supported(), reason="kernel lacks TCP_MD5SIG")
def test_tcp_md5_key_mismatch_blocks_session():
    loop = EventLoop(clock=RealClock())
    r1, io1 = _mk_speaker(loop, "x1", 65001, "1.1.1.1", "127.0.4.1", port=17903)
    r2, io2 = _mk_speaker(loop, "x2", 65002, "2.2.2.2", "127.0.4.2", port=17903)
    _peer(r1, io1, "127.0.4.1", "127.0.4.2", 65002, md5_key=b"right")
    _peer(r2, io2, "127.0.4.2", "127.0.4.1", 65001, md5_key=b"wrong")
    ios = [io1, io2]
    assert not _drive(
        loop,
        ios,
        lambda: any(
            p.state == PeerState.ESTABLISHED
            for inst in (r1, r2)
            for p in inst.peers.values()
        ),
        timeout=3.0,
    ), "session established despite MD5 key mismatch"
    for io in ios:
        io.close()


def test_two_daemons_ebgp_over_tcp():
    """Config-driven daemons: BGP transport=tcp end to end (the daemon
    profile the reference runs in production)."""
    from holo_tpu.daemon.daemon import Daemon

    loop = EventLoop(clock=RealClock())
    d1 = Daemon(loop=loop, name="t1")
    d2 = Daemon(loop=loop, name="t2")

    def conf(d, local, peer, asn, peer_as, rid, nets):
        c = d.candidate()
        c.set("interfaces/interface[lo0]/enabled", "true")
        c.set("interfaces/interface[lo0]/address", [f"{local}/24"])
        base = "routing/control-plane-protocols/bgp"
        c.set(f"{base}/as", asn)
        c.set(f"{base}/router-id", rid)
        c.set(f"{base}/transport", "tcp")
        c.set(f"{base}/port", 17904)
        c.set(f"{base}/neighbor[{peer}]/address", peer)
        c.set(f"{base}/neighbor[{peer}]/peer-as", peer_as)
        c.set(f"{base}/neighbor[{peer}]/connect-retry-interval", 1)
        for n in nets:
            c.set(f"{base}/network[{n}]/prefix", n)
        d.commit(c)

    try:
        conf(d1, "127.0.5.1", "127.0.5.2", 65001, 65002, "1.1.1.1",
             ["10.50.0.0/16"])
        conf(d2, "127.0.5.2", "127.0.5.1", 65002, 65001, "2.2.2.2", [])

        b1 = d1.routing.instances["bgp"]
        b2 = d2.routing.instances["bgp"]
        ios = [d1.routing.bgp_tcp_io, d2.routing.bgp_tcp_io]
        assert all(io is not None for io in ios)
        ok = _drive(
            loop, ios,
            lambda: N("10.50.0.0/16") in b2.loc_rib,
            timeout=15.0,
        )
        assert ok, (
            f"route did not propagate; states: "
            f"{[p.state for p in b1.peers.values()]}"
            f"{[p.state for p in b2.peers.values()]}"
        )
        assert b2.loc_rib[N("10.50.0.0/16")][0].attrs.as_path == (65001,)
        # The learned route reaches d2's RIB manager
        from holo_tpu.utils.southbound import Protocol
        entries = d2.routing.rib.routes.get(N("10.50.0.0/16"))
        assert entries is not None and Protocol.BGP in entries.entries
    finally:
        # Stop BOTH daemons: leaked threaded-instance pump loops keep
        # real-clock BGP connect-retry timers firing global metric
        # counters for the rest of the pytest process, which breaks the
        # postmortem bundle byte-determinism window downstream
        # (tests/test_resilience_chaos.py).
        for d in (d1, d2):
            d.stop()
        for io in (d1.routing.bgp_tcp_io, d2.routing.bgp_tcp_io):
            if io is not None:
                io.close()


def test_session_reset_allows_reestablishment():
    """FSM-initiated drop must close the transport so a fresh session can
    form (stale sockets would block inbound accepts)."""
    from holo_tpu.protocols.bgp import HoldTimerExpiredMsg

    loop = EventLoop(clock=RealClock())
    r1, io1 = _mk_speaker(loop, "s1", 65001, "1.1.1.1", "127.0.6.1", port=17905)
    r2, io2 = _mk_speaker(loop, "s2", 65002, "2.2.2.2", "127.0.6.2", port=17905)
    _peer(r1, io1, "127.0.6.1", "127.0.6.2", 65002)
    _peer(r2, io2, "127.0.6.2", "127.0.6.1", 65001)
    ios = [io1, io2]
    est = lambda: all(
        p.state == PeerState.ESTABLISHED
        for inst in (r1, r2)
        for p in inst.peers.values()
    )
    assert _drive(loop, ios, est)
    # Simulate hold-timer expiry on r1: notification + transport reset.
    loop.send("s1", HoldTimerExpiredMsg(next(iter(r1.peers))))
    loop.run_until_idle()
    assert next(iter(r1.peers.values())).state == PeerState.IDLE
    assert _drive(loop, ios, est, timeout=15.0), "did not re-establish"
    for io in ios:
        io.close()


def test_gtsm_ttl_security_session():
    """GTSM (RFC 5082, reference network.rs:107-141): with ttl-security
    hops=1 both sides send TTL 255 and enforce MINTTL 255 — a loopback
    direct session still forms (TTL undecremented), and the socket
    options are verifiably applied."""
    import socket as _socket

    from holo_tpu.utils.tcpio import IP_MINTTL, _TTL_MAX

    import ipaddress

    loop = EventLoop(clock=RealClock())
    r1, io1 = _mk_speaker(loop, "g1", 65001, "1.1.1.1", "127.0.9.1", port=PORT + 7)
    r2, io2 = _mk_speaker(loop, "g2", 65002, "2.2.2.2", "127.0.9.2", port=PORT + 7)
    for inst, io, lip, pip, ras in (
        (r1, io1, "127.0.9.1", "127.0.9.2", 65002),
        (r2, io2, "127.0.9.2", "127.0.9.1", 65001),
    ):
        cfg = PeerConfig(
            addr=ipaddress.ip_address(pip), remote_as=ras, ifname="tcp",
            hold_time=15, connect_retry=0.3,
        )
        inst.add_peer(cfg, ipaddress.ip_address(lip))
        io.add_peer(lip, pip, ttl_security=1)
        inst.start_peer(cfg.addr)
    assert _drive(
        loop, [io1, io2],
        lambda: all(p.state == PeerState.ESTABLISHED
                    for i in (r1, r2) for p in i.peers.values()),
    ), "GTSM session failed to establish"
    # The established socket carries the GTSM options.
    slot = io1.peers[ipaddress.ip_address("127.0.9.2")]
    assert slot.sock.getsockopt(_socket.IPPROTO_IP, _socket.IP_TTL) == _TTL_MAX
    assert slot.sock.getsockopt(_socket.IPPROTO_IP, IP_MINTTL) == _TTL_MAX


def test_tcp_mss_option_applied():
    """tcp-mss (reference network.rs set_mss): configured ONLY on the
    passive (listening) side, so the active peer's negotiated MSS proves
    the listener advertised the clamp in its SYN-ACK — applying it to
    the accepted socket after the handshake would be too late."""
    import ipaddress
    import socket as _socket

    import pytest

    loop = EventLoop(clock=RealClock())
    r1, io1 = _mk_speaker(loop, "s1", 65001, "1.1.1.1", "127.0.11.1", port=PORT + 9)
    r2, io2 = _mk_speaker(loop, "s2", 65002, "2.2.2.2", "127.0.11.2", port=PORT + 9)
    for inst, io, lip, pip, ras, mss in (
        (r1, io1, "127.0.11.1", "127.0.11.2", 65002, 1200),  # passive
        (r2, io2, "127.0.11.2", "127.0.11.1", 65001, None),  # active
    ):
        cfg = PeerConfig(
            addr=ipaddress.ip_address(pip), remote_as=ras, ifname="tcp",
            hold_time=15, connect_retry=0.3,
        )
        inst.add_peer(cfg, ipaddress.ip_address(lip))
        io.add_peer(lip, pip, tcp_mss=mss)
        inst.start_peer(cfg.addr)
    assert _drive(
        loop, [io1, io2],
        lambda: all(p.state == PeerState.ESTABLISHED
                    for i in (r1, r2) for p in i.peers.values()),
    ), "session with tcp-mss failed to establish"
    slot = io2.peers[ipaddress.ip_address("127.0.11.1")]
    mss = slot.sock.getsockopt(_socket.IPPROTO_TCP, _socket.TCP_MAXSEG)
    assert mss <= 1200, mss  # kernel may clamp lower, never higher
    # Live reconfiguration re-clamps; bad values are rejected up front.
    io1.update_mss("127.0.11.2", 1000)
    assert io1.peers[ipaddress.ip_address("127.0.11.2")].tcp_mss == 1000
    with pytest.raises(ValueError):
        io1.update_mss("127.0.11.2", 40000)
    with pytest.raises(ValueError):
        io1.add_peer("127.0.11.1", "127.0.11.9", tcp_mss=40000)
    for io in (io1, io2):
        io.close()


def test_listener_mss_scoped_to_bound_address():
    """A peer config change on one local address must never touch —
    or clear — another address's listener clamp (r5 review): the clamp
    is re-applied only to listeners bound to the changed peer's
    local ip, and removing the last clamped peer clears it."""
    import socket as _socket

    loop = EventLoop(clock=RealClock())
    io = BgpTcpIo(loop, "mss-scope", port=PORT + 11)
    io.add_peer("127.0.12.1", "127.0.12.9", tcp_mss=1400)
    io.listen("127.0.12.1")
    io.listen("127.0.12.2")
    fd1 = next(
        fd for fd, ip in io._listener_ip.items() if str(ip) == "127.0.12.1"
    )
    fd2 = next(
        fd for fd, ip in io._listener_ip.items() if str(ip) == "127.0.12.2"
    )
    ls1, ls2 = io._listeners[fd1], io._listeners[fd2]

    def user_mss(s):
        # On a LISTEN socket Linux reports user_mss (0 = unset).
        return s.getsockopt(_socket.IPPROTO_TCP, _socket.TCP_MAXSEG)

    assert user_mss(ls1) == 1400
    # Unclamped peer on the OTHER address: L1's clamp must survive.
    io.add_peer("127.0.12.2", "127.0.12.8", tcp_mss=None)
    assert user_mss(ls1) == 1400
    assert user_mss(ls2) in (0, 536)  # unset (platform default report)
    # Removing the last clamped peer on .1 clears that listener only.
    io.remove_peer("127.0.12.9")
    assert user_mss(ls1) in (0, 536)
    io.close()

"""Coverage-guided fuzzing sweep (reference fuzz/fuzz-all.sh analog).

34 targets over every wire decoder (tools/fuzz.py), each evolving a
corpus by line coverage under a per-target time cap.  Any non-DecodeError
exception is a crash and fails with the reproducing input.

The regression cases at the bottom are real crashes this fuzzer found:
prefix TLVs with stray host bits (IS-IS extended reach, BGP NLRI, LDP
FEC) and non-contiguous RFC 1195 narrow-metric masks raised ValueError
out of the decoders.
"""

import os

import pytest

from holo_tpu.tools.fuzz import COVERAGE_AVAILABLE, run_all, targets
from holo_tpu.utils.bytesbuf import DecodeError

BUDGET_S = float(os.environ.get("HOLO_TPU_FUZZ_BUDGET", "0.15"))


def test_target_inventory_matches_reference_scale():
    # The reference ships 31 libFuzzer targets; we match/beat that.
    assert len(targets()) >= 31


def test_coverage_guided_sweep_no_crashes():
    results = run_all(budget_s=BUDGET_S)
    crashed = {
        name: res.crashes[:2] for name, res in results.items() if res.crashes
    }
    assert not crashed, crashed
    # Guidance sanity: coverage feedback grew at least one corpus beyond
    # its seeds (i.e. the loop is genuinely coverage-driven).  Pre-3.12
    # interpreters have no sys.monitoring: the sweep still runs (blind),
    # but corpora cannot grow.
    if COVERAGE_AVAILABLE:
        assert any(r.corpus_size > 20 for r in results.values())


@pytest.mark.parametrize(
    "target,payload",
    [
        # IS-IS LSP: TLV 135 entry whose truncated prefix carries host
        # bits beyond the prefix length.
        (
            "isis_pdu_decode",
            bytes.fromhex(
                "831b01001401000000870000000000000001000000000007"
                "000003020c000a808080000000000002"
            ),
        ),
        # BGP UPDATE: withdrawn NLRI 1.0.0.0/1 (host bits set).
        (
            "bgp_update_decode",
            bytes.fromhex(
                "000100000001010100000100001c000000010400000400"
                "0f20000401000401010101040200040000"
            ),
        ),
        # LDP (legacy codec): FEC prefix with host bits.
        (
            "ldp_msg_decode",
            bytes.fromhex(
                "00010020010101010000040000160000000001000006"
                "020100010101010000040000160000000001"
            ),
        ),
    ],
)
def test_fuzzer_found_crashes_stay_fixed(target, payload):
    fn = targets()[target]
    try:
        fn(payload)
    except DecodeError:
        pass  # rejecting malformed input is fine; crashing is not

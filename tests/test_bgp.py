"""BGP: codecs, session establishment, propagation, decision, policy."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.protocols.bgp import (
    BgpInstance,
    KeepaliveMsg,
    MsgType,
    NotificationMsg,
    OpenMsg,
    Origin,
    PathAttrs,
    PeerConfig,
    PeerState,
    UpdateMsg,
    decode_msg,
    encode_msg,
)
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def test_message_roundtrips():
    o = OpenMsg(70000, 90, A("1.1.1.1"))  # 4-byte ASN via capability
    t, out = decode_msg(encode_msg(o))
    assert t == MsgType.OPEN and out.asn == 70000 and out.router_id == A("1.1.1.1")

    attrs = PathAttrs(Origin.IGP, (65001, 65002), A("10.0.0.1"), med=5,
                      local_pref=200)
    u = UpdateMsg(withdrawn=[N("192.0.2.0/24")], attrs=attrs,
                  nlri=[N("10.1.0.0/16"), N("10.2.0.0/24")])
    t, out = decode_msg(encode_msg(u))
    assert out.withdrawn == [N("192.0.2.0/24")]
    assert out.nlri == [N("10.1.0.0/16"), N("10.2.0.0/24")]
    assert out.attrs.as_path == (65001, 65002)
    assert out.attrs.next_hop == A("10.0.0.1")
    assert out.attrs.local_pref == 200

    t, _ = decode_msg(encode_msg(KeepaliveMsg()))
    assert t == MsgType.KEEPALIVE
    t, out = decode_msg(encode_msg(NotificationMsg(6, 2)))
    assert (out.code, out.subcode) == (6, 2)


def two_speakers(as1=65001, as2=65002):
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    b1 = BgpInstance("b1", as1, A("1.1.1.1"), fabric.sender_for("b1"))
    b2 = BgpInstance("b2", as2, A("2.2.2.2"), fabric.sender_for("b2"))
    loop.register(b1)
    loop.register(b2)
    fabric.join("l", "b1", "e0", A("10.0.0.1"))
    fabric.join("l", "b2", "e0", A("10.0.0.2"))
    b1.add_peer(PeerConfig(A("10.0.0.2"), as2, "e0"), A("10.0.0.1"))
    b2.add_peer(PeerConfig(A("10.0.0.1"), as1, "e0"), A("10.0.0.2"))
    b1.start_peer(A("10.0.0.2"))
    b2.start_peer(A("10.0.0.1"))
    return loop, fabric, b1, b2


def test_session_establishment_and_route_exchange():
    loop, fabric, b1, b2 = two_speakers()
    loop.advance(5)
    assert b1.peers[A("10.0.0.2")].state == PeerState.ESTABLISHED
    assert b2.peers[A("10.0.0.1")].state == PeerState.ESTABLISHED

    b1.originate(N("203.0.113.0/24"))
    loop.advance(2)
    best = b2.loc_rib.get(N("203.0.113.0/24"))
    assert best is not None
    assert best[0].attrs.as_path == (65001,)  # eBGP prepends
    assert best[0].attrs.next_hop == A("10.0.0.1")


def test_withdraw_and_peer_loss():
    loop, fabric, b1, b2 = two_speakers()
    loop.advance(5)
    b1.originate(N("203.0.113.0/24"))
    loop.advance(2)
    assert N("203.0.113.0/24") in b2.loc_rib

    # Silent peer death: hold timer expires, routes withdrawn.
    fabric.set_link_up("l", False)
    loop.advance(100)
    assert b2.peers[A("10.0.0.1")].state in (PeerState.IDLE, PeerState.CONNECT,
                                             PeerState.OPEN_SENT)
    assert N("203.0.113.0/24") not in b2.loc_rib


def test_decision_prefers_shorter_as_path():
    """b3 hears the same prefix from b1 (direct) and via b2 (longer path)."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    speakers = {}
    for i, asn in ((1, 65001), (2, 65002), (3, 65003)):
        b = BgpInstance(f"b{i}", asn, A(f"{i}.{i}.{i}.{i}"),
                        fabric.sender_for(f"b{i}"))
        loop.register(b)
        speakers[i] = b
    # full mesh of eBGP over one LAN
    for i in range(1, 4):
        fabric.join("lan", f"b{i}", "e0", A(f"10.0.0.{i}"))
    for i in range(1, 4):
        for j in range(1, 4):
            if i != j:
                speakers[i].add_peer(
                    PeerConfig(A(f"10.0.0.{j}"), 65000 + j, "e0",
                               connect_retry=1.0),
                    A(f"10.0.0.{i}"),
                )
    for i in range(1, 4):
        for j in range(1, 4):
            if i != j:
                speakers[i].start_peer(A(f"10.0.0.{j}"))
    loop.advance(10)
    speakers[1].originate(N("198.51.100.0/24"))
    loop.advance(5)
    best = speakers[3].loc_rib[N("198.51.100.0/24")]
    # direct path (65001) beats (65002, 65001) via b2
    assert best[0].attrs.as_path == (65001,)
    assert best[0].peer == A("10.0.0.1")
    assert len(best) >= 2  # the longer path is known but not best


def test_import_policy_rejects():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    b1 = BgpInstance("b1", 65001, A("1.1.1.1"), fabric.sender_for("b1"))
    b2 = BgpInstance("b2", 65002, A("2.2.2.2"), fabric.sender_for("b2"))
    loop.register(b1)
    loop.register(b2)
    fabric.join("l", "b1", "e0", A("10.0.0.1"))
    fabric.join("l", "b2", "e0", A("10.0.0.2"))
    b1.add_peer(PeerConfig(A("10.0.0.2"), 65002, "e0"), A("10.0.0.1"))
    b2.add_peer(
        PeerConfig(
            A("10.0.0.1"), 65001, "e0",
            import_policy=lambda p, a: None if p == N("203.0.113.0/24") else a,
        ),
        A("10.0.0.2"),
    )
    b1.start_peer(A("10.0.0.2"))
    b2.start_peer(A("10.0.0.1"))
    loop.advance(5)
    b1.originate(N("203.0.113.0/24"))
    b1.originate(N("198.51.100.0/24"))
    loop.advance(2)
    assert N("203.0.113.0/24") not in b2.loc_rib
    assert N("198.51.100.0/24") in b2.loc_rib


def test_engine_deactivation_and_late_neighbor_add():
    """instance.rs update(): unconfiguring ASN/router-id tears the instance
    down (sessions closed, tables cleared); neighbors added after activation
    are instantiated on the next update()."""
    from holo_tpu.protocols.bgp_engine import (
        ESTABLISHED,
        IDLE,
        BgpEngine,
        NeighborCfg,
    )

    sent = []
    eng = BgpEngine("test", send_cb=lambda k, p: sent.append((k, p)))
    eng.asn = 65001
    eng.cfg_identifier = "1.1.1.1"
    eng.neighbor_cfg["10.0.0.2"] = NeighborCfg(peer_as=65002)
    eng.update()
    assert eng.active and "10.0.0.2" in eng.neighbors

    # Late neighbor add: instantiated without instance restart.
    eng.neighbor_cfg["10.0.0.3"] = NeighborCfg(peer_as=65001)
    eng.update()
    assert "10.0.0.3" in eng.neighbors
    assert eng.neighbors["10.0.0.3"].peer_type == "internal"

    # Pretend one session is up, then unconfigure the ASN: the engine must
    # go inactive, close sessions (Cease sent), and clear all state.
    eng.neighbors["10.0.0.2"].state = ESTABLISHED
    eng.asn = 0
    eng.update()
    assert not eng.active and not eng.neighbors
    cease = [
        p
        for k, p in sent
        if k == "SendMessage" and "Notification" in p.get("msg", {})
    ]
    assert cease and cease[0]["msg"]["Notification"]["error_code"] == 6

    # Neighbor config removal while active closes just that neighbor.
    eng.asn = 65001
    eng.update()
    assert eng.active and set(eng.neighbors) == {"10.0.0.2", "10.0.0.3"}
    del eng.neighbor_cfg["10.0.0.3"]
    eng.update()
    assert set(eng.neighbors) == {"10.0.0.2"}


def test_community_attr_roundtrips():
    """RFC 1997/4360/5701/8092 community families + aggregation +
    route-reflection attrs survive the wire round-trip."""
    from holo_tpu.protocols.bgp import RouteRefreshMsg

    attrs = PathAttrs(
        Origin.IGP,
        (65001,),
        A("10.0.0.1"),
        communities=(0x00010002, 0xFFFFFF01),
        ext_communities=(b"\x00\x02\x00\x01\x00\x00\x00\x64",),
        extv6_communities=(bytes(20),),
        large_communities=((65001, 7, 9),),
        aggregator=(65010, A("9.9.9.9")),
        atomic_aggregate=True,
        originator_id=A("3.3.3.3"),
        cluster_list=(A("4.4.4.4"), A("5.5.5.5")),
    )
    u = UpdateMsg(attrs=attrs, nlri=[N("10.1.0.0/16")])
    _, out = decode_msg(encode_msg(u))
    a = out.attrs
    assert a.communities == (0x00010002, 0xFFFFFF01)
    assert a.ext_communities == (b"\x00\x02\x00\x01\x00\x00\x00\x64",)
    assert a.extv6_communities == (bytes(20),)
    assert a.large_communities == ((65001, 7, 9),)
    assert a.aggregator == (65010, A("9.9.9.9"))
    assert a.atomic_aggregate
    assert a.originator_id == A("3.3.3.3")
    assert a.cluster_list == (A("4.4.4.4"), A("5.5.5.5"))

    # ROUTE-REFRESH (RFC 2918) round-trip + capability negotiation.
    t, rr = decode_msg(encode_msg(RouteRefreshMsg(afi=2)))
    assert t == MsgType.ROUTE_REFRESH and rr.afi == 2 and rr.safi == 1
    _, o = decode_msg(encode_msg(OpenMsg(65001, 90, A("1.1.1.1"))))
    assert o.route_refresh


def test_malformed_community_lengths_rejected():
    import pytest

    from holo_tpu.protocols.bgp import (
        decode_aggregator,
        decode_comm,
        decode_ext_comm,
        decode_large_comm,
    )
    from holo_tpu.utils.bytesbuf import DecodeError, Reader

    for fn, bad in (
        (decode_comm, b"\x00\x01\x00"),  # not 4-aligned
        (decode_ext_comm, b"\x00" * 7),  # not 8-aligned
        (decode_large_comm, b"\x00" * 13),  # not 12-aligned
        (decode_aggregator, b"\x00" * 5),  # neither 6 nor 8 bytes
    ):
        with pytest.raises(DecodeError):
            fn(Reader(bad))


def test_communities_propagate_and_well_knowns_filter():
    """Transitive carry b1->b2, and NO_EXPORT suppresses eBGP
    advertisement (neighbor.rs:1083-1102 distribute filter)."""
    from holo_tpu.protocols.bgp import NO_EXPORT

    loop, fabric, b1, b2 = two_speakers()
    loop.advance(5)
    b1.originate(N("203.0.113.0/24"), communities=(0x00010002,))
    b1.originate(N("198.51.100.0/24"), communities=(NO_EXPORT,))
    loop.advance(2)
    best = b2.loc_rib.get(N("203.0.113.0/24"))
    assert best is not None and best[0].attrs.communities == (0x00010002,)
    # NO_EXPORT: never advertised over the eBGP session.
    assert N("198.51.100.0/24") not in b2.loc_rib


def test_route_refresh_resends_adj_rib_out():
    from holo_tpu.protocols.bgp import RouteRefreshMsg

    loop, fabric, b1, b2 = two_speakers()
    loop.advance(5)
    b1.originate(N("203.0.113.0/24"))
    loop.advance(2)
    assert N("203.0.113.0/24") in b2.loc_rib
    # b2 forgets the route (simulated RIB loss), then asks for a refresh.
    peer1 = b2.peers[A("10.0.0.1")]
    peer1.adj_rib_in.clear()
    b2.loc_rib.clear()
    b2._send(peer1, RouteRefreshMsg())
    loop.advance(2)
    assert N("203.0.113.0/24") in b2.loc_rib


def test_engine_attrs_json_carries_communities():
    """Recorded-corpus serde shape: comm/large_comm side-by-side with
    base, atomic_aggregate as a present-null key (serde Option<()>)."""
    from holo_tpu.protocols.bgp_engine import (
        _attrs_from_json,
        _attrs_to_json,
    )

    j = {
        "base": {
            "origin": "Igp",
            "as_path": {"segments": [{"seg_type": "Sequence", "members": [65001]}]},
            "nexthop": "10.0.0.1",
            "aggregator": {"asn": 65010, "identifier": "9.9.9.9"},
            "atomic_aggregate": None,
            "originator_id": "3.3.3.3",
            "cluster_list": ["4.4.4.4"],
        },
        "comm": [65538, 4294967041],
        "large_comm": [[65001, 7, 9]],
    }
    attrs = _attrs_from_json(j)
    assert attrs.comm == (65538, 4294967041)
    assert attrs.large_comm == ((65001, 7, 9),)
    assert attrs.aggregator == (65010, "9.9.9.9")
    assert attrs.atomic_aggregate
    assert _attrs_from_json(_attrs_to_json(attrs)) == attrs


def test_yang_notifications_session_lifecycle():
    """Reference holo-bgp northbound/notification.rs: established on
    session up; backward-transition (with last NOTIFICATION codes) on
    session loss."""
    loop, fabric, b1, b2 = two_speakers()
    notifs = []
    b1.notif_cb = notifs.append
    loop.advance(5)
    assert b1.peers[A("10.0.0.2")].state == PeerState.ESTABLISHED
    est = [n for n in notifs if "ietf-bgp:established" in n]
    assert est and est[0]["ietf-bgp:established"]["remote-address"] == "10.0.0.2"
    # Hold-timer expiry: b2 goes quiet, b1 sends (4,0) and transitions back.
    notifs.clear()
    loop.unregister("b2")
    loop.advance(300)
    back = [n["ietf-bgp:backward-transition"] for n in notifs
            if "ietf-bgp:backward-transition" in n]
    assert back, notifs
    assert back[0]["remote-addr"] == "10.0.0.2"
    assert back[0]["notification-sent"]["last-error-code"] == 4

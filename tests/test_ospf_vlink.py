"""RFC 2328 §15 virtual links: ADJACENCY FORMATION, not just route
borrowing (VERDICT round-2 item 6; reference interface.rs:50,84,135-148).

Topology: r1 is a backbone+transit-area ABR; r2 attaches ONLY to the
transit area (0.0.0.1) and to a far area (0.0.0.2).  A virtual link
r1<->r2 through the transit area must form a real adjacency (hellos,
DD exchange, flooding over the vlink), turn r2 into a backbone-attached
ABR, and carry area-2 prefixes into the backbone router r0.
"""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.protocols.ospf.instance import (
    IfConfig,
    IfUpMsg,
    InstanceConfig,
    OspfInstance,
)
from holo_tpu.protocols.ospf.interface import IfType
from holo_tpu.protocols.ospf.neighbor import NsmState
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def _rtr(loop, fabric, name, rid):
    r = OspfInstance(
        name=name,
        config=InstanceConfig(router_id=A(rid)),
        netio=fabric.sender_for(name),
    )
    loop.register(r)
    return r


def _p2p(fabric, link, r1, if1, a1, r2, if2, a2, prefix, area="0.0.0.0"):
    cfg = lambda: IfConfig(
        area_id=A(area), if_type=IfType.POINT_TO_POINT, cost=10
    )
    r1.add_interface(if1, cfg(), N(prefix), A(a1))
    r2.add_interface(if2, cfg(), N(prefix), A(a2))
    fabric.join(link, r1.name, if1, A(a1))
    fabric.join(link, r2.name, if2, A(a2))


def _vlink_iface(r):
    for i in r.areas[A("0.0.0.0")].interfaces.values():
        if i.config.if_type == IfType.VIRTUAL_LINK:
            return i
    return None


def test_virtual_link_adjacency_forms_and_carries_routes():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r0 = _rtr(loop, fabric, "r0", "10.0.0.100")  # pure backbone router
    r1 = _rtr(loop, fabric, "r1", "10.0.0.1")  # ABR: backbone + transit
    r2 = _rtr(loop, fabric, "r2", "10.0.0.2")  # transit + far area

    _p2p(fabric, "l01", r0, "e0", "10.1.0.1", r1, "e0", "10.1.0.2",
         "10.1.0.0/30", area="0.0.0.0")
    _p2p(fabric, "l12", r1, "e1", "10.2.0.1", r2, "e0", "10.2.0.2",
         "10.2.0.0/30", area="0.0.0.1")
    # r2's far-area prefix (a passive stub interface in area 0.0.0.2).
    r2.add_interface(
        "stub",
        IfConfig(area_id=A("0.0.0.2"), if_type=IfType.POINT_TO_POINT,
                 cost=1, passive=True),
        N("192.168.2.0/24"),
        A("192.168.2.1"),
    )

    # The virtual link, configured on both endpoints.
    r1.add_virtual_link(A("0.0.0.1"), A("10.0.0.2"))
    r2.add_virtual_link(A("0.0.0.1"), A("10.0.0.1"))

    for r, ifs in ((r0, ["e0"]), (r1, ["e0", "e1"]), (r2, ["e0", "stub"])):
        for i in ifs:
            loop.send(r.name, IfUpMsg(i))
    loop.advance(120)

    # The vlink interfaces materialized and the adjacency is FULL.
    for r, peer in ((r1, A("10.0.0.2")), (r2, A("10.0.0.1"))):
        vl = _vlink_iface(r)
        assert vl is not None, f"{r.name}: vlink interface missing"
        nbr = vl.neighbors.get(peer)
        assert nbr is not None and nbr.state == NsmState.FULL, (
            f"{r.name}: vlink adjacency not FULL "
            f"({nbr.state if nbr else 'absent'})"
        )
        # Both ends advertise the type-4 link in their backbone LSA.
        from holo_tpu.protocols.ospf.packet import (
            LsaKey,
            LsaType,
            RouterLinkType,
        )

        e = r.areas[A("0.0.0.0")].lsdb.get(
            LsaKey(LsaType.ROUTER, r.config.router_id, r.config.router_id)
        )
        assert any(
            l.link_type == RouterLinkType.VIRTUAL_LINK and l.id == peer
            for l in e.lsa.body.links
        ), f"{r.name}: no virtual-link in backbone router-LSA"

    # r2 is now backbone-attached: its far-area prefix reaches the pure
    # backbone router THROUGH the virtual link (as an inter-area route).
    assert N("192.168.2.0/24") in r0.routes, (
        "far-area prefix did not cross the virtual link into the backbone"
    )
    # And the backbone prefix reaches r2.
    assert N("10.1.0.0/30") in r2.routes


def test_virtual_link_tears_down_when_transit_path_dies():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    r1 = _rtr(loop, fabric, "r1", "10.0.0.1")
    r2 = _rtr(loop, fabric, "r2", "10.0.0.2")
    _p2p(fabric, "l12", r1, "e1", "10.2.0.1", r2, "e0", "10.2.0.2",
         "10.2.0.0/30", area="0.0.0.1")
    # r1 needs a backbone presence for area 0 to exist.
    r1.add_interface(
        "b0",
        IfConfig(area_id=A("0.0.0.0"), if_type=IfType.POINT_TO_POINT,
                 cost=1, passive=True),
        N("10.9.0.0/30"),
        A("10.9.0.1"),
    )
    r2.add_interface(
        "b0",
        IfConfig(area_id=A("0.0.0.0"), if_type=IfType.POINT_TO_POINT,
                 cost=1, passive=True),
        N("10.9.4.0/30"),
        A("10.9.4.1"),
    )
    r1.add_virtual_link(A("0.0.0.1"), A("10.0.0.2"))
    r2.add_virtual_link(A("0.0.0.1"), A("10.0.0.1"))
    for r, ifs in ((r1, ["e1", "b0"]), (r2, ["e0", "b0"])):
        for i in ifs:
            loop.send(r.name, IfUpMsg(i))
    loop.advance(120)
    vl = _vlink_iface(r1)
    assert vl is not None
    assert any(n.state == NsmState.FULL for n in vl.neighbors.values())

    # Kill the transit link: the endpoint becomes unreachable and the
    # vlink interface is torn down with it.
    fabric.set_link_up("l12", False)
    loop.advance(180)
    assert _vlink_iface(r1) is None, "vlink survived transit-path loss"

"""Dispatch survivability plane (ISSUE 19 acceptance contract).

Covers the overload/robustness semantics the pipeline promises under
pressure: class-aware dequeue (correctness > advisory > background),
graded load-shedding on a full queue (worst class first, correctness
never shed and still bounded-blocking), advisory submit-time deadlines
expired at dequeue, close() waking a capacity-blocked submitter into
``PipelineClosed``, the hung-dispatch watchdog (abandon + bit-identical
scalar fallback + breaker escalation + worker respawn), chaos-born
worker kills with supervised respawn (queued tickets survive), the
transient-vs-deterministic retry taxonomy ahead of the breaker, and the
disarmed-path identity contract (a poisoned deadline clock is never
read when no ticket carries a deadline).
"""

import threading
import time

import numpy as np
import pytest

from holo_tpu import pipeline
from holo_tpu.pipeline.dispatch import (
    DispatchPipeline,
    PipelineClosed,
    _guarded_launch,
)
from holo_tpu.resilience import overload
from holo_tpu.resilience.breaker import CircuitBreaker
from holo_tpu.resilience.faults import FaultInjector, FaultPlan, inject
from holo_tpu.resilience.watchdog import (
    DispatchWatchdog,
    reset_process_watchdog,
)
from holo_tpu.spf.backend import ScalarSpfBackend, TpuSpfBackend
from holo_tpu.spf.synth import random_ospf_topology


@pytest.fixture(autouse=True)
def _clean_process_state():
    yield
    reset_process_watchdog()
    pipeline.reset_process_pipeline()
    pipeline.reset_engine_tuner()
    overload.configure_retry(None)


def _topo(seed=1, n=30):
    return random_ospf_topology(
        n_routers=n, n_networks=5, extra_p2p=n // 2, seed=seed
    )


def _occupied_pipe(**kw):
    """Pipeline whose worker is parked inside a blocker run — queued
    submissions pile up behind it until ``release`` is set."""
    pipe = DispatchPipeline(**kw)
    release = threading.Event()
    started = threading.Event()

    def blocker():
        started.set()
        release.wait(30)

    t = pipe.submit(("blocker", 0), "one", run=blocker)
    assert started.wait(5), "worker never picked up the blocker"
    return pipe, release, t


# -- priority admission -------------------------------------------------


def test_class_aware_dequeue_correctness_first_fifo_within_rank():
    """Mixed-class backlog drains correctness first, FIFO within each
    class — advisory and background never queue ahead of FIB-feeding
    work regardless of arrival order."""
    pipe, release, blocker = _occupied_pipe(depth=1, capacity=16)
    order = []

    def mk(tag):
        return lambda: order.append(tag)

    tickets = [
        pipe.submit(("bg", 0), "one", run=mk("bg"), cls="background"),
        pipe.submit(("a1", 0), "one", run=mk("a1"), cls="advisory"),
        pipe.submit(("c1", 0), "one", run=mk("c1")),
        pipe.submit(("a2", 0), "one", run=mk("a2"), cls="advisory"),
        pipe.submit(("c2", 0), "one", run=mk("c2")),
    ]
    release.set()
    for t in tickets:
        t.result(timeout=10)
    pipe.close()
    assert order == ["c1", "c2", "a1", "a2", "bg"]


def test_submit_rejects_unknown_class_and_correctness_deadline():
    pipe = DispatchPipeline(depth=1)
    with pytest.raises(ValueError, match="unknown ticket class"):
        pipe.submit(("k", 0), "one", run=lambda: None, cls="bogus")
    with pytest.raises(ValueError, match="deadline"):
        pipe.submit(("k", 0), "one", run=lambda: None, deadline=1.0)
    pipe.close()


# -- graded load-shedding -----------------------------------------------


def test_full_queue_sheds_worst_class_first():
    """Capacity pressure evicts the worst-class (oldest within it)
    queued ticket; an unsheddable incoming background ticket sheds
    itself instead of walling the submitter."""
    pipe, release, blocker = _occupied_pipe(depth=1, capacity=2)
    done = []
    bg = pipe.submit(
        ("bg", 0), "one", run=lambda: done.append("bg"), cls="background"
    )
    a1 = pipe.submit(
        ("a1", 0), "one", run=lambda: done.append("a1"), cls="advisory"
    )
    # Queue full.  Incoming advisory evicts the background victim.
    a2 = pipe.submit(
        ("a2", 0), "one", run=lambda: done.append("a2"), cls="advisory"
    )
    assert bg.shed == "capacity" and bg.skipped
    assert bg.result(timeout=1) is None
    # Queue holds [a1, a2] — an incoming background ticket outranks
    # nothing, so it sheds itself (never blocks).
    bg2 = pipe.submit(
        ("bg2", 0), "one", run=lambda: done.append("bg2"), cls="background"
    )
    assert bg2.shed == "capacity" and bg2.skipped
    # Incoming correctness evicts the OLDEST advisory instead of
    # blocking while sheddable work occupies the queue.
    c1 = pipe.submit(("c1", 0), "one", run=lambda: done.append("c1"))
    assert a1.shed == "capacity"
    release.set()
    c1.result(timeout=10)
    a2.result(timeout=10)
    pipe.close()
    st = pipe.stats()
    assert st["sheds"] == 3
    assert st["shed-by-class"] == {"background": 2, "advisory": 1}
    assert "c1" in done and "a2" in done
    assert done.count("bg") == 0 and done.count("a1") == 0


def test_correctness_blocks_bounded_when_queue_all_correctness():
    """A queue full of correctness work has no victim: the correctness
    submitter blocks (bounded backpressure, the seed contract) and
    admits as soon as the worker frees a slot — it is NEVER shed."""
    pipe, release, blocker = _occupied_pipe(depth=1, capacity=1)
    first = pipe.submit(("c0", 0), "one", run=lambda: "c0")
    admitted = threading.Event()
    out = {}

    def submitter():
        out["ticket"] = pipe.submit(("c1", 0), "one", run=lambda: "c1")
        admitted.set()

    th = threading.Thread(target=submitter, daemon=True)
    th.start()
    assert not admitted.wait(0.4), "correctness submit must block, not shed"
    release.set()
    assert admitted.wait(10), "blocked correctness submit never admitted"
    assert out["ticket"].result(timeout=10) == "c1"
    assert first.result(timeout=10) == "c0"
    pipe.close()
    assert pipe.stats()["shed-by-class"].get("correctness", 0) == 0


def test_close_wakes_capacity_blocked_submitter_with_pipeline_closed():
    """ISSUE 19 satellite: a correctness submitter walled on a full
    queue must not sleep through close() — it wakes and raises
    ``PipelineClosed`` instead of waiting out a dead pipeline."""
    pipe, release, blocker = _occupied_pipe(depth=1, capacity=1)
    pipe.submit(("c0", 0), "one", run=lambda: None)
    failed = threading.Event()
    out = {}

    def submitter():
        try:
            pipe.submit(("c1", 0), "one", run=lambda: None)
        except PipelineClosed as exc:
            out["exc"] = exc
            failed.set()

    th = threading.Thread(target=submitter, daemon=True)
    th.start()
    time.sleep(0.2)
    assert not failed.is_set()
    release.set()  # let the worker drain so close() can join it
    pipe.close(timeout=10)
    assert failed.wait(5), "blocked submitter never saw PipelineClosed"
    assert isinstance(out["exc"], PipelineClosed)


# -- deadline-aware shedding --------------------------------------------


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_advisory_deadline_expires_at_dequeue():
    """An advisory ticket whose submit-time deadline lapsed while it
    queued is shed at dequeue (reason ``expired``) — the worker never
    runs it; correctness behind it is untouched."""
    clk = _FakeClock()
    pipe = DispatchPipeline(depth=1, capacity=8, clock=clk)
    release = threading.Event()
    started = threading.Event()
    pipe.submit(
        ("blocker", 0), "one",
        run=lambda: (started.set(), release.wait(30)),
    )
    assert started.wait(5)
    done = []
    adv = pipe.submit(
        ("a", 0), "one", run=lambda: done.append("a"),
        cls="advisory", deadline=5.0,
    )
    c = pipe.submit(("c", 0), "one", run=lambda: done.append("c"))
    clk.t = 10.0  # the advisory deadline lapses while queued
    release.set()
    c.result(timeout=10)
    assert adv.result(timeout=10) is None
    assert adv.shed == "expired" and adv.skipped
    assert done == ["c"]
    pipe.close()
    assert pipe.stats()["shed-by-class"] == {"advisory": 1}


def test_pipeline_default_advisory_deadline_applies():
    """``advisory_deadline`` stamps advisory tickets that did not pass
    their own; correctness is exempt by construction."""
    clk = _FakeClock()
    pipe = DispatchPipeline(
        depth=1, capacity=8, clock=clk, advisory_deadline=2.0
    )
    release = threading.Event()
    started = threading.Event()
    pipe.submit(
        ("blocker", 0), "one",
        run=lambda: (started.set(), release.wait(30)),
    )
    assert started.wait(5)
    adv = pipe.submit(("a", 0), "one", run=lambda: "a", cls="advisory")
    c = pipe.submit(("c", 0), "one", run=lambda: "c")
    clk.t = 100.0
    release.set()
    assert c.result(timeout=10) == "c"
    assert adv.result(timeout=10) is None and adv.shed == "expired"
    pipe.close()


def test_disarmed_path_never_reads_poisoned_clock():
    """Identity contract: with no deadline-carrying ticket anywhere,
    the pipeline NEVER reads its deadline clock — a poisoned clock
    proves the disarmed path is byte-identical to the seed."""

    def poisoned():
        raise AssertionError("deadline clock read on the disarmed path")

    pipe = DispatchPipeline(depth=2, capacity=4, clock=poisoned)
    tickets = [
        pipe.submit(("k", i), "one", run=lambda i=i: i, cls=cls)
        for i, cls in enumerate(
            ("correctness", "advisory", "background", "correctness")
        )
    ]
    for i, t in enumerate(tickets):
        assert t.result(timeout=10) == i
    pipe.close()
    assert pipe.stats()["sheds"] == 0


# -- hung-dispatch watchdog ---------------------------------------------


def test_watchdog_abandons_hang_serves_bit_identical_fallback():
    """Chaos hang inside the launch phase: the watchdog abandons the
    wedged phase within its budget, the ticket is served from the
    bit-identical scalar oracle, the breaker takes the hang as a
    failure (circuit opens), and a respawned worker keeps serving the
    queue."""
    topo = _topo(seed=11)
    ref = ScalarSpfBackend().compute(topo)
    pipe = pipeline.configure_process_pipeline(depth=2)
    breaker = CircuitBreaker(
        "watchdog-hang-test", failure_threshold=1, recovery_timeout=1e9
    )
    be = pipeline.wrap_spf_backend(TpuSpfBackend(breaker=breaker))
    wd = DispatchWatchdog(pipe, interval=0.05, floor=1.0).start()
    plan = FaultPlan(seed=1, dispatch_hang={"pipeline.launch": 30.0})
    with inject(FaultInjector(plan)) as inj:
        try:
            res = be.compute(topo)
            assert np.array_equal(res.dist, ref.dist)
            assert np.array_equal(res.nexthop_words, ref.nexthop_words)
            assert inj.injected["hang:pipeline.launch"] == 1
            assert wd.hangs == 1
            assert breaker.state == "open"
            assert breaker.last_error.startswith("hang:")
            st = pipe.stats()
            assert st["hangs"] == 1
            assert st["worker-respawns"] >= 1
            # The respawned worker owns the queue: open-circuit
            # dispatches keep flowing (served from the oracle up
            # front) — the pipeline is not wedged.
            res2 = be.compute(topo)
            assert np.array_equal(res2.dist, ref.dist)
            assert pipe.stats()["max-inflight-per-key"] <= 1
        finally:
            # Free the wedged thread before teardown (it is disowned
            # and exits at its next ownership check).
            inj.release_hangs()
            wd.stop()


def test_watchdog_check_is_noop_without_overrun():
    """The sentinel declares nothing while every phase is inside its
    budget, and the floor guards cold observatory sketches."""
    pipe = DispatchPipeline(depth=1, name="wd-quiet")
    wd = DispatchWatchdog(pipe, interval=0.05, floor=5.0)
    assert wd.budget("spf.one") == 5.0  # cold: floor wins
    assert wd.check() is False  # nothing in flight
    t = pipe.submit(("k", 0), "one", run=lambda: 7)
    assert t.result(timeout=10) == 7
    assert wd.check() is False
    assert wd.hangs == 0
    pipe.close()


# -- chaos worker kills + supervised respawn ----------------------------


def test_worker_kill_respawns_and_queued_tickets_survive():
    """``FaultPlan.worker_kill`` murders the worker thread at the loop
    top (no item in hand): the unsupervised pipeline self-respawns and
    every queued ticket still completes, per-key single-inflight
    intact."""
    pipe = DispatchPipeline(depth=2, capacity=16, name="kill-test")
    plan = FaultPlan(seed=3, worker_kill={"pipeline.worker": 1})
    with inject(FaultInjector(plan)) as inj:
        tickets = [
            pipe.submit(("k", i), "one", run=lambda i=i: i * i)
            for i in range(6)
        ]
        for i, t in enumerate(tickets):
            assert t.result(timeout=15) == i * i
        assert inj.injected["kill:pipeline.worker"] == 1
    pipe.drain(timeout=10)
    st = pipe.stats()
    assert st["worker-crashes"] == 1
    assert st["worker-respawns"] >= 1
    assert st["max-inflight-per-key"] <= 1
    pipe.close()


def test_supervisor_watch_worker_respawns_killed_pipeline_worker():
    """Supervised pipeline (``Supervisor.watch_worker``): the worker's
    chaos death marshals to the home loop as a CrashNotice, the
    RestartPolicy backoff fires, and ``respawn()`` brings a fresh
    thread up over the surviving queue."""
    from holo_tpu.resilience.supervisor import RestartPolicy, Supervisor
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    home = EventLoop(clock=VirtualClock())
    sup = Supervisor(RestartPolicy(base_delay=0.5, jitter=0.0)).install(home)
    pipe = DispatchPipeline(depth=2, name="supkill")
    pname = sup.watch_worker(pipe, "supkill")
    assert pname == "worker:supkill"
    assert pipe.on_worker_crash is not None

    def wait(cond, what):
        deadline = time.monotonic() + 10
        while not cond() and time.monotonic() < deadline:
            time.sleep(0.01)
            home.run_until_idle()  # pump CrashNotice / RestartDue
        assert cond(), what

    # Spawn the worker with one completed dispatch, then kill its idle
    # loop — no submit races the death, so ONLY the supervisor path can
    # bring it back.
    assert pipe.submit(("k", 0), "one", run=lambda: 1).result(timeout=10) == 1
    plan = FaultPlan(seed=3, worker_kill={"pipeline.worker": 1})
    with inject(FaultInjector(plan)):
        wait(lambda: pipe.stats()["worker-crashes"] == 1, "worker kill seen")
        wait(lambda: sup.crashes.get(pname) == 1, "crash notice marshaled")
        home.advance(1.0)  # backoff expires -> RestartDue -> respawn()
        wait(lambda: sup.restarts.get(pname) == 1, "supervised respawn")
    assert pipe.stats()["worker-respawns"] >= 1
    # The respawned worker serves the queue.
    assert pipe.submit(("k", 1), "one", run=lambda: 2).result(timeout=10) == 2
    pipe.close()


# -- transient-retry taxonomy -------------------------------------------


def test_transient_error_retried_before_breaker_counts():
    """A transient-classified launch failure gets one jittered-backoff
    retry BEFORE the breaker sees anything; recovery leaves zero
    strikes on the circuit."""
    overload.configure_retry(
        overload.RetryPolicy(retries=1, base_delay=0.0, jitter=0.0)
    )
    br = CircuitBreaker(
        "retry-transient", failure_threshold=3, recovery_timeout=1e9
    )
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) == 1:
            raise OSError("connection reset by peer")
        return "handle"

    verdict, guard, handle = _guarded_launch(br, "test.flaky", flaky)
    assert verdict == "ok" and handle == "handle"
    assert len(calls) == 2
    assert br.consecutive_failures == 0 and br.state == "closed"
    guard.success()


def test_deterministic_error_goes_straight_to_fallback():
    """A deterministic error (shape bug: retrying is pure added
    latency) is NOT retried — one call, one breaker strike, fallback
    verdict."""
    overload.configure_retry(
        overload.RetryPolicy(retries=1, base_delay=0.0, jitter=0.0)
    )
    br = CircuitBreaker(
        "retry-deterministic", failure_threshold=3, recovery_timeout=1e9
    )
    calls = []

    def broken():
        calls.append(1)
        raise RuntimeError("dimension mismatch in gather")

    verdict, guard, handle = _guarded_launch(br, "test.broken", broken)
    assert verdict == "fallback" and handle is None
    assert len(calls) == 1
    assert br.consecutive_failures == 1


def test_transient_exhaustion_still_strikes_breaker():
    """Retries are bounded: a persistently transient error burns its
    retry then strikes the breaker exactly once."""
    overload.configure_retry(
        overload.RetryPolicy(retries=1, base_delay=0.0, jitter=0.0)
    )
    br = CircuitBreaker(
        "retry-exhausted", failure_threshold=3, recovery_timeout=1e9
    )
    calls = []

    def down():
        calls.append(1)
        raise OSError("UNAVAILABLE: relay endpoint down")

    verdict, _guard, _handle = _guarded_launch(br, "test.down", down)
    assert verdict == "fallback"
    assert len(calls) == 2  # original + one retry
    assert br.consecutive_failures == 1


def test_is_transient_classification():
    assert overload.is_transient(OSError("boom"))
    assert overload.is_transient(RuntimeError("DEADLINE_EXCEEDED: slow"))
    assert overload.is_transient(RuntimeError("collective timed out"))
    assert not overload.is_transient(RuntimeError("bad gather shape"))
    from holo_tpu.resilience.faults import InjectedFault

    # Chaos faults carry no transient marker: injected strike counts
    # (dispatch_fail burn-downs) are preserved exactly.
    assert not overload.is_transient(InjectedFault("forced failure"))


def test_retry_backoff_is_deterministic_and_jittered():
    p = overload.RetryPolicy(retries=2, base_delay=0.1, jitter=0.5)
    a = p.backoff("spf.one", 1)
    b = p.backoff("spf.one", 1)
    c = p.backoff("spf.one", 2)
    assert a == b  # seeded by (context, attempt): reproducible
    assert 0.1 <= a <= 0.1 * 1.5
    assert c >= 0.2  # exponential base doubles per attempt


# -- chaos storms: digest parity under flood / hang ----------------------


def test_advisory_flood_storm_sheds_only_advisory_fib_parity():
    """ISSUE 19 chaos acceptance: a queue_flood advisory storm riding
    the live pipeline sheds ONLY advisory tickets; the correctness
    causal digest and final FIB are byte-identical to the flood-free
    control of the same seeded storm."""
    from holo_tpu.spf.synth_storm import run_convergence_storm

    def arm(flood):
        pipe = pipeline.configure_process_pipeline(depth=2, capacity=8)
        inj = FaultInjector(FaultPlan(seed=9))
        hook = None
        if flood:
            def hook(net, index, now):
                if index % 5 == 0:
                    inj.queue_flood(pipe, 24)
        _report, digest, net = run_convergence_storm(
            n_routers=40, events=16, seed=9,
            spf_backend=pipeline.wrap_spf_backend(TpuSpfBackend(64)),
            event_hook=hook,
        )
        pipe.drain(timeout=30)
        return digest, dict(net.kernel.fib), pipe.stats()

    d_ctl, fib_ctl, st_ctl = arm(flood=False)
    d_fld, fib_fld, st_fld = arm(flood=True)
    assert d_fld == d_ctl, "flood perturbed the correctness causal timeline"
    assert fib_fld == fib_ctl
    assert st_fld["shed-by-class"].get("advisory", 0) > 0
    assert st_fld["shed-by-class"].get("correctness", 0) == 0
    assert st_ctl["sheds"] == 0


def test_watchdog_hang_mid_storm_fib_parity():
    """A mid-storm launch hang abandoned by the watchdog leaves the
    final FIB byte-identical to the unfaulted control — the abandoned
    dispatch is served from the bit-identical oracle and the respawned
    worker finishes the storm."""
    from holo_tpu.spf.synth_storm import run_convergence_storm

    def arm(hang):
        pipe = pipeline.configure_process_pipeline(depth=2)
        breaker = CircuitBreaker(
            f"storm-hang-{hang}", failure_threshold=3,
            recovery_timeout=1e9,
        )
        wd = inj = None
        if hang:
            # The floor must clear a REAL first-compile launch wall at
            # this scale, or merely-slow dispatches get spuriously
            # abandoned mid-chain; only the injected 30s wedge may trip.
            wd = DispatchWatchdog(pipe, interval=0.1, floor=4.0).start()
            inj = FaultInjector(
                FaultPlan(seed=13, dispatch_hang={"pipeline.launch": 30.0})
            )
        cm = inject(inj) if inj is not None else None
        if cm is not None:
            cm.__enter__()
        try:
            _r, _d, net = run_convergence_storm(
                n_routers=40, events=12, seed=13,
                spf_backend=pipeline.wrap_spf_backend(
                    TpuSpfBackend(64, breaker=breaker)
                ),
            )
            pipe.drain(timeout=30)
            return dict(net.kernel.fib), pipe.stats(), wd
        finally:
            if inj is not None:
                inj.release_hangs()
            if cm is not None:
                cm.__exit__(None, None, None)
            if wd is not None:
                wd.stop()

    fib_ctl, _st_ctl, _ = arm(hang=False)
    fib_hang, st_hang, wd = arm(hang=True)
    assert fib_hang == fib_ctl
    assert wd.hangs == 1
    assert st_hang["hangs"] == 1
    assert st_hang["worker-respawns"] >= 1
    assert st_hang["max-inflight-per-key"] <= 1

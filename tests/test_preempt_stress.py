"""Thread-stress over the preemptive-isolation machinery.

SURVEY §5 calls thread-sanitizing mandatory once the borrow checker is
gone; TSan doesn't apply to Python, so this is the equivalent: hammer
the cross-thread paths (ThreadedLoop sends, LoopRouter routing,
marshalled calls, register/unregister churn) from many producer threads
at once and assert nothing is lost, duplicated, or deadlocked.  Run
with higher iteration counts via HOLO_TPU_STRESS_N.
"""

import os
import threading
import time

from holo_tpu.utils.preempt import (
    CallRunner,
    InstanceHandle,
    LoopRouter,
    ThreadedLoop,
    _MarshalCall,
)
from holo_tpu.utils.runtime import Actor, EventLoop, RealClock

N = int(os.environ.get("HOLO_TPU_STRESS_N", "2000"))


class Counter(Actor):
    def __init__(self, name):
        self.name = name
        self.seen = []

    def handle(self, msg):
        self.seen.append(msg)


def test_cross_thread_sends_lossless():
    """Many producer threads blast messages at actors spread over
    several ThreadedLoops through one LoopRouter: every message arrives
    exactly once, none deadlock the pumps."""
    primary = EventLoop(clock=RealClock())
    router = LoopRouter(primary)
    loops = [ThreadedLoop(f"stress{i}").start() for i in range(4)]
    counters = []
    for i, tl in enumerate(loops):
        c = Counter(f"actor{i}")
        tl.register(c)
        router.register_remote(c.name, tl)
        counters.append(c)
    pc = Counter("primary-actor")
    primary.register(pc)
    counters.append(pc)

    n_threads = 8

    def producer(t):
        for k in range(N):
            target = counters[(t + k) % len(counters)].name
            assert router.send(target, (t, k))

    threads = [
        threading.Thread(target=producer, args=(t,))
        for t in range(n_threads)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        primary.run_until_idle()
        if sum(len(c.seen) for c in counters) == n_threads * N:
            break
        time.sleep(0.01)
    total = sum(len(c.seen) for c in counters)
    assert total == n_threads * N, f"lost messages: {total}"
    # Exactly-once: no duplicates anywhere.
    for c in counters:
        assert len(set(c.seen)) == len(c.seen)
    for tl in loops:
        tl.stop()


def test_marshalled_calls_serialize_on_owner_threads():
    """InstanceHandle method calls from several threads all run on the
    instance's own pump thread (single-writer preserved under load), and
    marshalled callbacks all land on the primary loop."""
    primary = EventLoop(clock=RealClock())
    primary.register(CallRunner(), name="call-runner")

    class Inst(Actor):
        name = "inst"

        def __init__(self):
            self.count = 0
            self.threads = set()

        def bump(self, k):
            self.threads.add(threading.get_ident())
            self.count += 1  # unsynchronized on purpose
            return self.count

        def handle(self, msg):
            pass

    inst = Inst()
    tl = ThreadedLoop("inst-loop").start()
    tl.register(inst)
    handle = InstanceHandle(inst, tl)

    cb_hits = []

    def cb(v):
        cb_hits.append((threading.get_ident(), v))

    n_threads, per = 6, max(50, N // 20)

    def caller():
        for k in range(per):
            handle.bump(k)
            primary.send("call-runner", _MarshalCall(cb, (k,)))

    threads = [threading.Thread(target=caller) for _ in range(n_threads)]
    for th in threads:
        th.start()
    main_thread = threading.get_ident()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline and any(
        th.is_alive() for th in threads
    ):
        primary.run_until_idle()
        time.sleep(0.005)
    for th in threads:
        th.join(timeout=5)
        assert not th.is_alive(), "marshalled call deadlocked"
    primary.run_until_idle()
    # Single-writer: every bump ran on the ONE pump thread, so the
    # unsynchronized counter still reached the exact total.
    assert inst.threads == {tl._thread.ident}
    assert inst.count == n_threads * per
    # Callbacks all executed on the primary loop's (this) thread.
    assert len(cb_hits) == n_threads * per
    assert {t for t, _ in cb_hits} == {main_thread}
    tl.stop()


def test_register_unregister_churn_under_fire():
    """Remote actors appear and disappear while senders keep firing:
    sends to a de-registered name fail cleanly (False), never crash a
    pump or mis-deliver to the primary loop."""
    primary = EventLoop(clock=RealClock())
    router = LoopRouter(primary)
    stop = threading.Event()
    errors = []

    def churner():
        i = 0
        try:
            while not stop.is_set():
                tl = ThreadedLoop(f"churn{i}").start()
                c = Counter(f"ghost{i}")
                tl.register(c)
                router.register_remote(c.name, tl)
                time.sleep(0.001)
                router.unregister_remote(c.name)
                tl.stop()
                i += 1
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def sender():
        k = 0
        try:
            while not stop.is_set():
                # Whatever ghost currently exists — or not.
                router.send(f"ghost{k % 50}", k)
                k += 1
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=churner)] + [
        threading.Thread(target=sender) for _ in range(3)
    ]
    for th in threads:
        th.start()
    time.sleep(1.5)
    stop.set()
    for th in threads:
        th.join(timeout=10)
        assert not th.is_alive()
    assert not errors, errors
    # Nothing leaked onto the primary loop's inboxes for ghost names.
    assert not any(
        name.startswith("ghost") for name in primary.actors
    )

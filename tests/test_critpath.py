"""Critical-path ledger (ISSUE 17): cut-model invariants, chaos
phase-attribution, determinism, and the disarmed one-check gate.

The cut-model tests fuzz the telescoping invariant (phase sum ==
end-to-end wall for ANY stamp subset, clamped or missing).  The chaos
tests drive the REAL paths — ``TpuSpfBackend`` under an injected
``FaultPlan.dispatch_delay`` (must book to ``device``), a real
``DispatchPipeline`` per-key ordering stall (must book to
``queue_wait``), the scalar-fallback close (must book to ``fallback``)
— at unit scale and over the seeded storm, where the injected delay
must inflate the device phase while the causal digest stays
byte-identical.  ``explain --critical-path`` must render byte-identical
output across two same-seed runs, and the disarmed path must cost one
module-global check (no clock read), same structural gate as the
observatory's.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from holo_tpu import telemetry
from holo_tpu.resilience import faults
from holo_tpu.telemetry import convergence, critpath, observatory, profiling
from holo_tpu.telemetry.critpath import (
    PHASES,
    CritPathLedger,
    _decompose,
    _Rec,
    _verdict,
)


@pytest.fixture(autouse=True)
def _reset_critpath_state():
    yield
    critpath.configure(0)
    convergence.configure(0)
    observatory.configure(enabled=False)
    profiling.set_device_profiling(False)
    profiling.set_stage_timer(None)


# -- cut model -----------------------------------------------------------

_STAMPS = (
    "sched", "enqueue", "launch0", "marshal0", "marshal1",
    "device_end", "force0", "force1", "spf", "rib", "t_end",
)


def test_phase_sum_equals_wall_fuzzed():
    """The telescoping invariant: for ANY subset of stamps at ANY
    values (ordered, disordered, out of range), every phase is
    non-negative and the vector sums to the wall exactly."""
    rng = random.Random(17)
    for _ in range(2000):
        rec = _Rec("lsa", t0=rng.uniform(0.0, 2.0))
        for stamp in _STAMPS:
            if rng.random() < 0.7:
                setattr(rec, stamp, rng.uniform(0.0, 10.0))
        t_done = max(rng.uniform(0.0, 10.0), rec.t0)
        fallback = rng.random() < 0.3
        phases = _decompose(rec, t_done, fallback)
        assert set(phases) == set(PHASES)
        for name, v in phases.items():
            assert v >= 0.0, (name, v)
        assert abs(sum(phases.values()) - (t_done - rec.t0)) < 1e-9
        if fallback:
            assert phases["device"] == 0.0


def test_stampless_event_is_all_unattributed():
    rec = _Rec("bfd", t0=1.0)
    phases = _decompose(rec, 3.0, False)
    assert phases["unattributed"] == 2.0
    assert sum(phases.values()) == 2.0


def test_unpipelined_hold_books_as_coalesce_not_queue():
    # No enqueue stamp: sched→marshal is the delay-FSM hold.
    rec = _Rec("lsa", t0=0.0)
    rec.sched, rec.marshal0, rec.marshal1, rec.t_end = 0.1, 0.5, 0.6, 0.7
    phases = _decompose(rec, 0.7, False)
    assert phases["coalesce_wait"] == pytest.approx(0.4)
    assert phases["queue_wait"] == 0.0
    assert phases["marshal"] == pytest.approx(0.1)


def test_verdict_partition_and_tie_break():
    zero = dict.fromkeys(PHASES, 0.0)
    assert _verdict(zero) == "host"  # all-tie breaks host-ward
    q = dict(zero, queue_wait=1.0)
    assert _verdict(q) == "queue"
    d = dict(zero, device=1.0, queue_wait=0.5)
    assert _verdict(d) == "device"
    h = dict(zero, rib=2.0, device=1.0)
    assert _verdict(h) == "host"


# -- chaos attribution: unit scale ---------------------------------------

def _close(eid):
    convergence.observe(convergence.PHASE_SPF, eids=(eid,))
    convergence.observe(convergence.PHASE_RIB, eids=(eid,))
    convergence.fib_commit(eids=(eid,))


def test_injected_dispatch_delay_books_to_device_phase():
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.spf.synth import grid_topology

    convergence.configure(256)
    cp = critpath.configure(check_every=0)
    topo = grid_topology(4, 4, seed=2)
    be = TpuSpfBackend()
    be.compute(topo)  # warm: compile outside any event

    def one(plan):
        eid = convergence.begin("lsa")
        with convergence.activation((eid,)):
            with faults.inject(plan):
                be.compute(topo)
            _close(eid)
        return cp.waterfalls()[-1]

    clean = one(faults.FaultPlan())
    slow = one(faults.FaultPlan(dispatch_delay={"spf.dispatch": 0.02}))
    assert slow["phases"]["device"] >= clean["phases"]["device"] + 0.015
    # Wrong-phase attribution is a failure: the delay must NOT have
    # landed in the host/queue phases.
    for ph in ("wake", "coalesce_wait", "queue_wait", "force_wait"):
        assert slow["phases"][ph] < 0.015
    for w in (clean, slow):
        assert abs(sum(w["phases"].values()) - w["wall"]) < 1e-6


def test_per_key_ordering_stall_books_to_queue_wait():
    from holo_tpu.pipeline.dispatch import DispatchPipeline

    convergence.configure(256)
    cp = critpath.configure(check_every=0)
    pipe = DispatchPipeline(depth=2, name="cp-stall")
    gate = threading.Event()
    try:
        e1 = convergence.begin("lsa")
        with convergence.activation((e1,)):
            t1 = pipe.submit(
                "k", "spf",
                launch=lambda: "h",
                finish=lambda h: gate.wait(5.0) and "v1",
            )
        e2 = convergence.begin("lsa")
        with convergence.activation((e2,)):
            t2 = pipe.submit("k", "spf", run=lambda: "v2")
        time.sleep(0.15)  # worker: e1 in flight, e2 latched stalled
        gate.set()
        assert t1.result(5.0) == "v1"
        assert t2.result(5.0) == "v2"
        _close(e1)
        _close(e2)
    finally:
        gate.set()
        pipe.close()
    w2 = cp.waterfalls()[-1]
    assert w2["stalls"] >= 1
    assert w2["phases"]["queue_wait"] >= 0.1
    assert abs(sum(w2["phases"].values()) - w2["wall"]) < 1e-6


def test_force_wait_books_only_the_uncovered_seam_window():
    from holo_tpu.pipeline.dispatch import DispatchPipeline

    convergence.configure(256)
    cp = critpath.configure(check_every=0)
    # Pipelined force where the wait IS the dispatch executing: the
    # window is covered by the launch/finish stamps, so it books as
    # device — force_wait keeps only the uncovered residual (≈0).
    pipe = DispatchPipeline(depth=1, name="cp-force")
    gate = threading.Event()
    try:
        eid = convergence.begin("lsa")
        with convergence.activation((eid,)):
            t = pipe.submit(
                "kf", "spf", run=lambda: gate.wait(5.0) and "v"
            )
        threading.Timer(0.12, gate.set).start()
        assert t.result(5.0) == "v"  # blocks ≥0.1s at the seam
        _close(eid)
    finally:
        gate.set()
        pipe.close()
    w = cp.waterfalls()[-1]
    assert w["phases"]["device"] >= 0.1
    assert w["phases"]["force_wait"] < 0.05
    # A force window with NO covering dispatch stamps (the readiness
    # the caller waited on was produced elsewhere) books to force_wait.
    e2 = convergence.begin("lsa")
    cp.note_force((e2,), "b")
    time.sleep(0.06)
    cp.note_force((e2,), "e")
    _close(e2)
    w2 = cp.waterfalls()[-1]
    assert w2["phases"]["force_wait"] >= 0.05
    assert w2["verdict"] == "queue"


def test_scalar_fallback_relabels_to_fallback_phase():
    convergence.configure(256)
    cp = critpath.configure(check_every=0)
    eid = convergence.begin("lsa")
    with convergence.activation((eid,)):
        convergence.note_dispatch("spf.one", "fallback")
        time.sleep(0.01)  # the oracle's compute
        convergence.observe(convergence.PHASE_SPF, eids=(eid,))
        convergence.fib_commit(eids=(eid,))
    w = cp.waterfalls()[-1]
    assert w["fallback"] is True
    assert w["phases"]["fallback"] >= 0.008
    assert w["phases"]["device"] == 0.0
    assert w["verdict"] == "device"
    assert abs(sum(w["phases"].values()) - w["wall"]) < 1e-6


# -- chaos attribution: storm scale --------------------------------------

def test_storm_delay_inflates_device_phase_digest_identical():
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.spf.synth_storm import run_convergence_storm

    def run(plan):
        cp = critpath.configure(check_every=0)
        with faults.inject(plan):
            _rep, digest, _net = run_convergence_storm(
                n_routers=40, events=16, seed=5,
                spf_backend=TpuSpfBackend(),
            )
        q = cp.phase_quantiles()
        waterfalls = cp.waterfalls()
        return q, digest, waterfalls

    q0, d0, w0 = run(faults.FaultPlan())
    q1, d1, _w1 = run(
        faults.FaultPlan(dispatch_delay={"spf.dispatch": 0.02})
    )
    # Real sleeps are invisible to the virtual clock: same causal run.
    assert d0 == d1
    dev0 = q0.get("device", {"p50": 0.0})["p50"]
    assert q1["device"]["p50"] >= dev0 + 0.01
    # Gap-free at storm scale: every waterfall telescopes to its wall
    # and the residual stays near zero.
    assert w0
    for w in w0:
        assert abs(sum(w["phases"].values()) - w["wall"]) < 1e-6
    wall0 = q0.get("wall", {"p50": 0.0})["p50"]
    un0 = q0.get("unattributed", {"p50": 0.0})["p50"]
    assert wall0 > 0.0 and un0 < 0.01 * wall0


def test_sentinel_seeds_critpath_phase_keys():
    obs = observatory.configure(check_every=0)
    convergence.configure(256)
    cp = critpath.configure(check_every=0)
    eid = convergence.begin("lsa")
    with convergence.activation((eid,)):
        _close(eid)
    before = obs.sentinel()["seeded"]
    cp.checkpoint()
    assert obs.sentinel()["seeded"] > before


# -- surfaces ------------------------------------------------------------

def test_explain_critical_path_byte_identical(capsys):
    from holo_tpu.tools.cli import main as cli_main

    argv = [
        "explain", "--critical-path", "--storm", "40",
        "--events", "16", "--seed", "5",
    ]
    assert cli_main(argv) == 0
    out1 = capsys.readouterr().out
    assert cli_main(argv) == 0
    out2 = capsys.readouterr().out
    assert out1 == out2
    assert "critical path —" in out1
    assert "phase ledger (cut order):" in out1
    # The CLI disarmed the ledger on exit.
    assert critpath.active() is None


def test_explain_critical_path_json_empty_workload(capsys):
    import json as _json

    from holo_tpu.tools.cli import main as cli_main

    assert cli_main(
        ["explain", "--critical-path", "--k", "6", "--batch", "4",
         "--reps", "4", "--json"]
    ) == 0
    doc = _json.loads(capsys.readouterr().out)
    cp = doc["critical_path"]
    assert cp["completed"] == 0  # no convergence events in the mix
    assert cp["phases"] == [] and cp["events"] == []


def test_provider_leaf_carries_critical_path():
    from holo_tpu.telemetry.provider import TelemetryStateProvider

    convergence.configure(256)
    critpath.configure(check_every=0)
    eid = convergence.begin("lsa")
    with convergence.activation((eid,)):
        _close(eid)
    st = TelemetryStateProvider().get_state()["holo-telemetry"]
    leaf = st["critical-path"]
    assert leaf["completed"] >= 1
    assert leaf["verdicts"]["host"] >= 1
    assert "phases" in leaf


def test_device_residency_ledger_sums_planes():
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.spf.synth import grid_topology
    from holo_tpu.telemetry import residency

    be = TpuSpfBackend()
    be.compute(grid_topology(4, 4, seed=2))
    snap = residency.snapshot()
    assert snap["total-bytes"] > 0
    assert snap["planes"]["spf-graph"]["entries"] >= 1
    assert snap["planes"]["spf-graph"]["bytes"] > 0
    # The gauge family samples the same sums at scrape time.
    vals = telemetry.snapshot(prefix="holo_device_resident_bytes")
    assert any(v > 0 for v in vals.values())


def test_wait_seconds_carries_event_exemplar():
    from holo_tpu.pipeline.dispatch import DispatchPipeline
    from holo_tpu.telemetry.provider import _exemplar_leaf

    convergence.configure(256)
    pipe = DispatchPipeline(depth=1, name="cp-exemplar")
    gate = threading.Event()
    try:
        eid = convergence.begin("lsa")
        with convergence.activation((eid,)):
            t = pipe.submit(
                "ke", "spf", run=lambda: gate.wait(5.0) and "v"
            )
        threading.Timer(0.05, gate.set).start()
        assert t.result(5.0) == "v"  # blocked: the wait observes
        convergence.fib_commit(eids=(eid,))
    finally:
        gate.set()
        pipe.close()
    fams = {f.name: f for f in telemetry.registry().families()}
    hist = fams["holo_pipeline_wait_seconds"]
    leaves = [_exemplar_leaf(child) for _key, child in hist.children()]
    joined = ";".join(leaves)
    assert "event_id=" in joined or "span_id=" in joined


# -- disarmed contract ---------------------------------------------------

def test_disarmed_seams_are_one_global_check(monkeypatch):
    assert critpath.active() is None

    def boom():
        raise AssertionError("disarmed seam read the clock")

    monkeypatch.setattr(profiling, "clock", boom)
    critpath.note_enqueue((1, 2))
    critpath.note_launch((1,), "b")
    critpath.note_finish((1,), "e")
    critpath.note_force((1,), "b")
    critpath.note_stall((1,))
    # The profiling phase hook and convergence hook are uninstalled.
    assert profiling._PHASE_HOOK is None
    assert convergence._CP_HOOK is None
    with profiling.stage("x.y", "marshal"):
        pass  # no hook dispatch, no clock read via the hook


def test_hooks_install_and_uninstall_with_configure():
    cp = critpath.configure(check_every=0)
    assert profiling._PHASE_HOOK is not None
    assert convergence._CP_HOOK is cp
    critpath.configure(0)
    assert profiling._PHASE_HOOK is None
    assert convergence._CP_HOOK is None


def test_capacity_bound_evicts_oldest_open_record():
    cp = CritPathLedger(capacity=4, check_every=0)
    for eid in range(8):
        cp.ev_begin(eid, "lsa")
    assert len(cp._recs) == 4
    assert set(cp._recs) == {4, 5, 6, 7}
    assert cp.stats()["dropped"] == 4

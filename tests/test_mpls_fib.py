"""MPLS label routes to the FIB: LDP LIB x RIB -> LFIB programming.

Reference: holo-routing/src/rib.rs:152-212 (LIB merge) and
netlink.rs:30-223 (AF_MPLS route install incl. label stacks).
"""

import struct
from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.utils.southbound import LabelInstallMsg, Nexthop, Protocol


def test_netlink_mpls_payload_encoding():
    """AF_MPLS swap + IP-route label push encode the right attributes
    (checked at the byte level: no MPLS kernel module in this container)."""
    from holo_tpu.routing import netlink as nl

    k = nl.NetlinkKernel.__new__(nl.NetlinkKernel)  # no socket needed
    k.table = nl.RT_TABLE_MAIN
    k._links = {"eth0": 7}

    # label stack records: 20-bit label << 12, bottom-of-stack on last
    assert nl.NetlinkKernel._mpls_stack((100,)) == struct.pack(
        ">I", (100 << 12) | 0x100
    )
    assert nl.NetlinkKernel._mpls_stack((16001, 17)) == struct.pack(
        ">I", 16001 << 12
    ) + struct.pack(">I", (17 << 12) | 0x100)

    nh = Nexthop(addr=A("10.0.0.2"), ifname="eth0", labels=(10042,))
    payload = k._label_payload(10017, frozenset({nh}))
    # rtmsg header: AF_MPLS family, /20 "prefix" (one label record)
    assert payload[0] == nl.AF_MPLS and payload[1] == 20
    def attrs_of(buf):
        out = {}
        off = 12
        while off + 4 <= len(buf):
            ln, t = struct.unpack_from("<HH", buf, off)
            out[t] = buf[off + 4 : off + ln]
            off += (ln + 3) & ~3
        return out
    attrs = attrs_of(payload)
    assert attrs[nl.RTA_DST] == nl.NetlinkKernel._mpls_stack((10017,))
    assert attrs[nl.RTA_NEWDST] == nl.NetlinkKernel._mpls_stack((10042,))
    assert attrs[nl.RTA_VIA][2:] == A("10.0.0.2").packed
    assert struct.unpack("<i", attrs[nl.RTA_OIF])[0] == 7

    # pop (PHP): no outgoing labels -> no RTA_NEWDST
    pop = k._label_payload(10017, frozenset({Nexthop(addr=A("10.0.0.2"), ifname="eth0")}))
    assert nl.RTA_NEWDST not in attrs_of(pop)

    # FTN: IP route with a label push carries the MPLS encap
    ip_payload = k._route_payload(N("7.7.7.7/32"), frozenset({nh}))
    attrs = attrs_of(ip_payload)
    assert struct.unpack("<H", attrs[nl.RTA_ENCAP_TYPE])[0] == nl.LWTUNNEL_ENCAP_MPLS
    inner = attrs[nl.RTA_ENCAP]
    ln, t = struct.unpack_from("<HH", inner, 0)
    assert t == nl.MPLS_IPTUNNEL_DST
    assert inner[4:4 + ln - 4] == nl.NetlinkKernel._mpls_stack((10042,))


def test_ldp_lsp_end_to_end_lfib():
    """3 LSRs in a chain: the transit router installs a swap LFIB entry,
    the penultimate hop installs a pop (implicit-null from the egress)."""
    import ipaddress

    from holo_tpu.daemon.daemon import Daemon
    from holo_tpu.utils.netio import MockFabric
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    ds = {}
    for i, name in enumerate(("r1", "r2", "r3"), start=1):
        d = Daemon(loop=loop, netio=fabric, name=name)
        ds[name] = d
    # chain links r1-r2 (10.0.12.0/30) and r2-r3 (10.0.23.0/30)
    for proto in ("ospfv2", "ldp"):
        fabric.join("l12", f"r1.{proto}", "e12", ipaddress.ip_address("10.0.12.1"))
        fabric.join("l12", f"r2.{proto}", "e12", ipaddress.ip_address("10.0.12.2"))
        fabric.join("l23", f"r2.{proto}", "e23", ipaddress.ip_address("10.0.23.2"))
        fabric.join("l23", f"r3.{proto}", "e23", ipaddress.ip_address("10.0.23.3"))

    def conf(d, rid, ifaces):
        c = d.candidate()
        for ifname, addr in ifaces:
            c.set(f"interfaces/interface[{ifname}]/enabled", "true")
            c.set(f"interfaces/interface[{ifname}]/address", [addr])
        c.set("routing/control-plane-protocols/ospfv2/router-id", rid)
        for ifname, _ in ifaces:
            c.set(
                "routing/control-plane-protocols/ospfv2/"
                f"area[0.0.0.0]/interface[{ifname}]/interface-type",
                "point-to-point",
            )
        c.set("routing/control-plane-protocols/ldp/lsr-id", rid)
        c.set("routing/control-plane-protocols/ldp/enabled", "true")
        for ifname, _ in ifaces:
            c.set(
                f"routing/control-plane-protocols/ldp/interface[{ifname}]/name",
                ifname,
            )
        d.commit(c)

    conf(ds["r1"], "1.1.1.1", [("e12", "10.0.12.1/30")])
    conf(ds["r2"], "2.2.2.2", [("e12", "10.0.12.2/30"), ("e23", "10.0.23.2/30")])
    # r3 also owns a far stub network (the LSP's egress FEC two hops from r1)
    conf(ds["r3"], "3.3.3.3", [("e23", "10.0.23.3/30"), ("e30", "10.0.30.3/30")])
    loop.advance(120)

    far = N("10.0.30.0/30")
    # r2 (penultimate hop): transit FEC with a REAL local label; r3's
    # binding is implicit-null => pop entry (PHP), nexthop r3.
    k2 = ds["r2"].routing.rib.kernel
    pops = [
        (label, nhs)
        for label, nhs in k2.lfib.items()
        if nhs and all(nh.labels == () for nh in nhs)
    ]
    assert pops, k2.lfib
    assert any(
        nh.addr == A("10.0.23.3") for _l, nhs in pops for nh in nhs
    ), pops
    # r1: swap entry toward r2 carrying r2's (real) label for the far FEC.
    ldp2 = ds["r2"].routing.instances["ldp"]
    r2_label = ldp2.fec_table[far][0]
    k1 = ds["r1"].routing.rib.kernel
    swaps = [
        (label, nhs)
        for label, nhs in k1.lfib.items()
        if any(nh.labels == (r2_label,) for nh in nhs)
    ]
    assert swaps, (k1.lfib, r2_label)
    for _l, nhs in swaps:
        for nh in nhs:
            assert nh.addr == A("10.0.12.2")

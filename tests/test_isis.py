"""IS-IS: PDU codecs, 3-way adjacency, LSP flooding/sync, SPF routes."""

from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.protocols.isis.instance import (
    AdjacencyState,
    IsisIfConfig,
    IsisIfUpMsg,
    IsisInstance,
)
from holo_tpu.protocols.isis.packet import (
    ExtIpReach,
    ExtIsReach,
    HelloP2p,
    Lsp,
    LspId,
    P2pAdjState,
    AdjState3Way,
    Snp,
    decode_pdu,
)
from holo_tpu.utils.netio import MockFabric
from holo_tpu.utils.runtime import EventLoop, VirtualClock


def sysid(n: int) -> bytes:
    return bytes((0, 0, 0, 0, 0, n))


def test_hello_roundtrip():
    h = HelloP2p(
        circuit_type=3,
        sysid=sysid(1),
        hold_time=9,
        local_circuit_id=1,
        tlvs={
            "area_addresses": [b"\x49\x00\x01"],
            "protocols_supported": [0xCC],
            "ip_addresses": [A("10.0.0.1")],
            "p2p_adj": P2pAdjState(AdjState3Way.INITIALIZING, 1, sysid(2), 1),
        },
    )
    t, out = decode_pdu(h.encode())
    assert out.sysid == sysid(1) and out.hold_time == 9
    assert out.tlvs["p2p_adj"].neighbor_sysid == sysid(2)
    assert out.tlvs["ip_addresses"] == [A("10.0.0.1")]


def test_lsp_roundtrip_and_checksum():
    lsp = Lsp(
        2, 1200, LspId(sysid(1)), 5,
        tlvs={
            "area_addresses": [b"\x49\x00\x01"],
            "ext_is_reach": [ExtIsReach(sysid(2) + b"\x00", 10)],
            "ext_ip_reach": [ExtIpReach(N("10.0.0.0/24"), 10)],
        },
    )
    raw = lsp.encode()
    t, out = decode_pdu(raw)
    assert out.lsp_id == LspId(sysid(1)) and out.seqno == 5
    assert out.tlvs["ext_is_reach"] == [ExtIsReach(sysid(2) + b"\x00", 10)]
    assert out.tlvs["ext_ip_reach"] == [ExtIpReach(N("10.0.0.0/24"), 10)]
    # corruption must be detected
    bad = bytearray(raw)
    bad[30] ^= 0xFF
    import pytest
    from holo_tpu.utils.bytesbuf import DecodeError

    with pytest.raises(DecodeError):
        decode_pdu(bytes(bad))


def test_snp_roundtrip():
    s = Snp(2, True, sysid(3), [(1200, LspId(sysid(1)), 7, 0xBEEF)])
    t, out = decode_pdu(s.encode())
    assert out.complete and out.entries == [(1200, LspId(sysid(1)), 7, 0xBEEF)]


def mk_net(n_routers=3):
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    routers = []
    for i in range(n_routers):
        r = IsisInstance(f"is{i}", sysid(i + 1), netio=fabric.sender_for(f"is{i}"))
        loop.register(r)
        routers.append(r)
    return loop, fabric, routers


def link(loop, fabric, r1, i1, a1, r2, i2, a2, net, metric=10):
    cfg = IsisIfConfig(metric=metric)
    r1.add_interface(i1, cfg, A(a1), N(net))
    r2.add_interface(i2, cfg, A(a2), N(net))
    fabric.join(f"{r1.name}-{r2.name}", r1.name, i1, A(a1))
    fabric.join(f"{r1.name}-{r2.name}", r2.name, i2, A(a2))


def test_adjacency_and_routes_chain():
    loop, fabric, (r1, r2, r3) = mk_net(3)
    link(loop, fabric, r1, "e0", "10.0.12.1", r2, "e0", "10.0.12.2", "10.0.12.0/30", 10)
    link(loop, fabric, r2, "e1", "10.0.23.1", r3, "e0", "10.0.23.2", "10.0.23.0/30", 5)
    for r in (r1, r2, r3):
        for ifname in r.interfaces:
            loop.send(r.name, IsisIfUpMsg(ifname))
    loop.advance(30)

    assert r1.interfaces["e0"].adj.state == AdjacencyState.UP
    assert r2.interfaces["e0"].adj.state == AdjacencyState.UP
    assert r2.interfaces["e1"].adj.state == AdjacencyState.UP
    # LSDBs synchronized.
    assert set(r1.lsdb) == set(r2.lsdb) == set(r3.lsdb)
    # r1 routes to the far subnet through r2.
    route = r1.routes.get(N("10.0.23.0/30"))
    assert route is not None
    dist, nhs = route
    assert dist == 10 + 5
    assert {(ifname, str(addr)) for ifname, addr in nhs} == {("e0", "10.0.12.2")}


def test_link_failure_reroute_square():
    loop, fabric, (r1, r2, r3) = mk_net(3)
    # triangle: r1-r2 (1), r2-r3 (1), r1-r3 (10)
    link(loop, fabric, r1, "e0", "10.0.12.1", r2, "e0", "10.0.12.2", "10.0.12.0/30", 1)
    link(loop, fabric, r2, "e1", "10.0.23.1", r3, "e0", "10.0.23.2", "10.0.23.0/30", 1)
    link(loop, fabric, r1, "e1", "10.0.13.1", r3, "e1", "10.0.13.2", "10.0.13.0/30", 10)
    for r in (r1, r2, r3):
        for ifname in r.interfaces:
            loop.send(r.name, IsisIfUpMsg(ifname))
    loop.advance(30)
    dist, nhs = r1.routes[N("10.0.23.0/30")]
    assert dist == 2 and {ifn for ifn, _ in nhs} == {"e0"}

    fabric.set_link_up("is0-is1", False)
    loop.advance(30)  # hold time 9s -> adj down -> re-originate -> SPF
    route = r1.routes.get(N("10.0.23.0/30"))
    assert route is not None
    dist, nhs = route
    assert {ifn for ifn, _ in nhs} == {"e1"}
    assert dist == 10 + 1


def test_lan_dis_election_and_pseudonode():
    """Three routers on one LAN: DIS elected, pseudonode LSP, routes."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    routers = []
    for i in range(3):
        r = IsisInstance(f"is{i}", sysid(i + 1),
                         netio=fabric.sender_for(f"is{i}"))
        loop.register(r)
        routers.append(r)
    from holo_tpu.protocols.isis.instance import IsisIfConfig

    for i, r in enumerate(routers):
        cfg = IsisIfConfig(metric=10, circuit_type="broadcast",
                           priority=64 + (10 if i == 2 else 0))
        r.add_interface("e0", cfg, A(f"10.0.0.{i + 1}"), N("10.0.0.0/24"))
        fabric.join("lan", r.name, "e0", A(f"10.0.0.{i + 1}"))
    # Leaf prefix on r0 via a p2p stub iface (advertised in its LSP).
    routers[0].add_interface(
        "stub", IsisIfConfig(metric=5), A("192.168.9.1"), N("192.168.9.0/24")
    )
    for r in routers:
        loop.send(r.name, IsisIfUpMsg("e0"))
    loop.advance(60)

    # Highest priority (r2) is DIS; everyone agrees on the LAN ID.
    dis_id = sysid(3) + bytes((routers[2].interfaces["e0"].circuit_id,))
    for r in routers:
        assert r.interfaces["e0"].dis_lan_id == dis_id, r.name
    # Pseudonode LSP exists and lists all three members.
    from holo_tpu.protocols.isis.packet import LspId

    pn = LspId(sysid(3), pseudonode=routers[2].interfaces["e0"].circuit_id)
    for r in routers:
        assert pn in r.lsdb, f"{r.name} missing pseudonode LSP"
    members = {x.neighbor[:6] for x in routers[0].lsdb[pn].lsp.tlvs["ext_is_reach"]}
    assert members == {sysid(1), sysid(2), sysid(3)}
    # r2 and r1 route to r0's stub prefix across the LAN.
    for r in routers[1:]:
        route = r.routes.get(N("192.168.9.0/24"))
        assert route is not None, r.name
        dist, nhs = route
        assert dist == 10 + 5
        assert {str(a) for _, a in nhs} == {"10.0.0.1"}


def test_lan_dis_failover():
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    routers = []
    from holo_tpu.protocols.isis.instance import IsisIfConfig

    for i in range(3):
        r = IsisInstance(f"is{i}", sysid(i + 1),
                         netio=fabric.sender_for(f"is{i}"))
        loop.register(r)
        cfg = IsisIfConfig(metric=10, circuit_type="broadcast")
        r.add_interface("e0", cfg, A(f"10.0.0.{i + 1}"), N("10.0.0.0/24"))
        fabric.join("lan", r.name, "e0", A(f"10.0.0.{i + 1}"))
        routers.append(r)
    for r in routers:
        loop.send(r.name, IsisIfUpMsg("e0"))
    loop.advance(60)
    # Equal priority: highest sysid (r2) is DIS.
    assert routers[0].interfaces["e0"].dis_lan_id[:6] == sysid(3)
    # Kill the DIS: hold time expires, a new DIS takes over, old
    # pseudonode is no longer used for routing.
    loop.unregister("is2")
    loop.advance(60)
    assert routers[0].interfaces["e0"].dis_lan_id[:6] == sysid(2)
    assert routers[0].routes  # still have LAN routes via new pseudonode


def test_flooding_reduction_suppresses_redundant_floods():
    """Full-mesh triangle with flooding reduction: LSDBs still converge
    while redundant LSP transmissions drop measurably."""

    def build(reduction: bool):
        loop = EventLoop(clock=VirtualClock())
        fabric = MockFabric(loop)
        routers = []
        for i in range(3):
            r = IsisInstance(f"fr{i}", sysid(i + 1),
                             netio=fabric.sender_for(f"fr{i}"))
            r.flooding_reduction = reduction
            loop.register(r)
            routers.append(r)
        pairs = [(0, 1), (1, 2), (0, 2)]
        for a, b in pairs:
            octet = 10 * a + b + 1
            net = f"10.{octet}.0.0/30"
            link(loop, fabric, routers[a], f"e{a}{b}", f"10.{octet}.0.1",
                 routers[b], f"e{b}{a}", f"10.{octet}.0.2", net, 10)
        for r in routers:
            for ifname in r.interfaces:
                loop.send(r.name, IsisIfUpMsg(ifname))
        loop.advance(40)
        # topology change: metric bump re-originates and floods the mesh
        routers[0].interfaces["e01"].config.metric = 11
        routers[0]._originate_lsp()
        fabric.tx_log.clear()
        loop.advance(30)
        lsp_tx = 0
        from holo_tpu.protocols.isis.packet import PduType

        for _actor, _ifn, _dst, data in fabric.tx_log:
            if len(data) > 4 and data[4] in (
                int(PduType.LSP_L1), int(PduType.LSP_L2)
            ):
                lsp_tx += 1
        images = [sorted((lid.encode(), e.lsp.seqno) for lid, e in r.lsdb.items())
                  for r in routers]
        return lsp_tx, images

    tx_full, images_full = build(reduction=False)
    tx_red, images_red = build(reduction=True)
    assert images_red[0] == images_red[1] == images_red[2], (
        "LSDBs diverged under flooding reduction"
    )
    assert tx_red < tx_full, (tx_red, tx_full)


def test_flooding_reduction_leaf_delivery_soundness():
    """The soundness trap: X connects leaf W and triangle peers P, Q.
    W's LSPs must reach P and Q even with reduction enabled everywhere."""
    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    names = ["X", "P", "Q", "W"]
    routers = {}
    for i, nm in enumerate(names):
        r = IsisInstance(nm, sysid(i + 1), netio=fabric.sender_for(nm))
        r.flooding_reduction = True
        loop.register(r)
        routers[nm] = r
    X, P, Q, W = (routers[n] for n in names)
    link(loop, fabric, X, "xp", "10.1.0.1", P, "px", "10.1.0.2", "10.1.0.0/30", 10)
    link(loop, fabric, X, "xq", "10.2.0.1", Q, "qx", "10.2.0.2", "10.2.0.0/30", 10)
    link(loop, fabric, P, "pq", "10.3.0.1", Q, "qp", "10.3.0.2", "10.3.0.0/30", 10)
    link(loop, fabric, X, "xw", "10.4.0.1", W, "wx", "10.4.0.2", "10.4.0.0/30", 10)
    for r in routers.values():
        for ifname in r.interfaces:
            loop.send(r.name, IsisIfUpMsg(ifname))
    loop.advance(60)
    # W's LSP (and the whole LSDB) must be identical everywhere.
    images = {
        nm: sorted((lid.encode(), e.lsp.seqno) for lid, e in r.lsdb.items())
        for nm, r in routers.items()
    }
    assert images["P"] == images["W"] == images["Q"] == images["X"]
    # And W's prefix is routable from P and Q.
    for nm in ("P", "Q"):
        assert N("10.4.0.0/30") in dict(routers[nm].routes)


def test_lsp_retransmission_on_loss():
    loop, fabric, (r1, r2) = mk_net(2)
    link(loop, fabric, r1, "e0", "10.0.12.1", r2, "e0", "10.0.12.2", "10.0.12.0/30")
    for r in (r1, r2):
        loop.send(r.name, IsisIfUpMsg("e0"))
    loop.advance(10)
    assert set(r1.lsdb) == set(r2.lsdb)
    # Drop the next LSP flood once; retransmission must recover it.
    dropped = []

    def drop_one_lsp(linkname, dst, data):
        if data[4] in (18, 20) and not dropped:  # LSP PDU type
            dropped.append(True)
            return True
        return False

    fabric.add_drop_rule(drop_one_lsp)
    # Force a new LSP from r1 (metric change -> re-originate).
    r1.interfaces["e0"].config.metric = 99
    r1._originate_lsp()
    loop.advance(20)  # > retransmit interval
    assert dropped, "drop rule never triggered"
    e1 = r1.lsdb[list(r1.lsdb)[0]]
    lid = LspId(sysid(1))
    assert r2.lsdb[lid].lsp.seqno == r1.lsdb[lid].lsp.seqno


def test_overload_reachable_but_no_transit():
    """ISO 10589 §7.2.8.1 (reference spf.rs:563-574): an overloaded
    router's own prefixes still install, but nothing routes THROUGH it."""
    from holo_tpu.protocols.isis.instance import Adjacency, LspEntry

    loop = EventLoop(clock=VirtualClock())
    inst = IsisInstance("a", sysid(1))
    loop.register(inst)
    inst.add_interface("e0", IsisIfConfig(metric=10),
                       A("10.0.12.1"), N("10.0.12.0/24"))
    inst.interfaces["e0"].adj = Adjacency(
        sysid=sysid(2), state=AdjacencyState.UP, addr=A("10.0.12.2")
    )

    def mk(owner, nbrs, prefix, flags=0x03):
        return Lsp(
            2, 1200, LspId(sysid(owner)), 1, flags,
            tlvs={
                "ext_is_reach": [ExtIsReach(sysid(x) + b"\x00", 10)
                                 for x in nbrs],
                "ext_ip_reach": [ExtIpReach(N(prefix), 0)],
            },
        )

    for lsp in (
        mk(1, [2], "1.1.1.1/32"),
        mk(2, [1, 3], "2.2.2.2/32", flags=0x03 | 0x04),  # overloaded
        mk(3, [2], "3.3.3.3/32"),
    ):
        lsp.encode()
        inst.lsdb[lsp.lsp_id] = LspEntry(lsp, 0.0)
    inst.run_spf()
    # B itself is reachable (its loopback installs)…
    assert inst.routes[N("2.2.2.2/32")][0] == 10
    # …but C, only reachable THROUGH overloaded B, is not.
    assert N("3.3.3.3/32") not in inst.routes


def test_ipv6_reach_tlv_chunking_roundtrip():
    """15 full-length /128 entries exceed one TLV body (255B): the
    encoder must split them and the decoder must recover all of them."""
    from ipaddress import IPv6Network

    prefixes = [IPv6Network(f"2001:db8::{i:x}/128") for i in range(1, 16)]
    lsp = Lsp(
        2, 1200, LspId(sysid(1)), 1,
        tlvs={"ipv6_reach": [ExtIpReach(p, i)
                             for i, p in enumerate(prefixes)]},
    )
    raw = lsp.encode()
    t, out = decode_pdu(raw)
    assert [r.prefix for r in out.tlvs["ipv6_reach"]] == prefixes
    assert [r.metric for r in out.tlvs["ipv6_reach"]] == list(range(15))


def test_live_ipv6_origination_and_hostname():
    """Two live routers: IPv6 reachability and hostnames must flow from
    ORIGINATION (TLV 232/236/137), not just be decodable (RFC 5308/5301)."""
    from ipaddress import IPv6Address, IPv6Network

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    from holo_tpu.protocols.isis.instance import IsisIfConfig

    routers = []
    for i in (1, 2):
        r = IsisInstance(f"is{i}", sysid(i),
                         netio=fabric.sender_for(f"is{i}"))
        loop.register(r)
        r.add_interface(
            "e0", IsisIfConfig(metric=10),
            A(f"10.0.0.{i}"), N("10.0.0.0/24"),
            addr6=IPv6Address(f"fe80::{i}"),
            prefix6=IPv6Network(f"2001:db8:{i}::/64"),
        )
        fabric.join("wire", r.name, "e0", A(f"10.0.0.{i}"))
        routers.append(r)
    for r in routers:
        loop.send(r.name, IsisIfUpMsg("e0"))
    loop.advance(60)
    r1, r2 = routers
    # v6 route with the neighbor's link-local as next hop.
    route = r1.routes.get(IPv6Network("2001:db8:2::/64"))
    assert route is not None, "no v6 route from live origination"
    dist, nhs = route
    assert dist == 20  # dist(r2)=10 + advertised prefix metric 10
    assert {str(a) for _, a in nhs} == {"fe80::2"}
    # Hostname learned from the neighbor's LSP.
    assert r1.hostnames.get(sysid(2)) == "is2"
    assert r2.hostnames.get(sysid(1)) == "is1"
    # protocols_supported advertises IPv6 (NLPID 0x8E).
    own = r2.lsdb[LspId(sysid(1))].lsp
    assert 0x8E in own.tlvs["protocols_supported"]


def test_isis_authentication():
    """RFC 5304/5310: authenticated adjacency + LSDB sync; key mismatch
    and tampering drop PDUs."""
    import pytest

    from holo_tpu.protocols.isis.packet import (
        AuthCtxIsis,
        Lsp,
        LspId,
        decode_pdu,
    )
    from holo_tpu.utils.bytesbuf import DecodeError

    # codec level: round-trip + tamper for both TLV families
    for algo in ("hmac-md5", "hmac-sha256"):
        auth = AuthCtxIsis(key=b"k3y", algo=algo, key_id=9)
        lsp = Lsp(2, 1200, LspId(b"\x00\x00\x00\x00\x00\x01"), 4,
                  tlvs={"hostname": "a"})
        raw = lsp.encode(auth=auth)
        t, out = decode_pdu(raw, auth=auth)
        assert out.seqno == 4
        bad = bytearray(raw)
        bad[-1] ^= 0x40
        with pytest.raises(DecodeError):
            decode_pdu(bytes(bad), auth=auth)
        with pytest.raises(DecodeError):
            decode_pdu(raw, auth=AuthCtxIsis(key=b"other", algo=algo, key_id=9))
        # unauthenticated PDU rejected when auth required
        with pytest.raises(DecodeError):
            decode_pdu(Lsp(2, 1200, LspId(b"\x00" * 6), 1).encode(), auth=auth)

    def converge(key_a, key_b):
        loop = EventLoop(clock=VirtualClock())
        fabric = MockFabric(loop)
        insts = []
        for name, sid, addr, key in (
            ("ia", b"\x00\x00\x00\x00\x00\x0a", "10.7.0.1", key_a),
            ("ib", b"\x00\x00\x00\x00\x00\x0b", "10.7.0.2", key_b),
        ):
            inst = IsisInstance(
                name=name, sysid=sid, netio=fabric.sender_for(name),
                auth=AuthCtxIsis(key=key),
            )
            loop.register(inst)
            inst.add_interface("e0", IsisIfConfig(), A(addr), N("10.7.0.0/30"))
            fabric.join("l", name, "e0", A(addr))
            insts.append(inst)
        for inst in insts:
            loop.send(inst.name, IsisIfUpMsg("e0"))
        loop.advance(60)
        a, b = insts
        up = any(
            True for i in a.interfaces.values() for _ in i.up_adjacencies()
        )
        return up and set(a.lsdb) == set(b.lsdb)

    assert converge(b"ring0", b"ring0")
    assert not converge(b"ring0", b"wrong")


def test_isis_mt_origination_end_to_end():
    """RFC 5120 originate side: with mt_enabled the v6 reach rides the MT
    TLVs (ids 229/222/237) and an MT peer still computes v6 routes."""
    from ipaddress import IPv6Address as A6
    from ipaddress import IPv6Network as N6

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    insts = []
    for name, sid, a4, a6, p6 in (
        ("mta", b"\x00\x00\x00\x00\x00\x1a", "10.8.0.1", "fe80::8:1",
         "2001:db8:a::/64"),
        ("mtb", b"\x00\x00\x00\x00\x00\x1b", "10.8.0.2", "fe80::8:2",
         "2001:db8:b::/64"),
    ):
        inst = IsisInstance(
            name=name, sysid=sid, netio=fabric.sender_for(name),
            mt_enabled=True,
        )
        loop.register(inst)
        inst.add_interface(
            "e0", IsisIfConfig(), A(a4), N("10.8.0.0/30"),
            addr6=A6(a6), prefix6=N6(p6),
        )
        fabric.join("l", name, "e0", A(a4))
        insts.append(inst)
    for inst in insts:
        loop.send(inst.name, IsisIfUpMsg("e0"))
    loop.advance(60)
    a, b = insts
    # our own LSP carries MT TLVs, not plain ipv6 reach
    own = a.lsdb[LspId(a.sysid)].lsp
    assert own.tlvs.get("mt_ids"), own.tlvs.keys()
    assert own.tlvs.get("mt_ipv6_reach") and not own.tlvs.get("ipv6_reach")
    # the peer computes the v6 route from the MT topology
    r6 = b.routes.get(N6("2001:db8:a::/64"))
    assert r6 is not None, sorted(map(str, b.routes))


def test_isis_sr_prefix_sids():
    """RFC 8667: SRGB capability + prefix-SID sub-TLVs resolve to labels."""
    from holo_tpu.utils.sr import PrefixSid, SrConfig, Srgb

    loop = EventLoop(clock=VirtualClock())
    fabric = MockFabric(loop)
    insts = []
    for name, sid, addr, lo in (
        ("sa", b"\x00\x00\x00\x00\x00\x2a", "10.9.0.1", "1.1.1.1"),
        ("sb", b"\x00\x00\x00\x00\x00\x2b", "10.9.0.2", "2.2.2.2"),
    ):
        loop_pfx = N(f"{lo}/32")
        sr = SrConfig(
            enabled=True, srgb=Srgb(16000, 23999),
            prefix_sids={loop_pfx: PrefixSid(loop_pfx, int(lo[0]) * 10)},
        )
        inst = IsisInstance(
            name=name, sysid=sid, netio=fabric.sender_for(name), sr=sr
        )
        loop.register(inst)
        inst.add_interface("e0", IsisIfConfig(), A(addr), N("10.9.0.0/30"))
        inst.add_interface(
            "lo", IsisIfConfig(metric=0), A(lo), loop_pfx
        )
        fabric.join("l", name, "e0", A(addr))
        insts.append(inst)
    for inst in insts:
        loop.send(inst.name, IsisIfUpMsg("e0"))
    loop.advance(60)
    a, b = insts
    # a resolves b's loopback SID through its SRGB: 16000 + 20
    entry = a.sr_labels.get(N("2.2.2.2/32"))
    assert entry is not None, a.sr_labels
    label, route = entry
    assert label == 16000 + 20  # our SRGB base + the advertised index
    # and the capability TLV round-tripped through b's LSP
    e = a.lsdb[LspId(b.sysid)].lsp
    assert e.tlvs.get("sr_cap") == (16000, 8000)


def test_yang_notifications_adjacency_lifecycle():
    """Reference holo-isis northbound/notification.rs: adjacency up/down,
    database-overload, and auth failures reach the notif_cb sink."""
    loop, fabric, (r1, r2) = mk_net(2)
    notifs = []
    r1.notif_cb = notifs.append
    link(loop, fabric, r1, "e0", "10.0.12.1", r2, "e0", "10.0.12.2",
         "10.0.12.0/30", 10)
    for r in (r1, r2):
        loop.send(r.name, IsisIfUpMsg("e0"))
    loop.advance(30)
    assert r1.interfaces["e0"].adj.state == AdjacencyState.UP
    ups = [n for n in notifs if "ietf-isis:adjacency-state-change" in n]
    assert ups, notifs
    body = ups[-1]["ietf-isis:adjacency-state-change"]
    assert body["state"] == "up"
    assert body["interface-name"] == "e0"
    assert body["neighbor-system-id"].count(".") == 2  # dotted sysid
    # Hold-time expiry: silence r2 so r1's hold timer fires.
    notifs.clear()
    loop.unregister(r2.name)
    loop.advance(120)
    downs = [n for n in notifs if "ietf-isis:adjacency-state-change" in n
             and n["ietf-isis:adjacency-state-change"]["state"] == "down"]
    assert downs, notifs
    # Overload toggling emits database-overload and re-originates.
    notifs.clear()
    r1.set_overload(True)
    ov = [n for n in notifs if "ietf-isis:database-overload" in n]
    assert ov and ov[0]["ietf-isis:database-overload"]["overload"] == "on"
    r1.set_overload(False)
    assert any(
        n.get("ietf-isis:database-overload", {}).get("overload") == "off"
        for n in notifs
    )


def test_yang_notification_auth_failure():
    """A PDU failing digest verification raises the authentication-failure
    notification (wrong TLV type raises the -type-failure variant)."""
    from holo_tpu.protocols.isis.packet import AuthCtxIsis
    from holo_tpu.utils.netio import NetRxPacket

    loop, fabric, (r1, r2) = mk_net(2)
    notifs = []
    r1.notif_cb = notifs.append
    r1.auth = AuthCtxIsis(key=b"right-key", algo="hmac-md5")
    link(loop, fabric, r1, "e0", "10.0.12.1", r2, "e0", "10.0.12.2",
         "10.0.12.0/30", 10)
    # r2 signs with the wrong key: digest mismatch on r1's LSP path.
    r2.auth = AuthCtxIsis(key=b"wrong-key", algo="hmac-md5")
    r2._originate_lsp(force=True)
    raw = next(iter(r2.lsdb.values())).lsp.raw
    r1.handle(NetRxPacket(ifname="e0", src=b"\x02\x00\x00\x00\x00\x02",
                          dst=None, data=raw))
    fails = [n for n in notifs if "ietf-isis:authentication-failure" in n]
    assert fails, notifs
    assert "raw-pdu" in fails[0]["ietf-isis:authentication-failure"]

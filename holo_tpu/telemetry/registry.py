"""Process-wide metrics registry: counters, gauges, histograms.

The observability analog of the reference's tokio-console/tracing
instrumentation, shaped for the TPU hot paths: every metric is a named
family with optional label dimensions; children are created lazily per
label-value tuple and updated under a per-child lock (increments are a
couple of dict hits + a float add, cheap enough for the dispatch path —
gated by :func:`holo_tpu.telemetry.set_enabled` so the overhead bench
can A/B a disabled registry).

Naming convention (documented in COMPONENTS.md):

    holo_<subsystem>_<what>[_<unit>][_total]

e.g. ``holo_spf_dispatch_seconds`` (histogram),
``holo_rib_route_adds_total`` (counter), ``holo_ibus_subscribers``
(gauge).  Counters end in ``_total``; histograms of durations end in
``_seconds`` — both Prometheus conventions, so the text exposition
(:mod:`holo_tpu.telemetry.prometheus`) needs no renaming pass.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable

# Default histogram buckets: SPF dispatches span ~100us (tiny LSDB,
# warm jit) to minutes (50k-vertex cold compile) — log-spaced seconds.
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 120.0,
)

_enabled = True

# Leaf-version stamping (ISSUE 11): every metric write advances a
# process-wide monotonic stamp and records it on the child.  The gNMI
# shared-delta fan-out engine compares stamps instead of re-walking the
# subtree: an unchanged stamp proves the whole registry-backed state
# surface is byte-identical to the previous tick (suppress-redundant
# and heartbeat become epoch comparisons).  A single-element list keeps
# the read-modify-write GIL-atomic enough: racing writers may coalesce
# increments, but the stamp always ADVANCES when anything was written,
# which is the only property the delta engine needs.
_WRITE_STAMP = [0]
# Callback-backed gauges (``set_fn``) change value at COLLECT time with
# no write to stamp — their existence disables the stamp short-circuit.
_VOLATILE = [0]


def write_stamp() -> int:
    """Monotonic stamp of the last registry write (any child)."""
    return _WRITE_STAMP[0]


def volatile_children() -> int:
    """Number of live callback-backed gauge children (their values move
    without a write, so a non-zero count voids the stamp contract)."""
    return _VOLATILE[0]


def _bump_stamp() -> int:
    s = _WRITE_STAMP[0] + 1
    _WRITE_STAMP[0] = s
    return s


# Families registered with ``stamped=False`` update their children
# WITHOUT advancing the global write stamp: the delta engine's own
# bookkeeping (render counters, sample-update tallies) must not re-arm
# the walk it instruments — otherwise every heartbeat served from the
# render cache would wake the next tick's walk, which would see the
# counter leaves changed, advance the epoch, deliver, bump again, and
# never quiesce.  Unstamped children still render on every export
# surface; their changes reach suppress-redundant subscribers
# piggybacked on the next stamped write.


def set_enabled(on: bool) -> None:
    """Global kill switch: disabled metrics become no-ops (the overhead
    bench's control arm).  Collection still works — values just freeze."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


class Counter:
    """Monotonic counter child.  ``inc`` only accepts non-negative deltas."""

    __slots__ = ("_lock", "_value", "_stamp", "_stamped")

    def __init__(self, stamped: bool = True) -> None:
        self._lock = threading.Lock()
        self._value = 0.0
        self._stamp = 0
        self._stamped = stamped

    def inc(self, amount: float = 1.0) -> None:
        if not _enabled:
            return
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount
            self._stamp = _bump_stamp() if self._stamped else _WRITE_STAMP[0]

    @property
    def value(self) -> float:
        return self._value

    @property
    def stamp(self) -> int:
        """Write-time version: the global stamp of the last mutation."""
        return self._stamp


class Gauge:
    """Point-in-time value child.  ``set_fn`` makes it callback-backed
    (sampled at collect time — queue depths, cache sizes)."""

    __slots__ = ("_lock", "_value", "_fn", "_stamp", "_stamped")

    def __init__(self, stamped: bool = True) -> None:
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn: Callable[[], float] | None = None
        self._stamp = 0
        self._stamped = stamped

    def set(self, value: float) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value = float(value)
            self._stamp = _bump_stamp() if self._stamped else _WRITE_STAMP[0]

    def inc(self, amount: float = 1.0) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value += amount
            self._stamp = _bump_stamp() if self._stamped else _WRITE_STAMP[0]

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_fn(self, fn: Callable[[], float] | None) -> None:
        # Volatility accounting: a live callback makes this child's
        # value move without a stamped write, voiding the delta
        # engine's skip-the-walk short-circuit.
        if fn is not None and self._fn is None:
            _VOLATILE[0] += 1
        elif fn is None and self._fn is not None:
            _VOLATILE[0] -= 1
        self._fn = fn

    @property
    def stamp(self) -> int:
        return self._stamp

    @property
    def value(self) -> float:
        # The kill switch covers callback-backed gauges too: the
        # overhead bench's disabled arm must not run deferred O(N)
        # sampling closures at collect time.
        if self._fn is not None and _enabled:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 — sampling must never raise
                return 0.0
        return self._value


class Histogram:
    """Fixed-boundary histogram child (cumulative at render time).

    ``observe(..., exemplar={...})`` attaches an OpenMetrics exemplar to
    the bucket the observation lands in (last writer wins): a small
    label dict — in this codebase ``{"span_id": <trace span id>}`` — so
    a scrape can jump from a latency bucket straight to the trace span
    that produced it.  Storage is lazy (one list allocated on the first
    exemplar) and O(1) per observe: just a tuple swap under the lock.
    """

    __slots__ = (
        "_lock", "buckets", "_counts", "_sum", "_count", "_exemplars",
        "_stamp", "_stamped",
    )

    def __init__(
        self,
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
        stamped: bool = True,
    ) -> None:
        self._lock = threading.Lock()
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._exemplars: list | None = None  # lazy: [(labels, value)|None]
        self._stamp = 0
        self._stamped = stamped

    def observe(self, value: float, exemplar: dict | None = None) -> None:
        if not _enabled:
            return
        i = 0
        for i, b in enumerate(self.buckets):  # noqa: B007 — small, fixed
            if value <= b:
                break
        else:
            i = len(self.buckets)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            self._stamp = _bump_stamp() if self._stamped else _WRITE_STAMP[0]
            if exemplar is not None:
                if self._exemplars is None:
                    self._exemplars = [None] * (len(self.buckets) + 1)
                self._exemplars[i] = (
                    tuple((str(k), str(v)) for k, v in exemplar.items()),
                    float(value),
                )

    def exemplars(self) -> dict[float, tuple]:
        """{bucket le -> (label pairs, observed value)} for buckets that
        have one; the +Inf bucket keys as ``float('inf')``."""
        with self._lock:
            ex = list(self._exemplars) if self._exemplars is not None else []
        out: dict[float, tuple] = {}
        for i, e in enumerate(ex):
            if e is not None:
                le = (
                    self.buckets[i]
                    if i < len(self.buckets)
                    else float("inf")
                )
                out[le] = e
        return out

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def stamp(self) -> int:
        return self._stamp

    def cumulative(self) -> list[tuple[float, int]]:
        """[(le, cumulative_count)] including the +Inf bucket."""
        with self._lock:
            counts = list(self._counts)
        out = []
        acc = 0
        for b, c in zip(self.buckets, counts):
            acc += c
            out.append((b, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out


def deferred_mean(arr) -> Callable[[], float]:
    """One-shot lazy occupancy sampler for ``Gauge.set_fn``.

    Computes ``arr.mean()`` on the FIRST call (scrape time — off the
    dispatch path, holo-lint HL105), caches the float, and releases the
    array reference so a marshal-time closure does not pin a padded
    plane for the rest of the process lifetime.
    """
    cell: list = [arr, None]

    def sample() -> float:
        if cell[1] is None:
            a, cell[0] = cell[0], None
            cell[1] = float(a.mean()) if a is not None and a.size else 0.0
        return cell[1]

    return sample


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricFamily:
    """A named metric with label dimensions; children per label tuple."""

    def __init__(
        self,
        name: str,
        kind: str,
        help: str = "",
        labelnames: tuple[str, ...] = (),
        buckets: tuple[float, ...] | None = None,
        stamped: bool = True,
    ):
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self._buckets = buckets
        self._stamped = stamped
        self._lock = threading.Lock()
        self._children: dict[tuple, object] = {}

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            values = tuple(kv[n] for n in self.labelnames)
        key = tuple(str(v) for v in values)
        if len(key) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got {key}"
            )
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    if self.kind == "histogram":
                        child = Histogram(
                            self._buckets or DEFAULT_BUCKETS,
                            stamped=self._stamped,
                        )
                    else:
                        child = _KINDS[self.kind](stamped=self._stamped)
                    self._children[key] = child
        return child

    # Label-less families proxy the single child's API so call sites
    # read `family.inc()` instead of `family.labels().inc()`.

    def _default(self):
        return self.labels()

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def set(self, value: float) -> None:
        self._default().set(value)

    def set_fn(self, fn) -> None:
        self._default().set_fn(fn)

    def observe(self, value: float, exemplar: dict | None = None) -> None:
        self._default().observe(value, exemplar)

    @property
    def value(self):
        return self._default().value

    @property
    def count(self):
        return self._default().count

    @property
    def sum(self):
        return self._default().sum

    def cumulative(self):
        return self._default().cumulative()

    def children(self) -> Iterable[tuple[tuple, object]]:
        with self._lock:
            return list(self._children.items())


class MetricsRegistry:
    """Get-or-create registry of metric families (process-wide default in
    :mod:`holo_tpu.telemetry`)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: dict[str, MetricFamily] = {}

    def _get(
        self,
        name: str,
        kind: str,
        help: str,
        labelnames: tuple[str, ...],
        buckets: tuple[float, ...] | None = None,
        stamped: bool = True,
    ) -> MetricFamily:
        fam = self._families.get(name)
        if fam is not None:
            if fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}"
                )
            return fam
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = MetricFamily(
                    name, kind, help, labelnames, buckets, stamped=stamped
                )
                self._families[name] = fam
        return fam

    def counter(
        self,
        name: str,
        help: str = "",
        labelnames: tuple[str, ...] = (),
        stamped: bool = True,
    ) -> MetricFamily:
        return self._get(name, "counter", help, labelnames, stamped=stamped)

    def gauge(
        self,
        name: str,
        help: str = "",
        labelnames: tuple[str, ...] = (),
        stamped: bool = True,
    ) -> MetricFamily:
        return self._get(name, "gauge", help, labelnames, stamped=stamped)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: tuple[str, ...] = (),
        buckets: tuple[float, ...] | None = None,
        stamped: bool = True,
    ) -> MetricFamily:
        return self._get(
            name, "histogram", help, labelnames, buckets, stamped=stamped
        )

    def families(self) -> list[MetricFamily]:
        with self._lock:
            return sorted(self._families.values(), key=lambda f: f.name)

    def snapshot(self, prefix: str | None = None) -> dict:
        """Flat JSON-able view: counters/gauges -> number, histograms ->
        {count, sum} — what bench stages attach to their emitted rows."""
        out: dict = {}
        for fam in self.families():
            if prefix is not None and not fam.name.startswith(prefix):
                continue
            for key, child in fam.children():
                label = ",".join(
                    f"{n}={v}" for n, v in zip(fam.labelnames, key)
                )
                name = f"{fam.name}{{{label}}}" if label else fam.name
                if fam.kind == "histogram":
                    out[name] = {
                        "count": child.count,
                        "sum": round(child.sum, 6),
                    }
                else:
                    out[name] = child.value
        return out

    def clear(self) -> None:
        """Drop every family (tests only — live handles go stale)."""
        with self._lock:
            self._families.clear()

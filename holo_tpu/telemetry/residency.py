"""Unified device-residency byte ledger (ISSUE 17 satellite).

Every subsystem that parks tensors on the device grew its own partial
accounting — the marshaled-graph cache reports per-device bytes, the
BGP table reports ``resident-bytes``, the SPF backends' retained
``_prev_one`` delta seeds and the tropical tile attachments reported
nothing.  This module is the one instrument that sums them all: a
``holo_device_resident_bytes{plane}`` gauge family plus a
``holo-telemetry/device-residency`` gNMI leaf with one row per plane —
the HBM budget ROADMAP item 1's tenant fleet will allocate against.

Planes
------
- ``spf-graph`` — ``DeviceGraphCache`` ELL entries (the marshaled
  DeviceGraph plane sets, including their device-resident buffers
  under a process mesh);
- ``spf-graph-partitioned`` — the cache's stacked per-partition
  residents (``PartResident.planes``; ISSUE 15);
- ``tropical`` — blocked min-plus tile attachments riding the cache
  entries (ISSUE 13);
- ``spf-prev`` — the SPF backends' retained previous-result tensors
  (``_prev_one`` delta/multipath seeds; weakref-registered so a
  dropped backend never leaks through the ledger);
- ``bgp-table`` — the 13-lane Adj-RIB-In planes (ISSUE 16, summed
  from each backend's own ``resident-bytes``).

Discipline: everything is sampled lazily at scrape/snapshot time via
``set_fn`` — a daemon that never dispatched device work pays nothing
(the modules are looked up in ``sys.modules``, never imported), and
nothing here runs on a dispatch path.  Byte sums walk result pytrees
generically (``.nbytes`` over tuples/dicts), so a new plane member
costs no new accounting code.
"""

from __future__ import annotations

import sys
import weakref

from holo_tpu import telemetry

#: the fixed plane rows (an open set — these are the documented ones)
PLANES = (
    "spf-graph", "spf-graph-partitioned", "tropical", "spf-prev",
    "bgp-table",
)

# Sampled at scrape time only (set_fn below): stamped=False so ledger
# bookkeeping never wakes the gNMI fan-out walk (delta.py discipline).
_RESIDENT = telemetry.gauge(
    "holo_device_resident_bytes",
    "Device-resident plane bytes by subsystem (marshaled SPF graphs, "
    "partitioned residents, tropical tiles, retained previous-result "
    "tensors, BGP table lanes)",
    ("plane",),
    stamped=False,
)

# Live SPF-backend registry (weakrefs: a backend dropped with its
# engine must not leak here — the bgp_table._BACKENDS idiom).
_SPF_BACKENDS: list = []


def register_spf_backend(backend) -> None:
    """Called once from ``TpuSpfBackend.__init__`` — the ledger then
    sees its retained ``_prev_one`` planes."""
    _SPF_BACKENDS.append(weakref.ref(backend))


def _live_backends() -> list:
    out, dead = [], []
    for ref in _SPF_BACKENDS:
        b = ref()
        (out if b is not None else dead).append(b if b is not None else ref)
    for ref in dead:
        _SPF_BACKENDS.remove(ref)
    return out


def _nbytes(obj, depth: int = 0) -> int:
    """Generic device-pytree byte walk: sum ``.nbytes`` over array
    leaves through tuples/lists/dicts (NamedTuple result planes,
    (Spf, Multipath) pairs, DeviceGraph...).  Depth-bounded: an
    unexpected self-referential container terminates, not recurses."""
    if obj is None or depth > 6:
        return 0
    if not isinstance(obj, (dict, list, tuple)):
        nb = getattr(obj, "nbytes", None)
        if nb is not None:
            try:
                return int(nb)
            except (TypeError, ValueError):
                return 0
        return 0
    if isinstance(obj, dict):
        return sum(_nbytes(v, depth + 1) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(_nbytes(v, depth + 1) for v in obj)
    return 0


def _graph_cache():
    """The shared DeviceGraphCache, ONLY if the engine module is
    already loaded (scrape-time laziness: never import jax here)."""
    eng = sys.modules.get("holo_tpu.ops.spf_engine")
    return None if eng is None else eng.shared_graph_cache()


def _rows() -> dict[str, dict]:
    """{plane: {"bytes": int, "entries": int}} — one walk, all planes."""
    rows = {p: {"bytes": 0, "entries": 0} for p in PLANES}
    cache = _graph_cache()
    if cache is not None:
        # Point-in-time snapshots via the cache's own accessors (its
        # lock discipline); the walks below read plane pytrees only.
        with cache._lock:
            entries = list(cache._cache.values())
        for e in entries:
            rows["spf-graph"]["bytes"] += _nbytes(tuple(e.graph))
            rows["spf-graph"]["entries"] += 1
            if e.tropical is not None:
                rows["tropical"]["bytes"] += _nbytes(tuple(e.tropical))
                rows["tropical"]["entries"] += 1
        for res in cache.partitioned_entries().values():
            planes = getattr(res, "planes", None)
            if planes is not None:
                rows["spf-graph-partitioned"]["bytes"] += _nbytes(
                    tuple(planes)
                )
            rows["spf-graph-partitioned"]["entries"] += 1
    for backend in _live_backends():
        prev = getattr(backend, "_prev_one", None)
        if not prev:
            continue
        for out in list(prev.values()):
            rows["spf-prev"]["bytes"] += _nbytes(out)
            rows["spf-prev"]["entries"] += 1
    bgm = sys.modules.get("holo_tpu.ops.bgp_table")
    if bgm is not None:
        for st in bgm.backends_stats():
            rows["bgp-table"]["bytes"] += int(st.get("resident-bytes", 0))
            rows["bgp-table"]["entries"] += len(st.get("tables", {}))
    return rows


def _plane_bytes(plane: str) -> float:
    try:
        return float(_rows()[plane]["bytes"])
    except Exception:  # noqa: BLE001 — a scrape sampler must never
        # take the exposition (or a test teardown) down.
        return 0.0


# Scrape-time samplers, one per plane row — the gauge always reads
# live sums without any subsystem having to push updates.
for _p in PLANES:
    _RESIDENT.labels(plane=_p).set_fn(
        lambda p=_p: _plane_bytes(p)
    )
del _p


def snapshot() -> dict:
    """The ``holo-telemetry/device-residency`` gNMI leaf payload (and
    the bench's residency rows): per-plane bytes/entries + the total."""
    rows = _rows()
    return {
        "total-bytes": sum(r["bytes"] for r in rows.values()),
        "planes": rows,
    }

"""Lightweight span tracer: bounded ring of completed spans, exported
as Chrome trace-event JSON (load in chrome://tracing or Perfetto).

Spans nest per thread (a threadlocal stack); the active span id is
exposed for log correlation (the daemon's JSON log formatter stamps it
on every record so log lines join against trace dumps).  The ring is
bounded — a long-running daemon keeps the most recent ``capacity``
spans, never unbounded memory.

``HOLO_TPU_TRACE_DUMP=<path>`` (checked at package import) registers an
atexit dump of the default tracer, so any run — bench stage, test,
daemon — can be traced without code changes.
"""

from __future__ import annotations

import itertools
import json
import logging
import threading
import time
from collections import deque
from contextlib import contextmanager

log = logging.getLogger("holo_tpu.telemetry")


class Span:
    __slots__ = (
        "span_id", "parent_id", "name", "start_us", "dur_us", "tid", "attrs"
    )

    def __init__(self, span_id, parent_id, name, start_us, dur_us, tid, attrs):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start_us = start_us
        self.dur_us = dur_us
        self.tid = tid
        self.attrs = attrs


class SpanTracer:
    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._spans: deque[Span] = deque(maxlen=capacity)
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.clock = time.monotonic
        self._epoch = time.monotonic()
        self.enabled = True
        # Completion tap (the flight recorder): called with each Span
        # AFTER it is appended to the ring, outside the ring lock.
        self.on_complete = None

    def use_clock(self, clock, epoch: float | None = None) -> None:
        """Swap the time source (chaos tests pass the virtual loop
        clock so span start/duration — and everything downstream, the
        flight-recorder ring included — becomes deterministic).  The
        epoch defaults to ``clock()`` at the swap, so timestamps start
        near zero under either source."""
        self.clock = clock
        self._epoch = clock() if epoch is None else epoch

    # -- context (threadlocal span stack + instance name)

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current_span_id(self) -> int | None:
        st = getattr(self._tls, "stack", None)
        return st[-1][0] if st else None

    def current_instance(self) -> str | None:
        """Innermost enclosing span's ``instance`` attribute (protocol
        instances tag their spans; log records inherit the tag)."""
        st = getattr(self._tls, "stack", None)
        if not st:
            return None
        for span_id, attrs in reversed(st):
            inst = attrs.get("instance")
            if inst is not None:
                return str(inst)
        return None

    # -- recording

    @contextmanager
    def span(self, name: str, **attrs):
        if not self.enabled:
            yield None
            return
        span_id = next(self._ids)
        st = self._stack()
        parent = st[-1][0] if st else None
        st.append((span_id, attrs))
        t0 = self.clock()
        try:
            yield span_id
        finally:
            dur = self.clock() - t0
            st.pop()
            sp = Span(
                span_id,
                parent,
                name,
                (t0 - self._epoch) * 1e6,
                dur * 1e6,
                threading.get_ident() & 0xFFFFFFFF,
                attrs,
            )
            with self._lock:
                self._spans.append(sp)
            hook = self.on_complete
            if hook is not None:
                try:
                    hook(sp)
                except Exception:  # noqa: BLE001 — a tap must never
                    # break the traced code path (holo-lint HL106: the
                    # failure is still surfaced, at debug level).
                    log.debug("span completion tap failed", exc_info=True)

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    # -- export

    def to_chrome_trace(
        self, process_name: str = "holo_tpu", spans: list[Span] | None = None
    ) -> dict:
        """Chrome trace-event JSON object format (perfetto-loadable):
        one complete ('X') event per span, µs timestamps.  ``spans``
        lets a caller render a snapshot it already took (dump() —
        otherwise a span completing concurrently could make the counted
        and rendered sets differ)."""
        events = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": 1,
                "tid": 0,
                "args": {"name": process_name},
            }
        ]
        for sp in self.spans() if spans is None else spans:
            args = {
                k: (v if isinstance(v, (int, float, bool, str)) else str(v))
                for k, v in sp.attrs.items()
            }
            args["span_id"] = sp.span_id
            if sp.parent_id is not None:
                args["parent_id"] = sp.parent_id
            events.append(
                {
                    "name": sp.name,
                    "ph": "X",
                    "ts": round(sp.start_us, 3),
                    "dur": round(sp.dur_us, 3),
                    "pid": 1,
                    "tid": sp.tid,
                    "args": args,
                }
            )
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump(self, path) -> int:
        """Write the Chrome trace JSON; returns the span count dumped."""
        spans = self.spans()
        with open(path, "w") as fh:
            json.dump(self.to_chrome_trace(spans=spans), fh)
        return len(spans)

"""Per-dispatch device-time breakdown (the deep-profiling tentpole).

PR 2's dispatch telemetry measures the SPF/FRR hot path from the host
side only: one wall-clock histogram around the whole dispatch and a
readback timer.  This module splits each dispatch span into the three
phases that actually matter for the DeltaPath incremental-SPF work —

- **marshal** — host graph/plane preparation + the (async) jit call;
- **device** — device execution, measured by ``jax.block_until_ready``
  bracketing on CPU/relay backends (an optional
  ``jax.profiler.TraceAnnotation`` path activates on a real TPU so the
  phases also land in XLA's own profiler timeline);
- **readback** — device→host materialization of the result planes.

Each phase records a nested trace sub-span AND a
``holo_profile_stage_seconds{site,stage,device}`` histogram observation
carrying an OpenMetrics **exemplar** ``{span_id=...}`` — a scrape can
jump from a latency bucket straight to the trace span that produced it.
``device="-"`` is the whole-dispatch span; under a process mesh the
device phase additionally splits into per-device completion sub-spans
(``device=<id>``, :func:`device_stages`) so a straggling shard is
attributable to its chip.

Compile-time cost attribution rides the same switch: when a backend
sees a fresh (engine, shape) bucket it calls :func:`record_cost`, which
runs ``jit(...).lower(...).compile().cost_analysis()`` and records the
XLA FLOP / bytes-accessed estimates per dispatch site — the denominator
that turns a measured device time into achieved-vs-peak utilization.

Everything is **off by default** (``[telemetry] profile-device-time``
in holod.toml, :func:`set_device_profiling` programmatically): when
disabled, :func:`stage` costs one module-global bool check and
:func:`sync` is a no-op — no extra device synchronization is added to
the dispatch path, which is what the ``bench.py profiling_overhead``
gate (<2%) holds the enabled arm to as well.  Metric updates here are
O(1) (a float and a small exemplar tuple) — nothing reads device
values or reduces arrays on the traced path (holo-lint HL101/HL105).
"""

from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager, nullcontext

from holo_tpu import telemetry

log = logging.getLogger("holo_tpu.telemetry")

_STAGE_SECONDS = telemetry.histogram(
    "holo_profile_stage_seconds",
    "Per-dispatch sub-span time (marshal / device / readback); "
    "device=<id> rows are the per-device completion split of a "
    "mesh-sharded dispatch ('-' = host-side / whole-dispatch span)",
    ("site", "stage", "device"),
)
_COST_FLOPS = telemetry.gauge(
    "holo_profile_cost_flops",
    "XLA compile-time FLOP estimate for the last-compiled shape bucket",
    ("site",),
)
_COST_BYTES = telemetry.gauge(
    "holo_profile_cost_bytes",
    "XLA compile-time bytes-accessed estimate for the last-compiled "
    "shape bucket",
    ("site",),
)

_enabled = False

# Dispatch-observatory feed (ISSUE 12): when armed, every stage
# observation is ALSO handed to the observer callback — the streaming
# quantile sketches in holo_tpu.telemetry.observatory.  One module
# global: the disarmed hot-path cost is exactly this None check.
_OBSERVER = None

# Critical-path feed (ISSUE 17): when armed, every stage's begin AND
# end edge is handed to the phase hook — the cross-thread waterfall in
# holo_tpu.telemetry.critpath stamps the active convergence events
# with marshal/device cuts.  Same discipline as _OBSERVER: one module
# global, the disarmed hot-path cost is exactly this None check.
_PHASE_HOOK = None

# Stage timer: time.perf_counter in production; the observatory's
# DeterministicTimer swaps it so a seeded workload produces
# byte-identical sketches (set_stage_timer).
_timer = time.perf_counter
_timer_overridden = False

# Dispatch context (thread-local): the backend labels its dispatch
# window with (kind, engine, shape-bucket) so the observatory can key
# sketches without new arguments threading through every stage() call.
# Only ever entered while an observer is armed — dispatch_context()
# returns a shared null context otherwise, so the un-observed hot path
# pays one global check and one call.
_ctx_local = threading.local()
_NULLCTX = nullcontext()

# (site, shape signature) -> {"flops": float, "bytes": float}; one entry
# per compiled shape bucket, exactly mirroring the backends' jit caches.
_cost_lock = threading.Lock()
_cost_table: dict[tuple, dict] = {}


def set_device_profiling(on: bool) -> None:
    """Arm/disarm the per-dispatch breakdown (daemon boot reads
    ``[telemetry] profile-device-time``; bench/tests flip it directly)."""
    global _enabled
    _enabled = bool(on)


def device_profiling() -> bool:
    return _enabled


def set_observer(fn) -> None:
    """Install/remove the dispatch-observatory stage observer (ISSUE
    12; :func:`holo_tpu.telemetry.observatory.configure` is the only
    caller).  ``fn(site, stage, device, seconds)`` runs after every
    completed stage observation; ``None`` disarms — the stage hot path
    then pays exactly one global check for the feature."""
    global _OBSERVER
    _OBSERVER = fn


def observing() -> bool:
    """True while a stage observer (the observatory) is armed."""
    return _OBSERVER is not None


def set_phase_hook(fn) -> None:
    """Install/remove the critical-path stage-edge hook (ISSUE 17;
    :func:`holo_tpu.telemetry.critpath.configure` is the only caller).
    ``fn(site, stage, device, edge)`` runs at every stage begin
    (``edge='b'``) and clean-exit end (``edge='e'``) — the hook reads
    :func:`clock` itself, so a DeterministicTimer makes its stamps
    byte-identical too; ``None`` disarms."""
    global _PHASE_HOOK
    _PHASE_HOOK = fn


def set_stage_timer(fn) -> None:
    """Swap the stage timer (``None`` restores ``time.perf_counter``).
    The observatory's ``DeterministicTimer`` uses this for
    byte-identical seeded runs; nothing else should."""
    global _timer, _timer_overridden
    _timer = fn if fn is not None else time.perf_counter
    _timer_overridden = fn is not None


def stage_timer_overridden() -> bool:
    return _timer_overridden


def clock() -> float:
    """The stage timer — ``time.perf_counter`` unless a deterministic
    timer is installed.  Dispatch walls that feed the engine tuner read
    THIS instead of ``time.perf_counter`` directly, so a deterministic
    explain run makes deterministic tuner decisions (and the whole
    report stays byte-identical); in production the two are the same
    function."""
    return _timer()


def dispatch_ctx() -> dict | None:
    """The active dispatch context (observer keying), or None."""
    return getattr(_ctx_local, "ctx", None)


@contextmanager
def _dispatch_context(kw: dict):
    prev = getattr(_ctx_local, "ctx", None)
    _ctx_local.ctx = kw
    try:
        yield
    finally:
        _ctx_local.ctx = prev


def dispatch_context(**kw):
    """Label the enclosed dispatch for the observatory feed — the
    backends wrap each device dispatch with its (kind, engine,
    shape-bucket).  A shared null context when no observer is armed,
    so the unobserved dispatch path pays one check + one call."""
    if _OBSERVER is None:
        return _NULLCTX
    return _dispatch_context(kw)


@contextmanager
def stage(site: str, name: str, device: str = "-"):
    """One dispatch phase: a nested trace sub-span plus a
    ``holo_profile_stage_seconds`` observation whose exemplar links the
    bucket to the sub-span id.  ``site`` is the dispatch site
    (``spf.one``, ``spf.whatif``, ``frr.batch``, ...), ``name`` the
    phase (``marshal`` / ``device`` / ``readback``); ``device`` is the
    per-device split label of a sharded dispatch ('-' = whole span,
    see :func:`device_stages`).

    When the dispatch observatory is armed (:func:`set_observer`) the
    measured wall is ALSO fed to its streaming sketches — including
    with device profiling off, so the observatory can stay always-on
    without the histogram/exemplar machinery; observations keep the
    existing contract of recording only on clean exit."""
    obs = _OBSERVER
    ph = _PHASE_HOOK
    if ph is not None:
        _phase_guarded(ph, site, name, device, "b")
    if not _enabled:
        if obs is None:
            yield None
        else:
            t0 = _timer()
            yield None
            _observe_guarded(obs, site, name, device, _timer() - t0)
        if ph is not None:
            _phase_guarded(ph, site, name, device, "e")
        return
    t0 = _timer()
    with telemetry.span(f"{site}.{name}", stage=name, device=device) as sid:
        yield sid
    dt = _timer() - t0
    _STAGE_SECONDS.labels(site=site, stage=name, device=device).observe(
        dt, exemplar={"span_id": sid}
    )
    if obs is not None:
        _observe_guarded(obs, site, name, device, dt)
    if ph is not None:
        _phase_guarded(ph, site, name, device, "e")


def _observe_guarded(obs, site, name, device, dt) -> None:
    """The observatory is warn-only BY CONTRACT: an observer bug (e.g.
    a lock-free race losing a bin mid-quantile) must never propagate
    into the dispatch, where the circuit breaker would misread it as a
    device failure and serve the scalar fallback."""
    try:
        obs(site, name, device, dt)
    except Exception:  # noqa: BLE001 — see contract above
        log.debug("stage observer failed", exc_info=True)


def _phase_guarded(ph, site, name, device, edge) -> None:
    """Same warn-only contract as :func:`_observe_guarded`: a
    critical-path hook bug must never fail the dispatch it stamps."""
    try:
        ph(site, name, device, edge)
    except Exception:  # noqa: BLE001 — see contract above
        log.debug("stage phase hook failed", exc_info=True)


def device_stages(site: str, tree) -> bool:
    """Per-device completion split of a mesh-sharded dispatch: block on
    each device's result shards in device-id order, recording one
    ``stage(site, "device", device=<id>)`` sub-span each.

    Spans are sequential from the host's vantage point: the first
    device's span absorbs most of the wait and later spans measure the
    RESIDUAL skew after earlier devices completed — exactly the
    straggler signal worth watching on a real mesh (a healthy sharded
    dispatch shows one fat span and near-zero residuals; a slow chip
    shows up as a fat residual at its id).  Returns False — recording
    nothing — when profiling is disarmed or the result lives on fewer
    than two devices; callers then fall back to the plain :func:`sync`
    barrier, so single-device dispatch behavior is unchanged."""
    if not _enabled:
        return False
    import jax

    by_dev: dict = {}
    try:
        for leaf in jax.tree_util.tree_leaves(tree):
            shards = getattr(leaf, "addressable_shards", None)
            if not shards:
                continue
            for sh in shards:
                by_dev.setdefault(sh.device, []).append(sh.data)
    except Exception:  # noqa: BLE001 — introspection is best-effort;
        # the caller's sync barrier still bounds the device phase.
        log.debug("shard enumeration failed under profiling", exc_info=True)
        return False
    if len(by_dev) < 2:
        return False
    for dev in sorted(by_dev, key=lambda d: getattr(d, "id", 0)):
        with stage(site, "device", device=str(getattr(dev, "id", dev))):
            try:
                jax.block_until_ready(by_dev[dev])
            except Exception:  # noqa: BLE001 — same contract as sync()
                log.debug(
                    "block_until_ready failed under profiling", exc_info=True
                )
    return True


def sync(tree) -> None:
    """Completion barrier bounding the **device** phase: block until the
    jit result pytree is ready.  A no-op when profiling is off — the
    un-profiled dispatch path keeps its async overlap and pays for the
    device inside the readback materialization instead.  An armed
    observatory also needs the barrier: without it every device wall
    would hide inside the readback sketch."""
    if not _enabled and _OBSERVER is None:
        return
    import jax

    try:
        jax.block_until_ready(tree)
    except Exception:  # noqa: BLE001 — a profiler barrier must never
        # fail a dispatch the breaker would otherwise see succeed.
        log.debug("block_until_ready failed under profiling", exc_info=True)


def annotation(name: str):
    """``jax.profiler.TraceAnnotation`` on a real TPU (the phases then
    appear in XLA's own profiler timeline), a null context elsewhere."""
    from contextlib import nullcontext

    if not _enabled:
        return nullcontext()
    try:
        import jax

        if jax.default_backend() == "tpu":
            return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 — best-effort on exotic backends
        log.debug("profiler annotation unavailable", exc_info=True)
    return nullcontext()


def record_cost(site: str, jitfn, *args, shape_sig: tuple = ()) -> dict | None:
    """Compile-time FLOP/bytes estimate for a freshly-compiled shape
    bucket via ``jitfn.lower(*args).compile().cost_analysis()``.

    Called by the backends right after :meth:`_track_compile` reports a
    fresh (engine, shape) signature, so the table mirrors the jit cache
    one-to-one.  The lower+compile pair re-runs XLA compilation for the
    bucket (the AOT path does not share the jit dispatch cache), which
    is why this only runs when profiling is armed — it is compile-time
    cost on a cold bucket, never per-dispatch cost.  Never raises:
    backends without cost analysis record nothing.  The armed
    observatory needs the same capture (its roofline numerators), so
    either switch enables it."""
    if not _enabled and _OBSERVER is None:
        return None
    try:
        ca = jitfn.lower(*args).compile().cost_analysis()
    except Exception as e:  # noqa: BLE001 — platform-dependent API
        log.debug("cost analysis unavailable for %s: %r", site, e)
        return None
    if isinstance(ca, (list, tuple)):  # some jax versions: one per device
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return None
    entry = {
        "flops": float(ca.get("flops", 0.0)),
        "bytes": float(ca.get("bytes accessed", 0.0)),
    }
    with _cost_lock:
        _cost_table[(site, tuple(shape_sig))] = entry
    _COST_FLOPS.labels(site=site).set(entry["flops"])
    _COST_BYTES.labels(site=site).set(entry["bytes"])
    return entry


def stage_median(
    site: str, stage: str, device: str = "-"
) -> float | None:
    """Approximate median of ``holo_profile_stage_seconds{site,stage}``
    from the histogram's cumulative bucket counts (upper bucket
    boundary of the bucket containing the median — a <=2x
    overestimate given the log-spaced ladder, which is plenty for
    ratio decisions).  None when the stage has no observations.

    This is the engine auto-tuner's GLOBAL fallback signal
    (holo_tpu/pipeline/tuner.py): its per-shape-bucket decisions use
    the dispatch walls the backends feed it directly, but a
    fresh bucket with no samples can still consult the process-wide
    stage distribution, and the bench's tuner rows report both."""
    child = _STAGE_SECONDS.labels(site=site, stage=stage, device=device)
    total = child.count
    if not total:
        return None
    half = (total + 1) // 2
    for le, cum in child.cumulative():
        if cum >= half:
            return float(le)
    return None


def cost_table() -> dict[tuple, dict]:
    """Snapshot of {(site, shape signature) -> cost estimates}."""
    with _cost_lock:
        return {k: dict(v) for k, v in _cost_table.items()}


def clear_cost_table() -> None:
    """Tests only."""
    with _cost_lock:
        _cost_table.clear()


def capture_device_trace(
    trace_dir, n_routers: int = 48, seed: int = 3
) -> dict:
    """One REAL ``jax.profiler.trace()`` around a seeded SPF dispatch
    ([telemetry] device-trace-dir; ROADMAP item-5 carry-over).

    Relay-probe-aware: the capture only runs when the default platform
    is an actual TPU — the CPU/relay approximation yields an explicit
    ``relay: not-used`` row instead, NEVER a failure, so the bench's
    ``device_trace`` row stays interpretable while the relay is down.
    The compile is warmed outside the trace so the captured timeline is
    one steady-state dispatch, not a Mosaic compile."""
    from pathlib import Path

    from holo_tpu.telemetry import relay

    row: dict = {"relay": relay.not_used(), "captured": False,
                 "trace_dir": str(trace_dir)}
    try:
        import jax

        platform = jax.devices()[0].platform
    except Exception as e:  # noqa: BLE001 — a dead relay is a row, not a crash
        row["error"] = f"{type(e).__name__}: {e}"[:200]
        relay.note_probe(False, error=row["error"])
        return row
    row["platform"] = platform
    # The platform verdict doubles as the daemon's in-process relay
    # observation (holo_relay_up / holo-telemetry/relay): a daemon
    # configured with device-trace-dir reports what it actually found
    # instead of leaving the watch to the bench process alone.
    relay.note_probe(
        platform == "tpu",
        error=None if platform == "tpu" else f"platform={platform}",
    )
    if platform != "tpu":
        row["reason"] = f"no TPU attached (platform={platform})"
        return row
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.spf.synth import random_ospf_topology

    topo = random_ospf_topology(
        n_routers=n_routers,
        n_networks=max(n_routers // 8, 4),
        extra_p2p=max(n_routers // 2, 16),
        seed=seed,
    )
    backend = TpuSpfBackend()
    backend.compute(topo)  # warm: compile + marshal outside the trace
    out = Path(trace_dir)
    out.mkdir(parents=True, exist_ok=True)
    with jax.profiler.trace(str(out)):
        backend.compute(topo)
    row.update(
        relay="used",
        captured=True,
        n_vertices=int(topo.n_vertices),
        files=sum(1 for p in out.rglob("*") if p.is_file()),
    )
    return row

"""SLO plane: error budgets + multi-window burn-rate sentinels (ISSUE 20).

ROADMAP items 1 and 5 both schedule "under a latency SLO", but until
this module the framework had only raw instruments — convergence
end-cuts, critpath phase vectors, shed counters, breaker/fallback
events — with no *objectives*, *budgets*, or *compliance verdicts*
attached.  This engine is that vocabulary: declared objectives grade
the existing streams into rolling good/bad counts, the counts become
error budgets, and budget spend-rate ("burn") is watched by the
classic multi-window sentinel so a breach pages once, early, and
warn-only.

Objective model
---------------
An :class:`Objective` declares WHAT is graded and HOW:

- ``kind="latency"`` — per-event grading of trigger→FIB end-cuts
  (``feed`` via :func:`note_endcut`, fed by the convergence tracker's
  ``fib_commit`` close under a one-global-check hook) or synthetic
  canary probes (:func:`note_probe`, fed by
  :mod:`holo_tpu.telemetry.canary`).  An event is *good* when its
  latency ≤ ``threshold_s`` (a fallback-served event can still be
  good: the oracle delivered — the fallback fraction is reported
  separately); the target quantile is what the threshold is meant to
  hold at (``target`` = the good-fraction objective, e.g. 0.999).
- ``kind="availability"`` — continuous up/down grading (the relay
  watch: ``holo_relay_up`` flips via :func:`note_relay`).  The budget
  is *down seconds over the window*: burn = down_s / (W · (1−target)).
- ``kind="delivery"`` — per-ticket grading by dispatch priority class
  (:func:`note_served` / :func:`note_shed` from the pipeline's settle
  and shed paths): good = served, bad = shed.  The ``background``
  delivery objective is the canary's saturation signal — probes are
  background-class by design, so THEY are shed first and their shed
  rate is the first-class "the queue is full" indicator.

``source`` scopes the stream: a trigger class (``lsa``/``bfd``/…), a
priority class for delivery, ``relay`` for availability, or ``"*"``
(every trigger EXCEPT the canary's own — canary end-cuts ride the
storm's virtual clock and would dilute the production objective with
synthetic ≈0 walls; the canary grades through its own objective on
real probe walls).

Burn-rate math (the SRE standard, deterministic here)
-----------------------------------------------------
Events land in fixed-width buckets of the engine clock
(``fast_window / 60`` wide, trimmed past ``slow_window``).  For window
``W``: ``bad_frac = bad/(good+bad)`` over the buckets in ``[now−W,
now]`` and ``burn = bad_frac / (1 − target)`` — burn 1.0 spends
exactly the budget over the compliance window, burn 14.4 spends a
30-day budget in 50 hours (the classic fast-page threshold, the
default ``fast_burn``).  ``budget_remaining = 1 − bad_frac_slow /
(1 − target)`` clamped to [0, 1].  The clock is
:func:`profiling.clock` — perf_counter in production, the
observatory's ``DeterministicTimer`` under ``explain --slo``, which is
what makes the rendered report byte-identical.

The fast-window sentinel LATCHES: crossing ``fast_burn`` fires exactly
one ``holo_slo_sentinel_fires_total`` increment + one warn-only
``slo-burn`` flight event per excursion (re-arms when burn falls back
under), never a breaker, never a fallback — the observatory sentinel's
contract.  Latency sketches additionally seed ``slo.<objective>``
ledger rows through ``Observatory._sentinel_check`` at checkpoint, so
SLO latency regressions ratchet and flag with the same baseline
machinery and file as stage- and phase-level ones.

Armed/disarmed contract: off by default; every seam costs one
module-global ``None`` check while disarmed (poisoned-clock tests in
``tests/test_slo.py`` prove no clock read); armed overhead is gated
<2% by ``bench.py slo_overhead``.  No locks on the feeding threads —
bucket dicts mutate under the GIL (the DDSketch lock-free contract,
see observatory.py).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from holo_tpu import telemetry
from holo_tpu.telemetry import flight, profiling
from holo_tpu.telemetry.observatory import DDSketch

log = logging.getLogger("holo_tpu.telemetry")

#: objective kinds (closed set)
KINDS = ("latency", "availability", "delivery")
#: burn windows (names are the gauge label vocabulary)
WINDOWS = ("fast", "slow")

_BURN = telemetry.gauge(
    "holo_slo_burn_rate",
    "Error-budget burn rate per objective and window (1.0 spends the "
    "budget exactly over the compliance window)",
    ("objective", "window"),
    stamped=False,
)
_BUDGET = telemetry.gauge(
    "holo_slo_budget_remaining",
    "Fraction of the slow-window error budget left per objective",
    ("objective",),
    stamped=False,
)
_SENTINEL_FIRES = telemetry.counter(
    "holo_slo_sentinel_fires_total",
    "Burn-rate sentinel excursions per objective and window "
    "(latched: one fire per crossing, warn-only)",
    ("objective", "window"),
)


@dataclass(frozen=True)
class Objective:
    """One declared service-level objective (see module docstring)."""

    name: str
    kind: str = "latency"  # latency | availability | delivery
    source: str = "*"  # trigger class | priority class | relay | "*"
    quantile: float = 0.99
    threshold_s: float = 1.0
    target: float = 0.999

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"SLO target must be in (0, 1), got {self.target}"
            )
        if not 0.0 < self.quantile < 1.0:
            raise ValueError(
                f"SLO quantile must be in (0, 1), got {self.quantile}"
            )
        if self.threshold_s <= 0.0:
            raise ValueError(
                f"SLO threshold must be positive, got {self.threshold_s}"
            )

    @classmethod
    def from_config(cls, raw: dict) -> "Objective":
        """One ``[[telemetry.slo-objectives]]`` table (kebab keys)."""
        return cls(
            name=str(raw["name"]),
            kind=str(raw.get("kind", "latency")),
            source=str(raw.get("source", "*")),
            quantile=float(raw.get("quantile", 0.99)),
            threshold_s=float(raw.get("threshold-ms", 1000.0)) / 1e3,
            target=float(raw.get("target", 0.999)),
        )


def default_objectives() -> tuple[Objective, ...]:
    """The three-objective default the acceptance criteria name (plus
    the background delivery row that makes the canary's shed rate a
    budget instead of a counter)."""
    return (
        # Production trigger→FIB latency: every convergence end-cut
        # (lsa/lsp/bfd/carrier/ifconfig) graded at p99.  The threshold
        # covers a full delay-FSM SPF under 10% loss (LONG_WAIT + one
        # LS-retransmit ≈ 10 s virtual) — a healthy seeded storm stays
        # in budget; deployments with FRR-flip expectations declare a
        # tighter objective in [telemetry] slo-objectives.
        Objective("trigger-fib", "latency", "*", 0.99, 15.0, 0.99),
        # The canary's own objective: black-box probe availability —
        # real (profiling-clock) trigger→FIB walls through the live
        # dispatch path, graded tighter than production.
        Objective("canary", "latency", "canary", 0.99, 0.25, 0.99),
        # Relay availability: "MXU bets blocked on the relay" as
        # budget arithmetic (budget = down seconds over the window).
        Objective("relay", "availability", "relay", 0.99, 1.0, 0.999),
        # Background admission: probes/advisories shed first under
        # pressure — their shed rate is the saturation budget.
        Objective("background-delivery", "delivery", "background",
                  0.99, 1.0, 0.99),
    )


class _ObjState:
    """Rolling state for one objective.  Mutated lock-free on the
    feeding threads (fib_commit path, pipeline worker, canary loop):
    bucket dict get/set and scalar adds are GIL-atomic; a racing
    increment coalescing one count is inside the budget math's own
    noise (the DDSketch argument, observatory.py)."""

    __slots__ = (
        "obj", "buckets", "sketch", "fallbacks", "events",
        "latched", "fires", "down_spans", "up", "since",
    )

    def __init__(self, obj: Objective, alpha: float, max_bins: int):
        self.obj = obj
        # bucket index -> [good, bad] (latency/delivery) or
        # [up_s, down_s] (availability)
        self.buckets: dict[int, list] = {}
        self.sketch = DDSketch(alpha, max_bins)
        self.fallbacks = 0
        self.events = 0
        self.latched = {"fast": False, "slow": False}
        self.fires = {"fast": 0, "slow": 0}
        # availability only: closed down spans + current state
        self.down_spans: list = []  # [start, end] pairs
        self.up: bool | None = None
        self.since: float | None = None


class SloEngine:
    """Process-wide SLO engine (module singleton via :func:`configure`).
    Hot path = the ``note_*`` methods, fed by the convergence hook, the
    pipeline shed/settle seams, the relay watch, and the canary;
    everything else is cold reporting."""

    def __init__(
        self,
        objectives=None,
        clock=None,
        fast_window: float = 3600.0,
        slow_window: float = 86400.0,
        fast_burn: float = 14.4,
        slow_burn: float = 1.0,
        check_every: int = 16,
        alpha: float = 0.01,
        max_bins: int = 512,
    ):
        objs = tuple(objectives) if objectives else default_objectives()
        names = [o.name for o in objs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO objective names: {names}")
        self.fast_window = float(fast_window)
        self.slow_window = float(slow_window)
        if self.fast_window <= 0 or self.slow_window < self.fast_window:
            raise ValueError(
                "SLO windows must satisfy 0 < fast <= slow, got "
                f"{fast_window}/{slow_window}"
            )
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.check_every = int(check_every)
        self.bucket_w = self.fast_window / 60.0
        self._clock = clock if clock is not None else profiling.clock
        self._states = {
            o.name: _ObjState(o, alpha, max_bins) for o in objs
        }
        # routing tables: feed -> matching states (computed once so the
        # hot path is a tuple walk, not a per-note objective scan)
        self._latency_any = tuple(
            s for s in self._states.values()
            if s.obj.kind == "latency" and s.obj.source == "*"
        )
        self._latency_by_src: dict[str, tuple] = {}
        for s in self._states.values():
            if s.obj.kind == "latency" and s.obj.source != "*":
                self._latency_by_src.setdefault(s.obj.source, ())
                self._latency_by_src[s.obj.source] += (s,)
        self._avail = tuple(
            s for s in self._states.values()
            if s.obj.kind == "availability"
        )
        self._delivery_by_cls = {
            s.obj.source: s
            for s in self._states.values() if s.obj.kind == "delivery"
        }
        self._sheds: dict[tuple, int] = {}  # (class, reason) -> count
        self._notes = 0

    # -- hot path: grading ----------------------------------------------

    def _grade(self, st: _ObjState, good: bool, now: float) -> None:
        b = self.buckets_for(st, now)
        b[0 if good else 1] += 1
        st.events += 1
        self._notes += 1
        if not good or (
            self.check_every
            and self._notes % self.check_every == 0
        ):
            self._check(st, now)

    def buckets_for(self, st: _ObjState, now: float) -> list:
        i = int(now // self.bucket_w)
        b = st.buckets.get(i)
        if b is None:
            # setdefault is GIL-atomic: two racing first-graders both
            # land in the one surviving bucket (observatory idiom).
            b = st.buckets.setdefault(i, [0, 0])
            if len(st.buckets) > 2 * int(self.slow_window / self.bucket_w) + 4:
                self._trim(st, now)
        return b

    def _trim(self, st: _ObjState, now: float) -> None:
        floor = int((now - self.slow_window) // self.bucket_w)
        for i in [i for i in st.buckets if i < floor]:
            st.buckets.pop(i, None)
        if st.down_spans:
            t_floor = now - self.slow_window
            st.down_spans = [
                sp for sp in st.down_spans if sp[1] >= t_floor
            ]

    def note_endcut(self, trigger: str, seconds: float, fallback: bool) -> None:
        """One trigger→FIB end-cut (the convergence tracker's close
        hook; latency on the TRACKER's clock — virtual in storms)."""
        if trigger == "canary":
            # Canary end-cuts ride the tracker's possibly-VIRTUAL clock
            # (a storm's 5 s SPF-delay wait would grade as a 5 s probe);
            # the canary objective grades only the real profiling-clock
            # walls note_probe delivers.
            return
        now = self._clock()
        states = self._latency_by_src.get(trigger, ()) + self._latency_any
        for st in states:
            st.sketch.observe(max(seconds, 0.0))
            if fallback:
                st.fallbacks += 1
            self._grade(st, seconds <= st.obj.threshold_s, now)

    def note_probe(self, ok: bool, seconds: float | None) -> None:
        """One synthetic canary probe verdict (canary.py's close; the
        probe latency is a REAL profiling-clock wall)."""
        now = self._clock()
        for st in self._latency_by_src.get("canary", ()):
            good = bool(ok)
            if seconds is not None:
                st.sketch.observe(max(seconds, 0.0))
                good = good and seconds <= st.obj.threshold_s
            self._grade(st, good, now)

    def note_served(self, cls: str) -> None:
        """One pipeline ticket settled successfully, by class."""
        st = self._delivery_by_cls.get(cls)
        if st is not None:
            self._grade(st, True, self._clock())

    def note_shed(self, cls: str, reason: str) -> None:
        """One pipeline ticket shed (capacity eviction or deadline
        expiry), by class — the saturation stream."""
        key = (cls, reason)
        # GIL-atomic read-add-store; a racing shed coalescing one count
        # is inside the saturation signal's noise.
        self._sheds[key] = self._sheds.get(key, 0) + 1  # holo-lint: disable=HL204
        st = self._delivery_by_cls.get(cls)
        if st is not None:
            self._grade(st, False, self._clock())

    def note_relay(self, up: bool) -> None:
        """One relay probe verdict (the ``holo_relay_up`` flip)."""
        now = self._clock()
        for st in self._avail:
            if st.up is None:
                st.up, st.since = bool(up), now
            elif st.up and not up:
                st.up, st.since = False, now
            elif not st.up and up:
                st.down_spans.append([st.since, now])
                st.up, st.since = True, now
            st.events += 1
            self._check(st, now)

    # -- burn math ------------------------------------------------------

    def _down_seconds(self, st: _ObjState, now: float, window: float) -> float:
        lo = now - window
        down = 0.0
        for a, b in st.down_spans:
            down += max(0.0, min(b, now) - max(a, lo))
        if st.up is False and st.since is not None:
            down += max(0.0, now - max(st.since, lo))
        return down

    def _bad_frac(self, st: _ObjState, now: float, window: float):
        """(bad_fraction, good, bad) over ``[now - window, now]``;
        ``None`` fraction when the window saw no events."""
        if st.obj.kind == "availability":
            if st.up is None:
                return None, 0, 0
            # Budget = down seconds over the FULL window (an objective
            # younger than the window grades the unseen span as up —
            # the conservative read for a fresh daemon).
            down = self._down_seconds(st, now, window)
            return min(down / window, 1.0), 0, 0
        lo = int((now - window) // self.bucket_w)
        good = bad = 0
        for i, b in list(st.buckets.items()):
            if i >= lo:
                good += b[0]
                bad += b[1]
        if good + bad == 0:
            return None, 0, 0
        return bad / (good + bad), good, bad

    def burn(self, st: _ObjState, now: float, window: float) -> float | None:
        frac, _g, _b = self._bad_frac(st, now, window)
        if frac is None:
            return None
        return frac / max(1.0 - st.obj.target, 1e-9)

    def budget_remaining(self, st: _ObjState, now: float) -> float | None:
        frac, _g, _b = self._bad_frac(st, now, self.slow_window)
        if frac is None:
            return None
        spent = frac / max(1.0 - st.obj.target, 1e-9)
        return min(max(1.0 - spent, 0.0), 1.0)

    # -- sentinel -------------------------------------------------------

    def _check(self, st: _ObjState, now: float) -> None:
        for window, span, limit in (
            ("fast", self.fast_window, self.fast_burn),
            ("slow", self.slow_window, self.slow_burn),
        ):
            b = self.burn(st, now, span)
            if b is None:
                continue
            _BURN.labels(objective=st.obj.name, window=window).set(b)
            breached = b > limit
            if breached and not st.latched[window]:
                # Latch: one fire per excursion.  GIL-atomic bool flip
                # (single-writer per feeding path; a racing double-fire
                # window is the same one the observatory accepts).
                st.latched[window] = True
                st.fires[window] += 1
                _SENTINEL_FIRES.labels(
                    objective=st.obj.name, window=window
                ).inc()
                flight.event(
                    "slo-burn",
                    objective=st.obj.name,
                    window=window,
                    burn=round(b, 3),
                    limit=limit,
                )
                log.warning(
                    "slo: objective %r %s-window burn %.2f exceeds %.2f "
                    "— warn-only, dispatch unaffected",
                    st.obj.name, window, b, limit,
                )
            elif not breached and st.latched[window]:
                st.latched[window] = False
        rem = self.budget_remaining(st, now)
        if rem is not None:
            _BUDGET.labels(objective=st.obj.name).set(rem)

    def checkpoint(self) -> None:
        """Force one sentinel pass over every objective NOW, trim the
        bucket tails, and seed latency ``slo.<objective>`` rows through
        the dispatch observatory's baseline machinery (when armed) —
        the bench/explain bracket, same discipline as
        ``Observatory.checkpoint``."""
        from holo_tpu.telemetry import observatory

        now = self._clock()
        obs = observatory.active()
        for st in self._states.values():
            self._trim(st, now)
            self._check(st, now)
            if obs is not None and st.sketch.count:
                try:
                    obs._sentinel_check(
                        (f"slo.{st.obj.name}", "latency", "-", "-", "-"),
                        st.sketch,
                    )
                except Exception:  # noqa: BLE001 — warn-only by
                    # contract: a ledger bug must never propagate into
                    # the path that triggered this checkpoint.
                    log.debug("slo sentinel pass failed", exc_info=True)

    # -- cold reporting -------------------------------------------------

    def _objective_row(self, st: _ObjState, now: float) -> dict:
        o = st.obj
        fast_frac, fg, fb = self._bad_frac(st, now, self.fast_window)
        slow_frac, sg, sb = self._bad_frac(st, now, self.slow_window)
        row = {
            "objective": o.name,
            "kind": o.kind,
            "source": o.source,
            "target": o.target,
            "threshold_ms": round(o.threshold_s * 1e3, 3),
            "quantile": o.quantile,
            "events": st.events,
            "good_fast": fg,
            "bad_fast": fb,
            "good_slow": sg,
            "bad_slow": sb,
            "burn_fast": (
                round(self.burn(st, now, self.fast_window), 6)
                if fast_frac is not None else None
            ),
            "burn_slow": (
                round(self.burn(st, now, self.slow_window), 6)
                if slow_frac is not None else None
            ),
            "budget_remaining": (
                round(self.budget_remaining(st, now), 6)
                if slow_frac is not None else None
            ),
            "sentinel_fires_fast": st.fires["fast"],
            "sentinel_fires_slow": st.fires["slow"],
            "latched_fast": bool(st.latched["fast"]),
        }
        if o.kind == "latency":
            row["fallbacks"] = st.fallbacks
            if st.sketch.count:
                row["measured_ms"] = {
                    "p50": round((st.sketch.quantile(0.5) or 0.0) * 1e3, 3),
                    f"p{round(o.quantile * 100)}": round(
                        (st.sketch.quantile(o.quantile) or 0.0) * 1e3, 3
                    ),
                    "p99": round((st.sketch.quantile(0.99) or 0.0) * 1e3, 3),
                }
        if o.kind == "availability":
            row["down_s_fast"] = round(
                self._down_seconds(st, now, self.fast_window), 3
            )
            row["down_s_slow"] = round(
                self._down_seconds(st, now, self.slow_window), 3
            )
            row["state"] = (
                "unknown" if st.up is None else ("up" if st.up else "down")
            )
        return row

    def report(self) -> dict:
        """Deterministic report document (the ``explain --slo``
        payload): one row per objective in declaration order, plus the
        shed-by-(class, reason) saturation tally.  Byte-identical
        across same-seed runs under the DeterministicTimer."""
        now = self._clock()
        return {
            "windows": {
                "fast_s": self.fast_window,
                "slow_s": self.slow_window,
                "fast_burn_limit": self.fast_burn,
                "slow_burn_limit": self.slow_burn,
            },
            "objectives": [
                self._objective_row(st, now)
                for st in self._states.values()
            ],
            "sheds": {
                f"{cls}/{reason}": n
                for (cls, reason), n in sorted(self._sheds.items())
            },
        }

    def stats(self) -> dict:
        """The ``holo-telemetry/slo`` gNMI leaf payload."""
        now = self._clock()
        out = {"objectives": {}, "sheds": {}}
        for st in self._states.values():
            b = self.burn(st, now, self.fast_window)
            rem = self.budget_remaining(st, now)
            out["objectives"][st.obj.name] = {
                "kind": st.obj.kind,
                "events": st.events,
                "burn-fast": round(b, 6) if b is not None else None,
                "budget-remaining": (
                    round(rem, 6) if rem is not None else None
                ),
                "sentinel-fires": st.fires["fast"] + st.fires["slow"],
            }
        for (cls, reason), n in sorted(self._sheds.items()):
            out["sheds"][f"{cls}/{reason}"] = n
        return out

    def objective(self, name: str) -> _ObjState | None:
        """Test/bench surface: the state for one objective."""
        return self._states.get(name)


# -- process-wide singleton + one-global-check seams ---------------------

_SLO: SloEngine | None = None


def configure(enabled=True, objectives=None, **kw) -> SloEngine | None:
    """Arm (truthy ``enabled``) or disarm (falsy) the process-wide
    engine and (un)install the convergence end-cut hook.  ``kw`` passes
    through to :class:`SloEngine` (clock/windows/burn limits)."""
    global _SLO
    from holo_tpu.telemetry import convergence

    if enabled:
        _SLO = SloEngine(objectives=objectives, **kw)
        convergence.set_slo_hook(_SLO)
    else:
        _SLO = None
        convergence.set_slo_hook(None)
    return _SLO


def active() -> SloEngine | None:
    return _SLO


def enabled() -> bool:
    return _SLO is not None


def note_probe(ok: bool, seconds: float | None = None) -> None:
    """Canary probe verdict (no-op while disarmed)."""
    sl = _SLO
    if sl is None:
        return
    sl.note_probe(ok, seconds)


def note_served(cls: str) -> None:
    """Pipeline ticket served, by class (no-op while disarmed)."""
    sl = _SLO
    if sl is None:
        return
    sl.note_served(cls)


def note_shed(cls: str, reason: str) -> None:
    """Pipeline ticket shed, by class + reason (no-op while disarmed)."""
    sl = _SLO
    if sl is None:
        return
    sl.note_shed(cls, reason)


def note_relay(up: bool) -> None:
    """Relay probe verdict (no-op while disarmed)."""
    sl = _SLO
    if sl is None:
        return
    sl.note_relay(up)

"""Critical-path ledger: cross-thread trigger→FIB waterfalls (ISSUE 17).

The convergence observatory (ISSUE 6) measures the trigger→FIB path
end-to-end and the dispatch observatory (ISSUE 12) attributes the
*device* slice — but ROADMAP item 5's claim is that under flap storms
the p99 is owned by *host choreography* (actor wake, queue wait,
marshal, force-wait, RIB sync), and nothing measured which host phase
owns each millisecond.  This module is that instrument: it joins the
per-event causal ids from :mod:`holo_tpu.telemetry.convergence`, the
profiling sub-spans (marshal / device / readback) from
:mod:`holo_tpu.telemetry.profiling`, and the queue-lifecycle stamps
from :mod:`holo_tpu.pipeline.dispatch` (enqueue, launch, finish,
force-wait, per-key ordering stalls) into one per-event cross-thread
**waterfall**, then decomposes every completed event into an
exhaustive, gap-free phase vector whose sum equals the end-to-end wall
*by construction*.

Phase taxonomy (the cut model)
------------------------------
Stamps are absolute reads of :func:`profiling.clock` (perf_counter in
production, the observatory's ``DeterministicTimer`` under ``explain``
— which is what makes the rendered waterfall byte-identical).  Per
event the stamps become an ordered sequence of *cuts*, each clamped
monotonically into ``[t_begin, t_end]``; phases are the differences
between consecutive cuts, so they telescope to the wall exactly:

    begin ──wake──▶ spf-scheduled ──coalesce_wait──▶ enqueue
      ──queue_wait──▶ marshal-begin ──marshal──▶ marshal-end
      ──device──▶ device-end ──force_wait──▶ force-end
      ──rib──▶ spf-observed ──rib──▶ rib-observed
      ──fib_commit──▶ fib/fallback-observed
      ──unattributed──▶ event-closed (= t_done)

A missing stamp collapses its phase to zero (the cut inherits its
predecessor): an un-pipelined dispatch has no enqueue/force stamps, so
coalesce_wait absorbs the SPF delay-FSM hold and queue_wait/force_wait
read zero; a BFD local-repair event with no SPF at all lands its wall
in rib + fib_commit.  ``rib`` spans from result availability to the
last RIB op — BOTH the host route derivation (scalar next-hop
extraction from the device result, the spf-observed waypoint) and the
publish/apply slice: that is the "RIB sync" item of ROADMAP item 5's
host-choreography list.  When the breaker's scalar fallback served the
event, the device segment and the derivation slice (which then holds
the scalar oracle's compute) relabel to ``fallback`` (chaos contract:
a forced breaker trip must show up there, an injected
``FaultPlan.dispatch_delay`` in ``device``, a queue stall in
``queue_wait`` — wrong-phase attribution is a test failure).  The
residual that no stamp explains is *reported*, never hidden: the
``unattributed`` phase is the closing segment past the last stamp — an
event with NO stamps at all books its whole wall there — gated <1% of
the wall at p50 by ``bench.py critical_path``.

Aggregation + sentinel
----------------------
Per-phase walls stream into DDSketch quantiles keyed
``(trigger, phase, engine, shape-bucket, kind)`` — the engine/bucket
labels ride in on :func:`profiling.dispatch_ctx` exactly like the
dispatch observatory's sketches.  Every event also gets a
deterministic **bound verdict** (``host`` / ``queue`` / ``device``,
largest share wins, ties break host > queue > device — the analogue of
the roofline ridge-point verdict).  When a dispatch observatory is
armed, every ``check_every`` completions the per-phase sketches run
through ITS perf-regression sentinel (`Observatory._sentinel_check`)
under ``critpath.<trigger>/<phase>|...`` ledger keys, so phase-level
regressions latch, flag, and ratchet with the same machinery and the
same ledger file as stage-level ones.

Armed/disarmed contract: off by default; every seam costs one
module-global ``None`` check while disarmed; armed overhead is gated
<2% by ``bench.py critpath_overhead`` (paired interleaved min-of-N,
same harness as ``convergence_overhead``); no locks are taken on the
dispatch thread — records are plain dicts mutated under the GIL (the
DDSketch lock-free contract, see observatory.py).
"""

from __future__ import annotations

import logging
from collections import deque

from holo_tpu import telemetry
from holo_tpu.telemetry import convergence, profiling
from holo_tpu.telemetry.observatory import DDSketch

log = logging.getLogger("holo_tpu.telemetry")

#: exhaustive phase vector, in cut order (``fallback`` is the relabel
#: of device + route-derivation under a scalar-fallback verdict)
PHASES = (
    "wake", "coalesce_wait", "queue_wait", "marshal", "device",
    "force_wait", "rib", "fib_commit", "unattributed", "fallback",
)
#: verdict partition (host > queue > device on ties)
HOST_PHASES = (
    "wake", "coalesce_wait", "marshal", "rib", "fib_commit",
    "unattributed",
)
QUEUE_PHASES = ("queue_wait", "force_wait")
DEVICE_PHASES = ("device", "fallback")

#: profiling stage names folded into the marshal / device cuts
#: (``delta`` is the in-place incremental scatter — host marshal work;
#: ``solve`` is the partitioned block solve — device work)
_MARSHAL_STAGES = frozenset(("marshal", "delta"))
_DEVICE_STAGES = frozenset(("device", "readback", "solve"))

_VERDICTS = telemetry.counter(
    "holo_critpath_verdicts_total",
    "Completed trigger→FIB events by critical-path bound verdict",
    ("verdict",),
)
# Population gauges update on completion/stats only — stamped=False so
# ledger bookkeeping never wakes the gNMI fan-out walk (delta.py
# discipline, same as the observatory's gauges).
_OPEN = telemetry.gauge(
    "holo_critpath_open_events",
    "Causal events with an open critical-path record",
    stamped=False,
)
_SKETCHES_G = telemetry.gauge(
    "holo_critpath_sketches",
    "Live (trigger, phase, engine, shape-bucket, kind) phase sketches",
    stamped=False,
)


class _Rec:
    """One open event's stamp set.  Mutated lock-free: each field is
    written by exactly one logical stage of the event's life (the GIL
    makes the attribute stores atomic; a racing duplicate stamp
    resolves min/max-wards, inside the phase's own noise floor)."""

    __slots__ = (
        "trigger", "t0", "sched", "enqueue", "launch0", "marshal0",
        "marshal1", "device_end", "force0", "force1", "spf", "rib",
        "t_end", "stalls", "engine", "kind", "bucket",
    )

    def __init__(self, trigger: str, t0: float):
        self.trigger = trigger
        self.t0 = t0
        self.sched = None
        self.enqueue = None
        self.launch0 = None
        self.marshal0 = None
        self.marshal1 = None
        self.device_end = None
        self.force0 = None
        self.force1 = None
        self.spf = None
        self.rib = None
        self.t_end = None
        self.stalls = 0
        self.engine = "-"
        self.kind = "-"
        self.bucket = "-"


def _decompose(rec: _Rec, t_done: float, fallback: bool) -> dict:
    """The cut model: clamped-monotone cuts → telescoping phase dict.

    Every cut is forced into ``[previous cut, t_done]``, so the phase
    diffs are non-negative and sum to ``t_done - t0`` exactly (each
    term is an exact float difference of consecutive cuts)."""
    mb = rec.marshal0 if rec.marshal0 is not None else rec.launch0
    cuts = (
        ("wake", rec.sched),
        # No pipeline ⇒ no enqueue stamp: the sched→marshal hold is the
        # SPF delay FSM coalescing triggers, so it books as
        # coalesce_wait (queue_wait then reads zero), not vice versa.
        ("coalesce_wait", rec.enqueue if rec.enqueue is not None else mb),
        ("queue_wait", mb),
        ("marshal", rec.marshal1),
        ("device", rec.device_end),
        ("force_wait", rec.force1),
        # rib spans BOTH slices of RIB sync: host route derivation
        # from the ready result (…→spf-observed) and route publish +
        # apply (…→rib-observed).
        ("rib", rec.spf),
        ("rib", rec.rib),
        ("fib_commit", rec.t_end),
        # The closing segment past the last stamp: an event that
        # converged with NO stamps books its whole wall here — the
        # honest "no stamp explains this" residual the bench gates.
        ("unattributed", t_done),
    )
    prev = rec.t0
    phases = dict.fromkeys(PHASES, 0.0)
    derive = 0.0  # the …→spf-observed slice (fallback relabel below)
    for i, (name, c) in enumerate(cuts):
        c = prev if c is None else min(max(c, prev), t_done)
        phases[name] += c - prev
        if i == 6:  # the first rib slice: route derivation
            derive = c - prev
        prev = c
    if fallback:
        # The scalar oracle served this event: the device segment
        # (absent) plus the derivation slice — which then holds the
        # oracle's compute — are its phase, not a device/rib lie.
        phases["fallback"] = phases["device"] + derive
        phases["device"] = 0.0
        phases["rib"] -= derive
    return phases


def _verdict(phases: dict) -> str:
    host = sum(phases[p] for p in HOST_PHASES)
    queue = sum(phases[p] for p in QUEUE_PHASES)
    device = sum(phases[p] for p in DEVICE_PHASES)
    # Deterministic tie-break: host > queue > device (>= comparisons).
    if host >= queue and host >= device:
        return "host"
    if queue >= device:
        return "queue"
    return "device"


class CritPathLedger:
    """Process-wide critical-path instrument (module singleton via
    :func:`configure`).  Hot path = the stamp methods below, fed by
    the convergence/profiling/dispatch hooks; everything else is cold
    reporting."""

    def __init__(
        self,
        capacity: int = 1024,
        check_every: int = 64,
        alpha: float = 0.01,
        max_bins: int = 512,
        waterfalls: int = 64,
    ):
        self.capacity = int(capacity)
        self.check_every = int(check_every)
        self.alpha = float(alpha)
        self.max_bins = int(max_bins)
        # eid -> _Rec; plain insertion-ordered dict, GIL-atomic ops
        # only (no locks on the dispatch thread — ISSUE 17 contract).
        self._recs: dict[int, _Rec] = {}
        self._sketches: dict[tuple, DDSketch] = {}
        self._water: deque = deque(maxlen=int(waterfalls))
        self._verdicts = {"host": 0, "queue": 0, "device": 0}
        self._completed = 0
        self._dropped = 0
        self._sheds = 0

    # -- hot path: stamps -----------------------------------------------

    def ev_begin(self, eid: int, trigger: str) -> None:
        rec = _Rec(trigger, profiling.clock())
        # Lock-free bounded map: setdefault/pop are GIL-atomic; a
        # racing begin for a distinct eid interleaves cleanly.
        self._recs[eid] = rec
        while len(self._recs) > self.capacity:
            try:
                self._recs.pop(next(iter(self._recs)))
                self._dropped += 1
            except (StopIteration, KeyError):  # racing pop emptied it
                break

    def ev_sched(self, eid: int) -> None:
        rec = self._recs.get(eid)
        if rec is not None and rec.sched is None:
            rec.sched = profiling.clock()

    def ev_phase(self, eid: int, phase: str) -> None:
        rec = self._recs.get(eid)
        if rec is None:
            return
        now = profiling.clock()
        if phase == convergence.PHASE_SPF:
            if rec.spf is None:
                rec.spf = now
        elif phase == convergence.PHASE_RIB:
            if rec.rib is None:
                rec.rib = now
        else:  # fib / fallback: the closing cut
            if rec.t_end is None:
                rec.t_end = now

    def ev_done(self, eid: int, outcome: str, fallback: bool) -> None:
        rec = self._recs.pop(eid, None)
        if rec is None:
            return
        if outcome != "converged":
            self._dropped += 1
            return
        # Wall = trigger→fib-observed, same end cut as
        # holo_convergence_seconds; the close-time read only serves as
        # the end when the fib stamp is missing — in which case the
        # whole tail books as unattributed (residual by construction).
        t_done = rec.t_end
        if t_done is None:
            t_done = profiling.clock()
        t_done = max(t_done, rec.t0)
        phases = _decompose(rec, t_done, fallback)
        verdict = _verdict(phases)
        self._verdicts[verdict] += 1
        _VERDICTS.labels(verdict=verdict).inc()
        key4 = (rec.trigger, rec.engine, rec.bucket, rec.kind)
        for phase in PHASES:
            self._sketch(phase, key4).observe(phases[phase])
        self._sketch("wall", key4).observe(t_done - rec.t0)
        # deque.append with maxlen is GIL-atomic; the cold reader
        # copies via list() and tolerates a torn-window snapshot.
        self._water.append({  # holo-lint: disable=HL204
            "trigger": rec.trigger,
            "wall": round(t_done - rec.t0, 9),
            "phases": {p: round(phases[p], 9) for p in PHASES},
            "verdict": verdict,
            "engine": rec.engine,
            "kind": rec.kind,
            "bucket": rec.bucket,
            "stalls": rec.stalls,
            "fallback": bool(fallback),
        })
        self._completed += 1
        _OPEN.set(len(self._recs))
        if self.check_every and self._completed % self.check_every == 0:
            self._sentinel_pass()

    def _sketch(self, phase: str, key4: tuple) -> DDSketch:
        trigger, engine, bucket, kind = key4
        key = (trigger, phase, engine, bucket, kind)
        sk = self._sketches.get(key)
        if sk is None:
            # setdefault is GIL-atomic: two racing first-observers
            # both get the one surviving sketch (observatory idiom).
            sk = self._sketches.setdefault(  # holo-lint: disable=HL204
                key, DDSketch(self.alpha, self.max_bins)
            )
        return sk

    # profiling phase hook: fed every stage() begin/end edge while
    # armed.  Reads the clock itself; device != "-" rows are the
    # per-device skew split of one already-stamped sharded span.
    def _on_stage(self, site: str, name: str, device: str, edge: str) -> None:
        if device != "-":
            return
        if name in _MARSHAL_STAGES:
            eids = convergence.current()
            if not eids:
                return
            now = profiling.clock()
            for eid in eids:
                rec = self._recs.get(eid)
                if rec is None:
                    continue
                if edge == "b":
                    if rec.marshal0 is None:
                        rec.marshal0 = now
                elif rec.marshal1 is None or now > rec.marshal1:
                    rec.marshal1 = now
        elif name in _DEVICE_STAGES:
            eids = convergence.current()
            if not eids:
                return
            now = profiling.clock()
            ctx = profiling.dispatch_ctx() if edge == "b" else None
            for eid in eids:
                rec = self._recs.get(eid)
                if rec is None:
                    continue
                if edge == "e":
                    if rec.device_end is None or now > rec.device_end:
                        rec.device_end = now
                elif ctx is not None and rec.engine == "-":
                    rec.engine = str(ctx.get("engine", "-"))
                    rec.kind = str(ctx.get("kind", "-"))
                    rec.bucket = ctx.get("bucket") or "-"

    # dispatch queue-lifecycle stamps (module seams below fan in here)
    def note_enqueue(self, eids) -> None:
        now = profiling.clock()
        for eid in eids:
            rec = self._recs.get(eid)
            if rec is not None and rec.enqueue is None:
                rec.enqueue = now

    def note_launch(self, eids, edge: str) -> None:
        if edge != "b":
            return
        now = profiling.clock()
        for eid in eids:
            rec = self._recs.get(eid)
            if rec is not None and rec.launch0 is None:
                rec.launch0 = now

    def note_finish(self, eids, edge: str) -> None:
        if edge != "e":
            return
        now = profiling.clock()
        for eid in eids:
            rec = self._recs.get(eid)
            if rec is not None and (
                rec.device_end is None or now > rec.device_end
            ):
                rec.device_end = now

    def note_force(self, eids, edge: str) -> None:
        now = profiling.clock()
        for eid in eids:
            rec = self._recs.get(eid)
            if rec is None:
                continue
            if edge == "b":
                if rec.force0 is None:
                    rec.force0 = now
            elif rec.force1 is None or now > rec.force1:
                rec.force1 = now

    def note_stall(self, eids) -> None:
        for eid in eids:
            rec = self._recs.get(eid)
            if rec is not None:
                rec.stalls += 1

    def note_shed(self, eids) -> None:
        """Overload shed disposition: the dispatch never ran (capacity
        shed or deadline expiry), so the open records are discarded
        rather than decomposed — a shed event has no trigger→FIB wall.
        The tally is its own ledger line: sheds are a load-management
        verdict, not a tracker overflow (``dropped``)."""
        self._sheds += 1
        for eid in eids:
            self._recs.pop(eid, None)

    # -- sentinel (reuses the dispatch observatory's machinery) ---------

    def _sentinel_pass(self) -> None:
        from holo_tpu.telemetry import observatory

        obs = observatory.active()
        if obs is None:
            return
        for (trigger, phase, engine, bucket, kind), sk in list(
            self._sketches.items()
        ):
            if phase == "wall" or not sk.count:
                continue
            try:
                obs._sentinel_check(
                    (f"critpath.{trigger}", phase, engine, bucket, kind), sk
                )
            except Exception:  # noqa: BLE001 — warn-only by contract:
                # a sentinel bug must never propagate into the
                # fib_commit path that triggered this pass.
                log.debug("critpath sentinel pass failed", exc_info=True)
        _SKETCHES_G.set(len(self._sketches))

    def checkpoint(self) -> None:
        """Force one sentinel pass NOW (bench/explain bracket their
        runs with it, same discipline as ``Observatory.checkpoint``)."""
        self._sentinel_pass()

    # -- cold reporting -------------------------------------------------

    def _merged_phase(self, phase: str) -> DDSketch:
        out = DDSketch(self.alpha, self.max_bins)
        for (t, p, e, b, k), sk in list(self._sketches.items()):
            if p == phase and sk.count:
                out.merge(sk)
        return out

    def phase_quantiles(self) -> dict:
        """{phase: {p50, p99, mean}} merged across all sketch keys
        (plus the ``wall`` pseudo-phase), rounded canonically."""
        out = {}
        for phase in (*PHASES, "wall"):
            sk = self._merged_phase(phase)
            if not sk.count:
                continue
            out[phase] = {
                "p50": round(sk.quantile(0.5), 9),
                "p99": round(sk.quantile(0.99), 9),
                "mean": round(sk.total / sk.count, 9),
            }
        return out

    def host_fraction_p99(self) -> float | None:
        """Σ host-phase p99 / Σ all-phase p99 — the scalar ROADMAP item
        5's streaming-convergence refactor must drive down."""
        q = self.phase_quantiles()
        total = sum(q[p]["p99"] for p in PHASES if p in q)
        if total <= 0.0:
            return None
        host = sum(q[p]["p99"] for p in HOST_PHASES if p in q)
        return round(host / total, 6)

    def unattributed_frac_p50(self) -> float | None:
        """unattributed p50 as a fraction of the wall p50 — the
        gap-free gate (< 1% at p50 in ``bench.py critical_path``)."""
        q = self.phase_quantiles()
        wall = q.get("wall")
        if not wall or wall["p50"] <= 0.0:
            return None
        un = q.get("unattributed", {"p50": 0.0})
        return round(un["p50"] / wall["p50"], 6)

    def waterfalls(self) -> list[dict]:
        """Most recent completed waterfalls, oldest first."""
        return [dict(w) for w in self._water]

    def stats(self) -> dict:
        """The ``holo-telemetry/critical-path`` gNMI leaf payload."""
        out = {
            "open": len(self._recs),
            "completed": self._completed,
            "dropped": self._dropped,
            "sheds": self._sheds,
            "capacity": self.capacity,
            "sketches": len(self._sketches),
            "verdicts": dict(self._verdicts),
            "phases": self.phase_quantiles(),
        }
        hf = self.host_fraction_p99()
        if hf is not None:
            out["host-fraction-p99"] = hf
        uf = self.unattributed_frac_p50()
        if uf is not None:
            out["unattributed-frac-p50"] = uf
        return out

    def report(self, top: int = 8) -> dict:
        """Deterministic report document (the ``explain
        --critical-path`` payload): phase table in cut order, verdict
        tally, and the last ``top`` per-event waterfalls.  Events are
        numbered by completion order WITHIN this report — raw eids are
        process-global counters and would break byte-identity across
        same-process runs (the storm-digest precedent)."""
        phases = self.phase_quantiles()
        rows = [
            {"phase": p, **phases[p]} for p in PHASES if p in phases
        ]
        total_p99 = sum(r["p99"] for r in rows)
        for r in rows:
            r["share_p99"] = (
                round(r["p99"] / total_p99, 6) if total_p99 > 0 else 0.0
            )
        water = self.waterfalls()[-int(top):] if int(top) > 0 else []
        return {
            "completed": self._completed,
            "dropped": self._dropped,
            "sheds": self._sheds,
            "verdicts": dict(self._verdicts),
            "phases": rows,
            "wall": phases.get("wall"),
            "host-fraction-p99": self.host_fraction_p99(),
            "unattributed-frac-p50": self.unattributed_frac_p50(),
            "events": [
                {"n": i, **w} for i, w in enumerate(water)
            ],
        }


# -- process-wide singleton + one-global-check seams ---------------------

_CP: CritPathLedger | None = None


def configure(
    capacity: int = 1024,
    check_every: int = 64,
    waterfalls: int = 64,
) -> CritPathLedger | None:
    """Arm (``capacity`` > 0) or disarm (0) the process-wide ledger and
    (un)install the convergence + profiling hooks.  Requires an armed
    convergence tracker to see any events (the causal ids are the join
    key); the dispatch observatory is optional (without it the phase
    sketches still aggregate — only the sentinel pass is skipped)."""
    global _CP
    if capacity and int(capacity) > 0:
        _CP = CritPathLedger(
            int(capacity), check_every=check_every, waterfalls=waterfalls
        )
        profiling.set_phase_hook(_CP._on_stage)
        convergence.set_critpath_hook(_CP)
    else:
        _CP = None
        profiling.set_phase_hook(None)
        convergence.set_critpath_hook(None)
    return _CP


def active() -> CritPathLedger | None:
    return _CP


def enabled() -> bool:
    return _CP is not None


def note_enqueue(eids) -> None:
    """Dispatch-queue admission stamp (no-op while disarmed)."""
    cp = _CP
    if cp is None or not eids:
        return
    cp.note_enqueue(eids)


def note_launch(eids, edge: str) -> None:
    """Worker launch begin/end stamp (``edge`` = 'b' | 'e')."""
    cp = _CP
    if cp is None or not eids:
        return
    cp.note_launch(eids, edge)


def note_finish(eids, edge: str) -> None:
    """Worker finish begin/end stamp (``edge`` = 'b' | 'e')."""
    cp = _CP
    if cp is None or not eids:
        return
    cp.note_finish(eids, edge)


def note_force(eids, edge: str) -> None:
    """Force-seam (ticket result) wait begin/end stamp."""
    cp = _CP
    if cp is None or not eids:
        return
    cp.note_force(eids, edge)


def note_stall(eids) -> None:
    """Per-key ordering stall: a launchable item skipped because an
    earlier generation of its key is still in flight."""
    cp = _CP
    if cp is None or not eids:
        return
    cp.note_stall(eids)


def note_shed(eids) -> None:
    """Overload shed disposition (ISSUE 19).  No ``eids`` gate: a
    synthetic flood ticket carries none, but the shed itself must
    still land in the ledger tally."""
    cp = _CP
    if cp is None:
        return
    cp.note_shed(eids)

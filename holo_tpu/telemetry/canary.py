"""Synthetic canary prober: black-box trigger→FIB probing (ISSUE 20).

Nothing measured the serving path while user traffic was idle: every
latency the observatory knows comes from REAL topology events, so a
quiet daemon reports nothing — and the first sign of a wedged worker
or a saturated queue is a production trigger paying for it.  This
module is the always-on model graded continuously against the live
protocol ("Advanced Models for the OSPF Routing Protocol", PAPERS.md):
a standing synthetic OSPF instance whose heartbeat topology deltas run
through the REAL actor → ibus → pipeline → RIB path, closing each
probe at ``fib_commit`` so trigger→FIB latency is measured end to end
even on an idle daemon.

Probe contract
--------------
- The canary net (:class:`_CanaryNet`) is a five-router miniature of
  the storm topology — DUT root, two ECMP gateways, a hub, one stub
  leaf — living on the HOST loop (the daemon's or a storm's) with its
  own ibus, its own :class:`RibManager`, and its own mock kernel.  It
  shares exactly two things with production work: the event loop
  (scheduling) and the process dispatch pipeline (admission).  Its FIB
  is disjoint by construction — :func:`fib_digest` over the production
  kernel is asserted unperturbed by a riding canary (the ``slo_storm``
  gate).
- Each heartbeat flips the hub→leaf link metric 1↔2 and reinstalls
  both endpoint Router-LSAs under a fresh ``canary`` causal event.
  The delta forces a real SPF and a real route-metric change, so every
  healthy probe ends in a kernel install; the canary kernel matches
  the install back to the probe's event id (``unattributed`` counts
  installs that arrived with no matching causal id — the <1% bench
  gate on attribution quality).
- The canary's SPF dispatch rides the process pipeline as a
  ``background``-class ticket (site ``canary.probe``) when one is
  armed: probes are shed FIRST under pressure and can never displace
  correctness work — and the canary's own shed rate is therefore a
  first-class saturation signal (the ``background-delivery`` objective
  in :mod:`holo_tpu.telemetry.slo`).  A shed or timed-out probe serves
  the previous (stale, same-shape) SPF result so the synthetic
  instance never crashes, and grades the probe bad.
- Probe latency is a REAL wall (``profiling.clock()`` — perf_counter
  in production, the deterministic timer under ``explain``), NOT the
  loop's virtual clock: a storm's virtual end-cuts are blind to host
  stalls, which are exactly what the canary exists to see
  (``FaultPlan.dispatch_delay`` breaches, wedged workers, queue
  waits).  Results feed :func:`holo_tpu.telemetry.slo.note_probe` as
  the canary's own objective.

Arming: the daemon boots one prober from ``[telemetry] canary``;
bench/test storms arm one on the storm loop via their event hooks.
Disarmed, nothing here exists — the module seams in dispatch/slo are
the only residue, each one global check.
"""

from __future__ import annotations

import hashlib
import json
import logging
from dataclasses import dataclass
from ipaddress import IPv4Address, IPv4Network

from holo_tpu.resilience import faults
from holo_tpu.routing.rib import MockKernel, RibManager
from holo_tpu.telemetry import convergence, profiling, slo
from holo_tpu.utils.ibus import Ibus
from holo_tpu.utils.netio import NetIo
from holo_tpu.utils.runtime import Actor

log = logging.getLogger("holo_tpu.telemetry")

#: canary net indices (root DUT, dual gateways, hub, stub leaf)
_ROOT, _GW0, _GW1, _HUB, _LEAF = range(5)
#: the leaf's advertised prefix (TEST-NET-2 — never a production route)
_LEAF_PREFIX = IPv4Network("198.51.100.0/24")


def fib_digest(fib: dict) -> str:
    """Canonical digest of a kernel FIB (the bench identity gate —
    same spelling as the overload-storm stages)."""
    text = json.dumps(sorted((str(k), str(v)) for k, v in fib.items()))
    return hashlib.sha256(text.encode()).hexdigest()


class _DiscardIo(NetIo):
    """The synthetic neighbors have no receive side."""

    def send(self, ifname, src, dst, data) -> None:
        pass


def _rid(i: int) -> IPv4Address:
    """Canary router ids live in 192.168.0.x — disjoint from the storm
    harness's 10.x synthetic fleet and any production router id a test
    daemon uses, so a canary riding a storm can never alias."""
    return IPv4Address((192 << 24) | (168 << 16) | (i + 1))


@dataclass
class _Beat:
    """Heartbeat timer message (self-rearming via the canary actor)."""


@dataclass
class _ApplyLsas:
    """LSA batch delivered under a causal context (the loop delivery
    hook activates ``event_id`` for the handler's extent — same shape
    as the storm harness's message)."""

    lsas: list
    event_id: tuple | None = None


class _CanaryKernel(MockKernel):
    """Mock kernel that closes probes: every install is matched back to
    the open probe whose causal event id is active at commit time."""

    def __init__(self, prober: "CanaryProber"):
        super().__init__()
        self._prober = prober

    def install(self, *args, **kwargs):
        out = super().install(*args, **kwargs)
        self._prober._on_install(convergence.current())
        return out


class _ProbeBackend:
    """SPF facade for the canary instance: route the dispatch through
    the process pipeline as a background-class ticket when one is
    armed, compute inline otherwise.  Shed/timed-out dispatches serve
    the previous same-shape result (the synthetic topology never
    changes structurally — only the hub→leaf metric flips), so the
    instance's route derivation always has something to chew on."""

    name = "canary"

    def __init__(self, inner, prober: "CanaryProber"):
        self.inner = inner
        self._prober = prober
        self._stale = None
        self.sheds = 0
        self.timeouts = 0

    def __getattr__(self, attr):
        return getattr(self.inner, attr)

    def compute(self, topo, edge_mask=None, multipath_k: int = 1):
        from holo_tpu.pipeline import dispatch as pipeline

        inner = self.inner

        def run():
            # Breach seam: bench injects FaultPlan.dispatch_delay here
            # to slow ONLY the canary's dispatch (a real time.sleep —
            # visible to the probe's profiling-clock wall, invisible to
            # the storm's virtual end-cuts).
            faults.delaypoint("canary.probe")
            return inner.compute(topo, edge_mask, multipath_k=multipath_k)

        pipe = pipeline.process_pipeline()
        if pipe is None or pipe.closed:
            res = run()
            self._stale = res
            return res
        ticket = pipe.submit(
            ("canary", int(topo.root)), "canary", run=run,
            cls="background", site="canary.probe",
            deadline=self._prober.deadline,
        )
        res = None
        try:
            res = ticket.result(timeout=self._prober.overdue)
        except TimeoutError:
            self.timeouts += 1
            self._prober._probe_failed(ticket.eids, "timeout")
        except Exception:  # noqa: BLE001 — a probe dispatch error is a
            # bad probe, never a canary crash (warn-only plane).
            log.debug("canary probe dispatch failed", exc_info=True)
            self._prober._probe_failed(ticket.eids, "error")
        if res is None:
            if ticket.shed is not None:
                self.sheds += 1
                self._prober._probe_failed(ticket.eids, "shed")
            if self._stale is not None:
                return self._stale
            return run()  # first-ever dispatch: nothing stale to serve
        self._stale = res
        return res


class _CanaryActor(Actor):
    def __init__(self, prober: "CanaryProber"):
        self.prober = prober

    def handle(self, msg) -> None:
        if isinstance(msg, _ApplyLsas):
            self.prober.net.apply_lsas(msg.lsas)
        elif isinstance(msg, _Beat):
            self.prober._beat()
            self.prober._rearm()


class _CanaryNet:
    """The standing synthetic instance (see module docstring).  Names
    are ``canary-*`` so registration on a shared loop never collides
    with production actors or the storm harness."""

    DUT = "canary-dut"
    RIB = "canary-routing"
    ACTOR = "canary-driver"

    def __init__(self, loop, prober: "CanaryProber", spf_backend=None,
                 warmup: float = 30.0):
        from holo_tpu.protocols.ospf.instance import (
            IfConfig,
            InstanceConfig,
            OspfInstance,
        )
        from holo_tpu.protocols.ospf.interface import IfType, IsmState
        from holo_tpu.protocols.ospf.neighbor import Neighbor, NsmState
        from holo_tpu.spf.backend import ScalarSpfBackend

        self.loop = loop
        self.bus = Ibus(loop)
        self.kernel = _CanaryKernel(prober)
        self.rib = RibManager(self.bus, self.kernel)
        self.rib.name = self.RIB
        loop.register(self.rib)
        backend = _ProbeBackend(
            spf_backend if spf_backend is not None else ScalarSpfBackend(),
            prober,
        )
        self.inst = OspfInstance(
            name=self.DUT,
            config=InstanceConfig(router_id=_rid(_ROOT)),
            netio=_DiscardIo(),
            spf_backend=backend,
        )
        self.backend = backend
        loop.register(self.inst)
        self.inst.attach_ibus(self.bus, routing_actor=self.RIB)
        loop.register(_CanaryActor(prober), name=self.ACTOR)

        # Fixed miniature topology; only adj[_HUB][_LEAF] ever changes.
        self.adj: dict[int, dict[int, int]] = {i: {} for i in range(5)}
        for a, b in ((_ROOT, _GW0), (_ROOT, _GW1),
                     (_GW0, _HUB), (_GW1, _HUB), (_HUB, _LEAF)):
            self.adj[a][b] = self.adj[b][a] = 1
        self._seq: dict[int, int] = {}

        self.g0_addr = IPv4Address("192.168.255.2")
        self.g1_addr = IPv4Address("192.168.254.2")
        for ifname, net, our, nbr_idx, nbr_addr in (
            ("cn0", "192.168.255.0/30", "192.168.255.1", _GW0, self.g0_addr),
            ("cn1", "192.168.254.0/30", "192.168.254.1", _GW1, self.g1_addr),
        ):
            iface = self.inst.add_interface(
                ifname,
                IfConfig(if_type=IfType.POINT_TO_POINT, cost=1),
                IPv4Network(net),
                IPv4Address(our),
            )
            iface.state = IsmState.POINT_TO_POINT
            iface.neighbors[_rid(nbr_idx)] = Neighbor(
                router_id=_rid(nbr_idx), src=nbr_addr, state=NsmState.FULL
            )
        self.area = self.inst.areas[next(iter(self.inst.areas))]
        inner = getattr(loop, "loop", loop)  # ThreadedLoop hosts
        now = inner.clock.now()
        for i in range(5):
            self.area.lsdb.install(self.router_lsa(i), now)
        # Initial convergence outside any probe; a threaded host loop
        # converges on its own pump instead.
        self.inst._schedule_spf()
        if hasattr(loop, "advance"):
            loop.advance(warmup)

    def router_lsa(self, i: int):
        from holo_tpu.protocols.ospf.packet import (
            Lsa,
            LsaRouter,
            LsaType,
            Options,
            RouterLink,
            RouterLinkType,
        )

        seq = self._seq.get(i, 0) + 1
        self._seq[i] = seq
        links = []
        if i == _ROOT:
            links.append(RouterLink(
                RouterLinkType.POINT_TO_POINT, _rid(_GW0),
                IPv4Address("192.168.255.1"), self.adj[_ROOT][_GW0],
            ))
            links.append(RouterLink(
                RouterLinkType.POINT_TO_POINT, _rid(_GW1),
                IPv4Address("192.168.254.1"), self.adj[_ROOT][_GW1],
            ))
        else:
            for peer, metric in sorted(self.adj[i].items()):
                links.append(RouterLink(
                    RouterLinkType.POINT_TO_POINT, _rid(peer),
                    IPv4Address(0), metric,
                ))
        if i == _LEAF:
            links.append(RouterLink(
                RouterLinkType.STUB_NETWORK,
                _LEAF_PREFIX.network_address, _LEAF_PREFIX.netmask, 1,
            ))
        lsa = Lsa(
            age=1,
            options=Options(0x02),
            type=LsaType.ROUTER,
            lsid=_rid(i),
            adv_rtr=_rid(i),
            seq_no=seq,
            body=LsaRouter(links=links),
        )
        lsa.encode()  # §13.2 change detection needs a real wire image
        return lsa

    def flip_metric(self) -> int:
        """Toggle the hub→leaf metric 1↔2; returns the new metric.  The
        flip moves the leaf route's total cost, so every healthy probe
        ends in a kernel install."""
        m = 2 if self.adj[_HUB][_LEAF] == 1 else 1
        self.adj[_HUB][_LEAF] = self.adj[_LEAF][_HUB] = m
        return m

    def deliver(self, lsas: list, eid) -> None:
        self.loop.send(
            self.ACTOR,
            _ApplyLsas(lsas, (eid,) if eid is not None else None),
        )

    def apply_lsas(self, lsas: list) -> None:
        for lsa in lsas:
            self.inst._install_and_flood(self.area, lsa)
        for area in self.inst.areas.values():
            for iface in area.interfaces.values():
                for nbr in iface.neighbors.values():
                    nbr.ls_rxmt.clear()


class CanaryProber:
    """One standing canary (daemon boot or storm hook).  All probe
    state is touched on the host loop's thread only (beats, LSA
    applies, RIB installs all run there), so plain attributes suffice.
    """

    def __init__(
        self,
        loop,
        period: float = 5.0,
        deadline: float = 0.25,
        overdue: float = 10.0,
        spf_backend=None,
        warmup: float = 30.0,
    ):
        if period <= 0:
            raise ValueError(f"canary period must be positive, got {period}")
        self.period = float(period)
        #: pipeline deadline for the probe ticket (background class —
        #: a probe older than this is not owed a dispatch)
        self.deadline = float(deadline)
        #: real-clock budget before an unclosed probe grades bad
        self.overdue = float(overdue)
        self.loop = loop
        self._seq = 0
        self._open: dict[int, float] = {}  # probe eid -> profiling t0
        self._timer = None
        self._stopped = False
        # verdict tallies (stats/bench surface)
        self.probes = 0
        self.completed = 0
        self.attributed = 0
        self.unattributed = 0
        self.failed = 0
        self.overdue_count = 0
        self.last_ms = None
        self.net = _CanaryNet(
            loop, self, spf_backend=spf_backend, warmup=warmup
        )

    # -- heartbeat ------------------------------------------------------

    def start(self) -> None:
        """Arm the self-rearming heartbeat timer (daemon boot; storms
        get deterministic virtual-time beats the same way since timers
        fire during ``loop.advance``)."""
        self._stopped = False
        self._rearm()

    def stop(self) -> None:
        self._stopped = True
        t = self._timer
        if t is not None and hasattr(t, "cancel"):
            t.cancel()
        self._timer = None

    def _rearm(self) -> None:
        if self._stopped:
            return
        self._timer = self.loop.timer(_CanaryNet.ACTOR, _Beat)
        self._timer.start(self.period)

    def _beat(self) -> None:
        """One heartbeat: flip the canary link, open a probe, deliver
        the endpoint LSAs under its causal event."""
        if self._stopped:
            return
        net = self.net
        m = net.flip_metric()
        eid = convergence.begin("canary", seq=self._seq, metric=m)
        self._seq += 1
        if eid is None:
            # Tracker disarmed: nothing can close a probe — still flip
            # (the canary net stays live) but grade nothing.
            net.deliver([net.router_lsa(_HUB), net.router_lsa(_LEAF)], None)
            return
        self.probes += 1
        # Single-writer by construction: _beat, _on_install and
        # _sweep_overdue all run on the canary loop's actor thread
        # (the timer fires there; the RIB handler commits there).
        self._open[eid] = profiling.clock()  # holo-lint: disable=HL204
        net.deliver([net.router_lsa(_HUB), net.router_lsa(_LEAF)], eid)
        self._sweep_overdue()

    def beat(self) -> None:
        """Manual heartbeat (tests/bench hooks that want probes at
        exact storm indices instead of timer cadence)."""
        self._beat()

    # -- probe close paths ----------------------------------------------

    def _on_install(self, eids: tuple) -> None:
        """Canary-kernel install: close every open probe whose causal
        id is active at commit; an install with no matching id closes
        the oldest probe as ``unattributed`` (attribution quality is a
        bench gate, so miscounting must be visible, not silent)."""
        t1 = profiling.clock()
        hit = False
        for e in eids:
            t0 = self._open.pop(e, None)
            if t0 is None:
                continue
            hit = True
            self._close_ok(t1 - t0)
        if not hit and self._open:
            eid = next(iter(self._open))
            t0 = self._open.pop(eid)
            self.unattributed += 1
            self._close_ok(t1 - t0)

    def _close_ok(self, latency: float) -> None:
        lat = max(latency, 0.0)
        self.completed += 1
        self.attributed = self.completed - self.unattributed
        self.last_ms = round(lat * 1e3, 3)
        slo.note_probe(True, lat)

    def _probe_failed(self, eids: tuple, why: str) -> None:
        """Dispatch-side failure (shed / timeout / error): the probe's
        FIB change is never coming — grade it bad now."""
        closed = False
        for e in eids:
            if self._open.pop(e, None) is not None:
                closed = True
        if not closed:
            return
        self.completed += 1
        self.failed += 1
        slo.note_probe(False, None)
        log.debug("canary probe failed (%s)", why)

    def _sweep_overdue(self) -> None:
        t = profiling.clock()
        for eid, t0 in list(self._open.items()):
            if t - t0 > self.overdue:
                self._open.pop(eid, None)
                self.completed += 1
                self.failed += 1
                self.overdue_count += 1
                slo.note_probe(False, None)

    # -- surfaces --------------------------------------------------------

    def unattributed_fraction(self) -> float:
        """Installs closed without a matching causal id, as a fraction
        of completed probes (the <1% bench gate)."""
        if not self.completed:
            return 0.0
        return self.unattributed / self.completed

    def stats(self) -> dict:
        """holo-telemetry/slo canary sub-leaf + bench row."""
        return {
            "probes": self.probes,
            "completed": self.completed,
            "attributed": self.attributed,
            "unattributed": self.unattributed,
            "failed": self.failed,
            "overdue": self.overdue_count,
            "sheds": self.net.backend.sheds,
            "timeouts": self.net.backend.timeouts,
            "open": len(self._open),
            "last-ms": self.last_ms,
        }


# -- process-wide singleton (daemon boot) --------------------------------

_PROBER: CanaryProber | None = None


def configure(enabled=False, loop=None, **kw) -> CanaryProber | None:
    """Arm (build + start) or disarm (stop + drop) the process-wide
    prober.  ``loop`` is required to arm; ``kw`` passes through to
    :class:`CanaryProber` (period/deadline/overdue/warmup)."""
    global _PROBER
    if _PROBER is not None:
        _PROBER.stop()
        _PROBER = None
    if enabled:
        if loop is None:
            raise ValueError("canary.configure(enabled=True) needs a loop")
        _PROBER = CanaryProber(loop, **kw)
        _PROBER.start()
    return _PROBER


def active() -> CanaryProber | None:
    return _PROBER


def enabled() -> bool:
    return _PROBER is not None
